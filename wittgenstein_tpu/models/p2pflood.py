"""P2PFlood — flood routing on a random peer graph.

Reference: protocols/P2PFlood.java — when a node receives a flood message it
has not seen, it forwards it to all its peers except the sender
(core/messages/FloodMessage.java:47-54), after `delay_before_resent` ms and
with `delay_between_sends` ms between consecutive peers.  Dead nodes are
"officially up but actually not participating" byzantine-ish nodes
(P2PFlood.java:27-36).  A node is done when it has received
`msg_to_receive` distinct floods (P2PFlood.java:39-43, where the reference
checks the received set size against msgCount).

TPU-native state: `received`/`pending` are `[N, M]` bool matrices (M = number
of distinct floods); the per-node forward queue drains one message id per ms
(a burst of simultaneous new floods forwards over the next few ms — same
statistical behavior, fixed shapes).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from ..core import builders, p2p
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import prng

TAG_SENDERS = 0x464C4453


@struct.dataclass
class P2PFloodState:
    seed: jnp.ndarray         # int32 scalar — for the fan-out shuffle draws
    peers: jnp.ndarray        # int32 [N, D]
    degree: jnp.ndarray       # int32 [N]
    received: jnp.ndarray     # bool [N, M]
    pending: jnp.ndarray      # bool [N, M] — received, not yet forwarded
    pending_src: jnp.ndarray  # int32 [N, M] — who sent it to us (-1: nobody)


@register
class P2PFlood:
    """Parameters mirror P2PFlood.P2PFloodParameters (P2PFlood.java:46-110)."""

    # Every dest comes from the p2p peer graph, which skips self
    # (core/p2p.build_peer_graph) — core/network.unicast_floor_ms.
    may_self_send = False

    def __init__(self, node_count=100, dead_node_count=10,
                 delay_before_resent=50, msg_count=1, msg_to_receive=None,
                 peers_count=10, delay_between_sends=30,
                 node_builder_name=None, network_latency_name=None,
                 max_degree=None, inbox_cap=16, horizon=None):
        if msg_count > node_count - dead_node_count:
            # The reference's sender-selection loop would spin forever here
            # (P2PFlood.init:152-160 only picks live nodes).
            raise ValueError(
                f"msg_count={msg_count} needs that many live senders; only "
                f"{node_count - dead_node_count} nodes are up")
        self.node_count = node_count
        self.dead_node_count = dead_node_count
        self.delay_before_resent = delay_before_resent
        self.msg_count = msg_count
        self.msg_to_receive = (msg_count if msg_to_receive is None
                               else min(msg_to_receive, msg_count))
        self.peers_count = peers_count
        self.delay_between_sends = delay_between_sends
        self.builder = builders.get_by_name(node_builder_name)
        self.latency = latency_mod.get_by_name(network_latency_name)
        self.max_degree = max_degree or max(4 * peers_count, peers_count + 16)
        if horizon is None:
            # The ring must hold the full stagger schedule (last peer's
            # delay is delay_before_resent + delay_between_sends * (D-1))
            # plus a generous latency allowance, or arrivals get clamped.
            need = (delay_before_resent
                    + delay_between_sends * self.max_degree + 1024)
            horizon = 1 << (need - 1).bit_length()
        self.cfg = EngineConfig(
            n=node_count, horizon=horizon, inbox_cap=inbox_cap,
            payload_words=1, out_deg=self.max_degree, bcast_slots=1)

    def init(self, seed):
        n, m = self.node_count, self.msg_count
        nodes = self.builder.build(seed, n)
        # First dead_node_count nodes are down (P2PFlood.init: i < deadNodeCount).
        down = jnp.arange(n) < self.dead_node_count
        nodes = nodes.replace(down=down)

        peers, degree, _ = p2p.build_peer_graph(
            seed, n, self.peers_count, minimum=True,
            max_degree=self.max_degree)

        # msg_count distinct random live senders (P2PFlood.init:152-165):
        # order live nodes by a per-seed hash, take the first msg_count.
        ids = jnp.arange(n, dtype=jnp.int32)
        pri = prng.uniform_u32(prng.hash2(jnp.asarray(seed, jnp.int32),
                                          TAG_SENDERS), ids)
        pri = jnp.where(down, jnp.uint32(0xFFFFFFFF), pri)
        senders = jnp.argsort(pri)[:m].astype(jnp.int32)   # [M]

        received = jnp.zeros((n, m), bool).at[senders, jnp.arange(m)].set(True)
        pending = received
        pending_src = jnp.full((n, m), -1, jnp.int32)
        # "if (params.msgCount == 1) from.doneAt = 1" (P2PFlood.java:161-163).
        if m == 1:
            nodes = nodes.replace(
                done_at=nodes.done_at.at[senders].set(1))

        net = init_net(self.cfg, nodes, seed)
        return net, P2PFloodState(seed=jnp.asarray(seed, jnp.int32),
                                  peers=peers, degree=degree,
                                  received=received, pending=pending,
                                  pending_src=pending_src)

    def step(self, pstate, nodes, inbox, t, key):
        n, m = self.node_count, self.msg_count
        s = inbox.src.shape[1]
        i_idx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, s))
        msgid = jnp.clip(inbox.data[:, :, 0], 0, m - 1)

        # First-arrival-wins per (node, msg): scatter-min the inbox slot index
        # (slots are in deterministic delivery order).
        slot = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                                (n, s))
        slot_w = jnp.where(inbox.valid, slot, s)
        first = jnp.full((n, m), s, jnp.int32).at[i_idx, msgid].min(
            slot_w, mode="drop")
        arrived = first < s
        src = jnp.take_along_axis(
            inbox.src, jnp.clip(first, 0, s - 1), axis=1)

        new = arrived & ~pstate.received
        received = pstate.received | arrived
        pending = pstate.pending | new
        pending_src = jnp.where(new, src, pstate.pending_src)

        # Forward one pending msg id per node per ms.
        has = jnp.any(pending, axis=1)
        pick = jnp.argmax(pending, axis=1)                  # lowest id first
        payload = pick[:, None].astype(jnp.int32)
        exclude = pending_src[jnp.arange(n), pick]
        dest, pl, size, delay = p2p.flood_fanout(
            self.cfg, pstate.peers, has, exclude, payload, pstate.seed, t,
            local_delay=self.delay_before_resent,
            delay_between=self.delay_between_sends)
        pending = pending.at[jnp.arange(n), pick].set(
            jnp.where(has, False, pending[jnp.arange(n), pick]))

        out = empty_outbox(self.cfg).replace(
            dest=dest, payload=pl, size=size, delay=delay)

        # doneAt = network.time when the count reaches the target
        # (P2PFlood.java:39-43); never overwrite an earlier doneAt.
        count = jnp.sum(received, axis=1)
        done_now = (count >= self.msg_to_receive) & (nodes.done_at == 0)
        nodes = nodes.replace(
            done_at=jnp.where(done_now, jnp.maximum(t, 1),
                              nodes.done_at).astype(jnp.int32))

        return (pstate.replace(received=received, pending=pending,
                               pending_src=pending_src),
                nodes, out)

    def next_action_time(self, pstate, nodes, t):
        """Quiet-window oracle half (core/protocol.py): a node with a
        pending flood forwards one message id THIS ms (the resend/
        stagger delays ride in the outbox `delay` field, so the sends
        themselves sit in the mailbox ring — the engine oracle's
        territory); with no pending forwards anywhere, the next event is
        an arrival.  t == 0 is pinned for the initial-senders kick."""
        from ..core.protocol import FAR_FUTURE
        act_now = jnp.any(pstate.pending) | (t <= 0)
        return jnp.where(act_now, t, FAR_FUTURE).astype(jnp.int32)
