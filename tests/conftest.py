"""Test harness platform setup.

Force an 8-device virtual CPU mesh so sharding paths are exercised without
TPU hardware (the driver separately dry-runs the multi-chip path); see
wittgenstein_tpu/utils/platform.py for why this beats the env var.

Also enable JAX's persistent compilation cache (repo-local, gitignored):
the suite's wall time is dominated by XLA compiles on the 1-core
sandbox, and the cache cuts the compile-heavy tests ~4x on every run
after the first (measured: 112 s -> 26.5 s for the phase-hint equality
test).  JAX_COMPILATION_CACHE_DIR in the environment overrides the
location; set it to "" to disable.
"""

import os
import pathlib

from wittgenstein_tpu.utils.platform import force_virtual_cpu

force_virtual_cpu(8)

import jax

if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    cache = pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
    jax.config.update("jax_compilation_cache_dir", str(cache))
# Cache every program the suite compiles (the defaults skip
# fast-compiling ones, which is most of a 64-node test suite) — applied
# for an env-var-relocated cache too, not just the repo-local default.
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
