"""Rule ``host_sync`` — the compiled superstep must be free of host
round-trips.

The whole performance model (ROADMAP north star, SURVEY §2.6) assumes a
chunk of simulated milliseconds is ONE device program: any Python
callback, infeed/outfeed, or host transfer inside the scan serializes
the device on the host every iteration — catastrophic and silent (the
program still returns bit-correct results).  The reference has no
analogue (it runs on the JVM); this invariant is TPU-port-specific.

Checks, per protocol target:
  * jaxpr: no callback/debug primitives anywhere (pure_callback,
    io_callback, debug_callback, outside_call, host_callback, ...);
  * optimized HLO: no infeed/outfeed/send/recv ops and no custom-call
    to a host-python trampoline target.

The total count of offending constructs is also emitted as the
budgetable metric ``transfer_ops`` so the ratchet file pins it at 0 per
target (any occurrence is an error regardless; the budget entry makes
the zero an explicit, checked-in fact per audited build — including the
fast-forward while-loop bodies).
"""

from __future__ import annotations

import re

from . import hlo
from .framework import Finding, Rule, register_rule
from .rules_dtype import _iter_jaxprs

BAD_PRIMITIVES = {"pure_callback", "io_callback", "debug_callback",
                  "debug_print", "outside_call", "host_callback",
                  "host_local_array_to_global_array", "infeed", "outfeed"}

# Host-python trampolines XLA emits for jax callbacks (CPU and TPU
# spellings), matched as substrings of the custom_call_target.
BAD_CUSTOM_CALL_PAT = re.compile(
    r"callback|CallbackToHost|host_compute|SendToHost|RecvFromHost",
    re.IGNORECASE)

BAD_HLO_OPS = ("infeed", "outfeed", "send", "send-done", "recv",
               "recv-done")


@register_rule
class HostSyncRule(Rule):
    name = "host_sync"
    scope = "protocol"
    budgeted_metrics = ("transfer_ops",)

    def run(self, target, budget):
        findings = []
        n_bad = 0
        bad_prims = set()
        for j in _iter_jaxprs(target.jaxpr.jaxpr):
            for eqn in j.eqns:
                if eqn.primitive.name in BAD_PRIMITIVES:
                    bad_prims.add(eqn.primitive.name)
        for p in sorted(bad_prims):
            n_bad += 1
            findings.append(Finding(
                rule=self.name, target=target.name, severity="error",
                message=f"host-callback primitive {p!r} inside the traced "
                        "superstep — every scan iteration would sync with "
                        "the host"))

        text = target.hlo_text
        for opcode in BAD_HLO_OPS:
            n = len(re.findall(rf"= \S+ {re.escape(opcode)}\(", text))
            if n:
                n_bad += n
                findings.append(Finding(
                    rule=self.name, target=target.name, severity="error",
                    message=f"{n} `{opcode}` op(s) in the optimized HLO — "
                            "device/host transfer inside the step"))
        for tgt in sorted(hlo.custom_call_targets(text)):
            if BAD_CUSTOM_CALL_PAT.search(tgt):
                n_bad += 1
                findings.append(Finding(
                    rule=self.name, target=target.name, severity="error",
                    message=f"custom-call to host trampoline {tgt!r} in "
                            "the optimized HLO"))
        findings.append(Finding(
            rule=self.name, target=target.name, severity="info",
            metric="transfer_ops", value=n_bad,
            message=(f"transfer_ops={n_bad} host callbacks/transfers in "
                     "the compiled step" if n_bad else
                     "no host callbacks or transfers in the compiled "
                     "step (transfer_ops=0)")))
        return findings
