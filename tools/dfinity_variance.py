"""Dfinity golden-band variance study -> reports/DFINITY_VARIANCE.md.

The golden statistical-parity tests (tests/test_golden_parity.py) pin the
Dfinity block rate to the reference's published single-sample numbers
(Dfinity.java:467-481) within a band argued structurally in round 2.
This tool grounds the band in data: >= 32 seeds per condition, per-seed
block rates, and the spread that a single published sample could fall in.

Usage: python tools/dfinity_variance.py [seeds] [sim_s]
"""

import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from wittgenstein_tpu.utils.platform import (force_virtual_cpu,  # noqa: E402
                                             probe_backend)

if not probe_backend(timeout_s=120):
    print("backend down -> CPU", flush=True)
    force_virtual_cpu(1)

import jax                                             # noqa: E402
import numpy as np                                     # noqa: E402

from wittgenstein_tpu.core.network import scan_chunk   # noqa: E402
from wittgenstein_tpu.models.dfinity import (Dfinity,  # noqa: E402
                                             partition_by_x)

REF_RATE = {"bad": 5685 / 20_200, "perfect": 6733 / 20_200,
            "bad_partition": 4665 / 20_200}


def run_cond(latency, seeds, sim_s, partition=None):
    cap = max(512, int(sim_s / 3 * 5 * 2))
    proto = Dfinity(block_producers_count=10, attesters_count=10,
                    attesters_per_round=10, network_latency_name=latency,
                    block_capacity=cap)
    ticks = int(sim_s * 1000 // proto.tick_ms)
    t0 = time.perf_counter()
    nets, pss = jax.vmap(proto.init)(np.arange(seeds, dtype=np.int32))
    if partition is not None:
        nets = jax.vmap(lambda n: partition_by_x(n, partition))(nets)
    chunk = min(ticks, 5000)
    step = jax.jit(jax.vmap(scan_chunk(proto, chunk)))
    done = 0
    while done < ticks:
        nets, pss = step(nets, pss)
        done += chunk
    jax.block_until_ready(nets.time)
    wall = time.perf_counter() - t0
    assert int(np.asarray(pss.arena.dropped).sum()) == 0
    heights = np.asarray(pss.arena.height)
    heads = np.asarray(pss.head)
    blocks = np.array([heights[i][heads[i]].max() for i in range(seeds)])
    return blocks / sim_s, wall


def main():
    seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    sim_s = int(sys.argv[2]) if len(sys.argv) > 2 else 300
    out = []
    results = {}
    for cond, latency, part in (
            ("bad", "NetworkLatencyByDistanceWJitter", None),
            ("perfect", "NetworkNoLatency", None),
            ("bad_partition", "NetworkLatencyByDistanceWJitter", 0.2)):
        rates, wall = run_cond(latency, seeds, sim_s, part)
        results[cond] = rates
        ref = REF_RATE[cond]
        rel = rates / ref
        out.append(
            f"| {cond} | {rates.mean():.4f} | {rates.std(ddof=1):.4f} "
            f"| {rates.min():.4f} | {rates.max():.4f} | {ref:.4f} "
            f"| {rel.min():.3f}-{rel.max():.3f} | {wall / 60:.1f} |")
        print(out[-1], flush=True)

    bad = results["bad"] / REF_RATE["bad"]
    ratio = results["bad_partition"] / results["bad"]
    report = REPO / "reports" / "DFINITY_VARIANCE.md"
    report.write_text(f"""# Dfinity block-rate variance study

{seeds} seeds x {sim_s} simulated seconds per condition (the block
process is round-i.i.d., so rates transfer to the reference's 20.2k-s
window with even tighter spread), CPU platform, model defaults of
tests/test_golden_parity.py.

| condition | mean rate (blk/s) | std | min | max | published | measured/published range | wall min |
|---|---|---|---|---|---|---|---|
{chr(10).join(out)}

## Band justification

* **bad network**: measured mean/published = {bad.mean():.3f}, per-seed
  range {bad.min():.3f}-{bad.max():.3f} (std {bad.std(ddof=1):.3f}).  The
  r2 structural analysis (pipeline hides all but ~one beacon hop per
  round) predicted ~3.1-3.2 s/round vs the published sample's 3.55; the
  measured distribution sits exactly there and the golden band of
  [-15%, +20%] around the published rate covers the entire measured
  range with margin on both sides (and the per-seed spread at {sim_s} s
  shrinks ~sqrt({20_200 // max(sim_s, 1)}x) over the full 20.2k-s
  window).
* **perfect network**: deterministic one-block-per-round; measured std
  {results['perfect'].std(ddof=1):.5f} — the exact-rate +/- pipeline-slack
  band in the test is justified.
* **partition ratio**: measured partition/base ratio per seed
  {ratio.min():.3f}-{ratio.max():.3f} (mean {ratio.mean():.3f}) vs the
  published single-sample 0.821 — the published number lies below every
  measured seed, consistent with the r2 analysis that the reference's
  sample reflects an unexplained extra loss (left-side observer or
  partial-duration partition); the band floor of published-0.12 remains
  the right guard.
""")
    print(f"wrote {report}", flush=True)


if __name__ == "__main__":
    main()
