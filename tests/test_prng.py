"""Counter-based PRNG primitives — determinism and the keyed-permutation
pair (bij_perm / bij_perm_inv) that replaces the reference's stored
random-rank matrices (Handel.java:940-948; SURVEY.md §7.4.6)."""

import jax.numpy as jnp
import numpy as np

from wittgenstein_tpu.ops import prng


def test_bij_perm_is_a_permutation():
    for bits in (1, 2, 3, 5, 8, 12):
        n = 1 << bits
        xs = jnp.arange(n, dtype=jnp.int32)
        for key in (0, 1, 77, -5):
            ys = np.asarray(prng.bij_perm(jnp.int32(key), xs, bits))
            assert sorted(ys) == list(range(n)), (bits, key)


def test_bij_perm_inv_round_trips():
    for bits in (1, 2, 3, 4, 7, 11, 16, 20, 31):
        n = min(1 << bits, 4096)
        xs = jnp.arange(n, dtype=jnp.int32)
        for key in (0, 3, 12345, -1):
            k = jnp.int32(key)
            fwd = prng.bij_perm(k, xs, bits)
            back = np.asarray(prng.bij_perm_inv(k, fwd, bits))
            assert np.array_equal(back, np.asarray(xs)), (bits, key)
            # and the other direction
            inv = prng.bij_perm_inv(k, xs, bits)
            fwd2 = np.asarray(prng.bij_perm(k, inv, bits))
            assert np.array_equal(fwd2, np.asarray(xs)), (bits, key)


def test_bij_perm_dyn_matches_static_and_inverts():
    bits = jnp.asarray([3, 5, 8, 8, 12], jnp.int32)
    xs = jnp.asarray([5, 21, 200, 7, 4000], jnp.int32)
    key = jnp.int32(99)
    fwd = prng.bij_perm_dyn(key, xs, bits)
    for i, b in enumerate([3, 5, 8, 8, 12]):
        assert int(fwd[i]) == int(prng.bij_perm(key, xs[i], b))
    back = prng.bij_perm_inv_dyn(key, fwd, bits)
    assert np.array_equal(np.asarray(back), np.asarray(xs))


def test_uniform_float_half_open():
    u = np.asarray(prng.uniform_float(jnp.int32(7),
                                      jnp.arange(10000, dtype=jnp.int32)))
    assert (u >= 0).all() and (u < 1.0).all()
