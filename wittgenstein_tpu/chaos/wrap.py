"""`ChaosProtocol` — compile a `FaultSchedule` into any protocol.

The wrapper threads the schedule through every engine variant by the
same two seams the observability planes use, so no engine grows a
chaos-specific code path:

  * `apply_faults(net, t, gids=None)` — the engine's window-entry
    mutation hook (`core/network.step_ms` / `step_kms`, the batched
    twin, the sharded step): churn down-state and partition membership
    are STATELESS functions of t, evaluated and written at every
    window entry.  Statelessness is what makes the fast-forward engine
    sound (a landing window applies the cumulative state directly) and
    what makes window-entry application bit-identical to per-ms
    application whenever transitions are K-aligned (the
    `superstep_aligned` contract, gated in `check_chunk_config`).
  * `step` / `step_sharded` — the per-ms protocol step: the inner
    step's outbox is post-processed with the loss/delay adversaries.
    A lost unicast has its dest slot cleared (modeling link-level loss
    before the NIC counts it; the engine then never routes it), a
    delayed one gets `extra_ms` added to its sender-chosen delay (the
    engine's own sendArriveAt lane).  Both are per-ms exact in every
    variant because every engine runs the protocol step once per
    simulated ms.

Loss draws are counter-based (`ops/prng`) on (run seed, emit ms,
stable full-width outbox slot id) — exactly the keying discipline of
the engine's latency draws — so the realization is independent of
batch/shard layout: dense, vmapped, batched, fast-forward and sharded
runs of one (schedule, seed) agree bit for bit (tests/test_chaos.py).
The per-step PRNG key the engine already passes in (a raw
``fold_in(PRNGKey(seed), t)`` pair) is folded to the scalar stream
seed, so the wrapper needs no state of its own.

Fast-forward: the wrapper overrides `next_action_time` to clamp the
quiet-window oracle at the next churn/partition transition — a jump
may never cross one, because the oracle's delivery-validity reasoning
(e.g. a cross-partition broadcast arrival it excluded) is evaluated
under the CURRENT fault state and a transition can expand validity.
Landing ON a transition is fine: the landing window's `apply_faults`
evaluates the stateless fault state at the landing time.  Protocols
without the oracle keep not having one (fast-forward then never
jumps, which is trivially sound).

Composes with `obs.diff.FaultInjector` (wrap in either order) and with
every obs plane: taps observe the post-application state the engine
actually runs, so audit verdicts stay clean under churn/partition
(tests/test_chaos.py) and the flight recorder's `node_down`/`node_up`
kinds record each churn transition at its exact ms (obs/trace.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.protocol import FAR_FUTURE
from ..ops import prng
from .schedule import FaultSchedule

#: domain-separation tag for the loss draws (see ops/prng tags)
TAG_CHAOS = 0x43484153      # "CHAS"


def impact_summary(net) -> dict:
    """The 4-counter impact fingerprint of a (possibly seed-batched)
    final NetState — THE shared definition the bench `chaos` block and
    `tools/chaos.py` both report, so the two impact-vs-baseline views
    can never silently disagree."""
    nodes = net.nodes
    down = np.asarray(nodes.down)
    return {
        "done_count": int(((np.asarray(nodes.done_at) > 0) & ~down).sum()),
        "live_count": int((~down).sum()),
        "msg_sent": int(np.asarray(nodes.msg_sent).sum()),
        "msg_received": int(np.asarray(nodes.msg_received).sum()),
    }


def _key_seed(key) -> jnp.ndarray:
    """Fold the engine's per-step raw PRNG key (``fold_in(PRNGKey(seed),
    t)``, a [2] uint32 pair) to one uint32 stream seed.  A pure
    function of (run seed, t) — identical in every engine variant,
    since they all derive the step key the same way."""
    kd = jnp.asarray(key, jnp.uint32).reshape(-1)
    return prng.hash2(kd[0] ^ kd[-1], TAG_CHAOS)


class ChaosProtocol:
    """Protocol proxy carrying a `FaultSchedule` (module docstring).
    Everything not chaos-related delegates to the wrapped protocol, so
    the pair satisfies the same contract (`cfg`, `latency`, `init`,
    `schedule_lcm`/`phase_hints`, `may_self_send`, ...)."""

    def __init__(self, inner, schedule: FaultSchedule):
        if isinstance(schedule, dict):
            schedule = FaultSchedule.from_json(schedule)
        self._inner = inner
        #: the engine gates key on this attribute (`superstep_ok`,
        #: `check_chunk_config`) — one canonical name
        self.chaos_schedule = schedule.validate(n=inner.cfg.n)
        n = inner.cfg.n
        sch = self.chaos_schedule

        # -- churn: static (node, window) arrays + the owned-node mask
        if sch.churn:
            self._ch_node = jnp.asarray([e[0] for e in sch.churn],
                                        jnp.int32)
            self._ch_dm = jnp.asarray([e[1] for e in sch.churn], jnp.int32)
            self._ch_um = jnp.asarray([e[2] for e in sch.churn], jnp.int32)
            owned = np.zeros((n,), bool)
            owned[[e[0] for e in sch.churn]] = True
            self._ch_owned = jnp.asarray(owned)
        # -- partitions: per-event static range masks (few events — the
        # python loop in apply_faults stays tiny and fully unrolled)
        if sch.partitions:
            ever = np.zeros((n,), bool)
            masks = []
            for s, e, pid, lo, hi in sch.partitions:
                m = np.zeros((n,), bool)
                m[lo:hi] = True
                ever |= m
                masks.append(jnp.asarray(m))
            self._pt_masks = masks
            self._pt_ever = jnp.asarray(ever)
        # -- link adversary windows keep their python tuples (static,
        # unrolled in _mutate_outbox); precompute [n] range masks
        if sch.loss or sch.delay:
            self._link_masks = {}
            for kind in ("loss", "delay"):
                for ev in getattr(sch, kind):
                    for lo, hi in ((ev[3], ev[4]), (ev[5], ev[6])):
                        if (lo, hi) not in self._link_masks:
                            m = np.zeros((n,), bool)
                            m[lo:hi] = True
                            self._link_masks[(lo, hi)] = jnp.asarray(m)
        #: fault-state transition times for the fast-forward clamp
        times = sch.transition_times()
        self._trans = jnp.asarray(times, jnp.int32) if times else None
        # a protocol without the quiet-window oracle must stay without
        # one (next_work then treats every ms as active — the instance
        # attribute shadows the class method below)
        if getattr(inner, "next_action_time", None) is None:
            self.next_action_time = None

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # --------------------------------------------- window-entry mutation

    def apply_faults(self, net, t, gids=None):
        """Write the schedule's churn/partition state for absolute time
        `t` into `net.nodes` — the engine's window-entry hook.  Pure
        and stateless in t; a no-op (bitwise) at every non-transition
        ms.  `gids` (sharded engine) maps this shard's local rows to
        global node ids; batched states ([R, N] node leaves) broadcast
        against the [N] fault vectors.

        Ownership contract: a node NAMED in a churn event has its down
        flag fully owned by the schedule — outside its outage windows
        it is UP, including at entry, overriding any down state the
        protocol's init (or the spec's `partition` field) gave it.
        Statelessness requires this: an OR against the carried flag
        could never recover (the carried flag absorbs the outage).
        Express an entry outage as a window starting at ms 0;
        `ScenarioSpec.validate` refuses the partition-field clash."""
        sch = self.chaos_schedule
        if not sch.mutates_state:
            return net
        t = jnp.asarray(t, jnp.int32)
        nodes = net.nodes
        if sch.churn:
            active = (self._ch_dm <= t) & (t < self._ch_um)      # [E]
            down_vec = jnp.zeros((self.cfg.n,), bool).at[
                self._ch_node].max(active)
            owned = self._ch_owned
            if gids is not None:
                down_vec, owned = down_vec[gids], owned[gids]
            nodes = nodes.replace(
                down=jnp.where(owned, down_vec, nodes.down))
        if sch.partitions:
            part_vec = jnp.zeros((self.cfg.n,), jnp.int32)
            managed = jnp.zeros((self.cfg.n,), bool)
            for (s, e, pid, lo, hi), m in zip(sch.partitions,
                                              self._pt_masks):
                act = (t >= s) & (t < e)
                hit = act & m
                part_vec = jnp.where(hit, jnp.int32(pid), part_vec)
                managed = managed | hit
            ever = self._pt_ever
            if gids is not None:
                part_vec, managed, ever = (part_vec[gids], managed[gids],
                                           ever[gids])
            # inside a window: the window's id; outside every window: a
            # managed node heals to the global partition 0 (the
            # reference's endPartition); unmanaged nodes keep whatever
            # partition the underlying state carries
            nodes = nodes.replace(partition=jnp.where(
                managed, part_vec,
                jnp.where(ever, jnp.int32(0), nodes.partition)))
        return net.replace(nodes=nodes)

    # ------------------------------------------------- per-ms adversary

    def _mutate_outbox(self, out, t, key, gids=None):
        sch = self.chaos_schedule
        if not (sch.loss or sch.delay):
            return out
        t = jnp.asarray(t, jnp.int32)
        nl, ke = out.dest.shape
        gid = gids if gids is not None \
            else jnp.arange(self.cfg.n, dtype=jnp.int32)
        dest = out.dest
        live = dest >= 0
        dst_c = jnp.clip(dest, 0, self.cfg.n - 1)

        def link_match(ev):
            s, e, _val, slo, shi, dlo, dhi = ev
            act = (t >= s) & (t < e)
            src_in = self._link_masks[(slo, shi)][gid][:, None]
            dst_in = self._link_masks[(dlo, dhi)][dst_c]
            return act & src_in & dst_in & live

        if sch.delay:
            extra = jnp.zeros((nl, ke), jnp.int32)
            for ev in sch.delay:
                extra = extra + jnp.where(link_match(ev),
                                          jnp.int32(ev[2]), 0)
            out = out.replace(delay=out.delay + extra)
        if sch.loss:
            keep = jnp.ones((nl, ke), jnp.float32)
            for ev in sch.loss:
                keep = keep * jnp.where(link_match(ev),
                                        jnp.float32(1.0 - ev[2] / 1000.0),
                                        jnp.float32(1.0))
            # stable full-width slot id — the same id the engine keys
            # the latency draw on (`_route_unicast`), so the draw is
            # layout-independent
            midx = gid[:, None] * self.cfg.out_deg + out.slot0 + \
                jnp.arange(ke, dtype=jnp.int32)[None, :]
            u = prng.uniform_float(_key_seed(key), midx)
            lost = live & (u < (jnp.float32(1.0) - keep))
            out = out.replace(dest=jnp.where(lost, jnp.int32(-1), dest))
        return out

    # ------------------------------------------------- protocol contract

    def step(self, pstate, nodes, inbox, t, key, **kw):
        pstate, nodes, out = self._inner.step(pstate, nodes, inbox, t,
                                              key, **kw)
        return pstate, nodes, self._mutate_outbox(out, t, key)

    def step_sharded(self, pstate, nodes, inbox, t, key, gids):
        inner = getattr(self._inner, "step_sharded", None)
        if inner is not None:
            pstate, nodes, out = inner(pstate, nodes, inbox, t, key, gids)
        else:
            pstate, nodes, out = self._inner.step(pstate, nodes, inbox,
                                                  t, key)
        return pstate, nodes, self._mutate_outbox(out, t, key, gids=gids)

    def next_action_time(self, pstate, nodes, t):
        """The inner oracle clamped at the next churn/partition
        transition >= t (module docstring) — only defined when the
        inner protocol has the oracle (see __init__)."""
        nxt = self._inner.next_action_time(pstate, nodes, t)
        if self._trans is None:
            return nxt
        t = jnp.asarray(t, jnp.int32)
        nxt_f = jnp.min(jnp.where(self._trans >= t, self._trans,
                                  jnp.int32(FAR_FUTURE)))
        return jnp.minimum(jnp.asarray(nxt, jnp.int32),
                           nxt_f).astype(jnp.int32)
