"""Minimized repro + factor isolation of the >= 2^19 cardinal worker
crash (VERDICT r4 #8).

Round-4 facts (BENCH_NOTES.md): cardinal Handel runs CLEAN single-chip
at N = 2^18 = 262,144 (200 sim-ms, zero drops), but the TPU worker
process crashes outright ("kernel fault") executing the first chunk at
N = 2^19 — at BOTH 805 MB and forced-402 MB ring sub-planes, so it is
not the known ~1 GB single-buffer limit.  2^20 compiles (7.25 GB
resident) and crashes the same way.

An N-bisection is impossible: the level-tree protocols only support
power-of-two node counts and there is no power of two strictly between
2^18 and 2^19.  Instead this tool ISOLATES THE FACTOR with a matched
grid (each probe in a fresh subprocess — the fault poisons a process):

  A  N=2^18, horizon 96   — r4 known-good baseline
  B  N=2^19, horizon 96   — r4 known-bad baseline
  C  N=2^18, horizon 192  — same TOTAL ring bytes as B at half the N
  D  N=2^19, horizon 48   — same TOTAL ring bytes as A at twice the N

C fail + D ok   -> total-allocation fault (bytes, not node count).
C ok  + D fail  -> N-specific fault (scatter index space, buffer
                   count, or program shape — actionable for runtime
                   owners as "not memory pressure").
Results land in reports/RUNTIME_FAULT_REPRO.md; the `repro` mode is
the one-file standalone handover.

RUN THIS LAST in a round: the crash probes have historically wedged the
tunnel for hours (r4 end-of-round note) — never before the official
bench capture.

Usage:
  python tools/runtime_fault_repro.py repro <N> [sim_ms] [horizon]
  python tools/runtime_fault_repro.py grid
Env: WTPU_REPRO_SPLIT (box_split override; default sized to keep every
     ring sub-plane under 512 MB).
"""

import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

REPORT = REPO / "reports" / "RUNTIME_FAULT_REPRO.md"
SIM_MS = 20
HORIZON = 96                     # the r4 1M diet config (cardinal_1m)
INBOX = 12


def default_split(n, horizon):
    """Smallest power-of-two box_split keeping each ring sub-plane
    (horizon * n/P * INBOX int32) under 512 MB — half the known ~1 GB
    limit, so every probe exercises ONLY the unexplained fault."""
    p = 1
    while horizon * (n // p) * INBOX * 4 > 512 * 2 ** 20:
        p *= 2
    return p


def repro(n, sim_ms=SIM_MS, horizon=HORIZON):
    """The minimal faulting program (run in a fresh process)."""
    import jax

    from wittgenstein_tpu.core.network import scan_chunk
    from wittgenstein_tpu.models.handel import Handel

    split = int(os.environ.get("WTPU_REPRO_SPLIT",
                               default_split(n, horizon)))
    print(f"repro: N={n} split={split} horizon={horizon} inbox={INBOX} "
          f"platform={jax.default_backend()}", flush=True)
    proto = Handel(node_count=n, threshold=int(0.9 * n), mode="cardinal",
                   queue_cap=8, inbox_cap=INBOX, horizon=horizon)
    import dataclasses
    proto.cfg = dataclasses.replace(proto.cfg, box_split=split)
    t0 = time.perf_counter()
    net, ps = proto.init(0)
    print(f"repro: init done {time.perf_counter() - t0:.1f}s", flush=True)
    step = jax.jit(scan_chunk(proto, sim_ms))
    net, ps = step(net, ps)
    t = int(jax.device_get(net.time))          # materialize = execute
    print(f"repro: OK — t={t}, wall {time.perf_counter() - t0:.1f}s",
          flush=True)
    assert t == sim_ms


GRID = [
    ("A (r4 known-good)", 1 << 18, HORIZON),
    ("B (r4 known-bad)", 1 << 19, HORIZON),
    ("C (2^18, B's total ring bytes)", 1 << 18, 2 * HORIZON),
    ("D (2^19, A's total ring bytes)", 1 << 19, HORIZON // 2),
]


def grid():
    rows = []
    results = {}
    for label, n, horizon in GRID:
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, __file__, "repro", str(n),
                 str(SIM_MS), str(horizon)],
                capture_output=True, text=True, timeout=7200)
            ok = r.returncode == 0
            res = "OK" if ok else f"FAIL rc={r.returncode}"
            tail = (r.stdout + r.stderr).strip().splitlines()
            tail = tail[-1][:120] if tail else ""
        except subprocess.TimeoutExpired:
            # A wedged probe must not lose the completed rows: record
            # it and KEEP GOING (later probes will fail fast against
            # the wedged tunnel and the table will say so honestly).
            ok, res, tail = False, "TIMEOUT 7200s (tunnel wedge?)", ""
        wall = time.perf_counter() - t0
        results[label[0]] = ok
        rows.append((label, n, horizon, res, f"{wall:.0f}", tail))
        print(f"grid: {label}: {res} ({wall:.0f}s)", flush=True)
        write_report(rows, results)      # persist after EVERY probe


def write_report(rows, results):
    lines = [
        "# Runtime-fault repro: cardinal worker crash at >= 2^19 nodes",
        "",
        "Standalone repro: `python tools/runtime_fault_repro.py repro "
        "<N> [sim_ms] [horizon]` — init + one 20-ms cardinal chunk + "
        "materialize, fresh process, ring sub-planes capped at 512 MB "
        "(half the known ~1 GB single-buffer limit, so only the "
        "unexplained fault is in play).  r4 facts: 2^18 clean, "
        "2^19/2^20 worker crash ('kernel fault') at any sub-plane "
        "sizing (BENCH_NOTES.md).  No power of two exists strictly "
        "between them, so instead of a bisection the grid below "
        "matches TOTAL ring bytes across the N boundary.",
        "",
        "| probe | N | horizon | result | wall s | last line |",
        "|---|---|---|---|---|---|",
    ]
    for label, n, horizon, res, wall, tail in rows:
        lines.append(f"| {label} | {n:,} | {horizon} | {res} | {wall} "
                     f"| `{tail}` |")
    lines.append("")
    if {"A", "B", "C", "D"} <= set(results):
        if results["A"] and not results["B"]:
            if results["C"] and not results["D"]:
                lines.append(
                    "**Verdict: N-SPECIFIC fault** — 2^18 stays clean "
                    "even at 2^19's total ring bytes (C ok) and 2^19 "
                    "fails even at 2^18's (D fail): node count, not "
                    "allocation size, triggers it (scatter index "
                    "space / buffer count / program shape).")
            elif not results["C"] and results["D"]:
                lines.append(
                    "**Verdict: TOTAL-ALLOCATION fault** — the byte "
                    "total, not the node count, reproduces it (C "
                    "fail, D ok).")
            else:
                lines.append(
                    f"**Mixed outcome (C ok={results['C']}, D "
                    f"ok={results['D']})** — both factors contribute; "
                    "see the table.")
        else:
            lines.append("**Endpoints did not match the r4 facts** "
                         "(A clean / B crash) — the runtime changed; "
                         "see the table.")
    REPORT.write_text("\n".join(lines) + "\n")
    print(f"wrote {REPORT}", flush=True)


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "grid"
    if mode == "repro":
        repro(int(sys.argv[2]),
              int(sys.argv[3]) if len(sys.argv) > 3 else SIM_MS,
              int(sys.argv[4]) if len(sys.argv) > 4 else HORIZON)
    else:
        grid()
