"""Cardinal-mode Handel (models/handel_cardinal.py) — the O(N*L) tier-3
variant.  Mirrors the exact-mode test recipe (HandelTest.java): init
invariants, convergence, determinism, byzantine attacks, plus the
mode-dispatch plumbing and the drift band vs exact mode."""

import numpy as np
import pytest

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.core.protocol import PROTOCOLS
from wittgenstein_tpu.models.handel import Handel
from wittgenstein_tpu.models.handel_cardinal import HandelCardinal


def _cardinal(n=256, down=25, **kw):
    thr = kw.pop("threshold", int(0.99 * (n - down)))
    return HandelCardinal(node_count=n, nodes_down=down, threshold=thr,
                          pairing_time=4, dissemination_period_ms=20,
                          fast_path=10, **kw)


def _run(p, ms, seed=0):
    r = Runner(p, donate=False)
    net, ps = p.init(seed)
    net, ps = r.run_ms(net, ps, ms)
    return net, ps


def test_mode_dispatch_and_registry():
    p = Handel(node_count=256, nodes_down=25, threshold=229, mode="cardinal")
    assert isinstance(p, HandelCardinal)
    assert not isinstance(p, Handel)
    assert isinstance(Handel(node_count=256), Handel)
    assert PROTOCOLS["HandelCardinal"] is HandelCardinal
    with pytest.raises(ValueError, match="unknown Handel mode"):
        Handel(node_count=256, mode="nope")
    with pytest.raises(TypeError):
        # exact-only scale switches are not cardinal parameters
        Handel(node_count=256, mode="cardinal", emission_mode="hashed")
    with pytest.raises(ValueError, match="blacklist"):
        HandelCardinal(node_count=1 << 18, nodes_down=100,
                       byzantine_suicide=True)


def test_cardinal_converges_and_counts_are_sane():
    p = _cardinal()
    net, ps = _run(p, 1500)
    done_at = np.asarray(net.nodes.done_at)
    down = np.asarray(net.nodes.down)
    assert (done_at[~down] > 0).all()
    assert int(net.dropped) == 0 and int(net.clamped) == 0
    # Per-level bests never exceed the level size.
    lvl_best = np.asarray(ps.lvl_best)
    assert (lvl_best <= p.half[None, :]).all()
    assert (lvl_best >= 0).all()
    # Done nodes reached the threshold.
    total = 1 + lvl_best.sum(axis=1)
    assert (total[~down & (done_at > 0)] >= p.threshold).all()
    assert int(np.asarray(ps.sigs_checked).sum()) > 0


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 68 s; cardinal coverage stays via test_cardinal_converges_... and the
# phase-hint cardinal equality pair
def test_cardinal_determinism():
    p = _cardinal(n=128, down=12)
    net1, ps1 = _run(p, 1200, seed=5)
    net2, ps2 = _run(p, 1200, seed=5)
    assert np.array_equal(np.asarray(net1.nodes.done_at),
                          np.asarray(net2.nodes.done_at))
    assert np.array_equal(np.asarray(ps1.lvl_best), np.asarray(ps2.lvl_best))
    net3, _ = _run(p, 1200, seed=6)
    assert not np.array_equal(np.asarray(net1.nodes.done_at),
                              np.asarray(net3.nodes.done_at))


@pytest.mark.slow
def test_cardinal_drift_vs_exact_small():
    """The count-based accounting is the same per-level math as exact mode
    (updateVerifiedSignatures, Handel.java:686-750); dropped optimizations
    (demotion, finished-peer skip, union repair) shift completion times
    only modestly.  Band check at 512; the measured study lives in
    reports/CARDINAL_DRIFT.md."""
    means = {}
    for mode in ("exact", "cardinal"):
        p = Handel(node_count=512, nodes_down=51, threshold=int(0.99 * 461),
                   pairing_time=4, dissemination_period_ms=20, fast_path=10,
                   mode=mode)
        net, _ = _run(p, 2000)
        done_at = np.asarray(net.nodes.done_at)
        down = np.asarray(net.nodes.down)
        assert (done_at[~down] > 0).all(), mode
        means[mode] = done_at[~down].mean()
    drift = means["cardinal"] / means["exact"] - 1
    assert abs(drift) < 0.25, means


@pytest.mark.slow
def test_cardinal_byzantine_suicide():
    p = _cardinal(n=256, down=64, threshold=150, byzantine_suicide=True)
    net, ps = _run(p, 2500)
    done_at = np.asarray(net.nodes.done_at)
    down = np.asarray(net.nodes.down)
    assert (done_at[~down] > 0).all()
    # The attack planted invalid sigs: blacklists are non-empty.
    assert int(np.asarray(ps.blacklist).astype(np.uint64).sum()) > 0


@pytest.mark.slow
def test_cardinal_hidden_byzantine_slows_completion():
    base = _cardinal(n=256, down=64, threshold=150)
    att = _cardinal(n=256, down=64, threshold=150, hidden_byzantine=True)
    m = {}
    for name, p in (("base", base), ("att", att)):
        net, _ = _run(p, 5000)
        done_at = np.asarray(net.nodes.done_at)
        down = np.asarray(net.nodes.down)
        assert (done_at[~down] > 0).all(), name
        m[name] = done_at[~down].mean()
    # Useless count-1 plants waste verification slots.
    assert m["att"] >= m["base"], m


@pytest.mark.slow
def test_cardinal_vmap_seeds():
    import jax
    from wittgenstein_tpu.core.network import scan_chunk
    p = _cardinal(n=128, down=12)
    seeds = np.arange(2, dtype=np.int32)
    nets, pss = jax.vmap(p.init)(seeds)
    nets, pss = jax.jit(jax.vmap(scan_chunk(p, 1200)))(nets, pss)
    done_at = np.asarray(nets.nodes.done_at)
    down = np.asarray(nets.nodes.down)
    for i in range(2):
        assert (done_at[i][~down[i]] > 0).all()
    # Batch row 0 equals the single-seed run bit-for-bit.
    net0, _ = _run(p, 1200, seed=0)
    assert np.array_equal(done_at[0], np.asarray(net0.nodes.done_at))


@pytest.mark.slow
def test_cardinal_drift_vs_exact_4096():
    """Larger-N drift point (the VERDICT-requested 4k treatment; full
    multi-seed study in reports/CARDINAL_DRIFT.md)."""
    means = {}
    for mode in ("exact", "cardinal"):
        p = Handel(node_count=4096, nodes_down=409,
                   threshold=int(0.99 * 3687), pairing_time=4,
                   dissemination_period_ms=20, fast_path=10, mode=mode)
        net, _ = _run(p, 3000)
        done_at = np.asarray(net.nodes.done_at)
        down = np.asarray(net.nodes.down)
        assert (done_at[~down] > 0).all(), mode
        means[mode] = done_at[~down].mean()
    drift = means["cardinal"] / means["exact"] - 1
    assert abs(drift) < 0.25, means
