"""Serve-plane resilience (PR 10): retry-with-backoff, batch-width
degradation, chunk-boundary checkpoint/resume — driven by a
fault-injected launcher (the `Scheduler(launcher=)` seam), with
bit-identity to an uninterrupted run as the acceptance bar, plus the
chaos plane riding the request plane end to end.

Crash-only additions (PR 15): the poison-lane quarantine pin (one
planted always-fails lane inside a coalesced group fails ALONE, its
neighbors bit-identical to solo runs), the hung-launch watchdog pin
(a sleeping launcher is abandoned at its deadline and the drain loop's
wall stays bounded), and the stream-termination pin (a long-poll on a
failing/quarantined request returns a final error record instead of
hanging until client timeout).
"""

import dataclasses
import os
import threading
import time

import jax
import numpy as np
import pytest

import wittgenstein_tpu.models  # noqa: F401 — fill the registry
from wittgenstein_tpu.serve import (CompileRegistry, ScenarioSpec,
                                    Scheduler, Service)


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _spec(**kw):
    base = dict(protocol="PingPong", params={"node_count": 64},
                seeds=(0, 1), sim_ms=120, chunk_ms=40,
                obs=("metrics",))
    base.update(kw)
    return ScenarioSpec(**base)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """One uninterrupted run of the canonical spec — the bit-identity
    reference every resilience path is compared against."""
    sched = Scheduler(ledger_path=str(
        tmp_path_factory.mktemp("led") / "ref.jsonl"))
    rid = sched.submit(_spec())
    sched.run_pending()
    req = sched.request(rid)
    assert req.status == "done", req.error
    return req.final_state


def test_retry_with_backoff(reference):
    calls = {"n": 0}

    def flaky(fn, *args):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("injected launch failure")
        return fn(*args)

    sched = Scheduler(launcher=flaky, retry_backoff_s=0.0)
    rid = sched.submit(_spec())
    sched.run_pending()
    req = sched.request(rid)
    assert req.status == "done", req.error
    assert sched.resilience["retries"] == 2
    assert req.artifacts["resilience"]["retries"] == 2
    _trees_equal(reference, req.final_state)


def test_retries_exhausted_fails_group():
    def dead(fn, *args):
        raise RuntimeError("device gone")

    sched = Scheduler(launcher=dead, retry_backoff_s=0.0, max_retries=1)
    rid = sched.submit(_spec())
    sched.run_pending()
    req = sched.request(rid)
    assert req.status == "error"
    assert "device gone" in req.error


def test_width_degradation(reference):
    """A launcher that faults at full batch width (the OOM shape):
    the scheduler halves the lane batch and runs the halves
    sequentially instead of dropping requests — per-lane results
    bit-identical to the full-width run."""
    def narrow(fn, *args):
        if int(args[0].time.shape[0]) > 1:
            raise RuntimeError("injected OOM at full width")
        return fn(*args)

    sched = Scheduler(launcher=narrow, retry_backoff_s=0.0, max_retries=0)
    r1 = sched.submit(_spec(seeds=(0,)))
    r2 = sched.submit(_spec(seeds=(1,)))
    assert sched.request(r1).compile_key == sched.request(r2).compile_key
    sched.run_pending()
    assert sched.request(r1).status == "done", sched.request(r1).error
    assert sched.request(r2).status == "done", sched.request(r2).error
    assert sched.resilience["demotions"] > 0
    _trees_equal(jax.tree.map(lambda x: x[:1], reference),
                 sched.request(r1).final_state)
    _trees_equal(jax.tree.map(lambda x: x[1:], reference),
                 sched.request(r2).final_state)


def test_checkpoint_resume_bit_identical(reference, tmp_path):
    """Kill the scheduler after one chunk, resume from the checkpoint
    in a FRESH scheduler: the continuation is bit-identical to the
    uninterrupted run and the ledger row records the resume point."""
    ck = str(tmp_path / "ck")
    state = {"n": 0}

    def killer(fn, *args):
        if state["n"] >= 1:
            raise RuntimeError("KILLED")
        state["n"] += 1
        return fn(*args)

    crashed = Scheduler(launcher=killer, retry_backoff_s=0.0,
                        max_retries=0, checkpoint_dir=ck)
    rid = crashed.submit(_spec())
    crashed.run_pending()
    assert crashed.request(rid).status == "error"
    key = crashed.request(rid).compile_key
    assert os.path.exists(os.path.join(ck, f"group-{key[:16]}.npz"))

    from wittgenstein_tpu.obs import ledger
    led = str(tmp_path / "resumed.jsonl")
    fresh = Scheduler(checkpoint_dir=ck, ledger_path=led)
    rids = fresh.resume_checkpoints()
    assert len(rids) == 1
    fresh.run_pending()
    req = fresh.request(rids[0])
    assert req.status == "done", req.error
    assert req.resumed_from_ms == 40
    assert req.artifacts["resumed_from_ms"] == 40
    # THE acceptance pin: full-pytree equality with the uninterrupted
    # run (the first_divergence criterion, evaluated directly — the
    # final states are the whole trajectory's fingerprint for a
    # deterministic pure engine)
    _trees_equal(reference, req.final_state)
    # the finished group's checkpoint is gone; the ledger row carries
    # the resume provenance
    assert not os.path.exists(os.path.join(ck, f"group-{key[:16]}.npz"))
    rows = ledger.read_all(led)
    assert len(rows) == 1


def test_submit_never_overwrites_restored_ids():
    """Checkpoint-restored requests keep their original ids, which can
    sit AHEAD of a fresh scheduler's counter — submit() must allocate
    around them, never overwrite one."""
    from wittgenstein_tpu.serve.scheduler import Request

    sched = Scheduler()
    restored = _spec().validate()
    with sched._mu:
        # what resume_checkpoints leaves behind: a preserved id the
        # counter has not reached yet
        sched._requests["r0001"] = Request(
            id="r0001", spec=restored, compile_key=restored.compile_key())
    rid = sched.submit(_spec(seeds=(5, 6)))
    assert rid == "r0002"
    assert sched.request("r0001").spec is restored


def test_resume_empty_dir_is_noop(tmp_path):
    sched = Scheduler(checkpoint_dir=str(tmp_path / "none"))
    assert sched.resume_checkpoints() == []
    assert Scheduler().resume_checkpoints() == []


# --------------------------------------------------- crash-only (PR 15)


def _poison_launcher():
    """The deterministic always-fails-for-one-lane launcher: the
    poison request carries partition=(5,) (DATA — same compile key as
    its neighbors), so its lane is identifiable in ANY batch slice by
    node 5's down flag; every launch whose batch contains it fails."""
    def poison(fn, *args):
        if np.asarray(jax.device_get(args[0].nodes.down))[..., 5].any():
            raise RuntimeError("poison lane fault")
        return fn(*args)
    return poison


def test_poison_lane_quarantine_isolates_one_request(tmp_path):
    """THE quarantine pin: a 4-lane coalesced group with one planted
    poison lane fails ONLY that request — `quarantined` artifact +
    ledger row + per-tenant stat — and the other 3 lanes' final
    pytrees AND metrics/audit artifacts are bit-identical to solo
    Runner-equivalent (single-request scheduler) runs."""
    from wittgenstein_tpu.obs import ledger

    reg = CompileRegistry()
    healthy = [0, 1, 3]
    spec = _spec(obs=("metrics", "audit"))
    led = str(tmp_path / "led.jsonl")
    sched = Scheduler(registry=reg, launcher=_poison_launcher(),
                      retry_backoff_s=0.0, max_retries=0,
                      ledger_path=led)
    rids = {s: sched.submit(dataclasses.replace(spec, seeds=(s,)))
            for s in healthy[:2]}
    poison_rid = sched.submit(dataclasses.replace(
        spec, seeds=(2,), partition=(5,)))
    rids[3] = sched.submit(dataclasses.replace(spec, seeds=(3,)))
    keys = {sched.request(r).compile_key for r in rids.values()}
    assert keys == {sched.request(poison_rid).compile_key}  # coalesced
    sched.run_pending()

    bad = sched.request(poison_rid)
    assert bad.status == "error"
    assert "quarantined" in bad.error
    assert bad.artifacts["quarantined"] is True
    assert sched.resilience["quarantined"] == 1
    assert sched.tenancy_stats()["tenants"]["default"]["quarantined"] \
        == 1
    qrows = [r for r in ledger.read_all(led)
             if (r.extra or {}).get("quarantined")]
    assert len(qrows) == 1 and qrows[0].run == f"serve:{poison_rid}"

    # the 3 neighbors: done, and bit-identical to SOLO runs (final
    # pytree + metrics/audit blocks) — the quarantine left no residue
    for s in healthy:
        req = sched.request(rids[s])
        assert req.status == "done", req.error
        solo = Scheduler(registry=reg)
        solo_rid = solo.submit(dataclasses.replace(spec, seeds=(s,)))
        solo.run_pending()
        ref = solo.request(solo_rid)
        _trees_equal(ref.final_state, req.final_state)
        assert req.artifacts["summary"] == ref.artifacts["summary"]
        assert req.artifacts["engine_metrics"] == \
            ref.artifacts["engine_metrics"]
        assert req.artifacts["audit"] == ref.artifacts["audit"]


def test_watchdog_abandons_hung_launch(reference):
    """THE watchdog pin: a launcher that sleeps far past the deadline
    on its first call is abandoned on its worker thread, the retry
    completes the group bit-identically, and the drain loop's wall
    stays bounded by the deadline — never by the sleep."""
    reg = CompileRegistry()
    warm = Scheduler(registry=reg)
    wid = warm.submit(_spec())
    warm.run_pending()              # compile outside the timed window
    assert warm.request(wid).status == "done"

    calls = {"n": 0}

    def sleepy(fn, *args):
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(60)          # the wedge; daemon thread outlives
        return fn(*args)

    sched = Scheduler(registry=reg, launcher=sleepy,
                      retry_backoff_s=0.0, max_retries=1,
                      watchdog_factor=8.0, watchdog_floor_s=0.5)
    rid = sched.submit(_spec())
    t0 = time.perf_counter()
    sched.run_pending()
    elapsed = time.perf_counter() - t0
    req = sched.request(rid)
    assert req.status == "done", req.error
    assert sched.resilience["watchdog_trips"] == 1
    assert sched.resilience["retries"] == 1
    # the drain never blocked on the 60 s sleep: bound = deadline
    # (0.5 s) + the warm re-launch + slack, far under the sleep
    assert elapsed < 20, elapsed
    _trees_equal(reference, req.final_state)
    health = sched.health_stats()
    assert health["watchdog_trips"] == 1
    assert health["watchdog_deadline_s"] is not None


def test_stream_terminates_on_error_and_quarantine():
    """THE stream-termination pin: a `/w/batch/stream/{id}`-equivalent
    long-poll on a request that fails (or is quarantined) returns a
    final error record promptly — it must never hang until the client
    timeout."""
    def dead(fn, *args):
        raise RuntimeError("device gone")

    sched = Scheduler(launcher=dead, retry_backoff_s=0.0, max_retries=0)
    rid = sched.submit(_spec())
    out: dict = {}
    th = threading.Thread(
        target=lambda: out.update(sched.stream_chunks(rid,
                                                      timeout_s=30.0)))
    th.start()
    time.sleep(0.1)                 # the poll is parked on the condvar
    t0 = time.perf_counter()
    sched.run_pending()
    th.join(timeout=10)
    assert not th.is_alive(), "stream long-poll hung past the failure"
    assert time.perf_counter() - t0 < 10
    assert out["status"] == "error" and out["eof"]
    assert "device gone" in out["error"]

    # quarantined flavor: the final record carries the verdict
    sched2 = Scheduler(launcher=_poison_launcher(),
                       retry_backoff_s=0.0, max_retries=0)
    ok_rid = sched2.submit(_spec(seeds=(0,)))
    poison_rid = sched2.submit(_spec(seeds=(2,), partition=(5,)))
    out2: dict = {}
    th2 = threading.Thread(
        target=lambda: out2.update(sched2.stream_chunks(poison_rid,
                                                        timeout_s=30.0)))
    th2.start()
    time.sleep(0.1)
    sched2.run_pending()
    th2.join(timeout=10)
    assert not th2.is_alive()
    assert out2["eof"] and out2.get("quarantined") is True
    assert sched2.request(ok_rid).status == "done"


def test_chaos_spec_through_service(tmp_path):
    """A fault_schedule spec rides the whole request plane: coalesced
    by compile key (adversity is program), audited clean under
    churn/partition, and a planted counter attack is STILL flagged in
    its own window through the serve path."""
    fs = {"churn": [[3, 20, 60]], "partitions": [[30, 90, 1, 0, 32]]}
    spec = _spec(obs=("metrics", "audit"), fault_schedule=fs)
    svc = Service(scheduler=Scheduler(
        ledger_path=str(tmp_path / "l.jsonl")), auto=False)
    a = svc.submit(spec.to_json())
    b = svc.submit(dataclasses.replace(spec, seeds=(2, 3)).to_json())
    assert a["compile_key"] == b["compile_key"]      # same adversity
    plain = svc.submit(_spec(obs=("metrics", "audit")).to_json())
    assert plain["compile_key"] != a["compile_key"]  # program differs
    svc.run_pending()
    ra = svc.result(a["id"])
    assert ra["status"] == "done"
    assert ra["audit"]["clean"], ra["audit"]
    assert ra["spec"]["fault_schedule"] == fs

    # chaos + attack: the planted fault must still be caught
    attacked = dataclasses.replace(
        spec, seeds=(9,),
        attack={"at_ms": 37, "leaf": "nodes.msg_sent", "node": 5,
                "delta": -(1 << 20)})
    c = svc.submit(attacked.to_json())
    svc.run_pending()
    rc = svc.result(c["id"])
    assert rc["status"] == "done"
    assert not rc["audit"]["clean"]
    assert rc["audit"]["first"]["invariant"] == "counter_monotone"
    assert rc["audit"]["first"]["ms"] == 37

    # a malformed schedule 400s at submit with remedy text
    with pytest.raises(ValueError, match="ONE partition at a time"):
        svc.submit(_spec(fault_schedule={
            "partitions": [[10, 50, 1, 0, 32],
                           [20, 60, 2, 16, 48]]}).to_json())
