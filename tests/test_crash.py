"""Crash-only serve (PR 15): the durable submission journal and the
kill-anywhere recovery harness.

Fast tests pin the journal's replay edge cases from the WAL contract:
queued-but-unlaunched submits survive a process death, a double replay
refuses duplicate rids, a torn tail line after a tombstone is
tolerated loudly, a request with BOTH a journal entry and a group
checkpoint resumes from the checkpoint (never from scratch), and an
empty/missing journal is a no-op.  The slow tests drive the real
thing: the in-process matrix campaign kill with journal+checkpoint
resume, and tools/crash_test.py SIGKILLing a subprocess campaign at
>= 5 seeded-random offsets with the final `MatrixReport` bit-identical
to the uninterrupted run's.
"""

import dataclasses
import os
import time

import jax
import numpy as np
import pytest

import wittgenstein_tpu.models  # noqa: F401 — fill the registry
from wittgenstein_tpu.serve import (CompileRegistry, ScenarioSpec,
                                    Scheduler)
from wittgenstein_tpu.serve.journal import SubmissionJournal


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _spec(**kw):
    base = dict(protocol="PingPong", params={"node_count": 64},
                seeds=(0, 1), sim_ms=120, chunk_ms=40,
                obs=("metrics",))
    base.update(kw)
    return ScenarioSpec(**base)


@pytest.fixture(scope="module")
def registry():
    """One compiled program set for the module (the journal is
    host-side; every test runs the same chunk program)."""
    return CompileRegistry()


@pytest.fixture(scope="module")
def reference(registry, tmp_path_factory):
    sched = Scheduler(registry=registry, ledger_path=str(
        tmp_path_factory.mktemp("led") / "ref.jsonl"))
    rid = sched.submit(_spec())
    sched.run_pending()
    req = sched.request(rid)
    assert req.status == "done", req.error
    return req.final_state


def test_journal_replays_queued_but_unlaunched(registry, reference,
                                               tmp_path):
    """The WAL's reason to exist: submits ACCEPTED but never launched
    when the process died replay in a fresh scheduler — with their
    original rids, labels and ledger_extra — and run bit-identically;
    completion tombstones them (journal lag returns to 0)."""
    jd = str(tmp_path / "journal")
    dying = Scheduler(registry=registry, journal_dir=jd)
    a = dying.submit(_spec(), label="crash:a",
                     ledger_extra={"campaign": "x"})
    b = dying.submit(_spec(seeds=(7,)))
    assert SubmissionJournal(jd).lag() == 2
    # the process dies HERE — nothing ran, nothing checkpointed

    fresh = Scheduler(registry=registry, journal_dir=jd,
                      ledger_path=str(tmp_path / "led.jsonl"))
    got = fresh.recover()
    assert got["checkpoints"] == [] and got["journal"] == [a, b]
    assert fresh.request(a).label == "crash:a"
    assert fresh.request(a).ledger_extra == {"campaign": "x"}
    fresh.run_pending()
    assert fresh.request(a).status == "done"
    assert fresh.request(b).status == "done"
    _trees_equal(reference, fresh.request(a).final_state)
    assert SubmissionJournal(jd).lag() == 0
    assert fresh.resilience["replayed"] == 2


def test_double_replay_refuses_duplicate_rids(registry, tmp_path):
    jd = str(tmp_path / "journal")
    Scheduler(registry=registry, journal_dir=jd).submit(_spec())
    fresh = Scheduler(registry=registry, journal_dir=jd)
    assert len(fresh.resume_journal()) == 1
    # second replay: the rid is live — refused, not duplicated
    assert fresh.resume_journal() == []
    assert len(fresh.pending()) == 1


def test_tombstone_then_torn_tail_tolerated(registry, tmp_path,
                                            capsys):
    """A kill mid-append leaves a torn final line AFTER valid
    submit/tombstone rows: the tombstoned entry stays dead, the live
    entry replays, and the torn line is skipped with a loud stderr
    note (never raised)."""
    jd = str(tmp_path / "journal")
    j = SubmissionJournal(jd)
    j.record_submit("r0001", _spec())
    j.record_submit("r0002", _spec(seeds=(7,)))
    j.record_settled("r0001", "done")
    with open(j.path, "a") as f:
        f.write('{"kind": "submit", "rid": "r00')    # the torn tail
    fresh = Scheduler(registry=registry, journal_dir=jd)
    rids = fresh.resume_journal()
    assert rids == ["r0002"]
    assert "torn final line" in capsys.readouterr().err
    # compaction rewrote the journal down to the one live entry
    rows = open(j.path).read().strip().splitlines()
    assert len(rows) == 1 and '"r0002"' in rows[0]


def test_journal_plus_checkpoint_resumes_from_checkpoint(
        registry, reference, tmp_path):
    """A request with BOTH a journal entry and a group checkpoint
    resumes from the CHECKPOINT (progress kept), not from scratch —
    the journal entry is recognized by rid and skipped."""
    ck, jd = str(tmp_path / "ck"), str(tmp_path / "journal")
    calls = {"n": 0}

    def killer(fn, *args):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise RuntimeError("KILLED")
        return fn(*args)

    crashed = Scheduler(registry=registry, launcher=killer,
                        retry_backoff_s=0.0, max_retries=0,
                        checkpoint_dir=ck, journal_dir=jd)
    rid = crashed.submit(_spec())
    crashed.run_pending()
    assert crashed.request(rid).status == "error"
    assert os.listdir(ck)                   # chunk-1 checkpoint kept
    assert SubmissionJournal(jd).lag() == 1  # group errors replay

    fresh = Scheduler(registry=registry, checkpoint_dir=ck,
                      journal_dir=jd,
                      ledger_path=str(tmp_path / "led.jsonl"))
    got = fresh.recover()
    assert len(got["checkpoints"]) == 1
    assert got["journal"] == []             # skipped by rid — NOT a
    # second from-scratch copy of the same request
    req = fresh.request(got["checkpoints"][0])
    assert req.resumed_from_ms == 40        # from the checkpoint
    fresh.run_pending()
    assert req.status == "done", req.error
    _trees_equal(reference, req.final_state)
    assert SubmissionJournal(jd).lag() == 0


def test_empty_or_missing_journal_is_noop(tmp_path):
    assert Scheduler().resume_journal() == []
    sched = Scheduler(journal_dir=str(tmp_path / "fresh"))
    assert sched.resume_journal() == []
    assert sched.health_stats()["journal_lag"] == 0


def test_journal_write_failure_unaccepts_the_submit(tmp_path):
    """The durability promise: if the WAL append fails, the submit
    must fail LOUDLY and leave no half-accepted request behind."""
    jd = str(tmp_path / "journal")
    sched = Scheduler(journal_dir=jd)
    os.makedirs(sched.journal.path)         # append now raises OSError
    with pytest.raises(RuntimeError, match="NOT accepted"):
        sched.submit(_spec())
    assert sched.pending() == []
    assert sched._requests == {}


# -------------------------------------------------------- fleet leases


def test_lease_double_claim_refuses_without_append(tmp_path):
    """The common contention case: a second worker's claim on a LIVE
    lease returns False and appends NOTHING — refusal costs no disk
    row, so a hot rid can't bloat the lease file."""
    from wittgenstein_tpu.serve.journal import LeaseTable
    lt = LeaseTable(str(tmp_path), ttl_s=30.0)
    assert lt.claim("r1", "wa")
    lines = open(lt.path).read().splitlines()
    assert not lt.claim("r1", "wb")
    assert open(lt.path).read().splitlines() == lines
    assert lt.holder("r1") == "wa"
    # a renewal by the HOLDER is allowed (and does append)
    assert lt.claim("r1", "wa")
    assert len(open(lt.path).read().splitlines()) == len(lines) + 1


def test_lease_race_lexicographic_winner_and_no_resurrection(tmp_path):
    """Two workers that append before seeing each other (the genuine
    cross-process race window): the lexicographically smallest worker
    id holds, deterministically; the loser's next claim is refused —
    it must not resurrect the lease."""
    from wittgenstein_tpu.serve.journal import LEASE_SCHEMA, LeaseTable
    from wittgenstein_tpu.utils import jsonl
    lt = LeaseTable(str(tmp_path), ttl_s=30.0)
    assert lt.claim("r1", "wb")
    # "wa" raced: its row landed without seeing wb's (simulated by a
    # raw append — claim() would have refused after reading the file)
    jsonl.append_line(lt.path, {
        "schema": LEASE_SCHEMA, "kind": "claim", "rid": "r1",
        "worker": "wa", "deadline_unix": time.time() + 30.0,
        "ts_unix": time.time()}, fsync=True)
    assert lt.holder("r1") == "wa"          # lex-min wins
    assert not lt.claim("r1", "wb")         # loser backs off
    # release by the winner frees the rid for anyone
    lt.release("r1", "wa")
    assert lt.holder("r1") is None or lt.holder("r1") == "wb"


def test_lease_torn_tail_skipped_loudly(tmp_path, capsys):
    """A worker SIGKILLed mid-claim-append leaves a torn final line:
    the reader skips it with a named stderr note and every earlier
    claim still stands."""
    from wittgenstein_tpu.serve.journal import LeaseTable
    lt = LeaseTable(str(tmp_path), ttl_s=30.0)
    assert lt.claim("r1", "wa")
    with open(lt.path, "a") as f:
        f.write('{"kind": "claim", "rid": "r2", "worker": "w')
    assert lt.holder("r1") == "wa"
    assert lt.holder("r2") is None
    err = capsys.readouterr().err
    assert "leases" in err and "torn final line" in err


def test_expired_lease_reclaim_replays_original_rid(
        registry, reference, tmp_path):
    """The dead-worker story end to end: a worker claims a journal
    entry and dies (stops renewing); after the deadline a survivor
    reclaims the rid and the PR-15 replay path runs it under the
    ORIGINAL rid, bit-identical."""
    from wittgenstein_tpu.serve.journal import LeaseTable
    jd = str(tmp_path / "journal")
    dead = Scheduler(registry=registry, journal_dir=jd)
    rid = dead.submit(_spec())
    LeaseTable(jd, ttl_s=0.05).claim(rid, "wdead")
    # "wdead" is SIGKILLed here: no renewal, no release
    time.sleep(0.12)
    survivor = LeaseTable(jd, ttl_s=30.0)
    assert survivor.holder(rid) is None     # expired = reclaimable
    assert survivor.claim(rid, "walive")
    fresh = Scheduler(registry=registry, journal_dir=jd,
                      ledger_path=str(tmp_path / "led.jsonl"))
    [entry] = fresh.journal.replay()
    assert fresh.adopt_journal_entry(entry) == rid
    fresh.run_pending()
    req = fresh.request(rid)
    assert req.status == "done", req.error
    _trees_equal(reference, req.final_state)
    assert SubmissionJournal(jd).lag() == 0


def test_lease_compaction_preserves_live_claims(tmp_path):
    """compact() drops released/expired/superseded history but every
    CURRENT holder survives the rewrite (fleets only compact at
    quiescent time — this pins that even then it can't drop a live
    claim)."""
    from wittgenstein_tpu.serve.journal import LeaseTable
    lt = LeaseTable(str(tmp_path), ttl_s=30.0)
    assert lt.claim("r1", "wa")
    assert lt.claim("r1", "wa")             # renewal (superseded row)
    assert lt.claim("r2", "wb")
    lt.release("r2", "wb")                  # released
    lt.claim("r3", "wc", now=time.time() - 100.0)   # long expired
    lt.compact()
    assert lt.live() == {"r1": "wa"}
    rows = open(lt.path).read().splitlines()
    assert len(rows) == 1 and '"wa"' in rows[0]


def test_fleet_workers_partition_and_dedup_in_process(tmp_path):
    """Two in-process FleetWorkers over one fleet directory: every
    journal entry is claimed by exactly ONE worker (cold-key claim
    budget leaves the second compile key for the peer), both settle,
    and a duplicate resubmit after settle is served from the shared
    ledger without running (cross-worker dedup).  Fresh per-worker
    registries — the budget only bites on COLD keys, exactly the
    fleet-startup shape (compiles re-hit the persistent cache, so
    this stays fast)."""
    from wittgenstein_tpu.serve.fleet import FleetWorker, fleet_paths
    fd = str(tmp_path / "fleet")
    jd = fleet_paths(fd)["journal_dir"]
    j = SubmissionJournal(jd)
    j.record_submit("fw0001", _spec())
    # chunk_ms differs => a DISTINCT compile key (seeds alone share
    # one: width re-specializes inside the jitted callable)
    j.record_submit("fw0002", _spec(seeds=(7,), chunk_ms=60))
    wa = FleetWorker(fd, "wa", lease_ttl_s=30.0)
    wb = FleetWorker(fd, "wb", lease_ttl_s=30.0)
    for _ in range(6):
        wa.step()
        wb.step()
        if j.lag() == 0:
            break
    assert j.lag() == 0
    assert j.settled() == {"fw0001": "done", "fw0002": "done"}
    assert wa.counters["claimed"] + wb.counters["claimed"] == 2
    assert wa.counters["claimed"] == 1      # budget split the cold
    assert wb.counters["claimed"] == 1      # keys across the pair
    # duplicate of a settled spec: ledger join, no third launch
    j.record_submit("fw0003", _spec())
    wa.step()
    assert j.lag() == 0 and j.settled()["fw0003"] == "done"
    assert wa.counters["deduped"] == 1
    assert wa.sched.peek("fw0003") is None  # never entered the queue


# ------------------------------------------------------- kill anywhere


@pytest.mark.slow
def test_matrix_campaign_kill_resume_with_journal(tmp_path):
    """In-process kill-anywhere: a multi-group chaos-axis campaign is
    hard-stopped with finished cells (ledger rows), a mid-run group
    (checkpoint) AND queued-but-unlaunched cells (journal entries
    only).  A fresh scheduler + run_grid(resume=True) recovers all
    three classes and the report is bit-identical to the
    uninterrupted run's."""
    from tools.crash_test import CRASH_GRID, normalize_report
    from wittgenstein_tpu.matrix import SweepGrid, plan, run_grid

    g = SweepGrid.from_json(CRASH_GRID)
    p = plan(g)
    led = str(tmp_path / "led.jsonl")
    ck, jd = str(tmp_path / "ck"), str(tmp_path / "journal")
    ref = run_grid(g, Scheduler(
        ledger_path=str(tmp_path / "ref.jsonl")), plan_=p)
    assert ref.report.clean

    calls = {"n": 0}

    def killer(fn, *a):
        calls["n"] += 1
        if calls["n"] > 8:
            raise RuntimeError("KILLED")
        return fn(*a)

    crashed = run_grid(
        g, Scheduler(ledger_path=led, checkpoint_dir=ck,
                     journal_dir=jd, launcher=killer, max_retries=0,
                     retry_backoff_s=0.0),
        plan_=p, max_wave=2)
    assert 0 < crashed.report.data["cells_done"] < len(p.cells)
    assert os.listdir(ck)

    resumed = run_grid(g, Scheduler(ledger_path=led,
                                    checkpoint_dir=ck,
                                    journal_dir=jd),
                       plan_=p, resume=True)
    rinfo = resumed.report.data["resume"]
    assert rinfo["journal_replayed"] >= 1   # queued-but-unlaunched
    assert rinfo["resumed_requests"] >= 1
    assert resumed.report.clean
    assert normalize_report(resumed.report.to_json()) == \
        normalize_report(ref.report.to_json())
    for cid, st in resumed.states.items():
        _trees_equal(st, ref.states[cid])
    assert not os.listdir(ck)
    assert SubmissionJournal(jd).lag() == 0


@pytest.mark.slow
def test_crash_tool_kill_anywhere_bit_identical(tmp_path):
    """THE kill-anywhere acceptance pin: tools/crash_test.py SIGKILLs
    a subprocess campaign at >= 5 seeded-random wall offsets, resumes
    with journal+checkpoints every time, and the final MatrixReport is
    bit-identical to the uninterrupted run's."""
    from tools.crash_test import run_crash_test

    t0 = time.time()
    res = run_crash_test(str(tmp_path), kills=5, seed=0)
    assert res["ok"], res
    assert res["kills_requested"] == 5
    assert res["kills_landed"] + res["kills_missed"] == 5
    print(f"kill-anywhere: {res['kills_landed']} kills landed, "
          f"wall {time.time() - t0:.0f}s, resume={res['resume']}")
