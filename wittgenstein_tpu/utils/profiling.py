"""Tracing / profiling hooks — the TPU replacement for the reference's
observability surface (SURVEY.md §5.1): the reference has per-node
counters (Node.java:72-79), protocol counters, and a wall-clock print in
ProgressPerTime ("Simulation execution time", ProgressPerTime.java:111).
Here the counters already live in `NodeState`; this module adds the
missing pieces: an XLA profiler trace context and a one-line run report.

Usage::

    from wittgenstein_tpu.utils.profiling import trace, run_report
    with trace("/tmp/wtpu-trace"):          # view in TensorBoard/XProf
        net, ps = runner.run_ms(net, ps, 1000)
    print(run_report(net, wall_s))
"""

from __future__ import annotations

import contextlib
import time

import numpy as np


@contextlib.contextmanager
def trace(log_dir: str | None):
    """jax.profiler trace around a simulation stretch (no-op when log_dir
    is None, e.g. in CI)."""
    import jax
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def timed():
    """Wall-clock context: `with timed() as t: ...; t()` -> seconds of the
    BLOCK (frozen at exit — the "Simulation execution time" measurement,
    ProgressPerTime.java:111)."""
    box = {"end": None}
    t0 = time.perf_counter()

    def elapsed():
        return (box["end"] or time.perf_counter()) - t0

    try:
        yield elapsed
    finally:
        box["end"] = time.perf_counter()


def run_report(net, wall_s: float | None = None, ff: dict | None = None,
               trace: dict | None = None,
               audit: dict | None = None) -> str:
    """One-line run summary from the engine counters: simulated time,
    per-node message/byte traffic over live nodes (via the StatsHelper
    getters, which guard the all-down case), drop/clamp health, and
    sim-ms-per-second when wall_s is given.

    `ff` is the quiet-window skip accounting from a fast-forwarded run
    (`Runner(fast_forward=True).ff_stats()`, or the stats dict
    `core/network.fast_forward_chunk` returns): when given, the report
    carries ``skipped_ms`` / ``jump_count`` / ``skip_rate`` instead of
    silently omitting how the simulated span was covered.

    `trace` is the flight-recorder accounting from a traced run
    (`Runner(trace=spec).trace_stats()`): when given, the report
    carries the recorded-event count, the ring high-water mark against
    capacity, and — LOUDLY — the dropped-event count, so a silently
    truncated trace is visible in bench output instead of masquerading
    as a complete one.

    `audit` is the invariant-audit verdict from an audited run
    (`Runner(audit=spec).audit_stats()`): a clean run states what it
    proved (invariant count), a violated run SHOUTS the per-invariant
    counts and the first-violation record."""
    from . import stats
    nodes = net.nodes
    live = int(np.asarray((~np.asarray(nodes.down)).sum()))
    t = int(np.asarray(net.time))
    msg_r = stats.msg_received_stats(nodes)
    msg_s = stats.msg_sent_stats(nodes)
    by_s = stats.bytes_sent_stats(nodes)
    done = int(stats.done_count(nodes)["count"])
    parts = [
        f"sim={t}ms",
        f"live={live}",
        # max over an empty live set is the -inf sentinel; report 0.
        f"msgRecv avg={float(msg_r['avg']):.1f} "
        f"max={max(0.0, float(msg_r['max'])):.0f}",
        f"msgSent avg={float(msg_s['avg']):.1f}",
        f"bytesSent avg={float(by_s['avg']):.0f}",
        f"done={done}/{live}",
        f"dropped={int(np.asarray(net.dropped))}"
        f"+{int(np.asarray(net.bc_dropped))}bc",
        f"clamped={int(np.asarray(net.clamped))}",
    ]
    if ff is not None:
        skipped = int(np.asarray(ff["skipped_ms"]).reshape(-1)[0])
        jumps = int(np.asarray(ff["jump_count"]).reshape(-1)[0])
        parts.append(f"ff skipped={skipped}ms jumps={jumps} "
                     f"skip_rate={skipped / max(1, t):.3f}")
    if trace is not None:
        tr = (f"trace events={int(trace['events'])} "
              f"hw={int(trace['high_water'])}/{int(trace['capacity'])}")
        if int(trace["dropped"]) > 0:
            tr += (f" TRUNCATED dropped={int(trace['dropped'])} "
                   "(raise TraceSpec.capacity)")
        parts.append(tr)
    if audit is not None:
        if audit["total"] == 0:
            parts.append(f"audit clean "
                         f"({len(audit['invariants'])} invariants)")
        else:
            per = ",".join(f"{k}={v}"
                           for k, v in audit["violations"].items() if v)
            au = f"!! AUDIT VIOLATIONS total={audit['total']} [{per}]"
            first = audit.get("first")
            if first:
                au += (f" first=(ms {first['ms']} {first['invariant']} "
                       f"index={first['index']})")
            parts.append(au)
    if wall_s is not None and wall_s > 0:
        parts.append(f"wall={wall_s:.2f}s ({t / wall_s:.0f} sim-ms/s)")
    return "Simulation execution time: " + " ".join(parts)
