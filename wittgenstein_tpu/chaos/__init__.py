"""wittgenstein_tpu.chaos — the chaos plane: declarative fault
schedules compiled into every engine variant.

  FaultSchedule  — adversity as data: node crash/recover churn,
                   mid-run partition/heal windows, per-link message
                   loss and delay inflation, all bit-deterministic
                   from (schedule, seed) (chaos/schedule.py);
  ChaosProtocol  — the protocol proxy that compiles a schedule into
                   the dense, superstep-K, batched, fast-forward and
                   sharded engines through the window-entry
                   `apply_faults` hook and the per-ms outbox adversary
                   (chaos/wrap.py).

Serve carries schedules as the `ScenarioSpec.fault_schedule` field
(program-affecting: in digest + compile key); `tools/chaos.py` is the
one-command cross-engine identity check and impact report.
"""

from .schedule import FaultSchedule
from .wrap import ChaosProtocol, impact_summary

__all__ = ["FaultSchedule", "ChaosProtocol", "impact_summary"]
