"""HLO-level audit of the scan-carry writeback churn — thin CLI shim.

The generic machinery moved to `wittgenstein_tpu.analysis` (round 6):
the carry-copy rule there compiles ANY registered protocol's superstep
and budgets its while-body copies/DUS per protocol
(`python -m wittgenstein_tpu.analysis --rule carry_copy`).  This entry
point keeps the historical interface — the detailed per-op listing for
the exact bench build (batched Handel, WTPU_PLANE_BARRIER honored) that
found the round-5 40-copies regression:

  python tools/carry_audit.py [n] [seeds] [chunk_ms]

Run anywhere (CPU HLO shows the same copy-insertion decisions; run
on-chip for the Mosaic view).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    seeds = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 40

    from wittgenstein_tpu.analysis import rules_carry
    from wittgenstein_tpu.analysis.targets import handel_audit_target

    # Same knob bench.py honors: WTPU_PLANE_BARRIER=0 audits the
    # pre-fix build (reproduces the 40-copies-per-body baseline).
    target = handel_audit_target(
        n=n, seeds=seeds, chunk=chunk,
        plane_barrier=os.environ.get("WTPU_PLANE_BARRIER", "1") != "0")

    rows = rules_carry.audit(target)
    if not rows:
        from wittgenstein_tpu.analysis import hlo
        if not hlo.scan_bodies(target.hlo_text):
            print("WARNING: no scan-shaped while body matched in the "
                  "optimized HLO — parser found nothing (HLO text format "
                  "change?), NOT a copy-free build")
        else:
            print("scan while body is clean: no copy/DUS ops")
    by_body: dict[str, list] = {}
    for r in rows:
        by_body.setdefault(r.body, []).append(r)
    for body, rs in by_body.items():
        dus = [r for r in rs if r.op == "dynamic-update-slice"]
        copies = [r for r in rs if r.op == "copy"]
        tot_d = sum(r.bytes for r in dus)
        tot_c = sum(r.bytes for r in copies)
        print(f"== {body}: {sum(r.count for r in dus)} DUS "
              f"({tot_d / 1e6:.1f} MB), {sum(r.count for r in copies)} "
              f"copies ({tot_c / 1e6:.1f} MB)")
        for r in rs:
            print(f"  {r.op[:4]:4s} x{r.count:<4d} {r.bytes / 1e6:9.2f} MB  "
                  f"{r.shape:24s} {r.leaf or '?':40s} {r.source}")
    print(f"-- metrics: {rules_carry.metrics_from_rows(rows)}")


if __name__ == "__main__":
    main()
