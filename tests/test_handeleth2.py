"""HandelEth2 tests — the analogue of handeleth2/HandelEth2Test.java:
concurrent aggregations, full contributions, determinism."""

import pytest

import jax.numpy as jnp
import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.handeleth2 import (
    PERIOD_TIME, R, HandelEth2)


@pytest.mark.slow
def test_continuous_aggregation():
    p = HandelEth2(node_count=64, pairing_time=3, level_wait_time=100,
                   period_duration_ms=50,
                   network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    net, ps = r.run_ms(net, ps, PERIOD_TIME * 4 + 100)
    # After 4 period starts, R = 3 run concurrently and height-1..2 ended.
    assert np.all(np.asarray(ps.agg_done) == 2)
    assert np.all(np.asarray(ps.active))
    # Ended aggregations reached the full committee (64 contributions).
    contrib = np.asarray(ps.contributions)
    assert np.all(contrib == 2 * 64), contrib[:5]
    assert int(net.dropped) == 0


@pytest.mark.slow
def test_multi_hash_values():
    p = HandelEth2(node_count=64, period_duration_ms=50,
                   network_latency_name="NetworkNoLatency")
    net, ps = p.init(3)
    r = Runner(p, donate=False)
    net, ps = r.run_ms(net, ps, PERIOD_TIME + 100)
    # ~20% of nodes attest a nonzero hash (geometric draw, HNode.create).
    oh = np.asarray(ps.own_hash)[:, (1001) % R]
    frac = (oh > 0).mean()
    assert 0.05 < frac < 0.4, frac
    # The completed aggregation covers all nodes across hash values.
    inc = np.asarray(ps.inc)[:, 1001 % R]       # [N, H, W]
    card = np.unpackbits(inc.view(np.uint8), axis=-1).sum(axis=(1, 2))
    assert np.all(card == 64)


@pytest.mark.slow
def test_nodes_down_and_determinism():
    p = HandelEth2(node_count=64, nodes_down=6,
                   network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net1, ps1 = p.init(1)
    net2, ps2 = p.init(1)
    net1, ps1 = r.run_ms(net1, ps1, PERIOD_TIME * 2)
    net2, ps2 = r.run_ms(net2, ps2, PERIOD_TIME * 2)
    assert np.array_equal(np.asarray(ps1.inc), np.asarray(ps2.inc))
    live = ~np.asarray(net1.nodes.down)
    # Running aggregations reached the live population (58 of 64).
    inc = np.asarray(ps1.inc)
    card = np.unpackbits(inc.view(np.uint8), axis=-1).sum(axis=(2, 3))
    active = np.asarray(ps1.active)
    assert np.all(card[live][active[live]] >= 50), \
        card[live][active[live]].min()
