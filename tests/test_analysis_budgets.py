"""The round-5 carry-copy fix as a permanent tier-1 regression gate.

Round 5 cut the batched Handel superstep's scan-carry plane copies from
40 to 2 per while body (~31% of step time, reports/PROFILE_r4.md) by
adding the plane-ordering barrier in core/batched.py.  CPU HLO shows
the same copy-insertion decisions as TPU, so this compiles the pinned
small Handel analysis target and asserts the while-body plane-copy
count never climbs back above 2 — and that the checked-in budget file
actually encodes that gate (deleting the budget entry must fail here,
not silently stop gating).
"""

import json

from wittgenstein_tpu.analysis import framework, rules_carry
from wittgenstein_tpu.analysis.targets import get_target


def test_handel_while_body_plane_copies_le_2():
    target = get_target("Handel")
    from wittgenstein_tpu.analysis import hlo
    assert hlo.scan_bodies(target.hlo_text), (
        "no scan-shaped while body found in the compiled Handel "
        "superstep — the HLO parser matched nothing, so the plane-copy "
        "gate would pass vacuously (HLO text format change?)")
    metrics = rules_carry.measure(target)
    assert metrics["plane_copies"] <= 2, (
        f"Handel's compiled superstep copies {metrics['plane_copies']} "
        "mailbox ring planes per scan iteration (round-5 fixed state: 2)."
        " XLA's copy-insertion can no longer prove the scatters run in "
        "place — did the plane-ordering barrier in core/batched.py move "
        "or lose an operand? Run `python tools/carry_audit.py` for the "
        "per-leaf attribution.")


def test_checked_in_budget_encodes_the_gate():
    with open(framework.BUDGETS_PATH) as f:
        budgets = json.load(f)
    assert budgets["carry_copy"]["Handel"]["plane_copies"] <= 2
