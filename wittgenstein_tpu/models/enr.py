"""ENRGossiping — EIP-778 node-record gossip with peer rewiring and churn.

Reference: protocols/ENRGossiping.java (521 lines).  Nodes carry a set of
capabilities; they gossip versioned Records (StatusFloodMessage semantics:
newer seq replaces older, core/messages/StatusFloodMessage.java:33-45) every
`capGossipTime` ms; on receiving a record from an unconnected node they may
rewire: connect if the node adds capability value (addedValue :258-266,
score :380-400), evicting their least-valuable peer when full
(removeWorseIfPossible :402-428).  A changing fraction re-rolls capabilities
every `timeToChange` (:145-153); a new node joins every `timeToLeave/8` and
later leaves (addNewNode :155-163, exitNetwork :439-450).  A node is done
when every one of its capabilities has >= 3 matching peers (score maxed)
AND its cap-subgraph reaches at least half of that capability's live nodes
(isFullyConnected :225-246, isPartOfNetwork :330-360); doneAt is RELATIVE:
max(1, time - startTime) (:324-327).

TPU-native notes:
* The per-(node, capability) BFS of isPartOfNetwork becomes a boolean
  transitive closure of the cap-restricted adjacency matrix — log2(N)
  squarings of an [N, N] bool matrix on the MXU, computed every ms.
* The flood queue forwards one pending record per node per ms (as the other
  flood models); record content (the source's capabilities) is gathered at
  use time — staleness is one in-flight latency, below capGossipTime.
* The reference's selectChangingNodes quirk — the changing set is drawn
  from the FIRST `totalPeers` node ids (:145-153) — is reproduced.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..core import builders, p2p
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import prng

TAG_CAPS = 0x454E4330
TAG_JOIN = 0x454E4331
TAG_EXIT = 0x454E4332
TAG_GOSS = 0x454E4333
TAG_CHG = 0x454E4334
TAG_CHG_START = 0x454E4336

PEERS_PER_CAP = 3


def _draw_caps(seed, n, n_caps, cap_per_node):
    """capPerNode distinct capabilities per node (generateCap, :124-131):
    rank a per-(node, cap) hash and take the top capPerNode."""
    pri = prng.uniform_u32(
        seed, jnp.arange(n * n_caps, dtype=jnp.int32)).reshape(n, n_caps)
    order = jnp.argsort(pri, axis=1)
    rank = jnp.zeros((n, n_caps), jnp.int32).at[
        jnp.arange(n)[:, None], order].set(
        jnp.broadcast_to(jnp.arange(n_caps, dtype=jnp.int32)[None, :],
                         (n, n_caps)))
    return rank < cap_per_node


@struct.dataclass
class ENRState:
    seed: jnp.ndarray
    caps: jnp.ndarray         # bool [N, C]
    peers: jnp.ndarray        # int32 [N, D] (mutable adjacency)
    degree: jnp.ndarray       # int32 [N]
    seq: jnp.ndarray          # int32 [N] — own record sequence number
    seen_seq: jnp.ndarray     # int32 [N, N] — newest seq seen per source
    pending: jnp.ndarray      # bool [N, N] — records to forward
    pending_src: jnp.ndarray  # int32 [N, N] — who delivered each record
    join_at: jnp.ndarray      # int32 [N] (0 = initial member)
    exit_at: jnp.ndarray      # int32 [N] (0 = never leaves)
    start_time: jnp.ndarray   # int32 [N]
    gossip_start: jnp.ndarray  # int32 [N]
    change_start: jnp.ndarray  # int32 [N] (0 = never changes caps)


@register
class ENRGossiping:
    """Parameters mirror ENRParameters (ENRGossiping.java:26-106)."""

    # Churn mutates nodes.down inside step() (joins/exits) — the fused
    # 2-ms super-step would read stale liveness for the second ms
    # (core/network.scan_chunk rejects superstep=2 for this protocol).
    mutates_liveness = True

    def __init__(self, time_to_change=60_000, cap_gossip_time=10_000,
                 discard_time=100, time_to_leave=60_000, total_peers=5,
                 nodes=50, changing_nodes=10.0, max_peers=50,
                 number_of_different_capabilities=5, cap_per_node=3,
                 node_builder_name=None, network_latency_name=None,
                 join_slots=None, inbox_cap=16, horizon=1024):
        if cap_per_node > number_of_different_capabilities:
            raise ValueError("capPerNode > numberOfDifferentCapabilities")
        self.n_initial = nodes
        self.time_to_change = max(1, time_to_change)
        self.cap_gossip_time = max(1, cap_gossip_time)
        # discardTime is accepted for parameter parity but inert — the
        # reference stores and prints it without ever applying it
        # (ENRGossiping.java:41,94,501-502).
        self.discard_time = discard_time
        self.time_to_leave = max(8, time_to_leave)
        self.total_peers = total_peers
        self.changing_nodes = changing_nodes
        self.max_peers = max_peers
        self.n_caps = number_of_different_capabilities
        self.cap_per_node = cap_per_node
        # Joiner arena: one slot per addNewNode firing we provision for.
        self.join_slots = (8 if join_slots is None else join_slots)
        self.node_count = nodes + self.join_slots
        self.builder = builders.get_by_name(node_builder_name)
        self.latency = latency_mod.get_by_name(network_latency_name)
        # Peer-list arena width: the initial min-degree construction can
        # exceed maxPeers (the reference's maxPeers only gates onFlood
        # connects, :268-270), so size the slots generously.
        self.arena_deg = max(max_peers, 4 * total_peers, total_peers + 16)
        self.cfg = EngineConfig(
            n=self.node_count, horizon=horizon, inbox_cap=inbox_cap,
            payload_words=2, out_deg=self.arena_deg, bcast_slots=1)

    def init(self, seed):
        n, ni, C, D = (self.node_count, self.n_initial, self.n_caps,
                       self.arena_deg)
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        ids = jnp.arange(n, dtype=jnp.int32)
        is_joiner = ids >= ni
        nodes = nodes.replace(down=is_joiner)   # joiners start down

        caps = _draw_caps(prng.hash2(seed, TAG_CAPS), n, C,
                          self.cap_per_node)

        # Initial peer graph over ONLY the ni live nodes (P2PNetwork(
        # totalPeers, true): minimum-degree construction) — building over
        # the joiner arena would silently break the min-degree invariant
        # and couple the t=0 topology to join_slots.
        peers_i, _, _ = p2p.build_peer_graph(
            seed, ni, self.total_peers, minimum=True, max_degree=D)
        peers = jnp.full((n, D), -1, jnp.int32).at[:ni].set(peers_i)
        degree = jnp.sum(peers >= 0, axis=1).astype(jnp.int32)

        # Joiner k fires at (k+1) * timeToLeave/8 (addNewNode every
        # timeToLeave/8, :188-189), and exits timeToLeave-bounded later.
        k = jnp.maximum(ids - ni, 0)
        join_at = jnp.where(is_joiner, (k + 1) * (self.time_to_leave // 8),
                            0).astype(jnp.int32)
        exit_rand = prng.uniform_int(prng.hash2(seed, TAG_EXIT), ids,
                                     self.time_to_leave)
        exit_at = jnp.where(is_joiner, join_at + jnp.maximum(exit_rand, 1),
                            0).astype(jnp.int32)

        # Periodic gossip start: join + rand(capGossipTime) + 1 (:297-303).
        goss = prng.uniform_int(prng.hash2(seed, TAG_GOSS), ids,
                                self.cap_gossip_time)
        gossip_start = (join_at + goss + 1).astype(jnp.int32)

        # Changing set: first int(totalPeers * changingNodes) ids drawn from
        # [0, totalPeers) — reference quirk (:145-153).
        n_chg = min(int(self.total_peers * self.changing_nodes),
                    self.total_peers)
        chg = ids < 0
        if n_chg > 0:
            pri = prng.uniform_u32(prng.hash2(seed, TAG_CHG),
                                   jnp.arange(self.total_peers,
                                              dtype=jnp.int32))
            chosen = jnp.argsort(pri)[:n_chg]
            chg = chg.at[chosen].set(True)
        chg_start = prng.uniform_int(prng.hash2(seed, TAG_CHG_START), ids,
                                     self.time_to_change) + 1
        change_start = jnp.where(chg, chg_start, 0).astype(jnp.int32)

        net = init_net(self.cfg, nodes, seed)
        return net, ENRState(
            seed=seed, caps=caps, peers=peers, degree=degree,
            seq=jnp.zeros((n,), jnp.int32),
            seen_seq=jnp.full((n, n), -1, jnp.int32),
            pending=jnp.zeros((n, n), bool),
            pending_src=jnp.full((n, n), -1, jnp.int32),
            join_at=join_at, exit_at=exit_at,
            start_time=join_at,
            gossip_start=gossip_start, change_start=change_start)

    # ------------------------------------------------------------------

    def _score_counts(self, p, caps):
        """cnt[i, c] = number of i's peers with capability c."""
        peer_caps = jnp.where((p.peers >= 0)[..., None],
                              caps[jnp.maximum(p.peers, 0)], False)
        return jnp.sum(peer_caps, axis=1).astype(jnp.int32)    # [N, C]

    def _score_of(self, caps, cnt):
        """score(peers) (ENRGossiping.java:395-409): the reference walks the
        found-list WITH duplicates — a capability held by k matching peers
        contributes k * min(k, 3)."""
        return jnp.sum(jnp.where(caps,
                                 cnt * jnp.minimum(cnt, PEERS_PER_CAP), 0),
                       axis=-1).astype(jnp.int32)

    def _fully_connected(self, p, nodes, adj):
        """isFullyConnected (:225-246): score maxed AND each own cap's
        subgraph reaches >= |capSet|/2 live cap-holders.  `adj` is the
        symmetric [N, N] edge matrix step() already built."""
        n, C = self.node_count, self.n_caps
        alive = ~nodes.down
        cnt = self._score_counts(p, p.caps)
        score_ok = self._score_of(p.caps, cnt) >= \
            jnp.sum(p.caps, axis=1) * PEERS_PER_CAP

        ids = jnp.arange(n, dtype=jnp.int32)
        ok = jnp.ones((n,), bool)
        f32 = jnp.float32
        for c in range(C):
            m = p.caps[:, c] & alive                       # cap-subgraph
            a = adj & m[None, :] & m[:, None]
            # reach[i, j]: j reachable from i through the cap subgraph,
            # starting from i's cap-peers (i itself need not hold the cap).
            # True doubling: square the adjacency too, so diameter up to N
            # is covered in log2(N) steps.
            r = (adj & m[None, :]).astype(f32)             # direct cap-peers
            ac = a.astype(f32)
            for _ in range(max(1, (n - 1).bit_length())):
                r = jnp.minimum(r + r @ ac, 1.0)
                ac = jnp.minimum(ac + ac @ ac, 1.0)
            # explored = self + distinct reached others (:331-360)
            others = jnp.where(m[None, :], r > 0, False).at[ids, ids].set(
                False, mode="drop")
            reached = jnp.sum(others, axis=1).astype(jnp.int32)
            cap_total = jnp.sum(m).astype(jnp.int32)
            cap_ok = (~p.caps[:, c]) | ((reached + 1) >= cap_total // 2)
            ok = ok & cap_ok
        return score_ok & ok

    def step(self, p: ENRState, nodes, inbox, t, key):
        n, C, D = self.node_count, self.n_caps, self.arena_deg
        ids = jnp.arange(n, dtype=jnp.int32)
        S = inbox.src.shape[1]

        # ---- membership: joins and exits ----
        joining = (p.join_at > 0) & (t == p.join_at)
        leaving = (p.exit_at > 0) & (t == p.exit_at) & ~nodes.down
        nodes = nodes.replace(down=(nodes.down & ~joining) | leaving)
        alive = ~nodes.down
        peers, degree = p2p.disconnect(p.peers, p.degree, leaving)

        # Joiner links: totalPeers random live targets (addNewNode
        # :155-163); targets' reciprocal slots fill if they have room.
        if self.join_slots:
            tries = self.total_peers * 2
            cand = prng.uniform_int(
                prng.hash3(p.seed, TAG_JOIN, t),
                ids[:, None] * tries + jnp.arange(tries)[None, :], n)
            cand_ok = joining[:, None] & alive[jnp.maximum(cand, 0)] & \
                (cand != ids[:, None])
            # take the first total_peers valid candidates
            rank = jnp.cumsum(cand_ok, axis=1) - cand_ok
            take = cand_ok & (rank < self.total_peers)
            slot = jnp.where(take,
                             degree[:, None] + rank.astype(jnp.int32), D)
            peers = peers.reshape(-1).at[
                jnp.where(take & (slot < D), ids[:, None] * D + slot,
                          n * D).reshape(-1)].set(
                cand.reshape(-1), mode="drop").reshape(n, D)
            # The reciprocal (target-side) links are created by the
            # symmetrization pass below, same ms.
            degree = jnp.sum(peers >= 0, axis=1).astype(jnp.int32)

        # ---- receive records ----
        seen_seq, pending, pending_src = p.seen_seq, p.pending, p.pending_src
        caps, seq = p.caps, p.seq
        removed = jnp.zeros((n, n), bool)   # links dropped by removeWorse
        cnt = self._score_counts(p.replace(peers=peers), caps)
        base_score = self._score_of(caps, cnt)
        for s in range(S):
            ok = inbox.valid[:, s] & alive
            src = jnp.clip(inbox.src[:, s], 0, n - 1)
            origin = jnp.clip(inbox.data[:, s, 0], 0, n - 1)
            rseq = inbox.data[:, s, 1]
            old = seen_seq[ids, origin]
            newer = ok & (rseq > old) & (origin != ids)
            seen_seq = seen_seq.at[jnp.where(newer, ids, n),
                                   jnp.minimum(origin, n - 1)].set(
                rseq, mode="drop")
            pending = pending.reshape(-1).at[
                jnp.where(newer, ids * n + origin, n * n)].set(
                True, mode="drop").reshape(n, n)
            pending_src = pending_src.reshape(-1).at[
                jnp.where(newer, ids * n + origin, n * n)].set(
                src, mode="drop").reshape(n, n)

            # onFlood connect logic (:305-322)
            o_caps = caps[origin]                          # [N, C]
            connected = jnp.any(peers == origin[:, None], axis=1)
            can = newer & alive[origin] & \
                (degree[origin] < self.max_peers) & ~connected
            add_cnt = cnt + o_caps.astype(jnp.int32)
            gain = self._score_of(caps, add_cnt) - base_score
            want = can & (gain > 0)
            has_room = degree < self.max_peers
            # full -> try replacing the worst peer (removeWorse, :402-428)
            peer_caps = jnp.where((peers >= 0)[..., None],
                                  caps[jnp.maximum(peers, 0)], False)
            repl_cnt = (cnt[:, None, :] - peer_caps.astype(jnp.int32) +
                        o_caps[:, None, :].astype(jnp.int32))   # [N, D, C]
            repl_score = self._score_of(caps[:, None, :],
                                        repl_cnt)               # [N, D]
            repl_score = jnp.where(peers >= 0, repl_score, -1)
            best_repl = jnp.argmax(repl_score, axis=1)
            best_gain = jnp.take_along_axis(repl_score, best_repl[:, None],
                                            axis=1)[:, 0] - base_score
            do_repl = want & ~has_room & (best_gain > 0)
            # drop the replaced link; record it so the symmetric rebuild
            # removes BOTH directions (removeLink, :415-424)
            repl_peer = jnp.take_along_axis(
                jnp.maximum(peers, 0), best_repl[:, None], axis=1)[:, 0]
            removed = removed.reshape(-1).at[
                jnp.where(do_repl, ids * n + repl_peer, n * n)].set(
                True, mode="drop").reshape(n, n)
            peers = jnp.where(
                (do_repl[:, None] &
                 (jnp.arange(D)[None, :] == best_repl[:, None])),
                -1, peers)
            do_conn = (want & has_room) | do_repl
            free_slot = jnp.argmax(peers < 0, axis=1)
            has_free = jnp.any(peers < 0, axis=1)
            do_conn = do_conn & has_free
            peers = peers.reshape(-1).at[
                jnp.where(do_conn, ids * D + free_slot, n * D)].set(
                origin, mode="drop").reshape(n, D)
            # a re-created link cancels an earlier same-ms removal (the
            # reference's remove-then-create ordering keeps the last op)
            removed = removed.reshape(-1).at[
                jnp.where(do_conn, ids * n + origin, n * n)].set(
                False, mode="drop").reshape(n, n)
            # reciprocal side: origin gains us if it has a free slot —
            # deferred to the symmetrization pass below.
            degree = jnp.sum(peers >= 0, axis=1).astype(jnp.int32)
            cnt = self._score_counts(p.replace(peers=peers), caps)
            base_score = self._score_of(caps, cnt)

        # ---- symmetrize: ensure every link is mutual (createLink adds both
        # directions; removeLink removes both).  One pass per ms. ----
        has_edge = jnp.zeros((n, n), bool).reshape(-1).at[
            jnp.where(peers >= 0, ids[:, None] * n + jnp.maximum(peers, 0),
                      n * n).reshape(-1)].set(True, mode="drop").reshape(n, n)
        # createLink adds BOTH directions unconditionally (:150-158,
        # :362-366) — maxPeers only gates the onFlood connect decision, so
        # the union of the two directed views is the true edge set (a node
        # may temporarily exceed maxPeers, as in the reference).
        final_edge = (has_edge | has_edge.T) & ~(removed | removed.T)
        # rebuild peer lists from the edge matrix (id order)
        rank_e = jnp.cumsum(final_edge, axis=1) - 1
        slot_ok = final_edge & (rank_e < D)
        peers = jnp.full((n, D), -1, jnp.int32).reshape(-1).at[
            jnp.where(slot_ok, ids[:, None] * D + rank_e, n * D).reshape(-1)
        ].set(jnp.broadcast_to(ids[None, :], (n, n)).reshape(-1),
              mode="drop").reshape(n, D)
        degree = jnp.sum(peers >= 0, axis=1).astype(jnp.int32)

        # ---- capability changes (changeCap, :373-378) ----
        chg_due = alive & (p.change_start > 0) & (t >= p.change_start) & \
            ((t - p.change_start) % self.time_to_change == 0)
        new_caps = _draw_caps(prng.hash3(p.seed, TAG_CHG_START + 1, t), n, C,
                              self.cap_per_node)
        caps = jnp.where(chg_due[:, None], new_caps, caps)

        # ---- gossip own record (broadcastCapabilities, :369-371) ----
        goss_due = alive & (t >= p.gossip_start) & \
            ((t - p.gossip_start) % self.cap_gossip_time == 0)
        bump = goss_due | chg_due
        seq = seq + bump.astype(jnp.int32)
        # own record rides the same pending queue (origin = self)
        pending = pending.at[ids, ids].set(
            jnp.where(bump, True, pending[ids, ids]))
        pending_src = pending_src.at[ids, ids].set(
            jnp.where(bump, ids, pending_src[ids, ids]))

        # ---- forward one pending record per node per ms ----
        pend_live = pending & alive[:, None]
        has = jnp.any(pend_live, axis=1)
        pick = jnp.argmax(pend_live, axis=1).astype(jnp.int32)
        exclude = jnp.where(pick == ids, -1,
                            pending_src.reshape(-1)[ids * n + pick])
        payload = jnp.stack(
            [pick, seen_seq[ids, pick]], axis=1).astype(jnp.int32)
        payload = jnp.where((pick == ids)[:, None],
                            jnp.stack([ids, seq], axis=1), payload)
        dest, pl, size, delay = p2p.flood_fanout(
            self.cfg, peers, has, exclude, payload, p.seed, t,
            local_delay=10, delay_between=10)
        pending = pending.at[ids, pick].set(
            jnp.where(has, False, pending[ids, pick]))

        # ---- done check (setDoneAt, :324-327; relative time) ----
        full = self._fully_connected(
            p.replace(peers=peers, degree=degree, caps=caps), nodes,
            final_edge)
        done_now = alive & full & (nodes.done_at == 0)
        nodes = nodes.replace(done_at=jnp.where(
            done_now, jnp.maximum(1, t - p.start_time),
            nodes.done_at).astype(jnp.int32))

        out = empty_outbox(self.cfg).replace(dest=dest, payload=pl,
                                             size=size, delay=delay)
        return (p.replace(caps=caps, peers=peers, degree=degree, seq=seq,
                          seen_seq=seen_seq, pending=pending,
                          pending_src=pending_src), nodes, out)


def cont_if_enr(net, pstate):
    live = ~net.nodes.down
    return jnp.any(live & (net.nodes.done_at == 0))
