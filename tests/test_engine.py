"""Engine semantics tests — the analogue of core NetworkTest.java /
EnvelopeStorageTest.java: delivery, ordering, counters, partitions, drops."""

import jax
import jax.numpy as jnp
import pytest

from wittgenstein_tpu.core import builders
from wittgenstein_tpu.core.latency import (NetworkFixedLatency,
                                           NetworkNoLatency,
                                           NetworkUniformLatency)
from wittgenstein_tpu.core.network import Runner, step_ms
from wittgenstein_tpu.core.state import (EngineConfig, empty_outbox, init_net)


class OneShot:
    """Minimal protocol: node 0 sends one unicast to node 1 at t=0; every
    node records the messages it sees."""

    def __init__(self, n=4, latency=None, dest=1, size=7, cfg=None,
                 delay=0, all_send=False):
        self.latency = latency or NetworkFixedLatency(10)
        self.cfg = cfg or EngineConfig(n=n, horizon=64, inbox_cap=4,
                                       payload_words=2, out_deg=1,
                                       bcast_slots=2)
        self.dest = dest
        self.size = size
        self.delay = delay
        self.all_send = all_send      # every node i -> (i+1) % n at t=0

    def init(self, seed):
        nodes = builders.NodeBuilder().build(seed, self.cfg.n)
        net = init_net(self.cfg, nodes, seed)
        p = {"got": jnp.zeros(self.cfg.n, jnp.int32),
             "when": jnp.full(self.cfg.n, -1, jnp.int32)}
        return net, p

    def step(self, pstate, nodes, inbox, t, key):
        out = empty_outbox(self.cfg)
        ids = jnp.arange(self.cfg.n)
        sender = jnp.ones_like(ids, bool) if self.all_send else (ids == 0)
        dest = ((ids + 1) % self.cfg.n if self.all_send
                else jnp.full_like(ids, self.dest))
        out = out.replace(
            dest=jnp.where(sender & (t == 0), dest, -1)[:, None],
            payload=jnp.broadcast_to(
                jnp.where(sender[:, None, None], 42, 0),
                (self.cfg.n, 1, self.cfg.payload_words)).astype(jnp.int32),
            size=jnp.full((self.cfg.n, 1), self.size, jnp.int32),
            delay=jnp.full((self.cfg.n, 1), self.delay, jnp.int32))
        got = jnp.sum(inbox.valid, 1).astype(jnp.int32)
        pstate = {
            "got": pstate["got"] + got,
            "when": jnp.where((got > 0) & (pstate["when"] < 0), t,
                              pstate["when"]),
        }
        return pstate, nodes, out


def run(protocol, ms, seed=0):
    net, p = protocol.init(seed)
    return Runner(protocol, donate=False).run_ms(net, p, ms)


def test_unicast_delivery_time_and_counters():
    # Fixed latency 10: send at t=0 -> sentTime 1 -> arrival 11
    # (Network.java:420-487 semantics: arrival = sendTime + latency).
    proto = OneShot(latency=NetworkFixedLatency(10))
    net, p = run(proto, 20)
    assert int(p["when"][1]) == 11
    assert int(p["got"][1]) == 1
    assert int(jnp.sum(p["got"])) == 1
    assert int(net.nodes.msg_sent[0]) == 1
    assert int(net.nodes.bytes_sent[0]) == 7
    assert int(net.nodes.msg_received[1]) == 1
    assert int(net.nodes.bytes_received[1]) == 7
    assert int(net.dropped) == 0


def test_self_send_min_latency():
    # from == to gives latency 1 (NetworkLatency.java:27-29): arrival t+2.
    proto = OneShot(latency=NetworkFixedLatency(50), dest=0)
    _, p = run(proto, 10)
    assert int(p["when"][0]) == 2


def test_down_node_does_not_receive():
    proto = OneShot()
    net, p = proto.init(0)
    net = net.replace(nodes=net.nodes.replace(
        down=jnp.arange(proto.cfg.n) == 1))
    net, p = Runner(proto, donate=False).run_ms(net, p, 20)
    assert int(jnp.sum(p["got"])) == 0
    assert int(net.nodes.msg_received[1]) == 0
    # the sender still counts the attempt (Network.java:475-477)
    assert int(net.nodes.msg_sent[0]) == 1


def test_partition_blocks_delivery():
    proto = OneShot()
    net, p = proto.init(0)
    part = jnp.where(jnp.arange(proto.cfg.n) == 1, 1, 0).astype(jnp.int32)
    net = net.replace(nodes=net.nodes.replace(partition=part))
    net, p = Runner(proto, donate=False).run_ms(net, p, 20)
    assert int(jnp.sum(p["got"])) == 0


class Broadcaster(OneShot):
    def step(self, pstate, nodes, inbox, t, key):
        out = empty_outbox(self.cfg)
        sender = jnp.arange(self.cfg.n) == 0
        out = out.replace(bcast=sender & (t == 0),
                          bcast_size=jnp.full((self.cfg.n,), 3, jnp.int32))
        got = jnp.sum(inbox.valid, 1).astype(jnp.int32)
        pstate = {
            "got": pstate["got"] + got,
            "when": jnp.where((got > 0) & (pstate["when"] < 0), t,
                              pstate["when"]),
        }
        return pstate, nodes, out


def test_broadcast_reaches_everyone_once():
    proto = Broadcaster(n=8, latency=NetworkUniformLatency(30))
    net, p = run(proto, 40)
    assert [int(v) for v in p["got"]] == [1] * 8
    # sendAll counts n attempted sends (Network.java:341-347)
    assert int(net.nodes.msg_sent[0]) == 8
    assert int(net.nodes.bytes_sent[0]) == 24
    # every delivery within [2, 33] ms
    assert int(jnp.min(p["when"])) >= 2
    assert int(jnp.max(p["when"])) <= 33


def test_broadcast_latencies_are_stable_recomputation():
    # Same seed => identical arrival times (the Envelope.java:45-56
    # recomputed-latency contract); different seed => different ones.
    proto = Broadcaster(n=16, latency=NetworkUniformLatency(200))
    _, p1 = run(proto, 250, seed=5)
    _, p2 = run(proto, 250, seed=5)
    _, p3 = run(proto, 250, seed=9)
    assert jnp.array_equal(p1["when"], p2["when"])
    assert not jnp.array_equal(p1["when"], p3["when"])


def test_inbox_overflow_counts_drops():
    # All 8 nodes unicast node 0 with NoLatency (everything lands at t+2)
    # and inbox_cap 4 -> exactly 4 dropped, deterministically.
    class Storm(OneShot):
        def step(self, pstate, nodes, inbox, t, key):
            out = empty_outbox(self.cfg)
            out = out.replace(dest=jnp.where(t == 0, 0, -1) *
                              jnp.ones((self.cfg.n, 1), jnp.int32))
            got = jnp.sum(inbox.valid, 1).astype(jnp.int32)
            return {"got": pstate["got"] + got, "when": pstate["when"]}, \
                nodes, out

    proto = Storm(n=8, latency=NetworkNoLatency())
    net, p = run(proto, 5)
    assert int(p["got"][0]) == 4
    assert int(net.dropped) == 4


def test_far_future_clamps_without_spill():
    # delay 500 >> horizon 64, spill_cap 0: the arrival is clamped to the
    # ring edge and counted (the documented bounded-horizon contract).
    proto = OneShot(latency=NetworkFixedLatency(10), delay=500)
    net, p = run(proto, 80)
    assert int(net.clamped) == 1
    assert int(p["when"][1]) == 63          # t0 send -> 1 + (horizon-2)


def test_spill_delivers_far_future_arrivals_exactly():
    """With spill_cap > 0, an arrival far past the ring parks in the spill
    buffer and is delivered EXACTLY on time — the reference's
    unbounded-horizon semantics (MessageStorage, Network.java:201-299;
    sendArriveAt :384-390) without sizing the ring for it."""
    cfg = EngineConfig(n=4, horizon=64, inbox_cap=4, payload_words=2,
                       out_deg=1, bcast_slots=2, spill_cap=8)
    proto = OneShot(latency=NetworkFixedLatency(10), cfg=cfg, delay=500)
    net, p = run(proto, 520)
    assert int(p["when"][1]) == 511         # send t=1 + delay 500 + lat 10
    assert int(p["got"][1]) == 1 and int(jnp.sum(p["got"])) == 1
    assert int(net.clamped) == 0 and int(net.sp_dropped) == 0
    assert int(net.dropped) == 0
    assert int(jnp.sum(net.sp_arrival >= 0)) == 0   # slot freed after drain


def test_spill_overflow_counts():
    cfg = EngineConfig(n=4, horizon=64, inbox_cap=4, payload_words=2,
                       out_deg=1, bcast_slots=2, spill_cap=2)
    proto = OneShot(latency=NetworkFixedLatency(10), cfg=cfg, delay=500,
                    all_send=True)
    net, p = run(proto, 520)
    assert int(net.sp_dropped) == 2         # 4 far sends, 2 spill slots
    assert int(jnp.sum(p["got"])) == 2      # survivors still delivered
    assert int(jnp.sum(net.sp_arrival >= 0)) == 0


def test_mailbox_ring_wraps():
    # Horizon 64, run 200 ms with periodic resends crossing the wrap point.
    class Periodic(OneShot):
        def step(self, pstate, nodes, inbox, t, key):
            out = empty_outbox(self.cfg)
            sender = jnp.arange(self.cfg.n) == 0
            out = out.replace(dest=jnp.where(sender & (t % 50 == 0), 1,
                                             -1)[:, None])
            got = jnp.sum(inbox.valid, 1).astype(jnp.int32)
            return {"got": pstate["got"] + got, "when": pstate["when"]}, \
                nodes, out

    proto = Periodic(latency=NetworkFixedLatency(10))
    net, p = run(proto, 200)
    assert int(p["got"][1]) == 4  # sends at t=0,50,100,150


@pytest.mark.slow
def test_long_run_ring_integrity():
    """NetworkTest.java:425-435 analog (100M-ms run, scaled to 1M): a
    periodic sender over a horizon-64 ring that wraps ~15 625 times must
    deliver every message exactly once with exact counters and no
    residue."""

    class Tick:
        def __init__(self):
            self.latency = NetworkFixedLatency(5)
            self.cfg = EngineConfig(n=4, horizon=64, inbox_cap=4,
                                    payload_words=2, out_deg=1,
                                    bcast_slots=2)

        def init(self, seed):
            nodes = builders.NodeBuilder().build(seed, self.cfg.n)
            return (init_net(self.cfg, nodes, seed),
                    {"got": jnp.zeros(self.cfg.n, jnp.int32)})

        def step(self, pstate, nodes, inbox, t, key):
            out = empty_outbox(self.cfg)
            sender = (jnp.arange(self.cfg.n) == 0) & (t % 100 == 0)
            out = out.replace(
                dest=jnp.where(sender, 1, -1)[:, None])
            got = jnp.sum(inbox.valid, 1).astype(jnp.int32)
            return {"got": pstate["got"] + got}, nodes, out

    proto = Tick()
    net, p = proto.init(0)
    runner = Runner(proto, donate=False)
    for _ in range(100):
        net, p = runner.run_ms(net, p, 10_000)
    # sends at t = 0, 100, ..., 999900 all arrive at t+6 < 1M.
    assert int(net.time) == 1_000_000
    assert int(p["got"][1]) == 10_000
    assert int(jnp.sum(p["got"])) == 10_000
    assert int(net.nodes.msg_sent[0]) == 10_000
    assert int(net.nodes.msg_received[1]) == 10_000
    assert int(net.dropped) == 0 and int(net.clamped) == 0
    assert int(jnp.sum(net.box_count)) == 0       # no residue in the ring


def test_determinism_under_jit_copy():
    # The copy()+init() reproducibility contract (HandelTest.java:14-34):
    # re-initialising from the same seed reproduces runs exactly.
    proto = Broadcaster(n=32, latency=NetworkUniformLatency(100))
    n1, p1 = run(proto, 150, seed=3)
    n2, p2 = run(proto, 150, seed=3)
    assert jnp.array_equal(p1["when"], p2["when"])
    assert jnp.array_equal(n1.nodes.msg_received, n2.nodes.msg_received)


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 33 s; donate=big is a TPU memory configuration — CPU donation is a
# no-op, the wrapper's layout logic is exercised by tools/cardinal_1m
def test_runner_big_donation_bit_identical():
    """Runner(donate="big") — selective donation of >=1MB leaves (the
    tier-2 memory path, SCALE.md) — must be bit-identical to the
    undonated runner, including across the chunk_limit split."""
    from wittgenstein_tpu.models.handel import Handel
    import jax
    proto = Handel(node_count=128, nodes_down=12, threshold=114,
                   pairing_time=4, dissemination_period_ms=20)
    outs = []
    for donate in (False, "big"):
        r = Runner(proto, donate=donate, chunk_limit=300)
        net, ps = proto.init(7)
        net, ps = r.run_ms(net, ps, 700)   # 300 + 300 + 100 chunks
        outs.append((net, ps))
    (n1, p1), (n2, p2) = outs
    # "big" actually split something (the mailbox ring is > 1 MB).
    assert r._split is not None and len(r._split[1]) > 0
    for a, b in zip(jax.tree.leaves((n1, p1)), jax.tree.leaves((n2, p2))):
        assert jnp.array_equal(a, b)


def test_box_split_bit_equal():
    """EngineConfig.box_split (node-range ring sub-planes — the TPU
    runtime's ~1 GB single-buffer workaround at 100k-1M nodes) must be a
    pure layout change: full-pytree bit-equality at any P, including the
    reassembled inbox slices and every scatter path."""
    import dataclasses
    from wittgenstein_tpu.models.handel import Handel
    outs = []
    for p in (1, 2, 4):
        proto = Handel(node_count=128, nodes_down=12, threshold=114,
                       pairing_time=4, dissemination_period_ms=20)
        proto.cfg = dataclasses.replace(proto.cfg, box_split=p)
        r = Runner(proto, donate=False)
        net, ps = proto.init(3)
        net, ps = r.run_ms(net, ps, 300)
        outs.append((net, ps))
    import numpy as np
    base_net, base_ps = outs[0]
    # Compare the LOGICAL ring (concatenated sub-planes) + all other state.
    def logical(net, ps, p):
        cfg_h, cfg_n, cfg_c = 512, 128, 16
        ns = cfg_n // p
        def cat(planes):
            return np.concatenate(
                [np.asarray(pl).reshape(cfg_h, ns, cfg_c) for pl in planes],
                axis=1)
        f = len(net.box_data) // p
        data = [cat(net.box_data[fi * p:(fi + 1) * p]) for fi in range(f)]
        rest = [x for x in jax.tree.leaves((net, ps))
                if not any(x is y for y in
                           (*net.box_data, *net.box_src, *net.box_size))]
        return data, cat(net.box_src), cat(net.box_size), rest
    d0, s0, z0, rest0 = logical(base_net, base_ps, 1)
    for (net, ps), p in zip(outs[1:], (2, 4)):
        d, s, z, rest = logical(net, ps, p)
        for a, b in zip(d0, d):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(s0, s)
        np.testing.assert_array_equal(z0, z)
        assert len(rest0) == len(rest)
        for a, b in zip(rest0, rest):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
