"""wittgenstein_tpu.analysis — static-analysis passes over the compiled
simulator.

Compiles each registered protocol's superstep on CPU (the copy-insertion
and aliasing decisions the rules audit are backend-independent) and runs
pluggable rules over the optimized HLO, the jaxpr, and the Python
source, against checked-in per-protocol budgets that ratchet down, never
up.  See analysis/README.md for the rule catalogue and the CLI:

    python -m wittgenstein_tpu.analysis [--protocol NAME] [--rule NAME]
"""

from .framework import (RULES, Finding, Report, Rule, load_budgets,  # noqa
                        register_rule, run_analysis)
from .targets import AnalysisTarget, get_target, target_names  # noqa
