"""Rule ``host_durability`` — durable artifacts are written durably.

The serve plane's crash-only story (PR 13/15) rests on exactly two
write idioms, both living in `utils/jsonl.py` or shaped like it:

  * append-one-line + flush (+fsync when the write is an ack barrier),
  * write-temp + fsync + ``os.replace`` for whole-file rewrites.

A raw ``open(path, "w")`` + ``json.dump`` on a journal, ledger,
checkpoint or campaign-report path silently reintroduces the torn-file
window all of PR 15's kill-anywhere testing exists to close.  This
rule makes that a static error:

  * **strict zone** — wittgenstein_tpu/serve/, matrix/, memo/ and
    obs/ledger.py + obs/spans.py (the flight recorder's durable JSONL
    writer, PR 18) + obs/programs.py + obs/regress.py (the program
    catalog and the bench-history ledger, PR 20 — both are durable
    append-only logs) ARE the durable core: every raw write sink there
    (``open`` with a write mode, ``json.dump``, ``write_text``/
    ``write_bytes``, ``np.save*``, ``gzip.open``-for-write,
    ``checkpoint.save``) must sit in a function that fsyncs or
    ``os.replace``s before returning.
  * **tainted zone** — everywhere else scanned (obs/, server/, utils/,
    tools/): only sinks whose path expression *flows from a durable
    name* are checked.  Taint seeds are identifiers, attributes and
    string literals matching journal/ledger/checkpoint/ckpt/manifest/
    tombstone/memo, propagated through local (and module-level)
    assignments and ``with open(...) as f`` bindings; a module whose
    own filename matches (utils/checkpoint.py) taints all of its
    sinks.

`utils/jsonl.py` itself is exempt — it is the sanctioned
implementation the rule points everyone else at.

The fleet layer (PR 17) widened the strict zone's surface without
widening the rule: `serve/fleet.py` and the lease table in
`serve/journal.py` are covered by the serve/ prefix, and every
cross-process fleet write — journal submits, lease claims/releases,
ledger completion rows — already routes through `jsonl.append_line`
(fsync'd where the write is an ack or claim barrier) or whole-file
atomic replaces (worker stats snapshots, checkpoint files).

Suppressions: "relpath::qualname::sink" (e.g. the checked-in
``utils/checkpoint.py::save::numpy.savez_compressed`` — the documented
non-atomic primitive whose callers own the write-temp+replace dance;
``serve/fleet.py::spawn_worker::open`` — a worker's append-mode
STDOUT/STDERR log handed to Popen, operator diagnostics rather than
durable state).
"""

from __future__ import annotations

import ast
import re

from .framework import Finding, Rule, register_rule, parse_allow
from .host_common import (HOST_DIRS, Aliases, iter_source_files,
                          literal_strings, subtree_names)

STRICT_PREFIXES = ("wittgenstein_tpu/serve/", "wittgenstein_tpu/matrix/",
                   "wittgenstein_tpu/memo/")
STRICT_FILES = ("wittgenstein_tpu/obs/ledger.py",
                "wittgenstein_tpu/obs/spans.py",
                "wittgenstein_tpu/obs/programs.py",
                "wittgenstein_tpu/obs/regress.py")
EXEMPT_FILES = ("wittgenstein_tpu/utils/jsonl.py",)

DURABLE_PAT = re.compile(
    r"journal|ledger|checkpoint|ckpt|manifest|tombstone|memo(?!r)", re.I)

_SANCTIONERS = ("os.fsync", "os.replace")
_WRITE_MODE = re.compile(r"[wax+]")


def _mentions_durable(node) -> bool:
    return any(DURABLE_PAT.search(s)
               for s in subtree_names(node) + literal_strings(node))


def _write_mode_arg(call: ast.Call, pos: int):
    """The mode argument of an open()-style call (positional `pos` or
    ``mode=``) when it is a write-intent literal; None otherwise."""
    mode = None
    if len(call.args) > pos and isinstance(call.args[pos], ast.Constant):
        mode = call.args[pos].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and _WRITE_MODE.search(mode):
        return mode
    return None


def _sinks_in(node, aliases: Aliases):
    """Every raw write sink in `node`'s subtree:
    ``(sink_name, path_expr, lineno)``."""
    out = []
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        canon = aliases.canonical(call.func)
        f = call.func
        if canon == "open" or canon == "gzip.open":
            if _write_mode_arg(call, 1) and call.args:
                out.append((canon, call.args[0], call.lineno))
        elif isinstance(f, ast.Attribute) and f.attr == "open":
            # pathlib's Path.open(mode) — the path is the receiver
            if _write_mode_arg(call, 0):
                out.append(("open", f.value, call.lineno))
        elif canon == "json.dump":
            if len(call.args) > 1:
                out.append(("json.dump", call.args[1], call.lineno))
        elif isinstance(f, ast.Attribute) and f.attr in ("write_text",
                                                         "write_bytes"):
            out.append((f.attr, f.value, call.lineno))
        elif canon in ("numpy.save", "numpy.savez",
                       "numpy.savez_compressed"):
            if call.args:
                out.append((canon, call.args[0], call.lineno))
        elif canon.endswith("checkpoint.save") and call.args:
            out.append(("checkpoint.save", call.args[0], call.lineno))
    return out


def _sanctioned(fn_node, aliases: Aliases) -> bool:
    """True when the enclosing function fsyncs or os.replaces — the
    write-temp idiom, or an explicit durability barrier."""
    return any(isinstance(c, ast.Call)
               and aliases.canonical(c.func) in _SANCTIONERS
               for c in ast.walk(fn_node))


def _tainted_names(fn_node, module_seeds: frozenset) -> frozenset:
    """Local names whose value flows from a durable name (two passes
    over the function's assignments reach the chains in this tree)."""
    tainted = set(module_seeds)

    def expr_tainted(expr) -> bool:
        if _mentions_durable(expr):
            return True
        return any(isinstance(n, ast.Name) and n.id in tainted
                   for n in ast.walk(expr))

    for _ in range(2):
        for node in ast.walk(fn_node):
            pairs = []
            if isinstance(node, ast.Assign):
                pairs = [(t, node.value) for t in node.targets]
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                    and node.value is not None:
                pairs = [(node.target, node.value)]
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                pairs = [(i.optional_vars, i.context_expr)
                         for i in node.items if i.optional_vars]
            for target, value in pairs:
                if isinstance(target, ast.Name) and expr_tainted(value):
                    tainted.add(target.id)
    return frozenset(tainted)


def _functions(tree):
    """``(qualname, node)`` for top-level functions and methods, plus
    ("<module>", tree) for top-level code.  Nested functions stay part
    of their enclosing function's scope — a sink in a closure is
    sanctioned by the function that owns the write sequence."""
    out = [("<module>", tree)]
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node))
        elif isinstance(node, ast.ClassDef):
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append((f"{node.name}.{m.name}", m))
    return out


def scan_source_text(relpath: str, text: str, allow=()):
    """Lint one module; returns ``(relpath, qual, line, sink, why)``
    violations."""
    if relpath in EXEMPT_FILES:
        return []
    strict = relpath.startswith(STRICT_PREFIXES) or relpath in STRICT_FILES
    tree = ast.parse(text, filename=relpath)
    aliases = Aliases(tree)

    stem = relpath.rsplit("/", 1)[-1].removesuffix(".py")
    module_tainted = bool(DURABLE_PAT.search(stem))
    module_seeds = frozenset()
    if not strict:
        seeds = set()
        for node in tree.body:
            if isinstance(node, ast.Assign) and _mentions_durable(node.value):
                seeds |= {t.id for t in node.targets
                          if isinstance(t, ast.Name)}
            # a module-level `with open(...)` is rare; functions cover it
        module_seeds = frozenset(seeds)

    violations = []
    for qual, fn in _functions(tree):
        sinks = []
        if fn is tree:
            # module-level statements only (function bodies get their
            # own, correctly-scoped pass)
            for stmt in tree.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef)):
                    sinks += _sinks_in(stmt, aliases)
        else:
            sinks = _sinks_in(fn, aliases)
        if not sinks:
            continue
        if not strict:
            tainted = _tainted_names(fn, module_seeds)
        for sink, path_expr, line in sinks:
            if not strict and not module_tainted:
                hot = _mentions_durable(path_expr) or any(
                    isinstance(n, ast.Name) and n.id in tainted
                    for n in ast.walk(path_expr))
                if not hot:
                    continue
            if _sanctioned(fn, aliases):
                continue
            if f"{relpath}::{qual}::{sink}" in allow:
                continue
            zone = ("the durable core (serve/matrix/memo/ledger)"
                    if strict else "a durable path")
            violations.append(
                (relpath, qual, line, sink,
                 f"raw {sink} write on {zone} without fsync/os.replace "
                 "in the enclosing function — route it through "
                 "utils/jsonl.py (append_line/rewrite) or the "
                 "write-temp + fsync + os.replace idiom (allowlist "
                 f'key: "{relpath}::{qual}::{sink}")'))
    return violations


def scan_tree(dirs=HOST_DIRS, root=None, allow=()):
    violations, files = [], 0
    for relpath, text in iter_source_files(dirs, root=root):
        files += 1
        violations += scan_source_text(relpath, text, allow)
    return violations, files


@register_rule
class HostDurabilityRule(Rule):
    name = "host_durability"
    scope = "global"
    budgeted_metrics = ("violations",)

    def run(self, target, budget):
        allow = parse_allow(budget)
        violations, files = scan_tree(allow=allow)
        findings = [
            Finding(rule=self.name, target=f"{rel}:{line}",
                    severity="error", path=rel, line=line,
                    message=f"{qual}: {why}")
            for rel, qual, line, sink, why in violations]
        findings.append(Finding(
            rule=self.name, target="global", severity="info",
            metric="violations", value=len(violations),
            message=f"{files} host files: {len(violations)} raw "
                    "durable-path writes"))
        return findings

    def describe(self):
        _, files = scan_tree()
        return f"source: {files} host files (strict zone: serve/, " \
               "matrix/, memo/, obs/ledger.py)"
