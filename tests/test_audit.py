"""The invariant audit plane (wittgenstein_tpu/obs/audit.py).

Invariants, per the package contract:

  * audit-ON is simulation-bit-identical: the full (NetState, pstate)
    pytree after an audited chunk equals the uninstrumented engine's —
    dense scan (PingPong, Handel exact + cardinal, Dfinity), the
    superstep-K window engine (K ∈ {2, 4}), the batched twin, the
    fast-forward while loop (whose skip stats must also match), and
    the sharded runner (including the cross-shard conservation check);
  * clean runs audit CLEAN: zero violations across every monitored
    invariant for every covered protocol and engine variant;
  * a planted `FaultInjector` perturbation is FLAGGED, in the same
    window that `first_divergence()` localizes — the audit plane and
    the bisector must agree on where the run broke (the acceptance
    pin, for Handel exact and PingPong);
  * the audit totals cross-check against the metrics plane
    (`cross_check_metrics`), and the `audit_zero_cost` analysis rule
    catches silently-dead monitors.

Protocol configs mirror tests/test_trace.py / test_obs.py so the
compiles share the suite's persistent-cache entries where possible.
"""

import dataclasses
import importlib.util
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wittgenstein_tpu.core.batched import scan_chunk_batched
from wittgenstein_tpu.core.network import (Runner, fast_forward_chunk,
                                           scan_chunk)
from wittgenstein_tpu.obs import (AuditReport, AuditSpec, audit_block,
                                  audit_variant, cross_check_metrics,
                                  fast_forward_chunk_audit,
                                  scan_chunk_audit,
                                  scan_chunk_batched_audit)
from wittgenstein_tpu.obs.diff import FaultInjector, first_divergence


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _protocols():
    from wittgenstein_tpu.models.dfinity import Dfinity
    from wittgenstein_tpu.models.handel import Handel
    from wittgenstein_tpu.models.pingpong import PingPong

    return {
        "Handel": lambda: Handel(
            node_count=64, threshold=56, nodes_down=6, pairing_time=4,
            dissemination_period_ms=20, level_wait_time=50, fast_path=10),
        "HandelCardinal": lambda: Handel(
            node_count=64, threshold=56, nodes_down=6, pairing_time=4,
            dissemination_period_ms=20, fast_path=10, mode="cardinal"),
        "Dfinity": lambda: Dfinity(block_producers_count=10,
                                   attesters_count=10,
                                   attesters_per_round=10),
        "PingPong": lambda: PingPong(node_count=64),
    }


def _floor_handel():
    """test_superstep.py's floor-rich Handel: fixed 16 ms latency
    licenses the K ∈ {2, 4} window ladder."""
    from wittgenstein_tpu.models.handel import Handel
    return Handel(node_count=64, threshold=56, nodes_down=6,
                  pairing_time=4, dissemination_period_ms=20,
                  level_wait_time=50, fast_path=10, horizon=64,
                  network_latency_name="NetworkFixedLatency(16)")


# ------------------------------------------------------------------ ON


# Tier-1 keeps the two broadcast-bearing dense cells (PingPong exercises
# send/deliver + bc_consistency cheaply, Dfinity the committee-paced
# broadcast table); the Handel exact + cardinal dense cells live in the
# slow deep-matrix battery — Handel exact is ALSO gated fast through the
# batched twin and the superstep ladder below (reports/TIER1_DURATIONS.md).
@pytest.mark.parametrize("name", ["PingPong", "Dfinity"])
def test_audit_on_bit_identical_dense_and_clean(name):
    proto = _protocols()[name]()
    ms, seeds = 120, 2
    spec = AuditSpec()
    sd = jnp.arange(seeds, dtype=jnp.int32)

    nets, ps = jax.vmap(proto.init)(sd)
    ref = jax.jit(jax.vmap(scan_chunk(proto, ms)))(nets, ps)
    nets, ps = jax.vmap(proto.init)(sd)
    net2, ps2, ac = jax.jit(jax.vmap(scan_chunk_audit(proto, ms, spec)))(
        nets, ps)
    _trees_equal(ref, (net2, ps2))
    report = AuditReport.from_carry(spec, ac)
    assert report.clean, report.format()
    # the totals actually sampled the run (not a dead plane)
    assert report.totals_dict()["msg_sent"] > 0
    # a verdict built WITH the engine config claims only the compiled
    # subset (dense run: never shard_conservation)
    from wittgenstein_tpu.obs.audit import monitored_invariants
    mon = monitored_invariants(spec, proto.cfg)
    assert "shard_conservation" not in mon


def test_audit_superstep_windows_bit_identical_and_clean():
    proto = _floor_handel()
    spec = AuditSpec()
    net, ps = proto.init(0)
    ref = jax.jit(scan_chunk_audit(proto, 40, spec))(net, ps)
    assert AuditReport.from_carry(spec, ref[2]).clean
    for k in (2, 4):
        net, ps = proto.init(0)
        got = jax.jit(scan_chunk_audit(proto, 40, spec, superstep=k))(
            net, ps)
        # same trajectory AND the same per-window verdicts: the K-ms
        # conservation balance is exact per origin ms, so the fused
        # window proves exactly what the per-ms windows prove
        _trees_equal(ref[:2], got[:2])
        report = AuditReport.from_carry(spec, got[2])
        assert report.clean, (k, report.format())


def test_audit_batched_engine_bit_identical_and_clean():
    proto = _protocols()["Handel"]()
    ms, seeds = 80, 2
    spec = AuditSpec()
    sd = jnp.arange(seeds, dtype=jnp.int32)
    nets, ps = jax.vmap(proto.init)(sd)
    ref = jax.jit(scan_chunk_batched(proto, ms))(nets, ps)
    nets, ps = jax.vmap(proto.init)(sd)
    net2, ps2, ac = jax.jit(scan_chunk_batched_audit(proto, ms, spec))(
        nets, ps)
    _trees_equal(ref, (net2, ps2))
    assert AuditReport.from_carry(spec, ac).clean


def test_audit_fast_forward_bit_identical_and_clean():
    proto = _protocols()["PingPong"]()
    ms, seeds = 240, 2
    spec = AuditSpec()
    sd = jnp.arange(seeds, dtype=jnp.int32)
    nets, ps = jax.vmap(proto.init)(sd)
    ref = jax.jit(fast_forward_chunk(proto, ms, seed_axis=True))(nets, ps)
    nets, ps = jax.vmap(proto.init)(sd)
    net2, ps2, stats, ac = jax.jit(
        fast_forward_chunk_audit(proto, ms, spec, seed_axis=True))(
        nets, ps)
    _trees_equal(ref[:2], (net2, ps2))
    assert int(np.asarray(stats["skipped_ms"])) == \
        int(np.asarray(ref[2]["skipped_ms"])) > 0
    report = AuditReport.from_carry(spec, ac)
    assert report.clean, report.format()


def test_audit_sharded_runner_and_cross_shard_conservation():
    from jax.sharding import Mesh
    from wittgenstein_tpu.parallel.sharded import RingForward, ShardedRunner

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    proto = RingForward(n=64, stride=9, latency=10)
    runner = ShardedRunner(proto, mesh)
    spec = AuditSpec()
    snet, ps, ac = runner.run_ms(*runner.init(3), 24, audit=spec)
    # the audited run didn't perturb the simulation
    snet2, ps2 = runner.run_ms(*runner.init(3), 24)
    _trees_equal((snet, ps), (snet2, ps2))
    from wittgenstein_tpu.obs.audit import monitored_invariants
    report = AuditReport.from_carry(       # per-shard carries merged
        spec, ac,
        monitored=monitored_invariants(spec, proto.cfg, sharded=True))
    assert report.clean, report.format()
    assert "shard_conservation" in report.claimed
    assert "spill_budget" not in report.claimed
    # the cross-shard conservation monitor watched REAL traffic (the
    # ring protocol routes every send stride=9 nodes away, crossing
    # shard boundaries) and the batch-merged totals are global
    nodes = runner.gather_nodes(snet)
    assert report.totals_dict()["msg_received"] == \
        int(nodes.msg_received.sum()) > 0
    # one plane per pass
    from wittgenstein_tpu.obs import MetricsSpec
    with pytest.raises(ValueError, match="one plane per"):
        runner.run_ms(snet, ps, 24, metrics=MetricsSpec(), audit=spec)


@pytest.mark.slow
def test_audit_deep_matrix_bit_identical_and_clean():
    """The wide acceptance matrix (each cell a fresh compile, so
    slow-marked; the fast battery above already gates every contract
    once): the Handel exact + cardinal dense cells, ff Dfinity +
    Handel, superstep K=2 on the self-sending protocols, cardinal
    batched."""
    protos = _protocols()
    spec = AuditSpec()
    sd = jnp.arange(2, dtype=jnp.int32)
    # dense cells not in the fast battery
    for name in ("Handel", "HandelCardinal"):
        proto = protos[name]()
        nets, ps = jax.vmap(proto.init)(sd)
        ref = jax.jit(jax.vmap(scan_chunk(proto, 120)))(nets, ps)
        nets, ps = jax.vmap(proto.init)(sd)
        n2, p2, ac = jax.jit(jax.vmap(scan_chunk_audit(proto, 120,
                                                       spec)))(nets, ps)
        _trees_equal(ref, (n2, p2))
        assert AuditReport.from_carry(spec, ac).clean, name
    # fast-forward: the other two opted-in protocols
    for name in ("Dfinity", "Handel"):
        proto = protos[name]()
        nets, ps = jax.vmap(proto.init)(sd)
        ref = jax.jit(fast_forward_chunk(proto, 120, seed_axis=True))(
            nets, ps)
        nets, ps = jax.vmap(proto.init)(sd)
        n2, p2, stats, ac = jax.jit(fast_forward_chunk_audit(
            proto, 120, spec, seed_axis=True))(nets, ps)
        _trees_equal(ref[:2], (n2, p2))
        assert AuditReport.from_carry(spec, ac).clean, name
    # the universal K=2 window on the self-senders
    for name in ("PingPong", "Dfinity"):
        proto = protos[name]()
        net, ps = proto.init(0)
        ref = jax.jit(scan_chunk_audit(proto, 40, spec))(net, ps)
        net, ps = proto.init(0)
        got = jax.jit(scan_chunk_audit(proto, 40, spec, superstep=2))(
            net, ps)
        _trees_equal(ref[:2], got[:2])
        assert AuditReport.from_carry(spec, got[2]).clean, name
    # cardinal mode through the batched twin
    proto = protos["HandelCardinal"]()
    nets, ps = jax.vmap(proto.init)(sd)
    ref = jax.jit(scan_chunk_batched(proto, 80))(nets, ps)
    nets, ps = jax.vmap(proto.init)(sd)
    n2, p2, ac = jax.jit(scan_chunk_batched_audit(proto, 80, spec))(
        nets, ps)
    _trees_equal(ref, (n2, p2))
    assert AuditReport.from_carry(spec, ac).clean


# --------------------------------------------------- audit x triage


def _assert_injection_flagged(proto, at_ms, total_ms, chunk_ms):
    """The acceptance pin: a one-(ms, node, leaf) perturbation trips a
    conservation monitor in ITS OWN window, and the audit verdict
    agrees with `first_divergence()`'s localization."""
    bad = FaultInjector(proto, at_ms=at_ms, leaf="nodes.msg_sent",
                        node=5, delta=-(1 << 20))
    report, _ = audit_variant(bad, total_ms, {"superstep": 1},
                              AuditSpec(mode="first"))
    assert not report.clean
    assert report.first["invariant"] == "counter_monotone"
    assert report.first["ms"] == at_ms          # granularity-1 windows
    div = first_divergence(proto, {"superstep": 1}, {"superstep": 1},
                           total_ms, chunk_ms=chunk_ms, protocol_b=bad,
                           trace_spec=False)
    assert div is not None and div.ms == report.first["ms"]
    assert "msg_sent" in div.leaf
    # the report is loud about it
    assert "AUDIT" in report.format()
    assert "counter_monotone" in report.format()


def test_audit_flags_injected_fault_pingpong_and_agrees_with_bisector():
    from wittgenstein_tpu.models.pingpong import PingPong
    _assert_injection_flagged(PingPong(node_count=32), at_ms=37,
                              total_ms=64, chunk_ms=32)


def test_audit_flags_injected_fault_handel_and_agrees_with_bisector():
    _assert_injection_flagged(_protocols()["Handel"](), at_ms=21,
                              total_ms=40, chunk_ms=20)


def test_audit_mode_count_has_no_first_record():
    from wittgenstein_tpu.models.pingpong import PingPong
    bad = FaultInjector(PingPong(node_count=32), at_ms=37,
                        leaf="nodes.msg_sent", node=5, delta=-(1 << 20))
    report, _ = audit_variant(bad, 64, {"superstep": 1},
                              AuditSpec(mode="count"))
    assert not report.clean and report.first is None
    assert report.violations()["counter_monotone"] >= 1
    assert "mode='first'" in report.format()    # points at the remedy


# ------------------------------------------------------------ drivers


def test_runner_audit_and_report():
    proto = _protocols()["PingPong"]()
    spec = AuditSpec()
    r0 = Runner(proto)
    net, ps = proto.init(0)
    ref = r0.run_ms(net, ps, 200)

    r1 = Runner(proto, audit=spec)
    net, ps = proto.init(0)
    out = r1.run_ms(net, ps, 100)
    out = r1.run_ms(*out, 100)                  # chunked: carries stitch
    _trees_equal(ref, out)
    report = r1.audit_report()
    assert report.clean, report.format()
    rep = r1.run_report(out[0], wall_s=0.25)
    assert "audit clean" in rep and "AUDIT VIOLATIONS" not in rep
    # one plane per pass
    from wittgenstein_tpu.obs import MetricsSpec
    with pytest.raises(ValueError, match="run the chunk twice"):
        Runner(proto, metrics=MetricsSpec(), audit=spec)

    # a violated run SHOUTS in the report
    bad = FaultInjector(proto, at_ms=37, leaf="nodes.msg_sent", node=5,
                        delta=-(1 << 20))
    r2 = Runner(bad, audit=spec)
    net, ps = bad.init(0)
    out2 = r2.run_ms(net, ps, 100)
    assert "AUDIT VIOLATIONS" in r2.run_report(out2[0])


def test_audit_metrics_cross_check():
    from wittgenstein_tpu.obs import MetricsFrame, MetricsSpec
    from wittgenstein_tpu.obs.engine import scan_chunk_metrics

    proto = _protocols()["PingPong"]()
    ms, seeds = 120, 2
    sd = jnp.arange(seeds, dtype=jnp.int32)
    mspec = MetricsSpec(stat_each_ms=10)
    nets, ps = jax.vmap(proto.init)(sd)
    _, _, mc = jax.jit(jax.vmap(scan_chunk_metrics(proto, ms, mspec)))(
        nets, ps)
    frame = MetricsFrame.from_carry(mspec, mc)

    report, _ = audit_variant(proto, ms, {"superstep": 1}, AuditSpec(),
                              seeds=seeds)
    assert cross_check_metrics(report, frame) == []
    # and the cross-check actually compares something: corrupt one
    # audit total and it must scream
    broken = dataclasses.replace(report, totals=report.totals + 1)
    assert len(cross_check_metrics(broken, frame)) == len(
        [c for c in ("msg_sent", "msg_received", "drop_count",
                     "done_count") if mspec.col(c) is not None])


def test_audit_spec_validation_and_block():
    with pytest.raises(ValueError, match="mode"):
        AuditSpec(mode="loud")
    with pytest.raises(ValueError, match="unknown invariants"):
        AuditSpec(invariants=("ring_conservation", "nope"))
    with pytest.raises(ValueError, match="spill_budget"):
        AuditSpec(spill_budget=-1)
    # canonical ordering regardless of the order passed
    spec = AuditSpec(invariants=("counter_monotone", "ring_conservation"))
    assert spec.invariants == ("ring_conservation", "counter_monotone")
    assert spec.enabled("ring_conservation")
    assert not spec.enabled("bc_consistency")


def test_ledger_round_trip(tmp_path):
    from wittgenstein_tpu.obs import ledger

    path = tmp_path / "ledger.jsonl"
    line = {"metric": "m", "value": 12.5, "unit": "sim_ms/s",
            "sim_ms": 1000, "superstep": 2, "batch": 4,
            "audit": {"clean": True, "total": 0},
            "engine_metrics": {"totals": {"msg_sent": 7}}}
    mani = ledger.manifest_from_bench(line, config={"n": 64, "k": 2})
    assert mani.audit_clean is True
    assert mani.metrics_digest and mani.audit_digest
    assert mani.config_digest == ledger.digest({"n": 64, "k": 2})
    assert ledger.append(mani, path) == str(path)
    ledger.append(mani, path)                   # append-only: 2 rows
    rows = ledger.read_all(path)
    assert len(rows) == 2
    assert dataclasses.asdict(rows[0]) == dataclasses.asdict(mani)
    # a torn tail is skipped, not fatal
    with open(path, "a") as f:
        f.write("{not json\n")
    assert len(ledger.read_all(path)) == 2


# ------------------------------------------------------------- tools


def _cli():
    """Load tools/audit.py (tools/ is not a package)."""
    tools = pathlib.Path(__file__).resolve().parent.parent / "tools"
    sys.path.insert(0, str(tools))
    try:
        spec = importlib.util.spec_from_file_location(
            "audit_cli", tools / "audit.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(str(tools))
    return mod


def test_audit_cli_clean_and_violated(monkeypatch, capsys):
    monkeypatch.setenv("WTPU_LEDGER", "0")
    cli = _cli()
    rc = cli.main(["--proto", "pingpong", "--nodes", "32",
                   "--ms", "64"])
    out = capsys.readouterr().out
    assert rc == 0 and "CLEAN" in out
    rc = cli.main(["--proto", "pingpong", "--nodes", "32", "--ms", "64",
                   "--inject", "37:nodes.msg_sent:5:-1048576"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "counter_monotone" in out and "ms 37" in out
    # config errors are exit code 2
    assert cli.main(["--proto", "nope"]) == 2
    assert cli.main(["--proto", "pingpong", "--inject", "bad"]) == 2


# ------------------------------------------------------------- rules


def test_audit_zero_cost_rule_catches_dead_instrumentation():
    from wittgenstein_tpu.analysis.rules_audit import AuditZeroCostRule
    from wittgenstein_tpu.analysis.targets import AnalysisTarget

    def plain_chunk(x, y):
        def body(c, _):
            return (c[0] + 1, c[1] * 2), ()
        c, _ = jax.lax.scan(body, (x, y), length=3)
        return c

    rule = AuditZeroCostRule()
    args = (jnp.zeros((4,), jnp.int32), jnp.ones((4,), jnp.float32))
    clean = AnalysisTarget.from_fn("fake", plain_chunk, args)
    fs = rule.run(clean, {})
    vals = {f.metric: f.value for f in fs if f.metric}
    assert vals["carry_extra_leaves"] == 0
    assert not [f for f in fs if f.severity == "error"]

    # an uninstrumented build labeled as an audit target = silently-
    # dead monitors, which must be an error
    dead = AnalysisTarget.from_fn("fake+audit", plain_chunk, args)
    errs = [f for f in rule.run(dead, {}) if f.severity == "error"]
    assert errs and "silently dead" in errs[0].message
