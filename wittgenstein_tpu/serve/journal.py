"""Durable submission journal — the serve plane's write-ahead log.

The PR-10/13 checkpoint machinery makes a RUNNING group survivable: a
kill mid-chunk resumes from the last chunk boundary.  What it cannot
cover is the window this module exists for — a request that was
ACCEPTED but had not launched when the process died.  Its spec lived
only in the scheduler's in-memory queue, so the client holds an ack
for work that no longer exists anywhere.

`SubmissionJournal` closes that window with the classic WAL shape:

  * `record_submit` appends the accepted request (canonical spec JSON
    + rid + label/ledger_extra — everything `Scheduler.submit` was
    handed) to an append-only JSONL file and fsyncs BEFORE the submit
    acks.  An ack therefore implies a durable record; a journal write
    failure fails the submit loudly instead of promising durability
    the disk refused.
  * `record_settled` appends a tombstone when the request COMPLETES
    (done), is QUARANTINED (a deterministic poison verdict — re-running
    it would only re-quarantine) or is WITHDRAWN.  A generic group
    error is deliberately NOT tombstoned: it is presumed transient
    (dead device), and the crash-only contract is redo-beats-lose —
    those entries replay on the next recovery.  Tombstones are appends
    too — the journal is never edited in place, so a crash at ANY byte
    offset leaves at worst one torn tail line.
  * `replay` returns the un-tombstoned submit entries in submission
    order, reading through the shared torn-tail-tolerant JSONL reader
    (utils/jsonl.py): a line torn by the kill is skipped with a loud
    stderr note (one in-flight row, already un-acked), never raised.
  * `compact` atomically rewrites the file down to the live entries —
    `Scheduler.resume_journal` runs it after a replay so the journal's
    size tracks the live queue, not the service's lifetime.

The journal stores SPECS, not states: a replayed request re-runs from
scratch (bit-identical — the engine is a deterministic pure function
of the spec), and a request that ALSO left a group checkpoint resumes
from the checkpoint instead (`Scheduler.recover` orders the two).  A
memo snapshot-fork submission is journaled as its plain full-span
spec: the fork state died with the process, and an unforked re-run is
bit-identical by the fork contract — the fork provenance is dropped
on replay so the re-run's ledger row never claims a fork it didn't
take.
"""

from __future__ import annotations

import os
import threading
import time

from ..utils import jsonl

#: journal entry schema (bump on field changes; replay keys on it)
SCHEMA = 1

#: the journal file inside `journal_dir` (one per scheduler)
FILENAME = "submissions.jsonl"


class SubmissionJournal:
    """One scheduler's WAL (module docstring)."""

    #: lock inventory (analysis rule ``host_locks``): `_mu` guards the
    #: FILE, not attributes — every append/replay/compact serializes
    #: on it inside the methods below; no self attribute is mutated
    #: after __init__, so the owned set is empty by design.
    _LOCK_OWNS: dict = {"_mu": ()}

    def __init__(self, journal_dir):
        self.dir = str(journal_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, FILENAME)
        #: one lock serializes every file operation (append, replay,
        #: compact): a reader can never observe a half-written line
        #: from a concurrent in-process append (no false torn-tail
        #: warnings from `lag()` health polls), and compaction can
        #: never rewrite the file from a stale snapshot and erase a
        #: row appended since — the journal is per-scheduler, so
        #: in-process exclusion is the whole story
        self._mu = threading.Lock()

    # ------------------------------------------------------------ appends

    def record_submit(self, rid: str, spec, label=None,
                      ledger_extra=None) -> None:
        """Durably record one accepted submission (fsync'd — this runs
        BEFORE the submit acks).  Raises OSError through: the caller
        must not ack a request the journal could not hold."""
        with self._mu:
            jsonl.append_line(self.path, {
                "schema": SCHEMA, "kind": "submit", "rid": rid,
                "spec": spec.to_json(), "label": label,
                "ledger_extra": dict(ledger_extra) if ledger_extra
                else None,
                "ts_unix": time.time()}, fsync=True)

    def record_settled(self, rid: str, status: str) -> None:
        """Tombstone a settled request (done/quarantined/withdrawn —
        module docstring; transient group errors stay replayable).
        Never raises — a tombstone lost to a full disk costs one
        redundant (bit-identical) re-run on the next replay, which is
        the crash-only trade: redo beats lose."""
        import sys
        try:
            with self._mu:
                jsonl.append_line(self.path, {
                    "schema": SCHEMA, "kind": "tombstone", "rid": rid,
                    "status": status, "ts_unix": time.time()})
        except OSError as e:
            print(f"journal: tombstone append failed for {rid} ({e}); "
                  "the entry replays once more on the next resume",
                  file=sys.stderr)

    # ------------------------------------------------------------- replay

    def _replay_locked(self) -> list:
        live: dict = {}
        for _, row in jsonl.iter_lines(self.path, label="journal"):
            kind, rid = row.get("kind"), row.get("rid")
            if not rid:
                continue
            if kind == "submit" and row.get("schema") == SCHEMA:
                live.setdefault(rid, row)
            elif kind == "tombstone":
                live.pop(rid, None)
        return list(live.values())

    def replay(self) -> list:
        """The un-tombstoned submit entries, in submission order (the
        crash's survivors).  Torn/malformed lines are skipped loudly by
        the shared reader; a tombstone whose submit line is missing
        (or torn) is simply inert."""
        with self._mu:
            return self._replay_locked()

    def lag(self) -> int:
        """Entries accepted but not yet tombstoned — the health
        endpoint's "journal lag" number (0 = every acked request has
        settled)."""
        return len(self.replay())

    def compact(self) -> None:
        """Atomically rewrite the journal down to its CURRENT live
        entries — recomputed under the lock at rewrite time, so a
        submit or tombstone appended after an earlier `replay()`
        snapshot can never be erased (the fsync-before-ack promise
        survives compaction on a live scheduler).  Crash-safe via
        write-temp + os.replace; a failure leaves the uncompacted
        (still correct) file."""
        import sys
        try:
            with self._mu:
                jsonl.rewrite(self.path, self._replay_locked())
        except OSError as e:
            print(f"journal: compaction failed ({e}); the uncompacted "
                  "journal remains valid", file=sys.stderr)
