"""Fused Pallas GSF queue merge — the three-tier bounded-queue merge of
`models/gsf._receive` (existing entries ∪ incoming aggregates ∪ incoming
individuals, GSFSignature.java:539-553 under the documented bounded
policy) as one kernel.

Same motivation and structure as `ops/pallas_merge.py` (the Handel
delivery kernel): the XLA form materializes the [M, Q+2S, W]
candidate-sig concatenation, top_k's the tiered keys and gathers every
column through the order.  Here the candidate columns are synthesized
in-register (existing sig rows, pool-reconstructed aggregate rows, and
the individuals' one-bit rows built from the sender id), the Q-round
selection and gathers run in VMEM, the queue sig plane is updated in
place, and the `got_indiv` delta (the per-node OR of newly-admitted
individuals' bits) comes out of the same pass.

Key layout (must match `models/gsf._receive` exactly):
  tier = 2 for incoming individuals, else 0 if the entry is an
  individual else 1; key = (tier*(L+1) + (lvl if tier==1 else 0))*C + c
  for valid candidates (unique via the position term), BIG0 + c for
  invalid ones (lax.top_k's ascending-index tie rule, made explicit).

Bit-equality with the XLA path: tests/test_gsf.py::
test_gsf_pallas_merge_bit_equal (end-to-end full-pytree over a run).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

I32 = jnp.int32
U32 = jnp.uint32
BIG0 = 0x7FFFFF00
EXCLUDED = 0x7FFFFFFF


def gsf_merge_row_bytes(q_cap: int, s_cap: int, w: int) -> int:
    """Per-row VMEM cost model of `_gsf_kernel`: q_cap unrolled
    selection rounds over q_cap + 2*s_cap candidate columns with
    [blk, W]-lane sig temporaries (same structure as
    pallas_merge.merge_row_bytes, validated there on chip).  Named so
    the analysis vmem_budget rule evaluates the SAME model the launcher
    budgets with."""
    from .pallas_merge import _pad_lanes

    return q_cap * (q_cap + 2 * s_cap) * _pad_lanes(w) * 4


def _gsf_kernel(exf_ref, exl_ref, exi_ref, exk_ref, exs_ref,
                src_ref, lvl_ref, aok_ref, iok_ref, isig_ref,
                of_ref, ol_ref, oi_ref, os_ref, ogot_ref, okept_ref,
                *, q_cap, s_cap, levels):
    blk = exf_ref.shape[0]
    w = exs_ref.shape[2]
    c_tot = q_cap + 2 * s_cap

    exf = exf_ref[...]                                 # [blk, Q]
    exl = exl_ref[...]
    exi = exi_ref[...]                                 # 1 = individual
    ex_keep = exk_ref[...] != 0
    src = src_ref[...]                                 # [blk, S]
    lvl = lvl_ref[...]
    aok = aok_ref[...] != 0
    iok = iok_ref[...] != 0

    word_idx = jax.lax.broadcasted_iota(I32, (blk, w), 1)

    # Candidate columns: from/lvl/indiv/key as [blk] column lists; sig
    # rows fetched per column inside the selection loop.
    u_from, u_lvl, u_ind, keys = [], [], [], []
    for c in range(c_tot):
        if c < q_cap:
            f = jnp.where(ex_keep[:, c], exf[:, c], -1)
            lv = exl[:, c]
            ind = exi[:, c]
            tier = jnp.where(ind != 0, 0, 1)
        elif c < q_cap + s_cap:
            s = c - q_cap
            f = jnp.where(aok[:, s], src[:, s], -1)
            lv = lvl[:, s]
            ind = jnp.zeros((blk,), I32)
            tier = jnp.ones((blk,), I32)
        else:
            s = c - q_cap - s_cap
            f = jnp.where(iok[:, s], src[:, s], -1)
            lv = lvl[:, s]
            ind = jnp.ones((blk,), I32)
            tier = jnp.full((blk,), 2, I32)
        lvl_term = jnp.where(tier == 1, lv, 0)
        k = (tier * (levels + 1) + lvl_term) * c_tot + c
        keys.append(jnp.where(f >= 0, k, BIG0 + c))
        u_from.append(f)
        u_lvl.append(lv)
        u_ind.append(ind)
    key_mat = jnp.stack(keys, axis=1)                  # [blk, C]

    def cand_sig(c):
        if c < q_cap:
            return exs_ref[:, c, :]
        if c < q_cap + s_cap:
            return isig_ref[:, c - q_cap, :]
        # Individuals: ind_ok ? one_bit(src) : 0 — the exact junk
        # semantics of the XLA concatenation.
        s = c - q_cap - s_cap
        sid = src[:, s:s + 1]
        bit = jnp.where(word_idx == sid // 32,
                        U32(1) << (sid % 32).astype(U32), U32(0))
        return jnp.where(iok[:, s:s + 1], bit, U32(0))

    sel_f, sel_l, sel_i, sel_sig = [], [], [], []
    got_add = jnp.zeros((blk, w), U32)
    kept_ex_agg = jnp.zeros((blk, 1), I32)
    for _ in range(q_cap):
        kmin = jnp.min(key_mat, axis=1, keepdims=True)
        hit = key_mat == kmin                          # [blk, C]
        f = jnp.zeros((blk,), I32)
        lv = jnp.zeros((blk,), I32)
        ind = jnp.zeros((blk,), I32)
        sig = jnp.zeros((blk, w), U32)
        new_ind = jnp.zeros((blk,), bool)
        for c in range(c_tot):
            h = hit[:, c]
            f = jnp.where(h, u_from[c], f)
            lv = jnp.where(h, u_lvl[c], lv)
            ind = jnp.where(h, u_ind[c], ind)
            sig = jnp.where(h[:, None], cand_sig(c), sig)
            if c < q_cap:
                kept_ex_agg = kept_ex_agg + jnp.where(
                    (h & (u_from[c] >= 0) & (u_ind[c] == 0))[:, None],
                    1, 0)
            elif c >= q_cap + s_cap:
                new_ind = new_ind | h
        sel_f.append(f[:, None])
        sel_l.append(lv[:, None])
        sel_i.append(ind[:, None])
        sel_sig.append(sig)
        # got_indiv delta: newly admitted individuals' sender bits.
        fid = jnp.maximum(f, 0)[:, None]
        fbit = jnp.where(word_idx == fid // 32,
                         U32(1) << (fid % 32).astype(U32), U32(0))
        got_add = got_add | jnp.where((new_ind & (f >= 0))[:, None],
                                      fbit, U32(0))
        key_mat = jnp.where(hit, EXCLUDED, key_mat)

    of_ref[...] = jnp.concatenate(sel_f, axis=1)
    ol_ref[...] = jnp.concatenate(sel_l, axis=1)
    oi_ref[...] = jnp.concatenate(sel_i, axis=1)
    os_ref[...] = jnp.stack(sel_sig, axis=1)
    ogot_ref[...] = got_add
    okept_ref[...] = kept_ex_agg


@functools.partial(jax.jit, static_argnames=("levels", "interpret"))
def gsf_merge_pallas(q_from, q_lvl, q_indiv, ex_keep, q_sig,
                     src, level, agg_ok, ind_ok, sig_all,
                     levels: int, interpret: bool = False):
    """Fused GSF three-tier queue merge.  Returns (q_from', q_lvl',
    q_indiv' (bool), q_sig', got_add [M, W], kept_ex_agg [M]) —
    bit-identical to the `select_queue` tail of `models/gsf._receive`
    (dup/supersede/got_indiv masks are computed by the caller; `ex_keep`
    and `agg_ok`/`ind_ok` carry them in).
    """
    from jax.experimental import pallas as pl

    from .pallas_merge import _pick_block

    m, q = q_from.shape
    s = src.shape[1]
    w = q_sig.shape[2]
    assert sig_all.shape == (m, s, w), (q_sig.shape, sig_all.shape)
    c_tot = q + 2 * s
    if c_tot > 255:
        raise ValueError(f"gsf_merge_pallas supports q + 2s <= 255 "
                         f"(got {q} + 2*{s})")
    blk = _pick_block(m, gsf_merge_row_bytes(q, s, w))
    grid = (m // blk,)

    def spec(shape):
        return pl.BlockSpec((blk,) + shape,
                            lambda g: (g,) + (0,) * len(shape))

    kernel = functools.partial(_gsf_kernel, q_cap=q, s_cap=s,
                               levels=levels)
    out_shape = (
        jax.ShapeDtypeStruct((m, q), I32),
        jax.ShapeDtypeStruct((m, q), I32),
        jax.ShapeDtypeStruct((m, q), I32),
        jax.ShapeDtypeStruct((m, q, w), U32),
        jax.ShapeDtypeStruct((m, w), U32),
        jax.ShapeDtypeStruct((m, 1), I32),
    )
    o_f, o_l, o_i, o_s, o_got, o_kept = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec((q,)), spec((q,)), spec((q,)), spec((q,)),
                  spec((q, w)), spec((s,)), spec((s,)), spec((s,)),
                  spec((s,)), spec((s, w))],
        out_specs=[spec((q,)), spec((q,)), spec((q,)), spec((q, w)),
                   spec((w,)), spec((1,))],
        out_shape=out_shape,
        input_output_aliases={4: 3},            # q_sig updated in place
        interpret=interpret,
    )(q_from, q_lvl, q_indiv.astype(I32), ex_keep.astype(I32), q_sig,
      src, level, agg_ok.astype(I32), ind_ok.astype(I32), sig_all)
    return o_f, o_l, o_i != 0, o_s, o_got, o_kept[:, 0]
