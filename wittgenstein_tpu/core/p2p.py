"""P2P peer graphs + flood forwarding, fully vectorized.

Reference surface (SURVEY.md §2.1):
  - `P2PNetwork.setPeers` builds a random peer graph with either a minimum
    per-node degree or a target average degree (core/P2PNetwork.java:26-55);
    links are symmetric and deduplicated via an edge set (:63-113).
  - `P2PNode.peers` is an adjacency list (core/P2PNode.java:9-28).
  - `FloodMessage.action` forwards a newly received flood to all peers except
    the sender, in shuffled order, with `localDelay` before the first send and
    `delayBetweenPeers` between consecutive peers
    (core/messages/FloodMessage.java:47-54, P2PNetwork.sendPeers :127-132).

TPU-native design: the adjacency is a fixed-shape `[N, D]` int32 matrix
(-1 = empty slot) built in one shot from counter-based draws — construction is
deterministic per seed, jittable, and vmappable over seeds.  The reference's
sequential "top-up until everyone has >= c links" loop
(P2PNetwork.java:45-55) is inherently serial; we instead have every node draw
its quota at once and symmetrize, which preserves the invariants that matter
(min degree >= c for the minimum variant, expected degree ~= c for the average
variant, uniformly random partners) while being O(1) depth — a statistical
match, not a bit-for-bit one (SURVEY.md §7.4.3 sets that bar).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import prng

TAG_P2P = 0x50325030  # domain separation for peer-graph draws
TAG_SHUF = 0x50325346  # flood fan-out shuffle draws

_BIG = jnp.int32(0x7FFFFFFF)


def _scatter_adjacency(src, dst, keep, n, max_degree):
    """Turn a kept directed-edge list into a `[N, D]` adjacency + degree.

    Sorts edges by (src, dst), drops duplicates, ranks each kept edge within
    its source group (the same rank-in-group trick as the mailbox router,
    network.enqueue_unicast), and scatters `dst` into the source's next free
    slot.  Edges beyond `max_degree` are dropped and counted.
    """
    m = src.shape[0]
    src_k = jnp.where(keep, src, _BIG)
    dst_k = jnp.where(keep, dst, _BIG)
    o1 = jnp.argsort(dst_k, stable=True)
    order = o1[jnp.argsort(src_k[o1], stable=True)]
    src_s, dst_s = src_k[order], dst_k[order]

    dup = (src_s == jnp.roll(src_s, 1)) & (dst_s == jnp.roll(dst_s, 1))
    dup = dup.at[0].set(False)
    kept = (src_s != _BIG) & ~dup

    idx = jnp.arange(m, dtype=jnp.int32)
    # Rank among *kept* entries within each src group: cumulative kept count
    # minus the kept count at the group start.
    ckept = jnp.cumsum(kept.astype(jnp.int32))
    new_grp = (src_s != jnp.roll(src_s, 1)).at[0].set(True)
    grp_base = jax.lax.cummax(jnp.where(new_grp, ckept - kept, 0))
    rank = ckept - kept - grp_base

    ok = kept & (rank < max_degree)
    src_w = jnp.where(ok, src_s, n)                 # n is OOB -> dropped
    rank_w = jnp.where(ok, rank, max_degree)
    peers = jnp.full((n, max_degree), -1, jnp.int32)
    peers = peers.at[src_w, rank_w].set(dst_s, mode="drop")
    degree = jnp.zeros((n,), jnp.int32).at[src_w].add(
        ok.astype(jnp.int32), mode="drop")
    overflow = jnp.sum(kept & ~ok).astype(jnp.int32)
    return peers, degree, overflow


def build_peer_graph(seed, n: int, connection_count: int, minimum: bool = True,
                     max_degree: int | None = None):
    """Vectorized `P2PNetwork.setPeers` (core/P2PNetwork.java:26-55).

    minimum=True : every node draws `connection_count` uniform partners; the
                   symmetric closure gives min degree >= connection_count
                   (reference invariant) and mean ~= 2c (the reference's
                   shuffled top-up lands between c and 2c).
    minimum=False: n*c/2 uniform pairs (mean degree ~= c, the reference
                   invariant), then every node below min(3, c) draws up to 3
                   partners so nobody is isolated (:45-55).

    Returns (peers [N, D] int32 with -1 padding, degree [N] int32,
    overflow int32 scalar — symmetric-closure links dropped because a node's
    D slots were full; size D generously or assert overflow == 0).
    """
    if connection_count >= n:
        raise ValueError(
            f"wrong configuration: nodes={n}, "
            f"connection target={connection_count}")
    seed = prng.hash2(jnp.asarray(seed, jnp.int32), TAG_P2P)
    ids = jnp.arange(n, dtype=jnp.int32)

    def draw_partners(sub, count):
        # `count` *distinct* uniform partners per node: draw in [0, n-1) and
        # skip self, then repair within-row duplicates by redrawing them a
        # few rounds (collision probability decays ~(c^2/n)^rounds, so four
        # rounds make "fewer than c distinct partners" vanishingly rare —
        # preserving the reference's min-degree invariant, P2PNetwork:45-55).
        cols = []
        for j in range(count):
            p = prng.uniform_int(prng.hash2(seed, sub * 1000 + j), ids, n - 1)
            cols.append(p + (p >= ids))
        part = jnp.stack(cols, axis=1)                # [N, count]
        for r in range(1, 5):
            dup = jnp.zeros(part.shape, bool)
            for j in range(1, count):
                dup = dup.at[:, j].set(
                    jnp.any(part[:, :j] == part[:, j:j + 1], axis=1))
            redraw = prng.uniform_int(
                prng.hash2(seed, sub * 1000 + 500 + r),
                ids[:, None] * count + jnp.arange(count)[None, :], n - 1)
            redraw = redraw + (redraw >= ids[:, None])
            part = jnp.where(dup, redraw, part)
        return part

    if minimum:
        c = connection_count
        if max_degree is None:
            max_degree = max(4 * c, c + 16)
        part = draw_partners(1, c)                    # [N, c]
        a = jnp.repeat(ids, c)
        b = part.reshape(-1)
        src = jnp.concatenate([a, b])
        dst = jnp.concatenate([b, a])
        keep = jnp.ones_like(src, dtype=bool)
    else:
        c = connection_count
        if max_degree is None:
            max_degree = max(4 * c, c + 16)
        npairs = max(1, (n * c) // 2)
        pid = jnp.arange(npairs, dtype=jnp.int32)
        pa = prng.uniform_int(prng.hash2(seed, 7001), pid, n)
        pb = prng.uniform_int(prng.hash2(seed, 7002), pid, n)
        # Guaranteed floor: nodes whose pair-phase degree is below min(3, c)
        # draw 3 partners (the reference tops up below-minimum nodes only).
        deg0 = (jnp.zeros((n,), jnp.int32).at[pa].add(1, mode="drop")
                .at[pb].add(1, mode="drop"))
        lonely = deg0 < min(3, c)
        extra = draw_partners(2, min(3, max(1, c)))   # [N, e]
        e = extra.shape[1]
        xa = jnp.repeat(ids, e)
        xb = extra.reshape(-1)
        xkeep = jnp.repeat(lonely, e)
        src = jnp.concatenate([pa, pb, xa, xb])
        dst = jnp.concatenate([pb, pa, xb, xa])
        keep = jnp.concatenate([pa != pb, pa != pb, xkeep, xkeep])

    return _scatter_adjacency(src, dst, keep, n, max_degree)


def avg_peers(degree):
    """`P2PNetwork.avgPeers` (core/P2PNetwork.java:115-125)."""
    return jnp.sum(degree) // jnp.maximum(1, degree.shape[0])


def disconnect(peers, degree, node_mask):
    """Drop every link touching a masked node (`P2PNetwork.disconnect`,
    core/P2PNetwork.java:57-61): removes them as sources *and* from everyone
    else's peer lists (slots become -1; degree recomputed)."""
    dead_peer = jnp.where(peers >= 0, node_mask[jnp.maximum(peers, 0)], False)
    peers = jnp.where(dead_peer | node_mask[:, None], -1, peers)
    degree = jnp.sum(peers >= 0, axis=1).astype(jnp.int32)
    return peers, degree


def shuffled_order(seed, t, n: int, d: int):
    """Per-node pseudo-random slot permutation — the analogue of the
    `Collections.shuffle(dest, rd)` in sendPeers/action
    (P2PNetwork.java:127-132).  order[i, k] = the peer slot visited k-th in
    node i's shuffled order at time t (one argsort total)."""
    flat = jnp.arange(n * d, dtype=jnp.int32).reshape(n, d)
    pri = prng.uniform_u32(prng.hash3(seed, TAG_SHUF, t), flat)
    return jnp.argsort(pri, axis=1).astype(jnp.int32)


def flood_fanout(cfg, peers, forward, exclude_src, payload, seed, t,
                 local_delay=0, delay_between=0, size=1):
    """Outbox fields for `FloodMessage.action`-style forwarding.

    For every node with `forward[i]` set: send `payload[i]` to all its peers
    except `exclude_src[i]`, in a shuffled order, the k-th in that order
    delayed by `local_delay + k * delay_between` ms
    (core/messages/FloodMessage.java:47-54).

    Requires cfg.out_deg == peers.shape[1].  Returns (dest, payload, size,
    delay) arrays shaped for `Outbox`.
    """
    n, d = peers.shape
    assert cfg.out_deg == d, (cfg.out_deg, d)
    ok = forward[:, None] & (peers >= 0) & (peers != exclude_src[:, None])
    dest = jnp.where(ok, peers, -1)
    # Rank among *sent* slots only: count how many sent slots precede mine
    # in the shuffled order (excluded peers must not leave delay gaps).
    order = shuffled_order(seed, t, n, d)
    sent_sorted = jnp.take_along_axis(ok, order, axis=1)
    pos_sorted = jnp.cumsum(sent_sorted.astype(jnp.int32), axis=1) - 1
    pos = jnp.zeros((n, d), jnp.int32).at[
        jnp.arange(n)[:, None], order].set(pos_sorted)
    delay = local_delay + jnp.maximum(pos, 0) * delay_between
    out_payload = jnp.broadcast_to(payload[:, None, :],
                                   (n, d, payload.shape[-1]))
    out_size = jnp.full((n, d), size, jnp.int32)
    return dest, out_payload, out_size, delay.astype(jnp.int32)
