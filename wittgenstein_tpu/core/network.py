"""The TPU-native discrete-event engine.

This replaces the reference's single-threaded event loop (core/Network.java:
receiveUntil/nextMessage, :533-637) with a fixed-shape, jit-compiled
millisecond step driven by `lax.scan`:

  per ms t:   inbox  = mailbox slice [N, C] + broadcast recompute [N, B]
              state' = protocol.step(state, inbox, t)       # all N nodes at once
              mailbox.scatter(outbox arrivals)              # sort-based binning

Determinism comes for free: every random draw is a pure function of
(seed, t, ids) via `ops.prng`, and same-ms delivery order is fixed by the
stable sort in the scatter — the tensor analogue of the reference's
deterministic same-ms linked lists (Network.java:108-115).

Design notes vs the reference:
  * UNICAST arrivals beyond ``t + horizon - 1`` park in the spill buffer
    when ``cfg.spill_cap > 0`` (delivered exactly on time when the ring
    reaches them — the reference's rolling 60 s storage,
    Network.java:201-299, supports arbitrary horizons the same way) or are
    clamped into the ring and counted when ``spill_cap == 0``
    (`msg_discard_time` Network.java:36-40 is the sanctioned way to model
    bounded delivery windows).  Broadcast latencies are recomputed within
    the ring window and always clamp (counted in `clamped`) — a broadcast
    tail past the horizon needs a bigger ring, not spill.
  * Per-(node, ms) unicast deliveries beyond `inbox_cap` are counted in
    `NetState.dropped`; size the capacity for the protocol (tests assert 0).
  * Partition membership is evaluated at delivery time for broadcasts (the
    reference evaluates it at send time, Network.java:478); identical unless a
    partition changes while a message is in flight.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import prng
from .latency import full_latency, latency_floor_ms
from .protocol import FAR_FUTURE
from .state import EngineConfig, Inbox, NetState, Outbox


def _retire_broadcasts(cfg: EngineConfig, net: NetState, t) -> NetState:
    # A broadcast's last possible arrival is bc_time + horizon - 1.
    live = net.bc_active & ((t - net.bc_time) < cfg.horizon)
    return net.replace(bc_active=live)


def broadcast_arrivals(cfg: EngineConfig, model, net: NetState, nodes):
    """Per-(record, dest) broadcast arrival recompute — the one shared
    definition of the reference's stateless multicast-latency trick
    (Envelope.java:45-56, Network.java:493-503): latency is a pure function
    of (record seed, dest), never stored.  Returns ``(arrival [B, N],
    ok [B, N], clamped [B, N])`` where `ok` covers record-active, discard
    and partition checks (NOT the destination's down flag — delivery and
    introspection treat that differently) and `clamped` marks arrivals
    whose true latency outran the ring.
    """
    node_idx = jnp.arange(cfg.n, dtype=jnp.int32)
    delta = prng.uniform_delta(net.bc_seed[:, None], node_idx[None, :])
    lat = full_latency(model, nodes, net.bc_src[:, None], node_idx[None, :],
                       delta)
    # Discard is checked against the TRUE latency (Network.java:481 compares
    # nt before any storage), then the survivor is clamped into the ring.
    not_discarded = lat < cfg.msg_discard_time
    raw_lat = jnp.maximum(lat, 1)
    lat = jnp.clip(lat, 1, cfg.horizon - 2)
    arrival = net.bc_time[:, None] + 1 + lat
    ok = (net.bc_active[:, None] & not_discarded
          & (nodes.partition[net.bc_src][:, None] ==
             nodes.partition[None, :]))
    return arrival, ok, raw_lat != lat


def _bcast_inbox(cfg: EngineConfig, model, net: NetState, t):
    """Broadcast half of the time-t inbox: the per-(record, dest)
    arrival recompute of `broadcast_arrivals`, shaped for delivery.
    Returns ``(bc_data [N, B, F], bc_src [N, B], bc_size [N, B],
    bc_valid [N, B], n_clamped)``."""
    nodes = net.nodes
    n, b = cfg.n, cfg.bcast_slots
    arrival, bc_ok, clamped = broadcast_arrivals(cfg, model, net, nodes)
    bc_hit = bc_ok & (arrival == t) & (~nodes.down[None, :])     # [B, N]
    bc_valid = jnp.transpose(bc_hit)                             # [N, B]
    bc_data = jnp.broadcast_to(net.bc_payload[None, :, :],
                               (n, b, cfg.payload_words))
    bc_src = jnp.broadcast_to(net.bc_src[None, :], (n, b))
    bc_size = jnp.broadcast_to(net.bc_size[None, :], (n, b))
    # Broadcast deliveries whose true latency outran the ring (counted
    # once, at their clamped delivery ms).
    n_clamped = jnp.sum(bc_hit & clamped).astype(jnp.int32)
    return bc_data, bc_src, bc_size, bc_valid, n_clamped


def _unicast_inbox_window(cfg: EngineConfig, net: NetState, t, k: int):
    """Read K consecutive unicast inbox slices as ONE contiguous window.

    Requires ``t % k == 0`` with ``k`` dividing the horizon (so rows
    ``t % horizon .. t % horizon + k - 1`` never wrap) — the `step_kms`
    entry contract.  Returns ``(uc_data [K, N, C, F], uc_src [K, N, C],
    uc_size [K, N, C], uc_valid [K, N, C])`` with the same per-ms
    validity the per-ms slice computes (delivery-time down/partition
    checks are static across the window: `step_kms` requires a protocol
    that does not mutate liveness)."""
    nodes = net.nodes
    n, c, f = cfg.n, cfg.inbox_cap, cfg.payload_words
    p, ns = cfg.box_split, cfg.split_n
    h = t % cfg.horizon
    base = h * (ns * c)

    def rd(plane):
        return jax.lax.dynamic_slice(plane, (base,),
                                     (k * ns * c,)).reshape(k, ns, c)

    def rd_all(planes):
        if p == 1:
            return rd(planes[0])
        return jnp.concatenate([rd(pl) for pl in planes], axis=1)

    uc_data = jnp.stack(
        [rd_all(net.box_data[fi * p:(fi + 1) * p]) for fi in range(f)],
        axis=-1)                                    # [K, N, C, F]
    uc_src = rd_all(net.box_src)
    uc_size = rd_all(net.box_size)
    cnt = jax.lax.dynamic_slice(net.box_count, (h, 0), (k, n))   # [K, N]
    uc_valid = jnp.arange(c)[None, None, :] < cnt[:, :, None]
    deliver_ok = (~nodes.down[None, :, None]) & (
        nodes.partition[uc_src] == nodes.partition[None, :, None])
    return uc_data, uc_src, uc_size, uc_valid & deliver_ok


def build_inbox(cfg: EngineConfig, model, net: NetState, t):
    """Assemble the time-t inbox and bump receive counters.

    Mirrors the delivery path of Network.java:587-637: down destinations and
    cross-partition messages are silently dropped (:603-613), receive counters
    bumped per delivered message (:611-612).
    """
    nodes = net.nodes
    c, b, f = cfg.inbox_cap, cfg.bcast_slots, cfg.payload_words
    p, ns = cfg.box_split, cfg.split_n
    h = t % cfg.horizon

    # --- unicast slice: contiguous [Ns*C] window per sub-plane at
    # h*Ns*C, node-range sub-planes concatenated back to [N, C] ---
    base = h * (ns * c)

    def rd(plane):
        return jax.lax.dynamic_slice(plane, (base,),
                                     (ns * c,)).reshape(ns, c)

    def rd_all(planes):
        if p == 1:
            return rd(planes[0])
        return jnp.concatenate([rd(pl) for pl in planes], axis=0)

    uc_data = jnp.stack(
        [rd_all(net.box_data[fi * p:(fi + 1) * p]) for fi in range(f)],
        axis=-1)                                    # [N, C, F]
    uc_src = rd_all(net.box_src)
    uc_size = rd_all(net.box_size)
    uc_valid = jnp.arange(c)[None, :] < net.box_count[h][:, None]
    deliver_ok = (~nodes.down[:, None]) & (
        nodes.partition[uc_src] == nodes.partition[:, None])
    uc_valid = uc_valid & deliver_ok

    if b == 0:
        # Static no-broadcast path (protocols that never sendAll set
        # bcast_slots=0): no [B, N] latency recompute, and the inbox IS
        # the unicast slice — no concatenate materializing a copy.
        recv = jnp.sum(uc_valid, 1).astype(jnp.int32)
        rbytes = jnp.sum(jnp.where(uc_valid, uc_size, 0), 1).astype(
            jnp.int32)
        nodes = nodes.replace(msg_received=nodes.msg_received + recv,
                              bytes_received=nodes.bytes_received + rbytes)
        inbox = Inbox(data=uc_data, src=uc_src, valid=uc_valid)
        return inbox, nodes, jnp.asarray(0, jnp.int32)

    # --- broadcast recompute: which records arrive at exactly t? ---
    bc_data, bc_src, bc_size, bc_valid, n_clamped = _bcast_inbox(
        cfg, model, net, t)

    inbox = Inbox(
        data=jnp.concatenate([uc_data, bc_data], axis=1),
        src=jnp.concatenate([uc_src, bc_src], axis=1),
        valid=jnp.concatenate([uc_valid, bc_valid], axis=1),
    )

    recv = (jnp.sum(uc_valid, 1) + jnp.sum(bc_valid, 1)).astype(jnp.int32)
    rbytes = (jnp.sum(jnp.where(uc_valid, uc_size, 0), 1) +
              jnp.sum(jnp.where(bc_valid, bc_size, 0), 1)).astype(jnp.int32)
    nodes = nodes.replace(msg_received=nodes.msg_received + recv,
                          bytes_received=nodes.bytes_received + rbytes)
    return inbox, nodes, n_clamped


def _bin_into_ring(cfg: EngineConfig, net: NetState, t, src, dest, arrival,
                   payload, size, valid):
    """Scatter a batch of messages into the mailbox ring.

    A stable sort on (arrival, dest) bins messages into ring slots; rank
    within a (ms, dest) group + the current fill count gives each message
    its slot.  `dest` must already be clipped to [0, n); arrivals must lie
    within the ring: rel = arrival - t in [1, horizon-1] for the per-ms
    path, or [K, horizon + K - 2] for the fused `step_kms` path — rel >=
    horizon lands in one of the rows t % horizon .. t % horizon + K - 2,
    which is valid ONLY because step_kms clears all K consumed rows
    BEFORE binning (do not reorder).  Returns (net', n_dropped) —
    entries that found their (ms, dest) cell full.

    ``WTPU_PALLAS_ROUTE=1`` (or the serve plane's `route_kernel` knob)
    swaps the sort/scatter composition below for the fused Pallas
    routing megakernel (ops/pallas_route.py — bit-identical,
    tests/test_pallas_route.py; interpret mode on CPU).  The arrival
    contract above is exactly what makes the kernel's (row, dest)
    grouping coincide with the sort's (rel, dest) grouping: at most
    horizon-1 distinct rel values per batch, so rel % horizon is
    injective within it.
    """
    from ..ops.pallas_route import route_enabled
    if route_enabled():
        from ..ops.pallas_route import bin_into_ring_planes
        box_data, box_src, box_size, box_count, n_dropped = \
            bin_into_ring_planes(
                net.box_data, net.box_src, net.box_size, net.box_count,
                arrival % cfg.horizon, dest, src, size, payload, valid,
                horizon=cfg.horizon, cap=cfg.inbox_cap, n=cfg.n,
                split=cfg.box_split, payload_words=cfg.payload_words)
        return net.replace(box_data=box_data, box_src=box_src,
                           box_size=box_size, box_count=box_count), \
            n_dropped
    n, c = cfg.n, cfg.inbox_cap
    m = src.shape[0]
    rel = arrival - t
    # Two-pass stable radix sort on (rel, dest): avoids the int32 overflow a
    # fused `rel * n + dest` key would hit for n in the millions, yet still
    # yields one deterministic order with (rel, dest) groups contiguous.
    big = jnp.int32(0x7FFFFFFF)
    rel_k = jnp.where(valid, rel, big)
    dest_k = jnp.where(valid, dest, big)
    o1 = jnp.argsort(dest_k, stable=True)
    order = o1[jnp.argsort(rel_k[o1], stable=True)]
    rel_s, dest_s = rel_k[order], dest_k[order]
    idx = jnp.arange(m, dtype=jnp.int32)
    new_grp = ((rel_s != jnp.roll(rel_s, 1)) |
               (dest_s != jnp.roll(dest_s, 1))).at[0].set(True)
    rank = idx - jax.lax.cummax(jnp.where(new_grp, idx, 0))

    h_s = (arrival % cfg.horizon)[order]
    d_s = dest[order]
    ok_s = valid[order]
    slot = net.box_count[h_s, d_s] + rank
    ok_s = ok_s & (slot < c)

    # Flat 1-D scatters per node-range sub-plane (cell (h, d, slot) at
    # (h*Ns + d - j*Ns)*C + slot of sub-plane j = d // Ns); each
    # sub-plane's total size is the OOB sentinel for entries that belong
    # to another sub-plane or were dropped.
    p, ns = cfg.box_split, cfg.split_n
    f = cfg.payload_words
    payload_s = payload[order]
    src_s, size_s = src[order], size[order]
    box_data = list(net.box_data)
    box_src = list(net.box_src)
    box_size = list(net.box_size)
    sub_total = cfg.horizon * ns * c
    for j in range(p):
        dj = d_s - j * ns
        in_j = ok_s & (dj >= 0) & (dj < ns)
        flat_j = (h_s * ns + dj) * c + jnp.where(in_j, slot, 0)
        flat_jw = jnp.where(in_j, flat_j, sub_total)
        for fi in range(f):
            box_data[fi * p + j] = box_data[fi * p + j].at[flat_jw].set(
                payload_s[:, fi], mode="drop", unique_indices=True)
        box_src[j] = box_src[j].at[flat_jw].set(src_s, mode="drop",
                                                unique_indices=True)
        box_size[j] = box_size[j].at[flat_jw].set(size_s, mode="drop",
                                                  unique_indices=True)
    box_count = net.box_count.at[h_s, d_s].add(ok_s.astype(jnp.int32),
                                               mode="drop")
    n_dropped = jnp.sum(valid[order] & ~ok_s).astype(jnp.int32)
    return net.replace(box_data=tuple(box_data), box_src=tuple(box_src),
                       box_size=tuple(box_size), box_count=box_count), \
        n_dropped


def _alloc_free_slots(free, want):
    """Deterministic free-slot allocation for a fixed table: the i-th
    requester (in index order) takes the i-th free slot.  Returns
    ``(slot_w, ok)`` where slot_w == len(free) (an OOB sentinel for
    mode="drop" scatters) for requesters that found the table full."""
    cap = free.shape[0]
    rank = jnp.cumsum(want.astype(jnp.int32)) - 1
    n_free = jnp.sum(free).astype(jnp.int32)
    slot_order = jnp.argsort(~free, stable=True)        # free slots first
    ok = want & (rank < n_free)
    slot = slot_order[jnp.clip(rank, 0, cap - 1)]
    return jnp.where(ok, slot, cap), ok


def _park_in_spill(cfg: EngineConfig, net: NetState, src, dest, arrival,
                   payload, size, far):
    """Park far-future sends in the spill buffer (free slot = arrival < 0);
    overflow is counted in `sp_dropped`."""
    slot_w, ok = _alloc_free_slots(net.sp_arrival < 0, far)
    return net.replace(
        sp_arrival=net.sp_arrival.at[slot_w].set(arrival, mode="drop"),
        sp_src=net.sp_src.at[slot_w].set(src, mode="drop"),
        sp_dest=net.sp_dest.at[slot_w].set(dest, mode="drop"),
        sp_size=net.sp_size.at[slot_w].set(size, mode="drop"),
        sp_payload=net.sp_payload.at[slot_w].set(payload, mode="drop"),
        sp_dropped=net.sp_dropped + jnp.sum(far & ~ok).astype(jnp.int32))


def _drain_spill(cfg: EngineConfig, net: NetState, t):
    """Re-inject parked messages whose arrival is within ring reach.

    Entries parked by `enqueue_unicast` cross `arrival - t == horizon - 2`
    exactly once, but a restored/hand-built NetState (or a future horizon
    change) can hold entries already nearer than that — an exact-equality
    drain would leak them (never delivered, slot never freed).  Draining on
    <= with arrival clamped to t+1 (rel >= 1 for `_bin_into_ring`) is
    equivalent for the enqueue path and robust for any other state."""
    sel = (net.sp_arrival >= 0) & (net.sp_arrival - t <= cfg.horizon - 2)
    net2, n_drop = _bin_into_ring(cfg, net, t, net.sp_src, net.sp_dest,
                                  jnp.maximum(net.sp_arrival, t + 1),
                                  net.sp_payload, net.sp_size, sel)
    return net2.replace(
        sp_arrival=jnp.where(sel, -1, net2.sp_arrival),
        dropped=net2.dropped + n_drop)


def _route_unicast(cfg: EngineConfig, model, net: NetState, out: Outbox, t):
    """Shared unicast routing: sender counters, latency draws, validity.

    Returns ``(net', batch, abs_arrival_raw)`` where `batch` is the
    binnable tuple ``(src, dest_c, arrival, payload, size, valid, far)``
    — `arrival` already clamped into the ring relative to t, and
    `abs_arrival_raw` the unclamped absolute arrival (spill parking).

    The outbox may be NARROWER than cfg.out_deg (a contiguous slot window
    starting at out.slot0 — see Outbox.slot0): latency draws are keyed on
    the stable full-width slot id, so a narrow outbox whose live columns
    carry the same slot ids produces bit-identical arrivals while the
    binning sort runs over n * K_narrow entries instead of n * out_deg.
    """
    nodes = net.nodes
    n, k = cfg.n, out.dest.shape[1]
    m = n * k
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    dest = out.dest.reshape(m)
    payload = out.payload.reshape(m, cfg.payload_words)
    size = out.size.reshape(m)
    delay = out.delay.reshape(m)

    want = (dest >= 0) & (~nodes.down[src])
    dest_c = jnp.clip(dest, 0, n - 1)

    # Attempted sends bump the sender's counters regardless of whether the
    # destination is reachable (Network.java:475-477 increments before the
    # partition/down checks).  src is repeat(arange(n), k), so the
    # scatter-add is just a per-row sum.
    sent = nodes.msg_sent + jnp.sum(
        want.reshape(n, k), axis=1, dtype=jnp.int32)
    sbytes = nodes.bytes_sent + jnp.sum(
        jnp.where(want, size, 0).reshape(n, k), axis=1, dtype=jnp.int32)
    nodes = nodes.replace(msg_sent=sent, bytes_sent=sbytes)
    net = net.replace(nodes=nodes)

    seed_t = prng.hash3(net.seed, prng.TAG_LATENCY, t)
    # Stable full-width slot id (== arange(m) for a full-width outbox).
    midx = src * cfg.out_deg + out.slot0 + \
        jnp.arange(m, dtype=jnp.int32) % k
    delta = prng.uniform_delta(seed_t, midx)
    lat = full_latency(model, nodes, src, dest_c, delta)
    not_discarded = lat < cfg.msg_discard_time
    # `delay` is sender-chosen scheduling (send-at-future-time,
    # sendArriveAt Network.java:384-390).  Arrivals past the ring either
    # park in the spill buffer (spill_cap > 0 — delivered exactly on time
    # when the ring reaches them) or are clamped to the ring edge and
    # counted in `net.clamped` (tests/harness assert clamped == 0).
    raw_total = jnp.clip(delay, 0, None) + jnp.maximum(lat, 1)
    total = jnp.clip(raw_total, 1, cfg.horizon - 2)
    valid = want & not_discarded & (~nodes.down[dest_c]) & (
        nodes.partition[src] == nodes.partition[dest_c])
    far = valid & (raw_total > cfg.horizon - 2)
    batch = (src, dest_c, t + 1 + total, payload, size, valid, far)
    return net, batch, t + 1 + raw_total


def enqueue_unicast(cfg: EngineConfig, model, net: NetState, out: Outbox, t):
    """Route the step's unicast sends into the mailbox ring.

    The reference creates one MessageArrival per destination with a fresh
    latency draw, sorts them, and links them into per-ms buckets
    (Network.java:449-487).  Here: one latency draw per message, then the
    sort-based binning of `_bin_into_ring`.
    """
    net, batch, arrival_raw = _route_unicast(cfg, model, net, out, t)
    src, dest_c, arrival, payload, size, valid, far = batch
    if cfg.spill_cap > 0:
        net = _park_in_spill(cfg, net, src, dest_c, arrival_raw,
                             payload, size, far)
        ring_valid = valid & ~far
        n_clamped = jnp.asarray(0, jnp.int32)
    else:
        ring_valid = valid
        n_clamped = jnp.sum(far).astype(jnp.int32)

    net, n_dropped = _bin_into_ring(cfg, net, t, src, dest_c, arrival,
                                    payload, size, ring_valid)
    return net.replace(dropped=net.dropped + n_dropped,
                       clamped=net.clamped + n_clamped)


def enqueue_broadcast(cfg: EngineConfig, net: NetState, out: Outbox, t):
    """Allocate broadcast-table slots for this step's sendAll requests."""
    nodes = net.nodes
    n = cfg.n
    req = out.bcast & (~nodes.down)

    # sendAll counts one attempted send per destination (all N nodes,
    # including self — Network.java:341-347 sends to allNodes).
    sent = nodes.msg_sent + jnp.where(req, n, 0).astype(jnp.int32)
    sbytes = nodes.bytes_sent + jnp.where(req, out.bcast_size * n, 0)
    nodes = nodes.replace(msg_sent=sent, bytes_sent=sbytes)

    slot_w, ok = _alloc_free_slots(~net.bc_active, req)

    node_idx = jnp.arange(n, dtype=jnp.int32)
    bseed = prng.hash3(prng.hash2(net.seed, prng.TAG_BCAST),
                       jnp.full((n,), t, jnp.int32), node_idx)
    return net.replace(
        nodes=nodes,
        bc_active=net.bc_active.at[slot_w].set(True, mode="drop"),
        bc_src=net.bc_src.at[slot_w].set(node_idx, mode="drop"),
        bc_time=net.bc_time.at[slot_w].set(t, mode="drop"),
        bc_payload=net.bc_payload.at[slot_w].set(out.bcast_payload,
                                                 mode="drop"),
        bc_size=net.bc_size.at[slot_w].set(out.bcast_size, mode="drop"),
        bc_seed=net.bc_seed.at[slot_w].set(bseed.astype(jnp.int32),
                                           mode="drop"),
        bc_dropped=net.bc_dropped + jnp.sum(req & ~ok).astype(jnp.int32),
    )


def step_ms(protocol, net: NetState, pstate, hints=None, tap=None):
    """Advance the simulation by exactly one millisecond (pure, jittable).

    `hints` is an optional static phase-hint dict (see `scan_chunk`): when
    the protocol's task schedule is statically known, it tells the step
    which masked sub-computations cannot fire this ms so they are never
    traced at all — the tensor analogue of the reference's empty-ms
    skip in nextMessage (Network.java:533-570), where a ms with no events
    costs nothing.

    `tap` is the trace plane's observation hook (wittgenstein_tpu/obs/
    trace.py): a callable invoked twice per simulated ms during TRACING —
    ``tap(t, net, None)`` at ms entry (before retire/drain/delivery, so
    the tap can read the ms's ring row, spill drain set and pre-retire
    broadcast table as pure functions of the carried state) and
    ``tap(t, net, out)`` right after the protocol step (the outbox is the
    only per-message send information that never reaches the state).  The
    default ``tap=None`` traces ZERO extra operations — the uninstrumented
    program is bit-for-bit the historical one (the `trace_zero_cost` /
    `metrics_zero_cost` lints pin its carry width and op count).
    """
    cfg, model = protocol.cfg, protocol.latency
    t = net.time
    # Chaos-plane hook (wittgenstein_tpu/chaos): churn/partition state
    # is a stateless function of t, written at every ms entry BEFORE
    # anything observes or delivers — the tap then sees exactly the
    # liveness the engine runs under.  Protocols without the hook trace
    # zero extra operations (the zero-cost lints stay pinned).
    af = getattr(protocol, "apply_faults", None)
    if af is not None:
        net = af(net, t)
    if tap is not None:
        tap(t, net, None)
    if cfg.bcast_slots > 0:
        net = _retire_broadcasts(cfg, net, t)
    if cfg.spill_cap > 0:
        net = _drain_spill(cfg, net, t)
    inbox, nodes, bc_clamped = build_inbox(cfg, model, net, t)
    net = net.replace(nodes=nodes, clamped=net.clamped + bc_clamped)

    key = jax.random.fold_in(jax.random.PRNGKey(net.seed), t)
    if hints is None:
        pstate, nodes, out = protocol.step(pstate, net.nodes, inbox, t, key)
    else:
        pstate, nodes, out = protocol.step(pstate, net.nodes, inbox, t, key,
                                           hints=hints)
    net = net.replace(nodes=nodes)
    if tap is not None:
        tap(t, net, out)

    # Clear the consumed slot, then route new sends (their arrivals are
    # >= t+2, so they can never land in the slot just cleared).
    net = net.replace(box_count=net.box_count.at[t % cfg.horizon].set(0))
    net = enqueue_unicast(cfg, model, net, out, t)
    if cfg.bcast_slots > 0:
        net = enqueue_broadcast(cfg, net, out, t)
    return net.replace(time=t + 1), pstate


def step_kms(protocol, net: NetState, pstate, k: int, hints_k=None,
             tap=None):
    """Advance K milliseconds in one fused engine pass — the superstep.

    Bit-identical to K `step_ms` calls (tests/test_superstep.py) whenever
    the latency model provably never delivers a unicast in fewer than
    ``F = latency_floor_ms()`` milliseconds and ``K <= F + 1`` (the
    classic lookahead/conservative-window argument from parallel DES): a
    unicast sent at window ms t+i arrives no earlier than t+i+1+F >=
    t+K, so nothing produced inside the window can be consumed inside
    the window.  Self-sends bypass the model (full_latency pins
    src == dst to 1 ms), so a floor above 1 is only usable for protocols
    that declare ``may_self_send = False``.  That licenses:

      * all K unicast inbox slices read up-front as ONE contiguous
        K-row window (`_unicast_inbox_window`);
      * ONE sort-based binning over all K outboxes (keyed on
        (rel, dest) with rel relative to t, spanning [K, horizon+K-2];
        batch order inside a (ms, dest) cell equals the sequential
        order the per-ms path produces, so slots are identical);
      * all K consumed ring slots cleared with one K-row update.

    This cuts the engine's per-ms fixed cost (sorts, scatter passes,
    slices, clears) — the op-latency-bound regime's dominant term
    (BENCH_NOTES.md r3) — by ~K/2x over the historical 2-ms fusion.

    Broadcasts are NOT window-fused: their table evolves and their
    arrivals are recomputed per-ms-exactly inside the window
    (retire(t+i) -> deliver(t+i) -> step -> enqueue(t+i)), because a
    sendAll reaches its own sender in 1 ms and would otherwise land
    inside any K > 2 window.  The broadcast recompute is elementwise
    [B, N] work — none of the sort/scatter fixed cost being amortized —
    so per-ms exactness there costs nothing extra.

    Requirements (enforced by `check_chunk_config`): spill_cap == 0,
    K divides the horizon, entry time ≡ 0 (mod K), K <= floor + 1 via
    `unicast_floor_ms`, and a protocol that does not mutate liveness.

    `tap` is the trace plane's observation hook (see `step_ms`): it
    fires per SIMULATED ms inside the window — entry tap before each
    ms's broadcast retire, post tap right after its protocol step — so
    every recorded event carries its exact origin ms, never the window
    start (K-vs-1 trace equality pinned in tests/test_trace.py).
    """
    if hints_k is not None and len(hints_k) != k:
        raise ValueError(f"hints_k must have {k} entries, got "
                         f"{len(hints_k)}")
    if k == 1:
        return step_ms(protocol, net, pstate,
                       hints=None if hints_k is None else hints_k[0],
                       tap=tap)
    cfg, model = protocol.cfg, protocol.latency
    if cfg.spill_cap > 0:
        raise ValueError("step_kms requires spill_cap == 0 (spill drain "
                         "is inherently per-ms)")
    t = net.time
    # Chaos-plane hook: ONE stateless application per window.  Sound
    # because `check_chunk_config` requires every churn/partition
    # transition to be K-aligned, so the fault state is constant across
    # the window — each in-window ms (inbox validity, routing validity,
    # taps) sees exactly what the per-ms engine would.
    af = getattr(protocol, "apply_faults", None)
    if af is not None:
        net = af(net, t)
    # Entry tap for the window's FIRST ms: before retire, matching the
    # per-ms path's observation point.  Later ms tap inside the loop —
    # their ring rows are untouched until the window's deferred clear,
    # and in-window sends arrive >= t+K (the window soundness proof),
    # so each per-ms entry observation reads exactly the state the
    # per-ms engine would show it (tests/test_trace.py pins the K-vs-1
    # trace equality).
    if tap is not None:
        tap(t, net, None)
    if cfg.bcast_slots > 0:
        net = _retire_broadcasts(cfg, net, t)

    # All K unicast slices + their receive counters up-front (counters
    # are write-only to the protocol step, so the early bump is
    # unobservable — the step_2ms precedent).
    uc_data, uc_src, uc_size, uc_valid = _unicast_inbox_window(
        cfg, net, t, k)
    recv = jnp.sum(uc_valid, axis=(0, 2)).astype(jnp.int32)
    rbytes = jnp.sum(jnp.where(uc_valid, uc_size, 0),
                     axis=(0, 2)).astype(jnp.int32)
    net = net.replace(nodes=net.nodes.replace(
        msg_received=net.nodes.msg_received + recv,
        bytes_received=net.nodes.bytes_received + rbytes))

    outs = []
    for i in range(k):
        ti = t + i if i else t      # no dead `t + 0` eqn in the trace
        if i > 0 and tap is not None:
            tap(ti, net, None)
        if i > 0 and cfg.bcast_slots > 0:
            net = _retire_broadcasts(cfg, net, ti)
        if cfg.bcast_slots > 0:
            bc_data, bc_src, bc_size, bc_valid, n_cl = _bcast_inbox(
                cfg, model, net, ti)
            recv_b = jnp.sum(bc_valid, 1).astype(jnp.int32)
            rb_b = jnp.sum(jnp.where(bc_valid, bc_size, 0),
                           1).astype(jnp.int32)
            net = net.replace(
                nodes=net.nodes.replace(
                    msg_received=net.nodes.msg_received + recv_b,
                    bytes_received=net.nodes.bytes_received + rb_b),
                clamped=net.clamped + n_cl)
            inbox = Inbox(
                data=jnp.concatenate([uc_data[i], bc_data], axis=1),
                src=jnp.concatenate([uc_src[i], bc_src], axis=1),
                valid=jnp.concatenate([uc_valid[i], bc_valid], axis=1))
        else:
            inbox = Inbox(data=uc_data[i], src=uc_src[i],
                          valid=uc_valid[i])
        key = jax.random.fold_in(jax.random.PRNGKey(net.seed), ti)
        h_i = None if hints_k is None else hints_k[i]
        if h_i is None:
            pstate, nodes, out = protocol.step(pstate, net.nodes, inbox,
                                               ti, key)
        else:
            pstate, nodes, out = protocol.step(pstate, net.nodes, inbox,
                                               ti, key, hints=h_i)
        net = net.replace(nodes=nodes)
        outs.append(out)
        if tap is not None:
            tap(ti, net, out)
        if cfg.bcast_slots > 0:
            net = enqueue_broadcast(cfg, net, out, ti)

    # Clear all K consumed slots in one K-row window (h ≡ 0 mod K and
    # K | horizon: no wrap).
    h = t % cfg.horizon
    net = net.replace(box_count=jax.lax.dynamic_update_slice(
        net.box_count, jnp.zeros((k, cfg.n), jnp.int32), (h, 0)))

    # Route every outbox (latency draws keyed on each step's own ms),
    # then bin them together: one sort + one scatter pass for K ms.
    batches = []
    for i, out in enumerate(outs):
        net, b, _ = _route_unicast(cfg, model, net, out,
                                   t + i if i else t)
        batches.append(b)
    terms = [jnp.sum(b[6]) for b in batches]
    n_clamped = terms[0]
    for tm in terms[1:]:
        n_clamped = n_clamped + tm
    n_clamped = n_clamped.astype(jnp.int32)
    src = jnp.concatenate([b[0] for b in batches])
    dest = jnp.concatenate([b[1] for b in batches])
    arrival = jnp.concatenate([b[2] for b in batches])
    payload = jnp.concatenate([b[3] for b in batches])
    size = jnp.concatenate([b[4] for b in batches])
    valid = jnp.concatenate([b[5] for b in batches])
    net, n_dropped = _bin_into_ring(cfg, net, t, src, dest, arrival,
                                    payload, size, valid)
    net = net.replace(dropped=net.dropped + n_dropped,
                      clamped=net.clamped + n_clamped)
    return net.replace(time=t + k), pstate


def step_2ms(protocol, net: NetState, pstate, hints2=(None, None)):
    """Advance TWO milliseconds in one fused engine pass — the K == 2
    superstep (`step_kms`), kept as a named entry point because K == 2
    is the universally-valid fusion: the engine's minimum latency of
    1 ms is itself the floor (a send at t arrives no earlier than t+2),
    so no latency-model floor and no self-send declaration is needed.
    """
    return step_kms(protocol, net, pstate, 2, hints_k=list(hints2))


def split_spec(example, threshold=1 << 20):
    """(treedef, big_idx) for `split_donate_jit`: which leaves of the
    example state pytree are 'big' (>= threshold bytes) and get donated.
    The ONE place the predicate lives — Runner, bench.py and
    tools/cardinal_1m.py all derive their split through it.  Works on
    concrete arrays and on `jax.eval_shape` results alike."""
    import numpy as np
    leaves, treedef = jax.tree.flatten(example)
    big_idx = frozenset(
        i for i, x in enumerate(leaves)
        if int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
        >= threshold)
    return treedef, big_idx


def split_donate_jit(fn, treedef, big_idx):
    """Jit `fn(state_pytree) -> state_pytree` donating ONLY the large
    leaves: the axon TPU plugin fails (INVALID_ARGUMENT, poisoning the
    process) when the FULL simulator pytree is donated, while donating
    the >=1MB leaves alone halves peak memory for exactly the buffers
    that matter (SCALE.md).  `treedef`/`big_idx` come from
    `jax.tree.flatten` of an example state; returns ``call(*state)``.
    The single shared implementation of the leaf-interleaving trick —
    used by `Runner(donate="big")` and tools/cardinal_1m.py."""
    def split_run(big, small):
        bi, si = iter(big), iter(small)
        leaves = [next(bi) if i in big_idx else next(si)
                  for i in range(len(big) + len(small))]
        return fn(*jax.tree.unflatten(treedef, leaves))

    jitted = jax.jit(split_run, donate_argnums=(0,))

    def call(*state):
        leaves = jax.tree.leaves(state)
        big = tuple(x for i, x in enumerate(leaves) if i in big_idx)
        small = tuple(x for i, x in enumerate(leaves) if i not in big_idx)
        return jitted(big, small)

    return call


def unicast_floor_ms(protocol) -> int:
    """The provable lower bound on any of this protocol's unicast
    delivery latencies — the term that licenses a K-ms superstep window
    (K <= floor + 1, `step_kms`).

    `full_latency` pins src == dst sends to 1 ms REGARDLESS of the
    latency model, so the model's `latency_floor_ms` only applies to
    protocols that declare ``may_self_send = False`` (an audited promise
    that step() never emits a unicast with dest == src).  The default —
    no declaration — is the conservative 1: every protocol then still
    gets the universally-valid K == 2 fusion, never an unsound K."""
    if getattr(protocol, "may_self_send", True):
        return 1
    return latency_floor_ms(protocol.latency)


def superstep_ok(protocol, superstep: int = 2) -> bool:
    """True iff `step_kms` with this K is valid for this protocol (the
    chunk length and entry time must additionally be K-aligned —
    per-call properties the caller checks).  The single shared
    eligibility predicate: scan_chunk raises on violations,
    Runner/harness demote to the largest valid K (`pick_superstep`)."""
    cfg = protocol.cfg
    sched = getattr(protocol, "chaos_schedule", None)
    return (cfg.spill_cap == 0
            and superstep >= 1
            and cfg.horizon % superstep == 0
            and superstep < cfg.horizon
            and superstep <= unicast_floor_ms(protocol) + 1
            and not getattr(protocol, "mutates_liveness", False)
            and (sched is None or sched.superstep_aligned(superstep)))


def fast_forward_ok(protocol) -> bool:
    """True iff the quiet-window fast-forward path is worth taking for
    this protocol: spill-free (the spill drain is inherently per-ms,
    same constraint as `superstep_ok`) and the protocol implements the
    `next_action_time` oracle half (core/protocol.py).  Without the
    method `fast_forward_chunk` is still SOUND — the engine then treats
    every ms as active — but it never jumps, so callers gate on this."""
    return (protocol.cfg.spill_cap == 0 and
            getattr(protocol, "next_action_time", None) is not None)


def check_chunk_config(protocol, ms, t0_mod=None, superstep=1,
                       fast_forward=False):
    """The shared eligibility gate for the engine chunk variants — plain
    scan, fused superstep-K, phase-specialized, fast-forward.
    `scan_chunk` and the fast-forward builders (including the batched
    ones) route through it so each shared constraint and its remedy are
    stated in one place; the batched engine layers its own narrower
    preconditions (broadcast-free) on top.  The gate RAISES — it never
    silently changes results; drivers that want automatic demotion pick
    through `pick_superstep` before building.

    One obligation is structurally out of the gate's reach: the chunk
    builder never sees the ABSOLUTE entry time, so with superstep K it
    can verify K-alignment only as far as `t0_mod` (a residue mod the
    schedule lcm) pins it — completely when K | lcm, only mod
    gcd(K, lcm) otherwise, and not at all without phase specialization.
    Entering a superstep-K chunk at a time that is not a multiple of K
    is a CONTRACT VIOLATION the compiled window cannot detect (the
    K-row ring reads/clears land on the wrong rows); callers that know
    t0 must route through `pick_superstep(t0=...)`, which checks the
    absolute alignment (all in-tree drivers do)."""
    cfg = protocol.cfg
    if not isinstance(superstep, int) or superstep < 1:
        raise ValueError(f"superstep must be a positive int, got "
                         f"{superstep!r}")
    if fast_forward:
        if t0_mod is not None:
            raise ValueError(
                "fast_forward is incompatible with phase-specialized "
                "scans (t0_mod): phase hints statically specialize each "
                "ms of an unrolled schedule period, while fast-forward "
                "jumps the clock dynamically — the hint<->time pairing "
                "cannot survive a data-dependent jump. Drop t0_mod (the "
                "oracle already skips the hint-masked quiet ms, "
                "including data-dependent ones hints cannot see)")
        if cfg.spill_cap > 0:
            raise ValueError(
                f"fast_forward requires spill_cap == 0 (got "
                f"{cfg.spill_cap}): the spill drain re-examines the "
                "buffer every ms, so a skipped window could miss a "
                "re-injection. Use a horizon that covers the latency "
                "tail instead of spill, or run without fast_forward")
    if superstep >= 2:
        k = superstep
        even = "an even" if k == 2 else f"a multiple-of-{k}"
        if cfg.spill_cap > 0:
            raise ValueError(
                f"superstep={k} needs spill_cap == 0 (got "
                f"{cfg.spill_cap}): the spill drain is inherently "
                "per-ms. Fix: size the horizon for the latency tail "
                "instead of spill, or fall back to superstep=1")
        if getattr(protocol, "mutates_liveness", False):
            raise ValueError(
                f"superstep={k} needs a protocol whose step() does not "
                "mutate node liveness (every inbox validity check in the "
                "window is evaluated against window-entry down/partition "
                "state). Fix: superstep=1 for this protocol")
        if cfg.horizon % k or k >= cfg.horizon:
            raise ValueError(
                f"superstep={k} needs K to divide the horizon with room "
                f"to spare (horizon {cfg.horizon}): the K consumed ring "
                "rows are read and cleared as one contiguous window. "
                f"Fix: pad the horizon to a multiple of {k} (at least "
                f"{2 * k}), or lower K")
        sched = getattr(protocol, "chaos_schedule", None)
        if sched is not None and not sched.superstep_aligned(k):
            bad = [t for t in sched.transition_times() if t % k]
            raise ValueError(
                f"superstep={k} needs every chaos churn/partition "
                f"transition on a K-ms window boundary (misaligned: "
                f"{bad[:8]}): liveness/partition state is applied at "
                "window entry, so a mid-window transition would be "
                "visible to the per-ms engine but not the fused window. "
                f"Fix: align the FaultSchedule times to multiples of "
                f"{k}, pick a superstep dividing "
                f"gcd={sched.align_gcd() or 1} of the transition times, "
                "or fall back to superstep=1")
        floor = unicast_floor_ms(protocol)
        if k > floor + 1:
            self_send = getattr(protocol, "may_self_send", True)
            why = (
                "the protocol has not declared may_self_send = False, "
                "and a self-addressed unicast always arrives in exactly "
                "1 ms (full_latency pins src == dst), so only the "
                "universal K = 2 window is provable"
                if self_send else
                f"{protocol.latency!r} proves latency_floor_ms() = "
                f"{floor}, and a unicast sent at the window's first ms "
                f"can arrive {floor + 1} ms later — inside any window "
                f"longer than {floor + 1}")
            raise ValueError(
                f"superstep={k} exceeds the provable quiet window: {why}."
                f" Fix: use superstep <= {floor + 1}, switch to a latency"
                " model with a floor >= K-1 ms (e.g. NetworkFixedLatency,"
                " EthScanNetworkLatency), or — if step() provably never "
                "emits a unicast with dest == src — declare "
                "may_self_send = False on the protocol")
        if ms % k:
            raise ValueError(
                f"superstep={k} needs {even} chunk (got {ms}): the scan "
                f"advances in fused {k}-ms windows. Fix: make the chunk "
                f"length a multiple of {k}, or fall back to a smaller "
                "superstep for this chunk")
        if t0_mod is not None:
            # `t0_mod` is a residue mod the schedule lcm, so it pins the
            # absolute entry time only mod gcd(K, lcm) — that is the
            # provable part.  When K | lcm the check is complete
            # (t0 % K == t0_mod % K); otherwise K-alignment of the
            # ABSOLUTE entry time cannot be decided from t0_mod at all
            # and remains the caller's contract (`pick_superstep` sees
            # the real t0 and verifies it for the in-tree drivers; a
            # misaligned entry would make the K-row ring window
            # read/clear the wrong rows with no runtime error).
            import math
            sched = getattr(protocol, "schedule_lcm", None)
            g = math.gcd(k, sched) if sched else k
            if t0_mod % g:
                raise ValueError(
                    f"superstep={k} needs {even} entry time "
                    f"(t0_mod={t0_mod} is not 0 mod "
                    f"gcd(K, schedule_lcm)={g}, so NO absolute entry "
                    f"time can satisfy both time % lcm == {t0_mod} and "
                    f"the K-aligned window contract): the window's ring "
                    "rows are read as one K-aligned block. Fix: enter "
                    "on a K-aligned chunk boundary (in-tree drivers "
                    f"start at time 0 and use multiple-of-{k} chunks; "
                    "burn one unaligned superstep=1 chunk first to "
                    "realign), or keep superstep=1 for this chunk. "
                    "(allow_unaligned only relaxes the schedule-lcm "
                    "length check, not entry alignment — it cannot fix "
                    "this one.)")


#: Default upper bound for auto-picked superstep windows: past ~32 the
#: amortized fixed cost is already < 1/32 of its per-ms value while the
#: unrolled window body keeps growing compile time linearly.
AUTO_SUPERSTEP_MAX = 32


def pick_superstep(protocol, ms, t0=None, max_k: int = AUTO_SUPERSTEP_MAX,
                   also_divides=None, lcm=None) -> int:
    """The largest K for which `step_kms` is provably exact for chunks
    of `ms` entered at absolute time `t0` (and every later boundary
    ``t0 + j*ms`` — `ms % K == 0` keeps the alignment invariant across
    chunk reuse).  ``t0=None`` (entry time unknown, e.g. a traced
    value) conservatively returns 1.  `also_divides` adds a caller
    divisibility constraint (the obs interval: a K window must never
    straddle a `stat_each_ms` row); `lcm` adds the phase-specialized
    scan's constraints (chunk a multiple of the K-adjusted schedule
    lcm, K-aligned entry phase).  Never raises — this is the demotion
    half of the gate; `check_chunk_config` is the raising half."""
    import math

    ms = int(ms)
    best = 1
    for k in range(2, min(int(max_k), ms) + 1):
        if ms % k or (t0 is None or int(t0) % k):
            continue
        if also_divides is not None and also_divides % k:
            continue
        if lcm:
            # Only the chunk length constrains the phase-specialized
            # scan: its hint block spans lcm_k and k | lcm_k, so the
            # `t0 % k == 0` check above already K-aligns every window
            # start regardless of the entry's schedule residue (a
            # residue-based re-check here would demote K=8 for e.g.
            # t0=24, lcm=20 — a perfectly valid aligned entry).
            lcm_k = lcm * k // math.gcd(lcm, k)
            if ms % lcm_k:
                continue
        if superstep_ok(protocol, k):
            best = k
    return best


def next_work(protocol, net: NetState, pstate, t):
    """The next-event oracle: the earliest absolute ms >= t that can
    contain work, computed entirely on-device.  Min over

      (a) the next nonempty mailbox ring row — `box_count` is indexed by
          absolute-time-mod-horizon, and with ``spill_cap == 0`` every
          in-flight unicast lives in the ring, so a row with a nonzero
          count IS a pending delivery at ``t + ((row - t) % horizon)``;
      (b) the earliest live broadcast arrival >= t — recomputed exactly
          per (record, dest), the same stateless-latency trick as
          delivery (`broadcast_arrivals`); conservative only in keeping
          arrivals to down/irrelevant destinations (an under-jump, never
          an over-jump);
      (c) the protocol's `next_action_time(pstate, nodes, t)` timers —
          protocols without the method declare every ms active.

    Soundness contract (tests/test_fast_forward.py): every ms in
    ``[t, next_work)`` is bit-identical to a no-op step — empty inbox,
    no timer, `protocol.step` is the identity and emits nothing — so
    `fast_forward_chunk` may jump straight to the returned time.
    """
    cfg, model = protocol.cfg, protocol.latency
    far = jnp.int32(FAR_FUTURE)
    rows = jnp.arange(cfg.horizon, dtype=jnp.int32)
    row_any = jnp.any(net.box_count > 0, axis=-1)            # [H]
    nxt = jnp.min(jnp.where(row_any, t + (rows - t) % cfg.horizon, far))
    if cfg.bcast_slots > 0:
        # NOT redundant with the recompute build_inbox did this ms: the
        # oracle runs on the POST-step table — the step may have
        # enqueued new broadcasts or retired old ones, and reusing the
        # pre-step arrivals could miss a new record's arrival and
        # over-jump (the one failure mode the contract forbids).
        arrival, ok, _ = broadcast_arrivals(cfg, model, net, net.nodes)
        nxt = jnp.minimum(
            nxt, jnp.min(jnp.where(ok & (arrival >= t), arrival, far)))
    nat = getattr(protocol, "next_action_time", None)
    proto_next = t if nat is None else nat(pstate, net.nodes, t)
    return jnp.maximum(jnp.minimum(nxt, proto_next), t).astype(jnp.int32)


def _jump(cfg: EngineConfig, net: NetState, dt, t2):
    """Fast-forward `dt` provably-quiet milliseconds to absolute time
    `t2` in one hop.  Only time-translation-trivial state moves: the
    clock (which IS the ring head — rows are indexed by time % horizon,
    and every skipped row is empty by the oracle's guarantee) and
    broadcast retirement.  Retirement must match the per-ms path
    bit-for-bit: after per-ms steps t..t2-1 the last retire ran at
    t2-1, and retirement is monotone in t, so one retire at t2-1
    reproduces the whole sequence (idempotent when dt == 0)."""
    if cfg.bcast_slots > 0:
        net = net.replace(bc_active=net.bc_active &
                          ((t2 - 1 - net.bc_time) < cfg.horizon))
    return net.replace(time=net.time + dt)


def fast_forward_chunk(protocol, ms: int, seed_axis: bool = False,
                       superstep: int = 1):
    """Quiet-window fast-forwarding chunk: advance exactly `ms`
    simulated milliseconds as one `lax.while_loop` that runs a full
    `step_ms` body only on milliseconds that can contain work and jumps
    the clock by ``next_work - t`` across provably-quiet windows — the
    compiled-engine recovery of the reference's event-driven main loop
    (Network.java receiveUntil/nextMessage :533-637), which never pays
    for an empty ms.  Bit-identical to the per-ms `scan_chunk`
    (tests/test_fast_forward.py) because a skipped ms is exactly a
    no-op step body.

    ``seed_axis=True`` operates on vmap-batched state (leading [R] axis
    on every leaf, lockstep times — the bench/harness batch layout):
    ONE while loop whose body vmaps `step_ms` over the batch and jumps
    by the MIN of the per-seed oracles, so the whole batch stays in
    lockstep and a window is skipped only when every seed is quiet.

    Returns ``run(net, pstate) -> (net, pstate, stats)`` with
    ``stats = {"skipped_ms": int32, "jump_count": int32}`` — the skip
    accounting that makes a fast-forward speedup attributable
    (`bench.py` reports both).  `scan_chunk(fast_forward=True)` wraps
    this and drops the stats for interface-compatible callers.

    ``superstep=K`` runs the loop body as one fused `step_kms` window
    (jump to the next work, then advance in K-aligned supersteps): jump
    offsets are floored to multiples of K so every loop entry satisfies
    the superstep's alignment contract — an unaligned oracle target
    lands up to K-1 quiet ms early, which is sound (those ms are no-op
    steps the window simply executes).
    """
    check_chunk_config(protocol, ms, superstep=superstep,
                       fast_forward=True)
    cfg, k = protocol.cfg, superstep

    def run(net, pstate):
        t0 = net.time[0] if seed_axis else net.time
        t_end = t0 + ms

        def cond(carry):
            t = carry[0].time[0] if seed_axis else carry[0].time
            return t < t_end

        def body(carry):
            net, ps, skipped, jumps = carry
            if seed_axis:
                net, ps = jax.vmap(
                    lambda n_, p_: step_kms(protocol, n_, p_, k))(net, ps)
                t1 = net.time[0]
                nw = jnp.min(jax.vmap(
                    lambda n_, p_: next_work(protocol, n_, p_, t1))(
                    net, ps))
            else:
                net, ps = step_kms(protocol, net, ps, k)
                t1 = net.time
                nw = next_work(protocol, net, ps, t1)
            dt = jnp.clip(nw, t1, t_end) - t1
            if k > 1:
                dt = dt - dt % k          # keep entry times K-aligned
            net = _jump(cfg, net, dt, t1 + dt)
            return (net, ps, skipped + dt,
                    jumps + (dt > 0).astype(jnp.int32))

        z = jnp.asarray(0, jnp.int32)
        net, pstate, skipped, jumps = jax.lax.while_loop(
            cond, body, (net, pstate, z, z))
        return net, pstate, {"skipped_ms": skipped, "jump_count": jumps}

    return run


def scan_chunk(protocol, ms: int, t0_mod=None, allow_unaligned=False,
               superstep: int = 1, fast_forward: bool = False):
    """Returns ``run(net, pstate) -> (net, pstate)`` advancing `ms`
    milliseconds as one `lax.scan` — the single shared chunk body used by
    `Runner`, the harness, and the sharded runner.

    Phase specialization: protocols whose task schedule is statically
    known (no desynchronized start, constant node speed) expose
    ``schedule_lcm`` (the ms period after which the schedule repeats) and
    ``phase_hints(tmod)`` (which masked sub-computations can fire at
    ``time % lcm == tmod``).  Passing ``t0_mod`` (= entry ``net.time %
    lcm``, usually 0) then scans over lcm-sized blocks whose body UNROLLS
    one schedule period with per-ms static hints, so e.g. Handel's
    [N, Q, W] verification scoring is only traced on the
    1-in-pairing_time ms where any node can verify — the reference's own
    empty-ms skip (Network.java:533-570), recovered under jit.  (An
    earlier design dispatched each ms through ``lax.switch`` over the
    distinct hint variants — much cheaper to compile, but conditionals
    block XLA's in-place buffer aliasing, and copying the full simulator
    carry per ms cost far more than the skipped work saved; the unrolled
    block keeps every step inlined and alias-friendly.)  Results are
    bit-identical to the plain path (tests/test_phase_hints.py); callers
    must enter with ``net.time % schedule_lcm == t0_mod``.

    Nearly every caller REUSES the returned function for consecutive
    chunks, which keeps the alignment invariant only when ``ms`` is a
    multiple of the lcm — so that is enforced here (the one central
    guard; a config change that alters the lcm then fails loudly instead
    of silently dispatching the wrong phases from the second chunk on).
    A deliberately unaligned one-shot chunk may pass
    ``allow_unaligned=True`` (the sub-lcm tail is unrolled after the
    block scan); the next chunk's t0_mod is then ``(t0_mod + ms) % lcm``.

    ``superstep=K`` advances in fused K-ms engine windows (`step_kms` —
    bit-identical, tests/test_superstep.py) when the K-aware gate
    (`check_chunk_config`) proves the window: K <= the protocol's
    unicast latency floor + 1, K | horizon, K | chunk, K-aligned entry.

    ``fast_forward=True`` swaps the dense scan for the quiet-window
    `lax.while_loop` engine (`fast_forward_chunk` — bit-identical,
    tests/test_fast_forward.py), dropping the skip statistics; callers
    that want them use `fast_forward_chunk` directly.  Composes with
    `superstep` (K-aligned jumps) but not with `t0_mod` (see
    `check_chunk_config` for the remedy).
    """
    check_chunk_config(protocol, ms, t0_mod=t0_mod, superstep=superstep,
                       fast_forward=fast_forward)
    if fast_forward:
        base_ff = fast_forward_chunk(protocol, ms, superstep=superstep)

        def run_ff(net, pstate):
            net, pstate, _ = base_ff(net, pstate)
            return net, pstate

        return run_ff
    lcm = getattr(protocol, "schedule_lcm", None) if t0_mod is not None \
        else None
    if lcm and superstep > 1 and lcm % superstep:
        # Group hints across a K-aligned super-period.
        import math
        lcm = lcm * superstep // math.gcd(lcm, superstep)
    if lcm:
        if ms % lcm and not allow_unaligned:
            raise ValueError(
                f"phase-specialized chunk length {ms} is not a multiple of "
                f"the protocol schedule lcm {lcm}: reusing this chunk "
                "function would misalign the phase schedule after the "
                "first call. Use an lcm-multiple chunk, or pass "
                "allow_unaligned=True for a one-shot chunk and track "
                "t0_mod yourself.")
        sched = getattr(protocol, "schedule_lcm")
        hints = [protocol.phase_hints((t0_mod + dt) % sched)
                 for dt in range(lcm)]
        blocks, tail = divmod(ms, lcm)

        def run_spec(net, pstate):
            def body(carry, _):
                net, ps = carry
                if superstep > 1:
                    for i in range(0, len(hints), superstep):
                        net, ps = step_kms(
                            protocol, net, ps, superstep,
                            hints_k=hints[i:i + superstep])
                else:
                    for h in hints:
                        net, ps = step_ms(protocol, net, ps, hints=h)
                return (net, ps), ()
            if blocks:
                (net, pstate), _ = jax.lax.scan(body, (net, pstate),
                                                length=blocks)
            for h in hints[:tail]:
                net, pstate = step_ms(protocol, net, pstate, hints=h)
            return net, pstate

        return run_spec

    if superstep > 1:
        def run_k(net, pstate):
            def body(carry, _):
                return step_kms(protocol, *carry, superstep), ()
            (net2, p2), _ = jax.lax.scan(body, (net, pstate),
                                         length=ms // superstep)
            return net2, p2

        return run_k

    def run(net, pstate):
        def body(carry, _):
            return step_ms(protocol, *carry), ()
        (net2, p2), _ = jax.lax.scan(body, (net, pstate), length=ms)
        return net2, p2

    return run


class Runner:
    """Drives a protocol; caches one jitted scan per distinct chunk length.

    The analogue of Network.runMs (Network.java:318-338) — but a whole chunk
    of milliseconds is a single device program.

    donate="auto" disables buffer donation on TPU: the current (experimental)
    TPU plugin fails at runtime (INVALID_ARGUMENT) when the full simulator
    pytree is donated for the larger protocol states, and the failure
    poisons the process.  donate="big" donates ONLY leaves >=
    `donate_threshold` bytes (the mailbox ring, sig queues, pools — the
    buffers that dominate tier-2 residency, SCALE.md) via a split
    argument, halving peak memory for exactly the arrays that matter
    while keeping the donated pytree small; it is the configuration to
    try on TPU once hardware is reachable (bit-identical on CPU, where
    donation is a no-op — tested in tests/test_engine.py).

    Requests longer than `chunk_limit` ms are split into equal bounded
    chunks (scan composition — bit-identical results): very long single
    scans have crashed the current TPU runtime, and the split reuses ONE
    compiled program instead of compiling a fresh scan per distinct
    length.
    """

    def __init__(self, protocol, donate="auto", chunk_limit=10_000,
                 donate_threshold=1 << 20, superstep=1,
                 fast_forward=False, metrics=None, trace=None,
                 audit=None):
        self.protocol = protocol
        self._jits = {}
        if donate == "auto":
            donate = jax.default_backend() != "tpu"
        self._donate = donate
        self._donate_threshold = donate_threshold
        self._split = None          # (treedef, big_idx) for donate="big"
        self._validated = False
        self.chunk_limit = chunk_limit
        # fast_forward=True runs chunks through the quiet-window
        # while-loop engine (bit-identical) and accumulates the skip
        # stats (`ff_stats()` — utils/profiling.run_report reports
        # them).  Demoted silently when the protocol is ineligible,
        # matching the superstep demotion convention below.
        self._fast_forward = bool(fast_forward) and fast_forward_ok(protocol)
        # metrics (an obs.MetricsSpec) swaps in the instrumented chunk
        # builders: each chunk's MetricsCarry is appended to
        # `metrics_carries` (device arrays — no sync); `metrics_frame()`
        # fetches and stitches them.
        self._metrics = metrics
        # trace (an obs.TraceSpec) swaps in the flight-recorder chunk
        # builders (obs/trace.py — bit-identical trajectory); each
        # chunk's TraceCarry lands in `trace_carries` (device arrays —
        # no sync); `trace_frame()` decodes, `trace_stats()` surfaces
        # the truncation accounting (`run_report` prints it so a
        # clipped ring can never pass silently).
        if sum(p is not None for p in (metrics, trace, audit)) > 1:
            raise ValueError(
                "Runner supports ONE observability plane per pass "
                "(metrics=, trace=, audit=): the planes are separate "
                "carries and their builders do not compose yet. Fix: "
                "run the chunk twice (every plane is bit-identical on "
                "the trajectory), or pick the one you are debugging "
                "with")
        self._trace = trace
        # audit (an obs.AuditSpec) swaps in the invariant-monitor chunk
        # builders (obs/audit.py — bit-identical trajectory); each
        # chunk's AuditCarry lands in `audit_carries` (device arrays —
        # no sync); `audit_report()` decodes, and `run_report` prints a
        # LOUD verdict so a violated run can never pass silently.
        self._audit = audit
        self._ff_raw = []           # per-chunk device stats dicts
        self.metrics_carries = []
        self.trace_carries = []
        self.audit_carries = []
        # superstep=K fuses engine work across K-ms windows (step_kms,
        # bit-identical); the requested value is an UPPER BOUND — each
        # chunk runs the largest K <= it that `pick_superstep` proves
        # for the chunk length, entry time and config (a chunk that
        # proves nothing silently runs the per-ms path, results
        # identical).  "auto" lifts the bound to the engine default.
        if superstep == "auto":
            superstep = AUTO_SUPERSTEP_MAX
        self._superstep = int(superstep)

    def _chunk_fn(self, ms, superstep=1):
        key = (ms, superstep)
        if key not in self._jits:
            if self._metrics is not None and self._fast_forward:
                from ..obs.engine import fast_forward_chunk_metrics
                base = fast_forward_chunk_metrics(self.protocol, ms,
                                                  self._metrics,
                                                  superstep=superstep)
            elif self._metrics is not None:
                from ..obs.engine import scan_chunk_metrics
                base = scan_chunk_metrics(self.protocol, ms, self._metrics,
                                          superstep=superstep)
            elif self._trace is not None and self._fast_forward:
                from ..obs.trace import fast_forward_chunk_trace
                base = fast_forward_chunk_trace(self.protocol, ms,
                                                self._trace,
                                                superstep=superstep)
            elif self._trace is not None:
                from ..obs.trace import scan_chunk_trace
                base = scan_chunk_trace(self.protocol, ms, self._trace,
                                        superstep=superstep)
            elif self._audit is not None and self._fast_forward:
                from ..obs.audit import fast_forward_chunk_audit
                base = fast_forward_chunk_audit(self.protocol, ms,
                                                self._audit,
                                                superstep=superstep)
            elif self._audit is not None:
                from ..obs.audit import scan_chunk_audit
                base = scan_chunk_audit(self.protocol, ms, self._audit,
                                        superstep=superstep)
            elif self._fast_forward:
                base = fast_forward_chunk(self.protocol, ms,
                                          superstep=superstep)
            else:
                base = scan_chunk(self.protocol, ms, superstep=superstep)
            if self._donate == "big":
                self._jits[key] = split_donate_jit(base, *self._split)
            else:
                kw = {"donate_argnums": (0, 1)} if self._donate else {}
                self._jits[key] = jax.jit(base, **kw)
        return self._jits[key]

    def _call_chunk(self, fn, net, pstate):
        """Run one chunk and stash the fast-forward stats / metrics /
        trace carry its builder returns beyond ``(net, pstate)``."""
        out = fn(net, pstate)
        net, pstate = out[0], out[1]
        if self._fast_forward:
            self._ff_raw.append(out[2])
        if self._metrics is not None:
            self.metrics_carries.append(out[-1])
        if self._trace is not None:
            self.trace_carries.append(out[-1])
        if self._audit is not None:
            self.audit_carries.append(out[-1])
        return net, pstate

    def ff_stats(self):
        """Accumulated quiet-window skip accounting across every chunk
        this Runner ran, or None when fast-forward was off/never ran.
        Forces a device sync (host ints)."""
        if not self._ff_raw:
            return None
        import numpy as np
        return {
            "skipped_ms": sum(int(np.asarray(s["skipped_ms"]).reshape(-1)[0])
                              for s in self._ff_raw),
            "jump_count": sum(int(np.asarray(s["jump_count"]).reshape(-1)[0])
                              for s in self._ff_raw),
        }

    def metrics_frame(self):
        """Host-side `obs.MetricsFrame` stitched from every chunk's
        carry, or None when metrics were off/never ran."""
        if self._metrics is None or not self.metrics_carries:
            return None
        from ..obs.export import MetricsFrame
        return MetricsFrame.from_carries(self._metrics,
                                         self.metrics_carries)

    def trace_frame(self):
        """Host-side `obs.TraceFrame` stitched from every chunk's event
        ring, or None when tracing was off/never ran."""
        if self._trace is None or not self.trace_carries:
            return None
        from ..obs.decode import TraceFrame
        return TraceFrame.from_carries(self._trace, self.trace_carries)

    def trace_stats(self):
        """Flight-recorder truncation accounting across every chunk
        this Runner ran, or None when tracing was off/never ran: total
        recorded events, the per-chunk ring high-water mark, capacity,
        and the dropped-event count a silently clipped trace would
        otherwise hide.  Forces a device sync (host ints)."""
        if self._trace is None or not self.trace_carries:
            return None
        import numpy as np
        cursors = [np.asarray(jax.device_get(tc.cursor),
                              dtype=np.int64).reshape(-1)
                   for tc in self.trace_carries]
        dropped = sum(int(np.asarray(jax.device_get(tc.dropped),
                                     dtype=np.int64).sum())
                      for tc in self.trace_carries)
        return {"events": int(sum(c.sum() for c in cursors)),
                "high_water": int(max(c.max() for c in cursors)),
                "capacity": self._trace.capacity,
                "dropped": dropped}

    def audit_report(self):
        """Host-side `obs.AuditReport` stitched from every chunk's
        carry, or None when the audit plane was off/never ran.  Forces
        a device sync (host ints)."""
        if self._audit is None or not self.audit_carries:
            return None
        from ..obs.audit import monitored_invariants
        from ..obs.audit_report import AuditReport
        return AuditReport.from_carries(
            self._audit, self.audit_carries,
            monitored=monitored_invariants(self._audit,
                                           self.protocol.cfg))

    def audit_stats(self):
        """Audit verdict dict across every chunk this Runner ran, or
        None when the plane was off/never ran (`run_report` prints it
        LOUDLY — a violated run cannot pass silently)."""
        rep = self.audit_report()
        return None if rep is None else rep.stats()

    def run_report(self, net, wall_s=None):
        """One-line run summary (utils/profiling.run_report) carrying
        this Runner's quiet-window skip accounting, the trace
        truncation counters AND the audit verdict — a clipped event
        ring or a violated invariant shows up in bench output instead
        of passing silently."""
        from ..utils.profiling import run_report
        return run_report(net, wall_s, ff=self.ff_stats(),
                          trace=self.trace_stats(),
                          audit=self.audit_stats())

    def run_ms(self, net, pstate, ms: int):
        if not self._validated:
            validate = getattr(self.protocol.latency, "validate", None)
            if validate is not None and not isinstance(
                    jnp.asarray(net.nodes.city), jax.core.Tracer):
                validate(net.nodes)
            self._validated = True
        if self._donate == "big" and self._split is None:
            self._split = split_spec((net, pstate),
                                     self._donate_threshold)
        ms = int(ms)
        # Per-chunk superstep eligibility: K-aligned chunk + (statically
        # checkable) K-aligned entry time; a tracer entry time
        # conservatively falls back to the per-ms path.  The entry-time
        # readback blocks on the previous chunk, so it only happens when
        # superstep is actually enabled — the default path keeps fully
        # async dispatch.
        t_entry = None
        if self._superstep >= 2 and not isinstance(net.time,
                                                   jax.core.Tracer):
            t_entry = int(jax.device_get(net.time).reshape(-1)[0])
        stat_ms = (self._metrics.stat_each_ms
                   if self._metrics is not None else None)
        def eff(chunk_ms, t0):
            if self._superstep < 2:
                return 1
            return pick_superstep(self.protocol, chunk_ms, t0=t0,
                                  max_k=self._superstep,
                                  also_divides=stat_ms)
        if self.chunk_limit and ms > self.chunk_limit:
            # n_chunks equal pieces + one remainder piece at most: two
            # compiled programs for any length.
            whole, rem = divmod(ms, self.chunk_limit)
            fn = self._chunk_fn(self.chunk_limit,
                                eff(self.chunk_limit, t_entry))
            for _ in range(whole):
                net, pstate = self._call_chunk(fn, net, pstate)
                if t_entry is not None:
                    t_entry += self.chunk_limit
            if rem:
                net, pstate = self._call_chunk(
                    self._chunk_fn(rem, eff(rem, t_entry)), net, pstate)
            return net, pstate
        return self._call_chunk(self._chunk_fn(ms, eff(ms, t_entry)),
                                net, pstate)
