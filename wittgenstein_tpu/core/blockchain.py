"""Blockchain core layer: block arena + per-node chain state.

Reference surface (SURVEY.md §2.1): core/Block.java (height, id, parent,
producer, valid, proposalTime; isAncestor :69-79, hasDirectLink :86-100),
core/BlockChainNode.java (blocks by id/father/height, head, abstract
fork-choice `best` :50, onBlock dedup/validity :29-45), and
core/BlockChainNetwork.java (observer node, SendBlock message :22-41, full
head re-broadcast on endPartition :47-55, printStat :57-104).

TPU-native design (SURVEY §7.2.6): blocks live in one global **arena** of
fixed capacity A — a struct-of-arrays of int records; the block id IS the
arena slot (the reference's global `blockId` counter, Block.java:10).
Per-node chain knowledge is a `[N, A/32]` received-bitset plus a `[N]` head
index.  Ancestor logic is vectorized parent-pointer walking under
`lax.while_loop` (bounded by the chain height).  Protocols attach their own
parallel columns (difficulty, uncles, attestations...) next to the arena.

Chain *statistics* (blocks per producer, rewards, tx counts) are host-side
numpy walks over the frozen arena — they run once per experiment, not per
simulated ms (printStat parity, BlockChainNetwork.java:57-104).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops import bitset

U32 = jnp.uint32


@struct.dataclass
class Arena:
    """Global block table.  Slot 0 is the genesis block."""

    height: jnp.ndarray    # int32 [A]
    parent: jnp.ndarray    # int32 [A] (-1 for genesis)
    producer: jnp.ndarray  # int32 [A] (-1 for genesis)
    valid: jnp.ndarray     # bool [A]
    time: jnp.ndarray      # int32 [A] — proposalTime (engine ticks)
    n: jnp.ndarray         # int32 scalar — blocks allocated (incl. genesis)
    dropped: jnp.ndarray   # int32 scalar — allocations lost to a full arena

    @property
    def capacity(self):
        return self.height.shape[0]


def make_arena(capacity: int, genesis_height: int = 0) -> Arena:
    return Arena(
        height=jnp.zeros((capacity,), jnp.int32).at[0].set(genesis_height),
        parent=jnp.full((capacity,), -1, jnp.int32),
        producer=jnp.full((capacity,), -1, jnp.int32),
        valid=jnp.zeros((capacity,), bool).at[0].set(True),
        time=jnp.zeros((capacity,), jnp.int32),
        n=jnp.asarray(1, jnp.int32),
        dropped=jnp.asarray(0, jnp.int32),
    )


def alloc(arena: Arena, want, parent, producer, t, valid=None, height=None):
    """Allocate one block per requesting node (want [N] bool).

    Returns (arena, ids [N]) where ids[i] = -1 if i allocated nothing.
    Slot order follows node order within the tick — deterministic.
    `height` overrides the default parent.height + 1 (chains with height
    holes, e.g. Casper's slot-indexed blocks, Block.java allows height >
    parent.height + 1).
    """
    a = arena.capacity
    nreq = want.shape[0]
    rank = jnp.cumsum(want.astype(jnp.int32)) - 1
    slot = arena.n + rank
    ok = want & (slot < a)
    slot_w = jnp.where(ok, slot, a)
    if height is None:
        height = jnp.where(parent >= 0,
                           arena.height[jnp.maximum(parent, 0)] + 1, 1)
    if valid is None:
        valid = jnp.ones((nreq,), bool)
    arena = arena.replace(
        height=arena.height.at[slot_w].set(height, mode="drop"),
        parent=arena.parent.at[slot_w].set(parent, mode="drop"),
        producer=arena.producer.at[slot_w].set(producer, mode="drop"),
        valid=arena.valid.at[slot_w].set(valid, mode="drop"),
        time=arena.time.at[slot_w].set(
            jnp.broadcast_to(t, (nreq,)).astype(jnp.int32), mode="drop"),
        n=arena.n + jnp.sum(ok).astype(jnp.int32),
        dropped=arena.dropped + jnp.sum(want & ~ok).astype(jnp.int32),
    )
    return arena, jnp.where(ok, slot, -1)


def walk_to_height(arena: Arena, b, h):
    """Vectorized `while (cur.height > h) cur = cur.parent` (Block.java:
    72-78).  b, h broadcastable int32 arrays; -1 propagates."""
    b = jnp.asarray(b, jnp.int32)
    h = jnp.broadcast_to(jnp.asarray(h, jnp.int32), b.shape)

    def cond(cur):
        return jnp.any((cur >= 0) & (arena.height[jnp.maximum(cur, 0)] > h))

    def body(cur):
        step = (cur >= 0) & (arena.height[jnp.maximum(cur, 0)] > h)
        return jnp.where(step, arena.parent[jnp.maximum(cur, 0)], cur)

    return jax.lax.while_loop(cond, body, b)


def is_ancestor(arena: Arena, a, b):
    """True where block a is a strict ancestor of block b (Block.java:
    69-79)."""
    a = jnp.asarray(a, jnp.int32)
    up = walk_to_height(arena, b, arena.height[jnp.maximum(a, 0)])
    return (up == a) & (jnp.asarray(b) != a)


def has_direct_link(arena: Arena, a, b):
    """True where one of a, b is an ancestor of (or equal to) the other
    (Block.java:86-100)."""
    eq = jnp.asarray(a) == jnp.asarray(b)
    return eq | is_ancestor(arena, a, b) | is_ancestor(arena, b, a)


def common_ancestor(arena: Arena, a, b):
    """Lowest common ancestor of two blocks (vectorized)."""
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    ha = arena.height[jnp.maximum(a, 0)]
    hb = arena.height[jnp.maximum(b, 0)]
    h = jnp.minimum(ha, hb)
    a = walk_to_height(arena, a, h)
    b = walk_to_height(arena, b, h)

    def cond(st):
        x, y = st
        return jnp.any((x != y) & (x >= 0) & (y >= 0))

    def body(st):
        x, y = st
        step = (x != y) & (x >= 0) & (y >= 0)
        return (jnp.where(step, arena.parent[jnp.maximum(x, 0)], x),
                jnp.where(step, arena.parent[jnp.maximum(y, 0)], y))

    a, b = jax.lax.while_loop(cond, body, (a, b))
    return jnp.where(a == b, a, -1)


# ---------------------------------------------------------------- per-node

def n_words(capacity: int) -> int:
    return bitset.n_words(capacity)


def receive_block(received, ids_row, block_id, ok):
    """Mark block_id received for the masked nodes; returns (received,
    was_new [N])."""
    w = received.shape[-1]
    bit = bitset.one_bit(jnp.maximum(block_id, 0), w)
    known = bitset.intersects(received, bit)
    new = ok & (block_id >= 0) & ~known
    return jnp.where(new[:, None], received | bit, received), new


# ---------------------------------------------------------------- host side

def to_numpy(arena: Arena) -> dict:
    return {k: np.asarray(getattr(arena, k))
            for k in ("height", "parent", "producer", "valid", "time")} | {
            "n": int(arena.n)}


def chain_ids(arena_np: dict, head: int) -> list:
    """Block ids on the chain from head down to (excluding) genesis."""
    out, cur = [], int(head)
    while cur > 0:
        out.append(cur)
        cur = int(arena_np["parent"][cur])
    return out


def print_stat(arena_np: dict, head: int, node_info=None, small=True,
               out=print):
    """printStat parity (BlockChainNetwork.java:57-104): blocks in the
    observer's chain, per-producer counts."""
    chain = chain_ids(arena_np, head)
    producers = {}
    for b in chain:
        if not small:
            out(f"block: h:{arena_np['height'][b]}, id={b}, "
                f"creationTime:{arena_np['time'][b]}, "
                f"producer={arena_np['producer'][b]}, "
                f"parent:{arena_np['parent'][b]}")
        producers.setdefault(int(arena_np["producer"][b]), []).append(b)
    if not small:
        out(f"block count:{len(chain)} on {arena_np['n']}")
    for pid in sorted(producers):
        line = f"producer {pid}; {len(producers[pid])} blocks"
        if node_info:
            line += f"; {node_info(pid)}"
        out(line)
    return {"blocks_in_chain": len(chain),
            "per_producer": {k: len(v) for k, v in producers.items()}}
