"""Node-axis sharded engine: shard_map over a device mesh with explicit
cross-shard message exchange.

SURVEY.md §5.7/§7.2.7: the reference's scaling dimension is node count and
in-flight messages (single JVM heap); the TPU analogue is sharding the
node-state struct-of-arrays and the mailbox across devices, with
cross-shard delivery riding ICI collectives.  This module implements that
design for *shard-local* protocols (each node's step reads only its own
state and inbox — PingPong-style workloads; the level-structured
aggregation protocols use the GSPMD path in __graft_entry__ instead, where
XLA partitions the global-gather ops and inserts the collectives).

Design:
* Every shard owns N/S nodes: their NodeState slice, a local mailbox ring
  (same layout as core.state, sized per shard), and a replicated broadcast
  table (a broadcast is O(1) state, so replication is free — the same
  reasoning that makes sendAll O(1) on one chip).
* A step: build the local inbox -> protocol.step on local nodes ->
  split the outbox by destination shard into fixed-capacity buckets ->
  `jax.lax.all_to_all` over the 'sp' mesh axis (one ICI exchange per ms)
  -> enqueue the received bucket into the local ring.
* Send capacity: each shard may send up to `xcap` messages per destination
  shard per ms; overflow is counted in `xdropped` (the sharded analogue of
  NetState.dropped — size it for the protocol).

Latency draws key on GLOBAL message indices and node ids, and the node
coordinate/city tables are replicated into every shard (three [N] int32
all_gathers per ms, riding the same ICI exchange), so a sharded run is
bit-identical to the single-chip run of the same protocol for EVERY
latency model, including the positional ones
(NetworkLatencyByDistanceWJitter / city models) — tested on the virtual
CPU mesh in tests/test_sharded.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import network as net_mod
from ..core.latency import full_latency
from ..core import builders
from ..core.latency import NetworkFixedLatency
from ..core.state import (EngineConfig, Inbox, NetState, Outbox,
                          empty_outbox, init_net)
from ..ops import prng


@struct.dataclass
class ShardedNet:
    """Per-shard simulator state; leading axis inside shard_map is local."""

    net: NetState              # node axis = local slice; bc_* replicated
    shard_id: jnp.ndarray      # int32 scalar — this shard's index
    xdropped: jnp.ndarray      # int32 scalar — cross-shard bucket overflow


def _shard_spec(mesh):
    return NamedSharding(mesh, P("sp"))


class ShardedRunner:
    """Runs a shard-local protocol over a mesh axis 'sp'.

    The protocol contract matches core.protocol, with one extra rule: its
    `step(pstate, nodes, inbox, t, key)` must only touch node-local state
    (no cross-node gathers) — outputs address any GLOBAL node id via the
    outbox, and the runner routes them.
    """

    def __init__(self, protocol, mesh: Mesh, xcap: int = None):
        if "sp" not in mesh.axis_names:
            raise ValueError("mesh must have an 'sp' axis")
        if protocol.cfg.spill_cap:
            # The sharded delivery path clamps far-future arrivals to the
            # ring edge (like spill_cap == 0); honoring the spill contract
            # here needs a sharded spill buffer — refuse rather than
            # silently diverge from the single-chip engine.
            raise NotImplementedError(
                "ShardedRunner does not support EngineConfig.spill_cap > 0;"
                " size `horizon` for the protocol instead")
        if protocol.cfg.box_split != 1:
            raise NotImplementedError(
                "ShardedRunner shards the ring by node range itself; use "
                "box_split == 1 (sub-plane splitting is a single-chip "
                "buffer-limit workaround)")
        self.protocol = protocol
        self.mesh = mesh
        self.n_shards = mesh.shape["sp"]
        cfg = protocol.cfg
        if cfg.n % self.n_shards:
            raise ValueError(f"node count {cfg.n} not divisible by "
                             f"{self.n_shards} shards")
        self.n_local = cfg.n // self.n_shards
        # local engine config: same ring geometry over the local node count
        self.lcfg = EngineConfig(
            n=self.n_local, horizon=cfg.horizon, inbox_cap=cfg.inbox_cap,
            payload_words=cfg.payload_words, out_deg=cfg.out_deg,
            bcast_slots=cfg.bcast_slots,
            msg_discard_time=cfg.msg_discard_time)
        # per-destination-shard exchange capacity per ms
        self.xcap = xcap or max(16, 2 * self.n_local * cfg.out_deg //
                                max(1, self.n_shards))

    # ---------------------------------------------------------------- init

    def init(self, seed):
        """Global init then shard: NodeState slices per shard, fresh local
        rings, replicated broadcast table."""
        from ..core.state import init_net
        cfg, S = self.protocol.cfg, self.n_shards
        net, pstate = self.protocol.init(seed)

        def split_nodes(x):
            return x.reshape((S, self.n_local) + x.shape[1:])

        nodes_sh = jax.tree.map(split_nodes, net.nodes)
        lnet = jax.vmap(
            lambda nd, sid: init_net(self.lcfg, nd, seed).replace(
                time=net.time))(nodes_sh, jnp.arange(S))
        # replicate the broadcast table
        def rep(x):
            return jnp.broadcast_to(x[None], (S,) + x.shape)
        lnet = lnet.replace(
            bc_active=rep(net.bc_active), bc_src=rep(net.bc_src),
            bc_time=rep(net.bc_time), bc_payload=rep(net.bc_payload),
            bc_size=rep(net.bc_size), bc_seed=rep(net.bc_seed),
            seed=jnp.full((S,), net.seed, jnp.int32),
            time=jnp.full((S,), 0, jnp.int32))
        snet = ShardedNet(net=lnet,
                          shard_id=jnp.arange(S, dtype=jnp.int32),
                          xdropped=jnp.zeros((S,), jnp.int32))
        ps_sh = jax.tree.map(
            lambda x: x.reshape((S, self.n_local) + x.shape[1:])
            if x.ndim >= 1 and x.shape[0] == cfg.n else
            jnp.broadcast_to(x[None], (S,) + x.shape), pstate)
        spec = _shard_spec(self.mesh)
        put = lambda x: jax.device_put(x, spec)
        return jax.tree.map(put, snet), jax.tree.map(put, ps_sh)

    # ---------------------------------------------------------------- step

    def _local_inbox(self, snet: ShardedNet, t, part_all=None,
                     extra_all=None, tables=None):
        """Local-ring slice + broadcast recompute for the local nodes.
        Returns ``(inbox, nodes, sizes)`` — `sizes` is the per-slot
        payload-byte view ``[nl, C + B]`` the trace plane records
        (delivery itself reads sizes only for the receive counters).

        Global semantics preserved: latency draws key on GLOBAL ids."""
        cfg, lcfg = self.protocol.cfg, self.lcfg
        model = self.protocol.latency
        net = snet.net
        nodes = net.nodes
        nl, c, b, f = lcfg.n, cfg.inbox_cap, cfg.bcast_slots, \
            cfg.payload_words
        h = t % cfg.horizon
        base = h * (nl * c)
        uc_data = jnp.stack(
            [jax.lax.dynamic_slice(net.box_data[fi], (base,),
                                   (nl * c,)).reshape(nl, c)
             for fi in range(f)], axis=-1)
        uc_src = jax.lax.dynamic_slice(net.box_src[0], (base,),
                                       (nl * c,)).reshape(nl, c)
        uc_size = jax.lax.dynamic_slice(net.box_size[0], (base,),
                                        (nl * c,)).reshape(nl, c)
        uc_valid = jnp.arange(c)[None, :] < net.box_count[h][:, None]
        uc_valid = uc_valid & (~nodes.down[:, None])
        if part_all is not None:
            # delivery-time partition check, like build_inbox: enqueue
            # already filtered cross-partition sends, so with STATIC
            # partitions this is a no-op — but a mid-run partition
            # (chaos plane) opening while a message is in flight must
            # drop it at delivery exactly as the single-chip engine
            # does (box_src carries global ids; empty slots are already
            # masked by the count check above)
            uc_valid = uc_valid & (part_all[uc_src] ==
                                   nodes.partition[:, None])

        # broadcast recompute over GLOBAL destination ids
        gids = snet.shard_id * nl + jnp.arange(nl, dtype=jnp.int32)
        delta = prng.uniform_delta(net.bc_seed[:, None], gids[None, :])
        lat = self._bc_latency(snet, net.bc_src[:, None], gids[None, :],
                               delta, extra_all, tables)
        not_disc = lat < cfg.msg_discard_time
        lat = jnp.clip(lat, 1, cfg.horizon - 2)
        arrival = net.bc_time[:, None] + 1 + lat
        bc_valid = (net.bc_active[:, None] & (arrival == t) & not_disc &
                    (~nodes.down[None, :]))
        if part_all is not None:
            bc_valid = bc_valid & (part_all[net.bc_src][:, None] ==
                                   nodes.partition[None, :])
        bc_valid = jnp.transpose(bc_valid)
        inbox = Inbox(
            data=jnp.concatenate(
                [uc_data, jnp.broadcast_to(net.bc_payload[None],
                                           (nl, b, f))], axis=1),
            src=jnp.concatenate(
                [uc_src, jnp.broadcast_to(net.bc_src[None], (nl, b))],
                axis=1),
            valid=jnp.concatenate([uc_valid, bc_valid], axis=1))
        recv = (jnp.sum(uc_valid, 1) + jnp.sum(bc_valid, 1)).astype(
            jnp.int32)
        rbytes = (jnp.sum(jnp.where(uc_valid, uc_size, 0), 1) +
                  jnp.sum(jnp.where(bc_valid,
                                    net.bc_size[None, :], 0), 1)
                  ).astype(jnp.int32)
        nodes = nodes.replace(
            msg_received=nodes.msg_received + recv,
            bytes_received=nodes.bytes_received + rbytes)
        sizes = jnp.concatenate(
            [uc_size, jnp.broadcast_to(net.bc_size[None, :], (nl, b))],
            axis=1)
        return inbox, nodes, sizes

    def _bc_latency(self, snet, src_g, dst_g, delta, extra_all=None,
                    tables=None):
        """Latency between global ids, any model: positional models read
        the replicated [N] coordinate/city tables (`tables`); per-node
        extra latency (tor) is honored via the replicated extra_all
        table."""
        model = self.protocol.latency

        class _NodesStub:
            extra_latency = jnp.zeros_like(delta)
            if tables is not None:
                x, y, city = tables

        lat = model.extended(_NodesStub(), src_g, dst_g, delta)
        if extra_all is not None:
            lat = lat + extra_all[src_g] + extra_all[dst_g]
        return jnp.maximum(1, lat) * (src_g != dst_g) + (src_g == dst_g)

    def step_fn(self, superstep: int = 1, trace_spec=None,
                audit_spec=None):
        """Returns the shard_map'ed step: one simulated ms (default), or
        one fused K-ms superstep window.

        ``trace_spec`` (an `obs.TraceSpec`) compiles the flight
        recorder into the step: the returned function then maps
        ``(snet, pstate, TraceCarry) -> (snet, pstate, TraceCarry)``
        with PER-SHARD event rings — deliveries recorded from each
        shard's local inbox (dst = global id) and sends from its
        outbox (src/aux = the same global ids/slot the latency draw
        keys on), per-ms exact inside a K window.  Scope note: the
        sharded recorder covers `send`/`deliver` (+ the node filter);
        drop/spill/bc_retire kinds are decided inside the exchange
        machinery and stay counter-only here (`xdropped`,
        `net.dropped`).  Tracing is a pure read of values the step
        already computes, so the (state, pstate) trajectory is
        bit-identical to the untraced step (tests/test_trace.py).

        The K generalization mirrors `core/network.step_kms`: the local
        ring rows are untouched inside the window (K <= the protocol's
        unicast latency floor + 1, gated by the caller through
        `check_chunk_config`), so the window runs K local inbox reads
        and protocol steps with per-ms-exact broadcast interleaving,
        then ONE K-row slot clear, ONE outbox split with per-ms ranks
        (cross-shard drop semantics stay exactly per-ms: each origin ms
        keeps its own xcap sub-bucket), ONE `all_to_all` ICI exchange —
        the sharded engine's per-ms fixed cost — and ONE sort+scatter
        bin of the received window (reordered origin-ms-major so same-
        (ms, dest) slot order matches the sequential path bit-for-bit).
        Messages carry their origin-ms offset through the exchange so
        the receiver keys each latency draw on the origin ms, exactly
        as the per-ms path does.

        ``audit_spec`` (an `obs.AuditSpec`) compiles the invariant
        audit plane into the step instead: the returned function then
        carries a per-shard `AuditCarry` third argument.  The sharded
        monitors cover the local-ring/monotonicity invariants per
        shard, the replicated broadcast table's consistency, local
        ring conservation (received exchange candidates vs Δ local
        occupancy), and CROSS-SHARD exchange conservation: the
        per-destination-shard bucket counts each shard placed ride one
        extra tiny ``[S]`` all_to_all, so every shard verifies that
        what its peers claim they sent it equals what actually arrived
        (obs/audit.py `fold_window_sharded`).  Pure reads of values
        the step already computes — bit-identical trajectory
        (tests/test_audit.py)."""
        cfg, lcfg, S = self.protocol.cfg, self.lcfg, self.n_shards
        nl, k, xcap = self.n_local, cfg.out_deg, self.xcap
        K = superstep
        proto = self.protocol
        fw = cfg.payload_words
        if trace_spec is not None and audit_spec is not None:
            raise ValueError("one observability plane per step_fn")
        if trace_spec is not None:
            from ..obs.trace import KIND, _append
        if audit_spec is not None:
            from ..obs.audit import fold_window_sharded

        def one_shard(snet: ShardedNet, pstate, tc=None):
            net = snet.net
            t = net.time
            gids0 = snet.shard_id * nl + jnp.arange(nl, dtype=jnp.int32)
            # Chaos-plane hook (see network.step_kms): the window-entry
            # fault application runs on the LOCAL node slice (gids map
            # local rows to the schedule's global ids) BEFORE the
            # replicated-table gathers below, so every shard's view of
            # down/partition state is the post-fault one.
            af = getattr(proto, "apply_faults", None)
            if af is not None:
                net = af(net, t, gids=gids0)
            # replicated per-node tables for cross-shard checks (one [N]
            # all_gather each; rides the same ICI exchange)
            part_all = jax.lax.all_gather(net.nodes.partition,
                                          "sp").reshape(-1)
            extra_all = jax.lax.all_gather(net.nodes.extra_latency,
                                           "sp").reshape(-1)
            down_all = jax.lax.all_gather(net.nodes.down, "sp").reshape(-1)
            # Positional latency models read global coordinates/cities;
            # distance-free models declare `positional = False` and skip
            # the three [N] gathers (default True: unknown custom models
            # get the tables).
            if getattr(proto.latency, "positional", True):
                tables = (
                    jax.lax.all_gather(net.nodes.x, "sp").reshape(-1),
                    jax.lax.all_gather(net.nodes.y, "sp").reshape(-1),
                    jax.lax.all_gather(net.nodes.city, "sp").reshape(-1))
            else:
                tables = None
            snet = snet.replace(net=net)
            step = getattr(proto, "step_sharded", None)
            aobs = None
            if audit_spec is not None:
                # window-entry observations for the sharded fold: the K
                # consumed rows are intact until the deferred clear, so
                # one contiguous slice reads them all up-front
                aobs = {
                    "t_entry": jnp.asarray(t, jnp.int32),
                    "occ_entry": jnp.sum(net.box_count).astype(jnp.int32),
                    "dropped_entry": net.dropped,
                    "consumed": jnp.sum(jax.lax.dynamic_slice(
                        net.box_count, (t % cfg.horizon, 0),
                        (K, nl))).astype(jnp.int32),
                    "candidates": jnp.asarray(0, jnp.int32),
                    "xmismatch": jnp.asarray(0, jnp.int32),
                }

            # ---- K protocol steps: per-ms local inbox reads (the local
            # ring is untouched inside the window — binning is deferred)
            # with per-ms-exact broadcast retire/deliver/enqueue ----
            parts = []          # per-ms flattened outbox batches
            for i in range(K):
                ti = t + i
                net = net.replace(bc_active=net.bc_active & (
                    (ti - net.bc_time) < cfg.horizon))
                inbox, nodes, in_sizes = self._local_inbox(
                    snet.replace(net=net), ti, part_all, extra_all,
                    tables)
                if trace_spec is not None and trace_spec.enabled("deliver"):
                    width = inbox.valid.shape[1]
                    dst_g = jnp.broadcast_to(gids0[:, None], (nl, width))
                    slot = jnp.broadcast_to(
                        jnp.arange(width, dtype=jnp.int32)[None, :],
                        (nl, width))
                    tc = _append(trace_spec, tc, ti, KIND["deliver"],
                                 inbox.src.reshape(-1),
                                 dst_g.reshape(-1),
                                 in_sizes.reshape(-1), slot.reshape(-1),
                                 inbox.valid.reshape(-1))
                key = jax.random.fold_in(jax.random.PRNGKey(net.seed), ti)
                if step is not None:
                    # Shard-aware protocols receive their GLOBAL node ids.
                    pstate, nodes, out = step(pstate, nodes, inbox, ti,
                                              key, gids0)
                else:
                    pstate, nodes, out = proto.step(pstate, nodes, inbox,
                                                    ti, key)
                # Width may be narrower than cfg.out_deg (Outbox.slot0):
                # the latency key below stays on the full-width slot id.
                ke = out.dest.shape[1]
                m = nl * ke
                dest_i = out.dest.reshape(m)
                size_i = out.size.reshape(m)
                want_i = (dest_i >= 0) & (~nodes.down[jnp.arange(m) // ke])
                # counters for attempted sends (parity w/ enqueue_unicast)
                sent = nodes.msg_sent.at[jnp.arange(m) // ke].add(
                    want_i.astype(jnp.int32))
                sbytes = nodes.bytes_sent.at[jnp.arange(m) // ke].add(
                    jnp.where(want_i, size_i, 0))
                nodes = nodes.replace(msg_sent=sent, bytes_sent=sbytes)
                net = net.replace(nodes=nodes)
                if trace_spec is not None and trace_spec.enabled("send"):
                    tc = _append(
                        trace_spec, tc, ti, KIND["send"],
                        jnp.repeat(gids0, ke),
                        jnp.clip(dest_i, 0, cfg.n - 1), size_i,
                        jnp.repeat(gids0, ke) * k + out.slot0 +
                        jnp.arange(m, dtype=jnp.int32) % ke, want_i)
                parts.append((
                    jnp.repeat(gids0, ke),              # global src ids
                    dest_i,
                    out.payload.reshape(m, fw),
                    size_i,
                    out.delay.reshape(m),
                    # Global stable message index (src_g * out_deg + slot
                    # id): the single-chip engine keys its latency delta
                    # on exactly this (enqueue_unicast), so carrying it
                    # through the exchange keeps jittered models
                    # bit-identical to the unsharded run.
                    jnp.repeat(gids0, ke) * k + out.slot0 +
                    jnp.arange(m, dtype=jnp.int32) % ke,
                    jnp.full((m,), i, jnp.int32),       # origin-ms offset
                    want_i,
                ))
                # ---- broadcasts: replicated table, all shards agree ----
                req = out.bcast & (~nodes.down)
                if trace_spec is not None and trace_spec.enabled("send"):
                    tc = _append(trace_spec, tc, ti, KIND["send"], gids0,
                                 jnp.full((nl,), -1, jnp.int32),
                                 out.bcast_size,
                                 jnp.full((nl,), -1, jnp.int32), req)
                # gather every shard's requests (replicated result)
                req_all = jax.lax.all_gather(req, "sp").reshape(-1)
                pl_all = jax.lax.all_gather(out.bcast_payload,
                                            "sp").reshape(cfg.n, fw)
                sz_all = jax.lax.all_gather(out.bcast_size,
                                            "sp").reshape(-1)
                gout = empty_outbox(cfg).replace(
                    bcast=req_all, bcast_payload=pl_all, bcast_size=sz_all)
                # reuse the single-chip broadcast allocator on a stub net
                # (bc_* fields are global); counters from it are per-
                # GLOBAL-node, so apply the local slice separately
                gnet2 = net_mod.enqueue_broadcast(
                    EngineConfig(n=cfg.n, horizon=cfg.horizon,
                                 inbox_cap=cfg.inbox_cap,
                                 payload_words=fw, out_deg=cfg.out_deg,
                                 bcast_slots=cfg.bcast_slots),
                    net.replace(nodes=jax.tree.map(
                        lambda x: jnp.zeros((cfg.n,) + x.shape[1:],
                                            x.dtype),
                        net.nodes)), gout, ti)
                bsent = net.nodes.msg_sent + jnp.where(
                    req, cfg.n, 0).astype(jnp.int32)
                bbytes = net.nodes.bytes_sent + jnp.where(
                    req, out.bcast_size * cfg.n, 0)
                net = net.replace(
                    nodes=net.nodes.replace(msg_sent=bsent,
                                            bytes_sent=bbytes),
                    bc_active=gnet2.bc_active, bc_src=gnet2.bc_src,
                    bc_time=gnet2.bc_time, bc_payload=gnet2.bc_payload,
                    bc_size=gnet2.bc_size, bc_seed=gnet2.bc_seed,
                    bc_dropped=gnet2.bc_dropped)

            # ---- ONE K-row slot clear (entry time ≡ 0 mod K: no wrap) --
            net = net.replace(box_count=jax.lax.dynamic_update_slice(
                net.box_count, jnp.zeros((K, nl), jnp.int32),
                (t % cfg.horizon, 0)))

            # ---- split the window's outboxes by destination shard ----
            # Rank per (dest-shard, ORIGIN MS) group: each origin ms
            # keeps its own xcap sub-bucket, so cross-shard drop
            # semantics stay exactly per-ms whatever K is.
            src_g = jnp.concatenate([p[0] for p in parts])
            dest = jnp.concatenate([p[1] for p in parts])
            payload = jnp.concatenate([p[2] for p in parts])
            size = jnp.concatenate([p[3] for p in parts])
            delay = jnp.concatenate([p[4] for p in parts])
            midx = jnp.concatenate([p[5] for p in parts])
            toff = jnp.concatenate([p[6] for p in parts])
            want = jnp.concatenate([p[7] for p in parts])
            ma = src_g.shape[0]
            dshard = jnp.clip(dest, 0, cfg.n - 1) // nl
            order = jnp.argsort(jnp.where(want, dshard, S), stable=True)
            ds_s = jnp.where(want, dshard, S)[order]
            to_s = toff[order]
            idx = jnp.arange(ma, dtype=jnp.int32)
            new_grp = ((ds_s != jnp.roll(ds_s, 1)) |
                       (to_s != jnp.roll(to_s, 1))).at[0].set(True)
            rank = idx - jax.lax.cummax(jnp.where(new_grp, idx, 0))
            ok_s = (ds_s < S) & (rank < xcap)
            slot = jnp.where(ok_s, (ds_s * K + to_s) * xcap + rank,
                             S * K * xcap)
            # bucket fields [S * K * xcap, ...]
            def scatter(vals, fill):
                buf = jnp.full((S * K * xcap,) + vals.shape[1:], fill,
                               vals.dtype)
                return buf.at[slot].set(vals[order], mode="drop")
            b_src = scatter(src_g, -1)
            b_dest = scatter(dest, -1)
            b_payload = scatter(payload, 0)
            b_size = scatter(size, 0)
            b_delay = scatter(delay, 0)
            b_midx = scatter(midx, 0)
            b_toff = scatter(toff, 0)
            xdrop = jnp.sum((ds_s < S) & ~ok_s).astype(jnp.int32)

            # ---- the ICI exchange: ONE all_to_all for the window ----
            def xc(x):
                return jax.lax.all_to_all(
                    x.reshape((S, K * xcap) + x.shape[1:])[None],
                    "sp", split_axis=1, concat_axis=1)[0].reshape(
                    (S * K * xcap,) + x.shape[1:])

            # Origin-ms-major reorder of the received window: the per-ms
            # path bins ms i's messages before ms i+1's whatever their
            # source shard, and the stable binning sort below preserves
            # input order within a (rel, dest) group — so the input must
            # be (ms, shard, rank)-ordered for bit-identical slots.
            def omm(x):
                return x.reshape((S, K, xcap) + x.shape[1:]).swapaxes(
                    0, 1).reshape((S * K * xcap,) + x.shape[1:])

            xb_dest = xc(b_dest)
            if aobs is not None and audit_spec.enabled(
                    "shard_conservation"):
                # cross-shard conservation: what each peer CLAIMS it
                # sent me (its per-dest-shard bucket counts, exchanged
                # over one tiny [S] all_to_all) must equal what
                # actually arrived in its segment of the exchange
                sent_to = jnp.sum(
                    ok_s[:, None] &
                    (ds_s[:, None] == jnp.arange(S, dtype=jnp.int32)[
                        None, :]), axis=0).astype(jnp.int32)
                claims = jax.lax.all_to_all(
                    sent_to.reshape(S, 1)[None], "sp", split_axis=1,
                    concat_axis=1)[0].reshape(S)
                received_from = jnp.sum(
                    xb_dest.reshape(S, K * xcap) >= 0,
                    axis=1).astype(jnp.int32)
                aobs["xmismatch"] = jnp.sum(
                    jnp.abs(claims - received_from)).astype(jnp.int32)
            r_src = omm(xc(b_src))
            r_dest = omm(xb_dest)
            r_payload = omm(xc(b_payload))
            r_size = omm(xc(b_size))
            r_delay = omm(xc(b_delay))
            r_midx = omm(xc(b_midx))
            r_toff = omm(xc(b_toff))

            # ---- enqueue received into the local ring ----
            dl = jnp.clip(r_dest - snet.shard_id * nl, 0, nl - 1)
            # latency keyed by the global flat message index AND the
            # message's origin ms — the same draw enqueue_unicast makes
            # on one chip at that ms
            seed_t = prng.hash3(net.seed, prng.TAG_LATENCY, t + r_toff)
            delta = prng.uniform_delta(seed_t, r_midx)
            lat = self._bc_latency(snet, jnp.maximum(r_src, 0),
                                   jnp.where(r_dest >= 0, r_dest, 0),
                                   delta, extra_all, tables)
            # the same validity gates as enqueue_unicast: discard window,
            # destination down, cross-partition drop
            ok = (r_dest >= 0) & (lat < cfg.msg_discard_time) & \
                ~net.nodes.down[dl] & \
                (part_all[jnp.maximum(r_src, 0)] ==
                 net.nodes.partition[dl])
            if aobs is not None:
                aobs["candidates"] = jnp.sum(ok).astype(jnp.int32)
            raw_total = jnp.clip(r_delay, 0, None) + jnp.maximum(lat, 1)
            total = jnp.clip(raw_total, 1, cfg.horizon - 2)
            # Arrivals past the ring clamp (counted, like the single-chip
            # engine with spill_cap == 0; spill is unsupported here — see
            # __init__).
            n_clamped = jnp.sum(ok & (raw_total != total)).astype(jnp.int32)
            net = net.replace(clamped=net.clamped + n_clamped)
            arrival = t + r_toff + 1 + total
            from ..ops.pallas_route import route_enabled
            if route_enabled():
                # Fused Pallas binning of the received window — same
                # cells, same slot order (the local-ring half of the
                # WTPU_PALLAS_ROUTE megakernel; the origin-ms-major
                # reorder above already put the input in the per-ms
                # path's stable order).
                from ..ops.pallas_route import bin_into_ring_planes
                box_data, box_src, box_size, box_count, n_drop = \
                    bin_into_ring_planes(
                        net.box_data, net.box_src, net.box_size,
                        net.box_count, arrival % cfg.horizon, dl,
                        r_src, r_size, r_payload, ok,
                        horizon=cfg.horizon, cap=cfg.inbox_cap, n=nl,
                        split=1, payload_words=fw)
                dropped = net.dropped + n_drop
            else:
                mx = S * K * xcap
                big = jnp.int32(0x7FFFFFFF)
                rel_k = jnp.where(ok, arrival - t, big)
                d_k = jnp.where(ok, dl, big)
                o1 = jnp.argsort(d_k, stable=True)
                order2 = o1[jnp.argsort(rel_k[o1], stable=True)]
                rel_s, d_s = rel_k[order2], d_k[order2]
                idx2 = jnp.arange(mx, dtype=jnp.int32)
                ng = ((rel_s != jnp.roll(rel_s, 1)) |
                      (d_s != jnp.roll(d_s, 1))).at[0].set(True)
                rank2 = idx2 - jax.lax.cummax(jnp.where(ng, idx2, 0))
                h_s = ((t + rel_s) % cfg.horizon)
                ok2 = (rel_s < big) & (rank2 + net.box_count[
                    jnp.clip(h_s, 0, cfg.horizon - 1),
                    jnp.clip(d_s, 0, nl - 1)] < cfg.inbox_cap)
                slot2 = net.box_count[jnp.clip(h_s, 0, cfg.horizon - 1),
                                      jnp.clip(d_s, 0, nl - 1)] + rank2
                hnc = cfg.horizon * nl * cfg.inbox_cap
                flat = (jnp.clip(h_s, 0, cfg.horizon - 1) * nl +
                        jnp.clip(d_s, 0, nl - 1)) * cfg.inbox_cap + \
                    jnp.where(ok2, slot2, 0)
                flat_w = jnp.where(ok2, flat, hnc)
                pl_s = r_payload[order2]
                box_data = tuple(
                    net.box_data[fi].at[flat_w].set(
                        pl_s[:, fi], mode="drop", unique_indices=True)
                    for fi in range(fw))
                box_src = (net.box_src[0].at[flat_w].set(
                    r_src[order2], mode="drop", unique_indices=True),)
                box_size = (net.box_size[0].at[flat_w].set(
                    r_size[order2], mode="drop", unique_indices=True),)
                box_count = net.box_count.at[
                    jnp.clip(h_s, 0, cfg.horizon - 1),
                    jnp.clip(d_s, 0, nl - 1)].add(ok2.astype(jnp.int32),
                                                  mode="drop")
                dropped = net.dropped + jnp.sum(
                    (rel_s < big) & ~ok2).astype(jnp.int32)

            net = net.replace(
                box_data=box_data, box_src=box_src, box_size=box_size,
                box_count=box_count, dropped=dropped, time=t + K)
            snet = snet.replace(net=net, xdropped=snet.xdropped + xdrop)
            if aobs is not None:
                tc = fold_window_sharded(audit_spec, cfg, tc, aobs,
                                         snet, K)
            if tc is not None:
                return snet, pstate, tc
            return snet, pstate

        traced = trace_spec is not None or audit_spec is not None

        def wrapped(snet, pstate, tc=None):
            # shard_map blocks keep a leading length-1 shard axis; peel it
            # off for the body and restore it for the output specs.
            sq = lambda x: x.reshape(x.shape[1:])
            un = lambda x: x.reshape((1,) + x.shape)
            if traced:
                sn2, ps2, tc2 = one_shard(jax.tree.map(sq, snet),
                                          jax.tree.map(sq, pstate),
                                          jax.tree.map(sq, tc))
                return (jax.tree.map(un, sn2), jax.tree.map(un, ps2),
                        jax.tree.map(un, tc2))
            sn2, ps2 = one_shard(jax.tree.map(sq, snet),
                                 jax.tree.map(sq, pstate))
            return jax.tree.map(un, sn2), jax.tree.map(un, ps2)

        spec = P("sp")
        specs = (spec,) * (3 if traced else 2)
        # jax >= 0.6 exposes jax.shard_map (check_vma); 0.4.x only has
        # the experimental module (check_rep).  Same semantics; the
        # check is disabled either way (the per-shard body mixes
        # replicated broadcast state with sharded node state).
        if hasattr(jax, "shard_map"):
            return jax.shard_map(wrapped, mesh=self.mesh, in_specs=specs,
                                 out_specs=specs, check_vma=False)
        from jax.experimental.shard_map import shard_map
        return shard_map(wrapped, mesh=self.mesh, in_specs=specs,
                         out_specs=specs, check_rep=False)

    def _metric_values(self, spec, snet):
        """Global-aggregate counter values from the sharded state —
        the sharded analogue of obs.plane.counter_values.  Reductions
        over the shard axis lower to in-mesh collectives; everything
        stays on device (no host sync)."""
        net = snet.net
        nodes = net.nodes
        cols = set(spec.columns)
        out = {}
        if "msg_sent" in cols:
            out["msg_sent"] = jnp.sum(nodes.msg_sent)
        if "msg_received" in cols:
            out["msg_received"] = jnp.sum(nodes.msg_received)
        if "bytes_sent" in cols:
            out["bytes_sent"] = jnp.sum(nodes.bytes_sent)
        if "bytes_received" in cols:
            out["bytes_received"] = jnp.sum(nodes.bytes_received)
        if "done_count" in cols:
            out["done_count"] = jnp.sum((~nodes.down) & (nodes.done_at > 0))
        if "live_count" in cols:
            out["live_count"] = jnp.sum(~nodes.down)
        if "ring_rows" in cols:
            # box_count is [S, H, n_local]: a ring ROW is global (one
            # per ms slot), occupied when any shard holds a delivery.
            out["ring_rows"] = jnp.sum(
                jnp.any(net.box_count > 0, axis=(0, 2)))
        if "ring_occupancy" in cols:
            out["ring_occupancy"] = jnp.sum(net.box_count)
        if "bc_live" in cols:
            # bc table is replicated per shard; count one shard's view.
            out["bc_live"] = jnp.sum(net.bc_active[0])
        if "spill_hwm" in cols:
            out["spill_hwm"] = jnp.asarray(0, jnp.int32)  # spill unsupported
        if "drop_count" in cols:
            # dropped/clamped/xdropped are per-shard (local ring + local
            # exchange) — sum; bc_dropped rides the REPLICATED broadcast
            # table (every shard computes the same global value, like
            # bc_active above) — one shard's view, not a sum.
            out["drop_count"] = (
                jnp.sum(net.dropped) + net.bc_dropped[0] +
                jnp.sum(net.clamped) + jnp.sum(snet.xdropped))
        return {k: v.astype(jnp.int32) for k, v in out.items()}

    def run_ms(self, snet, pstate, ms: int, metrics=None,
               superstep: int = 1, trace=None, audit=None):
        """Advance `ms` milliseconds.  ``metrics`` (an
        `obs.MetricsSpec`) additionally records the global-aggregate
        interval series on device and returns ``(snet, pstate,
        MetricsCarry)`` — the sharded twin of
        `obs.engine.scan_chunk_metrics`.

        ``trace`` (an `obs.TraceSpec`) compiles the flight recorder
        into the step instead (`step_fn(trace_spec=...)` — per-shard
        event rings, deliver/send kinds) and returns ``(snet, pstate,
        TraceCarry)`` with a leading shard axis on the carry;
        `obs.TraceFrame.from_carry` merges the shards onto one
        timeline.  One plane per pass (both are bit-identical on the
        trajectory — run twice to get both).

        ``audit`` (an `obs.AuditSpec`) compiles the invariant audit
        plane into the step (`step_fn(audit_spec=...)` — local + cross-
        shard conservation monitors) and returns ``(snet, pstate,
        AuditCarry)`` with a leading shard axis on the carry;
        `obs.AuditReport.from_carry` merges the shards onto one
        verdict.  One plane per pass, like metrics/trace.

        ``superstep=K`` advances in fused K-ms windows (one ICI
        exchange, one sort/scatter bin and one slot clear per window —
        `step_fn(superstep=K)`, bit-identical); gated by the shared
        K-aware eligibility check plus an entry-time alignment read
        (blocks on in-flight work only when a superstep is requested)."""
        from ..core.network import check_chunk_config

        ms = int(ms)
        if sum(p is not None for p in (metrics, trace, audit)) > 1:
            raise ValueError(
                "run_ms(metrics=, trace=, audit=) is one plane per "
                "pass: run the chunk twice (every plane is "
                "bit-identical on the trajectory)")
        check_chunk_config(self.protocol, ms, superstep=superstep)
        if superstep > 1:
            if metrics is not None and metrics.stat_each_ms % superstep:
                raise ValueError(
                    f"superstep={superstep} windows record at window "
                    f"boundaries: stat_each_ms ({metrics.stat_each_ms}) "
                    "must be a multiple of the superstep")
            t_entry = int(np.asarray(
                jax.device_get(snet.net.time)).reshape(-1)[0])
            if t_entry % superstep:
                raise ValueError(
                    f"superstep={superstep} needs a K-aligned entry time "
                    f"(run is at t={t_entry}). Fix: advance "
                    f"{superstep - t_entry % superstep} ms with "
                    "superstep=1 first, or keep chunk lengths multiples "
                    "of the superstep from t=0")
        if not hasattr(self, "_jits"):
            self._jits = {}
            self._steps = {}
        if (superstep, trace, audit) not in self._steps:
            self._steps[(superstep, trace, audit)] = self.step_fn(
                superstep=superstep, trace_spec=trace, audit_spec=audit)
        key = (ms, metrics, trace, audit, superstep)
        if key not in self._jits:
            step = self._steps[(superstep, trace, audit)]
            if trace is not None:
                from ..obs.trace import init_trace

                @jax.jit
                def run(sn, ps):
                    tc0 = jax.vmap(lambda _: init_trace(trace))(
                        sn.net.time)

                    def body(carry, _):
                        return step(*carry), ()
                    (sn2, ps2, tc), _ = jax.lax.scan(
                        body, (sn, ps, tc0), length=ms // superstep)
                    return sn2, ps2, tc
            elif audit is not None:
                from ..obs.audit import init_audit_sharded

                @jax.jit
                def run(sn, ps):
                    ac0 = jax.vmap(
                        lambda s: init_audit_sharded(audit, s))(sn)

                    def body(carry, _):
                        return step(*carry), ()
                    (sn2, ps2, ac), _ = jax.lax.scan(
                        body, (sn, ps, ac0), length=ms // superstep)
                    return sn2, ps2, ac
            elif metrics is None:
                @jax.jit
                def run(sn, ps):
                    def body(carry, _):
                        return step(*carry), ()
                    (sn2, ps2), _ = jax.lax.scan(body, (sn, ps),
                                                 length=ms // superstep)
                    return sn2, ps2
            else:
                from ..obs.plane import init_metrics, record

                @jax.jit
                def run(sn, ps):
                    mc0 = init_metrics(metrics, ms, sn.net.time[0])

                    def body(carry, _):
                        sn, ps, mc = carry
                        sn, ps = step(sn, ps)
                        mc = record(metrics, mc, sn.net.time[0] - 1,
                                    self._metric_values(metrics, sn),
                                    n_steps=superstep)
                        return (sn, ps, mc), ()
                    (sn2, ps2, mc), _ = jax.lax.scan(body, (sn, ps, mc0),
                                                     length=ms // superstep)
                    return sn2, ps2, mc

            self._jits[key] = run
        with self.mesh:
            return self._jits[key](snet, pstate)

    # ---------------------------------------------------------------- util

    def gather_nodes(self, snet):
        """Collect the sharded NodeState back to a global one (host)."""
        return jax.tree.map(
            lambda x: np.asarray(x).reshape((-1,) + x.shape[2:]),
            snet.net.nodes)


# --------------------------------------------------------------- demo


@struct.dataclass
class RingState:
    received: jnp.ndarray   # int32 [N] — payload sum received
    count: jnp.ndarray      # int32 [N]


class RingForward:
    """Shard-local protocol: every node sends its id to (id + stride) % N
    each ms; nodes accumulate what they receive.  Exercises cross-shard
    unicast routing + the broadcast path (node 0 broadcasts at t == 0)."""

    # dest = (id + stride) % N with stride % N != 0 in every in-tree
    # config — never self (core/network.unicast_floor_ms).
    may_self_send = False

    def __init__(self, n=64, stride=9, latency=10, horizon=64):
        self.node_count = n
        self.stride = stride
        self.latency = (NetworkFixedLatency(latency)
                        if isinstance(latency, int) else latency)
        self.cfg = EngineConfig(n=n, horizon=horizon, inbox_cap=8,
                                payload_words=1, out_deg=1, bcast_slots=2)

    def init(self, seed):
        nodes = builders.NodeBuilder().build(seed, self.cfg.n)
        net = init_net(self.cfg, nodes, seed)
        return net, RingState(
            received=jnp.zeros((self.cfg.n,), jnp.int32),
            count=jnp.zeros((self.cfg.n,), jnp.int32))

    def _step(self, pstate, nodes, inbox, t, key, gids):
        got = jnp.sum(jnp.where(inbox.valid, inbox.data[:, :, 0], 0),
                      axis=1).astype(jnp.int32)
        cnt = jnp.sum(inbox.valid, axis=1).astype(jnp.int32)
        pstate = pstate.replace(received=pstate.received + got,
                                count=pstate.count + cnt)
        # Outbox sized to THIS slice (local under the sharded runner).
        nloc = gids.shape[0]
        send = t < 5                      # five rounds of sends
        dest = jnp.where(send, (gids + self.stride) % self.node_count, -1)
        out = Outbox(
            dest=dest[:, None],
            payload=(gids * 10)[:, None, None].astype(jnp.int32),
            size=jnp.ones((nloc, 1), jnp.int32),
            delay=jnp.zeros((nloc, 1), jnp.int32),
            bcast=(gids == 0) & (t == 0),
            bcast_payload=jnp.full((nloc, 1), 777, jnp.int32),
            bcast_size=jnp.ones((nloc,), jnp.int32))
        return pstate, nodes, out

    def step(self, pstate, nodes, inbox, t, key):
        gids = jnp.arange(self.cfg.n, dtype=jnp.int32)
        return self._step(pstate, nodes, inbox, t, key, gids)

    def step_sharded(self, pstate, nodes, inbox, t, key, gids):
        return self._step(pstate, nodes, inbox, t, key, gids)
