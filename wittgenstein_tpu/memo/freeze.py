"""Fixed-point lane freezing — stop recomputing converged lanes.

A done lane is a fixed point the dense engines recompute every ms: once
the PR-2 `next_work` oracle proves no ring row, broadcast arrival or
protocol timer can fire before the lane's end, every remaining
millisecond is bit-identical to a no-op step (the fast-forward
soundness contract, core/network.next_work).  The serve scheduler can
therefore slice the lane out of the running batch at a chunk boundary
and STITCH its tail analytically:

  * final state — `core/network._jump` to the lane's end: the clock
    moves (it IS the ring head) and broadcasts retire exactly as the
    per-ms path would have retired them; every other leaf is constant
    by the oracle's guarantee.
  * metrics     — every remaining interval row samples the SAME frozen
    counter values (`samples == stat_each_ms` per row — the dense
    recorder's count); only `bc_live` can still move (records retire
    by age), so its rows are computed through the `_jump` retirement
    formula per interval.
  * audit       — a quiet chunk violates nothing: zero counts, no
    first record, monotonicity snapshots and totals equal to the
    frozen state's (exactly what `fold_window` over no-op steps
    produces).
  * trace       — no events (nothing sends, delivers, finishes, or
    churns inside a provably-quiet window): an empty ring per chunk.

Scope: the dense `vmapped` and lockstep `batched` engines with
``spill_cap == 0`` (the oracle cannot see a spill buffer), and only
past any configured attack `at_ms` (the FaultInjector perturbs outside
the oracle's view).  The `fast_forward` engine is excluded on purpose:
it already skips quiet windows, and its batch-level `ff_*` metrics
columns record the JUMP pattern — slicing lanes there would change an
artifact the contract pins.  Chaos schedules are safe by construction:
`ChaosProtocol.next_action_time` clamps the oracle at every pending
churn/partition transition, so a lane with adversity still ahead is
never frozen.

The scheduler drives this (`serve/scheduler.py` `_freeze_pass`,
enabled via ``Scheduler(freeze=True)`` / ``WTPU_MEMO=1``); this module
holds the pure synthesis so the tail construction is testable against
the real engines' output bit for bit (tests/test_memo.py:
audit verdicts stay CLEAN and `cross_check_metrics` == []).
"""

from __future__ import annotations

import numpy as np

#: engines whose lanes may freeze (module docstring)
FREEZE_ENGINES = ("vmapped", "batched")


def freeze_supported(spec, cfg) -> bool:
    """Static half of the eligibility gate: engine + spill scope."""
    return spec.engine in FREEZE_ENGINES and cfg.spill_cap == 0


def build_probe(protocol):
    """The per-lane convergence oracle: a jitted, batch-vmapped
    `next_work` at each lane's own clock.  One [B] int fetch per chunk
    boundary; a lane whose every seed's next work lands at or past its
    end is a fixed point."""
    import jax

    from ..core.network import next_work

    return jax.jit(jax.vmap(
        lambda n_, p_: next_work(protocol, n_, p_, n_.time)))


def frozen_final(cfg, state, t_end: int):
    """The lane's end-of-run state, computed in one hop (module
    docstring): `_jump` over the provably-quiet tail — bit-identical to
    stepping it, including broadcast retirement."""
    import jax.numpy as jnp

    from ..core.network import _jump

    net, ps = state
    t2 = jnp.asarray(int(t_end), jnp.int32)
    return _jump(cfg, net, t2 - net.time, t2), ps


def _per_seed(arr):
    """Sum a [w, ...] leaf over every non-lane axis -> [w] int64."""
    a = np.asarray(arr, np.int64)
    return a.reshape(a.shape[0], -1).sum(axis=1) if a.ndim > 1 else a


def frozen_carries(spec, cfg, state, t0: int, n_chunks: int) -> dict:
    """Synthesize the frozen lane's remaining per-chunk obs carries for
    every plane in ``spec.obs`` (module docstring) — host-side numpy,
    once per frozen lane.  `state` is the lane's (net, pstate) slice
    (leading seed axis, width w) at chunk boundary `t0`."""
    import jax

    net = jax.device_get(state[0])
    nodes = net.nodes
    down = np.asarray(nodes.down, bool)
    done_at = np.asarray(nodes.done_at, np.int64)
    w = down.shape[0]
    msg_sent = _per_seed(nodes.msg_sent)
    msg_received = _per_seed(nodes.msg_received)
    bytes_sent = _per_seed(nodes.bytes_sent)
    bytes_received = _per_seed(nodes.bytes_received)
    drops = (_per_seed(net.dropped) + _per_seed(net.bc_dropped) +
             _per_seed(net.clamped) + _per_seed(net.sp_dropped))
    done_count = ((done_at > 0) & ~down).sum(axis=1)
    live_count = (~down).sum(axis=1)
    box = np.asarray(net.box_count, np.int64)
    ring_rows = (box > 0).any(axis=-1).sum(axis=-1)
    ring_occ = box.reshape(w, -1).sum(axis=1)
    spill = (np.asarray(net.sp_arrival, np.int64).reshape(w, -1) >= 0) \
        .sum(axis=1)
    bc_active = np.asarray(net.bc_active, bool).reshape(w, -1)
    bc_time = np.asarray(net.bc_time, np.int64).reshape(w, -1)
    chunk = int(spec.chunk_ms)
    out: dict = {}

    if "metrics" in spec.obs:
        from ..obs.plane import MetricsCarry
        from ..obs.spec import MetricsSpec
        mspec = MetricsSpec(stat_each_ms=spec.stat_each_ms)
        stat = mspec.stat_each_ms
        rows = mspec.n_intervals(chunk)
        const = {
            "msg_sent": msg_sent, "msg_received": msg_received,
            "bytes_sent": bytes_sent, "bytes_received": bytes_received,
            "done_count": done_count, "live_count": live_count,
            "ring_rows": ring_rows, "ring_occupancy": ring_occ,
            "bc_live": None,                # per-row (retirement below)
            "spill_hwm": spill, "drop_count": drops,
            "samples": None, "ff_skipped_ms": None, "ff_jumps": None,
        }
        chunks = []
        for c in range(int(n_chunks)):
            t0c = int(t0) + c * chunk
            series = np.zeros((w, rows, len(mspec.columns)), np.int32)
            for i, name in enumerate(mspec.columns):
                if name == "samples":
                    series[:, :, i] = stat
                elif name in ("ff_skipped_ms", "ff_jumps"):
                    pass            # the dense engines never jump
                elif name == "bc_live":
                    if cfg.bcast_slots > 0:
                        for r in range(rows):
                            # last executed ms of row r; retirement is
                            # the per-ms path's (network._jump): a
                            # record older than the horizon at that ms
                            # is gone
                            tau = t0c + (r + 1) * stat - 1
                            series[:, r, i] = (
                                bc_active &
                                (tau - bc_time < cfg.horizon)
                            ).sum(axis=1)
                else:
                    series[:, :, i] = const[name][:, None]
            chunks.append(MetricsCarry(
                t0=np.full((w,), t0c, np.int32), series=series))
        out["metrics"] = chunks

    if "audit" in spec.obs:
        from ..obs.audit import FIRST_FIELDS, INVARIANTS, AuditCarry
        mono = np.stack([msg_sent, msg_received, bytes_sent,
                         bytes_received, _per_seed(net.dropped),
                         _per_seed(net.bc_dropped),
                         _per_seed(net.clamped),
                         _per_seed(net.sp_dropped)],
                        axis=1).astype(np.int32)
        totals = np.stack([msg_sent, msg_received, drops, done_count],
                          axis=1).astype(np.int32)
        ac = AuditCarry(
            counts=np.zeros((w, len(INVARIANTS)), np.int32),
            first=np.full((w, len(FIRST_FIELDS)), -1, np.int32),
            prev_done=done_at.astype(np.int32),
            prev_counters=mono, totals=totals)
        out["audit"] = [ac] * int(n_chunks)

    if "trace" in spec.obs:
        from ..obs.trace import FIELDS, TraceCarry
        tc = TraceCarry(
            buf=np.zeros((w, spec.trace_capacity, len(FIELDS)),
                         np.int32),
            cursor=np.zeros((w,), np.int32),
            dropped=np.zeros((w,), np.int32),
            down=down.copy())
        out["trace"] = [tc] * int(n_chunks)
    return out
