"""OptimisticP2PSignature — naive signature flooding with verify-at-the-end.

Reference: protocols/OptimisticP2PSignature.java (193 lines).  Every node
floods its own signature; a node forwards each first-seen signature to all
its peers except the sender, with a +1 ms send delay
(onSig, :113-135); at `threshold` distinct signatures it stops forwarding
and sets doneAt = time + 2*pairingTime (:128-131) — the optimistic
aggregate-then-verify costing model described at :14-18.

TPU-native state: `received` is an [N, W]-word bitset; the forward queue
drains one signature id per node per ms (the reference forwards every new
sig in the same event; a same-ms burst here spreads over the next few ms —
statistical equivalence, SURVEY §7.4.3).  The first-arrival source is kept
per signature for the exclude-sender rule, which bounds memory at
[N, N] int32 — this protocol "sends a lot of messages so uses a lot of
memory and [is] slow to test" (:19) in the reference too; it runs at
hundreds-to-low-thousands of nodes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..core import builders, p2p
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import bitset
from ..ops.flat import gather2d, set2d

U32 = jnp.uint32


@struct.dataclass
class OptSigState:
    seed: jnp.ndarray
    peers: jnp.ndarray        # int32 [N, D]
    degree: jnp.ndarray       # int32 [N]
    received: jnp.ndarray     # u32 [N, W] verifiedSignatures
    pending: jnp.ndarray      # u32 [N, W] — received, not yet forwarded
    pending_src: jnp.ndarray  # int32 [N, N] — first sender per sig
    done: jnp.ndarray         # bool [N]


@register
class OptimisticP2PSignature:
    """Parameters mirror OptimisticP2PSignatureParameters (:32-74)."""

    def __init__(self, node_count=100, threshold=99, connection_count=20,
                 pairing_time=1, node_builder_name=None,
                 network_latency_name=None, max_degree=None, inbox_cap=192,
                 drain_rate=4, fanout_pacing_ms=1, horizon=512):
        if node_count > 4096:
            raise ValueError("OptimisticP2PSignature keeps an [N, N] "
                             "first-sender matrix; use <= 4096 nodes")
        self.node_count = node_count
        self.threshold = threshold
        self.connection_count = connection_count
        self.pairing_time = pairing_time
        self.builder = builders.get_by_name(node_builder_name)
        self.latency = latency_mod.get_by_name(network_latency_name)
        self.max_degree = max_degree or max(2 * connection_count,
                                            connection_count + 8)
        # The reference forwards every new signature in the same event; a
        # fixed-shape outbox forwards up to drain_rate queued signatures per
        # ms instead — size it so the early avalanche doesn't strand sigs
        # behind nodes that reach their threshold and stop forwarding.
        self.drain_rate = drain_rate
        # Spreading consecutive peer sends 1 ms apart bounds the per-(node,
        # ms) delivery burst at the avalanche peak (the reference delivers
        # unbounded same-ms bursts; its per-ms bucket is a linked list).
        self.fanout_pacing_ms = fanout_pacing_ms
        self.w = bitset.n_words(node_count)
        # Discard latencies that would outrun the arrival ring (the
        # reference's msgDiscardTime mechanism, core Network.java:36-40):
        # with city+Pareto jitter physics a ~1e-4 tail exceeds 500 ms, and
        # the flood's redundancy makes those copies irrelevant.  The margin
        # keeps pacing delays (<= max_degree * pacing) clamp-free.  Only
        # applied when the ring is big enough that the discard threshold
        # clears every realistic latency; with a small horizon discarding
        # would silently kill most traffic, so fall back to edge-clamping.
        discard = horizon - 2 - self.max_degree * fanout_pacing_ms
        cfg_kw = {"msg_discard_time": discard} if discard >= 500 else {}
        self.cfg = EngineConfig(
            n=node_count, horizon=horizon, inbox_cap=inbox_cap,
            payload_words=1, out_deg=self.max_degree * drain_rate,
            bcast_slots=1, **cfg_kw)

    def init(self, seed):
        n, w = self.node_count, self.w
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        # P2PNetwork(connectionCount, false): average-degree construction.
        peers, degree, _ = p2p.build_peer_graph(
            seed, n, self.connection_count, minimum=False,
            max_degree=self.max_degree)
        ids = jnp.arange(n, dtype=jnp.int32)
        own = bitset.one_bit(ids, w)
        net = init_net(self.cfg, nodes, seed)
        return net, OptSigState(
            seed=seed, peers=peers, degree=degree,
            # Distinct buffers: under donation the same buffer must not
            # appear twice in an executable's arguments.
            received=own, pending=bitset.one_bit(ids, w),
            pending_src=jnp.broadcast_to(ids[:, None], (n, n)) + 0,
            done=jnp.zeros((n,), bool))

    def step(self, p: OptSigState, nodes, inbox, t, key):
        n, w = self.node_count, self.w
        ids = jnp.arange(n, dtype=jnp.int32)
        S = inbox.src.shape[1]

        # Receive, vectorized across ALL inbox slots at once (an unrolled
        # per-slot loop compiles S copies of an [N, N] scatter — minutes of
        # XLA time at S=128).  First-arrival rule (onSig :113-135): mask
        # same-ms duplicate slots with an [S, S] lower-triangular equality
        # sweep, making (node, sig) indices UNIQUE — the only scatter form
        # the TPU backend lowers without serialization (ops/flat.py).
        ok = inbox.valid & (~p.done & ~nodes.down)[:, None]    # [N, S]
        sig = jnp.clip(inbox.data[:, :, 0], 0, n - 1)          # [N, S]
        src = jnp.clip(inbox.src, 0, n - 1)
        earlier = jnp.tril(jnp.ones((S, S), bool), k=-1)       # [s, s'<s]
        dup = jnp.any((sig[:, :, None] == sig[:, None, :]) &
                      ok[:, None, :] & earlier[None], axis=2)  # [N, S]
        word = gather2d(p.received, ids[:, None], sig // 32)
        had = ((word >> (sig % 32).astype(U32)) & U32(1)) != 0
        new = ok & ~dup & ~had                                 # [N, S]

        # Word updates without scatter: [N, S, W] one-hot OR-reduce.
        bmask = jnp.where(new, U32(1) << (sig % 32).astype(U32), U32(0))
        words = jnp.where(
            (sig // 32)[:, :, None] ==
            jnp.arange(w, dtype=jnp.int32)[None, None, :],
            bmask[:, :, None], U32(0))                         # [N, S, W]
        new_words = jax.lax.reduce(words, U32(0), jax.lax.bitwise_or, (1,))
        received = p.received | new_words
        pending = p.pending | new_words
        pending_src = set2d(p.pending_src, ids[:, None], sig, src, ok=new)

        # done at threshold: stop accepting new sigs, doneAt = t +
        # 2*pairing (:128-131).  Already-queued forwards keep draining —
        # the reference forwarded them at accept time, before done.
        count = bitset.popcount(received)
        done_now = ~p.done & (count >= self.threshold)
        done = p.done | done_now
        nodes = nodes.replace(done_at=jnp.where(
            done_now & (nodes.done_at == 0),
            jnp.maximum(1, t + 2 * self.pairing_time),
            nodes.done_at).astype(jnp.int32))
        # Sigs accepted before crossing the threshold were already
        # committed to forwarding by the reference (onSig forwards at
        # accept time, before setting done) — the queue keeps draining;
        # only NEW receipts stop (the ~done gate in the receive loop).

        # Forward up to drain_rate pending sigs per node per ms (lowest id
        # first), each fanned out to all peers except its first sender.
        D = self.max_degree
        dests, pls, sizes_, delays = [], [], [], []
        fan_cfg = EngineConfig(n=n, out_deg=D, payload_words=1)
        for _ in range(self.drain_rate):
            has = jnp.any(pending != 0, axis=1)
            word_has = pending != 0
            first_word = jnp.argmax(word_has, axis=1).astype(jnp.int32)
            word = jnp.take_along_axis(pending, first_word[:, None],
                                       axis=1)[:, 0]
            low = word & (~word + U32(1))      # lowest set bit
            bitpos = 31 - jax.lax.clz(
                jnp.maximum(low, U32(1)).astype(jnp.int32))
            pick = jnp.clip(first_word * 32 + bitpos.astype(jnp.int32),
                            0, n - 1)
            exclude = pending_src.reshape(-1)[ids * n + pick]
            payload = pick[:, None].astype(jnp.int32)
            d_, p_, s_, dl_ = p2p.flood_fanout(
                fan_cfg, p.peers, has, exclude, payload, p.seed, t,
                local_delay=1, delay_between=self.fanout_pacing_ms,
                size=4 + 48)
            dests.append(d_); pls.append(p_)
            sizes_.append(s_); delays.append(dl_)
            clear = bitset.one_bit(pick, w)
            pending = jnp.where(has[:, None], pending & ~clear, pending)

        out = empty_outbox(self.cfg).replace(
            dest=jnp.concatenate(dests, axis=1),
            payload=jnp.concatenate(pls, axis=1),
            size=jnp.concatenate(sizes_, axis=1),
            delay=jnp.concatenate(delays, axis=1))
        return (p.replace(received=received, pending=pending,
                          pending_src=pending_src, done=done), nodes, out)

    def done_pred(self, pstate, nodes):
        return jnp.all(nodes.down | pstate.done)


def cont_if_optimistic(net, pstate):
    live = ~net.nodes.down
    return jnp.any(live & ~pstate.done)
