#!/bin/bash
# Round-9 on-chip measurement session — run when .tpu_up appears.
# ORDER IS THE POINT (VERDICT r4 #2): the official bench number first,
# then this round's addition (the fused Pallas routing megakernel
# A/B), then the deferred pallas VMEM cost-model validation carried
# over from r8 (merge/score/gsf constants + the NEW route_row_bytes
# model) — the host-side _pick_block gate ships in PR 1/5/9, the
# on-chip Mosaic compile is the half only this session can do.
#
# Usage: nohup bash tools/run_measurements_r9.sh > reports/r9_onchip.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
R=reports
mkdir -p "$R"
stamp() { date -u +%H:%M:%S; }

echo "=== r9 on-chip session start $(stamp)"

# 1. OFFICIAL bench, unchanged engine defaults (batched superstep=2,
#    XLA routing — route_kernel=xla in the line).  Directly comparable
#    with r8; the new sort_ops_per_sim_ms field records the XLA
#    baseline the kernel removes.
echo "--- [1/6] official 2048x16 (xla route baseline) $(stamp)"
timeout 3600 python bench.py 2>&1 | tee "$R/bench_r9_official.log"

# 2. THE headline A/B: XLA vs Pallas route on the batched Handel
#    headline at K in {1, 4, 8}.  K=1 isolates the per-ms kernel win;
#    K=4/8 show what remains once superstep amortization already took
#    its share (chunk 240 admits every K and keeps phase
#    specialization on; the fixed-16 latency model licenses K=8).
#    K=1 runs the vmapped engine (the batched twin is hard-wired to
#    K>=2) — compare the xla/pallas pair WITHIN each K, not across
#    engines.  Every line carries route_kernel + sort_ops_per_sim_ms,
#    so the win is attributable from the JSON alone.
echo "--- [2/6] route A/B Handel batched headline $(stamp)"
for K in 1 4 8; do
  for RK in 0 1; do
    echo "--- K=$K pallas_route=$RK $(stamp)"
    WTPU_SUPERSTEP=$K WTPU_BENCH_CHUNK=240 WTPU_PALLAS_ROUTE=$RK \
      WTPU_BENCH_LATENCY='NetworkFixedLatency(16)' \
      timeout 3600 python bench.py 2>&1 \
      | tee "$R/bench_r9_handel_k${K}_route${RK}.log"
  done
done

# 3. P2PFlood route A/B (the second acceptance protocol: flood-shaped
#    traffic, every node fanning out per ms — the binning-bound
#    extreme).  Quiet-proto bench path, K=4 on the floor-rich model.
echo "--- [3/6] route A/B P2PFlood $(stamp)"
for RK in 0 1; do
  WTPU_BENCH_PROTO=p2pflood WTPU_BENCH_NODES=1024 WTPU_SUPERSTEP=4 \
    WTPU_BENCH_LATENCY='NetworkFixedLatency(8)' WTPU_PALLAS_ROUTE=$RK \
    timeout 1800 python bench.py 2>&1 \
    | tee "$R/bench_r9_p2pflood_route${RK}.log" || true
done

# 4. Bit-identity ON CHIP (the CPU suite pins interpret mode; this
#    pins the Mosaic lowering): the divergence bisector must exit 0
#    for xla-vs-pallas route at the headline shape.
echo "--- [4/6] route bit-identity bisector on-chip $(stamp)"
timeout 1800 python tools/divergence.py --proto handel --nodes 2048 \
  --ms 400 --a superstep=4,batched --b superstep=4,batched,pallas_route \
  --latency 'NetworkFixedLatency(16)' 2>&1 \
  | tee "$R/divergence_r9_route.log" || true

# 5. Pallas VMEM cost-model validation — STILL PENDING FROM r8 (the
#    r8 session never ran on-chip): merge/score/gsf constants PLUS the
#    new route_row_bytes model.  tools/pallas_validate_tpu.py compiles
#    the kernels at ladder block sizes and records requested
#    scoped-vmem vs the named models; a model that underestimates
#    shows up as a Mosaic OOM the host gate (_pick_block
#    on_over="warn" leg) predicted would fit.
echo "--- [5/6] pallas VMEM model validation (r8 backlog + route) $(stamp)"
timeout 3600 python tools/pallas_validate_tpu.py 2>&1 \
  | tee "$R/pallas_validate_r9.log"

# 6. Tracked-config suite (serve smoke + audit smoke included) with
#    the route kernel ON — ring_conservation must stay clean on real
#    hardware, not just under the interpreter.
echo "--- [6/6] bench_suite with pallas route $(stamp)"
WTPU_PALLAS_ROUTE=1 timeout 7200 python tools/bench_suite.py 2>&1 \
  | tee "$R/bench_suite_r9_route.log"

echo "=== r9 on-chip session done $(stamp)"
