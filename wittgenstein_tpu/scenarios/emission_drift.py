"""Stored vs hashed emission-order drift quantification (VERDICT r1 #4).

The stored emission mode reproduces the reference's per-(sender, level)
emission lists sorted by the rank each RECEIVER assigns to the sender
(Handel.java:991-1013) — a convergence optimization: early receivers
verify the sender's aggregate sooner because they score it higher.  The
hashed mode (the >32k-node path — no O(N^2) emission state) replaces the
list with a keyed level permutation: plain randomized round-robin, losing
that correlation.

This tool measures the cost: same config, both modes, a batch of seeds
each; reports the doneAt distribution over live nodes (mean / p50 / p90 /
p99 / max, completion fraction) and the relative drift.  Run:

    python -m wittgenstein_tpu.scenarios.emission_drift [out_dir] \
        [nodes] [seeds]

Results land in `<out_dir>/emission_drift_<nodes>n.csv` and are printed
as one JSON line per mode.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..core.harness import run_multiple_times
from ..models.handel import Handel, cont_if_handel
from ..tools.csvf import CSVFormatter
from .handel_scenarios import default_params


def run_mode(mode, nodes=2048, seeds=32, max_time=6000, chunk=250,
             first_seed=0, attack=None, dead_ratio=None,
             seed_batch=None):
    """One emission mode; `attack` in (None, "byzantine_suicide",
    "hidden_byzantine") turns the dead fraction into attackers — the
    rank-prioritized stored ordering matters most under attack (VERDICT
    r2 weak #5), so the drift must be measured there too.

    `seed_batch` caps the vmapped batch; larger seed counts run as
    SEQUENTIAL microbatches (deterministic, so exactly equivalent to
    one batch).  Required at >= 8192 nodes in stored mode: the
    [R, N, N] emission matrix is 268 MB per seed there, and one
    multi-seed batch in a single buffer is what OOM'd the r4
    8192-node on-chip attempt."""
    kw = {} if dead_ratio is None else {"dead_ratio": dead_ratio}
    params = default_params(nodes=nodes, **kw)
    if attack:
        params[attack] = True
    params["emission_mode"] = mode
    proto = Handel(**params)
    sb = seeds if seed_batch is None else min(seed_batch, seeds)
    assert seeds % sb == 0
    t0 = time.perf_counter()
    ld_parts, evicted = [], 0
    for b in range(seeds // sb):
        res = run_multiple_times(proto, run_count=sb, max_time=max_time,
                                 chunk=chunk, cont_if=cont_if_handel,
                                 first_seed=first_seed + b * sb)
        done_at = np.asarray(res.nets.nodes.done_at)
        down = np.asarray(res.nets.nodes.down)
        ld_parts += [done_at[i][~down[i]] for i in range(sb)]
        evicted += int(np.asarray(res.pstates.evicted).sum())
    wall = time.perf_counter() - t0
    live_done = np.concatenate(ld_parts)
    finished = live_done[live_done > 0]
    frac = finished.size / live_done.size
    nan = float("nan")
    q = (lambda p: float(np.percentile(finished, p)) if finished.size
         else nan)
    return {
        "mode": mode, "nodes": nodes, "seeds": seeds,
        "frac_done": round(frac, 4),
        "mean_ms": round(float(finished.mean()), 1) if finished.size
        else nan,
        "p50_ms": round(q(50), 1), "p90_ms": round(q(90), 1),
        "p99_ms": round(q(99), 1),
        "max_ms": float(finished.max()) if finished.size else nan,
        "evicted": evicted,
        "wall_s": round(wall, 1),
    }


def compare(nodes=2048, seeds=32, max_time=6000, out_dir=".", attack=None,
            dead_ratio=None, seed_batch=None):
    if seed_batch is None and nodes >= 8192:
        # Keep the stored-emission [R, N, N] matrix under the runtime's
        # ~1 GB single-buffer limit (268 MB/seed at 8192).
        seed_batch = max(1, (768 << 20) // (4 * nodes * nodes))
        while seeds % seed_batch:
            seed_batch -= 1
    csv = CSVFormatter(["mode", "nodes", "seeds", "frac_done", "mean_ms",
                        "p50_ms", "p90_ms", "p99_ms", "max_ms", "evicted",
                        "wall_s"])
    rows = {}
    for mode in ("stored", "hashed"):
        r = run_mode(mode, nodes=nodes, seeds=seeds, max_time=max_time,
                     attack=attack, dead_ratio=dead_ratio,
                     seed_batch=seed_batch)
        r["attack"] = attack or "none"
        rows[mode] = r
        csv.add(**r)                 # unknown keys are ignored by add()
        print(json.dumps(r))
    drift_mean = rows["hashed"]["mean_ms"] / rows["stored"]["mean_ms"] - 1
    drift_p90 = rows["hashed"]["p90_ms"] / rows["stored"]["p90_ms"] - 1
    print(json.dumps({"attack": attack or "none", "nodes": nodes,
                      "drift_mean_pct": round(100 * drift_mean, 2),
                      "drift_p90_pct": round(100 * drift_p90, 2)}))
    suffix = f"_{attack}" if attack else ""
    import os
    os.makedirs(out_dir, exist_ok=True)
    csv.save(f"{out_dir}/emission_drift_{nodes}n{suffix}.csv")
    return rows


if __name__ == "__main__":
    import sys
    out = sys.argv[1] if len(sys.argv) > 1 else "."
    nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    seeds = int(sys.argv[3]) if len(sys.argv) > 3 else 32
    compare(nodes=nodes, seeds=seeds, out_dir=out)
