"""Rule ``audit_zero_cost`` — the invariant audit plane may never
silently tax an unaudited build, and may never silently die.

Sibling of `trace_zero_cost` (rules_trace.py) and `metrics_zero_cost`
(rules_metrics.py), for the AUDIT plane (wittgenstein_tpu/obs/audit.py).
The contract is two-sided:

  * audit-OFF builds carry ZERO monitor residue.  The engine's `tap`
    hook defaults to None — a plain Python branch, so the
    uninstrumented program is the historical one BY CONSTRUCTION; this
    rule makes that structural claim an enforced ratchet: the chunk's
    outermost scan/while carry width over the state leaf count
    (`carry_extra_leaves`) is measured on every pre-existing target and
    budgeted at its known instrumentation, so a tap accidentally left
    threaded into a production builder fails the gate with the measured
    width;
  * an ``+audit`` target whose loop carry does NOT widen by the
    `AuditCarry` leaves (counts + first + prev_done + prev_counters +
    totals = 5) has silently-dead monitors — an error, not a budget.
"""

from __future__ import annotations

from .framework import Rule, register_rule
from .rules_metrics import zero_cost_findings

#: AuditCarry contributes this many pytree leaves (counts, first,
#: prev_done, prev_counters, totals).
_AUDIT_CARRY_LEAVES = 5

#: analysis target-name suffix of the audited builds
AUDIT_SUFFIX = "+audit"


@register_rule
class AuditZeroCostRule(Rule):
    name = "audit_zero_cost"
    scope = "protocol"
    budgeted_metrics = ("carry_extra_leaves", "jaxpr_eqns")

    def run(self, target, budget):
        return zero_cost_findings(
            self.name, target, AUDIT_SUFFIX, _AUDIT_CARRY_LEAVES,
            lambda extra: (
                f"audited target carries only {extra} extra loop "
                f"vars (< {_AUDIT_CARRY_LEAVES}: the AuditCarry "
                "leaves) — the invariant monitors are silently "
                "dead in this build"))
