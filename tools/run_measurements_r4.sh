#!/bin/bash
# Round-4 serialized measurement queue — TPU-first (each item probes the
# tunnel itself; CPU fallback only where the tool supports it).  Ordered
# by evidence value; logs land in reports/, each tool writes its own
# report.  Run from anywhere: cd's to the repo root.
cd "$(dirname "$0")/.."

echo "[q] 1M cardinal on the REAL chip (tier-3 evidence)"
WTPU_CARDINAL_PLATFORM=tpu python tools/cardinal_1m.py 120 \
    > reports/cardinal_1m_tpu.log 2>&1

echo "[q] on-chip op profile (superstep=2)"
python tools/tpu_profile.py > reports/profile_r4.log 2>&1

echo "[q] 256-seed microbatched headline (2048n, 16x16)"
WTPU_BENCH_SEEDS=256 WTPU_BENCH_SEED_BATCH=16 python bench.py \
    > reports/bench_r4_256seed.log 2>&1

echo "[q] tier-2 exact-hashed 16384n on the chip"
WTPU_BENCH_NODES=16384 WTPU_BENCH_SEEDS=1 WTPU_BENCH_MS=2000 \
    WTPU_BENCH_REPS=1 WTPU_BENCH_EMISSION=hashed \
    python bench.py > reports/bench_r4_exact16k.log 2>&1

echo "[q] tier-2 exact-hashed 32768n attempt (q_sig 939 MB at Q=7,"
echo "    pool off: the [N,R,W] pool alone would be 1.9 GB)"
WTPU_BENCH_NODES=32768 WTPU_BENCH_SEEDS=1 WTPU_BENCH_MS=2400 \
    WTPU_BENCH_REPS=1 WTPU_BENCH_EMISSION=hashed WTPU_BENCH_POOL=0 \
    WTPU_BENCH_QUEUE=7 WTPU_BENCH_BOX_SPLIT=2 \
    python bench.py > reports/bench_r4_exact32k.log 2>&1

echo "[q] tracked-config suite (PingPong/GSF/SanFermin/Dfinity)"
python tools/bench_suite.py > reports/bench_suite_r4.jsonl 2>&1

echo "[q] dfinity variance (32 seeds x 300 s)"
python tools/dfinity_variance.py 32 300 > reports/dfinity_variance.log 2>&1

echo "[q] reference-scale scenario sweeps (2048 x 8)"
python tools/scenario_sweeps_2048.py > reports/sweeps_2048.log 2>&1

echo "[q] emission drift 8192 honest x 8 seeds (device if up)"
python -m wittgenstein_tpu.scenarios.emission_drift reports 8192 8 \
    > reports/emission_8192.log 2>&1

echo "[q] emission drift attacks at 1024 x 8 seeds"
python - > reports/emission_attacks.log 2>&1 <<'EOF'
from wittgenstein_tpu.scenarios.emission_drift import compare
compare(nodes=1024, seeds=8, max_time=10000, out_dir="reports",
        attack="byzantine_suicide", dead_ratio=0.25)
compare(nodes=1024, seeds=8, max_time=10000, out_dir="reports",
        attack="hidden_byzantine", dead_ratio=0.25)
EOF

echo "[q] done"
