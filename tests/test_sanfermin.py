"""San Fermín tests — geometry unit tests (SanFerminHelper analogue) +
run-to-done + determinism for both variants."""

import pytest

import jax.numpy as jnp
import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.sanfermin import (
    SanFermin, SanFerminCappos, _cand_base, _half, _own_base, _pick_offset)


def test_geometry():
    # 16 nodes, bits = 4.  Node 5 = 0101.
    bits = 4
    ids = jnp.asarray([5])
    # cpl = 3: half = 1, buddy differs in last bit -> candidate base 4.
    h3 = _half(bits, jnp.asarray([3]))
    assert int(h3[0]) == 1
    assert int(_cand_base(ids, h3)[0]) == 4
    # cpl = 2: half = 2, own block [4,6) -> sibling [6,8).
    h2 = _half(bits, jnp.asarray([2]))
    assert int(h2[0]) == 2
    assert int(_own_base(ids, h2)[0]) == 4
    assert int(_cand_base(ids, h2)[0]) == 6
    # cpl = 0: half = 8, sibling is the other half of the network.
    h0 = _half(bits, jnp.asarray([0]))
    assert int(_cand_base(ids, h0)[0]) == 8


def test_pick_order():
    # Mirror (partner offset) first, then the remaining offsets in the
    # per-node ROTATION (partner + j) mod half — pick j is a bijection
    # between requesters and candidates, which is what keeps same-tick
    # fan-in at candidate_count + 1 instead of half-block (see
    # _pick_offset; the reference's index-order walk relies on
    # unbounded queues to absorb the difference).
    half = jnp.asarray([4])
    po = jnp.asarray([2])
    picks = [int(_pick_offset(jnp.asarray([j]), po, half)[0])
             for j in range(4)]
    assert picks == [2, 3, 0, 1]
    assert sorted(picks) == [0, 1, 2, 3]        # full walk, no repeats
    # Bijection across requesters at every pick index j: distinct
    # partners map to distinct candidates.
    for j in range(4):
        offs = [int(_pick_offset(jnp.asarray([j]), jnp.asarray([p]),
                                 half)[0]) for p in range(4)]
        assert sorted(offs) == [0, 1, 2, 3]


@pytest.mark.slow
def test_sanfermin_run_and_determinism():
    p = SanFermin(node_count=128, threshold=128, pairing_time=2,
                  reply_timeout=300, candidate_count=1,
                  network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    for _ in range(12):
        net, ps = r.run_ms(net, ps, 250)
        if bool(jnp.all(ps.done)):
            break
    assert bool(jnp.all(ps.done)), "all nodes finish level 0"
    assert int(net.dropped) == 0 and int(net.clamped) == 0
    agg = np.asarray(ps.agg)
    # Every node aggregated the full network (no failures configured).
    assert np.all(agg == 128)
    done_at = np.asarray(net.nodes.done_at)
    assert np.all(done_at > 0)

    # Determinism.
    net2, ps2 = p.init(0)
    for _ in range(12):
        net2, ps2 = r.run_ms(net2, ps2, 250)
        if bool(jnp.all(ps2.done)):
            break
    assert np.array_equal(np.asarray(net2.nodes.done_at), done_at)


@pytest.mark.slow
def test_cappos_run():
    p = SanFerminCappos(node_count=64, threshold=48, pairing_time=2,
                        timeout=150, candidate_count=4,
                        network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    for _ in range(12):
        net, ps = r.run_ms(net, ps, 250)
        if bool(jnp.all(ps.done)):
            break
    assert bool(jnp.all(ps.done))
    assert int(net.dropped) == 0
    # Threshold tracking fired for everyone (64-node full run covers 48).
    assert np.all(np.asarray(ps.threshold_at) > 0)
    assert np.all(np.asarray(net.nodes.done_at) > 0)
