"""One-shot data-vendoring script: convert the reference's public measurement
data (WonderNetwork city ping CSVs + world-cities geo CSV) into one compact
`wittgenstein_tpu/data/citydata.npz` so the framework is standalone.

Semantics replicated (not code):
  - tools/CSVLatencyReader.java: per-city Ping.csv, column 4 = avg RTT ms;
    city name matched by longest contained name ('+' means space); same-city
    RTT = 30 ms; cities missing a measurement in BOTH directions vs any other
    city are pruned from the matrix.
  - geoinfo/GeoAllCities.java: cities.csv (name, lat, long, population);
    population + 200000 offset; x = (long+180)*(W/360) then -45 (west half)
    or -70 (east half); y = H/2 - lat*H/180 then -35 if y < 0.2*H.

Run: python tools/vendor_city_data.py [reference_root]
"""

from __future__ import annotations

import csv
import os
import sys

import numpy as np

MAX_X, MAX_Y = 2000, 1112
SAME_CITY_RTT = 30.0

REF = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
RES = os.path.join(REF, "core", "src", "main", "resources")
OUT = os.path.join(os.path.dirname(__file__), "..", "wittgenstein_tpu",
                   "data", "citydata.npz")


def read_geo():
    geo = {}
    with open(os.path.join(RES, "cities.csv"), newline="",
              encoding="utf-8") as f:
        rd = csv.reader(f)
        next(rd)  # header
        for row in rd:
            name = row[0].replace(" ", "+")
            lat, lng, pop = float(row[1]), float(row[2]), int(row[3])
            x = int((lng + 180) * (MAX_X / 360.0))
            x += -45 if x < MAX_X / 2 else -70
            y = int(round(MAX_Y / 2.0 - lat * MAX_Y / 180.0))
            if y < 0.2 * MAX_Y:
                y -= 35
            geo[name] = (max(1, min(MAX_X, x)), max(1, min(MAX_Y, y)),
                         pop + 200_000)
    return geo


def read_pings():
    data_dir = os.path.join(RES, "Data")
    cities = sorted(os.listdir(data_dir))
    # Longest-contained-name matching, as the reference does.
    by_space = [(c, c.replace("+", " ")) for c in cities]
    lat = {c: {} for c in cities}
    for c in cities:
        path = os.path.join(data_dir, c, c + "Ping.csv")
        with open(path, newline="", encoding="utf-8") as f:
            rd = csv.reader(f)
            next(rd)
            for row in rd:
                loc = row[0]
                best = None
                for name, spaced in by_space:
                    if spaced in loc and (best is None or
                                          len(name) > len(best)):
                        best = name
                if best is not None:
                    lat[c][best] = float(row[4])
        lat[c][c] = SAME_CITY_RTT
    # Prune cities with measurements missing in both directions.
    while True:
        bad = {a for a in lat
               for b in lat if b not in lat[a] and a not in lat[b]}
        if not bad:
            break
        for b in bad:
            del lat[b]
    kept = sorted(lat)
    n = len(kept)
    m = np.zeros((n, n), np.float32)
    for i, a in enumerate(kept):
        for j, b in enumerate(kept):
            v = lat[a].get(b)
            if v is None:
                v = lat[b][a]
            m[i, j] = v
    return kept, m


def main():
    geo = read_geo()
    kept, rtt = read_pings()
    # The canonical city set: latency-complete AND geo-known (the reference's
    # NodeBuilderWithCity intersects CSVLatencyReader.cities() with the geo
    # map the same way).
    idx = [i for i, c in enumerate(kept) if c in geo]
    names = [kept[i] for i in idx]
    rtt = rtt[np.ix_(idx, idx)]
    x = np.array([geo[c][0] for c in names], np.int32)
    y = np.array([geo[c][1] for c in names], np.int32)
    pop = np.array([geo[c][2] for c in names], np.int64)
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    np.savez_compressed(OUT, names=np.array(names), x=x, y=y, population=pop,
                        rtt=rtt)
    print(f"wrote {OUT}: {len(names)} cities, rtt {rtt.shape}, "
          f"range [{rtt.min():.1f}, {rtt.max():.1f}] ms")


if __name__ == "__main__":
    main()
