"""Multi-tenant load generator for the serve request plane.

Hundreds of concurrent mixed-compile-key `ScenarioSpec`s across >= 3
tenants hammer ONE auto-draining `serve.Service` from client threads —
the shape a production deployment actually sees — and the run records
the tenancy plane's honest numbers (BENCH_NOTES.md r15 `tenancy`
block):

  * p50/p99 submit->result latency per tenant and overall (wall clock
    from the client's submit call to its poll observing "done");
  * rejection rate: 429-equivalent `AdmissionError`s per tenant
    (clients back off `retry_after_s` and retry, bounded — the
    admission-control round trip, not a crash);
  * preemption count (scheduler chunk-boundary yields) and per-tenant
    completion counts (zero starvation is asserted: every tenant's
    every request eventually completes);
  * compile amortization: completed requests per program build — the
    coalescing story under tenancy (tenancy fields are NOT in the
    compile key, so mixed tenants still share programs).

Tenant mix (weights/budgets exercise every tenancy mechanism):
  interactive — weight 4, short single-seed specs, deadline-carrying;
  campaign    — weight 1, BOUNDED queue (max_queued; the 429 source),
                wider multi-seed specs;
  batch       — weight 2, unbounded, mixed spans.

Usage: python tools/serve_load.py [--requests N] [--out PATH]
       [--stream] [--kill-after S] [--timeline DIR]
       (default 120 requests; --out writes the JSON line to a file
       as well as stdout; --stream adds the long-poll partial-metrics
       smoke check: one spec streamed boundary by boundary over
       `/w/batch/stream`-equivalent `Service.stream`, asserting one
       delta per chunk; --kill-after S hard-stops the clients after S
       seconds and reports the `/w/batch/health` snapshot taken at
       the kill — the crash-safety observability block under real
       load: uptime, queue depths, journal lag, quarantine count,
       watchdog trips, chunk-wall EMA; --timeline DIR turns the host
       flight recorder ON — span JSONL per process under DIR plus one
       merged Perfetto timeline.json where the request-lifecycle host
       spans and one probe request's device trace-ring/metrics lanes
       render together)
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax                                        # noqa: E402

import wittgenstein_tpu.models                    # noqa: E402, F401
from wittgenstein_tpu.serve import (              # noqa: E402
    AdmissionError, ScenarioSpec, Scheduler, Service)

#: the three compile keys of the mix (latency_model is program-
#: affecting) — mixed keys make the DRR/preemption path do real work
LATENCIES = (None, "NetworkFixedLatency(10)", "NetworkFixedLatency(30)")


def tenant_specs(name: str, count: int):
    """The tenant's request list (deterministic — seeds/spans derive
    from the request index, so two runs of the generator submit the
    same work)."""
    out = []
    for i in range(count):
        lat = LATENCIES[i % len(LATENCIES)]
        if name == "interactive":
            out.append(ScenarioSpec(
                protocol="PingPong", params={"node_count": 64},
                seeds=(i,), sim_ms=80, chunk_ms=40, obs=("metrics",),
                latency_model=lat, tenant=name, priority=2,
                deadline_ms=60_000))
        elif name == "campaign":
            out.append(ScenarioSpec(
                protocol="PingPong", params={"node_count": 64},
                seeds=(100 + 2 * i, 101 + 2 * i), sim_ms=160,
                chunk_ms=40, obs=("metrics",), latency_model=lat,
                tenant=name))
        else:
            out.append(ScenarioSpec(
                protocol="PingPong", params={"node_count": 64},
                seeds=(500 + i,), sim_ms=120 if i % 2 else 80,
                chunk_ms=40, obs=("metrics",), latency_model=lat,
                tenant=name))
    return out


def drive_tenant(svc, specs, rec, poll_s=0.02, max_attempts=50,
                 stop=None):
    """One tenant's client thread: submit each spec (backing off on
    429s), poll to completion, record the submit->result wall.  A set
    `stop` event (--kill-after) abandons the remaining work — the
    hard-stop shape a killed client population actually has."""
    for spec in specs:
        if stop is not None and stop.is_set():
            return
        t0 = time.perf_counter()
        rid = None
        for _ in range(max_attempts):
            try:
                rid = svc.submit(spec.to_json())["id"]
                break
            except AdmissionError as e:
                rec["rejected"] += 1
                time.sleep(min(e.retry_after_s, 0.5))
                if stop is not None and stop.is_set():
                    return
        if rid is None:
            rec["gave_up"] += 1
            continue
        while True:
            st = svc.status(rid)
            if st["status"] in ("done", "error"):
                break
            if stop is not None and stop.is_set():
                return
            time.sleep(poll_s)
        if st["status"] == "done":
            rec["done"] += 1
            rec["lat_ms"].append(1e3 * (time.perf_counter() - t0))
        else:
            rec["errors"] += 1


def stream_smoke(svc) -> dict:
    """The --stream check: submit one multi-chunk spec to the
    auto-draining service and LONG-POLL its per-chunk totals until
    eof; a healthy stream yields exactly sim_ms/chunk_ms boundary
    entries with monotone times and per-chunk deltas.  Returns the
    JSON block (``ok`` False on any shortfall)."""
    spec = ScenarioSpec(protocol="PingPong", params={"node_count": 64},
                        seeds=(0,), sim_ms=160, chunk_ms=40,
                        obs=("metrics",), tenant="stream")
    rid = svc.submit(spec.to_json())["id"]
    chunks, polls = [], 0
    after = None
    t0 = time.perf_counter()
    while True:
        out = svc.stream(rid, after_ms=after, timeout_s=10.0)
        polls += 1
        chunks += out["chunks"]
        after = out["next_after_ms"]
        if out["eof"] or polls > 64:
            break
    wall = time.perf_counter() - t0
    times = [c["t_ms"] for c in chunks]
    want = spec.sim_ms // spec.chunk_ms
    ok = (times == sorted(set(times)) and len(chunks) == want
          and all("delta" in c and "totals" in c for c in chunks)
          and out["eof"])
    return {"ok": ok, "chunks": len(chunks), "expected": want,
            "polls": polls, "wall_s": round(wall, 3),
            "final_totals": chunks[-1]["totals"] if chunks else None}


def fleet_tenants() -> dict:
    """The tenancy policies of the mix, shared by the single-process
    scheduler and the fleet front tier so both runs refuse/weight the
    same way."""
    return {"interactive": {"weight": 4},
            "campaign": {"weight": 1, "max_queued": 4,
                         "retry_after_s": 0.2},
            "batch": {"weight": 2}}


def timeline_probe(sch, timeline_dir) -> dict:
    """The device-merge exercise behind --timeline: run ONE probe
    request with the trace ring and metrics plane compiled in
    (`keep_carries=True` keeps the raw per-chunk carries on the
    finished record), rebuild its device Perfetto lanes, and merge
    them with every span log under `timeline_dir` into one
    ``timeline.json`` — host queue->compile->launch->chunks->settle
    over wall time next to the engine's simulated-time lanes."""
    import glob
    import os

    from wittgenstein_tpu.obs.decode import TraceFrame
    from wittgenstein_tpu.obs.export import (MetricsFrame,
                                             spans_to_perfetto,
                                             to_perfetto,
                                             trace_to_perfetto)
    from wittgenstein_tpu.obs.spans import read_spans
    from wittgenstein_tpu.obs.spec import MetricsSpec
    from wittgenstein_tpu.obs.trace import TraceSpec

    spec = ScenarioSpec(protocol="PingPong", params={"node_count": 64},
                        seeds=(7,), sim_ms=120, chunk_ms=40,
                        obs=("metrics", "trace"), tenant="batch")
    rid = sch.submit(spec, keep_carries=True, label="timeline-probe")
    deadline = time.time() + 120.0
    while time.time() < deadline:
        sch.run_pending()
        req = sch.peek(rid)
        if req is not None and req.status in ("done", "error"):
            break
        time.sleep(0.02)
    device = []
    req = sch.peek(rid)
    if req is not None and req.status == "done" and req.final_carries:
        carries = req.final_carries
        if "metrics" in carries:
            mf = MetricsFrame.from_carries(
                MetricsSpec(stat_each_ms=spec.stat_each_ms),
                carries["metrics"])
            device.append(to_perfetto(mf))
        if "trace" in carries:
            tf = TraceFrame.from_carries(
                TraceSpec(capacity=spec.trace_capacity),
                carries["trace"])
            device.append(trace_to_perfetto(tf))
    rows = []
    files = sorted(glob.glob(os.path.join(timeline_dir, "**",
                                          "spans*.jsonl"),
                             recursive=True))
    for f in files:
        rows.extend(read_spans(f))
    out = os.path.join(timeline_dir, "timeline.json")
    trace = spans_to_perfetto(rows, device=device, path=out)
    return {"path": out, "span_logs": len(files), "spans": len(rows),
            "device_lanes": len(device), "probe_rid": rid,
            "events": len(trace["traceEvents"])}


def fleet_load_once(workers: int, per: int, *, base_dir,
                    lease_ttl_s: float = 10.0,
                    ready_timeout_s: float = 300.0,
                    timeline=None) -> dict:
    """One fleet measurement: spawn `workers` worker processes over a
    fresh fleet directory, wait until every worker has published a
    stats snapshot (measuring steady-state submit->result throughput,
    not worker cold-start), then run the SAME three-tenant client mix
    through a `FleetService` front tier and report per-worker-count
    latency/throughput/builds."""
    import glob
    import os

    from wittgenstein_tpu.serve import FleetService
    from wittgenstein_tpu.serve.fleet import (aggregate_worker_stats,
                                              fleet_paths, spawn_worker)

    d = os.path.join(base_dir, f"fleet-{workers}w")
    svc = FleetService(d, tenants=fleet_tenants())
    tdir = None
    if timeline is not None:
        # one span-log dir per worker count: the same worker ids recur
        # across the sweep, and two counts appending into one file
        # would interleave unrelated runs on one timeline
        tdir = os.path.join(timeline, f"{workers}w")
        os.makedirs(tdir, exist_ok=True)
    procs = [spawn_worker(d, f"w{i}", lease_ttl_s=lease_ttl_s,
                          idle_exit_s=4.0, max_wall_s=900.0,
                          timeline=tdir)
             for i in range(workers)]
    stats_glob = os.path.join(fleet_paths(d)["stats_dir"],
                              "worker-*.json")
    t_ready = time.time()
    while len(glob.glob(stats_glob)) < workers:
        if time.time() - t_ready > ready_timeout_s:
            for p in procs:
                p.terminate()
            raise RuntimeError(
                f"fleet-load: only {len(glob.glob(stats_glob))}/"
                f"{workers} workers became ready in "
                f"{ready_timeout_s:.0f}s; see worker logs in {d}")
        if all(p.poll() is not None for p in procs):
            raise RuntimeError(
                f"fleet-load: every worker exited before becoming "
                f"ready; see worker logs in {d}")
        time.sleep(0.1)
    recs = {name: {"submitted": per, "done": 0, "errors": 0,
                   "rejected": 0, "gave_up": 0, "lat_ms": []}
            for name in ("interactive", "campaign", "batch")}
    t0 = time.perf_counter()
    threads = [threading.Thread(
        target=drive_tenant, args=(svc, tenant_specs(n, per), recs[n]),
        kwargs={"poll_s": 0.1}, name=f"fleet-load-{n}")
        for n in recs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    # let the workers idle-exit so their FINAL stats snapshots (the
    # build counters) are on disk before aggregating
    deadline = time.time() + 60.0
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            p.terminate()
    agg = aggregate_worker_stats(d)
    all_lat = sorted(x for r in recs.values() for x in r["lat_ms"])
    done_total = sum(r["done"] for r in recs.values())
    builds = agg["registry"].get("misses", 0)
    return {
        "workers": workers,
        "completed": done_total,
        "submitted": 3 * per,
        "errors": sum(r["errors"] + r["gave_up"] for r in recs.values()),
        "rejections_429": sum(r["rejected"] for r in recs.values()),
        "wall_s": round(wall, 2),
        "throughput_rps": round(done_total / max(wall, 1e-9), 3),
        "p50_ms": pct(all_lat, 0.50),
        "p99_ms": pct(all_lat, 0.99),
        "program_builds": builds,
        "requests_per_build": round(done_total / max(1, builds), 1),
        "repacked": agg["resilience"].get("repacked", 0),
        "worker_deduped": agg["counters"].get("deduped", 0),
        "per_tenant": {n: {"completed": r["done"],
                           "rejected_429": r["rejected"],
                           "p50_ms": pct(sorted(r["lat_ms"]), 0.50),
                           "p99_ms": pct(sorted(r["lat_ms"]), 0.99)}
                       for n, r in recs.items()},
        "per_worker": {w: {k: blk.get(k) for k in
                           ("claimed", "processed", "deduped")}
                       | {"builds": (blk.get("registry") or {}
                                     ).get("misses")}
                       for w, blk in agg["workers"].items()},
    }


def fleet_load(worker_counts, requests: int, *, base_dir=None,
               timeline=None) -> dict:
    """The --workers sweep: the same request mix at each worker count
    (fresh fleet directory each — no cross-run dedup), with the
    scaling ratios the ISSUE pins (submit->result throughput at N
    workers vs 1) computed when 1 is in the sweep."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="wtpu-serve-fleet-")
    per = max(1, requests // 3)
    by = {}
    for w in worker_counts:
        print(f"fleet-load: measuring {w} worker(s)...", flush=True,
              file=sys.stderr)
        by[str(w)] = fleet_load_once(w, per, base_dir=base,
                                     timeline=timeline)
    block = {"schema": 1, "requests": 3 * per, "by_workers": by,
             "dir": base}
    if "1" in by:
        base_rps = by["1"]["throughput_rps"]
        block["speedup_vs_1"] = {
            w: round(b["throughput_rps"] / max(base_rps, 1e-9), 2)
            for w, b in by.items() if w != "1"}
        block["requests_per_build_vs_1"] = {
            w: round(b["requests_per_build"]
                     / max(by["1"]["requests_per_build"], 1e-9), 2)
            for w, b in by.items() if w != "1"}
    return block


def pct(sorted_vals, q):
    """Upper nearest-rank percentile (ceil, not floor: a floored p99
    over ~100 samples would read ~p98 and hide the one true tail
    outlier — the number this tool exists to report)."""
    import math
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1,
            math.ceil(q * (len(sorted_vals) - 1)))
    return round(sorted_vals[i], 1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/serve_load.py",
        description="multi-tenant serve load generator (tenancy bench)")
    ap.add_argument("--requests", type=int, default=120,
                    help="total requests across the 3 tenants "
                         "(default 120)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="also write the JSON line here")
    ap.add_argument("--stream", action="store_true",
                    help="add the long-poll partial-metrics smoke "
                         "check (one spec streamed chunk by chunk)")
    ap.add_argument("--workers", default=None, metavar="N[,M...]",
                    help="fleet scaling sweep (serve/fleet.py): run "
                         "the same request mix through a FleetService "
                         "front tier at each worker-process count "
                         "(e.g. '1,2,4') and report per-count p50/p99, "
                         "aggregate submit->result throughput and "
                         "requests-per-build; a fresh fleet directory "
                         "per count keeps the runs independent")
    ap.add_argument("--fleet-dir", default=None, metavar="DIR",
                    help="base directory for the --workers sweep "
                         "(default: a temp dir)")
    ap.add_argument("--kill-after", type=float, default=None,
                    metavar="S",
                    help="hard-stop the client threads after S "
                         "seconds and report the /w/batch/health "
                         "snapshot taken at the kill (the crash-"
                         "safety observability exercise; completion "
                         "checks are skipped — a killed run cannot "
                         "promise completion)")
    ap.add_argument("--timeline", default=None, metavar="DIR",
                    help="turn the host-plane flight recorder ON: "
                         "span JSONL per process under DIR, plus one "
                         "merged Perfetto timeline.json (host "
                         "lifecycle spans + one probe request's "
                         "device metrics/trace lanes; with --workers, "
                         "a span log per worker process per count)")
    args = ap.parse_args(argv)

    if args.workers is not None:
        try:
            counts = [int(x) for x in args.workers.split(",") if x]
            if not counts or any(c < 1 for c in counts):
                raise ValueError(args.workers)
        except ValueError:
            print(f"config error: --workers wants a comma list of "
                  f"positive ints, got {args.workers!r}",
                  file=sys.stderr)
            return 2
        if args.timeline is not None:
            import os
            os.makedirs(args.timeline, exist_ok=True)
        block = fleet_load(counts, args.requests,
                           base_dir=args.fleet_dir,
                           timeline=args.timeline)
        if args.timeline is not None:
            # render the workers' span logs (all counts) onto one
            # merged Perfetto timeline; the per-count subdirs keep
            # distinct pids per worker per count
            import glob
            import os

            from wittgenstein_tpu.obs.export import spans_to_perfetto
            from wittgenstein_tpu.obs.spans import read_spans
            rows = []
            for f in sorted(glob.glob(os.path.join(
                    args.timeline, "**", "spans*.jsonl"),
                    recursive=True)):
                rows.extend(read_spans(f))
            tpath = os.path.join(args.timeline, "timeline.json")
            spans_to_perfetto(rows, path=tpath)
            block["timeline"] = {"path": tpath, "spans": len(rows)}
        worst_p99 = max((b["p99_ms"] or 0)
                        for b in block["by_workers"].values())
        line = json.dumps({"metric": "serve_fleet_p99_ms",
                           "value": worst_p99, "unit": "ms",
                           "fleet": block,
                           "platform": jax.default_backend()})
        print(line)
        if args.out:
            pathlib.Path(args.out).write_text(line + "\n")
        bad = {w: b for w, b in block["by_workers"].items()
               if b["errors"] or b["completed"] < b["submitted"]}
        if bad:
            print(f"fleet-load: incomplete counts {sorted(bad)}",
                  file=sys.stderr)
            return 1
        return 0

    per = max(1, args.requests // 3)
    ins = None
    if args.timeline is not None:
        import os

        from wittgenstein_tpu.serve.instrument import Instrumentation
        os.makedirs(args.timeline, exist_ok=True)
        ins = Instrumentation(
            span_path=os.path.join(args.timeline, "spans-serve.jsonl"),
            worker="serve")
    sch = Scheduler(
        tenants=fleet_tenants(),
        quantum_chunks=2,
        instrument=ins)
    svc = Service(scheduler=sch, auto=True)
    recs = {name: {"submitted": per, "done": 0, "errors": 0,
                   "rejected": 0, "gave_up": 0, "lat_ms": []}
            for name in ("interactive", "campaign", "batch")}
    t0 = time.perf_counter()
    stop = threading.Event() if args.kill_after is not None else None
    threads = [threading.Thread(target=drive_tenant,
                                args=(svc, tenant_specs(n, per), recs[n]),
                                kwargs={"stop": stop},
                                name=f"load-{n}")
               for n in recs]
    for t in threads:
        t.start()
    health_at_kill = None
    if stop is not None:
        # the --kill-after exercise: snapshot /w/batch/health UNDER
        # load at the kill instant, then hard-stop the clients — the
        # health block is what an operator's probe would have seen
        # just before the process died
        time.sleep(max(0.0, args.kill_after))
        health_at_kill = svc.health()
        stop.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stream_block = stream_smoke(svc) if args.stream else None
    timeline_block = (timeline_probe(sch, args.timeline)
                      if args.timeline is not None else None)
    svc.close()

    ten = svc.tenancy_stats()
    reg = svc.registry_stats()
    all_lat = sorted(x for r in recs.values() for x in r["lat_ms"])
    done_total = sum(r["done"] for r in recs.values())
    starved = [n for n, r in recs.items() if r["done"] < r["submitted"]
               and not r["errors"] and not r["gave_up"]]
    tenancy = {
        "schema": 1,                        # BENCH_NOTES r15
        "requests": 3 * per,
        "completed": done_total,
        "rejections_429": sum(r["rejected"] for r in recs.values()),
        "preemptions": ten["preemptions"],
        "p50_ms": pct(all_lat, 0.50),
        "p99_ms": pct(all_lat, 0.99),
        "program_builds": reg["misses"],
        "requests_per_build": round(done_total / max(1, reg["misses"]),
                                    1),
        "chunk_wall_ema_s": ten["chunk_wall_ema_s"],
        "per_tenant": {
            n: {"submitted": r["submitted"], "completed": r["done"],
                "rejected_429": r["rejected"], "errors": r["errors"],
                "gave_up": r["gave_up"],
                "p50_ms": pct(sorted(r["lat_ms"]), 0.50),
                "p99_ms": pct(sorted(r["lat_ms"]), 0.99),
                "weight": ten["tenants"].get(n, {}).get("weight")}
            for n, r in recs.items()},
    }
    out = {
        "metric": "serve_load_p99_ms",
        "value": tenancy["p99_ms"],
        "unit": "ms",
        "wall_total_s": round(wall, 2),
        "tenancy": tenancy,
        "registry": reg,
        "health": svc.health(),
        "platform": jax.default_backend(),
    }
    if health_at_kill is not None:
        out["killed_after_s"] = args.kill_after
        out["health_at_kill"] = health_at_kill
    if stream_block is not None:
        out["stream"] = stream_block
    if timeline_block is not None:
        out["timeline"] = timeline_block
    line = json.dumps(out)
    print(line)
    if args.out:
        pathlib.Path(args.out).write_text(line + "\n")
    if stream_block is not None and not stream_block["ok"]:
        print(f"STREAM smoke failed: {stream_block}", file=sys.stderr)
        return 1
    if health_at_kill is not None:
        # a killed run cannot promise completion: the health snapshot
        # IS the product; starvation/error gates apply only to full
        # runs
        return 0
    if starved:
        print(f"STARVATION: tenant(s) {starved} did not complete their "
              "requests", file=sys.stderr)
        return 1
    errs = sum(r["errors"] + r["gave_up"] for r in recs.values())
    if errs:
        print(f"{errs} request(s) errored or gave up", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
