#!/bin/bash
# Round-4 phase-4 chip queue: the fixed 1M run first, then the
# remaining items.
cd "$(dirname "$0")/.."
while pgrep -f "python bench.py" > /dev/null; do sleep 30; done

echo "[q4] 1M cardinal on the REAL chip (plain per-ms scan — the phased"
echo "     block's 63% HBM fragmentation was the last OOM)"
WTPU_CARDINAL_PLATFORM=tpu python tools/cardinal_1m.py 120 \
    > reports/cardinal_1m_tpu.log 2>&1

echo "[q4] tier-2 exact 32768n, plain per-ms scan + donation"
WTPU_BENCH_NODES=32768 WTPU_BENCH_SEEDS=1 WTPU_BENCH_MS=2400 \
    WTPU_BENCH_REPS=1 WTPU_BENCH_EMISSION=hashed WTPU_BENCH_POOL=0 \
    WTPU_BENCH_QUEUE=7 WTPU_BENCH_BOX_SPLIT=2 WTPU_BENCH_DONATE=big \
    WTPU_BENCH_SPEC=0 WTPU_BENCH_SUPERSTEP=1 \
    python bench.py > reports/bench_r4_exact32k.log 2>&1

echo "[q4] dfinity variance (32 seeds x 300 s)"
python tools/dfinity_variance.py 32 300 > reports/dfinity_variance.log 2>&1

echo "[q4] suite retry: sanfermin + dfinity tracked configs"
python tools/bench_suite.py sanfermin_32768n dfinity_10k_validators \
    >> reports/bench_suite_r4.jsonl 2>reports/bench_suite_retry.log

echo "[q4] reference-scale scenario sweeps (2048 x 8)"
python tools/scenario_sweeps_2048.py > reports/sweeps_2048.log 2>&1

echo "[q4] emission drift 8192 honest x 8 seeds"
python -m wittgenstein_tpu.scenarios.emission_drift reports 8192 8 \
    > reports/emission_8192.log 2>&1

echo "[q4] emission drift attacks at 1024 x 8 seeds"
python - > reports/emission_attacks.log 2>&1 <<'PYEOF'
from wittgenstein_tpu.scenarios.emission_drift import compare
compare(nodes=1024, seeds=8, max_time=10000, out_dir="reports",
        attack="byzantine_suicide", dead_ratio=0.25)
compare(nodes=1024, seeds=8, max_time=10000, out_dir="reports",
        attack="hidden_byzantine", dead_ratio=0.25)
PYEOF

echo "[q4] done"
