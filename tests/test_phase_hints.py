"""Phase-specialized scan (core/network.scan_chunk t0_mod) — bit-equality
with the plain per-ms scan.

The specialization is the tensor analogue of the reference's empty-ms skip
(Network.java:533-570): on a ms where no node can be on a pairing or period
boundary, the corresponding masked sub-computations reduce to the identity,
so skipping them must be EXACTLY a no-op — including the narrow fast-path
outbox (Outbox.slot0), whose latency draws must key to the same slot ids
as the full-width outbox.  These tests assert full (NetState, HandelState)
pytree equality between the two paths, in honest runs (with the fast path
exercising the every-ms branch) and under both byzantine attacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wittgenstein_tpu.core.network import scan_chunk
from wittgenstein_tpu.models.handel import Handel


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _run_both(proto, ms, seeds=2):
    assert proto.schedule_lcm is not None and ms % proto.schedule_lcm == 0
    plain = jax.jit(jax.vmap(scan_chunk(proto, ms)))
    spec = jax.jit(jax.vmap(scan_chunk(proto, ms, t0_mod=0)))
    sd = jnp.arange(seeds, dtype=jnp.int32)
    nets, ps = jax.vmap(proto.init)(sd)
    out_plain = plain(nets, ps)
    nets, ps = jax.vmap(proto.init)(sd)
    out_spec = spec(nets, ps)
    return out_plain, out_spec


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 165 s: the heaviest tier-1 test; the cardinal twin below keeps the
# phase-hint equality gate in the fast suite
def test_specialized_scan_bit_equal_honest():
    proto = Handel(node_count=64, threshold=56, nodes_down=6,
                   pairing_time=4, dissemination_period_ms=20,
                   level_wait_time=50, fast_path=10)
    assert proto.schedule_lcm == 20
    a, b = _run_both(proto, 120)
    _trees_equal(a, b)
    # The run did something: verifications happened and aggregates grew
    # (fast-path level completions exercise the every-ms branch).
    _, ps = b
    assert int(np.asarray(ps.sigs_checked).sum()) > 0
    assert int(np.asarray(ps.fast_pending).sum()) >= 0  # drained each ms
    from wittgenstein_tpu.ops import bitset
    assert int(np.asarray(bitset.popcount(ps.last_agg)).sum()) > 0


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 99 s; both phase-hint equality pairs are now slow-only —
# test_desynchronized_start_never_specializes keeps the guard-rail fast
def test_specialized_scan_bit_equal_cardinal():
    proto = Handel(node_count=64, threshold=56, nodes_down=6,
                   pairing_time=4, dissemination_period_ms=20,
                   level_wait_time=50, fast_path=10, mode="cardinal")
    assert proto.schedule_lcm == 20
    a, b = _run_both(proto, 120)
    _trees_equal(a, b)
    _, ps = b
    assert int(np.asarray(ps.sigs_checked).sum()) > 0


@pytest.mark.slow
@pytest.mark.parametrize("attack", ["byzantine_suicide", "hidden_byzantine"])
def test_specialized_scan_bit_equal_attacks(attack):
    proto = Handel(node_count=64, threshold=48, nodes_down=16,
                   pairing_time=4, dissemination_period_ms=20,
                   level_wait_time=50, fast_path=10, **{attack: True})
    a, b = _run_both(proto, 100)
    _trees_equal(a, b)


@pytest.mark.slow
def test_specialized_scan_uneven_periods():
    # pairing 3, period 10 -> lcm 30; exercises non-divisor phase math.
    proto = Handel(node_count=64, threshold=60, nodes_down=0,
                   pairing_time=3, dissemination_period_ms=10,
                   level_wait_time=30, fast_path=4)
    assert proto.schedule_lcm == 30
    a, b = _run_both(proto, 90)
    _trees_equal(a, b)


def test_desynchronized_start_never_specializes():
    proto = Handel(node_count=64, threshold=56, nodes_down=0,
                   desynchronized_start=17)
    assert proto.schedule_lcm is None
    # t0_mod is then ignored and the plain path is used.
    fn = scan_chunk(proto, 40, t0_mod=0)
    net, p = proto.init(jnp.asarray(0, jnp.int32))
    net2, _ = jax.jit(fn)(net, p)
    assert int(net2.time) == 40


@pytest.mark.slow
def test_specialized_scan_non_multiple_length():
    # A non-lcm-multiple chunk misaligns on REUSE, so it must be an
    # explicit one-shot opt-in (allow_unaligned); the schedule is then
    # tiled/truncated to the chunk and stays bit-identical.
    proto = Handel(node_count=64, threshold=56, nodes_down=6,
                   pairing_time=4, dissemination_period_ms=20,
                   level_wait_time=50, fast_path=10, mode="cardinal")
    with pytest.raises(ValueError, match="multiple"):
        scan_chunk(proto, 50, t0_mod=0)
    plain = jax.jit(scan_chunk(proto, 50))
    spec = jax.jit(scan_chunk(proto, 50, t0_mod=0, allow_unaligned=True))
    net, ps = proto.init(jnp.asarray(0, jnp.int32))
    a = plain(net, ps)
    net, ps = proto.init(jnp.asarray(0, jnp.int32))
    b = spec(net, ps)
    _trees_equal(a, b)
