"""GSFSignature — "Gossiping San Fermín" BLS aggregation.

Reference: protocols/GSFSignature.java (769 lines).  Mechanism (SURVEY.md
§2.4): every node runs log2(N) San Fermín levels; each `periodDurationMs` it
walks its levels and sends, per open level, its best *finished-level prefix*
plus everything verified below that level to one round-robin peer
(doCycle, GSFSignature.java:212-224).  Incoming signature sets queue for
verification; every `pairingTime` ms the best-scoring set is verified
(evaluateSig/checkSigs, :482-534,:539-580).  Oversized sets complete several
levels at once (updateVerifiedSignatures, :383-460); completing a level
triggers `acceleratedCallsCount` immediate sends at the next levels
(:438-451).  Individual signatures ride along for byzantine resistance
(onNewSig, :546-553).

TPU-native design (mirrors models/handel.py; one [N, W] row per bitset):

* Levels share Handel's id-space geometry (allSigsAtLevel,
  GSFSignature.java:359-372 == Handel.java:667-680), so the LevelMixin
  popcount/range machinery applies unchanged.
* The global verified set V is ONE [N, W] row (own bit at init,
  GSFNode ctor :176).  A level's verified set is V & range_l; the replace
  update `andNot(waitedSigs); or(sigs)` (:432-436) is a masked merge on V.
  (The reference's per-level sets can briefly hold out-of-range stragglers;
  we fold those into V directly — statistical equivalence, SURVEY §7.4.3.)
* A message carries (level, finishedPrefix, roundSlot) — the actual sig set
  is reconstructed at delivery from the sender's snapshot pool:
  sigs = (pool[src, slot] & block(src, level-1)) | block(src, fin), which is
  exactly doCycle's `toSend` (getLastFinishedLevel :197-210 is the 2^fin
  block around the sender; the or-accumulated lower-level sets are
  pool & block(src, level-1)).
* toVerify (:539-553) is a fixed [N, Q] queue keyed by (from, level);
  newer sets from the same (from, level) replace older (supersets in
  practice); individual signatures enqueue once ever per (sender, level)
  via the got_indiv dedup row (:546-553).  checkSigs' score is evaluated
  for the whole queue in one shot; the winner verifies after
  `nodePairingTime` ms (pend_* slot), losers with score 0 are evicted —
  the reference's iterator-remove curation (:560-567).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core import builders
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import bitset, prng
from ..ops.flat import gather2d, gather_rows, set2d, set_rows
from ._levels import (LevelMixin, get_bit_rows as _get_bit_rows,
                      keyed_level_peer, select_queue)

TAG_BAD = 0x47424144      # bad-node choice
TAG_PERM = 0x47504552     # per-(node, level) peer-order permutation
U32 = jnp.uint32
BIG = jnp.int32(1 << 30)


@struct.dataclass
class GSFState:
    seed: jnp.ndarray          # int32 scalar
    pairing: jnp.ndarray       # int32 [N] nodePairingTime (speedRatio-scaled)
    verified: jnp.ndarray      # u32 [N, W] — global verified set V (:170)
    ver_indiv: jnp.ndarray     # u32 [N, W] indivVerifiedSig, all levels packed
    got_indiv: jnp.ndarray     # u32 [N, W] individualSignatures dedup (:551)
    remaining: jnp.ndarray     # int32 [N, L] remainingCalls per level
    pos: jnp.ndarray           # int32 [N, L] posInLevel round-robin pointer
    q_from: jnp.ndarray        # int32 [N, Q] (-1 = empty)
    q_lvl: jnp.ndarray         # int32 [N, Q]
    q_indiv: jnp.ndarray       # bool [N, Q]
    q_sig: jnp.ndarray         # u32 [N, Q, W] — the full queued set
    pend_from: jnp.ndarray     # int32 [N] in-flight verification (-1 = none)
    pend_lvl: jnp.ndarray      # int32 [N]
    pend_sig: jnp.ndarray      # u32 [N, W]
    pend_at: jnp.ndarray       # int32 [N]
    accel_pending: jnp.ndarray  # int32 [N] — bitmask of accelerated levels
    pool: jnp.ndarray          # u32 [N, R, W] — V snapshots per send round
    sigs_checked: jnp.ndarray  # int32 [N]
    evicted: jnp.ndarray       # int32 scalar


@register
class GSFSignature(LevelMixin):
    """Parameters mirror GSFSignatureParameters (GSFSignature.java:27-107)."""

    # Dests come from sibling-half level peer sets — never self
    # (core/network.unicast_floor_ms).
    may_self_send = False

    def __init__(self, node_count=1024, threshold=None, pairing_time=3,
                 timeout_per_level_ms=50, period_duration_ms=10,
                 accelerated_calls_count=10, nodes_down=0,
                 node_builder_name=None, network_latency_name=None,
                 queue_cap=16, inbox_cap=16, horizon=512,
                 pallas_merge=None):
        # Fused Pallas queue merge (ops/pallas_gsf_merge.py) —
        # bit-identical to the XLA merge (tests/test_gsf.py); shared
        # auto-default policy with Handel.
        from ..ops.pallas_merge import resolve_pallas_default
        self.pallas_merge = resolve_pallas_default(pallas_merge)
        if self.pallas_merge and queue_cap + 2 * inbox_cap > 255:
            # The kernel's unique-key headroom (BIG0 + position); fail
            # at construction, not after a 10-minute backend init.
            raise ValueError(
                f"pallas_merge supports queue_cap + 2*inbox_cap <= 255 "
                f"(got {queue_cap} + 2*{inbox_cap}); pass "
                "pallas_merge=False for wider rows")
        if node_count & (node_count - 1):
            raise ValueError("power-of-two node counts only (the reference "
                             "rounds to pow2, MoreMath.roundPow2)")
        threshold = (int(node_count * 0.99) if threshold is None
                     else threshold)
        if not (0 <= nodes_down < node_count and
                threshold + nodes_down <= node_count and
                threshold <= node_count):
            raise ValueError(f"nodeCount={node_count}, threshold={threshold},"
                             f" nodesDown={nodes_down} "
                             "(GSFSignature.java:70-75)")
        self.node_count = node_count
        self.threshold = threshold
        self.pairing_time = pairing_time
        self.timeout_per_level = timeout_per_level_ms
        self.period = period_duration_ms
        self.accel = accelerated_calls_count
        self.nodes_down = nodes_down
        self.queue_cap = queue_cap
        self.builder = builders.get_by_name(node_builder_name)
        self.latency = latency_mod.get_by_name(network_latency_name)

        self.bits = max(1, int(math.log2(node_count)))
        self.levels = self.bits + 1
        # The queue-merge sort key is (tier*(L+1)+lvl)*M + pos in int32
        # with tier <= 2 and M = Q + 2S (see receive); enforce it fits.
        _m = queue_cap + 2 * inbox_cap
        if (2 * (self.levels + 1) + self.levels) * _m + _m >= 2 ** 31:
            raise ValueError(
                "queue-merge sort key would overflow int32: reduce "
                f"queue_cap={queue_cap}/inbox_cap={inbox_cap}")
        self.w = bitset.n_words(node_count)
        self.rounds = horizon // max(1, period_duration_ms) + 2
        self.half = np.array([0] + [1 << (l - 1)
                                    for l in range(1, self.levels)],
                             np.int32)
        k = (self.levels - 1) + self.accel
        self.cfg = EngineConfig(n=node_count, horizon=horizon,
                                inbox_cap=inbox_cap, payload_words=3,
                                out_deg=k, bcast_slots=0)

    # ------------------------------------------------------------ primitives

    def _peer_at(self, seed, ids, level, pos):
        """The `pos`-th peer of `ids` at `level` in its shuffled peer order
        (randomSubset + Collections.shuffle, GSFSignature.java:462-476, as a
        keyed permutation of the level range — no stored [N, N] lists)."""
        return keyed_level_peer(seed, TAG_PERM, ids, level, pos)

    def _fin_level(self, pc):
        """Last finished level f: levels 1..f all complete (getLastFinished
        Level, :197-210).  pc [N, L] per-level popcounts of V."""
        halfs = jnp.asarray(self.half)[None, :]
        comp = (pc >= halfs) | (halfs == 0)          # level 0 always complete
        run = jnp.cumprod(comp.astype(jnp.int32), axis=1)
        return jnp.sum(run, axis=1).astype(jnp.int32) - 1   # [N], 0..L-1

    # ---------------------------------------------------------------- init

    def init(self, seed):
        n, w, L, Q = self.node_count, self.w, self.levels, self.queue_cap
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        ids = jnp.arange(n, dtype=jnp.int32)

        if self.nodes_down:
            pri = prng.uniform_u32(prng.hash2(seed, TAG_BAD), ids)
            down = jnp.zeros((n,), bool).at[
                jnp.argsort(pri)[:self.nodes_down]].set(True)
            nodes = nodes.replace(down=down)

        pairing = jnp.maximum(
            1, (self.pairing_time * nodes.speed_ratio)).astype(jnp.int32)
        halfs = jnp.asarray(self.half)
        remaining = jnp.broadcast_to(halfs[None, :], (n, L)).astype(jnp.int32)

        net = init_net(self.cfg, nodes, seed)
        pstate = GSFState(
            seed=seed, pairing=pairing,
            verified=bitset.one_bit(ids, w),
            ver_indiv=jnp.zeros((n, w), U32),
            got_indiv=jnp.zeros((n, w), U32),
            remaining=remaining,
            pos=jnp.zeros((n, L), jnp.int32),
            q_from=jnp.full((n, Q), -1, jnp.int32),
            q_lvl=jnp.zeros((n, Q), jnp.int32),
            q_indiv=jnp.zeros((n, Q), bool),
            q_sig=jnp.zeros((n, Q, w), U32),
            pend_from=jnp.full((n,), -1, jnp.int32),
            pend_lvl=jnp.zeros((n,), jnp.int32),
            pend_sig=jnp.zeros((n, w), U32),
            pend_at=jnp.zeros((n,), jnp.int32),
            accel_pending=jnp.zeros((n,), jnp.int32),
            pool=jnp.zeros((n, self.rounds, w), U32),
            sigs_checked=jnp.zeros((n,), jnp.int32),
            evicted=jnp.asarray(0, jnp.int32),
        )
        return net, pstate

    # ---------------------------------------------------------------- step

    def step(self, p: GSFState, nodes, inbox, t, key):
        onehot = self._word_onehot(jnp.arange(self.node_count,
                                              dtype=jnp.int32))
        subm = self._subword_masks(jnp.arange(self.node_count,
                                              dtype=jnp.int32))
        hi = jnp.arange(self.node_count, dtype=jnp.int32) >> 5

        p = self._receive(p, nodes, inbox, t)
        p, nodes = self._apply_pending(p, nodes, t, onehot, subm, hi)
        p = self._pick_verification(p, nodes, t, onehot, subm, hi)
        p, out = self._disseminate(p, nodes, t, onehot, subm, hi)
        return p, nodes, out

    # -- receive (onNewSig, :539-553)

    def _receive(self, p: GSFState, nodes, inbox, t):
        n, w, L, Q = self.node_count, self.w, self.levels, self.queue_cap
        S = inbox.src.shape[1]

        valid = inbox.valid
        src = jnp.clip(inbox.src, 0, n - 1)
        level = jnp.clip(inbox.data[:, :, 0], 0, L - 1)
        fin = jnp.clip(inbox.data[:, :, 1], 0, L - 1)
        rslot = jnp.clip(inbox.data[:, :, 2], 0, self.rounds - 1)

        # Reconstruct the sender's toSend set (see module docstring).
        pool_row = gather_rows(p.pool, src, rslot)            # [N, S, W]
        low = self._sender_block_mask(src, level)             # [N, S, W]
        fin_block = self._block_mask_dyn(src, fin)
        sig_all = (pool_row & low) | fin_block

        # Queue merge, vectorized across ALL slots at once (the unrolled
        # per-slot loop compiled S insert/evict blocks).  Bounded-queue
        # policy: queued INDIVIDUAL entries are immovable (their got_indiv
        # dedup bit would otherwise lose the sig forever — the reference
        # keys individuals per level, but a sender only ever appears at ONE
        # level of a given receiver); aggregates keep one entry per
        # (sender, level) — newest wins — prioritized by LEVEL ascending
        # (scoring favors early levels), existing before incoming, then
        # inbox-slot order.  Policy change from the old loop: ALL same-ms
        # aggregates now outrank same-ms individual sigs for capacity (the
        # loop interleaved them by slot); individuals fill leftover slots.
        # One tiered sort over (existing ∪ inc-agg ∪ inc-indiv) does it.
        M = Q + 2 * S
        later = jnp.triu(jnp.ones((S, S), bool), k=1)[None]
        dup = jnp.any((src[:, :, None] == src[:, None, :]) &
                      (level[:, :, None] == level[:, None, :]) &
                      valid[:, None, :] & later, axis=2)
        agg_ok = valid & ~dup                # newest same-key message wins
        superseded = jnp.any(
            (p.q_from[:, :, None] == src[:, None, :]) &
            (p.q_lvl[:, :, None] == level[:, None, :]) &
            (~p.q_indiv)[:, :, None] & agg_ok[:, None, :], axis=2)
        ex_keep = (p.q_from >= 0) & ~superseded

        # Incoming individuals: once ever per sender — first slot this ms
        # wins, and senders already in got_indiv are consumed.
        earlier = jnp.tril(jnp.ones((S, S), bool), k=-1)[None]
        dup_ind = jnp.any((src[:, :, None] == src[:, None, :]) &
                          valid[:, None, :] & earlier, axis=2)
        ind_ok = valid & ~dup_ind & ~_get_bit_rows(p.got_indiv, src)

        if self.pallas_merge:
            from ..ops.pallas_gsf_merge import gsf_merge_pallas
            q_from, q_lvl, q_indiv, q_sig, got_add, kept_ex_agg = \
                gsf_merge_pallas(
                    p.q_from, p.q_lvl, p.q_indiv, ex_keep, p.q_sig,
                    src, level, agg_ok, ind_ok, sig_all, levels=L,
                    interpret=jax.default_backend() != "tpu")
            got_indiv = p.got_indiv | got_add
            evicted = p.evicted + jnp.sum(
                jnp.sum(ex_keep & ~p.q_indiv, axis=1) -
                kept_ex_agg).astype(jnp.int32)
            return p.replace(q_from=q_from, q_lvl=q_lvl,
                             q_indiv=q_indiv, q_sig=q_sig,
                             got_indiv=got_indiv, evicted=evicted)

        u_from = jnp.concatenate(
            [jnp.where(ex_keep, p.q_from, -1),
             jnp.where(agg_ok, src, -1),
             jnp.where(ind_ok, src, -1)], axis=1)           # [N, M]
        u_lvl = jnp.concatenate([p.q_lvl, level, level], axis=1)
        u_indiv = jnp.concatenate(
            [p.q_indiv, jnp.zeros_like(agg_ok),
             jnp.ones_like(ind_ok)], axis=1)
        u_sig = jnp.concatenate(
            [p.q_sig, sig_all,
             jnp.where(ind_ok[..., None], bitset.one_bit(src, w),
                       U32(0))], axis=1)                     # [N, M, W]

        valid_u = u_from >= 0
        pos = jnp.arange(M, dtype=jnp.int32)[None, :]
        is_inc_ind = pos >= Q + S                            # tier 2
        tier = jnp.where(is_inc_ind, 2,
                         jnp.where(u_indiv, 0, 1))           # existing
        #                                                      indiv = 0
        lvl_term = jnp.where(tier == 1, u_lvl, 0)
        sel2, sel3, order = select_queue(
            (tier * (L + 1) + lvl_term) * M + pos, valid_u, Q,
            {"from": u_from, "lvl": u_lvl, "indiv": u_indiv},
            {"sig": u_sig})
        q_from, q_lvl, q_indiv = sel2["from"], sel2["lvl"], sel2["indiv"]
        q_sig = sel3["sig"]

        # got_indiv consumed only for incoming individuals that made it in.
        sel_new_ind = (jnp.take_along_axis(
            jnp.broadcast_to(is_inc_ind, valid_u.shape), order, axis=1) &
            (q_from >= 0))
        ind_bits = jnp.where(sel_new_ind[..., None],
                             bitset.one_bit(jnp.maximum(q_from, 0), w),
                             U32(0))
        got_indiv = p.got_indiv | jax.lax.reduce(
            ind_bits, U32(0), jax.lax.bitwise_or, (1,))

        # Diagnostic: displaced existing aggregate entries.
        kept_ex_agg = jnp.sum(
            (order < Q) &
            jnp.take_along_axis(valid_u & ~u_indiv, order, axis=1), axis=1)
        evicted = p.evicted + jnp.sum(
            jnp.sum(ex_keep & ~p.q_indiv, axis=1) -
            kept_ex_agg).astype(jnp.int32)

        return p.replace(q_from=q_from, q_lvl=q_lvl, q_indiv=q_indiv,
                         q_sig=q_sig, got_indiv=got_indiv, evicted=evicted)

    # -- apply a finished verification (updateVerifiedSignatures, :383-460)

    def _apply_pending(self, p: GSFState, nodes, t, onehot, subm, hi):
        n, w, L = self.node_count, self.w, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        halfs = jnp.asarray(self.half)
        due = (p.pend_from >= 0) & (t >= p.pend_at)

        lvl = p.pend_lvl
        sigs = p.pend_sig
        exp = halfs[lvl]                                      # [N]

        # Individual sig marking (:387-390): |sigs| == 1 marks the sender
        # as individually verified; every apply or's the level's verified
        # individual sigs into the set.
        card0 = bitset.popcount(sigs)
        mark_ind = due & (card0 == 1)
        ver_indiv = jnp.where(mark_ind[:, None], p.ver_indiv | sigs,
                              p.ver_indiv)
        lmask = self._range_mask_dyn(ids, lvl)                # [N, W]
        sigs = sigs | (ver_indiv & lmask)

        # Oversized set -> complete the consecutive levels it includes
        # (:395-417), then clamp to the level range.
        pc_v = self._level_pc(p.verified, onehot, subm, hi)   # [N, L]
        oversized = due & (bitset.popcount(sigs) > exp)
        incl = jnp.stack(
            [jnp.ones((n,), bool)] +
            [bitset.includes(sigs & self._range_mask_dyn(
                ids, jnp.full((n,), l, jnp.int32)),
                self._range_mask_dyn(ids, jnp.full((n,), l, jnp.int32)))
             for l in range(1, L)], axis=1)                   # [N, L]
        run = jnp.cumprod(incl.astype(jnp.int32), axis=1)
        fin_in = jnp.sum(run, axis=1).astype(jnp.int32) - 1   # consec prefix
        was_comp = pc_v >= halfs[None, :]
        lvl_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
        newly = (run > 0) & ~was_comp & (lvl_idx >= 1) & oversized[:, None]
        reset_any = jnp.any(newly, axis=1)
        comp_mask = self._block_mask_dyn(ids, jnp.where(oversized, fin_in, 0))
        verified = jnp.where(oversized[:, None], p.verified | comp_mask,
                             p.verified)
        sigs = jnp.where(oversized[:, None], lmask, sigs)

        # Merge with the level's current set when disjoint (:419-425).
        ver_l = verified & lmask
        ver_l_card = bitset.popcount(ver_l)
        disjoint = ~bitset.intersects(sigs, ver_l) & (ver_l_card > 0)
        sigs = jnp.where(disjoint[:, None], sigs | ver_l, sigs)

        # Improvement -> replace the level's set inside V; out-of-range bits
        # fold into V directly (:427-436).
        improved = due & ((bitset.popcount(sigs & lmask) > ver_l_card) |
                          reset_any)
        verified = jnp.where(improved[:, None],
                             (verified & ~lmask) | sigs, verified)

        # Reset remainingCalls for levels >= min(affected) (:423-430 + the
        # newly-completed reset); affected base = the applied level, or the
        # first newly completed level if lower.
        first_new = jnp.argmax(newly, axis=1).astype(jnp.int32)
        base_l = jnp.where(reset_any, jnp.minimum(lvl, first_new), lvl)
        reset_row = improved[:, None] & (lvl_idx >= base_l[:, None])
        remaining = jnp.where(reset_row, halfs[None, :], p.remaining)

        # Accelerated calls (:438-451): queue levels (lvl+1 .. fin+1).
        accel_pending = p.accel_pending
        if self.accel > 0:
            pc2 = self._level_pc(verified, onehot, subm, hi)
            fin_now = self._fin_level(pc2)                     # [N]
            cand = (improved[:, None] & (lvl_idx > lvl[:, None]) &
                    (lvl_idx <= jnp.minimum(fin_now + 1, L - 1)[:, None]))
            bits_ = jnp.sum(jnp.where(cand, jnp.int32(1) << lvl_idx, 0),
                            axis=1).astype(jnp.int32)
            accel_pending = accel_pending | bits_

        # doneAt at threshold (:452-456).
        total = bitset.popcount(verified)
        done_now = (nodes.done_at == 0) & due & (total >= self.threshold)
        nodes = nodes.replace(done_at=jnp.where(
            done_now, jnp.maximum(t, 1), nodes.done_at).astype(jnp.int32))

        p = p.replace(verified=verified, ver_indiv=ver_indiv,
                      remaining=remaining, accel_pending=accel_pending,
                      pend_from=jnp.where(due, -1, p.pend_from))
        return p, nodes

    # -- checkSigs / evaluateSig (:482-580)

    def _pick_verification(self, p: GSFState, nodes, t, onehot, subm, hi):
        n, L, Q = self.node_count, self.levels, self.queue_cap
        ids = jnp.arange(n, dtype=jnp.int32)
        halfs = jnp.asarray(self.half)
        active = ~nodes.down
        due = active & (p.pend_from < 0) & ((t - 1) % p.pairing == 0) & \
            (t >= 1)

        filled = p.q_from >= 0                                 # [N, Q]
        rows = ids[:, None]
        elvl = p.q_lvl
        sig = p.q_sig
        exp = halfs[elvl]                                      # [N, Q]
        if self.pallas_merge:
            # Same switch as the merge kernel: one fused pass instead
            # of ~5 HBM round-trips over the sig plane
            # (ops/pallas_score.gsf_score_pallas, bit-equal by test).
            from ..ops.pallas_score import gsf_score_pallas
            (ver_l_card, card_sig, inter, pc_wi, pc_wv,
             inter_ind) = gsf_score_pallas(
                sig, elvl, ids, p.verified, p.ver_indiv,
                interpret=jax.default_backend() != "tpu")
        else:
            emask = self._range_mask_dyn(rows, elvl)           # [N, Q, W]
            ver_l = p.verified[:, None, :] & emask
            ver_l_card = bitset.popcount(ver_l)
            indiv_l = p.ver_indiv[:, None, :] & emask
            with_indiv = indiv_l | sig
            card_sig = bitset.popcount(sig)
            inter = bitset.intersects(sig, ver_l)
            pc_wi = bitset.popcount(with_indiv)
            pc_wv = bitset.popcount(with_indiv | ver_l)
            inter_ind = bitset.intersects(sig, indiv_l)

        new_total = jnp.where(
            ver_l_card == 0, card_sig,
            jnp.where(inter, pc_wi, pc_wv))
        added = jnp.where(ver_l_card == 0, new_total,
                          new_total - ver_l_card)
        indiv_bonus = ((card_sig == 1) & ~inter_ind).astype(jnp.int32)
        score = jnp.where(
            added <= 0, indiv_bonus,
            jnp.where(new_total == exp, 1_000_000 - elvl * 10,
                      100_000 - elvl * 100 + added))
        score = jnp.where(ver_l_card >= exp, 0, score)
        score = jnp.where(filled, score, -1)

        best = jnp.argmax(score, axis=1)                       # [N]
        best_score = jnp.take_along_axis(score, best[:, None], axis=1)[:, 0]
        do = due & (best_score > 0)

        vfrom = gather2d(p.q_from, ids, best)
        vlvl = gather2d(p.q_lvl, ids, best)
        vsig = gather_rows(p.q_sig, ids, best)

        # Curation: due nodes drop score-0 entries (:560-567) + the winner.
        drop = due[:, None] & (score == 0)
        q_from = jnp.where(drop, -1, p.q_from)
        q_from = set2d(q_from, ids, best, -1, ok=do)

        return p.replace(
            q_from=q_from,
            pend_from=jnp.where(do, vfrom, p.pend_from),
            pend_lvl=jnp.where(do, vlvl, p.pend_lvl),
            pend_sig=jnp.where(do[:, None], vsig, p.pend_sig),
            pend_at=jnp.where(do, t + p.pairing, p.pend_at),
            sigs_checked=p.sigs_checked + do.astype(jnp.int32))

    # -- doCycle + accelerated sends + outbox (:191-224, :438-451)

    def _disseminate(self, p: GSFState, nodes, t, onehot, subm, hi):
        n, w, L = self.node_count, self.w, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        halfs_np = self.half
        halfs = jnp.asarray(halfs_np)
        active = ~nodes.down
        per_due = active & (t >= 1) & ((t - 1) % self.period == 0)

        pc = self._level_pc(p.verified, onehot, subm, hi)      # [N, L]
        fin = self._fin_level(pc)                              # [N]
        # card(V & block_{l-1}) = 1 + sum_{l'<l} pc  (own bit + lower ranges).
        cum_low = 1 + jnp.cumsum(pc, axis=1) - pc              # [N, L]
        lvl_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
        two_fin = (jnp.int32(1) << jnp.clip(fin, 0, 30))[:, None]
        to_send_card = jnp.where(fin[:, None] <= lvl_idx - 1, cum_low,
                                 two_fin)

        # hasStarted (:283-303): timeout or a full set to send.
        started = ((t >= lvl_idx * self.timeout_per_level) |
                   (to_send_card >= halfs[None, :])) & (halfs[None, :] > 0)
        send_l = per_due[:, None] & started & (p.remaining > 0)

        peer = self._peer_at(p.seed, ids[:, None],
                             jnp.broadcast_to(lvl_idx, (n, L)),
                             p.pos % jnp.maximum(halfs[None, :], 1))
        pos = jnp.where(send_l, (p.pos + 1) % jnp.maximum(halfs[None, :], 1),
                        p.pos)
        remaining = jnp.where(send_l, p.remaining - 1, p.remaining)

        rslot = (t // self.period) % self.rounds
        K = self.cfg.out_deg
        dest = jnp.full((n, K), -1, jnp.int32)
        payload = jnp.zeros((n, K, 3), jnp.int32)
        sizes = jnp.ones((n, K), jnp.int32)
        # SendSigs size = 1 + expected/8 + 96 (:146-152).
        sz_l = 1 + halfs[None, :] // 8 + 96
        dest = dest.at[:, :L - 1].set(jnp.where(send_l, peer, -1)[:, 1:])
        payload = payload.at[:, :L - 1, 0].set(
            jnp.broadcast_to(lvl_idx, (n, L))[:, 1:])
        payload = payload.at[:, :L - 1, 1].set(
            jnp.broadcast_to(fin[:, None], (n, L))[:, 1:])
        payload = payload.at[:, :L - 1, 2].set(rslot)
        sizes = sizes.at[:, :L - 1].set(
            jnp.broadcast_to(sz_l, (n, L))[:, 1:])

        # Accelerated sends: drain the lowest queued level, `accel` peers at
        # once (getRemainingPeers(acceleratedCallsCount), :444-449).
        accel_pending = p.accel_pending
        if self.accel > 0:
            ac = self.accel
            lsb = accel_pending & -accel_pending
            fl = jnp.where(lsb > 0,
                           31 - jax.lax.clz(jnp.maximum(lsb, 1)),
                           0).astype(jnp.int32)                # [N]
            fhalf = jnp.maximum(halfs[fl], 1)
            frem = gather2d(remaining, ids, fl)
            fpos = gather2d(pos, ids, fl)
            k_idx = jnp.arange(ac, dtype=jnp.int32)[None, :]
            fsend = (fl > 0) & active
            fok = fsend[:, None] & (k_idx < jnp.minimum(frem, ac)[:, None])
            fpeer = self._peer_at(p.seed, ids[:, None],
                                  jnp.broadcast_to(fl[:, None], (n, ac)),
                                  (fpos[:, None] + k_idx) % fhalf[:, None])
            koff = L - 1
            dest = dest.at[:, koff:koff + ac].set(
                jnp.where(fok, fpeer, -1))
            payload = payload.at[:, koff:koff + ac, 0].set(fl[:, None])
            payload = payload.at[:, koff:koff + ac, 1].set(fin[:, None])
            payload = payload.at[:, koff:koff + ac, 2].set(rslot)
            sizes = sizes.at[:, koff:koff + ac].set(
                (1 + fhalf // 8 + 96)[:, None])
            nsent = jnp.sum(fok, axis=1).astype(jnp.int32)
            pos = set2d(pos, ids, fl, (fpos + nsent) % fhalf, ok=fsend)
            remaining = set2d(remaining, ids, fl,
                              jnp.maximum(frem - nsent, 0), ok=fsend)
            accel_pending = jnp.where(fsend, accel_pending & ~lsb,
                                      accel_pending)

        # Snapshot pool: senders record their V row for this round slot.
        wrote = jnp.any(dest >= 0, axis=1)
        pool = set_rows(p.pool, ids, jnp.full((n,), rslot, jnp.int32),
                        p.verified, ok=wrote)

        out = empty_outbox(self.cfg).replace(dest=dest, payload=payload,
                                             size=sizes)
        return p.replace(pos=pos, remaining=remaining, pool=pool,
                         accel_pending=accel_pending), out

    # ---------------------------------------------------------------- misc

    def done(self, pstate, nodes):
        return jnp.all(nodes.down | (nodes.done_at > 0))


def cont_if_gsf(net, pstate):
    """newConfIf (GSFSignature.java:676-688): continue while any live node
    is below the threshold."""
    live = ~net.nodes.down
    return jnp.any(live & (net.nodes.done_at == 0))
