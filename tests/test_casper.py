"""CasperIMD tests — the analogue of CasperIMDTest.java: init structure,
chain growth + consensus, fork-choice vote counting, byz variants,
determinism."""

import jax.numpy as jnp
import numpy as np
import pytest

from wittgenstein_tpu.core import blockchain as bc
from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.casper import CasperIMD


def make(**kw):
    args = dict(cycle_length=4, block_producers_count=2,
                attesters_per_round=10, byz_kind="ByzBlockProducerWF",
                byz_delay=0, tick_ms=40,
                network_latency_name="NetworkLatencyByDistanceWJitter")
    args.update(kw)
    return CasperIMD(**args)


def test_init_structure():
    p = make()
    net, ps = p.init(0)
    # observer + producers + attesters (CasperIMDTest.java:21-41)
    assert p.node_count == 1 + 2 + 40
    assert int(ps.arena.n) == 1            # genesis only
    assert np.all(np.asarray(ps.head) == 0)
    byz = np.asarray(net.nodes.byzantine)
    assert byz[1] and not byz[0] and not byz[2:].any()


def test_chain_growth_and_consensus():
    p = make()
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    net, ps = r.run_ms(net, ps, 8000)      # 40 slots
    n_blocks = int(ps.arena.n) - 1
    assert 35 <= n_blocks <= 41            # ~1 block per slot
    hh = np.asarray(ps.arena.height)[np.asarray(ps.head)]
    assert hh.max() >= 37
    assert hh.max() - hh.min() <= 2        # everyone near the tip
    assert int(net.dropped) == 0 and int(net.bc_dropped) == 0
    # attesters vote once per cycle: 40 attesters, ~10 cycles
    assert 350 <= int(ps.att_n) <= 400
    # blocks include attestations
    inc = np.asarray(ps.included)[1:int(ps.arena.n)]
    pop = (np.unpackbits(inc.view(np.uint8), axis=1)).sum()
    assert pop > 100


def test_attestation_endorses_ancestors():
    p = make()
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    net, ps = r.run_ms(net, ps, 4000)
    arena = bc.to_numpy(ps.arena)
    anc = np.asarray(ps.att_anc)
    heads = np.asarray(ps.att_head)
    for a in range(min(10, int(ps.att_n))):
        h = int(heads[a])
        if h == 0:
            continue
        par = int(arena["parent"][h])
        # head's parent endorsed, head itself not (Attestation :118-126)
        assert anc[a, par // 32] >> (par % 32) & 1
        assert not (anc[a, h // 32] >> (h % 32) & 1)


# tier-1 budget (reports/TIER1_DURATIONS.md): ~20 s per variant and the
# three exercise the same step machinery — one stays fast, two go slow.
@pytest.mark.parametrize("kind", [
    "ByzBlockProducer",
    pytest.param("ByzBlockProducerSF", marks=pytest.mark.slow),
    pytest.param("ByzBlockProducerNS", marks=pytest.mark.slow)])
def test_byz_variants_run(kind):
    p = make(byz_kind=kind, byz_delay=1000 if kind == "ByzBlockProducer"
             else 0)
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    net, ps = r.run_ms(net, ps, 6000)      # 30 slots
    assert int(ps.arena.n) > 20
    hh = np.asarray(ps.arena.height)[np.asarray(ps.head)]
    assert hh.max() >= 25
    # byz producer actually produced blocks
    prods = np.asarray(ps.arena.producer)[1:int(ps.arena.n)]
    assert (prods == 1).sum() > 5


def test_fork_choice_unit():
    """CasperIMDTest.java:101-228 analog: `best` on hand-crafted block
    topologies — direct-link/taller rule, attestation counting across a
    fork from the common ancestor, and the deterministic id tie-break."""
    import jax.numpy as jnp

    from wittgenstein_tpu.core import blockchain as bc
    from wittgenstein_tpu.ops import bitset

    proto = make(random_on_ties=False)
    net, p = proto.init(0)
    n = proto.node_count

    def alloc_one(arena, parent, t):
        want = jnp.zeros((n,), bool).at[0].set(True)
        arena, blk = bc.alloc(arena, want,
                              jnp.full((n,), parent, jnp.int32),
                              jnp.zeros((n,), jnp.int32), t)
        return arena, int(blk[0])

    # chain A: genesis -> a1 -> a2 ; fork B: genesis -> b1
    arena = p.arena
    arena, a1 = alloc_one(arena, 0, 5)
    arena, a2 = alloc_one(arena, a1, 6)
    arena, b1 = alloc_one(arena, 0, 7)
    p = p.replace(arena=arena)

    def best(pp, x, y):
        out = proto._best(pp, jnp.full((n,), x, jnp.int32),
                          jnp.full((n,), y, jnp.int32), jnp.int32(50))
        return int(out[0])

    # 1) ancestor vs descendant: direct link -> taller wins, both orders
    # (best :214-217).
    assert best(p, a1, a2) == a2
    assert best(p, a2, a1) == a2

    # 2) fork with votes: 2 attestations head=a2, 1 head=b1, all endorsing
    # the common ancestor (genesis) -> the A branch wins regardless of
    # argument order; flip the counts and B wins despite lower height
    # (best :222-249, countAttestations :262-288).
    def with_votes(heads):
        pp = p.replace(att_n=jnp.asarray(len(heads), jnp.int32))
        ah = pp.att_head
        anc = pp.att_anc
        for j, hblk in enumerate(heads):
            ah = ah.at[j].set(hblk)
            anc = anc.at[j].set(bitset.one_bit(jnp.asarray(0), proto.aw))
        recv = jnp.zeros_like(pp.recv_att).at[:, 0].set(
            jnp.uint32((1 << len(heads)) - 1))
        return pp.replace(att_head=ah, att_anc=anc, recv_att=recv)

    pv = with_votes([a2, a2, b1])
    assert best(pv, a2, b1) == a2
    assert best(pv, b1, a2) == a2
    pv = with_votes([b1, b1, a2])
    assert best(pv, a2, b1) == b1

    # 3) equal votes, random_on_ties=False -> higher id wins (:252).
    pv = with_votes([a2, b1])
    assert best(pv, a2, b1) == max(a2, b1)
    assert best(pv, b1, a2) == max(a2, b1)


@pytest.mark.slow   # tier-1 budget (reports/TIER1_DURATIONS.md, PR-6
# round): 23 s warm — same-seed repeat of the 4000-ms Casper run whose
# semantics test_chain_growth_and_consensus already gates fast; the
# determinism CONTRACT keeps its fast gates via the Handel, GSF and
# PingPong determinism runs (the avalanche-determinism precedent).
def test_determinism():
    p = make(random_on_ties=False)
    r = Runner(p, donate=False)
    net1, ps1 = p.init(2)
    net2, ps2 = p.init(2)
    net1, ps1 = r.run_ms(net1, ps1, 4000)
    net2, ps2 = r.run_ms(net2, ps2, 4000)
    assert np.array_equal(np.asarray(ps1.head), np.asarray(ps2.head))
    assert int(ps1.arena.n) == int(ps2.arena.n)
    assert np.array_equal(np.asarray(ps1.att_head), np.asarray(ps2.att_head))
