"""Handel — practical multi-signature aggregation for large Byzantine
committees (arXiv:1906.05132).  The flagship protocol.

Reference: protocols/Handel.java (1054 lines).  Mechanism recap (SURVEY.md
§2.4): every node runs log2(N) binary-tree levels; per level it periodically
sends its best aggregate to one peer (round-robin through an emission list
ordered by the receivers' reception ranks, Handel.java:940-948,:991-1013);
incoming aggregates queue for verification; every `pairingTime` ms a node
picks ONE signature to verify using a variable-size rank window with a
scoring function (bestToVerify, Handel.java:566-630), simulating the pairing
cost; verified aggregates merge into per-level incoming sets, propagate into
upper levels' outgoing sets, trigger fast-path sends on level completion
(:738-743), and finish the node at the threshold (:747-749).

TPU-native design (all shapes fixed, everything vmappable over seeds):

* Level ranges partition the id space.  Node i's level-l peer set is the
  sibling half of its 2^l-aligned block (allSigsAtLevel, Handel.java:667-680)
  — contiguous and DISJOINT across levels.  So ONE [N, W] uint32 bitset row
  per node stores all levels' state at once (W = N/32 words), and a level's
  view is a computed range mask.  Per-level objects disappear.
* `totalIncoming = lastAggVerified | verifiedIndSignatures` and
  `totalOutgoing(l) = totalIncoming & block_mask(i, l-1)` are identities in
  the reference (updateVerifiedSignatures, Handel.java:686-750), so both are
  derived, not stored.  All per-level cardinalities come from ONE
  popcount-per-level primitive: word-level population counts contracted
  against a word→level one-hot on the MXU (`_level_pc`), since every 32-bit
  word of a node's row belongs to exactly one level.
* Reception ranks: the reference shuffles the full node list per node into an
  [N, N] rank matrix (setReceivingRanks, :940-948).  Impossible at 1M nodes;
  instead rank(i, s) = bij_perm(hash(seed, i), s) — a keyed bijective
  permutation, recomputed in-kernel (SURVEY.md §7.4.6).  Verification
  demotion (receptionRanks[from] += N, :830-834) becomes a per-(node, sender)
  `demoted` bit: one demotion is remembered, repeats are rare and absorbed.
* Messages carry (level, flags, round-slot) only — 3 words.  Signature bits
  are reconstructed at delivery from a rotating per-sender snapshot pool
  `pool[N, R, W]` written at send time: exact send-time aggregates without
  per-destination bitset copies in the mailbox (the same memory trick as the
  reference's recomputed-latency envelopes, Envelope.java:45-56; a fast-path
  write inside a dissemination round can refresh the same slot early, which
  only makes in-flight data marginally fresher).
* The unbounded per-level verification queues `toVerifyAgg` become ONE flat
  pool of Q slots per node tagged with (sender, level, rank); a slot's sig
  row holds only its level's range bits, so no per-level copies exist.  One
  entry per (sender, level) — newer aggregates supersede older (supersets in
  practice); evict the highest-rank entry when full.  bestToVerify's
  curation drops non-improving entries each pairing tick, exactly like the
  reference (:597-614).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core import builders
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import bitset, prng
from ..ops.flat import add2d, gather2d, gather_rows, set2d, set_rows
from ._levels import (LevelMixin, StaticScheduleMixin,
                      get_bit_rows as _get_bit_rows,
                      keyed_level_peer, merge_bounded_queue, sibling_base)

TAG_RANK = 0x48524E4B     # reception-rank permutation keys
TAG_BAD = 0x48424144      # bad-node choice
TAG_START = 0x48535452    # desynchronized start draw
TAG_LEVEL = 0x484C564C    # random level pick in checkSigs
TAG_EMIT = 0x48454D49     # hashed emission-order permutation keys

U32 = jnp.uint32
BIG = jnp.int32(1 << 30)


_sibling_base = sibling_base  # shared geometry (_levels.sibling_base)


@struct.dataclass
class HandelState:
    seed: jnp.ndarray          # int32 scalar
    start_at: jnp.ndarray      # int32 [N] (desynchronizedStart, Handel:56-61)
    pairing: jnp.ndarray       # int32 [N] nodePairingTime (speedRatio-scaled)
    ver_ind: jnp.ndarray       # u32 [N, W] verifiedIndSignatures (+ own bit)
    last_agg: jnp.ndarray      # u32 [N, W] lastAggVerified, all levels packed
    finished_peers: jnp.ndarray  # u32 [N, W]
    blacklist: jnp.ndarray     # u32 [N, W]
    demoted: jnp.ndarray       # u32 [N, W] — reception-rank demotion bits
    q_from: jnp.ndarray        # int32 [N, Q]  (-1 = empty slot)
    q_lvl: jnp.ndarray         # int32 [N, Q]
    q_rank: jnp.ndarray        # int32 [N, Q]
    q_bad: jnp.ndarray         # bool [N, Q]
    # The queued sig rows, stored as `state_split` node-range PIECES of
    # [N/P, Q, W] each (P == 1 -> a 1-tuple; layouts identical,
    # bit-equal for any P).  Same motivation as EngineConfig.box_split:
    # the TPU runtime faults on executions touching any single buffer
    # past ~1 GB, and [32768, Q, 1024] u32 pads to 1.07 GB under (8,
    # 128) tiling for ANY Q <= 8 (BENCH_NOTES.md r4) — splitting by
    # node range is what lets exact mode reach 32k on one chip.  The
    # receive merge and verification scoring also compute their
    # [*, Q|S, W] transients per piece, bounding peak memory the same
    # way.
    q_sig: tuple               # P x u32 [N/P, Q, W] — entry's level bits
    pool: jnp.ndarray          # u32 [N, R, W] — outgoing snapshots per round
    emission: jnp.ndarray      # int32 [N, N] — per-level sorted receiver ids
    pos: jnp.ndarray           # int32 [N, L] — posInLevel round-robin pointer
    curr_window: jnp.ndarray   # int32 [N]
    added_cycle: jnp.ndarray   # int32 [N] extraCycle countdown
    pend_from: jnp.ndarray     # int32 [N] in-flight verification (-1 = none)
    pend_level: jnp.ndarray    # int32 [N]
    pend_bad: jnp.ndarray      # bool [N]
    pend_sig: jnp.ndarray      # u32 [N, W]
    pend_at: jnp.ndarray       # int32 [N] — apply time
    fast_pending: jnp.ndarray  # int32 [N] — level bitmask of queued
    #                            fast-path sends (drained lowest-first,
    #                            one level per ms)
    sigs_checked: jnp.ndarray  # int32 [N]
    msg_filtered: jnp.ndarray  # int32 [N]
    evicted: jnp.ndarray       # int32 scalar — queue evictions (diagnostic)


@register
class Handel(LevelMixin, StaticScheduleMixin):
    """Parameters mirror Handel.HandelParameters (Handel.java:22-142).

    ``mode="cardinal"`` dispatches to the O(N*L)-state tier-3 variant
    (models/handel_cardinal.py, SCALE.md): same protocol semantics under
    count-based per-level aggregation, no O(N^2) state."""

    # Every unicast dest comes from a level peer set — the SIBLING half
    # of the node's 2^l-aligned block (models/_levels.py), which never
    # contains the node itself — so the latency model's floor licenses
    # superstep windows beyond 2 (core/network.unicast_floor_ms).
    may_self_send = False

    def __new__(cls, *args, mode="exact", **kwargs):
        if cls is Handel and mode == "cardinal":
            from .handel_cardinal import HandelCardinal
            obj = object.__new__(HandelCardinal)
            # Not a Handel subclass, so Python will not auto-call
            # __init__ on the returned object — do it here.  Cardinal
            # mode accepts the shared parameter subset; exact-only scale
            # switches (emission_mode, snapshot_pool, ...) are rejected
            # by its signature.
            obj.__init__(*args, **kwargs)
            return obj
        if mode not in ("exact", "cardinal"):
            raise ValueError(f"unknown Handel mode {mode!r}")
        return super().__new__(cls)

    def __init__(self, node_count=2048, threshold=None, pairing_time=3,
                 level_wait_time=50, extra_cycle=10,
                 dissemination_period_ms=10, fast_path=10, nodes_down=0,
                 node_builder_name=None, network_latency_name=None,
                 desynchronized_start=0, window_initial=16, window_min=1,
                 window_max=128, queue_cap=16, inbox_cap=16, horizon=512,
                 emission_lookahead=8, byzantine_suicide=False,
                 hidden_byzantine=False, emission_mode=None,
                 snapshot_pool=None, prefix_pc=None, pallas_merge=None,
                 state_split=1, mode="exact"):
        # `mode` is consumed by __new__ ("cardinal" dispatches to
        # HandelCardinal before this body runs); it reaches here only as
        # "exact".
        if node_count & (node_count - 1):
            raise ValueError("we support only power-of-two node counts "
                             "(Handel.java:119-121)")
        # Scale switches (SURVEY.md §7.4.6: stored [N, N] matrices cannot
        # exist at large N — recompute from hashes instead):
        # * emission_mode "stored" keeps the reference-exact emission lists
        #   (receivers sorted by the rank they assign to the sender,
        #   Handel.java:991-1013) as an [N, N] matrix; "hashed" derives the
        #   emission order from a keyed bijective permutation of the level
        #   range — O(1) state, but plain randomized round-robin: the
        #   rank-prioritized ordering (a convergence optimization) is lost.
        # * snapshot_pool False drops the [N, R, W] send-time snapshot pool;
        #   deliveries then reconstruct the aggregate from the sender's
        #   CURRENT state (marginally fresher than sent — the same
        #   direction of drift the pool's fast-path refresh already has).
        # Defaults cut over past 32768 nodes — exactly where the stored
        # matrix was previously a hard error, so configurations that ran
        # before keep their reference-exact semantics unchanged.
        if emission_mode is None:
            emission_mode = "stored" if node_count <= 32768 else "hashed"
        if emission_mode not in ("stored", "hashed"):
            raise ValueError(f"unknown emission_mode {emission_mode!r}")
        if snapshot_pool is None:
            snapshot_pool = node_count <= 32768
        if emission_mode == "stored" and node_count > 32768:
            raise ValueError("stored emission lists are O(N^2); use "
                             "emission_mode='hashed' past 32768 nodes")
        self.emission_mode = emission_mode
        self.snapshot_pool = snapshot_pool
        # Fused Pallas delivery-merge + verification-scoring kernels
        # (ops/pallas_merge.py, ops/pallas_score.py) — bit-identical to
        # the XLA paths (tests/test_pallas_merge.py, test_pallas_score
        # .py, test_handel.py::test_pallas_merge_path_bit_equal); CPU
        # runs with pallas_merge=True go through the interpreter.
        # Shared auto-default policy (resolve_pallas_default).
        from ..ops.pallas_merge import resolve_pallas_default
        self.pallas_merge = resolve_pallas_default(pallas_merge)
        if self.pallas_merge and queue_cap + inbox_cap > 255:
            # The kernel's unique-key headroom (BIG0 + position); fail
            # at construction, not after a 10-minute backend init.
            raise ValueError(
                f"pallas_merge supports queue_cap + inbox_cap <= 255 "
                f"(got {queue_cap} + {inbox_cap}); pass "
                "pallas_merge=False for wider rows")
        # Past ~16k nodes the [N, W, L] word->level one-hot for the MXU
        # popcount contraction is gigabytes; the prefix-sum path computes
        # the SAME values (tested bit-equal) in O(N * W).
        self.prefix_pc = (node_count > 16384 if prefix_pc is None
                          else prefix_pc)
        threshold = (int(node_count * 0.99) if threshold is None
                     else threshold)
        if not (0 <= nodes_down < node_count and
                threshold + nodes_down <= node_count):
            raise ValueError(f"nodeCount={node_count}, threshold={threshold},"
                             f" nodesDown={nodes_down} (Handel.java:113-118)")
        self.node_count = node_count
        self.threshold = threshold
        self.pairing_time = pairing_time
        self.level_wait_time = level_wait_time
        self.extra_cycle = extra_cycle
        self.period = dissemination_period_ms
        self.fast_path = fast_path
        self.nodes_down = nodes_down
        self.desynchronized_start = desynchronized_start
        self.window_initial = window_initial
        self.window_min = window_min
        self.window_max = window_max
        self.queue_cap = queue_cap
        self.emission_lookahead = emission_lookahead
        if (byzantine_suicide or hidden_byzantine) and not nodes_down:
            raise ValueError("byzantine attacks need nodes_down > 0 "
                             "(the attacker controls the down nodes)")
        self.byzantine_suicide = byzantine_suicide
        self.hidden_byzantine = hidden_byzantine
        # state_split: q_sig node-range pieces (see HandelState.q_sig).
        if node_count % state_split:
            raise ValueError(f"state_split {state_split} must divide "
                             f"node_count {node_count}")
        if state_split > 1 and (byzantine_suicide or hidden_byzantine):
            # The attack paths are O(N^2) sweeps only run at small N,
            # where splitting is never needed; keeping them unsplit
            # avoids blocking the queue-insert scatter.
            raise ValueError("state_split > 1 is for tier-2 scale runs; "
                             "byzantine attack modes require "
                             "state_split == 1")
        self.state_split = state_split
        self.builder = builders.get_by_name(node_builder_name)
        self.latency = latency_mod.get_by_name(network_latency_name)

        # The queue-merge sort key is rank * (Q + S + 1) + pos in int32
        # (see _merge_queue); ranks stay < 2*N even after demotion, so the
        # key is bounded by 2*N*(Q+S+1) — enforce it fits.
        if 2 * node_count * (queue_cap + inbox_cap + 1) >= 2 ** 31:
            raise ValueError(
                "queue-merge sort key would overflow int32: "
                f"2*{node_count}*({queue_cap}+{inbox_cap}+1) >= 2**31; "
                "reduce queue_cap/inbox_cap or node_count")
        # q_sig's flat gathers index Ns*Q*W int32 cells PER PIECE
        # (ops/flat.py); found the hard way at 65536 nodes x queue_cap
        # 16 (exactly 2^31).  state_split raises the ceiling
        # proportionally.
        _w = (node_count + 31) // 32
        _ns = node_count // state_split
        if _ns * queue_cap * _w >= 2 ** 31:
            raise ValueError(
                f"verification-queue flat index would overflow int32: "
                f"{_ns}*{queue_cap}*{_w} >= 2**31 per q_sig piece; "
                "reduce queue_cap or raise state_split (SCALE.md tier 2)")
        self.bits = max(1, int(math.log2(node_count)))
        self.levels = self.bits + 1            # levels 0..bits
        self.w = bitset.n_words(node_count)
        self.rounds = horizon // max(1, dissemination_period_ms) + 2
        # half[l] = size of the level-l peer range (0 for level 0).
        self.half = np.array([0] + [1 << (l - 1)
                                    for l in range(1, self.levels)],
                             np.int32)
        # K outbox slots: one per sending level (1..levels-1) + fast path.
        k = (self.levels - 1) + fast_path
        self.cfg = EngineConfig(n=node_count, horizon=horizon,
                                inbox_cap=inbox_cap, payload_words=3,
                                out_deg=k, bcast_slots=0)

    # ------------------------------------------------------------ primitives






    def _rank(self, seed, i_ids, s_ids):
        """Reception rank node i assigns to sender s (the [N, N] shuffled
        matrix of setReceivingRanks, Handel.java:940-948, as a keyed
        permutation)."""
        key = prng.hash3(seed, TAG_RANK, i_ids)
        return prng.bij_perm(key, s_ids, self.bits)

    def _emission_peer(self, seed, i_ids, level, pos):
        """Hashed emission order: the `pos`-th receiver of node i at
        `level` (replaces the stored per-(node, level) emission list,
        Handel.java:991-1013, for large N).  NOTE: the stored list is
        sorted by the rank receivers assign to the sender — a convergence
        optimization the keyed permutation does NOT reproduce; hashed mode
        is plain randomized round-robin (the GSF emission model)."""
        return jnp.minimum(
            keyed_level_peer(seed, TAG_EMIT, i_ids, level, pos),
            self.node_count - 1)

    def _byz_candidates(self, p, nodes, excl_bits):
        """Per (node, level) lowest-reception-rank byzantine (down) peer,
        excluding senders whose bit is set in `excl_bits` [N, W].  The
        adversary's peer scan of createSuicideByzantineSig
        (Handel.java:538-559) and HiddenByzantine.firstByzantine (:844-858),
        as masked per-level argmin sweeps over the contiguous level ranges.
        Returns ([N, L] rank — BIG when none, [N, L] id — -1 when none).
        O(N^2) work: only evaluated when an attack flag is on."""
        n, L = self.node_count, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        br = jnp.full((n, L), BIG, jnp.int32)
        bi = jnp.full((n, L), -1, jnp.int32)
        for l in range(1, L):
            half = 1 << (l - 1)
            base = _sibling_base(ids, half)
            cand = base[:, None] + jnp.arange(half, dtype=jnp.int32)[None, :]
            rank = self._rank(p.seed, ids[:, None], cand) + \
                jnp.where(_get_bit_rows(p.demoted, cand), n, 0)
            ok = nodes.down[cand] & ~_get_bit_rows(excl_bits, cand)
            rank = jnp.where(ok, rank, BIG)
            pos = jnp.argmin(rank, axis=1)
            best = jnp.take_along_axis(rank, pos[:, None], axis=1)[:, 0]
            bid = jnp.take_along_axis(cand, pos[:, None], axis=1)[:, 0]
            br = br.at[:, l].set(best)
            bi = bi.at[:, l].set(jnp.where(best < BIG, bid, -1))
        return br, bi

    # ---------------------------------------------------------------- init

    def init(self, seed):
        n, w, L, Q = self.node_count, self.w, self.levels, self.queue_cap
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        ids = jnp.arange(n, dtype=jnp.int32)

        # chooseBadNodes (Network.java:52-64): nodes_down distinct random.
        if self.nodes_down:
            pri = prng.uniform_u32(prng.hash2(seed, TAG_BAD), ids)
            down = jnp.zeros((n,), bool).at[
                jnp.argsort(pri)[:self.nodes_down]].set(True)
            nodes = nodes.replace(down=down)

        start_at = (prng.uniform_int(prng.hash2(seed, TAG_START), ids,
                                     self.desynchronized_start)
                    if self.desynchronized_start else
                    jnp.zeros((n,), jnp.int32))
        pairing = jnp.maximum(
            1, (self.pairing_time * nodes.speed_ratio)).astype(jnp.int32)

        # Emission lists: for each (node, level), receivers of the level
        # sorted by the rank THEY assign to us (Handel.java:991-1013), laid
        # out per node as concatenated levels (level l at columns
        # [2^(l-1), 2^l)); column 0 unused (level 0 has no peers).  In
        # hashed mode the order is a keyed permutation recomputed in-kernel
        # (see __init__) and no matrix exists.
        if self.emission_mode == "stored":
            emission = jnp.zeros((n, n), jnp.int32)
            for l in range(1, L):
                half = 1 << (l - 1)
                base = _sibling_base(ids, half)                   # [N]
                recv = base[:, None] + jnp.arange(half)[None, :]  # [N, half]
                key = self._rank(seed, recv,
                                 jnp.broadcast_to(ids[:, None], recv.shape))
                order = jnp.argsort(key * n + (recv - base[:, None]), axis=1)
                emission = emission.at[:, half:2 * half].set(
                    jnp.take_along_axis(recv, order, axis=1))
        else:
            emission = jnp.zeros((1, 1), jnp.int32)

        def zero_bits():
            # Fresh buffer per field: under donation the same buffer must
            # not appear twice in an executable's arguments.
            return jnp.zeros((n, w), U32)

        net = init_net(self.cfg, nodes, seed)
        pstate = HandelState(
            seed=seed, start_at=start_at, pairing=pairing,
            ver_ind=bitset.one_bit(ids, w), last_agg=zero_bits(),
            finished_peers=zero_bits(), blacklist=zero_bits(),
            demoted=zero_bits(),
            q_from=jnp.full((n, Q), -1, jnp.int32),
            q_lvl=jnp.zeros((n, Q), jnp.int32),
            q_rank=jnp.zeros((n, Q), jnp.int32),
            q_bad=jnp.zeros((n, Q), bool),
            q_sig=tuple(jnp.zeros((n // self.state_split, Q, w), U32)
                        for _ in range(self.state_split)),
            pool=(jnp.zeros((n, self.rounds, w), U32) if self.snapshot_pool
                  else jnp.zeros((1, 1, 1), U32)),
            emission=emission, pos=jnp.zeros((n, L), jnp.int32),
            curr_window=jnp.full((n,), self.window_initial, jnp.int32),
            added_cycle=jnp.full((n,), self.extra_cycle, jnp.int32),
            pend_from=jnp.full((n,), -1, jnp.int32),
            pend_level=jnp.zeros((n,), jnp.int32),
            pend_bad=jnp.zeros((n,), bool),
            pend_sig=jnp.zeros((n, w), U32),
            pend_at=jnp.zeros((n,), jnp.int32),
            fast_pending=jnp.zeros((n,), jnp.int32),
            sigs_checked=jnp.zeros((n,), jnp.int32),
            msg_filtered=jnp.zeros((n,), jnp.int32),
            evicted=jnp.asarray(0, jnp.int32),
        )
        return net, pstate

    # ---------------------------------------------------------------- step

    def step(self, p: HandelState, nodes, inbox, t, key, hints=None):
        h = hints or {}
        ids = jnp.arange(self.node_count, dtype=jnp.int32)
        active = (~nodes.down) & (t >= p.start_at + 1)
        onehot = None if self.prefix_pc else self._word_onehot(ids)
        subm = self._subword_masks(ids)
        hi = ids >> 5

        p = self._receive(p, nodes, inbox, t)
        if h.get("verify", True):
            p, nodes = self._apply_pending(p, nodes, t, onehot, subm, hi)
            p = self._pick_verification(p, nodes, t, active, onehot,
                                        subm, hi)
        p, out = self._disseminate(p, nodes, t, active, onehot, subm, hi,
                                   periodic=h.get("periodic", True))
        return p, nodes, out

    # -- receive: queue incoming aggregates (onNewSig, Handel.java:753-786)

    def _receive(self, p: HandelState, nodes, inbox, t):
        n, w, L, Q = self.node_count, self.w, self.levels, self.queue_cap
        P = self.state_split
        ns = n // P
        ids = jnp.arange(n, dtype=jnp.int32)
        done = nodes.done_at > 0

        valid = inbox.valid                                   # [N, S]
        src = jnp.clip(inbox.src, 0, n - 1)
        level = jnp.clip(inbox.data[:, :, 0], 0, L - 1)
        flags = inbox.data[:, :, 1]
        rslot = jnp.clip(inbox.data[:, :, 2], 0, self.rounds - 1)

        # Filters (Handel.java:755-763): done -> counted; pre-start or
        # blacklisted sender -> silently ignored.
        blk = _get_bit_rows(p.blacklist, src)
        ok = valid & ~done[:, None] & (t >= p.start_at)[:, None] & ~blk
        filtered = jnp.sum(valid & done[:, None], axis=1).astype(jnp.int32)
        fin = ok & ((flags & 1) != 0)
        rank_all = self._rank(p.seed, ids[:, None], src) + \
            jnp.where(_get_bit_rows(p.demoted, src), n, 0)

        # Queue merge, vectorized across ALL slots at once, per q_sig
        # node-range piece (bounds the [ns, S|Q, W] transients — see
        # HandelState.q_sig).  The reference queues every incoming
        # aggregate in an unbounded per-level list (onNewSig :753-786);
        # this implementation bounds memory with the shared
        # bounded-queue policy (_levels.merge_bounded_queue): one entry
        # per (sender, level) — newest wins — keep the Q best
        # (lowest-reception-rank) candidates.
        parts = {k: [] for k in ("from", "lvl", "rank", "bad")}
        pieces, fin_parts = [], []
        ev = jnp.asarray(0, jnp.int32)
        for j in range(P):
            sl = slice(j * ns, (j + 1) * ns)
            src_j, level_j, ok_j = src[sl], level[sl], ok[sl]
            # levelFinished -> finishedPeers (Handel.java:770-772).
            fin_bits = jnp.where(fin[sl][..., None],
                                 bitset.one_bit(src_j, w), U32(0))
            fin_parts.append(jax.lax.reduce(
                fin_bits, U32(0), jax.lax.bitwise_or, (1,)))
            # Reconstruct sigs from the senders' snapshot pool (one flat
            # gather); pool-free mode reads the sender's CURRENT
            # aggregate instead (see __init__).
            if self.snapshot_pool:
                sig_all = gather_rows(p.pool, src_j, rslot[sl]) & \
                    self._sender_block_mask(src_j, level_j)
            else:
                sig_all = (p.last_agg | p.ver_ind)[src_j] & \
                    self._sender_block_mask(src_j, level_j)
            if self.pallas_merge:
                from ..ops.pallas_merge import merge_queue_pallas
                q_f, q_l, q_r, q_b, q_s, ev_j = merge_queue_pallas(
                    p.q_from[sl], p.q_lvl[sl], p.q_rank[sl],
                    p.q_bad[sl], p.q_sig[j], src_j, level_j,
                    rank_all[sl], ok_j, sig_all, q_cap=Q,
                    interpret=jax.default_backend() != "tpu")
            else:
                sel2, sel3, ev_j = merge_bounded_queue(
                    p.q_from[sl], p.q_lvl[sl], p.q_rank[sl], src_j,
                    level_j, rank_all[sl], ok_j, Q,
                    {"bad": (p.q_bad[sl], jnp.zeros_like(ok_j))},
                    {"sig": (p.q_sig[j], sig_all)})
                q_f, q_l, q_r, q_b, q_s = (sel2["from"], sel2["lvl"],
                                           sel2["rank"], sel2["bad"],
                                           sel3["sig"])
            parts["from"].append(q_f)
            parts["lvl"].append(q_l)
            parts["rank"].append(q_r)
            parts["bad"].append(q_b)
            pieces.append(q_s)
            ev = ev + ev_j

        def cat(xs):
            return xs[0] if P == 1 else jnp.concatenate(xs, axis=0)

        finished = p.finished_peers | cat(fin_parts)
        return p.replace(q_from=cat(parts["from"]),
                         q_lvl=cat(parts["lvl"]),
                         q_rank=cat(parts["rank"]),
                         q_bad=cat(parts["bad"]),
                         q_sig=tuple(pieces), finished_peers=finished,
                         msg_filtered=p.msg_filtered + filtered,
                         evicted=p.evicted + ev)

    # -- apply a finished verification (updateVerifiedSignatures, :686-750)

    def _apply_pending(self, p: HandelState, nodes, t, onehot, subm, hi):
        n, w, L = self.node_count, self.w, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        due = (p.pend_from >= 0) & (t >= p.pend_at)

        vs_from, vs_level, vs_sig, vs_bad = (p.pend_from, p.pend_level,
                                             p.pend_sig, p.pend_bad)
        # Bad sig -> blacklist the sender (suicide attack, :690-699).
        bad = due & vs_bad
        blacklist = jnp.where(bad[:, None],
                              p.blacklist | bitset.one_bit(vs_from, w),
                              p.blacklist)
        ok = due & ~vs_bad

        lmask = self._range_mask_dyn(ids, vs_level)           # [N, W]
        from_bit = bitset.one_bit(jnp.maximum(vs_from, 0), w)
        ver_ind = jnp.where(ok[:, None], p.ver_ind | from_bit, p.ver_ind)

        # lastAgg(level) = sig if it intersects the old, else old | sig —
        # only when the combined set improves on verifiedInd (:710-724).
        old_agg_l = p.last_agg & lmask
        ver_l = ver_ind & lmask
        improves = (bitset.popcount(vs_sig | ver_l) >
                    bitset.popcount(ver_l))
        inter = bitset.intersects(old_agg_l, vs_sig)
        new_agg_l = jnp.where((improves & inter)[:, None], vs_sig,
                              jnp.where(improves[:, None],
                                        old_agg_l | vs_sig, old_agg_l))
        last_agg = jnp.where(ok[:, None],
                             (p.last_agg & ~lmask) | new_agg_l, p.last_agg)

        total_inc = last_agg | ver_ind
        inc_pc = self._level_pc(total_inc, onehot, subm, hi)  # [N, L]
        halfs = jnp.asarray(self.half)[None, :]               # [1, L]
        vs_half = jnp.where(vs_level > 0,
                            1 << jnp.clip(vs_level - 1, 0, 30), 0)
        vs_inc = gather2d(inc_pc, ids, vs_level)
        just_completed = ok & (vs_inc >= vs_half) & (vs_half > 0)

        # Fast path (:738-743): on level completion, EVERY upper level
        # whose outgoing set is complete sends to fast_path peers.  The
        # reference sends them all in the same event; here the qualifying
        # levels queue into a bitmask drained one level per ms (K-slot
        # budget) — a <=L-ms stagger, far below the dissemination period.
        fast_pending = p.fast_pending
        if self.fast_path > 0:
            og_size = 1 + jnp.cumsum(inc_pc, axis=1) - inc_pc  # sum l'<l
            og_complete = og_size >= halfs                     # [N, L]
            cand = (og_complete &
                    (jnp.arange(L)[None, :] > vs_level[:, None]) &
                    (halfs > 0) & just_completed[:, None])
            bits = jnp.sum(
                jnp.where(cand, jnp.int32(1) << jnp.arange(L)[None, :], 0),
                axis=1).astype(jnp.int32)
            fast_pending = fast_pending | bits

        # doneAt at threshold (:747-749).
        total_card = bitset.popcount(total_inc)
        done_now = (nodes.done_at == 0) & ok & (total_card >= self.threshold)
        nodes = nodes.replace(done_at=jnp.where(
            done_now, jnp.maximum(t, 1), nodes.done_at).astype(jnp.int32))

        p = p.replace(blacklist=blacklist, ver_ind=ver_ind,
                      last_agg=last_agg, fast_pending=fast_pending,
                      pend_from=jnp.where(due, -1, p.pend_from))
        return p, nodes

    # -- pick next signature to verify (checkSigs/bestToVerify, :566-630)

    def _pick_verification(self, p: HandelState, nodes, t, active,
                           onehot, subm, hi):
        n, w, L, Q = self.node_count, self.w, self.levels, self.queue_cap
        ids = jnp.arange(n, dtype=jnp.int32)
        due = (active & (p.pend_from < 0) &
               ((t - (p.start_at + 1)) % p.pairing == 0))

        total_inc = p.last_agg | p.ver_ind
        inc_pc = self._level_pc(total_inc, onehot, subm, hi)   # [N, L]
        ver_pc = self._level_pc(p.ver_ind, onehot, subm, hi)
        agg_pc = self._level_pc(p.last_agg, onehot, subm, hi)
        halfs = jnp.asarray(self.half)[None, :]

        rows = ids[:, None]
        filled = p.q_from >= 0                                 # [N, Q]
        elvl = p.q_lvl
        cur_size = gather2d(inc_pc, rows, elvl)                # [N, Q]
        blk = _get_bit_rows(p.blacklist, jnp.maximum(p.q_from, 0))

        # The W-wide queue work — sizeIfIncluded (:545-552) and the
        # score popcounts (:651-664) — runs per q_sig node-range piece
        # (bounds the [ns, Q, W] transients; see HandelState.q_sig),
        # emitting only [ns, Q] summaries.
        P = self.state_split
        ns = n // P
        s_inc_p, pc_sig_p, pc_sv_p, inter_agg_p = [], [], [], []
        for j in range(P):
            sl = slice(j * ns, (j + 1) * ns)
            sig = p.q_sig[j]                                  # [ns, Q, W]
            if self.pallas_merge:
                # Same switch as the delivery-merge kernel: one fused
                # pass instead of ~6 HBM round-trips over the sig plane
                # (ops/pallas_score.py, bit-equal by test).
                from ..ops.pallas_score import score_queue_pallas
                si, ps, pv, ia = score_queue_pallas(
                    sig, elvl[sl], ids[sl], total_inc[sl], p.ver_ind[sl],
                    p.last_agg[sl],
                    interpret=jax.default_backend() != "tpu")
                s_inc_p.append(si)
                pc_sig_p.append(ps)
                pc_sv_p.append(pv)
                inter_agg_p.append(ia)
                continue
            emask = self._range_mask_dyn(ids[sl][:, None], elvl[sl])
            inc_e = total_inc[sl][:, None, :] & emask
            ver_e = p.ver_ind[sl][:, None, :] & emask
            agg_e = p.last_agg[sl][:, None, :] & emask
            disj = ~bitset.intersects(sig, inc_e)
            merged = jnp.where(disj[..., None], sig | inc_e, sig)
            s_inc_p.append(bitset.popcount(merged | ver_e))
            pc_sig_p.append(bitset.popcount(sig))
            pc_sv_p.append(bitset.popcount(sig | ver_e))
            inter_agg_p.append(bitset.intersects(sig, agg_e))

        def cat(xs):
            return xs[0] if P == 1 else jnp.concatenate(xs, axis=0)

        s_inc = cat(s_inc_p)
        pc_sig = cat(pc_sig_p)
        pc_sig_ver = cat(pc_sv_p)
        inter_agg = cat(inter_agg_p)
        improving = filled & ~blk & (s_inc > cur_size)
        keep = improving | ~filled          # curation (:597-614)

        # windowIndex = min rank over the whole queue per level (:573-574).
        lvl_eq = (elvl[:, None, :] ==
                  jnp.arange(L, dtype=jnp.int32)[None, :, None])  # [N, L, Q]
        rank_b = jnp.where(filled[:, None, :] & lvl_eq, p.q_rank[:, None, :],
                           BIG)
        win_lo = jnp.min(rank_b, axis=2)                       # [N, L]
        win_lo_e = gather2d(win_lo, rows, elvl)
        inside = improving & (p.q_rank <= win_lo_e +
                              p.curr_window[:, None])

        # score (:651-664) — from the per-piece popcount summaries.
        halfs_arr = jnp.asarray(self.half)
        agg_card_e = gather2d(agg_pc, rows, elvl)
        half_e = halfs_arr[elvl]
        sc_disj = agg_card_e + pc_sig
        sc_join = jnp.maximum(0, pc_sig_ver - agg_card_e)
        score = jnp.where(inter_agg, sc_join, sc_disj)
        score = jnp.where(agg_card_e >= half_e, 0, score)
        score_in = jnp.where(inside, score, -1)

        # Per-level best: inside-window best score, else lowest rank outside.
        score_b = jnp.where(lvl_eq, score_in[:, None, :], -1)
        in_slot = jnp.argmax(score_b, axis=2)                  # [N, L]
        in_ok = jnp.max(score_b, axis=2) > 0
        out_rank_b = jnp.where(lvl_eq & (improving & ~inside)[:, None, :],
                               p.q_rank[:, None, :], BIG)
        out_slot = jnp.argmin(out_rank_b, axis=2)
        out_ok = jnp.min(out_rank_b, axis=2) < BIG
        best_slot = jnp.where(in_ok, in_slot, out_slot)        # [N, L]
        has_best = (in_ok | out_ok) & due[:, None]

        # byzantineSuicide (Handel.java:538-559, :577-583): if a still-
        # unblacklisted byzantine peer's rank falls inside the level's
        # verification window, the adversary plants an invalid signature
        # from it, and it preempts the level's honest pick.
        if self.byzantine_suicide:
            sbr, sbi = self._byz_candidates(p, nodes, p.blacklist)
            # Strict < here vs <= in the honest window test above is the
            # reference's own boundary convention (:545 `rank < maxRank`
            # vs :597 `rank <= windowIndex + currWindowSize`).
            s_ok = ((win_lo < BIG) &
                    (sbr < win_lo + p.curr_window[:, None]))   # [N, L]
            has_best = has_best | (s_ok & due[:, None])

        # chooseBestFromLevels (:788-790): uniform random non-empty level.
        cnt = jnp.sum(has_best, axis=1).astype(jnp.int32)
        r = prng.uniform_int(prng.hash3(p.seed, TAG_LEVEL, t), ids,
                             jnp.maximum(cnt, 1))
        csum = jnp.cumsum(has_best, axis=1).astype(jnp.int32)
        pick_level = jnp.argmax((csum == r[:, None] + 1) & has_best, axis=1)
        do = due & (cnt > 0)

        slot = gather2d(best_slot, ids, pick_level)
        vfrom = gather2d(p.q_from, ids, slot)
        vbad = gather2d(p.q_bad, ids, slot)
        vsig = cat([gather_rows(p.q_sig[j],
                                jnp.arange(ns, dtype=jnp.int32),
                                slot[j * ns:(j + 1) * ns])
                    for j in range(P)])
        # keep_entry: the picked QUEUE slot survives (an adversarial sig was
        # verified instead; the honest entry stays queued, :577-583,:905-913).
        keep_entry = jnp.zeros_like(do)

        if self.byzantine_suicide:
            use_s = do & gather2d(s_ok, ids, pick_level)
            s_id = gather2d(sbi, ids, pick_level)
            # An s_ok level may have no honest candidate at all; the planted
            # sig is then the only pick for it.
            vfrom = jnp.where(use_s, s_id, vfrom)
            vbad = vbad | use_s
            vsig = jnp.where(use_s[:, None], U32(0), vsig)
            keep_entry = keep_entry | use_s

        # HiddenByzantine (Handel.java:840-917): flood with valid but useless
        # single-signer aggregates from byzantine peers.  If a byzantine peer
        # outranks the picked signature, the adversary injects a 1-bit sig
        # from it; a rerun of bestToVerify then either verifies the plant
        # (wasting the pairing slot) or leaves it polluting the queue.
        if self.hidden_byzantine:
            hbr, hbi = self._byz_candidates(p, nodes,
                                            p.blacklist | total_inc)
            h_rank = gather2d(hbr, ids, pick_level)
            h_id = gather2d(hbi, ids, pick_level)
            honest = do & ~keep_entry
            # No re-attack while the previous plant for this (id, level) is
            # still queued (the `last`-in-toVerifyAgg check, :883-893).
            queued = jnp.any((p.q_from == h_id[:, None]) &
                             (p.q_lvl == pick_level[:, None]), axis=1)
            can = (honest & (h_id >= 0) & ~queued &
                   (h_rank < gather2d(p.q_rank, ids, slot)))   # :898-901
            # Rerun verdict: the plant is inside its own window; it beats an
            # outside-window pick outright, an inside pick only on score.
            # Plant score = aggregate card + 1 (disjoint single bit, :651-664).
            h_score = gather2d(agg_pc, ids, pick_level) + 1
            s_picked = gather2d(score, ids, slot)
            was_in = gather2d(in_ok, ids, pick_level)
            h_win = can & (~was_in | (h_score > s_picked))
            h_sig = bitset.one_bit(jnp.maximum(h_id, 0), w)
            vfrom = jnp.where(h_win, h_id, vfrom)
            vbad = vbad & ~h_win
            vsig = jnp.where(h_win[:, None], h_sig, vsig)
            keep_entry = keep_entry | h_win
            h_fail = can & ~h_win                               # :905-913

        # Window resize (:821-823): grow on good, shrink on bad, clamped to
        # [min, max] then to the level size.
        lsize = jnp.maximum(halfs_arr[pick_level], 1)
        grown = jnp.where(vbad, p.curr_window // 4, 2 * p.curr_window)
        new_win = jnp.clip(grown, self.window_min, self.window_max)
        curr_window = jnp.where(do, jnp.minimum(new_win, lsize),
                                p.curr_window)

        # Rank demotion (:830-834) — remembered as a bit.
        demoted = jnp.where(
            do[:, None],
            p.demoted | bitset.one_bit(jnp.maximum(vfrom, 0), w), p.demoted)

        # Curation sweep for due nodes + removal of the picked entry.
        q_from = jnp.where(due[:, None] & ~keep, -1, p.q_from)
        q_from = set2d(q_from, ids, slot, -1, ok=do & ~keep_entry)
        q_lvl, q_rank, q_bad, q_sig = p.q_lvl, p.q_rank, p.q_bad, p.q_sig

        if self.hidden_byzantine:
            # A failed attack leaves the plant in the queue (:905-913),
            # in a free slot or evicting the worst-ranked entry.
            free = q_from < 0
            any_free = jnp.any(free, axis=1)
            worst = jnp.argmax(jnp.where(free, -1, q_rank), axis=1)
            worst_rank = jnp.take_along_axis(q_rank, worst[:, None],
                                             axis=1)[:, 0]
            islot = jnp.where(any_free, jnp.argmax(free, axis=1), worst)
            ins = h_fail & (any_free | (h_rank < worst_rank))
            q_from = set2d(q_from, ids, islot, h_id, ok=ins)
            q_lvl = set2d(q_lvl, ids, islot, pick_level, ok=ins)
            q_rank = set2d(q_rank, ids, islot, h_rank, ok=ins)
            q_bad = set2d(q_bad, ids, islot, False, ok=ins)
            # state_split == 1 enforced for attack modes (__init__).
            q_sig = (set_rows(q_sig[0], ids, islot, h_sig, ok=ins),)

        return p.replace(
            q_from=q_from, q_lvl=q_lvl, q_rank=q_rank, q_bad=q_bad,
            q_sig=q_sig, curr_window=curr_window, demoted=demoted,
            pend_from=jnp.where(do, vfrom, p.pend_from),
            pend_level=jnp.where(do, pick_level, p.pend_level),
            pend_bad=jnp.where(do, vbad, p.pend_bad),
            pend_sig=jnp.where(do[:, None], vsig, p.pend_sig),
            pend_at=jnp.where(do, t + p.pairing, p.pend_at),
            sigs_checked=p.sigs_checked + do.astype(jnp.int32))

    # -- dissemination (doCycle, :331-343,:470-504) + outbox assembly

    def _disseminate(self, p: HandelState, nodes, t, active,
                     onehot, subm, hi, periodic=True):
        n, w, L = self.node_count, self.w, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        done = nodes.done_at > 0
        halfs_np = self.half                                   # numpy [L]
        halfs = jnp.asarray(halfs_np)[None, :]
        total_inc = p.last_agg | p.ver_ind
        bad_bits = p.finished_peers | p.blacklist
        rslot = (t // self.period) % self.rounds
        # Non-periodic ms can only populate the fast-path slots: emit a
        # NARROW outbox covering just those columns (slot ids preserved
        # via Outbox.slot0, so latency draws stay bit-identical) — the
        # engine's binning sort then runs over n*fast_path entries
        # instead of n*out_deg.
        K = self.cfg.out_deg if periodic else max(1, self.fast_path)
        koff = L - 1 if periodic else 0
        dest = jnp.full((n, K), -1, jnp.int32)
        payload = jnp.zeros((n, K, 3), jnp.int32)
        sizes = jnp.ones((n, K), jnp.int32)

        # `periodic=False` (static phase hint, see `scan_chunk`): no node
        # can be on a period boundary this ms, so the per-period
        # dissemination block below — level popcounts, open-level tests
        # and the emission-list lookahead — reduces to the identity it
        # would have computed (send_l all-False, pos/added_cycle
        # unchanged, level outbox slots empty) and is skipped entirely.
        # Only the fast path (which drains every ms) remains.
        if periodic:
            per_due = active & ((t - (p.start_at + 1)) % self.period == 0)
            # extraCycle (:331-343): done nodes keep disseminating for
            # added_cycle more periods.
            send_ok = per_due & (~done | (p.added_cycle > 0))
            added_cycle = jnp.where(per_due & done,
                                    jnp.maximum(p.added_cycle - 1, 0),
                                    p.added_cycle)

            inc_pc = self._level_pc(total_inc, onehot, subm, hi)  # [N, L]
            og_size = 1 + jnp.cumsum(inc_pc, axis=1) - inc_pc  # sum l'<l + own
            og_complete = og_size >= halfs
            inc_complete = inc_pc >= halfs
            lvl_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
            is_open = ((t >= (lvl_idx - 1) * self.level_wait_time) |
                       og_complete) & (halfs > 0)

            # Candidate existence per level: any waited peer not finished and
            # not blacklisted (else outgoingFinished, :470-504).
            fin_pc = self._level_pc(bad_bits, onehot, subm, hi)
            any_cand = (halfs - fin_pc) > 0

            # Round-robin pick: next non-finished peer in emission order,
            # looking ahead `look` entries from posInLevel.
            look = self.emission_lookahead
            half_cols = jnp.maximum(halfs, 1)                  # [1, L]
            offs = (p.pos[:, :, None] + jnp.arange(look)[None, None, :]) % \
                half_cols[:, :, None]                          # [N, L, k]
            if self.emission_mode == "stored":
                cols = jnp.minimum(half_cols[:, :, None] + offs, n - 1)
                cand_ids = gather2d(p.emission, ids[:, None, None], cols)
            else:
                cand_ids = self._emission_peer(p.seed, ids[:, None, None],
                                               lvl_idx[:, :, None], offs)
            okc = ~_get_bit_rows(bad_bits, cand_ids)           # [N, L, k]
            found = jnp.any(okc, axis=2)
            first = jnp.argmax(okc, axis=2)
            # candidate at the first ok position (max trick: invalid -> -1).
            peer = jnp.max(jnp.where(
                okc & (jnp.arange(look)[None, None, :] == first[..., None]),
                cand_ids, -1), axis=2)                         # [N, L]

            send_l = send_ok[:, None] & is_open & any_cand & found
            adv = per_due[:, None] & is_open & any_cand
            pos = jnp.where(adv,
                            (p.pos + jnp.where(found, first + 1, look)) %
                            half_cols, p.pos)

            # SendSigs size (bytes): 1 + expected/8 + 96*2 (:255-259).
            sz_l = 1 + halfs // 8 + 192                        # [1, L]
            dest = dest.at[:, :L - 1].set(jnp.where(send_l, peer, -1)[:, 1:])
            payload = payload.at[:, :L - 1, 0].set(lvl_idx[:, 1:])
            payload = payload.at[:, :L - 1, 1].set(
                inc_complete.astype(jnp.int32)[:, 1:])
            payload = payload.at[:, :L - 1, 2].set(rslot)
            sizes = sizes.at[:, :L - 1].set(
                jnp.broadcast_to(sz_l, (n, L))[:, 1:])
        else:
            added_cycle = p.added_cycle
            pos = p.pos

        # Fast-path sends on level completion (:738-743), bypassing the
        # period gate: drain the lowest queued level's fast_path peers.
        fast_pending = p.fast_pending
        if self.fast_path > 0:
            fp = self.fast_path
            lsb = fast_pending & -fast_pending
            fl = jnp.where(lsb > 0,
                           31 - jax.lax.clz(jnp.maximum(lsb, 1)), 0)
            fl = fl.astype(jnp.int32)                          # [N], 0 = none
            halfs_arr = jnp.asarray(halfs_np)
            fhalf = jnp.maximum(halfs_arr[fl], 1)
            fpos = gather2d(pos, ids, fl)
            foffs = (fpos[:, None] + jnp.arange(fp)[None, :]) % \
                fhalf[:, None]
            if self.emission_mode == "stored":
                fcols = jnp.minimum(fhalf[:, None] + foffs, n - 1)
                fids = gather2d(p.emission, ids[:, None], fcols)
            else:
                fids = self._emission_peer(p.seed, ids[:, None],
                                           fl[:, None], foffs)
            fok = ~_get_bit_rows(bad_bits, fids)
            fsend = (fl > 0) & active & ~done
            fdest = jnp.where(fsend[:, None] & fok, fids, -1)
            dest = dest.at[:, koff:koff + fp].set(fdest)
            payload = payload.at[:, koff:koff + fp, 0].set(fl[:, None])
            payload = payload.at[:, koff:koff + fp, 2].set(rslot)
            sizes = sizes.at[:, koff:koff + fp].set(
                (1 + fhalf // 8 + 192)[:, None])
            pos = add2d(pos, ids, jnp.maximum(fl, 1),
                        jnp.where(fsend, jnp.sum(fok, axis=1), 0))
            fast_pending = jnp.where(fsend, fast_pending & ~lsb,
                                     fast_pending)
            # Done nodes never fast-path again; drop stale queued levels.
            fast_pending = jnp.where(done, 0, fast_pending)

        # Snapshot pool: any sender this ms records its current total_inc;
        # receivers mask out their level's view at delivery.
        if self.snapshot_pool:
            wrote = jnp.any(dest >= 0, axis=1)
            pool = set_rows(p.pool, ids, jnp.full((n,), rslot, jnp.int32),
                            total_inc, ok=wrote)
        else:
            pool = p.pool

        # slot0 clamped into [0, out_deg): with fast_path == 0 the narrow
        # non-periodic outbox is a single always-empty column (dest all
        # -1), and slot0 == L-1 == out_deg would collide its stable
        # latency-key slot id with the next node's slot 0 (ADVICE r3).
        out = empty_outbox(self.cfg, k=K,
                           slot0=0 if periodic else
                           min(L - 1, self.cfg.out_deg - 1)).replace(
            dest=dest, payload=payload, size=sizes)
        return p.replace(pos=pos, added_cycle=added_cycle, pool=pool,
                         fast_pending=fast_pending), out

    # ---------------------------------------------------------------- misc

    def done(self, pstate, nodes):
        return jnp.all(nodes.down | (nodes.done_at > 0))


def cont_if_handel(net, pstate):
    """Handel.newContIf (Handel.java:1040-1049): run while any live node is
    not done or still owes extra dissemination cycles."""
    live = ~net.nodes.down
    return jnp.any(live & ((net.nodes.done_at == 0) |
                           (pstate.added_cycle > 0)))
