"""Tools tests — Graph/CSVFormatter/NodeDrawer parity smoke tests
(GraphTest / CSVFormatterTest / NodeDrawerTest analogues)."""

import os

import numpy as np

from wittgenstein_tpu.core import builders
from wittgenstein_tpu.tools.csvf import CSVFormatter
from wittgenstein_tpu.tools.graph import (Graph, Series, clean_series,
                                          stat_series)
from wittgenstein_tpu.tools.node_drawer import NodeDrawer


def test_csv_formatter():
    c = CSVFormatter(["a", "b"])
    c.add(a=1, b=2)
    c.add(b=4, a=3)
    c.add(a=5)                       # missing column -> empty cell
    assert str(c) == "a,b\n1,2\n3,4\n5,\n"


def test_stat_and_clean_series():
    r1 = Series("r1"); r2 = Series("r2")
    for x, (y1, y2) in enumerate([(1, 3), (2, 4), (5, 5), (5, 5), (5, 5)]):
        r1.add(x, y1); r2.add(x, y2)
    st = stat_series("s", [r1, r2])
    assert st["min"].ys == [1, 2, 5, 5, 5]
    assert st["max"].ys == [3, 4, 5, 5, 5]
    assert st["avg"].ys == [2, 3, 5, 5, 5]
    clean_series([r1, r2])           # trim the shared flat tail
    assert len(r1.ys) == 3


def test_graph_png(tmp_path):
    g = Graph("t", "x", "y")
    s = Series("s")
    for i in range(10):
        s.add(i, i * i)
    g.add_series(s)
    path = str(tmp_path / "g.png")
    g.save(path)
    assert os.path.getsize(path) > 1000


def test_node_drawer_gif(tmp_path):
    nodes = builders.NodeBuilder().build(0, 50)
    d = NodeDrawer(vmin=0, vmax=1, dot=3)
    for f in range(3):
        d.draw(nodes, np.linspace(0, 1, 50))
    path = str(tmp_path / "n.gif")
    d.save_gif(path)
    assert os.path.getsize(path) > 1000


def test_node_drawer_world_map_background():
    """NodeDrawer.java:20-24 parity: the bundled world-map-2000px.png is
    the frame background (vendored asset, attributed like citydata.npz)."""
    from wittgenstein_tpu.tools.node_drawer import _MAP_PATH, _background

    assert os.path.exists(_MAP_PATH)
    img = _background()
    from wittgenstein_tpu.core.state import MAX_X, MAX_Y
    assert img.size == (MAX_X, MAX_Y)
    # A real map is not the flat synthesized graticule (exactly 2
    # colors): the anti-aliased landmass has a broader palette.
    arr = np.asarray(img)
    assert len(np.unique(arr.reshape(-1, 3), axis=0)) > 8


def test_city_population_weighting():
    """CityPopulationTest parity (core CityPopulationTest.java): the
    'cities' builder samples cities proportionally to population via the
    cumulative-probability table (NodeBuilder.java:127-139)."""
    import numpy as np
    from wittgenstein_tpu.core.builders import NodeBuilder, load_city_db

    _, _, _, pops = load_city_db()
    share = pops / pops.sum()
    n = 20_000
    nodes = NodeBuilder(location="cities").build(11, n)
    city = np.asarray(nodes.city)
    assert (city >= 0).all() and (city < len(pops)).all()
    counts = np.bincount(city, minlength=len(pops))
    emp = counts / n
    # The top-population city must be sampled near its share, and overall
    # the empirical distribution must track population shares.
    top = int(np.argmax(share))
    assert emp[top] > 0.5 * share[top]
    assert emp[top] < 2.0 * share[top] + 0.01
    # L1 distance between empirical and target distribution is small.
    assert float(np.abs(emp - share).sum()) < 0.25
    # Heaviest decile of cities holds its population share of nodes.
    order = np.argsort(share)[::-1]
    k = max(1, len(pops) // 10)
    target = share[order[:k]].sum()
    assert abs(emp[order[:k]].sum() - target) < 0.05
