"""Scenario-driver smoke tests (VERDICT r1 #9): one tiny-config invocation
per sweep in scenarios/ asserting it produces output, so the analog of
HandelScenarios.java:163-604 cannot rot silently.

Each sweep writes CSV (and sometimes PNG/GIF) into tmp_path and returns the
CSVFormatter; we assert the file exists and carries the swept rows.
"""

import os

import pytest

from wittgenstein_tpu.scenarios import (gsf_scenarios, handel_scenarios,
                                        optimistic_scenarios,
                                        p2phandel_scenarios)


def _rows(csv_path):
    with open(csv_path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    return lines


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 22 s sweep smoke; Handel itself is heavily covered in the fast suite
def test_handel_tor_sweep_smoke(tmp_path):
    csv = handel_scenarios.tor_sweep(fractions=(0.33,), nodes=32, seeds=2,
                                     out_dir=str(tmp_path))
    assert csv.rows, "sweep produced no rows"
    lines = _rows(tmp_path / "handel_tor.csv")
    assert lines[0].startswith("tor") and len(lines) == 2


def test_optimistic_node_scaling_smoke(tmp_path):
    csv = optimistic_scenarios.node_scaling(counts=(32,), seeds=2,
                                            out_dir=str(tmp_path))
    assert csv.rows
    assert len(_rows(tmp_path / "optimistic_scaling.csv")) == 2


@pytest.mark.slow
def test_handel_node_scaling_smoke(tmp_path):
    csv = handel_scenarios.node_scaling(counts=(32,), seeds=2,
                                        out_dir=str(tmp_path))
    assert csv.rows
    assert os.path.exists(tmp_path / "handel_node_scaling.csv")
    assert os.path.exists(tmp_path / "handel_node_scaling.png")


@pytest.mark.slow
def test_handel_desync_sweep_smoke(tmp_path):
    csv = handel_scenarios.desync_sweep(starts=(50,), nodes=32, seeds=2,
                                        out_dir=str(tmp_path))
    assert csv.rows
    assert len(_rows(tmp_path / "handel_desync.csv")) == 2


@pytest.mark.slow
def test_handel_byz_sweeps_smoke(tmp_path):
    csv = handel_scenarios.byz_suicide_sweep(ratios=(0.25,), nodes=32,
                                             seeds=2, out_dir=str(tmp_path))
    assert csv.rows
    csv = handel_scenarios.hidden_byz_sweep(ratios=(0.25,), nodes=32,
                                            seeds=2, out_dir=str(tmp_path))
    assert csv.rows


@pytest.mark.slow
def test_handel_log_errors_smoke(tmp_path):
    csv = handel_scenarios.log_errors(error_rate=0.2, counts=(32,), seeds=2,
                                      out_dir=tmp_path)
    assert csv.rows
    assert os.path.exists(tmp_path / "handel_errors.csv")
    assert os.path.exists(tmp_path / "handel_errors.png")


@pytest.mark.slow
def test_handel_extra_cycle_sweep_smoke(tmp_path):
    csv = handel_scenarios.extra_cycle_sweep(cycles=(10,), nodes=32,
                                             seeds=2, out_dir=tmp_path)
    assert csv.rows
    assert os.path.exists(tmp_path / "handel_extra_cycle.csv")


@pytest.mark.slow
def test_handel_contacted_node_sweep_smoke(tmp_path):
    csv = handel_scenarios.contacted_node_sweep(fast_paths=(0, 10),
                                                nodes=32, seeds=2,
                                                out_dir=tmp_path)
    assert csv.rows
    assert os.path.exists(tmp_path / "handel_fastpath.csv")
    # fast_path=0 must still complete (the fast path is an optimization).
    fd = csv.columns.index("frac_done")
    assert all(r[fd] == 1.0 for r in csv.rows)


@pytest.mark.slow
def test_handel_period_sweep_smoke(tmp_path):
    csv = handel_scenarios.period_sweep(periods=(20,), nodes=32, seeds=2,
                                        out_dir=str(tmp_path))
    assert csv.rows


@pytest.mark.slow
def test_handel_gen_anim_smoke(tmp_path):
    out = handel_scenarios.gen_anim(nodes=32,
                                    out_path=str(tmp_path / "h.gif"),
                                    frames=4, frame_ms=50)
    assert os.path.getsize(out) > 0


@pytest.mark.slow
def test_gsf_scenarios_smoke(tmp_path):
    csv = gsf_scenarios.sigs_per_time(nodes=32, max_time=1500,
                                      stat_each_ms=100,
                                      out_dir=str(tmp_path))
    assert csv.rows, "no samples collected"
    assert os.path.exists(tmp_path / "gsf_sigs_per_time.png")
    gif = gsf_scenarios.draw_imgs(nodes=32,
                                  out_path=str(tmp_path / "g.gif"),
                                  frames=4, frame_ms=50)
    assert os.path.getsize(gif) > 0


@pytest.mark.slow
def test_p2phandel_strategy_sweep_smoke(tmp_path):
    # signers+relays must exceed the default connection target (40,
    # P2PHandel.java parity) — 64+8 is the module's own smoke config.
    csv = p2phandel_scenarios.strategy_sweep(
        signers=64, relays=8, seeds=2, out_dir=str(tmp_path),
        strategies=(p2phandel_scenarios.ALL,))
    assert csv.rows
