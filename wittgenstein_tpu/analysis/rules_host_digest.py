"""Rule ``host_digest`` — digest/compile-key purity, statically.

Every cross-run contract in the campaign stack keys on a digest:
`ScenarioSpec.digest()` names ledger rows, `compile_key()` names
compile-cache groups, `SweepGrid.grid_digest()` names campaigns,
`MemoTable.key()` names persisted prefix states.  The BFT-scale sweep
papers only trust campaign results because every cell is reproducible
— so a digest that reads the clock, the environment, or Python's
per-process `hash()`/`id()` breaks resume, memoization and dedup at
once, silently (the digest still LOOKS fine; it just never matches
again).

This rule taint-walks the call graph from every digest entry point
(any function whose name matches ``digest``/``compile_key``, plus
`MemoTable.key`) across the scanned host modules (serve/, matrix/,
memo/, obs/, utils/) and errors on reachable:

  * wall-clock / PRNG / uniqueness sources: ``time.*``,
    ``datetime.*``, ``random.*``, ``numpy.random*``, ``uuid.*``,
    ``secrets.*``, ``os.urandom``;
  * ambient state: ``os.environ`` / ``os.getenv``;
  * per-process identity: builtin ``id()`` and ``hash()`` (PYTHONHASHSEED
    makes ``hash`` differ across processes — canonical JSON + sha256
    is the sanctioned fingerprint, obs/ledger.digest);
  * order-sensitive iteration over unsorted ``dict``/``set`` views
    (``for k in d.items()``, ``"".join(s)``, ``list(d.keys())`` ...)
    — rebuild comprehensions (``{k: v for ...}``) are exempt, they
    are order-free under the canonical ``sort_keys`` dump.

Calls that leave the scanned set (json, hashlib, the model registry)
are trusted leaves: models/ and core/ are already covered by the
``determinism`` rule.

Suppressions: "relpath::qualname::pattern" (pattern is the banned
dotted name, or "unsorted-iteration").
"""

from __future__ import annotations

import ast
import re

from .framework import Finding, Rule, register_rule, parse_allow
from .host_common import Aliases, iter_source_files, self_attr

SCAN_DIRS = ("wittgenstein_tpu/serve", "wittgenstein_tpu/matrix",
             "wittgenstein_tpu/memo", "wittgenstein_tpu/obs",
             "wittgenstein_tpu/utils")

#: entry points: name pattern + explicit extras
ENTRY_NAME = re.compile(r"digest|compile_key")
EXTRA_ENTRIES = (("wittgenstein_tpu/memo/table.py", "MemoTable.key"),
                 ("wittgenstein_tpu/matrix/search.py",
                  "SearchSpec.digest"))

#: method names followed through ``obj.m()`` calls on unresolvable
#: receivers — the serializer/canonicalizer vocabulary of this tree
CURATED_METHODS = frozenset(
    {"to_json", "canonical_json", "digest", "compile_key", "validate",
     "key"})

BANNED_PREFIXES = {
    "time": "wall-clock read inside a digest path",
    "datetime": "wall-clock read inside a digest path",
    "random": "stateful PRNG inside a digest path",
    "numpy.random": "stateful PRNG inside a digest path",
    "uuid": "per-process uniqueness inside a digest path",
    "secrets": "entropy source inside a digest path",
    "os.urandom": "entropy source inside a digest path",
    "os.getenv": "ambient environment read inside a digest path",
    "os.environ": "ambient environment read inside a digest path",
    "id": "per-process object identity inside a digest path",
    "hash": "PYTHONHASHSEED-dependent hash() inside a digest path",
}


def _banned(canon: str):
    for prefix, reason in BANNED_PREFIXES.items():
        if canon == prefix or canon.startswith(prefix + "."):
            return prefix, reason
    return None


class _Module:
    def __init__(self, relpath, tree):
        self.relpath = relpath
        self.tree = tree
        self.aliases = Aliases(tree)
        self.funcs: dict[str, ast.AST] = {}      # qual -> def node
        self.cls_of: dict[str, str] = {}         # qual -> class name
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for m in node.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        q = f"{node.name}.{m.name}"
                        self.funcs[q] = m
                        self.cls_of[q] = node.name


def _load_modules(root=None):
    mods = {}
    for relpath, text in iter_source_files(SCAN_DIRS, root=root):
        mods[relpath] = _Module(relpath, ast.parse(text, filename=relpath))
    return mods


def _edges(mod: _Module, qual: str, mods: dict, stem_index: dict,
           method_index: dict):
    """Call edges out of one function: ``(relpath, qual)`` targets
    within the scanned set (everything else is a trusted leaf)."""
    fn = mod.funcs[qual]
    cls = mod.cls_of.get(qual)
    out = set()
    for call in ast.walk(fn):
        if not isinstance(call, ast.Call):
            continue
        f = call.func
        attr = self_attr(f)
        if attr is not None and cls is not None:
            q = f"{cls}.{attr}"
            if q in mod.funcs:
                out.add((mod.relpath, q))
            continue
        if isinstance(f, ast.Name):
            if f.id in mod.funcs:
                out.add((mod.relpath, f.id))
                continue
        canon = mod.aliases.canonical(f)
        if canon and "." in canon:
            head, leaf = canon.rsplit(".", 1)
            stem = head.rsplit(".", 1)[-1]
            for rel in stem_index.get(stem, ()):
                if leaf in mods[rel].funcs:
                    out.add((rel, leaf))
        if isinstance(f, ast.Attribute) and f.attr in CURATED_METHODS:
            out.update(method_index.get(f.attr, ()))
    return out


def _iter_violations(fn, aliases: Aliases):
    """Banned constructs inside one reachable function body:
    ``(line, pattern, reason)``."""
    hits = []

    def check_call(node):
        if isinstance(node, ast.Call):
            b = _banned(aliases.canonical(node.func))
            if b:
                hits.append((node.lineno,) + b)

    def unsorted_src(expr):
        if isinstance(expr, ast.Call) \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in ("keys", "values", "items"):
            return f"dict.{expr.func.attr}()"
        if isinstance(expr, ast.Set):
            return "set literal"
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
                and expr.func.id in ("set", "frozenset"):
            return f"{expr.func.id}()"
        return None

    for node in ast.walk(fn):
        check_call(node)
        if isinstance(node, ast.Subscript):
            b = _banned(aliases.canonical(node.value))
            if b:
                hits.append((node.lineno,) + b)
        iters = []
        if isinstance(node, ast.For):
            iters = [node.iter]
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            iters = [g.iter for g in node.generators]
        elif isinstance(node, ast.Call):
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else node.func.id if isinstance(node.func, ast.Name) else ""
            if name in ("join", "list", "tuple", "enumerate") and node.args:
                iters = [node.args[0]]
        for it in iters:
            src = unsorted_src(it)
            if src:
                hits.append((node.lineno, "unsorted-iteration",
                             f"order-sensitive iteration over unsorted "
                             f"{src} feeding a digest (wrap in sorted())"))
    return hits


def scan_tree(root=None, allow=()):
    """All digest-purity violations: ``(relpath, qual, line, pattern,
    reason)``, plus (n_entries, n_reachable, n_files)."""
    mods = _load_modules(root=root)
    stem_index: dict = {}
    method_index: dict = {}
    for rel, mod in mods.items():
        stem_index.setdefault(
            rel.rsplit("/", 1)[-1].removesuffix(".py"), []).append(rel)
        for q in mod.funcs:
            name = q.rsplit(".", 1)[-1]
            if "." in q and name in CURATED_METHODS:
                method_index.setdefault(name, set()).add((rel, q))

    entries = set()
    for rel, mod in mods.items():
        for q in mod.funcs:
            if ENTRY_NAME.search(q.rsplit(".", 1)[-1]):
                entries.add((rel, q))
    entries.update(e for e in EXTRA_ENTRIES if
                   e[0] in mods and e[1] in mods[e[0]].funcs)

    reachable, frontier = set(entries), list(entries)
    while frontier:
        rel, q = frontier.pop()
        for edge in _edges(mods[rel], q, mods, stem_index, method_index):
            if edge not in reachable:
                reachable.add(edge)
                frontier.append(edge)

    violations = []
    for rel, q in sorted(reachable):
        mod = mods[rel]
        for line, pattern, reason in _iter_violations(mod.funcs[q],
                                                      mod.aliases):
            if f"{rel}::{q}::{pattern}" in allow:
                continue
            violations.append((rel, q, line, pattern, reason))
    return violations, (len(entries), len(reachable), len(mods))


@register_rule
class HostDigestRule(Rule):
    name = "host_digest"
    scope = "global"
    budgeted_metrics = ("violations",)

    def run(self, target, budget):
        allow = parse_allow(budget)
        violations, (n_entry, n_reach, n_files) = scan_tree(allow=allow)
        findings = [
            Finding(rule=self.name, target=f"{rel}:{line}",
                    severity="error", path=rel, line=line,
                    message=f"{qual}: {reason} (allowlist key: "
                            f'"{rel}::{qual}::{pattern}")')
            for rel, qual, line, pattern, reason in violations]
        findings.append(Finding(
            rule=self.name, target="global", severity="info",
            metric="violations", value=len(violations),
            message=f"{n_entry} digest entry points, {n_reach} reachable "
                    f"functions over {n_files} host files: "
                    f"{len(violations)} purity violations"))
        return findings

    def describe(self):
        _, (n_entry, n_reach, n_files) = scan_tree()
        return f"source: {n_files} host files, {n_entry} digest " \
               f"entry points ({n_reach} reachable functions)"
