"""Network latency models — the simulation "physics", fully vectorized.

Each model is a callable ``extended(nodes, src, dst, delta) -> int32 ms`` over
arrays of source/destination node indices and per-(message, dest) uniform
draws ``delta in [0, 99]`` — the same contract as the reference's
``NetworkLatency.getExtendedLatency`` (core/NetworkLatency.java:12-34).
`full_latency` applies the shared wrapper semantics: same node -> 1 ms,
otherwise ``max(1, extraLatency[src] + extraLatency[dst] + extended)``
(NetworkLatency.java:27-34).

Models are plain Python objects holding jnp constants; they hash by identity
and are closed over statically by the jitted step, so switching models means
one recompile — never dynamic dispatch inside the kernel.

The latency-floor contract (`latency_floor_ms`): each model may expose a
``latency_floor_ms() -> int`` returning a CONSERVATIVE, provable lower
bound F >= 1 on ``full_latency(model, nodes, src, dst, delta)`` over all
DISTINCT node pairs (src != dst), all positions/cities the builders can
produce, all deltas, and any ``extra_latency >= 0``.  Same-node sends are
excluded — `full_latency` short-circuits them to 1 ms regardless of the
model, which is why the engine's superstep gate additionally requires a
protocol that never unicasts to itself before trusting a floor > 1
(core/network.check_chunk_config).  The contract is one-sided: returning
too LOW only costs superstep-K opportunity; returning higher than an
achievable latency would let `step_kms` fuse a window a message arrives
inside, silently corrupting results — when in doubt return 1.  Soundness
is property-tested against sampled latencies in
tests/test_latency.py::test_latency_floor_is_sound.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .state import MAX_DIST, MAX_X, MAX_Y


def torus_dist(nodes, src, dst):
    """Distance on the round 2000x1112 map (core/Node.java:278-282)."""
    dx = jnp.abs(nodes.x[src] - nodes.x[dst])
    dy = jnp.abs(nodes.y[src] - nodes.y[dst])
    dx = jnp.minimum(dx, MAX_X - dx)
    dy = jnp.minimum(dy, MAX_Y - dy)
    return jnp.sqrt((dx * dx + dy * dy).astype(jnp.float32)).astype(jnp.int32)


def gpd_inverse(y, shape=1.4, location=-0.3, scale=0.35):
    """Generalized Pareto inverse CDF (core/utils/GeneralizedParetoDistribution
    .java:26-46): closed form, so the jitter draw is one fused expression."""
    y = jnp.clip(y, 0.0, 0.999999)
    main = location + scale / shape * (jnp.power(1.0 - y, -shape) - 1.0)
    return jnp.where(y < 1e-6, jnp.float32(location), main)


class NetworkNoLatency:
    """Always 1 ms (NetworkLatency.java:271-275)."""
    positional = False

    name = "NetworkNoLatency"

    def extended(self, nodes, src, dst, delta):
        return jnp.ones_like(delta)

    def latency_floor_ms(self):
        return 1

    def __repr__(self):
        return self.name


class NetworkFixedLatency:
    """Constant latency (NetworkLatency.java:235-249)."""
    positional = False

    def __init__(self, fixed: int):
        self.fixed = max(1, int(fixed))
        self.name = f"NetworkFixedLatency({self.fixed})"

    def extended(self, nodes, src, dst, delta):
        return jnp.full_like(delta, self.fixed)

    def latency_floor_ms(self):
        # extended == fixed everywhere; extra_latency >= 0 only adds.
        return self.fixed

    def __repr__(self):
        return self.name


class NetworkUniformLatency:
    """Uniform in [0, max]: ``(delta / 99) * max`` (NetworkLatency.java:255-269)."""
    positional = False

    def __init__(self, max_latency: int):
        self.max_latency = max(1, int(max_latency))
        self.name = f"NetworkUniformLatency({self.max_latency})"

    def extended(self, nodes, src, dst, delta):
        return ((delta.astype(jnp.float32) / 99.0) *
                self.max_latency).astype(jnp.int32)

    def latency_floor_ms(self):
        return 1                        # delta == 0 -> extended == 0

    def __repr__(self):
        return self.name


class NetworkLatencyByDistanceWJitter:
    """One-way latency = (0.022 * miles + 4.862 + ParetoJitter) / 2
    (NetworkLatency.java:49-73): linear fit of RTT vs distance plus a
    generalized-Pareto jitter term, halved because both are round-trip fits."""

    name = "NetworkLatencyByDistanceWJitter"
    EARTH_PERIMETER_MILES = 24_860.0

    def extended(self, nodes, src, dst, delta):
        dist = torus_dist(nodes, src, dst).astype(jnp.float32)
        miles = dist * ((self.EARTH_PERIMETER_MILES / 2.0) / MAX_DIST)
        fixed = miles * 0.022 + 4.862
        jitter = gpd_inverse(delta.astype(jnp.float32) / 100.0)
        return ((fixed + jitter) * 0.5).astype(jnp.int32)

    def latency_floor_ms(self):
        # dist >= 0 and the Pareto jitter's infimum is its location
        # (gpd_inverse(0) == -0.3): extended >= int((4.862 - 0.3)/2) == 2
        # even for co-located nodes.
        return max(1, int((4.862 + float(gpd_inverse(jnp.float32(0.0))))
                          * 0.5))

    def __repr__(self):
        return self.name


# AWS inter-region ping matrix, ms RTT, measured Jan 2019 (NetworkLatency
# .java:86-152).  Region order (alphabetical city list order is NOT the matrix
# order — the matrix order is the regionPerCity insertion ids 0..10):
AWS_REGIONS = ["Oregon", "Virginia", "Mumbai", "Seoul", "Singapore", "Sydney",
               "Tokyo", "Canada central", "Frankfurt", "Ireland", "London"]
_AWS_UPPER = np.array([
    [0, 81, 216, 126, 165, 138, 97, 64, 164, 131, 141],
    [0, 0, 182, 181, 232, 195, 167, 13, 88, 80, 75],
    [0, 0, 0, 152, 62, 223, 123, 194, 111, 122, 113],
    [0, 0, 0, 0, 97, 133, 35, 184, 259, 254, 264],
    [0, 0, 0, 0, 0, 169, 69, 218, 162, 174, 171],
    [0, 0, 0, 0, 0, 0, 105, 210, 282, 269, 271],
    [0, 0, 0, 0, 0, 0, 0, 156, 235, 222, 234],
    [0, 0, 0, 0, 0, 0, 0, 0, 101, 78, 87],
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 24, 13],
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 12],
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]], dtype=np.int32)
AWS_RTT = _AWS_UPPER + _AWS_UPPER.T


class AwsRegionNetworkLatency:
    """11-region measured ping matrix, halved, plus Pareto jitter; same-region
    is 1 ms (NetworkLatency.java:86-152).  Node ``city`` indexes AWS_REGIONS."""

    name = "AwsRegionNetworkLatency"

    def __init__(self):
        self.rtt = jnp.asarray(AWS_RTT)

    def validate(self, nodes):
        # The reference throws for nodes outside its region map
        # (NetworkLatency.java:144-151); with city == -1 the r1 == r2 branch
        # would silently make the whole network 1 ms, so fail loudly instead.
        import numpy as np
        if np.any(np.asarray(nodes.city) < 0):
            raise ValueError(
                "AwsRegionNetworkLatency needs city-positioned nodes "
                "(NodeBuilder(location='aws')); got city == -1 nodes")

    def extended(self, nodes, src, dst, delta):
        r1 = nodes.city[src]
        r2 = nodes.city[dst]
        jitter = gpd_inverse(delta.astype(jnp.float32) / 100.0).astype(jnp.int32)
        lat = jnp.maximum(1, self.rtt[r1, r2] // 2 + jitter)
        return jnp.where(r1 == r2, 1, lat)

    def latency_floor_ms(self):
        return 1                        # same-region distinct pairs: 1 ms

    def __repr__(self):
        return self.name


def build_distribution(proportions, values):
    """Expand a (proportions %, values ms) histogram spec into the 100-bucket
    table the reference interpolates (MeasuredNetworkLatency.setLatency,
    NetworkLatency.java:286-305): within each band, values ramp linearly in
    integer steps from the previous band's value."""
    table = np.zeros(100, np.int32)
    li, cur, total = 0, 0, 0
    for prop, val in zip(proportions, values):
        if prop == 0:
            cur = val
            continue
        total += prop
        step = (val - cur) // prop
        for _ in range(prop):
            cur += step
            table[li] = cur
            li += 1
    if total != 100 or li != 100:
        raise ValueError(f"proportions must sum to 100 (got {total}, {li})")
    return table


class MeasuredNetworkLatency:
    """Arbitrary 100-bucket latency distribution (NetworkLatency.java:277-359)."""

    positional = False

    def __init__(self, proportions, values, name="MeasuredNetworkLatency"):
        self.table = jnp.asarray(build_distribution(proportions, values))
        self.name = name

    def extended(self, nodes, src, dst, delta):
        return self.table[delta]

    def latency_floor_ms(self):
        # Exhaustive min over the finite delta space (the table).
        return max(1, int(np.asarray(self.table).min()))

    def __repr__(self):
        return self.name


# ethstats.net block-propagation distribution (NetworkLatency.java:366-383).
ETHSCAN_PROP = [16, 18, 17, 12, 8, 5, 4, 3, 3, 1, 1, 2, 1, 1, 8]
ETHSCAN_VAL = [250, 500, 1000, 1250, 1500, 1750, 2000, 2250, 2500, 2750,
               4500, 6000, 8500, 9750, 10000]


class EthScanNetworkLatency(MeasuredNetworkLatency):
    def __init__(self):
        super().__init__(ETHSCAN_PROP, ETHSCAN_VAL, name="EthScanNetworkLatency")


class NetworkLatencyByCity:
    """WonderNetwork measured city-to-city RTT halved; same node 1 ms
    (NetworkLatency.java:159-194).  Node ``city`` indexes the vendored city
    database (core/geo.py) — the pruned CSVLatencyReader matrix."""

    name = "NetworkLatencyByCity"

    def __init__(self):
        from . import geo
        self.rtt = jnp.asarray(geo.load().rtt)

    def validate(self, nodes):
        import numpy as np
        if np.any(np.asarray(nodes.city) < 0):
            raise ValueError(
                "NetworkLatencyByCity needs city-positioned nodes "
                "(NodeBuilder(location='cities')); the reference throws "
                "IllegalStateException for DEFAULT_CITY nodes "
                "(NetworkLatency.java:175-178)")

    def extended(self, nodes, src, dst, delta):
        half = 0.5 * self.rtt[nodes.city[src], nodes.city[dst]]
        return jnp.maximum(1, jnp.round(half)).astype(jnp.int32)

    def latency_floor_ms(self):
        # Exhaustive min over the finite (c1, c2) pair space, through the
        # same rounding expression (monotone, so min commutes).  Distinct
        # nodes in one city hit the matrix DIAGONAL, so it is included.
        return max(1, int(np.maximum(
            1, np.round(0.5 * np.asarray(self.rtt))).min()))

    def __repr__(self):
        return self.name


class NetworkLatencyByCityWJitter(NetworkLatencyByCity):
    """City matrix + generalized-Pareto jitter; 10 ms intra-city RTT
    (NetworkLatency.java:200-233)."""

    name = "NetworkLatencyByCityWJitter"

    def extended(self, nodes, src, dst, delta):
        c1, c2 = nodes.city[src], nodes.city[dst]
        raw = gpd_inverse(delta.astype(jnp.float32) / 100.0)
        raw = raw + jnp.where(c1 == c2, 10.0, self.rtt[c1, c2])
        return jnp.maximum(1, jnp.round(0.5 * raw)).astype(jnp.int32)

    def latency_floor_ms(self):
        # Same-city pairs use the 10 ms constant; cross-city pairs the
        # OFF-diagonal matrix entries.  Jitter infimum = location (-0.3).
        m = np.asarray(self.rtt).astype(np.float64)
        off = m + np.eye(m.shape[0]) * np.float64(1 << 30)
        rtt_min = min(10.0, float(off.min()))
        jit0 = float(gpd_inverse(jnp.float32(0.0)))
        return max(1, int(np.round(0.5 * (rtt_min + jit0))))

    def __repr__(self):
        return self.name


class IC3NetworkLatency:
    """IC3 paper percentile table keyed by covered-area ratio
    (NetworkLatency.java:399-417)."""

    name = "IC3NetworkLatency"

    def extended(self, nodes, src, dst, delta):
        dist = torus_dist(nodes, src, dst).astype(jnp.float32)
        surface = dist * dist * np.float32(np.pi)
        position = (surface * 100.0 / (MAX_X * MAX_Y)).astype(jnp.int32)
        bounds = jnp.asarray([10, 33, 50, 67, 90, 1 << 30], jnp.int32)
        halves = jnp.asarray([92 // 2, 125 // 2, 152 // 2, 200 // 2, 276 // 2,
                              350 // 2], jnp.int32)
        idx = jnp.searchsorted(bounds, position)
        return halves[jnp.minimum(idx, 5)]

    def latency_floor_ms(self):
        return 92 // 2                  # min of the halved percentile table

    def __repr__(self):
        return self.name


class NetworkHeterogeneousLatency:
    """Per-link heterogeneous, ASYMMETRIC geography: every unordered
    node pair gets a stable base draw in ``[base, base + spread]`` and
    every ORDERED pair a direction skew in ``[0, skew]``, so
    ``A -> B != B -> A`` in general — the missing realistic-geography
    axis (ROADMAP item 2): chaos delay-inflation windows then compose
    with links that were never uniform to begin with.

    Draws are counter-based (ops/prng) and keyed on the model's own
    ``seed`` parameter, NOT the run seed: the link map is fixed
    "geography" shared by every run of the model, reproducible from the
    registry name alone (``NetworkHeterogeneousLatency(base,spread,
    skew[,seed])``), and a different seed is a different (but equally
    stable) topology.  `delta` is unused — per-link latency is
    deterministic, like the fixed model; jitter belongs to the models
    that fit one (ByDistanceWJitter) or to a chaos delay window."""

    positional = False

    #: domain tag for the link draws ("HETL") — never shares a stream
    #: with the engine's TAG_LATENCY per-message deltas
    _TAG = 0x4845544C

    def __init__(self, base: int, spread: int = 0, skew: int = 0,
                 seed: int = 0):
        base, spread, skew, seed = (int(base), int(spread), int(skew),
                                    int(seed))
        if base < 1 or spread < 0 or skew < 0 or seed < 0:
            # spec-validated: a bad parameterisation must surface as the
            # request plane's 400 with remedy, not compile a floor-0
            # model that silently breaks the superstep contract
            raise ValueError(
                f"NetworkHeterogeneousLatency wants base >= 1, "
                f"spread >= 0, skew >= 0, seed >= 0; got ({base}, "
                f"{spread}, {skew}, {seed})")
        self.base, self.spread, self.skew, self.seed = (base, spread,
                                                        skew, seed)
        self.name = (f"NetworkHeterogeneousLatency({base},{spread},"
                     f"{skew},{seed})")

    def extended(self, nodes, src, dst, delta):
        from ..ops import prng
        key = prng.hash2(jnp.int32(self.seed), jnp.int32(self._TAG))
        lo = jnp.minimum(src, dst)
        hi = jnp.maximum(src, dst)
        pair = prng.uniform_int(prng.hash2(key, 1), prng.hash2(lo, hi),
                                self.spread + 1)
        skew = prng.uniform_int(prng.hash2(key, 2), prng.hash2(src, dst),
                                self.skew + 1)
        return (self.base + pair + skew).astype(jnp.int32)

    def latency_floor_ms(self):
        # pair/skew draws are >= 0 and extra_latency only adds: the
        # base IS the provable floor (tight — a zero draw achieves it).
        return self.base

    def __repr__(self):
        return self.name


class NetworkCSVLatency(NetworkLatencyByCity):
    """Measured per-city-pair latency loaded from a CSV file — the
    reference's `CSVLatencyReader` beyond the vendored `citydata.npz`
    (ROADMAP item 2): bring your own ping matrix.

    CSV shape: a header row naming the cities (an optional leading
    label cell is ignored), then one row per source city — its name
    followed by the measured RTT in ms to each destination city, in
    header order.  The matrix may be ASYMMETRIC (A->B != B->A is real
    geography) and the diagonal is the intra-city RTT.  Only the
    MATRIX differs from the vendored model: `extended` (halved RTT,
    floored at 1 ms) and the exhaustive `latency_floor_ms` are
    inherited from `NetworkLatencyByCity`, so swapping the vendored
    matrix for a measured file changes DATA, not semantics.  Node
    ``city`` indexes the header order.

    A missing or malformed file refuses at CONSTRUCTION with remedy
    text: `ScenarioSpec.validate` routes latency names through
    `get_by_name`, so a bad path surfaces as the request plane's 400,
    never as a mid-campaign crash."""

    def __init__(self, path: str):
        import csv
        import os

        self.path = str(path)
        self.name = f"NetworkCSVLatency({self.path})"
        if not os.path.isfile(self.path):
            raise ValueError(
                f"NetworkCSVLatency: no CSV at {self.path!r}. Fix: "
                "point the name at a readable file of the form "
                "'city,CityA,CityB,...' header + one 'CityA,rtt,...' "
                "row per city (RTT in ms)")
        with open(self.path, newline="") as f:
            rows = [r for r in csv.reader(f)
                    if r and any(c.strip() for c in r)]
        if len(rows) < 2:
            raise ValueError(
                f"NetworkCSVLatency: {self.path!r} holds no matrix "
                "(need a header row + at least one city row)")
        header = [c.strip() for c in rows[0]]
        # an optional leading label cell ("city", "", ...) is ignored
        # when the data rows carry one leading name cell
        cities = header[1:] if len(header) == len(rows[1]) else header
        n = len(cities)
        if n < 1 or len(set(cities)) != n:
            raise ValueError(
                f"NetworkCSVLatency: {self.path!r} header names "
                f"{cities!r} are empty or duplicated — one distinct "
                "city per column")
        mat = np.zeros((n, n), np.int32)
        names = []
        for i, row in enumerate(rows[1:]):
            cells = [c.strip() for c in row]
            if len(cells) != n + 1:
                raise ValueError(
                    f"NetworkCSVLatency: {self.path!r} row {i + 1} has "
                    f"{len(cells)} cell(s); expected a city name + "
                    f"{n} RTT values (header order: {cities})")
            names.append(cells[0])
            for j, cell in enumerate(cells[1:]):
                try:
                    val = float(cell)
                except ValueError:
                    raise ValueError(
                        f"NetworkCSVLatency: {self.path!r} row "
                        f"{i + 1} column {cities[j]!r}: {cell!r} is "
                        "not a number (RTT in ms)") from None
                if val < 0:
                    raise ValueError(
                        f"NetworkCSVLatency: {self.path!r} row "
                        f"{i + 1} column {cities[j]!r}: RTT {val} "
                        "must be >= 0 ms")
                mat[i, j] = np.int32(round(val))
        if len(names) != n or [x.lower() for x in names] != \
                [x.lower() for x in cities]:
            raise ValueError(
                f"NetworkCSVLatency: {self.path!r} row names {names} "
                f"do not match the header {cities} in order — the "
                "matrix must be square over one city list")
        self.cities = tuple(cities)
        self.rtt = jnp.asarray(mat)     # (deliberately NOT the parent
        # __init__: the matrix comes from the file, not core/geo)

    def validate(self, nodes):
        city = np.asarray(nodes.city)
        if np.any(city < 0):
            raise ValueError(
                f"{self.name} needs city-positioned nodes "
                "(NodeBuilder(location='cities')); got city == -1 "
                "nodes")
        if np.any(city >= len(self.cities)):
            raise ValueError(
                f"{self.name} covers {len(self.cities)} cities but "
                f"nodes reference city id {int(city.max())} — the CSV "
                "must name every city the node builder assigns")


def latency_name(kind: str, fixed: int) -> str:
    """Reference-compatible registry names (RegistryNetworkLatencies.name,
    RegistryNetworkLatencies.java:17-26): 'NetworkFixedLatency(100)' etc."""
    cls = {"FIXED": "NetworkFixedLatency",
           "UNIFORM": "NetworkUniformLatency"}[kind.upper()]
    return f"{cls}({int(fixed)})"


#: parametrised registry constructors — name(int[,int...]) forms
_PARAM_MODELS = {
    "NetworkFixedLatency": NetworkFixedLatency,
    "NetworkUniformLatency": NetworkUniformLatency,
    "NetworkHeterogeneousLatency": NetworkHeterogeneousLatency,
}

#: parametrised constructors taking one RAW STRING argument (a path)
_PATH_MODELS = {
    "NetworkCSVLatency": NetworkCSVLatency,
}


def get_by_name(name: str | None):
    """String-keyed latency lookup (RegistryNetworkLatencies.getByName,
    :34-59): parametrised ``Class(int[,int...])`` names (plus the
    string-argument ``NetworkCSVLatency(path.csv)``), then a
    by-class-simple-name fallback; None falls back to
    NetworkLatencyByDistanceWJitter.  A malformed parameter list — or
    a missing/malformed CSV — is a ValueError with the expected form:
    the request plane's 400."""
    if not name:
        return NetworkLatencyByDistanceWJitter()
    if "(" in name and name.endswith(")"):
        cls, arg = name[:-1].split("(", 1)
        if cls in _PATH_MODELS:
            return _PATH_MODELS[cls](arg.strip())
        ctor = _PARAM_MODELS.get(cls)
        if ctor is None:
            raise KeyError(f"unknown parametrised latency {name!r}; "
                           f"known: {sorted(_PARAM_MODELS) + sorted(_PATH_MODELS)}")
        try:
            args = [int(x) for x in arg.split(",")] if arg.strip() else []
            return ctor(*args)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"bad parameters in latency name {name!r}: {e} "
                f"(expected comma-separated ints, e.g. "
                f"'NetworkFixedLatency(100)' or "
                f"'NetworkHeterogeneousLatency(20,10,6)')") from None
    model = globals().get(name)
    if model is None or not hasattr(model, "extended"):
        raise KeyError(f"unknown latency model {name!r}")
    return model() if isinstance(model, type) else model


def full_latency(model, nodes, src, dst, delta):
    """The shared `getLatency` wrapper (NetworkLatency.java:27-34)."""
    base = nodes.extra_latency[src] + nodes.extra_latency[dst]
    lat = jnp.maximum(1, base + model.extended(nodes, src, dst, delta))
    return jnp.where(src == dst, jnp.ones_like(lat), lat)


def latency_floor_ms(model) -> int:
    """The model's provable distinct-pair latency floor (see the module
    docstring contract), or the universal floor of 1 when the model does
    not implement the method — unknown/custom models never license a
    superstep window they cannot prove."""
    fn = getattr(model, "latency_floor_ms", None)
    if fn is None:
        return 1
    return max(1, int(fn()))


class MathisNetworkThroughput:
    """Size-dependent delay from the TCP Mathis equation
    (core/NetworkThroughput.java:14-57): one-way latency from the wrapped
    model, plus transfer time at min(MSS*8/(RTT*sqrt(loss)), window/RTT)
    for messages larger than one segment."""

    MSS = 1460
    LOSS = 0.004

    def __init__(self, latency_model, window_bytes=87380 * 1024):
        self.latency_model = latency_model
        self.window_bits = 8 * window_bytes
        self.name = f"MathisNetworkThroughput({latency_model!r})"

    def delay(self, nodes, src, dst, delta, msg_size):
        st = full_latency(self.latency_model, nodes, src, dst,
                          delta).astype(jnp.float32)
        rtt = st * 2.0
        bandwidth = (self.MSS * 8) / (rtt * np.sqrt(self.LOSS))
        w_max = self.window_bits / rtt
        av = jnp.minimum(bandwidth, w_max)
        slow = (8.0 * msg_size) / av + st
        return jnp.where(msg_size < self.MSS, st,
                         slow.astype(jnp.int32).astype(jnp.float32)
                         ).astype(jnp.int32)

    def latency_floor_ms(self):
        # delay >= st == the wrapped model's full latency (transfer time
        # only adds), so the wrapped floor carries over.
        return latency_floor_ms(self.latency_model)

    def __repr__(self):
        return self.name


def _quantile_table(lat, name):
    """Bucket observed latencies into the 100-quantile
    MeasuredNetworkLatency form shared by both estimators (the reference's
    histogram build, NetworkLatency.java:468-508)."""
    import numpy as np_
    lat = np_.sort(lat)
    qs = np_.quantile(lat, (np_.arange(100) + 1) / 100.0,
                      method="lower").astype(np_.int32)
    qs = np_.maximum.accumulate(np_.maximum(qs, 1))
    table = MeasuredNetworkLatency.__new__(MeasuredNetworkLatency)
    table.table = jnp.asarray(qs)
    table.name = name
    return table


def estimate_latency(model, nodes, rounds=100, seed=0):
    """Monte-Carlo sample a latency model into a MeasuredNetworkLatency
    (NetworkLatency.estimateLatency, NetworkLatency.java:432-474): draw
    src/dst pairs across the node set, bucket the observed latencies into a
    100-quantile table."""
    import numpy as np_
    from ..ops import prng
    n = int(nodes.x.shape[0])
    ids = jnp.arange(rounds * n, dtype=jnp.int32)
    s = prng.hash2(jnp.asarray(seed, jnp.int32), jnp.int32(0x4C455354))
    src = prng.uniform_int(prng.hash2(s, 1), ids, n)
    dst = prng.uniform_int(prng.hash2(s, 2), ids, n)
    delta = prng.uniform_delta(prng.hash2(s, 3), ids)
    keep = src != dst
    lat = np_.asarray(full_latency(model, nodes, src, dst, delta))[
        np_.asarray(keep)]
    return _quantile_table(
        lat, f"MeasuredNetworkLatency(estimate of {model!r})")


def estimate_p2p_latency(model, nodes, peers, degree, rounds=100, seed=0):
    """estimate_latency restricted to DIRECT peers of each sampled source
    (NetworkLatency.estimateP2PLatency, NetworkLatency.java:446-460):
    `peers` is the [N, D] peer-id matrix and `degree` the per-node valid
    peer count from core/p2p.build_peer_graph."""
    import numpy as np_
    from ..ops import prng
    n = int(nodes.x.shape[0])
    ids = jnp.arange(rounds * n, dtype=jnp.int32)
    s = prng.hash2(jnp.asarray(seed, jnp.int32), jnp.int32(0x50325045))
    src = prng.uniform_int(prng.hash2(s, 1), ids, n)
    deg = jnp.maximum(degree[src], 1)
    col = prng.uniform_int(prng.hash2(s, 2), ids, deg)
    dst = peers[src, col]
    delta = prng.uniform_delta(prng.hash2(s, 3), ids)
    keep = (dst >= 0) & (dst != src)
    lat = np_.asarray(full_latency(model, nodes, src,
                                   jnp.maximum(dst, 0), delta))[
        np_.asarray(keep)]
    return _quantile_table(
        lat, f"MeasuredNetworkLatency(p2p estimate of {model!r})")
