"""`FaultSchedule` — adversity as data.

The reference simulator's core workload is adversity: nodes stop and
start mid-run (Node.java stop()/start()), partitions open and heal
(Network.java partition/endPartition :639-649), messages are lost and
delayed by a hostile network.  Our reproduction only expressed "nodes
down at entry" and the single-point `FaultInjector` probe; this module
makes the whole adversity axis DECLARATIVE: one frozen, hashable,
JSON-able schedule that compiles into every engine variant
(core/network.step_ms / step_kms, the batched twin, the fast-forward
while loop, the sharded runner) through `chaos.wrap.ChaosProtocol`.

Fault classes (all times are absolute simulated ms, all windows
half-open ``[start, end)``):

  churn       ``(node, down_ms, up_ms)`` — the node is down (cannot
              send, cannot receive) during the window and recovers at
              `up_ms`.  State loss is the engine's own delivery
              semantics: every unicast ARRIVING while the node is down
              is consumed undelivered (the ring row is cleared after
              its ms — the message is gone, not delayed), and
              broadcasts recomputed during the window skip it — the
              node's in-flight inbound state is lost.  Its protocol
              state is retained across the outage (the reference's
              stop()/start() contract: Node objects survive).
  partitions  ``(start_ms, end_ms, part_id, lo, hi)`` — nodes with id
              in ``[lo, hi)`` move to partition `part_id` (>= 1)
              during the window and HEAL back to the global partition
              0 at `end_ms` — the reference's mid-run
              partition/endPartition as data.  Windows that would
              assign one node two ids at once are refused.
  loss        ``(start_ms, end_ms, permille, src_lo, src_hi, dst_lo,
              dst_hi)`` — each unicast EMITTED during the window on a
              matching (src, dst) link is lost with probability
              permille/1000, decided by a counter-based draw keyed on
              (run seed, emit ms, stable message slot id) — the same
              keying discipline as the engine's latency draws, so the
              realization is bit-deterministic and engine-layout
              independent.  Overlapping windows compose:
              p = 1 - prod(1 - p_i).  Unicast only (a broadcast is one
              O(1) record; per-destination broadcast loss would need
              the delivery-recompute path and is out of scope).
  delay       ``(start_ms, end_ms, extra_ms, src_lo, src_hi, dst_lo,
              dst_hi)`` — unicasts emitted during the window on a
              matching link have `extra_ms` added to their
              sender-chosen delay (latency inflation; overlapping
              windows add).  Unicast only, like loss.

Determinism contract: the schedule is static data closed over by the
compiled program, loss draws are pure functions of (seed, t, slot id),
and churn/partition state is a STATELESS function of t evaluated at
every engine window entry — so the same (schedule, seed) yields
bit-identical trajectories across dense, superstep-K, batched,
fast-forward and sharded engines (tests/test_chaos.py).  The one
alignment obligation that buys this: churn/partition transition times
must be multiples of any superstep K the run uses (liveness is
evaluated at window entry; a mid-window transition would be visible to
the per-ms engine but not the fused window).  `superstep_aligned` is
the predicate; `core/network.check_chunk_config` raises the remedy and
`pick_superstep` demotes K automatically.
"""

from __future__ import annotations

import dataclasses
import math

#: schedule schema version (the ScenarioSpec `fault_schedule` field
#: carries this structure; readers key on the spec's own schema).
FIELDS = ("churn", "partitions", "loss", "delay")

_ARITY = {"churn": 3, "partitions": 5, "loss": 7, "delay": 7}
_SHAPE = {
    "churn": "(node, down_ms, up_ms)",
    "partitions": "(start_ms, end_ms, part_id, lo, hi)",
    "loss": "(start_ms, end_ms, permille, src_lo, src_hi, dst_lo, dst_hi)",
    "delay": "(start_ms, end_ms, extra_ms, src_lo, src_hi, dst_lo, "
             "dst_hi)",
}


def _err(msg: str) -> ValueError:
    return ValueError(f"FaultSchedule: {msg}")


def _norm(name: str, events) -> tuple:
    out = []
    try:
        events = tuple(events or ())
    except TypeError:
        raise _err(f"{name} must be a list of {_SHAPE[name]} rows, got "
                   f"{events!r}") from None
    for i, ev in enumerate(events):
        try:
            ev = tuple(ev)
        except TypeError:
            raise _err(f"{name}[{i}] must be a {_SHAPE[name]} row, got "
                       f"{ev!r}") from None
        if len(ev) != _ARITY[name]:
            raise _err(f"{name}[{i}] must be {_SHAPE[name]}, got "
                       f"{len(ev)} value(s) {ev!r}")
        try:
            out.append(tuple(int(x) for x in ev))
        except (TypeError, ValueError):
            raise _err(f"{name}[{i}] must be all ints, got {ev!r}") \
                from None
    return tuple(out)


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """One declarative adversity schedule (frozen, hashable — safe to
    close over in jit; see the module docstring for event semantics)."""

    churn: tuple = ()
    partitions: tuple = ()
    loss: tuple = ()
    delay: tuple = ()

    def __post_init__(self):
        for name in FIELDS:
            object.__setattr__(self, name, _norm(name, getattr(self,
                                                               name)))

    # ------------------------------------------------------------- shape

    @property
    def empty(self) -> bool:
        return not (self.churn or self.partitions or self.loss
                    or self.delay)

    @property
    def mutates_state(self) -> bool:
        """True when the schedule needs the engine's window-entry
        `apply_faults` hook (churn/partition state); loss/delay act on
        the outbox inside the per-ms protocol step and need no hook."""
        return bool(self.churn or self.partitions)

    def transition_times(self) -> tuple:
        """Every ms at which churn/partition state CHANGES, sorted —
        the times the fast-forward engine must never jump across
        (`ChaosProtocol.next_action_time` clamps to them) and the times
        the superstep alignment contract is about."""
        times = set()
        for node, dm, um in self.churn:
            times.update((dm, um))
        for s, e, pid, lo, hi in self.partitions:
            times.update((s, e))
        return tuple(sorted(times))

    def superstep_aligned(self, k: int) -> bool:
        """True iff every churn/partition transition lands on a K-ms
        window boundary — the condition under which the window-entry
        fault application is bit-identical to the per-ms one (module
        docstring).  Loss/delay windows are applied per-ms inside the
        step and never constrain K."""
        if k <= 1:
            return True
        return all(t % k == 0 for t in self.transition_times())

    def align_gcd(self) -> int:
        """gcd of all transition times (0 when there are none): every
        valid superstep K divides it."""
        g = 0
        for t in self.transition_times():
            g = math.gcd(g, t)
        return g

    def counts(self) -> dict:
        """Event counts per fault class (the bench `chaos` block /
        summary form)."""
        return {name: len(getattr(self, name)) for name in FIELDS}

    # -------------------------------------------------------- validation

    def validate(self, n: int | None = None,
                 sim_ms: int | None = None) -> "FaultSchedule":
        """Refuse a malformed schedule with remedy text (the serve
        plane's 400 path).  `n` (node count) and `sim_ms` bound ids and
        windows when known.  Returns self on success."""
        for i, (node, dm, um) in enumerate(self.churn):
            if node < 0 or (n is not None and node >= n):
                raise _err(f"churn[{i}] node {node} out of range for a "
                           f"{n}-node network")
            if not 0 <= dm < um:
                raise _err(
                    f"churn[{i}] window [{dm}, {um}) is malformed: needs "
                    "0 <= down_ms < up_ms (use up_ms past the simulated "
                    "span for a crash that never recovers)")
        by_node: dict = {}
        for i, (node, dm, um) in enumerate(self.churn):
            by_node.setdefault(node, []).append((dm, um, i))
        for node, wins in by_node.items():
            wins.sort()
            for (d0, u0, i0), (d1, u1, i1) in zip(wins, wins[1:]):
                if d1 < u0:
                    raise _err(
                        f"churn[{i0}] and churn[{i1}] overlap on node "
                        f"{node} ([{d0}, {u0}) vs [{d1}, {u1})): one "
                        "outage per node at a time. Fix: merge them "
                        "into one window")
        for i, (s, e, pid, lo, hi) in enumerate(self.partitions):
            if not 0 <= s < e:
                raise _err(f"partitions[{i}] window [{s}, {e}) is "
                           "malformed: needs 0 <= start_ms < end_ms")
            if pid < 1:
                raise _err(
                    f"partitions[{i}] part_id {pid} is reserved: 0 is "
                    "the global partition every healed node returns to "
                    "(the reference's endPartition). Fix: use "
                    "part_id >= 1")
            if not (0 <= lo < hi and (n is None or hi <= n)):
                raise _err(f"partitions[{i}] node range [{lo}, {hi}) is "
                           f"malformed for a {n}-node network: needs "
                           "0 <= lo < hi <= n")
        for i, a in enumerate(self.partitions):
            for j in range(i + 1, len(self.partitions)):
                b = self.partitions[j]
                t_overlap = a[0] < b[1] and b[0] < a[1]
                r_overlap = a[3] < b[4] and b[3] < a[4]
                if t_overlap and r_overlap:
                    raise _err(
                        f"partitions[{i}] and partitions[{j}] overlap "
                        f"(times [{a[0]}, {a[1]}) vs [{b[0]}, {b[1]}), "
                        f"nodes [{a[3]}, {a[4]}) vs [{b[3]}, {b[4]})): "
                        "a node can live in ONE partition at a time. "
                        "Fix: split the windows so no node is claimed "
                        "twice, or merge them into one window")
        for kind in ("loss", "delay"):
            label = "permille" if kind == "loss" else "extra_ms"
            for i, (s, e, val, slo, shi, dlo, dhi) in enumerate(
                    getattr(self, kind)):
                if not 0 <= s < e:
                    raise _err(f"{kind}[{i}] window [{s}, {e}) is "
                               "malformed: needs 0 <= start_ms < end_ms")
                if kind == "loss" and not 0 <= val <= 1000:
                    raise _err(f"loss[{i}] permille {val} out of range "
                               "[0, 1000] (1000 = every matching "
                               "unicast lost)")
                if kind == "delay" and val < 0:
                    raise _err(f"delay[{i}] extra_ms {val} must be >= 0")
                for which, (rlo, rhi) in (("src", (slo, shi)),
                                          ("dst", (dlo, dhi))):
                    if not (0 <= rlo < rhi and (n is None or rhi <= n)):
                        raise _err(
                            f"{kind}[{i}] {which} range [{rlo}, {rhi}) "
                            f"is malformed for a {n}-node network: "
                            "needs 0 <= lo < hi <= n")
        if sim_ms is not None:
            for name in FIELDS:
                for i, ev in enumerate(getattr(self, name)):
                    start = ev[1] if name == "churn" else ev[0]
                    if start >= sim_ms:
                        raise _err(
                            f"{name}[{i}] starts at ms {start}, outside "
                            f"the simulated span [0, {sim_ms}): the "
                            "fault would never fire. Fix: move it into "
                            "the span or extend sim_ms")
        return self

    # ----------------------------------------------------- serialization

    def to_json(self) -> dict:
        """JSON form (lists of lists) — the `ScenarioSpec.fault_schedule`
        field's wire shape; omits empty fault classes for a compact
        canonical form."""
        return {name: [list(ev) for ev in getattr(self, name)]
                for name in FIELDS if getattr(self, name)}

    @classmethod
    def from_json(cls, data) -> "FaultSchedule":
        """Inverse of `to_json` (dict or JSON string).  Unknown keys are
        refused with the known field list — a typo'd fault class
        silently dropped would run a different adversity than the
        requester meant."""
        import json as _json

        if isinstance(data, (str, bytes)):
            data = _json.loads(data)
        if not isinstance(data, dict):
            raise _err(f"expected a JSON object with keys from {FIELDS}, "
                       f"got {type(data).__name__}")
        unknown = set(data) - set(FIELDS)
        if unknown:
            raise _err(f"unknown fault class(es) {sorted(unknown)}; "
                       f"known: {FIELDS} — each maps to a list of "
                       f"{', '.join(_SHAPE[f] for f in FIELDS)} rows")
        # row normalization (incl. the non-iterable-row refusals) is
        # _norm's job in __post_init__ — pass values through verbatim
        return cls(**data)
