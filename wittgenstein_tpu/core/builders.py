"""Node builders: vectorized population of the NodeState struct-of-arrays.

Reference: core/NodeBuilder.java (random-position and city-weighted builders)
and the Node aspects (core/Node.java:145-244): speed-ratio models and the
Tor-like extra-latency aspect.  A builder here is a declarative spec; `build`
materialises all N nodes in one shot from counter-based draws, so node
construction is deterministic per seed and vmappable over seeds.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from ..ops import prng
from . import geo
from .latency import AWS_REGIONS
from .state import MAX_X, MAX_Y, NodeState, default_nodes

# GeoAWS city positions on the 2000x1112 map (geoinfo/GeoAWS.java:10-23),
# in AWS_REGIONS order.
AWS_CITY_X = np.array([271, 513, 1344, 1641, 1507, 1773, 1708, 422, 985, 891,
                       937], np.int32)
AWS_CITY_Y = np.array([261, 316, 426, 312, 532, 777, 316, 256, 226, 200, 205],
                      np.int32)


def load_city_db():
    """Returns (names, x, y, population) from the vendored city database
    (see core/geo.py; the reference reads cities.csv via
    geoinfo/GeoAllCities.java:16-75)."""
    db = geo.load()
    return (list(db.names), db.x.astype(np.int32), db.y.astype(np.int32),
            db.population.astype(np.float64))


@dataclasses.dataclass(frozen=True)
class NodeBuilder:
    """Declarative node spec.

    location: 'random' | 'aws' | 'cities'
    speed:    'constant' | 'uniform' | 'gaussian' | 'pareto'
    tor:      fraction of nodes given +500 ms extra latency
              (Node.ExtraLatencyAspect, Node.java:151-161)
    """

    location: str = "random"
    speed: str = "constant"
    tor: float = 0.0

    def build(self, seed, n: int) -> NodeState:
        nodes = default_nodes(n)
        # Domain-separated from the engine's latency/broadcast streams.
        seed = prng.hash2(jnp.asarray(seed, jnp.int32), prng.TAG_BUILDER)
        ids = jnp.arange(n, dtype=jnp.int32)

        if self.location == "random":
            # NodeBuilderWithRandomPosition (NodeBuilder.java:77-96):
            # independent uniform x in [1, MAX_X], y in [1, MAX_Y].
            x = 1 + prng.uniform_int(prng.hash2(seed, 1), ids, MAX_X)
            y = 1 + prng.uniform_int(prng.hash2(seed, 2), ids, MAX_Y)
            city = jnp.full((n,), -1, jnp.int32)
        else:
            if self.location == "aws":
                cx, cy = jnp.asarray(AWS_CITY_X), jnp.asarray(AWS_CITY_Y)
                ncity = len(AWS_REGIONS)
                # AWS cities are equal-weighted (GeoAWS population = 1 each).
                city = prng.uniform_int(prng.hash2(seed, 3), ids, ncity)
            else:
                _, xs, ys, pops = load_city_db()
                cx, cy = jnp.asarray(xs), jnp.asarray(ys)
                # Population-weighted selection (NodeBuilder.java:127-139,
                # geoinfo cumulativeProbability).
                cum = np.cumsum(pops / pops.sum())
                u = prng.uniform_float(prng.hash2(seed, 3), ids)
                city = jnp.searchsorted(jnp.asarray(cum, jnp.float32),
                                        u).astype(jnp.int32)
                city = jnp.minimum(city, len(xs) - 1)
            x, y = cx[city], cy[city]

        speed = self._speed_ratios(seed, ids)
        extra = jnp.where(
            prng.uniform_float(prng.hash2(seed, 5), ids) < self.tor, 500, 0
        ).astype(jnp.int32) if self.tor > 1e-3 else jnp.zeros((n,), jnp.int32)

        return nodes.replace(x=x.astype(jnp.int32), y=y.astype(jnp.int32),
                             city=city, speed_ratio=speed, extra_latency=extra)

    def _speed_ratios(self, seed, ids):
        u = prng.uniform_float(prng.hash2(seed, 4), ids)
        if self.speed == "constant":
            return jnp.ones_like(u)
        if self.speed == "uniform":
            # Half the nodes uniformly fast in [0.33, 1.0), half uniformly
            # slow in [1.0, 3.0) (Node.UniformSpeed, Node.java:233-244).
            u2 = prng.uniform_float(prng.hash2(seed, 6), ids)
            fast = 0.33 + u2 * 0.67
            slow = 1.0 + u2 * 2.0
            return jnp.where(u < 0.5, fast, slow)
        if self.speed == "gaussian":
            # max(0.33, N(0,1) + 1) (Node.GaussianSpeed, Node.java:206-217);
            # inverse-CDF via erfinv keeps the draw counter-based.
            from jax.scipy.special import erfinv
            z = jnp.sqrt(2.0) * erfinv(jnp.clip(2.0 * u - 1.0, -0.999999,
                                                0.999999))
            return jnp.maximum(0.33, z + 1.0)
        if self.speed == "pareto":
            # min(max, 1 + GPD(shape=1, loc=0, scale=1)) — ParetoSpeed with
            # typical parameters (Node.java:186-204).
            from .latency import gpd_inverse
            return jnp.minimum(3.0, 1.0 + gpd_inverse(u, 1.0, 0.0, 1.0))
        raise ValueError(f"unknown speed model {self.speed!r}")


def registry_name(location: str, speed_constant: bool, tor: float) -> str:
    """Reference-compatible builder name (RegistryNodeBuilders.name,
    RegistryNodeBuilders.java:22-26), e.g. 'RANDOM_SPEED=CONSTANT_TOR=0.33'."""
    site = {"aws": "AWS", "cities": "CITIES", "random": "RANDOM"}[location]
    speed = "CONSTANT" if speed_constant else "GAUSSIAN"
    tor_s = (repr(tor) + "000")[:4]
    return f"{site}_speed={speed}_tor={tor_s}".upper()


@lru_cache(maxsize=1)
def _registry():
    reg = {}
    for loc in ("aws", "cities", "random"):
        for const in (True, False):
            for tor in (0.0, 0.01, 0.10, 0.20, 0.33, 0.5, 0.6, 0.8, 1.0):
                # Note: the reference names the non-constant variant GAUSSIAN
                # but actually installs UniformSpeed
                # (RegistryNodeBuilders.java:60-62); we reproduce that quirk.
                nb = NodeBuilder(location=loc,
                                 speed="constant" if const else "uniform",
                                 tor=tor)
                reg[registry_name(loc, const, tor)] = nb
    return reg


def get_by_name(name: str | None) -> NodeBuilder:
    """String-keyed lookup (RegistryNodeBuilders.getByName, :72-82)."""
    if not name or not name.strip():
        name = registry_name("random", True, 0.0)
    reg = _registry()
    if name not in reg:
        raise KeyError(f"{name} not in the builder registry")
    return reg[name]
