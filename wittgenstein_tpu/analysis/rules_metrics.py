"""Rule ``metrics_zero_cost`` — the metrics plane may never silently
tax the hot path, and may never silently die.

The obs package's contract (wittgenstein_tpu/obs) is two-sided:

  * metrics-OFF builds carry ZERO instrumentation residue.  Enforced
    structurally: the chunk's outermost scan/while carry width must
    equal the state pytree's leaf count exactly (any extra carried
    array is residue — budget `carry_extra_leaves`, pinned at 0 for
    the dense targets and at the fast-forward engine's two skip
    counters for the `+ff` ones), and the total jaxpr equation count is
    ratcheted (`jaxpr_eqns`) so leftover dead reductions can't ride in
    unnoticed either;
  * metrics-ON builds actually instrument: an `+metrics`/`+ffmetrics`
    target whose loop carry does NOT widen by the `MetricsCarry` leaves
    has a silently-dead plane — an error, not a budget.

Both sides run over the same pinned compiles as every other rule, so
`python -m wittgenstein_tpu.analysis` proves the invariant per
protocol per engine variant.
"""

from __future__ import annotations

from .framework import Finding, Rule, register_rule

#: MetricsCarry contributes this many pytree leaves (t0 + series).
_METRICS_CARRY_LEAVES = 2

#: analysis target-name suffixes of the instrumented builds
INSTRUMENTED_SUFFIXES = ("+metrics", "+ffmetrics")


def _loop_carry_widths(jaxpr) -> list:
    """(primitive, carry_width) of every top-level scan/while eqn, in
    program order.  The chunk loop is top-level in every pinned target
    (vmap inlines batching before make_jaxpr returns)."""
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(("scan", eqn.params["num_carry"]))
        elif eqn.primitive.name == "while":
            carry = (len(eqn.invars) - eqn.params["cond_nconsts"] -
                     eqn.params["body_nconsts"])
            out.append(("while", carry))
    return out


def _count_eqns(jaxpr) -> int:
    from .rules_dtype import _iter_jaxprs
    return sum(len(j.eqns) for j in _iter_jaxprs(jaxpr))


def zero_cost_findings(rule_name, target, suffixes, plane_leaves,
                       dead_message) -> list:
    """The shared body of the three plane zero-cost rules (metrics /
    trace / audit): measure the chunk's outermost scan/while carry
    width over the state leaf count + the jaxpr equation count, and
    error when a target carrying one of `suffixes` does NOT widen by
    its plane's `plane_leaves` — ONE implementation, so the three
    planes' residue contracts can never drift apart.
    `dead_message(extra)` renders the plane-specific error text."""
    import jax

    n_state = len(jax.tree.leaves(target.args))
    loops = _loop_carry_widths(target.jaxpr.jaxpr)
    if not loops:
        return [Finding(
            rule=rule_name, target=target.name, severity="warning",
            message="no top-level scan/while loop in the traced "
                    "chunk — carry-residue check has nothing to "
                    "measure")]
    # The chunk loop: the widest top-level loop (phase-specialized
    # builds can emit a narrower tail scan after the block scan).
    prim, carry = max(loops, key=lambda pc: pc[1])
    extra = carry - n_state
    findings = [
        Finding(rule=rule_name, target=target.name, severity="info",
                metric="carry_extra_leaves", value=extra,
                message=f"{prim} carry holds {carry} vars for "
                        f"{n_state} state leaves "
                        f"(carry_extra_leaves={extra})"),
        Finding(rule=rule_name, target=target.name, severity="info",
                metric="jaxpr_eqns", value=_count_eqns(target.jaxpr.jaxpr),
                message="total jaxpr equations in the compiled chunk"),
    ]
    if target.name.endswith(suffixes) and extra < plane_leaves:
        findings.append(Finding(
            rule=rule_name, target=target.name, severity="error",
            message=dead_message(extra)))
    return findings


@register_rule
class MetricsZeroCostRule(Rule):
    name = "metrics_zero_cost"
    scope = "protocol"
    budgeted_metrics = ("carry_extra_leaves", "jaxpr_eqns")

    def run(self, target, budget):
        return zero_cost_findings(
            self.name, target, INSTRUMENTED_SUFFIXES,
            _METRICS_CARRY_LEAVES,
            lambda extra: (
                f"instrumented target carries only {extra} extra "
                f"loop vars (< {_METRICS_CARRY_LEAVES}: the "
                "MetricsCarry leaves) — the metrics plane is "
                "silently dead in this build"))
