"""Backend platform forcing for tests and driver dry runs.

One shared definition of the init-order-sensitive trick used by
tests/conftest.py and __graft_entry__.dryrun_multichip: the sandbox's
sitecustomize imports jax and registers a TPU plugin before user code
runs, overriding the JAX_PLATFORMS env var — but backends are not
initialized yet, so `jax.config.update` still wins, and XLA_FLAGS is read
at first CPU-client init, which also happens later.
"""

from __future__ import annotations

import os


def force_virtual_cpu(n_devices: int = 8) -> None:
    """Force the CPU platform with `n_devices` virtual devices.

    Must run before the first device/backend use (anything that builds an
    array).  If XLA_FLAGS already carries a device-count flag it is kept
    as-is (callers should assert len(jax.devices()) afterwards when they
    need an exact count).
    """
    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    jax.config.update("jax_platforms", "cpu")
