"""The simulation server — IServer parity (wserver/IServer.java:9-34).

The reference wraps its simulator in a Spring-Boot REST facade
(wserver/Server.java, ws/WServer.java): discover protocols by classpath
scan, instantiate one from a WParameters JSON, drive it with runMs, read
node state and pending messages, stop/start nodes, attach "external" nodes
whose deliveries are shipped to a remote system that replies with messages
to inject (core/External.java, Network.java:616-623).

This `Server` is the transport-agnostic core: the protocol registry is the
`@register` table (the classpath-scan analogue), parameters are the
protocol constructors' keyword arguments (the WParameters analogue), and
the external bridge accepts any callable — the HTTP client in
`server/http.py` (ExternalRest parity) is one such callable, the tests'
in-process mock (ExternalMockImplementation parity) another.

External-node semantics: a node marked external is stopped in-engine (it no
longer acts); while any external exists, `run_ms` advances 1 ms at a time,
peeks each external's deliveries (EnvelopeInfo), hands them to the
handler, and injects the returned SendMessages — the reference does the
same per-delivery hop, in-loop (Network.java:616-623).
"""

from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp
import numpy as np

from ..core import network as net_mod
from ..core.protocol import PROTOCOLS, get_protocol
from ..core.state import empty_outbox


def list_protocols() -> list:
    """GET /w/protocols (Server.java:56-70)."""
    return sorted(PROTOCOLS)


def protocol_parameters(name: str) -> dict:
    """GET /w/protocols/{name}: the parameter template with defaults (the
    WParameters JSON analogue)."""
    cls = get_protocol(name)
    sig = inspect.signature(cls.__init__)
    out = {}
    for pname, prm in sig.parameters.items():
        if pname == "self":
            continue
        out[pname] = None if prm.default is inspect.Parameter.empty \
            else prm.default
    return out


def validate_parameters(name: str, params: dict | None):
    """THE parameter gate: `protocol_parameters`'s template is the
    single source for what a request may pass — an unknown kwarg is
    refused here with the template echoed (the HTTP layer surfaces it
    as a 400), instead of surfacing as a deep `TypeError` from the
    protocol constructor.  Returns the protocol class on success.
    `serve.spec.ScenarioSpec.validate` routes through the same gate, so
    the interactive server and the batch plane agree on what a valid
    parameter set is."""
    import json

    try:
        cls = get_protocol(name)
    except KeyError as e:
        raise ValueError(str(e)) from None
    template = protocol_parameters(name)
    unknown = sorted(set(params or {}) - set(template))
    if unknown:
        raise ValueError(
            f"unknown parameter(s) {unknown} for {name}; the template "
            f"(GET /w/protocols/{name}) is: "
            f"{json.dumps(template, sort_keys=True, default=str)}")
    return cls


class Server:
    """Mirrors wserver/Server.java's surface, state-pytree edition."""

    def __init__(self):
        self.protocol = None
        self.protocol_name = None
        self.net = None
        self.pstate = None
        self.runner = None
        self.externals = {}           # node id -> handler(list[dict])->list

    # ---- lifecycle (IServer.init / runMs) ----

    def init(self, name: str, params: dict | None = None, seed: int = 0):
        cls = validate_parameters(name, params)
        self.protocol = cls(**(params or {}))
        self.protocol_name = name
        self.net, self.pstate = self.protocol.init(seed)
        self.runner = net_mod.Runner(self.protocol, donate=False)
        self.externals = {}

    def _require(self):
        if self.protocol is None:
            raise RuntimeError("no protocol initialized (POST /network/init)")

    def run_ms(self, ms: int) -> None:
        self._require()
        if not self.externals:
            self.net, self.pstate = self.runner.run_ms(self.net, self.pstate,
                                                       ms)
            return
        # With externals attached: single-ms steps + bridge per ms.
        for _ in range(int(ms)):
            t = int(self.net.time)
            for nid, handler in self.externals.items():
                delivered = self.peek_messages(nid, t)
                if delivered:
                    for msg in handler(delivered) or []:
                        self.send(msg["from"], msg["to"],
                                  msg.get("payload"), msg.get("delay", 0))
            self.net, self.pstate = self.runner.run_ms(self.net, self.pstate,
                                                       1)

    def time(self) -> int:
        self._require()
        return int(self.net.time)

    # ---- node state ----

    def node_info(self, nid: int) -> dict:
        self._require()
        nid = int(nid)
        if not (0 <= nid < self.protocol.cfg.n):
            raise ValueError(f"no node {nid}; network has "
                             f"{self.protocol.cfg.n} nodes")
        nd = self.net.nodes
        return {
            "nodeId": int(nid),
            "x": int(nd.x[nid]), "y": int(nd.y[nid]),
            "city": int(nd.city[nid]),
            "down": bool(nd.down[nid]),
            "byzantine": bool(nd.byzantine[nid]),
            "external": int(nid) in self.externals,
            "doneAt": int(nd.done_at[nid]),
            "msgReceived": int(nd.msg_received[nid]),
            "msgSent": int(nd.msg_sent[nid]),
            "bytesReceived": int(nd.bytes_received[nid]),
            "bytesSent": int(nd.bytes_sent[nid]),
        }

    def all_nodes(self) -> list:
        self._require()
        nd = self.net.nodes
        cols = {k: np.asarray(getattr(nd, v)) for k, v in [
            ("x", "x"), ("y", "y"), ("city", "city"), ("down", "down"),
            ("byzantine", "byzantine"), ("doneAt", "done_at"),
            ("msgReceived", "msg_received"), ("msgSent", "msg_sent"),
            ("bytesReceived", "bytes_received"),
            ("bytesSent", "bytes_sent")]}
        out = []
        for i in range(self.protocol.cfg.n):
            row = {k: v[i].item() for k, v in cols.items()}
            row["nodeId"] = i
            row["external"] = i in self.externals
            out.append(row)
        return out

    def stop_node(self, nid: int) -> None:
        """POST /network/nodes/{id}/stop (Server.java:135-143)."""
        self._set_down(nid, True)

    def start_node(self, nid: int) -> None:
        self._set_down(nid, False)

    def _set_down(self, nid: int, val: bool) -> None:
        self._require()
        if not (0 <= int(nid) < self.protocol.cfg.n):
            raise ValueError(f"no node {nid}")
        nodes = self.net.nodes
        self.net = self.net.replace(
            nodes=nodes.replace(down=nodes.down.at[int(nid)].set(val)))

    # ---- messages ----

    def peek_messages(self, nid: int | None = None,
                      at: int | None = None) -> list:
        """GET /network/messages: pending deliveries as EnvelopeInfo dicts
        (EnvelopeInfo.java; arrivingAt == the peeked ms only — the mailbox
        is time-bucketed, so we report the next deliverable slice)."""
        self._require()
        cfg = self.protocol.cfg
        t = int(self.net.time) if at is None else int(at)
        # Externals are stopped in-engine (their deliveries are diverted to
        # the handler, like Network.java:616-623 skipping action); lift the
        # down flag for the peek so their inbox is visible.
        net = self.net
        if self.externals:
            down = net.nodes.down
            for x in self.externals:
                down = down.at[x].set(False)
            net = net.replace(nodes=net.nodes.replace(down=down))
        inbox, _, _ = net_mod.build_inbox(cfg, self.protocol.latency,
                                          net, jnp.asarray(t))
        valid = np.asarray(inbox.valid)
        src = np.asarray(inbox.src)
        data = np.asarray(inbox.data)
        out = []
        rows = range(cfg.n) if nid is None else [int(nid)]
        for i in rows:
            for s in np.nonzero(valid[i])[0]:
                out.append({"from": int(src[i, s]), "to": int(i),
                            "arrivingAt": t,
                            "payload": [int(x) for x in data[i, s]]})
        return out

    def pending_messages(self) -> list:
        """GET /network/messages — the FULL in-flight set (Server.java:
        168-171): every undelivered unicast in the mailbox ring plus every
        active broadcast's future per-dest arrivals, recomputed from the
        counter PRNG exactly as delivery will, as EnvelopeInfo dicts sorted
        by (arrivingAt, sentAt, from, to) (EnvelopeInfo.java:33-47).

        sentAt is -1 for unicasts: the ring, like the reference's envelope
        compression (Envelope.java:45-56), does not retain send times.
        Broadcast rows apply the down/partition filter at peek time (the
        engine applies it at delivery); unicast rows were filtered at send
        time, as the reference's createMessageArrival does."""
        self._require()
        cfg = self.protocol.cfg
        t = int(self.net.time)
        H, n, c, f = cfg.horizon, cfg.n, cfg.inbox_cap, cfg.payload_words
        out = []

        count = np.asarray(self.net.box_count)                   # [H, N]
        # Sub-planes (EngineConfig.box_split) reassemble along the node
        # axis: sub-plane j holds nodes [j*Ns, (j+1)*Ns) as [H, Ns, C].
        p, ns = cfg.box_split, cfg.split_n
        src = np.concatenate(
            [np.asarray(pl).reshape(H, ns, c) for pl in self.net.box_src],
            axis=1)                                              # [H, N, C]
        data = np.stack(
            [np.concatenate([np.asarray(pl).reshape(H, ns, c)
                             for pl in self.net.box_data[fi * p:
                                                         (fi + 1) * p]],
                            axis=1)
             for fi in range(f)])                                # [F,H,N,C]
        for h in np.nonzero(count.sum(axis=1))[0]:
            arriving = t + int((int(h) - t) % H)
            for d in np.nonzero(count[h])[0]:
                for s in range(int(count[h, d])):
                    out.append({
                        "from": int(src[h, d, s]), "to": int(d),
                        "sentAt": -1, "arrivingAt": arriving,
                        "payload": [int(data[fi, h, d, s])
                                    for fi in range(f)]})

        sp_arr = np.asarray(self.net.sp_arrival)
        if sp_arr.size:
            sp_src = np.asarray(self.net.sp_src)
            sp_dest = np.asarray(self.net.sp_dest)
            sp_pay = np.asarray(self.net.sp_payload)
            for s in np.nonzero(sp_arr >= 0)[0]:
                out.append({"from": int(sp_src[s]), "to": int(sp_dest[s]),
                            "sentAt": -1, "arrivingAt": int(sp_arr[s]),
                            "payload": [int(x) for x in sp_pay[s]]})

        if bool(np.asarray(self.net.bc_active).any()):
            # External nodes are stopped in-engine but their deliveries DO
            # reach the bridge (run_ms lifts the down flag, like
            # Network.java:616-623 diverting instead of dropping) — lift it
            # for the peek too so their in-flight traffic is visible.
            nodes = self.net.nodes
            down = nodes.down
            for x in self.externals:
                down = down.at[x].set(False)
            nodes = nodes.replace(down=down)
            arrival, ok, _ = net_mod.broadcast_arrivals(
                cfg, self.protocol.latency, self.net, nodes)
            pend = ok & (arrival >= t) & (~nodes.down[None, :])
            pend_np = np.asarray(pend)
            arr_np = np.asarray(arrival)
            bsrc = np.asarray(self.net.bc_src)
            btime = np.asarray(self.net.bc_time)
            bpay = np.asarray(self.net.bc_payload)
            for r, d in zip(*np.nonzero(pend_np)):
                out.append({
                    "from": int(bsrc[r]), "to": int(d),
                    "sentAt": int(btime[r]),
                    "arrivingAt": int(arr_np[r, d]),
                    "payload": [int(x) for x in bpay[r]]})

        out.sort(key=lambda e: (e["arrivingAt"], e["sentAt"], e["from"],
                                e["to"]))
        return out

    def send(self, src: int, dest: int, payload=None, delay: int = 0):
        """POST /network/send (SendMessage.java): inject a unicast."""
        self._require()
        cfg = self.protocol.cfg
        out = empty_outbox(cfg)
        pl = jnp.zeros((cfg.payload_words,), jnp.int32)
        for i, v in enumerate((payload or [])[:cfg.payload_words]):
            pl = pl.at[i].set(int(v))
        out = out.replace(
            dest=out.dest.at[int(src), 0].set(int(dest)),
            payload=out.payload.at[int(src), 0].set(pl),
            delay=out.delay.at[int(src), 0].set(int(delay)))
        # A stopped/external sender still injects (the reference's inject
        # path goes through Network.send on the external's behalf).
        was_down = bool(self.net.nodes.down[int(src)])
        net = self.net
        if was_down:
            net = net.replace(nodes=net.nodes.replace(
                down=net.nodes.down.at[int(src)].set(False)))
        net = net_mod.enqueue_unicast(cfg, self.protocol.latency, net, out,
                                      jnp.asarray(int(net.time)))
        if was_down:
            net = net.replace(nodes=net.nodes.replace(
                down=net.nodes.down.at[int(src)].set(True)))
        self.net = net

    # ---- external bridge (External.java / ExternalRest.java) ----

    def set_external(self, nid: int, handler) -> None:
        """Mark a node external: stop it in-engine, route its deliveries to
        `handler(list[EnvelopeInfo]) -> list[SendMessage dict]`."""
        self._require()
        self.stop_node(nid)
        self.externals[int(nid)] = handler

    def clear_external(self, nid: int) -> None:
        self._require()
        self.externals.pop(int(nid), None)
        self.start_node(nid)
