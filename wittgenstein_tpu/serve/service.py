"""`Service` — the submit/status/result surface of the request plane.

Transport-agnostic like `server/core.Server`: the HTTP layer
(`server/http.py` `/w/batch/*`) and in-process callers (tests,
`tools/serve_bench.py`, the bench_suite `serve_smoke` stage) drive the
same object.  JSON in, JSON out:

  submit(spec_json)  -> {"id", "status", "compile_key"}; a bad spec
                        raises ValueError with remedy text (the HTTP
                        layer's 400)
  status(id)         -> lifecycle + the streaming-progress snapshot the
                        scheduler refreshes from the on-device metrics
                        plane at every chunk boundary
  result(id)         -> the finished request's artifacts (engine_metrics
                        / trace / audit blocks, summary, manifest path);
                        a not-yet-done request answers with its status
                        instead of an error (poll-friendly)
  registry_stats()   -> compile-registry warm/cold counters

``auto=True`` (the server default) drains the queue on a background
worker thread, so submit returns immediately and status streams; with
``auto=False`` (tests, benchmarks) the caller drains explicitly via
`run_pending()` for deterministic scheduling.
"""

from __future__ import annotations

import threading

from .scheduler import Scheduler
from .spec import ScenarioSpec


class Service:
    def __init__(self, scheduler: Scheduler | None = None,
                 auto: bool = True):
        self.scheduler = scheduler or Scheduler()
        self._auto = auto
        self._wake = threading.Event()
        self._stop = False
        self._worker = None

    # ------------------------------------------------------------ worker

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._drain_loop,
                                            daemon=True,
                                            name="wtpu-serve-worker")
            self._worker.start()

    def _drain_loop(self):
        while not self._stop:
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            if self._stop:
                return
            if self.scheduler.pending():
                self.scheduler.run_pending()

    def close(self):
        self._stop = True
        self._wake.set()
        if self._worker is not None:
            self._worker.join(timeout=5)

    # --------------------------------------------------------- endpoints

    def submit(self, body: dict) -> dict:
        """POST /w/batch/submit — body is a `ScenarioSpec` JSON object."""
        spec = ScenarioSpec.from_json(body or {})
        rid = self.scheduler.submit(spec)
        if self._auto:
            self._ensure_worker()
            self._wake.set()
        req = self.scheduler.request(rid)
        return {"id": rid, "status": req.status,
                "compile_key": req.compile_key}

    def status(self, rid: str) -> dict:
        """GET /w/batch/status/{id}."""
        return self.scheduler.request(rid).status_json()

    def result(self, rid: str) -> dict:
        """GET /w/batch/result/{id} — artifacts when done, else the
        status snapshot (poll until ``"status" == "done"``)."""
        req = self.scheduler.request(rid)
        if req.status != "done":
            return req.status_json()
        out = dict(req.artifacts)
        out["status"] = "done"
        if req.manifest_path:
            out["manifest_path"] = req.manifest_path
        return out

    def run_pending(self) -> dict:
        """POST /w/batch/run — synchronous drain (manual mode / ops)."""
        return self.scheduler.run_pending()

    def registry_stats(self) -> dict:
        """GET /w/batch/registry."""
        return self.scheduler.registry.registry_block()
