"""OptimisticP2PSignature + P2PHandel tests."""

import pytest

import jax.numpy as jnp
import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.optimistic import OptimisticP2PSignature
from wittgenstein_tpu.models.p2phandel import (P2PHandel, compressed_size)
from wittgenstein_tpu.ops import bitset


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 54 s; test_scenarios.test_optimistic_node_scaling_smoke keeps the
# protocol running in the fast suite
def test_optimistic_run():
    # OptimisticP2PSignature.main: 1000 nodes, threshold n/2+1, 13 peers,
    # pairing 3 — scaled down for the test.
    p = OptimisticP2PSignature(node_count=128, threshold=65,
                               connection_count=13, pairing_time=3,
                               network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    for _ in range(10):
        net, ps = r.run_ms(net, ps, 200)
        if bool(jnp.all(ps.done)):
            break
    assert bool(jnp.all(ps.done))
    assert int(net.dropped) == 0 and int(net.clamped) == 0
    done_at = np.asarray(net.nodes.done_at)
    assert np.all(done_at > 0)
    card = np.asarray(bitset.popcount(ps.received))
    assert np.all(card >= 65)
    # Determinism
    net2, ps2 = p.init(0)
    for _ in range(int(net.time) // 200):
        net2, ps2 = r.run_ms(net2, ps2, 200)
    assert np.array_equal(np.asarray(net2.nodes.done_at), done_at)


def test_compressed_size():
    # compressedSize doc examples (P2PHandel.java:147-158), 8-bit sets:
    # 1101 0111 -> 5 (pair {2,3} merges), 1111 1110 -> ... our canonical
    # dyadic count: full pairs {0,1},{2,3} merge into one level-1 segment.
    def cs(bits_str, n_sign=16):
        v = 0
        for i, c in enumerate(bits_str):
            if c == "1":
                v |= 1 << i
        row = jnp.asarray([[v]], jnp.uint32)
        return int(compressed_size(row, n_sign)[0])

    # 1101 0111 (bits 0,1,3,4,6,7? — string is bit order LSB-first here):
    # pairs: (1,1)=full, (0,1), (0,1), (1,1)=full -> 2 singles + 2 segments
    assert cs("11010111") == 2 + 2
    # all 8 bits set: one aligned run of 4 pairs -> 1 segment
    assert cs("11111111") == 1
    # 0111 0111 (LSB-first): pairs (0,1),(1,1),(0,1),(1,1) -> 2 singles in
    # partial pairs + 2 non-adjacent full-pair segments
    assert cs("01110111") == 4
    # complete set shortcut
    assert cs("1" * 16, n_sign=16) == 1


@pytest.mark.slow
def test_p2phandel_run():
    p = P2PHandel(signing_node_count=100, relaying_node_count=20,
                  threshold=99, connection_count=10, pairing_time=10,
                  sigs_send_period=50, double_aggregate_strategy=True,
                  send_sigs_strategy="dif",
                  network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    for _ in range(20):
        net, ps = r.run_ms(net, ps, 500)
        done = np.asarray(net.nodes.done_at)
        if (done > 0).all():
            break
    assert (done > 0).all(), f"{(done > 0).sum()}/{len(done)} done"
    assert int(net.dropped) == 0
    card = np.asarray(bitset.popcount(ps.verified))
    assert np.all(card >= 99)


@pytest.mark.slow
def test_p2phandel_cmp_all_strategy():
    """The remaining send strategy (P2PHandel.java:25-34 'cmp_all': full
    state, compressed-size costing) — runs to completion like the others;
    with it, all four strategies are exercised across the suite (all:
    scenario smoke, dif/cmp_diff: the tests around this one)."""
    p = P2PHandel(signing_node_count=64, relaying_node_count=8,
                  threshold=60, connection_count=8, pairing_time=10,
                  sigs_send_period=50, double_aggregate_strategy=False,
                  send_sigs_strategy="cmp_all",
                  network_latency_name="NetworkFixedLatency(20)")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    for _ in range(20):
        net, ps = r.run_ms(net, ps, 500)
        done = np.asarray(net.nodes.done_at)
        if (done > 0).all():
            break
    assert (done > 0).all()
    assert int(net.dropped) == 0
    # Smoke-level byte accounting only (the compressed-size model itself
    # is unit-tested via compressed_size in this file's cs() tests).
    assert int(np.asarray(net.nodes.bytes_sent).sum()) > 0


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 55 s; P2PHandel stays gated by the (slow) ff equality battery
def test_p2phandel_checksigs1():
    p = P2PHandel(signing_node_count=64, relaying_node_count=0,
                  threshold=60, connection_count=8, pairing_time=10,
                  sigs_send_period=50, double_aggregate_strategy=False,
                  send_sigs_strategy="cmp_diff", send_state=True,
                  network_latency_name="NetworkNoLatency")
    r = Runner(p, donate=False)
    net, ps = p.init(1)
    for _ in range(20):
        net, ps = r.run_ms(net, ps, 500)
        done = np.asarray(net.nodes.done_at)
        if (done > 0).all():
            break
    assert (done > 0).all()
