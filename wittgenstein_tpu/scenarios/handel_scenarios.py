"""Handel experiment sweeps — HandelScenarios.java parity.

The reference's default scenario (HandelScenarios.java:61-123): 2048 nodes,
10% dead, threshold 0.99*live, pairing 4 ms, levelWait 50 ms, period 20 ms,
fastPath 10, CITIES builder.  Sweeps: node-count log scaling (:324-363),
tor fraction (:177), desynchronized start (:192), period (:433+).

Every sweep point runs a BATCH of seeds in one device program
(core/harness.run_multiple_times — the vmapped RunMultipleTimes), and
results land in a CSVFormatter + Graph PNG.  Run as
`python -m wittgenstein_tpu.scenarios.handel_scenarios [out_dir]` for a
small smoke sweep.
"""

from __future__ import annotations

import time

import numpy as np

from ..core import builders
from ..core.harness import run_multiple_times
from ..models.handel import Handel, cont_if_handel
from ..tools.csvf import CSVFormatter
from ..tools.graph import Graph, Series


def default_params(nodes=2048, dead_ratio=0.10, **overrides):
    """HandelScenarios.defaultParams (:61-123)."""
    dead = int(nodes * dead_ratio)
    params = dict(node_count=nodes, nodes_down=dead,
                  threshold=int(0.99 * (nodes - dead)),
                  pairing_time=4, level_wait_time=50,
                  dissemination_period_ms=20, fast_path=10,
                  node_builder_name=builders.registry_name(
                      "cities", True, 0.0),
                  network_latency_name="NetworkLatencyByDistanceWJitter")
    params.update(overrides)
    return params


def _run_point(params, seeds, max_time=4000, chunk=250):
    proto = Handel(**params)
    t0 = time.perf_counter()
    res = run_multiple_times(proto, run_count=seeds, max_time=max_time,
                             chunk=chunk, cont_if=cont_if_handel)
    wall = time.perf_counter() - t0
    # Queue-eviction guard (VERDICT r1 weak #3): the bounded verification
    # queue is a tensorization of the reference's unbounded toVerifyAgg
    # (Handel.java:830-834); in non-attack scenarios nothing may be
    # evicted, or the semantics silently degrade.  Byzantine floods evict
    # by design (see tests/test_handel.py hiddenByzantine stress).
    evicted = int(np.asarray(res.pstates.evicted).sum())
    if not (params.get("hidden_byzantine") or params.get("byzantine_suicide")):
        assert evicted == 0, \
            f"{evicted} queue evictions in a non-byzantine scenario: " \
            "queue_cap is undersized for this config"
    done_at = np.asarray(res.nets.nodes.done_at)
    down = np.asarray(res.nets.nodes.down)
    per_run_done = [done_at[i][~down[i]] for i in range(seeds)]
    return {
        "avg_done_ms": float(np.mean([d.mean() for d in per_run_done])),
        "max_done_ms": float(np.max([d.max() for d in per_run_done])),
        "frac_done": float(np.mean([(d > 0).mean() for d in per_run_done])),
        "wall_s": wall,
        "msg_sent_avg": float(np.asarray(res.nets.nodes.msg_sent).mean()),
        "bytes_sent_avg": float(
            np.asarray(res.nets.nodes.bytes_sent).mean()),
    }


def node_scaling(counts=(128, 256, 512, 1024, 2048), seeds=4, out_dir="."):
    """Log node-count scaling (HandelScenarios.byNodeCount-style,
    :324-363)."""
    csv = CSVFormatter(["nodes", "avg_done_ms", "max_done_ms", "wall_s",
                        "msg_sent_avg"])
    g = Graph("Handel: time to aggregate vs node count", "nodes",
              "avg doneAt (ms)")
    s = Series("avg doneAt")
    for n in counts:
        r = _run_point(default_params(nodes=n), seeds)
        csv.add(nodes=n, **{k: round(v, 1) for k, v in r.items()
                            if k in csv.columns})
        s.add(n, r["avg_done_ms"])
        print(f"nodes={n}: {r}")
    g.add_series(s)
    csv.save(f"{out_dir}/handel_node_scaling.csv")
    g.save(f"{out_dir}/handel_node_scaling.png")
    return csv


def tor_sweep(fractions=(0.0, 0.1, 0.33), nodes=256, seeds=4, out_dir="."):
    """Tor-like extra-latency fraction sweep (:177)."""
    csv = CSVFormatter(["tor", "avg_done_ms", "max_done_ms"])
    for tor in fractions:
        name = builders.registry_name("random", True, tor)
        # Tor adds +500 ms extra latency per endpoint (builders.py), so a
        # tor->tor hop can reach ~1100+ ms: size the mailbox ring for it
        # (the engine clamps arrivals past horizon-2 and the harness
        # fails on any clamp).
        r = _run_point(default_params(nodes=nodes, node_builder_name=name,
                                      horizon=2048), seeds,
                       max_time=8000)
        csv.add(tor=tor, avg_done_ms=round(r["avg_done_ms"], 1),
                max_done_ms=round(r["max_done_ms"], 1))
        print(f"tor={tor}: {r}")
    csv.save(f"{out_dir}/handel_tor.csv")
    return csv


def desync_sweep(starts=(0, 50, 200), nodes=256, seeds=4, out_dir="."):
    """Desynchronized start sweep (:192)."""
    csv = CSVFormatter(["desync_ms", "avg_done_ms", "max_done_ms"])
    for d in starts:
        r = _run_point(default_params(nodes=nodes,
                                      desynchronized_start=d), seeds)
        csv.add(desync_ms=d, avg_done_ms=round(r["avg_done_ms"], 1),
                max_done_ms=round(r["max_done_ms"], 1))
        print(f"desync={d}: {r}")
    csv.save(f"{out_dir}/handel_desync.csv")
    return csv


def period_sweep(periods=(10, 20, 50), nodes=256, seeds=4, out_dir="."):
    """Dissemination period sweep (:433-604)."""
    csv = CSVFormatter(["period_ms", "avg_done_ms", "bytes_sent_avg"])
    for p in periods:
        r = _run_point(default_params(nodes=nodes,
                                      dissemination_period_ms=p), seeds)
        csv.add(period_ms=p, avg_done_ms=round(r["avg_done_ms"], 1),
                bytes_sent_avg=round(r["bytes_sent_avg"], 1))
        print(f"period={p}: {r}")
    csv.save(f"{out_dir}/handel_period.csv")
    return csv


def byz_suicide_sweep(ratios=(0.0, 0.1, 0.25, 0.5), nodes=256, seeds=4,
                      out_dir="."):
    """byzantineSuicide attack impact sweep (HandelScenarios.runOnce with
    byzantineSuicide, :204-257): byzantine ratio vs time-to-aggregate of the
    honest majority.  Threshold stays at 0.99 * live."""
    csv = CSVFormatter(["byz_ratio", "avg_done_ms", "max_done_ms",
                        "frac_done"])
    for ratio in ratios:
        params = default_params(nodes=nodes, dead_ratio=ratio,
                                byzantine_suicide=ratio > 0)
        r = _run_point(params, seeds, max_time=8000)
        csv.add(byz_ratio=ratio, avg_done_ms=round(r["avg_done_ms"], 1),
                max_done_ms=round(r["max_done_ms"], 1),
                frac_done=round(r["frac_done"], 3))
        print(f"byz_suicide ratio={ratio}: {r}")
    csv.save(f"{out_dir}/handel_byz_suicide.csv")
    return csv


def hidden_byz_sweep(ratios=(0.0, 0.1, 0.25, 0.5), nodes=256, seeds=4,
                     out_dir="."):
    """hiddenByzantine attack impact sweep (HandelScenarios :259-289)."""
    csv = CSVFormatter(["byz_ratio", "avg_done_ms", "max_done_ms",
                        "frac_done"])
    for ratio in ratios:
        params = default_params(nodes=nodes, dead_ratio=ratio,
                                hidden_byzantine=ratio > 0)
        r = _run_point(params, seeds, max_time=8000)
        csv.add(byz_ratio=ratio, avg_done_ms=round(r["avg_done_ms"], 1),
                max_done_ms=round(r["max_done_ms"], 1),
                frac_done=round(r["frac_done"], 3))
        print(f"hidden_byz ratio={ratio}: {r}")
    csv.save(f"{out_dir}/handel_hidden_byz.csv")
    return csv


def log_errors(error_rate=0.2, counts=(32, 64, 128, 256), seeds=4,
               out_dir="."):
    """Fail-silent error-rate node scaling (HandelScenarios.logErrors
    :365-430): time + message counts as n doubles at a fixed dead
    fraction.  Reference default sweeps n = 32..4096 at errors = 0.2."""
    csv = CSVFormatter(["nodes", "error_rate", "avg_done_ms",
                        "msg_sent_avg", "frac_done"])
    g = Graph(f"Handel under {int(error_rate * 100)}% fail-silent errors",
              "nodes", "avg doneAt (ms)")
    s = Series(f"errors={int(error_rate * 100)}%")
    for n in counts:
        r = _run_point(default_params(nodes=n, dead_ratio=error_rate),
                       seeds, max_time=8000)
        csv.add(nodes=n, error_rate=error_rate,
                avg_done_ms=round(r["avg_done_ms"], 1),
                msg_sent_avg=round(r["msg_sent_avg"], 1),
                frac_done=round(r["frac_done"], 3))
        s.add(n, r["avg_done_ms"])
        print(f"errors={error_rate} nodes={n}: {r}")
    g.add_series(s)
    csv.save(f"{out_dir}/handel_errors.csv")
    g.save(f"{out_dir}/handel_errors.png")
    return csv


def extra_cycle_sweep(cycles=(10, 15, 20, 30, 40, 50), nodes=256, seeds=4,
                      dead_ratio=0.10, out_dir="."):
    """extraCycle sweep (HandelScenarios.logExtraCycle :568-585): done
    nodes keep disseminating for ec more periods; measures the cost of
    the grace cycles vs completion reliability.  Reference runs n=4096,
    r=5 seeds."""
    csv = CSVFormatter(["extra_cycle", "avg_done_ms", "msg_sent_avg",
                        "frac_done"])
    for ec in cycles:
        r = _run_point(default_params(nodes=nodes, dead_ratio=dead_ratio,
                                      extra_cycle=ec), seeds,
                       max_time=8000)
        csv.add(extra_cycle=ec, avg_done_ms=round(r["avg_done_ms"], 1),
                msg_sent_avg=round(r["msg_sent_avg"], 1),
                frac_done=round(r["frac_done"], 3))
        print(f"extra_cycle={ec}: {r}")
    csv.save(f"{out_dir}/handel_extra_cycle.csv")
    return csv


def contacted_node_sweep(fast_paths=(0, 5, 10, 20, 40), nodes=256, seeds=4,
                         dead_ratio=0.10, out_dir="."):
    """Fast-path peer-count sweep (HandelScenarios.logContactedNode
    :588-632): time and messages vs the number of peers contacted on
    level completion.  Reference runs n=4096, r=5 seeds."""
    csv = CSVFormatter(["fast_path", "avg_done_ms", "msg_sent_avg",
                        "frac_done"])
    g = Graph("Handel: time vs fast-path peer count", "fast path peers",
              "avg doneAt (ms)")
    s = Series("avg doneAt")
    for fp in fast_paths:
        r = _run_point(default_params(nodes=nodes, dead_ratio=dead_ratio,
                                      fast_path=fp), seeds, max_time=8000)
        csv.add(fast_path=fp, avg_done_ms=round(r["avg_done_ms"], 1),
                msg_sent_avg=round(r["msg_sent_avg"], 1),
                frac_done=round(r["frac_done"], 3))
        s.add(fp, r["avg_done_ms"])
        print(f"fast_path={fp}: {r}")
    g.add_series(s)
    csv.save(f"{out_dir}/handel_fastpath.csv")
    g.save(f"{out_dir}/handel_fastpath.png")
    return csv


def gen_anim(nodes=256, out_path="handel.gif", frames=20, frame_ms=50):
    """Animated GIF of aggregation progress (HandelScenarios.genAnim :291,
    NodeDrawer parity)."""
    from ..core.network import Runner
    from ..ops import bitset
    from ..tools.node_drawer import NodeDrawer
    params = default_params(nodes=nodes,
                            node_builder_name=None)
    proto = Handel(**params)
    runner = Runner(proto, donate=False)
    net, ps = proto.init(0)
    drawer = NodeDrawer(vmin=1, vmax=nodes)
    for _ in range(frames):
        net, ps = runner.run_ms(net, ps, frame_ms)
        vals = np.asarray(bitset.popcount(ps.last_agg | ps.ver_ind))
        drawer.draw(net.nodes, vals)
        if bool((np.asarray(net.nodes.done_at)[
                ~np.asarray(net.nodes.down)] > 0).all()):
            break
    drawer.save_gif(out_path, ms_per_frame=120)
    return out_path


if __name__ == "__main__":
    import sys
    out = sys.argv[1] if len(sys.argv) > 1 else "."
    node_scaling(counts=(128, 256), seeds=2, out_dir=out)
    tor_sweep(fractions=(0.0, 0.33), nodes=128, seeds=2, out_dir=out)
