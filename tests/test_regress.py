"""Bench-history regression gate (PR 20) — detector + CLI semantics.

Acceptance pins:
  * a seeded 2x wall inflation exits 1 and the finding NAMES the
    stage and series; re-gating the same data after a clean round
    exits 0;
  * rows from a different host fingerprint are NEVER compared (the
    cross-host key isolation);
  * direction semantics: ``*per_sec*`` regresses on a drop, wall
    series regress on a rise, count series are not gated;
  * the median/MAD threshold survives an outlier INSIDE the baseline
    window, and the relative floor keeps a zero-MAD history from
    flagging noise;
  * a torn history tail (SIGKILL mid-append) is tolerated on reload;
  * exit 2 on missing/empty history or an unknown round.
"""

import json
import os
import sys

import pytest

from wittgenstein_tpu.obs import regress
from wittgenstein_tpu.obs.regress import (BenchHistory,
                                          detect_regressions, gate,
                                          read_history,
                                          series_direction,
                                          stage_measures)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fill(h, stage="route", rounds=5, wall=0.5, value=100.0,
          host=None, metric="route_msgs_per_sec", digest="abc",
          jitter=0.0):
    for r in range(rounds):
        h.append(stage=stage, round_id=f"base{r}",
                 measures={"value": value + jitter * r,
                           "wall_median_s": wall + 0.01 * jitter * r},
                 config_digest=digest, backend="cpu", host=host,
                 metric=metric)


# ------------------------------------------------------------- the gate

def test_wall_inflation_exits_1_naming_stage(tmp_path):
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h, jitter=1.0)
    h.append(stage="route", round_id="new",
             measures={"value": 101.0, "wall_median_s": 1.0},
             config_digest="abc", backend="cpu",
             metric="route_msgs_per_sec")
    code, findings, summary = gate(p)
    assert code == 1 and summary["regressions"] == 1
    [f] = findings
    assert f["stage"] == "route" and f["series"] == "wall_median_s"
    assert f["direction"] == "down" and f["ratio"] == pytest.approx(
        1.0 / 0.52, rel=0.05)
    assert "route.wall_median_s" in regress.format_findings(findings)


def test_clean_rerun_exits_0(tmp_path):
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h, jitter=1.0)
    h.append(stage="route", round_id="new",
             measures={"value": 103.0, "wall_median_s": 0.53},
             config_digest="abc", backend="cpu",
             metric="route_msgs_per_sec")
    code, findings, summary = gate(p)
    assert code == 0 and not findings
    assert summary["series_checked"] == 2


def test_throughput_drop_is_a_regression(tmp_path):
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h)
    h.append(stage="route", round_id="new", measures={"value": 50.0},
             config_digest="abc", backend="cpu",
             metric="route_msgs_per_sec")
    code, findings, _ = gate(p)
    assert code == 1
    assert findings[0]["series"] == "value"
    assert findings[0]["direction"] == "up"     # higher-is-better fell


def test_cross_host_rows_never_compared(tmp_path):
    """A laptop's baseline must not gate a TPU host: the new round
    from an unknown host has NO baseline, so nothing is checked."""
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h, host="laptop/arm64")
    h.append(stage="route", round_id="new",
             measures={"value": 1.0, "wall_median_s": 99.0},
             config_digest="abc", backend="cpu", host="tpuvm/x86_64",
             metric="route_msgs_per_sec")
    code, findings, summary = gate(p)
    assert code == 0 and not findings
    assert summary["series_checked"] == 0
    assert summary["series_skipped_no_baseline"] == 2


def test_config_digest_partitions_baselines(tmp_path):
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h, digest="k1-config", wall=0.1)
    h.append(stage="route", round_id="new",
             measures={"wall_median_s": 5.0},
             config_digest="k4-config", backend="cpu",
             metric="route_msgs_per_sec")
    code, _, summary = gate(p)
    assert code == 0 and summary["series_checked"] == 0


# ------------------------------------------------------------- detector

def test_directions():
    assert series_direction("value", "route_msgs_per_sec") == "up"
    assert series_direction("value", "analysis_smoke_wall_s") == "down"
    assert series_direction("wall_median_s", None) == "down"
    assert series_direction("wall_s", "x_events") == "down"
    # count-like series are not gated
    assert series_direction("value", "trace_smoke_events") is None
    assert series_direction("value", None) is None


def test_stage_measures_extraction():
    res = {"metric": "m", "value": 7, "wall_median_s": 0.25,
           "wall_s": 1.5, "reps": 2, "unit": "x",
           "crosscheck": "sync_override"}
    assert stage_measures(res) == {"value": 7.0, "wall_s": 1.5,
                                   "wall_median_s": 0.25}
    assert stage_measures({"metric": "m", "error": "boom"}) == {}
    assert stage_measures({"value": True}) == {}    # bools are not data


def test_mad_threshold_survives_baseline_outlier():
    hist = [{"stage": "s", "config_digest": "d", "backend": "cpu",
             "host": "h", "round": f"r{i}", "metric": "x_per_sec",
             "measures": {"value": v}}
            for i, v in enumerate([100.0, 101.0, 99.0, 100.0, 30.0])]
    new = [{"stage": "s", "config_digest": "d", "backend": "cpu",
            "host": "h", "round": "n", "metric": "x_per_sec",
            "measures": {"value": 97.0}}]
    findings, checked = detect_regressions(hist, new)
    assert checked == 1 and not findings    # median ~100, MAD robust


def test_rel_floor_gates_zero_mad_history():
    hist = [{"stage": "s", "config_digest": "d", "backend": "cpu",
             "host": "h", "round": f"r{i}", "metric": "x_per_sec",
             "measures": {"value": 100.0}} for i in range(5)]
    mk = lambda v: [{"stage": "s", "config_digest": "d",  # noqa: E731
                     "backend": "cpu", "host": "h", "round": "n",
                     "metric": "x_per_sec", "measures": {"value": v}}]
    # within the 10% floor: clean; past it: flagged
    assert not detect_regressions(hist, mk(95.0))[0]
    assert detect_regressions(hist, mk(85.0))[0]


def test_min_baseline_skips_thin_history():
    hist = [{"stage": "s", "config_digest": "d", "backend": "cpu",
             "host": "h", "round": f"r{i}", "metric": "x_per_sec",
             "measures": {"value": 100.0}} for i in range(2)]
    new = [{"stage": "s", "config_digest": "d", "backend": "cpu",
            "host": "h", "round": "n", "metric": "x_per_sec",
            "measures": {"value": 1.0}}]
    findings, checked = detect_regressions(hist, new)
    assert checked == 0 and not findings


# ----------------------------------------------------------- durability

def test_torn_tail_tolerated(tmp_path):
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h, rounds=3)
    with open(p, "ab") as f:        # the SIGKILL mid-append shape
        f.write(b'{"schema": 1, "stage": "route", "measur')
    rows = read_history(p)
    assert len(rows) == 3
    code, _, _ = gate(p)
    assert code == 0


def test_non_history_rows_skipped(tmp_path, capsys):
    p = tmp_path / "hist.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"not": "a history row"}) + "\n")
        f.write(json.dumps({"stage": "s", "round": "r",
                            "measures": {"value": 1.0}}) + "\n")
    rows = read_history(p)
    assert len(rows) == 1
    assert "not a history row" in capsys.readouterr().err


def test_append_error_degrades_loudly(tmp_path, capsys):
    h = BenchHistory(tmp_path)      # a DIRECTORY: open() fails
    h.append(stage="s", round_id="r", measures={"value": 1.0})
    assert h.stats()["write_errors"] == 1
    assert "regress" in capsys.readouterr().err


# ------------------------------------------------------------------ CLI

def test_exit_2_on_missing_or_unknown(tmp_path):
    assert gate(tmp_path / "missing.jsonl")[0] == 2
    p = tmp_path / "hist.jsonl"
    BenchHistory(p).append(stage="s", round_id="r",
                           measures={"value": 1.0})
    assert gate(p, round_id="nope")[0] == 2


def test_tools_regress_cli(tmp_path, capsys):
    from tools import regress as cli
    p = tmp_path / "hist.jsonl"
    h = BenchHistory(p)
    _fill(h, jitter=1.0)
    h.append(stage="route", round_id="bad",
             measures={"wall_median_s": 2.0}, config_digest="abc",
             backend="cpu", metric="route_msgs_per_sec")
    capsys.readouterr()
    assert cli.main([str(p)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION route.wall_median_s" in out
    assert cli.main([str(p), "--json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["exit"] == 1
    assert verdict["findings"][0]["stage"] == "route"
    assert cli.main([str(tmp_path / "missing.jsonl")]) == 2
