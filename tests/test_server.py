"""Server tests — WServerTest.java parity: protocol discovery, parameter
templates for every protocol, init→send→runMs→time workflow over HTTP, and
the external-node bridge with a mock remote (ExternalMockImplementation)."""

import json
import urllib.request

import numpy as np
import pytest

import wittgenstein_tpu.models  # noqa: F401 — fills the registry
from wittgenstein_tpu.server import core as score
from wittgenstein_tpu.server.http import make_server


def test_protocol_discovery_and_templates():
    names = score.list_protocols()
    assert len(names) >= 16
    for expected in ("Handel", "GSFSignature", "CasperIMD", "Dfinity",
                     "ETHPoW", "SanFermin", "Paxos", "Slush", "Snowflake",
                     "P2PFlood", "ENRGossiping", "PingPong"):
        assert expected in names
    # WServerTest.java:66-124 round-trips the parameter JSON for EVERY
    # registered protocol.
    for name in names:
        tpl = score.protocol_parameters(name)
        assert isinstance(tpl, dict) and tpl, name


def test_workflow_in_process():
    s = score.Server()
    s.init("PingPong", {"node_count": 64}, seed=0)
    assert s.time() == 0
    s.run_ms(300)
    assert s.time() == 300
    nodes = s.all_nodes()
    assert len(nodes) == 64
    assert sum(n["msgReceived"] for n in nodes) > 0
    # stop / start round-trip (Server.java:135-143)
    s.stop_node(5)
    assert s.node_info(5)["down"]
    s.start_node(5)
    assert not s.node_info(5)["down"]


def test_external_bridge_mock():
    # ExternalMockImplementation parity: the "remote" sees deliveries for
    # the external node and replies with an injected message.
    s = score.Server()
    s.init("PingPong", {"node_count": 32}, seed=0)
    seen = []

    def mock(delivered):
        seen.extend(delivered)
        # reply: the external node answers the first sender
        return [{"from": delivered[0]["to"], "to": delivered[0]["from"],
                 "payload": [1]}] if delivered else []

    s.set_external(3, mock)
    assert s.node_info(3)["external"] and s.node_info(3)["down"]
    s.run_ms(300)
    # PingPong's witness broadcast reaches node 3 -> the mock saw it.
    assert seen, "external node received its deliveries"
    assert all(e["to"] == 3 for e in seen)


def test_pending_messages_full_in_flight_set():
    """Server.java:168-171 exposes the WHOLE in-flight set, not just the
    next ms: injected unicasts at different delays + a live broadcast must
    all appear, sorted by (arrivingAt, sentAt, from, to)."""
    s = score.Server()
    s.init("PingPong", {"node_count": 32}, seed=0)
    # Two unicasts, 100 ms apart; delay d arrives at t + 1 + d + latency.
    s.send(1, 2, payload=[7], delay=50)
    s.send(4, 5, payload=[9], delay=150)
    msgs = s.pending_messages()
    uni = [m for m in msgs if m["sentAt"] == -1]
    assert {(m["from"], m["to"]) for m in uni} == {(1, 2), (4, 5)}
    a12 = next(m for m in uni if m["from"] == 1)
    a45 = next(m for m in uni if m["from"] == 4)
    assert a12["arrivingAt"] > 51 and a45["arrivingAt"] > a12["arrivingAt"]
    assert a12["payload"][0] == 7 and a45["payload"][0] == 9

    # One ms in, the witness's Ping broadcast is in flight: every live
    # dest whose arrival is still in the future shows as a sentAt=0 row —
    # including an external node's (down in-engine, but its deliveries DO
    # reach the bridge, so the peek must show them).
    s.set_external(9, lambda delivered: [])
    s.run_ms(1)
    msgs = s.pending_messages()
    bc = [m for m in msgs if m["sentAt"] == 0]
    assert len(bc) > 20 and all(m["arrivingAt"] >= 1 for m in bc)
    assert any(m["to"] == 9 for m in bc), "external node's in-flight hidden"
    s.clear_external(9)
    assert msgs == sorted(msgs, key=lambda e: (e["arrivingAt"], e["sentAt"],
                                               e["from"], e["to"]))
    # Delivered messages leave the set.
    s.run_ms(1000)
    assert s.pending_messages() == []


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return json.loads(r.read())


def _post(port, path, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_http_round_trip():
    import threading
    httpd = make_server(0)
    port = httpd.server_address[1]
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        names = _get(port, "/w/protocols")
        assert "PingPong" in names
        tpl = _get(port, "/w/protocols/PingPong")
        assert "node_count" in tpl
        _post(port, "/w/network/init/PingPong", {"node_count": 32})
        _post(port, "/w/network/runMs/200")
        assert _get(port, "/w/network/time") == 200
        nodes = _get(port, "/w/network/nodes")
        assert len(nodes) == 32
        n0 = _get(port, "/w/network/nodes/0")
        assert n0["nodeId"] == 0
        _post(port, "/w/network/nodes/4/stop")
        assert _get(port, "/w/network/nodes/4")["down"]
        _post(port, "/w/network/send",
              {"from": 1, "to": 2, "payload": [7]})
        msgs = _get(port, "/w/network/messages")
        assert isinstance(msgs, list)
        assert any(m["from"] == 1 and m["to"] == 2 for m in msgs)
    finally:
        httpd.shutdown()


def _put(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_external_sink_endpoint():
    """ws/ExternalWS.java:21-40: the demo sink accepts an EnvelopeInfo
    PUT and replies with an empty SendMessage list — including when it is
    the external endpoint of a simulation on the SAME server."""
    import threading
    httpd = make_server(0)
    port = httpd.server_address[1]
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    try:
        out = _put(port, "/w/external_sink",
                   [{"from": 0, "to": 3, "arrivingAt": 5, "payload": [1]}])
        assert out == []
        # Self-referential bridge: node 3's deliveries are shipped to this
        # same server's sink (lock-free route — no deadlock).
        _post(port, "/w/network/init/PingPong", {"node_count": 32})
        _post(port, "/w/network/nodes/3/external",
              {"url": f"http://127.0.0.1:{port}/w/external_sink"})
        _post(port, "/w/network/runMs/120")
        assert _get(port, "/w/network/time") == 120
        assert _get(port, "/w/network/nodes/3")["external"]
    finally:
        httpd.shutdown()
