"""Dfinity consensus — block producers, attester committees, random beacon.

Reference: protocols/Dfinity.java (480 lines).  Mechanism (SURVEY.md §2.4):
three roles — block producers propose when the random beacon selects their
round (onRandomBeaconOnce :253-260), attester committees vote on proposals
and assemble a "signed" block at majority (onProposal :295-316, onVote
:276-283, sendBlock :285-293), and a random-beacon committee exchanges
signatures per height, emitting the beacon at majority
(onRandomBeaconExchange :364-372, sendRB :374-380); each block received by
a beacon node starts the next height's beacon exchange, paced by
`roundTime` (onBlock :385-409).  Fork choice: higher block wins; ties keep
the current head (DfinityBlockComparator :106-128 — its producer tie-break
compares a producer with itself, so the comparator returns >= 0 and `best`
keeps o1; reproduced).  The main() demo exercises map partitions
(:452-465) — see `partition_by_x` / `heal_partition`.

TPU-native notes: votes and beacon exchanges accumulate as voter bitsets
([N, A, Vw] / [N, H, Rw]); majority triggers are evaluated once per tick
after the whole inbox lands (within-tick message order coarsening —
statistical equivalence, SURVEY §7.4.3).  Unicast fan-outs queue per
node and drain one batch per tick, COMMITTEE-addressed: proposals and
votes go to the target height's attester committee (the strided residue
class `_my_round` rotates — all attesters when att_rounds == 1, the
reference-default shape), beacon exchanges to every beacon node.  The
outbox therefore scales with committee width, not validator count,
which is what makes 10k-validator configs tractable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core import blockchain as bc
from ..core import builders
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import bitset, prng

U32 = jnp.uint32
ROUND_TIME_MS = 3000

K_PROPOSAL, K_VOTE, K_RB_EXCH, K_RB_RESULT, K_BLOCK = 0, 1, 2, 3, 4


@struct.dataclass
class DfinityState:
    seed: jnp.ndarray
    arena: bc.Arena
    recv_blk: jnp.ndarray      # u32 [N, Aw]
    head: jnp.ndarray          # int32 [N]
    last_beacon: jnp.ndarray   # int32 [N]
    # attesters
    votes: jnp.ndarray         # u32 [N, A, Cw] — committee-position voter
    #                            sets per block (Cw = words(att_width))
    vote_for_h: jnp.ndarray    # int32 [N] (-1 = none)
    buffered: jnp.ndarray      # u32 [N, Aw] — future proposals
    maj_height: jnp.ndarray    # u32 [N, Hw] — committeeMajorityHeight
    # beacon nodes
    rb_height: jnp.ndarray     # int32 [N]
    rb_last_sent: jnp.ndarray  # int32 [N]
    exchanged: jnp.ndarray     # u32 [N, H, Rw] — per-height exchange sets
    # outgoing queues
    q_vote: jnp.ndarray        # u32 [N, Aw] — blocks to vote on (to attesters)
    q_prop: jnp.ndarray        # int32 [N] (-1) — proposal to send
    q_prop_at: jnp.ndarray     # int32 [N]
    q_exch_h: jnp.ndarray      # int32 [N] (-1) — beacon exchange height
    q_exch_at: jnp.ndarray     # int32 [N]
    q_bcast_blk: jnp.ndarray   # u32 [N, Aw] — SendBlock broadcasts
    q_rb_h: jnp.ndarray        # int32 [N] (-1) — beacon result broadcast
    wait_for_h: jnp.ndarray    # int32 [N] (-1) — producer waiting for parent


@register
class Dfinity:
    """Parameters mirror DfinityParameters (Dfinity.java:14-75).  Node
    layout: 0 = observer, then attesters, block producers, beacon nodes."""

    def __init__(self, block_producers_count=10, attesters_count=10,
                 attesters_per_round=10, block_construction_time=1,
                 attestation_construction_time=1,
                 percentage_dead_attester=0, node_builder_name=None,
                 network_latency_name=None, tick_ms=10, block_capacity=512,
                 inbox_cap=None, bcast_slots=160, horizon=64):
        self.n_bp = block_producers_count
        self.bp_per_round = 5
        self.bp_rounds = max(1, block_producers_count // self.bp_per_round)
        self.n_att = attesters_count
        self.att_per_round = attesters_per_round
        self.att_rounds = max(1, attesters_count // attesters_per_round)
        self.n_rb = attesters_per_round
        self.majority = attesters_per_round // 2 + 1
        self.t_block = max(1, block_construction_time // tick_ms)
        self.t_att = max(1, attestation_construction_time // tick_ms)
        self.dead_att_pct = percentage_dead_attester
        self.tick_ms = tick_ms
        self.round_ticks = ROUND_TIME_MS // tick_ms
        self.node_count = 1 + self.n_att + self.n_bp + self.n_rb
        self.capacity = block_capacity
        self.aw = bc.n_words(block_capacity)
        self.hw = bc.n_words(block_capacity)      # heights bounded by blocks
        self.builder = builders.get_by_name(node_builder_name)
        from .ethpow import _TickScaled
        self.latency = _TickScaled(
            latency_mod.get_by_name(network_latency_name), tick_ms)
        # Broadcast budget: every attester re-broadcasts each committee
        # block and every beacon node each beacon result, all alive for
        # `horizon` ticks — size the table for two overlapping waves.
        # Unicast fan-out is COMMITTEE-addressed (proposals/votes go to
        # the height's attester committee, the strided id set _my_round
        # rotates; identical to all-attester addressing when att_rounds
        # == 1, i.e. every reference-default config), so the outbox
        # width scales with committee size, not validator count — what
        # makes the 10k-validator tracked config tractable.
        # Committee width: a residue class holds ceil(n_att/att_rounds)
        # members when the counts do not divide evenly (15 attesters in
        # 10-member rounds -> att_rounds 1, class size 15) — size the
        # fan-out for the largest class, masking overshoot ids at send.
        self.att_width = -(-self.n_att // self.att_rounds)
        # Voter sets are COMMITTEE-POSITION bitsets, not validator-id
        # bitsets: only height h's committee (the rotating residue class)
        # votes on h's blocks, and a member's position within its class
        # is (id - 1) // att_rounds < att_width.  [N, capacity, cw]
        # with cw = words(att_width) replaces the r4 [N, capacity,
        # words(N)] layout that made 10k validators uncompilable
        # (words(10111) = 316 -> 6.5 GB; words(100) = 4 -> 83 MB).
        # Same majority counts: vote assembly in the reference is also
        # per-committee (Dfinity.java:265-351).
        self.cw = bitset.n_words(self.att_width)
        k = max(self.att_width, self.n_rb)        # one fan-out batch per tick
        self.cfg = EngineConfig(
            n=self.node_count, horizon=horizon,
            inbox_cap=inbox_cap or (self.att_width +
                                    self.bp_per_round + 8),
            payload_words=2, out_deg=k, bcast_slots=bcast_slots)

    # role masks ------------------------------------------------------
    def _roles(self):
        ids = np.arange(self.node_count)
        att = (ids >= 1) & (ids <= self.n_att)
        bp = (ids > self.n_att) & (ids <= self.n_att + self.n_bp)
        rb = ids > self.n_att + self.n_bp
        return (jnp.asarray(att), jnp.asarray(bp), jnp.asarray(rb))

    def _my_round(self):
        ids = np.arange(self.node_count)
        att_round = np.where((ids >= 1) & (ids <= self.n_att),
                             (ids - 1) % self.att_rounds, -1)
        bp_round = np.where((ids > self.n_att) &
                            (ids <= self.n_att + self.n_bp),
                            (ids - 1 - self.n_att) % self.bp_rounds, -1)
        return jnp.asarray(att_round), jnp.asarray(bp_round)

    def init(self, seed):
        n, a = self.node_count, self.capacity
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        ids = jnp.arange(n, dtype=jnp.int32)
        att, bp, rb = self._roles()
        if self.dead_att_pct:
            pri = prng.uniform_u32(prng.hash2(seed, 0x44454144), ids)
            k = (self.n_att * self.dead_att_pct) // 100
            att_pri = jnp.where(att, pri, jnp.uint32(0xFFFFFFFF))
            dead = jnp.zeros((n,), bool).at[jnp.argsort(att_pri)[:k]].set(
                True)
            nodes = nodes.replace(down=dead)

        net = init_net(self.cfg, nodes, seed)
        return net, DfinityState(
            seed=seed, arena=bc.make_arena(a),
            recv_blk=bitset.one_bit(jnp.zeros((n,), jnp.int32), self.aw),
            head=jnp.zeros((n,), jnp.int32),
            last_beacon=jnp.zeros((n,), jnp.int32),
            votes=jnp.zeros((n, a, self.cw), U32),
            vote_for_h=jnp.full((n,), -1, jnp.int32),
            buffered=jnp.zeros((n, self.aw), U32),
            maj_height=jnp.zeros((n, self.hw), U32),
            rb_height=jnp.ones((n,), jnp.int32),
            rb_last_sent=jnp.zeros((n,), jnp.int32),
            exchanged=jnp.zeros((n, a, bitset.n_words(self.n_rb)), U32),
            q_vote=jnp.zeros((n, self.aw), U32),
            q_prop=jnp.full((n,), -1, jnp.int32),
            q_prop_at=jnp.zeros((n,), jnp.int32),
            q_exch_h=jnp.full((n,), -1, jnp.int32),
            q_exch_at=jnp.zeros((n,), jnp.int32),
            q_bcast_blk=jnp.zeros((n, self.aw), U32),
            q_rb_h=jnp.full((n,), -1, jnp.int32),
            wait_for_h=jnp.full((n,), -1, jnp.int32),
        )

    # ------------------------------------------------------------ helpers

    def _best(self, p, cur, alt):
        """Comparator :106-128: valid, then height; ties keep cur."""
        ok = (alt >= 0) & p.arena.valid[jnp.maximum(alt, 0)]
        hc = p.arena.height[jnp.maximum(cur, 0)]
        ha = p.arena.height[jnp.maximum(alt, 0)]
        return jnp.where(ok & (ha > hc), alt, cur)

    def _on_beacon(self, p, h, okmask, t):
        """onRandomBeacon once-per-height dispatch (:203-211)."""
        n = self.node_count
        ids = jnp.arange(n, dtype=jnp.int32)
        att, bp, rb = self._roles()
        att_round, bp_round = self._my_round()
        once = okmask & (p.last_beacon < h)
        p = p.replace(last_beacon=jnp.where(once, h, p.last_beacon))
        rd = h                                      # rd value = height (:375)

        # producer (:253-260): selected and parent in hand -> propose
        head_h = p.arena.height[jnp.maximum(p.head, 0)]
        sel_bp = once & bp & (rd % self.bp_rounds == bp_round)
        now = sel_bp & (head_h == h - 1)
        p = p.replace(
            q_prop=jnp.where(now, -2, p.q_prop),
            q_prop_at=jnp.where(now, t + self.t_block, p.q_prop_at),
            wait_for_h=jnp.where(sel_bp & ~now, h - 1, p.wait_for_h))

        # attester (:336-355): start voting, vote buffered proposals of h
        hbit_has = bitset.get_bit(p.maj_height,
                                  jnp.clip(h, 0, self.capacity - 1))
        sel_att = once & att & (rd % self.att_rounds == att_round) & \
            ~hbit_has
        p = p.replace(vote_for_h=jnp.where(sel_att, h, p.vote_for_h))
        # buffered proposals at height h -> queue votes
        buf_bits = p.buffered
        h_match = p.arena.height[None, :] == h[:, None]
        bmask = _mask_blocks(h_match, self.capacity)
        q_vote = jnp.where(sel_att[:, None], p.q_vote | (buf_bits & bmask),
                           p.q_vote)
        p = p.replace(q_vote=q_vote,
                      buffered=jnp.where(sel_att[:, None], U32(0),
                                         p.buffered))

        # beacon node fast-forward (:414-420)
        ff = once & rb & (h > p.rb_height)
        p = p.replace(rb_height=jnp.where(ff, h, p.rb_height),
                      rb_last_sent=jnp.where(ff, p.rb_height,
                                             p.rb_last_sent))
        return p

    # ---------------------------------------------------------------- step

    def step(self, p: DfinityState, nodes, inbox, t, key):
        n = self.node_count
        ids = jnp.arange(n, dtype=jnp.int32)
        alive = ~nodes.down
        att, bp, rb = self._roles()
        S = inbox.src.shape[1]

        # init kick (:447-449): beacon nodes broadcast height 1 at t == 1
        kick = alive & rb & (t == 1) & (p.rb_last_sent == 0)
        p = p.replace(q_rb_h=jnp.where(kick, 1, p.q_rb_h),
                      rb_last_sent=jnp.where(kick, 1, p.rb_last_sent))

        # ---- receive, fully vectorized over the S inbox slots: every
        # update is either an OR-reduce across slots or a scatter-add of
        # bits that are distinct within the tick (one vote per (sender,
        # block), one exchange per (sender, height)). ----
        ok = inbox.valid & alive[:, None]                     # [N, S]
        kind = inbox.data[:, :, 0]
        val = jnp.clip(inbox.data[:, :, 1], 0, self.capacity - 1)
        src = jnp.clip(inbox.src, 0, n - 1)

        # -- BLOCK (onBlock for every role) --
        from ._levels import get_bit_rows
        is_blk = ok & (kind == K_BLOCK)
        new_b = is_blk & ~get_bit_rows(p.recv_blk, val)
        blk_or = jax.lax.reduce(
            jnp.where(new_b[..., None], bitset.one_bit(val, self.aw),
                      U32(0)), U32(0), jax.lax.bitwise_or, (1,))
        recv_blk = p.recv_blk | blk_or
        bh_all = p.arena.height[val]                          # [N, S]
        # head: highest received block this tick vs current (ties keep cur)
        cand_h = jnp.max(jnp.where(new_b, bh_all, -1), axis=1)
        cand_slot = jnp.argmax(jnp.where(new_b, bh_all, -1), axis=1)
        cand = jnp.take_along_axis(val, cand_slot[:, None], axis=1)[:, 0]
        head_h0 = p.arena.height[jnp.maximum(p.head, 0)]
        take = (cand_h > head_h0) & jnp.any(new_b, axis=1)
        head = jnp.where(take, cand, p.head)
        head_h = jnp.where(take, cand_h, head_h0)
        # attester bookkeeping (:319-333)
        hbits = jax.lax.reduce(
            jnp.where((new_b & att[:, None])[..., None],
                      bitset.one_bit(jnp.clip(bh_all, 0,
                                              self.capacity - 1), self.hw),
                      U32(0)), U32(0), jax.lax.bitwise_or, (1,))
        vote_cancel = jnp.any(new_b & (bh_all == p.vote_for_h[:, None]),
                              axis=1) & att
        # producer waiting for its parent (:243-249)
        fire = bp & jnp.any(new_b, axis=1) & (head_h == p.wait_for_h)
        # beacon: catch rb_height up to the new head (:385-409); the
        # reference advances once per received block — catching up to
        # head+1 in one tick is the multi-block-per-tick equivalent.
        start = rb & jnp.any(new_b, axis=1) & (head_h >= p.rb_height)
        rb_height = jnp.where(start, head_h + 1, p.rb_height)
        rb_idx = ids - (1 + self.n_att + self.n_bp)
        ownbit = bitset.one_bit(jnp.maximum(rb_idx, 0),
                                bitset.n_words(self.n_rb))
        hclip = jnp.clip(rb_height, 0, self.capacity - 1)
        olde = p.exchanged[ids, hclip]
        exchanged = p.exchanged.at[jnp.where(start, ids, n), hclip].set(
            olde | ownbit, mode="drop")
        par = p.arena.parent[jnp.maximum(head, 0)]
        wt = p.arena.time[jnp.maximum(par, 0)] + 2 * self.round_ticks
        wt = jnp.where(wt <= t, t + self.t_att, wt)
        p = p.replace(
            recv_blk=recv_blk, head=head,
            maj_height=p.maj_height | hbits,
            vote_for_h=jnp.where(vote_cancel, -1, p.vote_for_h),
            q_prop=jnp.where(fire, -2, p.q_prop),
            q_prop_at=jnp.where(fire, t + self.t_block, p.q_prop_at),
            wait_for_h=jnp.where(fire, -1, p.wait_for_h),
            rb_height=rb_height, exchanged=exchanged,
            q_exch_h=jnp.where(start, rb_height, p.q_exch_h),
            q_exch_at=jnp.where(start, wt, p.q_exch_at))

        # -- PROPOSAL (:295-316) --
        is_prop = ok & att[:, None] & (kind == K_PROPOSAL)
        live_vote = is_prop & (p.vote_for_h[:, None] == bh_all)
        # Own committee position (valid whenever live_vote holds — the
        # node was selected for this height's committee by _on_beacon).
        own_pos = jnp.clip((ids - 1) // self.att_rounds, 0,
                           self.att_width - 1)
        ownvote = bitset.one_bit(own_pos, self.cw)            # [N, Cw]
        vbase = (ids[:, None] * self.capacity + val) * self.cw
        widx = vbase[..., None] + jnp.arange(self.cw)[None, None, :]
        widx = jnp.where(live_vote[..., None], widx,
                         n * self.capacity * self.cw)
        # own-vote bits are distinct per (node, block): accumulate via add
        vote_add = jnp.zeros_like(p.votes).reshape(-1).at[
            widx.reshape(-1)].add(
            jnp.broadcast_to(ownvote[:, None, :], widx.shape).reshape(-1),
            mode="drop").reshape(p.votes.shape)
        q_vote = p.q_vote | jax.lax.reduce(
            jnp.where(live_vote[..., None], bitset.one_bit(val, self.aw),
                      U32(0)), U32(0), jax.lax.bitwise_or, (1,))
        buffered = p.buffered | jax.lax.reduce(
            jnp.where((is_prop & ~live_vote &
                       (bh_all > head_h[:, None]))[..., None],
                      bitset.one_bit(val, self.aw), U32(0)),
            U32(0), jax.lax.bitwise_or, (1,))
        p = p.replace(q_vote=q_vote, buffered=buffered)

        # -- VOTE (:276-283): scatter sender committee-position bits
        # (distinct per tick WITHIN a committee — the validity mask
        # restricts to the voted block's own rotating residue class, so
        # two senders can never share a position bit for one block) --
        is_vote = (ok & att[:, None] & (kind == K_VOTE) &
                   (src >= 1) & (src <= self.n_att) &
                   ((src - 1) % self.att_rounds ==
                    bh_all % self.att_rounds))
        src_pos = jnp.clip((src - 1) // self.att_rounds, 0,
                           self.att_width - 1)
        sbit_v = bitset.one_bit(src_pos, self.cw)             # [N, S, Cw]
        vidx = ((ids[:, None] * self.capacity + val) * self.cw)[
            ..., None] + jnp.arange(self.cw)[None, None, :]
        vidx = jnp.where(is_vote[..., None], vidx,
                         n * self.capacity * self.cw)
        vote_add = vote_add.reshape(-1).at[vidx.reshape(-1)].add(
            sbit_v.reshape(-1), mode="drop").reshape(p.votes.shape)

        # -- RB exchange (:364-372) --
        is_ex = ok & rb[:, None] & (kind == K_RB_EXCH)
        fresh = is_ex & (val >= p.rb_height[:, None]) & \
            (val > p.rb_last_sent[:, None])
        rb_src = jnp.clip(src - (1 + self.n_att + self.n_bp), 0,
                          self.n_rb - 1)
        rw = bitset.n_words(self.n_rb)
        ebit = bitset.one_bit(rb_src, rw)                     # [N, S, Rw]
        eidx = ((ids[:, None] * self.capacity + val) * rw)[..., None] + \
            jnp.arange(rw)[None, None, :]
        eidx = jnp.where(fresh[..., None], eidx, n * self.capacity * rw)
        exch_add = jnp.zeros_like(p.exchanged).reshape(-1).at[
            eidx.reshape(-1)].add(ebit.reshape(-1),
                                  mode="drop").reshape(p.exchanged.shape)
        p = p.replace(exchanged=jax.tree.map(jnp.bitwise_or, p.exchanged,
                                             exch_add))

        # -- beacon result: once-per-height dispatch (highest wins) --
        beacon_h = jnp.max(jnp.where(ok & (kind == K_RB_RESULT), val, -1),
                           axis=1)
        p = self._on_beacon(p, beacon_h, beacon_h >= 0, t)

        # merge tick votes + majority checks (:276-316)
        votes = jax.tree.map(lambda a, b: a | b, p.votes, vote_add)
        p = p.replace(votes=votes)
        vh = p.vote_for_h
        # blocks at our vote height with majority support
        counts = bitset.popcount(votes)             # [N, A]
        h_eq = p.arena.height[None, :] == vh[:, None]
        maj = (counts >= self.majority) & h_eq & \
            (vh >= 0)[:, None] & att[:, None] & alive[:, None]
        any_maj = jnp.any(maj, axis=1)
        maj_blk = jnp.argmax(maj, axis=1).astype(jnp.int32)
        # sendBlock (:285-293): broadcast, mark heights, stop voting
        p = p.replace(
            q_bcast_blk=p.q_bcast_blk | jnp.where(
                any_maj[:, None], bitset.one_bit(maj_blk, self.aw), U32(0)),
            maj_height=p.maj_height | jnp.where(
                any_maj[:, None],
                bitset.one_bit(jnp.clip(p.arena.height[maj_blk], 0,
                                        self.capacity - 1), self.hw),
                U32(0)),
            vote_for_h=jnp.where(any_maj, -1, p.vote_for_h))

        # beacon majority (:364-380)
        hclip = jnp.clip(p.rb_height, 0, self.capacity - 1)
        exch_cnt = bitset.popcount(p.exchanged[ids, hclip])
        rb_maj = alive & rb & (exch_cnt >= self.majority) & \
            (p.rb_height > p.rb_last_sent)
        p = p.replace(
            q_rb_h=jnp.where(rb_maj, p.rb_height, p.q_rb_h),
            rb_last_sent=jnp.where(rb_maj, p.rb_height, p.rb_last_sent))

        # ---- producer proposal build (createProposal :222-241) ----
        build = (p.q_prop == -2) & (t >= p.q_prop_at) & alive
        heads = p.head
        arena, blk = bc.alloc(p.arena, build, heads, ids, t)
        p = p.replace(arena=arena,
                      q_prop=jnp.where(build, jnp.maximum(blk, 0), p.q_prop))
        recv, _ = bc.receive_block(p.recv_blk, ids, blk, build)
        p = p.replace(recv_blk=recv)

        # ---- outbox ----
        K = self.cfg.out_deg
        A = self.att_width
        dest = jnp.full((n, K), -1, jnp.int32)
        payload = jnp.zeros((n, K, 2), jnp.int32)
        rb_ids = 1 + self.n_att + self.n_bp + \
            jnp.arange(self.n_rb, dtype=jnp.int32)

        def committee_ids(hh):
            # Height hh's attester committee: the strided residue class
            # _my_round selects for that round ((id-1) % att_rounds ==
            # hh % att_rounds), width = the LARGEST class (att_width);
            # ids past n_att (short classes / non-divisible counts) are
            # masked to -1.  att_rounds == 1 yields every attester — the
            # reference-default configuration.
            ids_c = (1 + (hh[:, None] % self.att_rounds) +
                     jnp.arange(A, dtype=jnp.int32)[None, :] *
                     self.att_rounds)
            return jnp.where(ids_c <= self.n_att, ids_c, -1)

        # proposal batch to the proposal height's committee
        send_prop = (p.q_prop >= 0) & alive
        prop_h = p.arena.height[jnp.maximum(p.q_prop, 0)]
        dest = dest.at[:, :A].set(
            jnp.where(send_prop[:, None], committee_ids(prop_h), -1))
        payload = payload.at[:, :A, 0].set(
            jnp.where(send_prop[:, None], K_PROPOSAL, 0))
        payload = payload.at[:, :A, 1].set(p.q_prop[:, None])
        p = p.replace(q_prop=jnp.where(send_prop, -1, p.q_prop))

        # else: one vote batch per tick to the voted block's committee
        has_v = jnp.any(p.q_vote != 0, axis=1) & ~send_prop & alive
        fw = jnp.argmax(p.q_vote != 0, axis=1).astype(jnp.int32)
        word = jnp.take_along_axis(p.q_vote, fw[:, None], axis=1)[:, 0]
        low = word & (~word + U32(1))
        bpos = 31 - jax.lax.clz(jnp.maximum(low, U32(1)).astype(jnp.int32))
        vblk = jnp.clip(fw * 32 + bpos, 0, self.capacity - 1)
        vote_h = p.arena.height[vblk]
        dest = dest.at[:, :A].set(
            jnp.where(has_v[:, None], committee_ids(vote_h),
                      dest[:, :A]))
        payload = payload.at[:, :A, 0].set(
            jnp.where(has_v[:, None], K_VOTE,
                      payload[:, :A, 0]))
        payload = payload.at[:, :A, 1].set(
            jnp.where(has_v[:, None], vblk[:, None],
                      payload[:, :A, 1]))
        p = p.replace(q_vote=jnp.where(
            has_v[:, None], p.q_vote & ~bitset.one_bit(vblk, self.aw),
            p.q_vote))

        # beacon exchange batch to all beacon nodes
        send_ex = (p.q_exch_h >= 0) & (t >= p.q_exch_at) & alive
        dest = dest.at[:, :self.n_rb].set(
            jnp.where(send_ex[:, None], rb_ids[None, :],
                      dest[:, :self.n_rb]))
        payload = payload.at[:, :self.n_rb, 0].set(
            jnp.where(send_ex[:, None], K_RB_EXCH,
                      payload[:, :self.n_rb, 0]))
        payload = payload.at[:, :self.n_rb, 1].set(
            jnp.where(send_ex[:, None], p.q_exch_h[:, None],
                      payload[:, :self.n_rb, 1]))
        p = p.replace(q_exch_h=jnp.where(send_ex, -1, p.q_exch_h))

        # broadcasts: beacon result first, else one queued block
        has_blk = jnp.any(p.q_bcast_blk != 0, axis=1)
        fw2 = jnp.argmax(p.q_bcast_blk != 0, axis=1).astype(jnp.int32)
        word2 = jnp.take_along_axis(p.q_bcast_blk, fw2[:, None],
                                    axis=1)[:, 0]
        low2 = word2 & (~word2 + U32(1))
        bpos2 = 31 - jax.lax.clz(jnp.maximum(low2, U32(1)).astype(jnp.int32))
        bblk = jnp.clip(fw2 * 32 + bpos2, 0, self.capacity - 1)
        do_rb = (p.q_rb_h >= 0) & alive
        do_blk = has_blk & ~do_rb & alive
        bcast = do_rb | do_blk
        bpayload = jnp.stack(
            [jnp.where(do_rb, K_RB_RESULT, K_BLOCK),
             jnp.where(do_rb, p.q_rb_h, bblk)], axis=1).astype(jnp.int32)
        p = p.replace(
            q_rb_h=jnp.where(do_rb, -1, p.q_rb_h),
            q_bcast_blk=jnp.where(
                do_blk[:, None],
                p.q_bcast_blk & ~bitset.one_bit(bblk, self.aw),
                p.q_bcast_blk))

        out = empty_outbox(self.cfg).replace(
            dest=dest, payload=payload,
            bcast=bcast, bcast_payload=bpayload,
            bcast_size=jnp.ones((n,), jnp.int32))
        return p, nodes, out

    def next_action_time(self, p: DfinityState, nodes, t):
        """Quiet-window oracle half (core/protocol.py): Dfinity's step
        acts only on deliveries (the engine oracle's territory), the
        t == 1 beacon kick, proposal builds maturing at ``q_prop_at``,
        beacon exchanges maturing at ``q_exch_at``, and queued sends
        (proposals, votes, block/beacon broadcasts) which drain one
        batch per tick.  Majority checks fire on the tick the deciding
        delivery lands, so they never pin a quiet ms.  Between round
        waves (roundTime = 3000 ms paced by tick_ms) the chain is
        genuinely idle — the quiet-heavy regime fast-forward targets."""
        from ..core.protocol import masked_min
        alive = ~nodes.down
        _, _, rb = self._roles()
        kick = masked_min(1, alive & rb & (p.rb_last_sent == 0) & (t <= 1))
        build = masked_min(jnp.maximum(p.q_prop_at, t),
                           alive & (p.q_prop == -2))
        exch = masked_min(jnp.maximum(p.q_exch_at, t),
                          alive & (p.q_exch_h >= 0))
        imm = alive & ((p.q_prop >= 0) |
                       jnp.any(p.q_vote != 0, axis=1) |
                       (p.q_rb_h >= 0) |
                       jnp.any(p.q_bcast_blk != 0, axis=1))
        queued = masked_min(t, imm)
        return jnp.minimum(jnp.minimum(kick, build),
                           jnp.minimum(exch, queued))


def _mask_blocks(h_match, capacity):
    """Pack an [N, A] bool into [N, Aw] words."""
    n = h_match.shape[0]
    idx = jnp.arange(capacity, dtype=jnp.int32)
    word = idx // 32
    onebit = (U32(1) << (idx % 32).astype(U32))
    return jnp.zeros((n, bc.n_words(capacity)), U32).at[:, word].add(
        jnp.where(h_match, onebit[None, :], U32(0)))


def partition_by_x(net, ratio: float):
    """Network.partition (:693-707): nodes left of ratio*MAX_X form
    partition 1."""
    from ..core.state import MAX_X
    cut = int(ratio * MAX_X)
    part = jnp.where(net.nodes.x <= cut, 1, 0).astype(jnp.int32)
    return net.replace(nodes=net.nodes.replace(partition=part))


def heal_partition(net, pstate):
    """BlockChainNetwork.endPartition (:47-55): clear partitions and have
    every node re-broadcast its head."""
    net = net.replace(nodes=net.nodes.replace(
        partition=jnp.zeros_like(net.nodes.partition)))
    aw = pstate.q_bcast_blk.shape[1]
    pstate = pstate.replace(
        q_bcast_blk=pstate.q_bcast_blk | bitset.one_bit(pstate.head, aw))
    return net, pstate
