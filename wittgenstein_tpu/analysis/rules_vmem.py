"""Rule ``vmem_budget`` — every Pallas kernel's block-size cost model
must fit the scoped-VMEM budget at its real launch configurations.

Background: the first on-chip compile of the merge kernel at blk=256
requested a 56.26 MB scoped-vmem stack against the 16 MB limit
(reports/pallas_validate_r5.log).  The fix was a per-row cost model fed
to `_pick_block` — but the models were inline arithmetic at each launch
site with nothing holding them together (ADVICE.md r5 items 2-3).  This
rule pins them down statically, off-chip:

  * every kernel's named cost model (merge_row_bytes,
    gsf_merge_row_bytes, score_row_bytes — the launchers call the SAME
    functions) is evaluated at the representative configs below; the
    block `_pick_block` picks must fit the budget, and a config whose
    single row exceeds it must RAISE (no more silent blk=1);
  * an AST check over ops/pallas_*.py that every `_pick_block` call
    site passes a row-bytes estimate — a new kernel launched with the
    bare `_pick_block(m)` form reintroduces exactly the unbudgeted
    compile the round-5 OOM came from.

Representative configs cover the shipped tiers: the 2048-node headline
(w=64), the 32k exact tier (w=1024), and the small CPU-test shapes.
"""

from __future__ import annotations

import ast
import pathlib

from .framework import Finding, Rule, register_rule

OPS_DIR = pathlib.Path(__file__).resolve().parent.parent / "ops"


def _kernel_models():
    """(kernel name, cost_fn, [(m, kwargs, label), ...]) — shapes
    mirror the launch sites: merge S = inbox_cap (delivery slots),
    score W = ceil(n/32) sig words."""
    from ..ops.pallas_gsf_merge import gsf_merge_row_bytes
    from ..ops.pallas_merge import merge_row_bytes
    from ..ops.pallas_route import route_row_bytes
    from ..ops.pallas_score import score_row_bytes

    return [
        ("pallas_merge.merge_queue_pallas", merge_row_bytes, [
            (2048, dict(q_cap=16, s_cap=12, w=64), "headline-2048n"),
            (32768, dict(q_cap=16, s_cap=12, w=1024), "tier2-32k"),
            (64, dict(q_cap=16, s_cap=12, w=2), "cpu-test"),
        ]),
        ("pallas_gsf_merge.gsf_merge_pallas", gsf_merge_row_bytes, [
            (1024, dict(q_cap=16, s_cap=16, w=32), "gsf-1024n"),
            (32768, dict(q_cap=16, s_cap=16, w=1024), "gsf-32k"),
        ]),
        ("pallas_score.score_queue_pallas", score_row_bytes, [
            (2048, dict(q_cap=16, w=64), "headline-2048n"),
            (32768, dict(q_cap=16, w=1024), "tier2-32k"),
        ]),
        # routing megakernel: m is the per-sub-plane destination count
        # (the grid's row axis); rows mirror the bench/test ring shapes
        ("pallas_route.bin_into_ring_planes", route_row_bytes, [
            (2048, dict(horizon=256, inbox_cap=12, payload_words=2),
             "headline-2048n"),
            (65536, dict(horizon=256, inbox_cap=12, payload_words=2),
             "tier2-cardinal-65k"),
            (64, dict(horizon=64, inbox_cap=12, payload_words=2),
             "cpu-test"),
        ]),
    ]


def _unbudgeted_pick_block_calls() -> list[str]:
    """`_pick_block(m)` call sites missing the row-bytes argument — or
    (the PR-9 extension) passing a bare numeric literal instead of a
    named cost model — as "file:line[ reason]" strings.  A literal is
    exactly the unbudgeted-launch failure mode with a number pasted
    over it: nothing ties it to the kernel's real temporaries, so a
    kernel change silently invalidates it; call sites must route
    through a ``*_row_bytes`` model (directly or via a local
    variable)."""
    bad = []
    for path in sorted(OPS_DIR.glob("pallas_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if name != "_pick_block":
                continue
            row_arg = node.args[1] if len(node.args) >= 2 else next(
                (k.value for k in node.keywords
                 if k.arg == "row_bytes"), None)
            if row_arg is None:
                bad.append(f"{path.name}:{node.lineno}")
            elif isinstance(row_arg, ast.Constant):
                bad.append(f"{path.name}:{node.lineno} "
                           "(literal row-bytes)")
    return bad


def check_model(kernel: str, cost_fn, configs,
                rule_name="vmem_budget") -> list[Finding]:
    """Evaluate one kernel's cost model at `configs` ((m, kwargs,
    label) triples) against the scoped-VMEM budget.  Exposed so tests
    can feed a deliberately over-budget fake model and watch it get
    rejected."""
    from ..ops.pallas_merge import _VMEM_BUDGET, _pick_block

    findings = []
    for m, kw, label in configs:
        row = cost_fn(**kw)
        try:
            blk = _pick_block(m, row)
        except ValueError as e:
            findings.append(Finding(
                rule=rule_name, target=kernel, severity="error",
                message=f"{label}: cost model rejects the config even "
                        f"at blk=1 ({e})"))
            continue
        if blk * row > _VMEM_BUDGET:
            findings.append(Finding(
                rule=rule_name, target=kernel, severity="error",
                message=f"{label}: blk={blk} x {row} B/row = "
                        f"{blk * row / 1e6:.1f} MB exceeds the "
                        f"{_VMEM_BUDGET / 1e6:.1f} MB budget"))
        else:
            findings.append(Finding(
                rule=rule_name, target=kernel, severity="info",
                message=f"{label}: blk={blk}, {blk * row / 1e6:.2f} MB "
                        f"of {_VMEM_BUDGET / 1e6:.1f} MB"))
    return findings


@register_rule
class VmemBudgetRule(Rule):
    name = "vmem_budget"
    scope = "global"

    def run(self, target, budget):
        findings = []
        for kernel, cost_fn, configs in _kernel_models():
            findings += check_model(kernel, cost_fn, configs, self.name)
        for site in _unbudgeted_pick_block_calls():
            findings.append(Finding(
                rule=self.name, target=site, severity="error",
                message="_pick_block called without a row-bytes cost "
                        "estimate — unbudgeted Pallas launch (the r5 "
                        "56 MB scoped-VMEM compile failure mode)"))
        return findings

    def describe(self):
        return f"{len(_kernel_models())} Pallas kernel cost models"
