"""ENRGossiping tests — cap distribution, rewiring toward done, churn,
determinism (ENRGossipingTest.java analogue)."""

import pytest

import jax.numpy as jnp
import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.enr import ENRGossiping


def make(seed=0, **kw):
    args = dict(nodes=40, total_peers=5, max_peers=12,
                number_of_different_capabilities=5, cap_per_node=2,
                cap_gossip_time=500, time_to_change=5_000,
                time_to_leave=20_000, changing_nodes=0.4,
                network_latency_name="NetworkLatencyByDistanceWJitter")
    args.update(kw)
    return ENRGossiping(**args)


def test_init_invariants():
    p = make()
    net, ps = p.init(0)
    caps = np.asarray(ps.caps)
    # Every node has exactly cap_per_node capabilities.
    assert np.all(caps.sum(1) == 2)
    # Capabilities are distributed (no orphan capability among the initial
    # nodes — the reference throws if any cap has a single holder).
    assert np.all(caps[:40].sum(0) >= 2)
    # Joiner slots start down with scheduled join times.
    down = np.asarray(net.nodes.down)
    assert down[40:].all() and not down[:40].any()
    assert np.all(np.asarray(ps.join_at)[40:] > 0)
    # Peer graph symmetric among initial nodes.
    peers = np.asarray(ps.peers)
    for i in range(40):
        for q in peers[i][peers[i] >= 0]:
            assert i in peers[q], (i, q)


def test_run_rewires_and_finishes():
    p = make()
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    for _ in range(30):
        net, ps = r.run_ms(net, ps, 500)
        done = np.asarray(net.nodes.done_at)
        live = ~np.asarray(net.nodes.down)
        if (done[live] > 0).all():
            break
    frac = (done[live] > 0).mean()
    # Rewiring should connect a large majority of live nodes to their
    # capability groups within 15 s.
    assert frac > 0.8, f"only {frac:.2f} done"
    assert int(net.dropped) == 0


@pytest.mark.slow
def test_churn_membership():
    p = make(time_to_leave=4_000)   # joins every 500 ms, quick exits
    r = Runner(p, donate=False)
    net, ps = p.init(1)
    seen_alive = []
    for _ in range(10):
        net, ps = r.run_ms(net, ps, 500)
        seen_alive.append(int((~np.asarray(net.nodes.down)).sum()))
    # Membership changed over time (joins happened; exits eventually).
    assert len(set(seen_alive)) > 1, seen_alive


@pytest.mark.slow
def test_determinism():
    p = make()
    r = Runner(p, donate=False)
    net1, ps1 = p.init(3)
    net2, ps2 = p.init(3)
    for _ in range(4):
        net1, ps1 = r.run_ms(net1, ps1, 500)
        net2, ps2 = r.run_ms(net2, ps2, 500)
    assert np.array_equal(np.asarray(ps1.peers), np.asarray(ps2.peers))
    assert np.array_equal(np.asarray(net1.nodes.done_at),
                          np.asarray(net2.nodes.done_at))
