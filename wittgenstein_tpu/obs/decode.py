"""Host side of the event flight recorder: TraceFrame + formatting.

A `TraceFrame` wraps the fetched event ring(s) of one or more
`TraceCarry` pytrees (obs/trace.py) as a structured ``[E, 6]`` int64
array in recorded order, plus the truncation accounting (`dropped`,
`high_water`) that makes a silently-clipped trace impossible: every
consumer — `Runner.run_report`, the bench ``trace`` JSON block, the
divergence CLI — surfaces the counter.

Per-seed / per-shard carries (leading batch axes on the buffer) decode
into one frame with a parallel ``buffer`` column; multi-buffer frames
are stable-sorted by event time so lockstep streams interleave on one
timeline while each buffer's within-ms order is preserved.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .trace import EVENTS, FIELDS, KIND, TraceSpec

_COL = {name: i for i, name in enumerate(FIELDS)}


@dataclasses.dataclass
class TraceFrame:
    """Host-side view of one capture's event stream."""

    spec: TraceSpec
    events: np.ndarray          # int64 [E, 6] — FIELDS order
    buffer: np.ndarray          # int64 [E] — originating seed/shard ring
    dropped: int                # events lost to full rings (sum)
    high_water: int             # max rows any single ring filled

    @classmethod
    def from_carry(cls, spec: TraceSpec, tc) -> "TraceFrame":
        """Fetch a device `TraceCarry`.  A batched carry (leading axes
        on every leaf — per-seed or per-shard rings) is merged onto one
        timeline: events keep their per-buffer order and are stable-
        sorted by time across buffers."""
        buf = np.asarray(tc.buf, dtype=np.int64)
        cursor = np.asarray(tc.cursor, dtype=np.int64).reshape(-1)
        dropped = int(np.asarray(tc.dropped, dtype=np.int64).sum())
        bufs = buf.reshape((-1,) + buf.shape[-2:])
        evs, ids = [], []
        for i, (b, c) in enumerate(zip(bufs, cursor)):
            evs.append(b[:c])
            ids.append(np.full(int(c), i, np.int64))
        events = (np.concatenate(evs) if evs
                  else np.zeros((0, len(FIELDS)), np.int64))
        buffer = (np.concatenate(ids) if ids else np.zeros((0,), np.int64))
        if len(bufs) > 1 and events.shape[0]:
            order = np.argsort(events[:, _COL["time_ms"]], kind="stable")
            events, buffer = events[order], buffer[order]
        return cls(spec=spec, events=events, buffer=buffer,
                   dropped=dropped,
                   high_water=int(cursor.max(initial=0)))

    @classmethod
    def from_carries(cls, spec: TraceSpec, carries) -> "TraceFrame":
        """Stitch consecutive chunks' carries into one frame (chunk
        order = time order for a single run; truncation accounting is
        summed/maxed across chunks)."""
        frames = [cls.from_carry(spec, tc) for tc in carries]
        return cls(
            spec=spec,
            events=np.concatenate([f.events for f in frames])
            if frames else np.zeros((0, len(FIELDS)), np.int64),
            buffer=np.concatenate([f.buffer for f in frames])
            if frames else np.zeros((0,), np.int64),
            dropped=sum(f.dropped for f in frames),
            high_water=max((f.high_water for f in frames), default=0))

    # ------------------------------------------------------------ views

    @property
    def n_events(self) -> int:
        return self.events.shape[0]

    def column(self, name: str) -> np.ndarray:
        return self.events[:, _COL[name]]

    def counts(self) -> dict:
        """Events per kind name (only kinds that occur)."""
        kinds, n = np.unique(self.column("kind"), return_counts=True)
        return {EVENTS[int(k)]: int(c) for k, c in zip(kinds, n)}

    def _select(self, mask) -> "TraceFrame":
        return dataclasses.replace(self, events=self.events[mask],
                                   buffer=self.buffer[mask])

    def window(self, t_lo: int, t_hi: int) -> "TraceFrame":
        """Events with ``t_lo <= time_ms < t_hi``."""
        t = self.column("time_ms")
        return self._select((t >= t_lo) & (t < t_hi))

    def filter(self, kinds=None, node=None) -> "TraceFrame":
        """Restrict to kind names and/or events touching `node` (src or
        dst)."""
        mask = np.ones(self.n_events, bool)
        if kinds is not None:
            codes = {KIND[k] for k in kinds}
            mask &= np.isin(self.column("kind"), sorted(codes))
        if node is not None:
            mask &= ((self.column("src") == node) |
                     (self.column("dst") == node))
        return self._select(mask)

    def rows(self) -> list:
        """Structured dicts, one per event (kind decoded to its name)."""
        out = []
        for ev in self.events:
            d = {name: int(ev[i]) for i, name in enumerate(FIELDS)}
            d["kind"] = EVENTS[d["kind"]]
            out.append(d)
        return out

    def format(self, limit: int | None = 50) -> str:
        """Human-readable event listing (``limit=None`` for all)."""
        lines = []
        evs = self.events if limit is None else self.events[:limit]
        for ev in evs:
            t, kind, src, dst, nbytes, aux = (int(x) for x in ev)
            dst_s = "all" if dst == -1 else f"{dst}"
            lines.append(f"[{t:>7} ms] {EVENTS[kind]:<12} "
                         f"src={src:>5} dst={dst_s:>5} "
                         f"{nbytes:>6} B aux={aux}")
        extra = self.n_events - len(evs)
        if extra > 0:
            lines.append(f"... {extra} more events")
        if self.dropped:
            lines.append(f"!! ring truncated: {self.dropped} events "
                         f"dropped (capacity {self.spec.capacity}) — "
                         "raise TraceSpec.capacity")
        return "\n".join(lines)


def trace_block(frame: TraceFrame, extra: dict | None = None) -> dict:
    """The ``trace`` block for `BENCH_*.json` (schema: BENCH_NOTES.md
    r9): truncation accounting always — a clipped trace announces
    itself — plus per-kind counts; never the raw event rows (one JSON
    line must stay one line)."""
    out = {
        "capacity": frame.spec.capacity,
        "events": frame.n_events,
        "high_water": frame.high_water,
        "dropped": frame.dropped,
        "truncated": frame.dropped > 0,
        "counts": frame.counts(),
    }
    if frame.spec.node_filter is not None:
        out["node_filter"] = list(frame.spec.node_filter)
    if extra:
        out.update(extra)
    return out
