"""Program observatory (PR 20) — catalog durability + serve wiring.

Acceptance pins:
  * catalog OFF costs nothing: a `catalog=None` scheduler never
    imports `obs.programs` (subprocess sys.modules check) and never
    touches a catalog write path (rigged to explode in-process —
    the spans-OFF convention);
  * one cold build round-trips ONE durable, fully-populated catalog
    row (compile key, backend, build/lower/compile walls,
    memory_analysis bytes, cost_analysis flops, build-time cost-model
    predictions), idempotent across launches;
  * catalog-ON artifacts are bit-identical to catalog-OFF outside the
    honest wall clock (the capture serves launches FROM the compiled
    executable — it IS the program);
  * a SIGKILL mid-append leaves at most one torn row, and reload
    parses every complete row (the jsonl torn-tail contract);
  * the registry hit/miss gauges and the cost-model drift gauges land
    in the metrics exposition; `/w/batch/programs` serves the report;
  * tools/programs.py renders a catalog file or run directory and
    exits 2 on no rows.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

import wittgenstein_tpu.models  # noqa: F401 — fills the registry
from wittgenstein_tpu.obs.metrics import parse_exposition
from wittgenstein_tpu.obs.programs import (CatalogProgram,
                                           ProgramCatalog,
                                           read_catalog,
                                           summarize_programs)
from wittgenstein_tpu.serve import ScenarioSpec, Scheduler, Service
from wittgenstein_tpu.serve.instrument import Instrumentation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(**kw):
    base = dict(protocol="PingPong", params={"node_count": 64},
                seeds=(0,), sim_ms=80, chunk_ms=40, obs=("metrics",))
    base.update(kw)
    return ScenarioSpec(**base)


def _run(sch, spec=None):
    rid = sch.submit(spec or _spec())
    sch.run_pending()
    req = sch.request(rid)
    assert req.status == "done", req.error
    return req


# ------------------------------------------------------- catalog is OFF

def test_catalog_off_imports_nothing():
    """The is-None branch is the whole OFF story: a plain scheduler
    run must never even IMPORT the observatory module."""
    code = (
        "import sys\n"
        "import wittgenstein_tpu.models\n"
        "from wittgenstein_tpu.serve import ScenarioSpec, Scheduler\n"
        "sch = Scheduler()\n"
        "rid = sch.submit(ScenarioSpec(protocol='PingPong',"
        " params={'node_count': 64}, seeds=(0,), sim_ms=80,"
        " chunk_ms=40, obs=('metrics',)))\n"
        "sch.run_pending()\n"
        "assert sch.request(rid).status == 'done'\n"
        "assert 'wittgenstein_tpu.obs.programs' not in sys.modules, "
        "'catalog=None imported the observatory'\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, capture_output=True,
        text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_catalog_off_write_paths_never_touched(monkeypatch):
    """Rig every catalog write path to explode, then run a full
    lifecycle with catalog=None (the spans-OFF convention)."""
    def boom(*a, **k):
        raise AssertionError("catalog touched with catalog OFF")
    monkeypatch.setattr(CatalogProgram, "__init__", boom)
    monkeypatch.setattr(ProgramCatalog, "record_build", boom)
    monkeypatch.setattr(ProgramCatalog, "record_program", boom)
    monkeypatch.setattr(ProgramCatalog, "observe_chunk", boom)
    sch = Scheduler()
    assert sch.catalog is None and sch.registry.catalog is None
    _run(sch)


# ------------------------------------------------------ row round trip

def test_cold_build_round_trips_one_row(tmp_path):
    p = tmp_path / "programs.jsonl"
    cat = ProgramCatalog(path=p)
    sch = Scheduler(catalog=cat)
    _run(sch)
    # a second request on the same compile key: warm, no new row
    _run(sch, _spec(seeds=(1,)))
    rows = read_catalog(p)
    assert len(rows) == 1, [r.get("key") for r in rows]
    row = rows[0]
    for field in ("schema", "key", "plane", "backend", "protocol",
                  "build_wall_s", "lower_wall_s", "compile_wall_s",
                  "memory", "cost", "predicted", "arg_leaves",
                  "batch"):
        assert field in row, (field, sorted(row))
    assert row["compile_wall_s"] > 0 and row["build_wall_s"] > 0
    assert row["memory"].get("temp_bytes", 0) > 0
    assert row["predicted"]["route_vmem_bytes"] > 0
    assert row["predicted"]["vmem_budget_bytes"] > 0
    # chunk-wall samples aggregated per key; drift joins them
    stats = cat.chunk_stats()
    assert stats[row["key"]]["count"] >= 2, stats
    [d] = cat.drift()
    assert d["vmem_ratio"] > 0 and d["chunks"] >= 2, d
    rep = cat.report()
    assert rep["count"] == 1
    assert rep["top_compile"][0]["key"] == row["key"]
    assert rep["catalog"]["path"] == str(p)


def test_artifacts_bit_identical_catalog_on_off(tmp_path):
    """The capture serves launches FROM the compiled executable, so a
    catalogued run's artifacts are the uncatalogued run's artifacts —
    the only honest difference is the wall clock."""
    spec = _spec(obs=("metrics", "audit"))
    a = _run(Scheduler(), spec).artifacts
    b = _run(Scheduler(
        catalog=ProgramCatalog(path=tmp_path / "p.jsonl")),
        spec).artifacts
    norm = lambda d: json.dumps(                       # noqa: E731
        {k: v for k, v in d.items() if k != "wall_s"},
        sort_keys=True, default=str)
    assert norm(a) == norm(b)


# ----------------------------------------------------------- durability

def test_torn_tail_reload(tmp_path):
    p = tmp_path / "programs.jsonl"
    cat = ProgramCatalog(path=p)
    cat.record_program("k1", "metrics", lower_wall_s=0.1,
                       compile_wall_s=0.5, memory={"temp_bytes": 10},
                       cost={"flops": 1e6})
    cat.record_program("k2", "metrics", lower_wall_s=0.1,
                       compile_wall_s=0.7, memory={"temp_bytes": 20},
                       cost={})
    with open(p, "ab") as f:        # the SIGKILL mid-append shape
        f.write(b'{"schema": 1, "key": "k3", "compile_wa')
    rows = read_catalog(p)
    assert [r["key"] for r in rows] == ["k1", "k2"]


def test_sigkill_mid_append_at_most_one_torn_row(tmp_path):
    """A real SIGKILL against a process appending catalog rows in a
    loop: every complete row parses, and the raw file holds at most
    ONE extra (torn) line."""
    p = tmp_path / "programs.jsonl"
    code = (
        "import sys\n"
        "from wittgenstein_tpu.obs.programs import ProgramCatalog\n"
        f"cat = ProgramCatalog(path={str(p)!r}, fsync=False)\n"
        "for i in range(100000):\n"
        "    cat.record_program(f'k{i}', 'metrics', lower_wall_s=0.1,\n"
        "        compile_wall_s=0.5, memory={'temp_bytes': i},\n"
        "        cost={'flops': 1.0})\n")
    proc = subprocess.Popen(
        [sys.executable, "-c", code], cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if p.exists() and p.stat().st_size > 4096:
                break
            time.sleep(0.05)
        assert p.exists(), "writer never produced a row"
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    rows = read_catalog(p)
    raw_lines = len([ln for ln in p.read_bytes().split(b"\n") if ln])
    assert rows, "no complete rows survived the kill"
    assert raw_lines - len(rows) <= 1, (raw_lines, len(rows))
    assert all(r["key"] == f"k{i}" for i, r in enumerate(rows))


def test_write_error_degrades_loudly(tmp_path, capsys):
    """An unwritable catalog path must not take the build down with
    it — the row is lost, counted, and shouted to stderr."""
    cat = ProgramCatalog(path=tmp_path)    # a DIRECTORY: open() fails
    row = cat.record_program("k", "metrics", lower_wall_s=0.1,
                             compile_wall_s=0.5, memory={}, cost={})
    assert row is not None
    assert cat.stats()["write_errors"] == 1
    assert "programs" in capsys.readouterr().err


# -------------------------------------------------------------- metrics

def test_registry_and_drift_gauges_in_exposition(tmp_path):
    ins = Instrumentation(worker="t")
    cat = ProgramCatalog(path=tmp_path / "p.jsonl")
    sch = Scheduler(instrument=ins, catalog=cat)
    assert cat.metrics is ins.metrics      # adopted, one registry
    _run(sch)
    from wittgenstein_tpu.serve.instrument import scheduler_exposition
    m = parse_exposition(scheduler_exposition(sch))
    assert m.get("wtpu_registry_misses", 0) >= 1
    assert "wtpu_registry_hits" in m
    assert m.get("wtpu_programs_cataloged") == 1
    key = read_catalog(tmp_path / "p.jsonl")[0]["key"]
    assert any(k.startswith("wtpu_costmodel_drift{") and key in k
               for k in m), sorted(k for k in m if "wtpu_" in k)
    assert any(k.startswith("wtpu_program_compile_seconds{")
               for k in m)
    # the chunk-wall histogram fed through the shared registry
    assert m.get("wtpu_program_chunk_seconds_count", 0) >= 1


def test_programs_endpoint(tmp_path):
    svc = Service(scheduler=Scheduler(
        catalog=ProgramCatalog(path=tmp_path / "p.jsonl")), auto=False)
    off = Service(scheduler=Scheduler(), auto=False).programs()
    assert off["catalog"] == "off" and off["count"] == 0
    svc.submit(_spec().to_json())
    svc.run_pending()
    rep = svc.programs()
    assert rep["count"] == 1 and rep["top_compile"]
    assert rep["drift"][0]["vmem_ratio"] > 0


# ------------------------------------------------------------------ CLI

def test_tools_programs_cli(tmp_path, capsys):
    from tools import programs as cli
    assert cli.main([str(tmp_path / "missing")]) == 2
    cat = ProgramCatalog(path=tmp_path / "programs-w0.jsonl")
    cat.record_program("kx", "metrics", lower_wall_s=0.1,
                       compile_wall_s=0.5,
                       memory={"temp_bytes": 1024},
                       cost={"flops": 1e6})
    capsys.readouterr()
    assert cli.main([str(tmp_path)]) == 0          # directory glob
    out = capsys.readouterr().out
    assert "kx" in out and "top compile-wall" in out
    assert cli.main([str(tmp_path / "programs-w0.jsonl"),
                     "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["count"] == 1


def test_summarize_orders_by_compile_wall():
    rows = [{"key": "a", "plane": "m", "compile_wall_s": 0.1,
             "memory": {"temp_bytes": 10},
             "predicted": {"route_vmem_bytes": 100}},
            {"key": "b", "plane": "m", "compile_wall_s": 0.9,
             "memory": {"temp_bytes": 900},
             "predicted": {"route_vmem_bytes": 100}}]
    rep = summarize_programs(rows)
    assert [t["key"] for t in rep["top_compile"]] == ["b", "a"]
    assert rep["compile_wall_total_s"] == pytest.approx(1.0)
    # |log ratio| ordering: the 10x over-prediction (ratio 0.1)
    # outranks the 9x under-prediction — both directions equally loud
    assert rep["drift_outliers"][0]["key"] == "a"
    assert rep["drift_outliers"][1]["key"] == "b"
