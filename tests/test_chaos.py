"""The chaos plane (wittgenstein_tpu/chaos).

Invariants, per the package contract:

  * bit-determinism: one (FaultSchedule, seed) yields bit-identical
    trajectories across the dense per-ms engine, the superstep-K
    window engine, the batched seed-folded twin, the fast-forward
    while loop (fault-aware jump clamping) and the sharded runner;
  * zero residue: the chaos wrap with an EMPTY schedule is
    bit-identical to the unwrapped protocol;
  * obs planes compose: audit verdicts stay CLEAN under
    churn/partition (and a planted FaultInjector counter fault is
    still caught in its own window), churn drives the flight
    recorder's node_down/node_up kinds at their exact ms, and the
    metrics plane sees the outage;
  * refusal with remedy: malformed/overlapping windows and
    K-misaligned transitions are refused, never silently coerced.

Protocol configs mirror tests/test_superstep.py / test_sharded.py so
compiles share the suite's persistent-cache entries where possible.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from wittgenstein_tpu.chaos import ChaosProtocol, FaultSchedule
from wittgenstein_tpu.core.network import (check_chunk_config,
                                           fast_forward_chunk,
                                           pick_superstep, scan_chunk,
                                           superstep_ok)
from wittgenstein_tpu.models.pingpong import PingPong


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


#: the canonical small adversity: two crash/recover outages, one
#: mid-run partition that heals, lossy links, a delay window — all
#: transitions even (K=2-aligned)
SCHED = FaultSchedule(churn=((3, 20, 60), (5, 40, 100)),
                      partitions=((30, 90, 1, 0, 32),),
                      loss=((0, 120, 250, 0, 64, 0, 64),),
                      delay=((10, 50, 3, 0, 64, 0, 64),))


# ----------------------------------------------------------- validation


def test_schedule_refusals():
    with pytest.raises(ValueError, match="down_ms < up_ms"):
        FaultSchedule(churn=((3, 60, 20),)).validate()
    with pytest.raises(ValueError, match="out of range"):
        FaultSchedule(churn=((99, 0, 10),)).validate(n=64)
    with pytest.raises(ValueError, match="overlap on node"):
        FaultSchedule(churn=((3, 0, 50), (3, 40, 80))).validate()
    with pytest.raises(ValueError, match="ONE partition at a time"):
        FaultSchedule(partitions=((10, 50, 1, 0, 32),
                                  (20, 60, 2, 16, 48))).validate()
    with pytest.raises(ValueError, match="reserved"):
        FaultSchedule(partitions=((10, 50, 0, 0, 32),)).validate()
    with pytest.raises(ValueError, match="permille"):
        FaultSchedule(loss=((0, 10, 2000, 0, 8, 0, 8),)).validate()
    with pytest.raises(ValueError, match="never fire"):
        SCHED.validate(n=64, sim_ms=10)
    with pytest.raises(ValueError, match="unknown fault class"):
        FaultSchedule.from_json({"churns": [[1, 0, 10]]})
    with pytest.raises(ValueError, match="must be"):
        FaultSchedule.from_json({"churn": [[1, 0]]})
    # non-iterable rows/classes are ValueError too (the remedy-text
    # refusal contract — never a bare TypeError)
    with pytest.raises(ValueError, match="churn\\[0\\] must be"):
        FaultSchedule.from_json({"churn": [5]})
    with pytest.raises(ValueError, match="churn must be a list"):
        FaultSchedule.from_json({"churn": 5})
    # disjoint partitions (in time OR node range) are fine
    FaultSchedule(partitions=((10, 50, 1, 0, 32),
                              (10, 50, 2, 32, 64),
                              (50, 60, 3, 0, 64))).validate(n=64)


def test_schedule_roundtrip_and_alignment():
    assert FaultSchedule.from_json(SCHED.to_json()) == SCHED
    assert SCHED.transition_times() == (20, 30, 40, 60, 90, 100)
    assert SCHED.superstep_aligned(2)
    assert not SCHED.superstep_aligned(4)       # 30/90 misalign
    assert SCHED.align_gcd() == 10
    assert FaultSchedule().empty and FaultSchedule().superstep_aligned(8)


def test_superstep_gate_and_demotion():
    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, FaultSchedule(churn=((3, 21, 60),)))
    with pytest.raises(ValueError, match="window boundary"):
        check_chunk_config(cp, 120, superstep=2)
    assert not superstep_ok(cp, 2)
    # pick_superstep silently demotes to the per-ms path
    assert pick_superstep(cp, 120, t0=0) == 1
    # an aligned schedule keeps K=2
    cp2 = ChaosProtocol(proto, FaultSchedule(churn=((3, 20, 60),)))
    assert pick_superstep(cp2, 120, t0=0) == 2


# ----------------------------------------------------- engine identity


def test_empty_schedule_zero_residue():
    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, FaultSchedule())
    a = jax.jit(scan_chunk(proto, 120))(*proto.init(0))
    b = jax.jit(scan_chunk(cp, 120))(*cp.init(0))
    _trees_equal(a, b)


def test_dense_superstep_ff_bit_identity():
    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, SCHED)
    ref = jax.jit(scan_chunk(cp, 120))(*cp.init(0))
    k2 = jax.jit(scan_chunk(cp, 120, superstep=2))(*cp.init(0))
    _trees_equal(ref, k2)
    net, ps, stats = jax.jit(
        lambda n, p: fast_forward_chunk(cp, 120)(n, p))(*cp.init(0))
    _trees_equal(ref, (net, ps))
    # the quiet-heavy protocol must actually have jumped — i.e. the
    # fault-aware clamp was exercised, not bypassed by a dense run
    assert int(stats["skipped_ms"]) > 0
    # determinism: a second run is bit-identical
    _trees_equal(ref, jax.jit(scan_chunk(cp, 120))(*cp.init(0)))


def test_batched_bit_identity():
    from wittgenstein_tpu.core.batched import scan_chunk_batched
    from wittgenstein_tpu.models.handel import Handel

    sched = FaultSchedule(churn=((3, 20, 60), (9, 40, 104)),
                          partitions=((40, 80, 1, 0, 32),),
                          loss=((0, 120, 200, 0, 64, 0, 64),))
    proto = Handel(node_count=64, threshold=50, nodes_down=6,
                   pairing_time=4,
                   network_latency_name="NetworkFixedLatency(16)")
    cp = ChaosProtocol(proto, sched)
    nets, ps = jax.vmap(cp.init)(jnp.arange(3, dtype=jnp.int32))
    a = jax.jit(jax.vmap(scan_chunk(cp, 120, superstep=4)))(nets, ps)
    b = jax.jit(scan_chunk_batched(cp, 120, superstep=4))(nets, ps)
    _trees_equal(a, b)


def test_sharded_bit_identity():
    from jax.sharding import Mesh

    from wittgenstein_tpu.parallel.sharded import RingForward, ShardedRunner

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:8] if len(devs) >= 8 else devs[:1]),
                ("sp",))
    sched = FaultSchedule(churn=((5, 2, 20), (17, 4, 30)),
                          partitions=((6, 24, 1, 0, 16),),
                          loss=((0, 40, 300, 0, 64, 0, 64),))
    cp = ChaosProtocol(RingForward(n=64, stride=9, latency=10), sched)
    sr = ShardedRunner(cp, mesh)
    snet, sps = sr.init(0)
    snet, sps = sr.run_ms(snet, sps, 40)
    gn = sr.gather_nodes(snet)
    net, ps = jax.jit(scan_chunk(cp, 40))(*cp.init(0))
    for name in ("down", "partition", "msg_sent", "msg_received",
                 "done_at"):
        np.testing.assert_array_equal(
            np.asarray(getattr(gn, name)),
            np.asarray(getattr(net.nodes, name)), err_msg=name)
    np.testing.assert_array_equal(
        np.asarray(sps.received).reshape(-1), np.asarray(ps.received))


# ------------------------------------------------------------ adversary


def test_total_loss_blocks_unicasts():
    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, FaultSchedule(
        loss=((0, 120, 1000, 0, 64, 0, 64),)))
    net, ps = jax.jit(scan_chunk(cp, 120))(*cp.init(0))
    net0, ps0 = jax.jit(scan_chunk(proto, 120))(*proto.init(0))
    # every unicast on every link lost: the witness's sendAll ping (a
    # broadcast — loss is unicast-only by design) still lands, but no
    # pong ever makes it back, while the baseline clearly converges
    assert int(np.asarray(ps.pongs).sum()) == 0
    assert int(np.asarray(ps0.pongs).sum()) > 0
    assert (int(np.asarray(net.nodes.msg_received).sum()) <
            int(np.asarray(net0.nodes.msg_received).sum()))


def test_delay_inflation_shifts_arrivals_exactly():
    from wittgenstein_tpu.core.latency import NetworkFixedLatency
    from wittgenstein_tpu.obs.decode import TraceFrame
    from wittgenstein_tpu.obs.trace import TraceSpec, scan_chunk_trace

    proto = PingPong(node_count=8, latency=NetworkFixedLatency(5))
    cp = ChaosProtocol(proto, FaultSchedule(
        delay=((0, 200, 7, 0, 8, 0, 8),)))
    spec = TraceSpec(capacity=2048, events=("send", "deliver"))
    _, _, tc0 = jax.jit(scan_chunk_trace(proto, 60, spec))(*proto.init(0))
    _, _, tc1 = jax.jit(scan_chunk_trace(cp, 60, spec))(*cp.init(0))

    def first_pong_ms(tc):
        # the pong is the UNICAST leg (the ping is a broadcast, which
        # delay inflation deliberately leaves alone): a delivery whose
        # source is not the witness (node 0)
        fr = TraceFrame.from_carry(spec, tc).filter(kinds=("deliver",))
        t = fr.column("time_ms")[fr.column("src") != 0]
        assert t.size > 0
        return int(t.min())

    # fixed latency + constant inflation: the first pong lands EXACTLY
    # extra_ms later than the baseline's
    assert first_pong_ms(tc1) == first_pong_ms(tc0) + 7


# ------------------------------------------------------------ obs planes


def test_trace_node_down_up_kinds():
    from wittgenstein_tpu.obs.decode import TraceFrame
    from wittgenstein_tpu.obs.trace import TraceSpec, scan_chunk_trace

    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, SCHED)
    spec = TraceSpec(capacity=4096)
    run = jax.jit(scan_chunk_trace(cp, 120, spec))
    net, ps, tc = run(*cp.init(0))
    fr = TraceFrame.from_carry(spec, tc)
    dn = fr.filter(kinds=("node_down",))
    up = fr.filter(kinds=("node_up",))
    assert [(int(t), int(s)) for t, s in
            zip(dn.column("time_ms"), dn.column("src"))] == \
        [(20, 3), (40, 5)]
    assert [(int(t), int(s)) for t, s in
            zip(up.column("time_ms"), up.column("src"))] == \
        [(60, 3), (100, 5)]
    # trace-ON is bit-identical on the faulted trajectory
    _trees_equal(jax.jit(scan_chunk(cp, 120))(*cp.init(0)), (net, ps))
    # decode/export round trip covers the new kind
    assert len(fr.rows()) == fr.n_events
    from wittgenstein_tpu.obs.export import trace_to_perfetto
    p = trace_to_perfetto(fr)
    assert sum(1 for e in p["traceEvents"]
               if e.get("ph") == "X") == fr.n_events
    # K=2 window engine records the identical event stream
    _, _, tc2 = jax.jit(scan_chunk_trace(cp, 120, spec, superstep=2))(
        *cp.init(0))
    np.testing.assert_array_equal(np.asarray(tc.buf), np.asarray(tc2.buf))
    assert int(tc.cursor) == int(tc2.cursor)


def test_audit_clean_under_chaos_and_fault_still_caught():
    from wittgenstein_tpu.obs.audit import AuditSpec
    from wittgenstein_tpu.obs.audit_report import audit_variant
    from wittgenstein_tpu.obs.diff import FaultInjector

    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, SCHED)
    report, states = audit_variant(cp, 120, {"superstep": 1},
                                   AuditSpec())
    assert report.clean, report.format()
    # audited trajectory == unaudited faulted trajectory
    plain = jax.jit(jax.vmap(scan_chunk(cp, 120)))(
        *jax.vmap(cp.init)(jnp.arange(1, dtype=jnp.int32)))
    _trees_equal(plain, states)
    # a planted counter fault under the SAME chaos is still flagged, in
    # its own window (the audit catalogue stays sharp under adversity)
    planted = ChaosProtocol(
        FaultInjector(proto, at_ms=37, leaf="nodes.msg_sent", node=5,
                      delta=-(1 << 20)), SCHED)
    rep2, _ = audit_variant(planted, 120, {"superstep": 1}, AuditSpec())
    assert not rep2.clean
    assert rep2.first is not None
    assert rep2.first["invariant"] == "counter_monotone"
    assert rep2.first["ms"] == 37


def test_metrics_plane_sees_the_outage():
    from wittgenstein_tpu.obs.engine import scan_chunk_metrics
    from wittgenstein_tpu.obs.export import MetricsFrame
    from wittgenstein_tpu.obs.spec import MetricsSpec

    proto = PingPong(node_count=64)
    cp = ChaosProtocol(proto, SCHED)
    mspec = MetricsSpec(stat_each_ms=10)
    net, ps, mc = jax.jit(scan_chunk_metrics(cp, 120, mspec))(*cp.init(0))
    frame = MetricsFrame.from_carry(mspec, mc)
    live = frame.series[:, list(mspec.columns).index("live_count")]
    # both nodes down in [40, 60); one in [20, 40) and [60, 100)
    assert int(live.min()) == 62
    assert int(live[-1]) == 64          # both recovered by the end
    _trees_equal(jax.jit(scan_chunk(cp, 120))(*cp.init(0)), (net, ps))


# ---------------------------------------------------------- serve plane


def test_scenario_spec_fault_schedule():
    import wittgenstein_tpu.models  # noqa: F401 — fill the registry
    from wittgenstein_tpu.serve import ScenarioSpec

    base = dict(protocol="PingPong", params={"node_count": 64},
                seeds=(0,), sim_ms=120, chunk_ms=60)
    plain = ScenarioSpec(**base)
    spec = ScenarioSpec(**base, fault_schedule=SCHED.to_json())
    # program-affecting: folds into BOTH digest and compile key
    assert spec.digest() != plain.digest()
    assert spec.compile_key() != plain.compile_key()
    # canonical normalization: dict-order / empty-class variants of the
    # same adversity digest equal
    noisy = dict(SCHED.to_json())
    noisy["delay"] = list(noisy["delay"])
    assert ScenarioSpec(**base, fault_schedule=noisy).digest() == \
        spec.digest()
    assert ScenarioSpec(**base, fault_schedule={}).digest() == \
        plain.digest()
    # round trip through canonical JSON
    assert ScenarioSpec.from_json(spec.canonical_json()) == spec
    resolved = spec.validate()
    assert isinstance(resolved.superstep, int)
    proto = resolved.build_protocol()
    assert isinstance(proto, ChaosProtocol)

    # refusal with remedy -> the HTTP layer's 400
    with pytest.raises(ValueError, match="ONE partition at a time"):
        ScenarioSpec(**base, fault_schedule={
            "partitions": [[10, 50, 1, 0, 32],
                           [20, 60, 2, 16, 48]]}).validate()
    with pytest.raises(ValueError, match="out of range"):
        ScenarioSpec(**base,
                     fault_schedule={"churn": [[999, 0, 10]]}).validate()
    with pytest.raises(ValueError, match="never fire"):
        ScenarioSpec(**base, fault_schedule={
            "churn": [[3, 500, 600]]}).validate()
    with pytest.raises(ValueError, match="unknown fault class"):
        ScenarioSpec(**base, fault_schedule={"zaps": []})
    # churn OWNS its nodes' liveness — a node also named down-at-entry
    # would be silently revived at ms 0, so the clash is refused
    with pytest.raises(ValueError, match="churn owns"):
        ScenarioSpec(**base, partition=(3,), fault_schedule={
            "churn": [[3, 100, 120]]}).validate()
    # misaligned transitions refuse an explicit superstep with remedy
    with pytest.raises(ValueError, match="window boundary"):
        ScenarioSpec(**base, superstep=2, fault_schedule={
            "churn": [[3, 21, 60]]}).validate()
    # ... while "auto" demotes to the per-ms path
    auto = ScenarioSpec(**base, superstep="auto", fault_schedule={
        "churn": [[3, 21, 60]]}).validate()
    assert auto.superstep == 1


def test_from_env_captures_chaos():
    from wittgenstein_tpu.serve.spec import ScenarioSpec

    env = {"WTPU_BENCH_PROTO": "pingpong", "WTPU_BENCH_NODES": "64",
           "WTPU_CHAOS": '{"churn": [[3, 20, 60]]}'}
    spec = ScenarioSpec.from_env(env)
    assert spec.fault_schedule == {"churn": [[3, 20, 60]]}
    env2 = dict(env, WTPU_CHAOS="{broken")
    assert ScenarioSpec.from_env(env2).fault_schedule is None
    assert ScenarioSpec.from_env(
        dict(env, WTPU_CHAOS="{}")).fault_schedule is None
