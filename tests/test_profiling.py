"""Profiling/observability hooks (SURVEY.md §5.1 replacement)."""

import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.pingpong import PingPong
from wittgenstein_tpu.utils.profiling import run_report, timed, trace


def test_run_report_and_timers(tmp_path):
    proto = PingPong(node_count=64)
    net, ps = proto.init(0)
    with timed() as t:
        with trace(None):                      # no-op path
            net, ps = Runner(proto, donate=False).run_ms(net, ps, 300)
    wall = t()
    rep = run_report(net, wall)
    assert rep.startswith("Simulation execution time:")
    assert "sim=300ms" in rep and "live=64" in rep
    assert "dropped=0" in rep and "sim-ms/s" in rep
    assert wall > 0


def test_run_report_all_down_and_frozen_timer():
    import time as _time
    proto = PingPong(node_count=8)
    net, ps = proto.init(0)
    # All nodes down: the report must not crash or NaN.
    net = net.replace(nodes=net.nodes.replace(
        down=np.ones(8, bool) | np.asarray(net.nodes.down)))
    rep = run_report(net)
    assert "live=0" in rep and "nan" not in rep
    # Timer freezes at block exit.
    with timed() as t:
        _time.sleep(0.05)
    frozen = t()
    _time.sleep(0.05)
    assert abs(t() - frozen) < 1e-9
