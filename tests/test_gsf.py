"""GSFSignature tests — the analogue of GSFSignatureTest.java: init
invariants, run-to-done, copy/seed determinism."""

import jax
import jax.numpy as jnp
import numpy as np

from wittgenstein_tpu.core.harness import run_multiple_times
from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.gsf import GSFSignature, cont_if_gsf
from wittgenstein_tpu.ops import bitset


def test_init_invariants():
    # GSFSignatureTest.java:22-42: after init every node has exactly its own
    # signature verified, and level geometry covers the id space.
    p = GSFSignature(node_count=64, threshold=50, nodes_down=0,
                     network_latency_name="NetworkLatencyByDistanceWJitter")
    net, ps = p.init(0)
    card = np.asarray(bitset.popcount(ps.verified))
    assert np.all(card == 1)
    for i in (0, 17, 63):
        assert bool(bitset.get_bit(ps.verified[i][None, :],
                                   jnp.asarray([i]))[0])
    # remainingCalls per level == the level size (peers.size()).
    rem = np.asarray(ps.remaining)
    assert rem.shape == (64, 7)
    assert list(rem[0]) == [0, 1, 2, 4, 8, 16, 32]


def test_peer_order_is_permutation():
    p = GSFSignature(node_count=64)
    net, ps = p.init(3)
    ids = jnp.zeros((16,), jnp.int32) + 5
    lvl = jnp.full((16,), 5, jnp.int32)   # half = 16
    pos = jnp.arange(16, dtype=jnp.int32)
    peers = np.asarray(p._peer_at(ps.seed, ids, lvl, pos))
    # Node 5 at level 5: its 32-block is [0, 32), it sits in the lower
    # half, so the sibling half is [16, 32).
    assert sorted(peers) == list(range(16, 32))


def test_run_to_done_and_determinism():
    p = GSFSignature(node_count=128, threshold=115, pairing_time=3,
                     period_duration_ms=10, accelerated_calls_count=10,
                     nodes_down=12,
                     network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)
    net, ps = p.init(0)
    net2, ps2 = p.init(0)
    for _ in range(8):
        net, ps = r.run_ms(net, ps, 250)
        if bool(p.done(ps, net.nodes)):
            break
    assert bool(p.done(ps, net.nodes)), "live nodes must all reach threshold"
    assert int(net.dropped) == 0 and int(net.clamped) == 0
    live = ~np.asarray(net.nodes.down)
    done_at = np.asarray(net.nodes.done_at)
    assert np.all(done_at[live] > 0)
    card = np.asarray(bitset.popcount(ps.verified))
    assert np.all(card[live] >= 115)

    # Determinism (GSFSignatureTest.java:127+ testCopy analogue): re-init
    # same seed, re-run, states identical.
    for _ in range(2):
        net2, ps2 = r.run_ms(net2, ps2, 250)
    net3, ps3 = p.init(0)
    for _ in range(2):
        net3, ps3 = r.run_ms(net3, ps3, 250)
    assert np.array_equal(np.asarray(ps2.verified), np.asarray(ps3.verified))
    assert np.array_equal(np.asarray(net2.nodes.done_at),
                          np.asarray(net3.nodes.done_at))


def test_harness_multirun():
    p = GSFSignature(node_count=64, threshold=58, nodes_down=4,
                     network_latency_name="NetworkNoLatency")
    res = run_multiple_times(p, run_count=2, max_time=3000, chunk=250,
                             cont_if=cont_if_gsf)
    assert np.all(np.asarray(res.stopped_at) > 0)


def test_gsf_pallas_merge_bit_equal():
    """The fused GSF queue-merge kernel (ops/pallas_gsf_merge.py,
    interpret mode on CPU) leaves the ENTIRE simulation bit-identical:
    full pytree equality after a run exercising aggregates, individuals
    and evictions (small queue forces displacement)."""
    kw = dict(node_count=128, threshold=115, nodes_down=12,
              queue_cap=4, inbox_cap=8,
              network_latency_name="NetworkLatencyByDistanceWJitter")
    outs = []
    for pallas in (False, True):
        p = GSFSignature(pallas_merge=pallas, **kw)
        net, ps = p.init(7)
        net, ps = Runner(p, donate=False).run_ms(net, ps, 600)
        outs.append((net, ps))
    for (pa, a), b in zip(
            jax.tree_util.tree_leaves_with_path(outs[0]),
            jax.tree.leaves(outs[1])):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=jax.tree_util.keystr(pa))
