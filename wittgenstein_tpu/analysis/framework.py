"""Rule framework: findings, budgets, the ratchet, and the runner.

A rule is a named check with one entry point:

    run(target, budget) -> list[Finding]

Compiled rules (``scope == "protocol"``) get an `AnalysisTarget` per
protocol; global rules (``scope == "global"``) run once with
``target=None`` (source lints, kernel cost models).  A `Finding` with
severity "error" fails the run; "info" findings carry the measured
metrics that budgets are ratcheted from.

Budgets (analysis/budgets.json) ratchet DOWN, never up: `--update-
budgets` writes a metric only when the measured value is strictly below
the checked-in one (or when no budget exists yet).  A regression above
budget is an error finding; tightening requires nothing; loosening
requires a human editing the JSON in a reviewed diff.  That is the same
one-way gate the round-5 carry-copy fix needed and did not have
(ISSUE: a one-off audit script guards nothing).
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

BUDGETS_PATH = pathlib.Path(__file__).resolve().parent / "budgets.json"

SEVERITIES = ("error", "warning", "info")

#: machine-readable report schema (`Report.to_json()["schema"]`).
#: 1 = the original unversioned shape (no schema field, findings
#: without spans); 2 adds this field plus per-finding `path`/`line`
#: source spans.  Consumers should accept unknown EXTRA fields within
#: a schema version; field removals/renames bump it.
REPORT_SCHEMA = 2


@dataclasses.dataclass
class Finding:
    rule: str
    target: str             # protocol/target name, or file for lints
    severity: str           # "error" | "warning" | "info"
    message: str
    metric: str | None = None   # budgetable metric name
    value: object = None        # measured value for `metric`
    path: str | None = None     # repo-relative source file, for lints
    line: int | None = None     # 1-based line within `path`

    def to_json(self):
        return dataclasses.asdict(self)

    def span(self) -> str:
        """``path:line`` when the finding carries a source span."""
        if self.path is None:
            return ""
        return f"{self.path}:{self.line}" if self.line else self.path


class Rule:
    """Base class; subclasses set `name`, `scope` and implement run()."""

    name: str = ""
    scope: str = "protocol"     # "protocol" | "global"
    #: metrics (by name) the budget ratchet tracks for this rule
    budgeted_metrics: tuple = ()

    def run(self, target, budget: dict) -> list[Finding]:
        raise NotImplementedError

    def describe(self) -> str:
        """One-line target summary for ``--list`` (global rules
        override this to say what they scan)."""
        return ""


RULES: dict[str, Rule] = {}


def register_rule(cls):
    inst = cls()
    assert inst.name and inst.name not in RULES, inst.name
    RULES[inst.name] = inst
    return cls


def _install_rules():
    """Import the rule modules for their registration side effect."""
    from . import (rules_audit, rules_carry, rules_determinism,  # noqa: F401
                   rules_dtype, rules_host_digest, rules_host_durability,
                   rules_host_except, rules_host_locks, rules_hostsync,
                   rules_metrics, rules_superstep, rules_trace, rules_vmem)


def parse_allow(budget: dict) -> frozenset:
    """The rule's suppression list from its budget block: a frozenset
    of ``"relpath::qualname::pattern"`` strings (``budgets.json`` key
    ``<rule>.allow``).  The syntax is shared across every source rule
    (determinism and the host-plane family), so an exemption is always
    a reviewed budget-file diff, never a code-side skip."""
    return frozenset(budget.get("allow", ()))


def is_allowed(allow, relpath: str, qualname: str, pattern: str) -> bool:
    """True when ``relpath::qualname::pattern`` is suppressed."""
    return f"{relpath}::{qualname}::{pattern}" in allow


def load_budgets(path=BUDGETS_PATH) -> dict:
    if pathlib.Path(path).exists():
        with open(path) as f:
            return json.load(f)
    return {}


def save_budgets(budgets: dict, path=BUDGETS_PATH):
    with open(path, "w") as f:
        json.dump(budgets, f, indent=2, sort_keys=True)
        f.write("\n")


def check_budget(findings, budgets, rule, target_name) -> list[Finding]:
    """Turn measured info-findings into errors where they exceed the
    checked-in budget.  Metrics with no budget entry yet pass (run
    --update-budgets to pin them)."""
    out = list(findings)
    rb = budgets.get(rule.name, {}).get(target_name, {})
    for f in findings:
        if f.metric is None or f.metric not in rule.budgeted_metrics:
            continue
        limit = rb.get(f.metric)
        if limit is not None and f.value is not None and f.value > limit:
            out.append(Finding(
                rule=rule.name, target=target_name, severity="error",
                metric=f.metric, value=f.value,
                message=(f"{f.metric}={f.value} exceeds the checked-in "
                         f"budget {limit} (analysis/budgets.json ratchets "
                         "down only — fix the regression, do not raise "
                         "the budget)")))
    return out


def ratchet_budgets(findings, budgets, rules) -> dict:
    """Fold measured metrics into `budgets`, downward only."""
    for f in findings:
        rule = rules.get(f.rule)
        if (rule is None or f.metric is None
                or f.metric not in rule.budgeted_metrics
                or not isinstance(f.value, (int, float))):
            continue
        rb = budgets.setdefault(f.rule, {}).setdefault(f.target, {})
        old = rb.get(f.metric)
        if old is None or f.value < old:
            rb[f.metric] = f.value
    return budgets


@dataclasses.dataclass
class Report:
    findings: list
    targets: list
    rules: list
    errors: list = dataclasses.field(init=False)

    def __post_init__(self):
        self.errors = [f for f in self.findings if f.severity == "error"]

    @property
    def ok(self):
        return not self.errors

    def to_json(self):
        return {"schema": REPORT_SCHEMA, "ok": self.ok,
                "targets": self.targets, "rules": self.rules,
                "n_errors": len(self.errors),
                "findings": [f.to_json() for f in self.findings]}


def run_analysis(target_names=None, rule_names=None, budgets=None,
                 progress=None, source_only=False) -> Report:
    """Run `rule_names` (default: all) over `target_names` (default: the
    full pinned registry) against `budgets` (default: the checked-in
    file).  Compile failures become error findings, not crashes — a
    protocol whose superstep stops compiling on CPU is itself a
    regression the report must surface.  ``source_only`` restricts the
    run to global (source-lint) rules and skips the compiled-target
    registry entirely — no protocol import, no XLA, seconds not
    minutes (the ``--source`` CLI mode)."""
    _install_rules()
    budgets = load_budgets() if budgets is None else budgets
    rules = [RULES[r] for r in (rule_names or sorted(RULES))]
    if source_only:
        rules = [r for r in rules if r.scope == "global"]
        names = []
    else:
        from . import targets as targets_mod
        names = list(target_names) if target_names is not None \
            else list(targets_mod.target_names())

    findings: list[Finding] = []
    for rule in rules:
        if rule.scope != "global":
            continue
        if progress:
            progress(f"rule {rule.name} (global)")
        fs = rule.run(None, budgets.get(rule.name, {}))
        findings += check_budget(fs, budgets, rule, "global")

    proto_rules = [r for r in rules if r.scope == "protocol"]
    for name in names if proto_rules else []:
        target = targets_mod.get_target(name)
        for rule in proto_rules:
            if progress:
                progress(f"rule {rule.name} on {name}")
            try:
                fs = rule.run(target, budgets.get(rule.name, {}).get(name, {}))
            except Exception as e:          # noqa: BLE001
                findings.append(Finding(
                    rule=rule.name, target=name, severity="error",
                    message=f"rule crashed: {type(e).__name__}: {e}"))
                continue
            findings += check_budget(fs, budgets, rule, name)
    return Report(findings=findings, targets=names,
                  rules=[r.name for r in rules])
