"""`SweepGrid` — a declarative scenario matrix as data.

The reference's protocol packages sweep one axis at a time with
hand-rolled runners and print ad-hoc tables; the BFT-evaluation
campaigns this repo targets (PAPERS.md 2208.14745, 2309.17245) are the
opposite shape: ONE declarative grid over protocol params x N x
latency model x chaos schedule x attack x seeds whose value is the
comparable cross-cell report, not any single run.  `SweepGrid` is that
grid, frozen and JSON-able like `ScenarioSpec`:

  base   — a `ScenarioSpec` JSON object, the template every cell
           starts from;
  axes   — an ordered list of named axes.  Each axis either names one
           override path (``field``: a spec field like ``latency_model``
           / ``seeds`` / ``fault_schedule``, or ``params.<kwarg>``) with
           a value list, or pairs several paths per value (``field``
           omitted, every value a ``{path: value}`` dict — e.g. an
           engine/K axis that must move both fields together);
  exclude — label-matching rules (``{axis_name: label}``); a cell
           matching EVERY entry of any rule is dropped from the
           expansion (the classic "batched engine x K=1 is not a
           config" hole-punch).

`expand()` is DETERMINISTIC: the Cartesian product in declared axis
order, row-major, exclusions filtered — two processes expanding the
same grid JSON enumerate byte-identical cells.  Each cell's id is its
label path (``"N=64/lat=fixed30/chaos=clean/seed=s3"``), stable under
exclusion-rule changes, and its spec is a full `ScenarioSpec` (a
malformed cell refuses at expansion, naming the cell — the CLI's
exit-2 / HTTP-400 path).  `grid_digest()` is the content digest of the
canonical JSON: every ledger row and report a grid produces carries
it, so thousands of rows join back to ONE grid by construction.
"""

from __future__ import annotations

import dataclasses
import json

from ..serve.spec import ScenarioSpec

#: grid schema version (bump on field changes; readers key on it)
SCHEMA = 1

#: spec fields an axis may override (everything but the schema pin)
SPEC_FIELDS = tuple(sorted(
    f.name for f in dataclasses.fields(ScenarioSpec) if f.name != "schema"))

#: the adversity paths — axes touching these get fault-free twin
#: resolution in the MatrixReport (impact deltas vs the clean cell)
ADVERSITY_FIELDS = ("fault_schedule", "attack")


def _err(msg: str) -> ValueError:
    return ValueError(f"SweepGrid: {msg}")


def _check_path(path, axis_name: str):
    if not isinstance(path, str):
        raise _err(f"axis {axis_name!r}: override path {path!r} must be "
                   "a string")
    if path.startswith("params.") and len(path) > len("params."):
        return
    if path not in SPEC_FIELDS:
        raise _err(f"axis {axis_name!r}: unknown override path {path!r}; "
                   f"use 'params.<ctor kwarg>' or a spec field "
                   f"({', '.join(SPEC_FIELDS)})")


def _default_label(value) -> str | None:
    """Scalar values label themselves; structured values (schedules,
    paired overrides, attacks) need explicit labels — None signals
    'ask the author'."""
    if value is None:
        return "none"
    if isinstance(value, bool):
        return str(value).lower()
    if isinstance(value, (int, float, str)):
        return str(value)
    if isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, float, str, bool)) for v in value):
        return ",".join(str(v) for v in value)
    return None


@dataclasses.dataclass(frozen=True)
class Axis:
    """One named sweep dimension (normalized; see module docstring)."""

    name: str
    values: tuple
    labels: tuple
    field: str | None = None        # None = paired-override values

    def to_json(self) -> dict:
        out = {"name": self.name, "values": list(self.values),
               "labels": list(self.labels)}
        if self.field is not None:
            out["field"] = self.field
        return out

    @property
    def adversity(self) -> bool:
        """Does this axis move a fault/attack path?  (Twin resolution.)"""
        if self.field is not None:
            return self.field in ADVERSITY_FIELDS
        return any(p in ADVERSITY_FIELDS for v in self.values
                   if isinstance(v, dict) for p in v)

    def clean_label(self) -> str | None:
        """The label of this adversity axis's fault-free value (the
        twin every adverse cell is compared against), or None when the
        axis has no clean value."""
        for val, lab in zip(self.values, self.labels):
            if self.field is not None:
                if val is None:
                    return lab
            elif isinstance(val, dict) and all(
                    val.get(p) is None for p in ADVERSITY_FIELDS
                    if p in val):
                return lab
        return None


@dataclasses.dataclass(frozen=True)
class Cell:
    """One expanded grid cell: its stable id (the label path), the
    per-axis labels, and the full `ScenarioSpec`."""

    id: str
    labels: dict                    # axis name -> value label
    spec: ScenarioSpec


@dataclasses.dataclass(frozen=True)
class SweepGrid:
    """See the module docstring.  Frozen; hash by canonical JSON."""

    base: dict
    axes: tuple = ()
    exclude: tuple = ()
    name: str = "grid"
    schema: int = SCHEMA

    def __post_init__(self):
        if not isinstance(self.base, dict) or "protocol" not in self.base:
            raise _err("base must be a ScenarioSpec JSON object with a "
                       "'protocol' field (serve/spec.py schema)")
        object.__setattr__(self, "base", dict(self.base))
        axes = []
        seen = set()
        for raw in self.axes:
            axes.append(self._norm_axis(raw))
            if axes[-1].name in seen:
                raise _err(f"duplicate axis name {axes[-1].name!r}")
            seen.add(axes[-1].name)
        if not axes:
            raise _err("a grid needs at least one axis (a single cell "
                       "is a plain ScenarioSpec — submit it to "
                       "/w/batch/submit instead)")
        object.__setattr__(self, "axes", tuple(axes))
        rules = []
        for rule in self.exclude:
            if not isinstance(rule, dict) or not rule:
                raise _err(f"exclusion rule {rule!r} must be a non-empty "
                           "{axis_name: label} object")
            by_name = {a.name: a for a in axes}
            for k, v in rule.items():
                if k not in by_name:
                    raise _err(f"exclusion rule names unknown axis {k!r}; "
                               f"axes: {sorted(by_name)}")
                if str(v) not in by_name[k].labels:
                    raise _err(
                        f"exclusion rule value {v!r} is not a label of "
                        f"axis {k!r} (labels: {list(by_name[k].labels)})")
            rules.append({k: str(v) for k, v in sorted(rule.items())})
        object.__setattr__(self, "exclude", tuple(rules))

    @staticmethod
    def _norm_axis(raw) -> Axis:
        if isinstance(raw, Axis):
            raw = raw.to_json()
        if not isinstance(raw, dict):
            raise _err(f"axis {raw!r} must be an object with "
                       "name/values[/field/labels]")
        unknown = set(raw) - {"name", "field", "values", "labels"}
        if unknown:
            raise _err(f"axis {raw.get('name', raw)!r}: unknown key(s) "
                       f"{sorted(unknown)}; known: name field values "
                       "labels")
        name = raw.get("name")
        if not name or not isinstance(name, str):
            raise _err(f"axis {raw!r} needs a non-empty string 'name'")
        values = raw.get("values")
        if not isinstance(values, (list, tuple)) or not values:
            raise _err(f"axis {name!r} needs a non-empty 'values' list")
        field = raw.get("field")
        if field is not None:
            _check_path(field, name)
        else:
            for v in values:
                if not isinstance(v, dict) or not v:
                    raise _err(
                        f"axis {name!r} has no 'field', so every value "
                        "must be a non-empty {path: value} override "
                        f"object (the paired-axis form); got {v!r}")
                for p in v:
                    _check_path(p, name)
        labels = raw.get("labels")
        if labels is None:
            labels = [_default_label(v) for v in values]
            missing = [i for i, lab in enumerate(labels) if lab is None]
            if missing:
                raise _err(
                    f"axis {name!r}: values at index(es) {missing} are "
                    "structured (dict/schedule) and cannot label "
                    "themselves — pass explicit 'labels' (one short "
                    "string per value)")
        labels = [str(x) for x in labels]
        if len(labels) != len(values):
            raise _err(f"axis {name!r}: {len(labels)} labels for "
                       f"{len(values)} values")
        if len(set(labels)) != len(labels):
            raise _err(f"axis {name!r}: duplicate labels {labels} — "
                       "cell ids are label paths and must be unique")
        bad = [lab for lab in labels if "/" in lab or "=" in lab]
        if bad:
            raise _err(f"axis {name!r}: label(s) {bad} contain '/' or "
                       "'=' (reserved by the cell-id path form)")
        return Axis(name=str(name), values=tuple(values),
                    labels=tuple(labels), field=field)

    def __hash__(self):
        # the dataclass-generated field-tuple hash would TypeError on
        # the dict-typed `base`; content identity IS the canonical JSON
        return hash(self.canonical_json())

    # ------------------------------------------------------- serialization

    def to_json(self) -> dict:
        return {"schema": self.schema, "name": self.name,
                "base": dict(self.base),
                "axes": [a.to_json() for a in self.axes],
                "exclude": [dict(r) for r in self.exclude]}

    def canonical_json(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_json(cls, data) -> "SweepGrid":
        """Inverse of `to_json` (dict or JSON string); unknown keys are
        refused with the known list — the `ScenarioSpec.from_json`
        contract (a typo'd key silently dropped would digest as a
        different grid than the author meant)."""
        if isinstance(data, (str, bytes)):
            data = json.loads(data)
        if not isinstance(data, dict):
            raise _err(f"expected a JSON object, got "
                       f"{type(data).__name__}")
        known = {"schema", "name", "base", "axes", "exclude"}
        unknown = set(data) - known
        if unknown:
            raise _err(f"unknown field(s) {sorted(unknown)}; known: "
                       f"{sorted(known)}")
        if data.get("schema", SCHEMA) != SCHEMA:
            raise _err(f"unsupported schema {data.get('schema')!r} "
                       f"(this reader understands schema {SCHEMA})")
        if "base" not in data:
            raise _err("missing required field 'base' (a ScenarioSpec "
                       "JSON object)")
        kw = {k: data[k] for k in known & set(data)}
        for key in ("axes", "exclude"):
            if key in kw:
                kw[key] = tuple(kw[key])
        return cls(**kw)

    def grid_digest(self) -> str:
        """Content digest of the whole grid — what every per-cell
        ledger row and the MatrixReport carry (obs/ledger.digest)."""
        from ..obs.ledger import digest
        return digest(self.to_json())

    # ----------------------------------------------------------- expansion

    def cell_id(self, labels: dict) -> str:
        """The stable id of the cell at these axis labels."""
        return "/".join(f"{a.name}={labels[a.name]}" for a in self.axes)

    def _excluded(self, labels: dict) -> bool:
        return any(all(labels.get(k) == v for k, v in rule.items())
                   for rule in self.exclude)

    def n_cells_raw(self) -> int:
        """Product of axis lengths, BEFORE exclusion filtering."""
        n = 1
        for a in self.axes:
            n *= len(a.values)
        return n

    def expand(self) -> list:
        """Deterministic cell list (module docstring).  A cell whose
        merged spec is malformed refuses with the cell id prefixed —
        grid authoring errors surface before anything compiles."""
        import copy
        import itertools

        cells = []
        for combo in itertools.product(*(range(len(a.values))
                                         for a in self.axes)):
            labels = {a.name: a.labels[i]
                      for a, i in zip(self.axes, combo)}
            if self._excluded(labels):
                continue
            merged = copy.deepcopy(self.base)
            for a, i in zip(self.axes, combo):
                val = a.values[i]
                overrides = {a.field: val} if a.field is not None else val
                for path, v in overrides.items():
                    if path.startswith("params."):
                        merged.setdefault("params", {})[
                            path[len("params."):]] = copy.deepcopy(v)
                    elif v is None:
                        # a None axis value CLEARS the field back to the
                        # spec default (the fault-free / default-model
                        # twin cells) rather than forcing null into
                        # non-nullable fields
                        merged.pop(path, None)
                    else:
                        merged[path] = copy.deepcopy(v)
            cid = self.cell_id(labels)
            try:
                spec = ScenarioSpec.from_json(merged)
            except (ValueError, TypeError) as e:
                raise _err(f"cell {cid!r}: {e}") from None
            cells.append(Cell(id=cid, labels=labels, spec=spec))
        if not cells:
            raise _err("exclusion rules removed every cell — nothing "
                       "to run (loosen the rules or drop an axis)")
        return cells

    # ----------------------------------------------------------- twin map

    def twin_id(self, labels: dict) -> str | None:
        """The fault-free/attack-free twin of the cell at `labels`:
        same labels with every adversity axis at its clean value.
        None when the cell IS clean, or when some adversity axis has
        no clean value to fall back to."""
        adversity = [(a, a.clean_label()) for a in self.axes
                     if a.adversity]
        if not adversity:
            return None
        twin = dict(labels)
        moved = False
        for axis, clean in adversity:
            if labels.get(axis.name) == clean:
                continue
            if clean is None:
                return None
            twin[axis.name] = clean
            moved = True
        if not moved:
            return None                 # the cell is its own twin
        if self._excluded(twin):
            return None
        return self.cell_id(twin)
