"""Word-packed bitset kernels: the TPU representation of java.util.BitSet.

The reference's aggregation protocols are bitset algebra over node-id sets
(Handel.java lastAggVerified/totalIncoming/..., GSFSignature, San Fermín).
Here a bitset over [0, n) is a row of ``ceil(n/32)`` uint32 words; all ops
are elementwise, so they batch freely over [N, W] node-state matrices.

Contiguous-range masks matter because the binary-tree protocols only ever
deal in aligned ranges (a node's level-l peer set is the sibling half of its
2^l-aligned block — Handel.allSigsAtLevel, Handel.java:667-680), so a mask
is computed from (base, length) arithmetic, never stored.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U32 = jnp.uint32
WORD = 32


def n_words(n: int) -> int:
    return (n + WORD - 1) // WORD


def popcount(bits, axis=-1):
    """Total set bits along the word axis."""
    return jnp.sum(jax.lax.population_count(bits).astype(jnp.int32),
                   axis=axis)


def one_bit(idx, w: int):
    """[..., W] bitset with exactly bit `idx` set (idx int array)."""
    idx = jnp.asarray(idx)
    word = jnp.arange(w, dtype=jnp.int32)
    hit = (idx[..., None] // WORD) == word
    return jnp.where(hit, U32(1) << (idx[..., None] % WORD).astype(U32),
                     U32(0))


def get_bit(bits, idx):
    """Read bit `idx` from [..., W] bitsets (idx broadcastable int array)."""
    word = jnp.take_along_axis(bits, (idx[..., None] // WORD), axis=-1)[..., 0]
    return ((word >> (idx % WORD).astype(U32)) & U32(1)) != 0


def range_mask(base, length, w: int):
    """[..., W] mask of the contiguous bit range [base, base+length).

    base/length are int arrays (broadcast to the leading shape).  Handles the
    hi==32 full-word case without a 1<<32 overflow.
    """
    base = jnp.asarray(base, jnp.int32)[..., None]
    end = base + jnp.asarray(length, jnp.int32)[..., None]
    wlo = jnp.arange(w, dtype=jnp.int32) * WORD
    lo = jnp.clip(base - wlo, 0, WORD)
    hi = jnp.clip(end - wlo, 0, WORD)
    full = U32(0xFFFFFFFF)
    m_hi = jnp.where(hi >= WORD, full, (U32(1) << hi.astype(U32)) - U32(1))
    m_lo = jnp.where(lo >= WORD, full, (U32(1) << lo.astype(U32)) - U32(1))
    return m_hi & ~m_lo


def includes(a, b, axis=-1):
    """True where bitset a ⊇ b (BitSetUtils.include, core/utils/
    BitSetUtils.java)."""
    return jnp.all((b & ~a) == 0, axis=axis)


def intersects(a, b, axis=-1):
    return jnp.any((a & b) != 0, axis=axis)
