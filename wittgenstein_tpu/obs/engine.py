"""Instrumented engine chunk builders — the metrics-ON twins of
`core/network.scan_chunk` / `fast_forward_chunk` and the batched
seed-folded pair in `core/batched`.

Each builder returns the uninstrumented engine's result tuple with a
`MetricsCarry` appended; the simulation dataflow is the SAME functions
(`step_kms`, `step_kms_batched`, the oracle, the jump) — the recorder
only reads the carried state between steps, which is what the
bit-identity tests in tests/test_obs.py pin.  The instrumented dense
path runs the per-ms engine (superstep=1); every engine variant is
bit-identical to it (tests/test_superstep.py, test_batched.py,
test_fast_forward.py), so an instrumented per-ms run observes exactly
the trajectory the fused/batched production engines compute.

The uninstrumented builders never import this module — metrics-OFF
compiles with zero residue, enforced by the `metrics_zero_cost`
analysis rule.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.batched import step_kms_batched
from ..core.network import (check_chunk_config, fast_forward_ok, next_work,
                            step_kms, step_ms, superstep_ok, _jump)
from .plane import init_metrics, record_jump, record_step
from .spec import MetricsSpec


def step_ms_metrics(protocol, spec: MetricsSpec, net, pstate, mc):
    """One instrumented millisecond: `step_ms` then the interval
    recorder.  The building block of every dense builder below."""
    net, pstate = step_ms(protocol, net, pstate)
    return net, pstate, record_step(spec, mc, net)


def _check_superstep_interval(spec: MetricsSpec, superstep: int):
    """K-window recording samples at window boundaries, so a window must
    never straddle a `stat_each_ms` row — the counter attribution the
    interval recorder promises."""
    if superstep > 1 and spec.stat_each_ms % superstep:
        raise ValueError(
            f"the superstep={superstep} engine advances in fused "
            f"{superstep}-ms windows, so stat_each_ms must be a multiple "
            f"of it (got {spec.stat_each_ms}) — a straddling interval "
            "would sample mid-window state the fused step never "
            "materializes. Fix: pick stat_each_ms divisible by the "
            "superstep, or a smaller superstep")


def scan_chunk_metrics(protocol, ms: int, spec: MetricsSpec,
                       superstep: int = 1):
    """Returns ``run(net, pstate) -> (net, pstate, MetricsCarry)``
    advancing `ms` milliseconds as one `lax.scan` with the recorder in
    the carry — the instrumented twin of
    `scan_chunk(protocol, ms, superstep=K)`.  K-window bodies record
    once per window with ``n_steps=K`` (sampling granularity is the
    window; `stat_each_ms` must be a multiple of K so rows never
    straddle one — same convention as the batched fused-pair engine)."""
    check_chunk_config(protocol, ms, superstep=superstep)
    _check_superstep_interval(spec, superstep)
    k = superstep

    def run(net, pstate):
        mc = init_metrics(spec, ms, net.time)

        def body(carry, _):
            if k == 1:
                return step_ms_metrics(protocol, spec, *carry), ()
            net, ps, mc = carry
            net, ps = step_kms(protocol, net, ps, k)
            return (net, ps, record_step(spec, mc, net, n_steps=k)), ()

        (net2, p2, mc), _ = jax.lax.scan(body, (net, pstate, mc),
                                         length=ms // k)
        return net2, p2, mc

    return run


def fast_forward_chunk_metrics(protocol, ms: int, spec: MetricsSpec,
                               seed_axis: bool = False,
                               superstep: int = 1):
    """Instrumented twin of `fast_forward_chunk`: returns
    ``run(net, pstate) -> (net, pstate, stats, MetricsCarry)``.  Jumps
    land in the `ff_skipped_ms`/`ff_jumps` columns of their origin
    interval; intervals wholly inside a quiet window keep
    ``samples == 0`` (host-side forward fill — exact, since a skipped
    ms is a no-op step).  ``seed_axis=True`` mirrors the engine's
    vmap-batched mode: per-seed recorders (series ``[R, T, K]``),
    lockstep rows.  ``superstep=K`` fuses the loop body into K-ms
    windows with K-aligned jumps, recording once per window."""
    check_chunk_config(protocol, ms, superstep=superstep,
                       fast_forward=True)
    _check_superstep_interval(spec, superstep)
    cfg, k = protocol.cfg, superstep

    def run(net, pstate):
        t0 = net.time[0] if seed_axis else net.time
        t_end = t0 + ms
        if seed_axis:
            r = net.time.shape[0]
            mc0 = jax.vmap(lambda t: init_metrics(spec, ms, t))(net.time)
        else:
            mc0 = init_metrics(spec, ms, net.time)

        def cond(carry):
            t = carry[0].time[0] if seed_axis else carry[0].time
            return t < t_end

        def body(carry):
            net, ps, mc, skipped, jumps = carry
            if seed_axis:
                net, ps = jax.vmap(
                    lambda n_, p_: step_kms(protocol, n_, p_, k))(net, ps)
                mc = jax.vmap(
                    lambda m_, n_: record_step(spec, m_, n_, n_steps=k))(
                    mc, net)
                t1 = net.time[0]
                nw = jnp.min(jax.vmap(
                    lambda n_, p_: next_work(protocol, n_, p_, t1))(
                    net, ps))
            else:
                net, ps = step_kms(protocol, net, ps, k)
                mc = record_step(spec, mc, net, n_steps=k)
                t1 = net.time
                nw = next_work(protocol, net, ps, t1)
            dt = jnp.clip(nw, t1, t_end) - t1
            if k > 1:
                dt = dt - dt % k          # keep entry times K-aligned
            net = _jump(cfg, net, dt, t1 + dt)
            if seed_axis:
                mc = jax.vmap(
                    lambda m_: record_jump(spec, m_, t1, dt))(mc)
            else:
                mc = record_jump(spec, mc, t1, dt)
            return (net, ps, mc, skipped + dt,
                    jumps + (dt > 0).astype(jnp.int32))

        z = jnp.asarray(0, jnp.int32)
        net, pstate, mc, skipped, jumps = jax.lax.while_loop(
            cond, body, (net, pstate, mc0, z, z))
        return net, pstate, {"skipped_ms": skipped,
                             "jump_count": jumps}, mc

    return run


def _check_batched(protocol, ms: int, spec: MetricsSpec,
                   superstep: int = 2):
    if (superstep < 2 or ms % superstep or protocol.cfg.spill_cap
            or protocol.cfg.bcast_slots
            or not superstep_ok(protocol, superstep)):
        raise ValueError("the batched metrics builders need a chunk that "
                         f"is a multiple of superstep={superstep} (>= 2) "
                         "and a spill-free, broadcast-free, superstep-"
                         "eligible protocol (core/batched.py scope)")
    _check_superstep_interval(spec, superstep)


def scan_chunk_batched_metrics(protocol, ms: int, spec: MetricsSpec,
                               plane_barrier: bool = True,
                               superstep: int = 2):
    """Instrumented twin of `scan_chunk_batched`: per-seed recorders
    over the seed-folded fused engine; each `step_kms_batched` pass
    records once with ``n_steps=K`` (sampling granularity is the fused
    window — `stat_each_ms` must be a multiple of K, so rows never
    straddle one)."""
    _check_batched(protocol, ms, spec, superstep)
    k = superstep

    def run(net, pstate):
        mc0 = jax.vmap(lambda t: init_metrics(spec, ms, t))(net.time)

        def body(carry, _):
            net, ps, mc = carry
            net, ps = step_kms_batched(protocol, net, ps, k,
                                       plane_barrier=plane_barrier)
            mc = jax.vmap(
                lambda m_, n_: record_step(spec, m_, n_, n_steps=k))(
                mc, net)
            return (net, ps, mc), ()

        (net2, p2, mc), _ = jax.lax.scan(body, (net, pstate, mc0),
                                         length=ms // k)
        return net2, p2, mc

    return run


def fast_forward_chunk_batched_metrics(protocol, ms: int,
                                       spec: MetricsSpec,
                                       plane_barrier: bool = True,
                                       superstep: int = 2):
    """Instrumented twin of `fast_forward_chunk_batched` (batch-min
    oracle, K-aligned jumps): returns ``run(net, pstate) ->
    (net, pstate, stats, MetricsCarry)`` with per-seed recorders."""
    check_chunk_config(protocol, ms, superstep=superstep,
                       fast_forward=True)
    _check_batched(protocol, ms, spec, superstep)
    if not fast_forward_ok(protocol):
        raise ValueError("fast_forward_chunk_batched_metrics needs a "
                         "protocol implementing next_action_time — same "
                         "precondition as the uninstrumented engine")
    from ..core.batched import _next_work_batched
    k = superstep

    def run(net, pstate):
        t_end = net.time[0] + ms
        mc0 = jax.vmap(lambda t: init_metrics(spec, ms, t))(net.time)

        def cond(carry):
            return carry[0].time[0] < t_end

        def body(carry):
            net, ps, mc, skipped, jumps = carry
            net, ps = step_kms_batched(protocol, net, ps, k,
                                       plane_barrier=plane_barrier)
            mc = jax.vmap(
                lambda m_, n_: record_step(spec, m_, n_, n_steps=k))(
                mc, net)
            t1 = net.time[0]
            nw = jnp.clip(_next_work_batched(protocol, net, ps, t1),
                          t1, t_end)
            dt = (nw - t1) - (nw - t1) % k    # keep entry times K-aligned
            net = net.replace(time=net.time + dt)
            mc = jax.vmap(lambda m_: record_jump(spec, m_, t1, dt))(mc)
            return (net, ps, mc, skipped + dt,
                    jumps + (dt > 0).astype(jnp.int32))

        z = jnp.asarray(0, jnp.int32)
        net, pstate, mc, skipped, jumps = jax.lax.while_loop(
            cond, body, (net, pstate, mc0, z, z))
        return net, pstate, {"skipped_ms": skipped,
                             "jump_count": jumps}, mc

    return run
