"""The matrix driver: a planned grid through the serve `Scheduler`.

Groups run CONTIGUOUSLY in plan order; within a group, cells are
submitted in waves of at most `max_wave` cells and drained — every
wave after the first is a registry HIT (same compile key, seeds are
data), so the whole group runs on the programs the first wave built
while wave batching bounds the coalesced lane width (a thousand
single-seed cells never concatenate into one thousand-lane state).
Retry-with-backoff, batch-width degradation and chunk-boundary
checkpoint/resume all ride along for free — they are `Scheduler`
properties (PR 10), not driver ones.

The driver ASSERTS the compile-key-minimal contract: with a cold
registry, program builds after the run must equal the plan's
`expected_builds` (one build per (compile key, obs plane) — see
planner.py's vocabulary note); a warm registry may only build fewer.
A violated assertion is a scheduling bug, raised loudly rather than
recorded.

Per-cell `RunManifest` ledger rows are labelled ``matrix:<cell id>``
and carry the grid digest + axis labels in `extra`, so a sweep's
provenance is one ``grep grid_digest`` over the ledger.
"""

from __future__ import annotations

import dataclasses
import time

from ..serve.scheduler import Scheduler
from .grid import SweepGrid
from .planner import MatrixPlan, plan
from .report import MatrixReport


@dataclasses.dataclass
class MatrixRun:
    """One grid run: the report artifact plus the in-memory per-cell
    products the artifact deliberately leaves out (full obs blocks,
    kept final states for bit-identity verification)."""

    report: MatrixReport
    artifacts: dict                 # cell id -> scheduler artifacts
    states: dict                    # cell id -> final (net, ps) slices
    requests: dict                  # cell id -> request id


def _drain(sch: Scheduler, rids: list, poll_s: float = 0.05):
    """Drive the scheduler until every request settles.  `run_pending`
    is single-drainer (a concurrent service worker may own the drain);
    polling statuses instead of trusting our own processed count keeps
    the driver correct in both in-process and service-threaded use."""
    ins = getattr(sch, "_ins", None)
    t0 = 0.0 if ins is None else ins.now()
    while True:
        sch.run_pending()
        statuses = []
        for rid in rids:
            req = sch.peek(rid)
            # evicted == already-done (keep_done retention bound)
            statuses.append("done" if req is None else req.status)
        if all(s in ("done", "error") for s in statuses):
            if ins is not None:
                from ..serve.instrument import GRID_DRAIN
                ins.end(GRID_DRAIN, t0, n=len(rids))
            return
        time.sleep(poll_s)


def _harvest(sch: Scheduler, pairs, results, artifacts, states,
             keep_all, keep) -> int:
    """Pull settled requests into the per-cell result tables
    (IMMEDIATELY after each drain: the scheduler's keep_done eviction
    may drop finished records once later waves pile up).  Returns the
    number of cells done."""
    ins = getattr(sch, "_ins", None)
    t0 = 0.0 if ins is None else ins.now()
    done = 0
    for cell, rid in pairs:
        req = sch.peek(rid)
        if req is None:
            results[cell.id] = {
                "status": "error",
                "error": "request evicted before harvest "
                         "(raise Scheduler keep_done above max_wave)"}
            continue
        if req.status == "done":
            results[cell.id] = {"status": "done",
                                "artifacts": req.artifacts}
            artifacts[cell.id] = req.artifacts
            if keep_all or cell.id in keep:
                states[cell.id] = req.final_state
            done += 1
        else:
            results[cell.id] = {"status": "error",
                                "error": req.error or req.status}
    if ins is not None:
        from ..serve.instrument import GRID_HARVEST
        ins.end(GRID_HARVEST, t0, n=len(pairs), done=done)
    return done


def _row_artifacts(row) -> dict:
    """Rebuild the artifact subset a `MatrixReport` cell row needs
    from a finished cell's `RunManifest` ledger row (the durable
    completion facts `Scheduler._finalize` rides in `extra`).  The
    resulting report row is IDENTICAL to the live run's — summary,
    audit verdict/violations and the time_to_done headline were all
    computed once at finalize from the same blocks."""
    ex = row.extra or {}
    art = {"summary": dict(ex["summary"]), "from_ledger": True,
           "ledger_row": row.run}
    if row.audit_clean is not None:
        art["audit"] = {"clean": bool(row.audit_clean),
                        "violations": dict(ex.get("violations", {}))}
    if ex.get("time_to_done_ms") is not None:
        art["time_to_done_ms"] = int(ex["time_to_done_ms"])
    if ex.get("forked_from"):
        # fork provenance survives the ledger round trip, so a resumed
        # campaign's report rows stay identical to the live run's
        art["forked_from"] = dict(ex["forked_from"])
    return art


def _load_resume(plan_: MatrixPlan, sch: Scheduler, ledger_path):
    """The campaign-resume join (run_grid(resume=True)): per-group
    checkpoints re-enqueued through `Scheduler.resume_checkpoints`
    (spec digests verified file-side), the scheduler's durable
    submission journal replayed through `Scheduler.resume_journal`
    (queued-but-never-launched cells survive the kill too), plus
    finished-cell ledger rows keyed on the grid digest — and, for
    cells not in THIS grid's rows, a cross-grid dedup by exact config
    digest.  Returns ``(served, pre, counts)``: ledger-served results
    by cell id, checkpoint/journal-requeued (cell, rid) pairs, and the
    resume accounting.  Refuses LOUDLY (ValueError with remedy) on
    checkpoints from a different grid or cells whose spec no longer
    digests to the checkpointed one — silently mixing trajectories of
    two different campaigns is the one thing resume must never do."""
    from ..obs import ledger as ledger_mod

    cells_by_id = {c.id: c for c in plan_.cells}
    rids = sch.resume_checkpoints()
    # mid-flight MEMO PREFIX checkpoints are withdrawn, not resumed:
    # the killed process took the prefix's pre-crash obs carries with
    # it, and a prefix resumed without them could not stitch full-span
    # artifacts for its forked cells — the prefix re-runs (or table-
    # hits) instead, which is cheap relative to the campaign it saves
    prefix_rids = [rid for rid in rids
                   if (sch.request(rid).ledger_extra or {}
                       ).get("memo_prefix")]
    if prefix_rids:
        drop_keys = set()
        for rid in prefix_rids:
            req = sch.request(rid)
            if (req.ledger_extra or {}).get("grid_digest") \
                    == plan_.grid_digest:
                # only this grid's prefix files are discarded — a
                # foreign campaign's checkpoint stays for ITS resume
                drop_keys.add(req.compile_key)
        sch.withdraw(prefix_rids)
        for key in drop_keys:
            sch.discard_checkpoint(key)
        rids = [rid for rid in rids if rid not in set(prefix_rids)]
    # the durable submission journal: cells that were ACCEPTED but
    # never launched (no checkpoint, no ledger row) replay here —
    # entries a checkpoint already restored are skipped by rid inside
    # resume_journal.  This grid's replayed CELLS are adopted below
    # exactly like checkpoint-requeued ones (they re-run their full
    # span from scratch, bit-identically); replayed memo-PREFIX
    # entries are withdrawn (the fork machinery re-runs or table-hits
    # them); entries from OTHER campaigns stay queued — they are that
    # campaign's durable submits, and the drain completes them with
    # their own ledger rows.
    journal_rids = sch.resume_journal()
    adopt, foreign = [], 0
    for rid in journal_rids:
        ex = sch.request(rid).ledger_extra or {}
        if ex.get("grid_digest") == plan_.grid_digest:
            if ex.get("memo_prefix"):
                sch.withdraw([rid])
            else:
                adopt.append(rid)
        else:
            foreign += 1
    if foreign:
        import sys
        print(f"matrix resume: {foreign} journal-replayed request(s) "
              "belong to other campaigns; left queued for their own "
              "resume/drain", file=sys.stderr)
    rids = rids + adopt
    pre = []
    try:
        for rid in rids:
            req = sch.request(rid)
            ex = req.ledger_extra or {}
            cid = ex.get("cell")
            if ex.get("grid_digest") != plan_.grid_digest \
                    or cid not in cells_by_id:
                raise ValueError(
                    f"matrix resume: checkpoint request {rid} belongs "
                    f"to grid {ex.get('grid_digest')!r} / cell "
                    f"{cid!r}, not this grid ({plan_.grid_digest}). "
                    "Fix: point --checkpoint-dir at the directory "
                    "this grid's interrupted run used, or delete the "
                    "stale checkpoints to restart those groups from "
                    "scratch")
            want = cells_by_id[cid].spec.digest()
            got = (req.requested or req.spec).digest()
            if got != want:
                raise ValueError(
                    f"matrix resume: cell {cid!r} now digests to "
                    f"{want} but its checkpoint was written for {got} "
                    "— the spec was edited since the interrupted run. "
                    "Fix: restore the original grid, or delete the "
                    "stale checkpoint to re-run the cell under the "
                    "new spec")
            pre.append((cells_by_id[cid], rid))
    except ValueError:
        # roll back EVERY re-enqueued request before refusing: on a
        # shared scheduler, valid earlier files' requests left queued
        # would run with no harvester (wasted device time + surprise
        # ledger rows)
        sch.withdraw(rids)
        raise
    requeued = {c.id for c, _ in pre}
    by_cell: dict = {}
    by_digest: dict = {}
    for row in ledger_mod.read_all(ledger_path):
        ex = row.extra or {}
        if "summary" not in ex or row.audit_clean is False:
            continue        # unclean / pre-r15 rows cannot serve cells
        if ex.get("grid_digest") == plan_.grid_digest and ex.get("cell"):
            by_cell[ex["cell"]] = row
        by_digest.setdefault(row.config_digest, row)
    served: dict = {}
    counts = {"from_ledger": 0, "deduped": 0,
              "resumed_requests": len(pre),
              "journal_replayed": len(adopt)}
    for cell in plan_.cells:
        if cell.id in requeued:
            continue        # mid-flight, not finished — must re-run
        dig = cell.spec.digest()
        row, dedup = by_cell.get(cell.id), False
        if row is not None and row.config_digest != dig:
            row = None      # same id, edited spec: never serve stale
        if row is None:
            row, dedup = by_digest.get(dig), True
        if row is None:
            continue
        served[cell.id] = {"status": "done",
                           "artifacts": _row_artifacts(row)}
        counts["deduped" if dedup else "from_ledger"] += 1
    return served, pre, counts


def _run_prefixes(sch: Scheduler, plan_: MatrixPlan, fplan, table,
                  stats: dict, max_wave: int) -> dict:
    """The memo fork phase: run (or table-load) every fork group's
    honest prefix ONCE through the scheduler, then hand each cell its
    `ForkState` (state + obs carries + fork point + prefix digest).
    Prefix requests coalesce among themselves like any other same-key
    submissions; waves bound how many finished prefix states sit in
    the scheduler's done table at once (its keep_done eviction must
    never race the harvest).  Returns ``{cell id: ForkState}``."""
    forks: dict = {}
    run_groups = []
    for fg in fplan.groups:
        chunk = fg.prefix_spec.chunk_ms
        if table is not None:
            hit = table.get(fg.prefix_spec)
            if hit is not None:
                state, carries = hit
                stats["table_hits"] += 1
                served = _assign_forks(forks, fg, plan_, state, carries,
                                       stats)
                stats["prefix_chunks_saved"] += \
                    served * (fg.fork_ms // chunk)
                continue
        run_groups.append(fg)
    for lo in range(0, len(run_groups), max_wave):
        wave = run_groups[lo:lo + max_wave]
        pending = []
        for fg in wave:
            rid = sch.submit(
                fg.prefix_spec,
                label=f"memo:prefix:{fg.prefix_digest[:8]}",
                ledger_extra={"grid_digest": plan_.grid_digest,
                              "memo_prefix": fg.prefix_digest},
                keep_carries=True)
            pending.append((fg, rid))
        _drain(sch, [rid for _, rid in pending])
        for fg, rid in pending:
            req = sch.peek(rid)
            if req is None or req.status != "done":
                stats["prefix_failed"] += 1
                continue        # cells fall back to the unforked path
            stats["prefix_runs"] += 1
            chunk = fg.prefix_spec.chunk_ms
            state, carries = req.final_state, req.final_carries or {}
            if table is not None:
                table.put(fg.prefix_spec, state, carries)
            served = _assign_forks(forks, fg, plan_, state, carries,
                                   stats)
            # honest accounting: the prefix itself cost fork_chunks,
            # each forked cell saves them (a fully-vetoed group goes
            # NEGATIVE — the prefix ran for nothing)
            stats["prefix_chunks_saved"] += \
                (served - 1) * (fg.fork_ms // chunk)
    return forks


def _assign_forks(forks: dict, fg, plan_: MatrixPlan, state, carries,
                  stats: dict) -> int:
    """Hand one completed prefix to its cells, gated per cell by the
    runtime chaos-no-op soundness check (memo/prefix.py); a vetoed
    cell runs unforked.  Returns how many cells were forked."""
    from ..memo import chaos_noop_before_fork
    from ..serve.scheduler import ForkState

    served = 0
    for cid in fg.cells:
        if cid not in plan_.resolved:
            continue
        if not chaos_noop_before_fork(plan_.resolved[cid], state,
                                      fg.fork_ms):
            stats["fork_vetoed"] += 1
            continue
        forks[cid] = ForkState(
            state=state,
            carries={p: list(cs) for p, cs in carries.items()},
            at_ms=fg.fork_ms, prefix_digest=fg.prefix_digest)
        served += 1
    stats["forked_cells"] += served
    return served


def run_grid(grid: SweepGrid, scheduler: Scheduler | None = None,
             plan_: MatrixPlan | None = None, *, ledger_path=None,
             checkpoint_dir=None, journal_dir=None, max_wave: int = 64,
             keep_states=("*",), progress=None,
             strict_builds: bool = True,
             resume: bool = False, memo=None,
             workers: int | None = None, fleet_dir=None,
             fleet_opts: dict | None = None) -> MatrixRun:
    """Run every cell of `grid` (module docstring) and build the
    `MatrixReport`.

    keep_states — cell ids whose final (net, pstate) slices to retain
        for bit-identity verification ("*" keeps all; device memory
        scales with it, so thousand-cell campaigns pass a pinned
        subset).
    progress    — optional callback(dict) at every wave boundary:
        cells done/total, groups done, program builds so far, wall.
    strict_builds — raise when measured registry builds disagree with
        the plan (the compile-key-minimal contract).  The measurement
        is the registry's GLOBAL miss counter, so it can only be
        attributed to this run when the scheduler is ours alone; pass
        False when sharing a scheduler with concurrent traffic (the
        service's auto mode) — the report still records the measured
        delta, it just can't be an assertion there.
    resume      — end-to-end campaign resume: re-enqueue this grid's
        per-group checkpoints (the scheduler needs the interrupted
        run's `checkpoint_dir`), serve already-finished cells from
        their ledger rows (keyed on the grid digest; an exact config-
        digest match from ANOTHER grid is served too and counted as
        `deduped`), and re-plan only the unfinished cells.  Refuses
        loudly on spec/digest mismatches with stale checkpoints.  The
        resulting report's cell rows are bit-identical to an
        uninterrupted run's (tests/test_matrix.py kill-mid-campaign
        pin); the run-local accounting (wall, program_builds, the
        `resume` block) honestly differs.
    memo        — memoized supersteps (wittgenstein_tpu/memo; True, a
        `MemoConfig`, or a dict of its fields): cells differing only
        in post-fork adversity share ONE honest-prefix run and fork
        from its chunk-boundary state (+ obs carries), bit-identical
        to unforked runs; a configured `table` additionally reuses
        completed prefixes ACROSS runs (content-addressed on-disk
        store).  The report grows a `memo` block (prefix runs, table
        hits, `prefix_chunks_saved` — matching the fork plan's
        prediction on a veto-free cold-table run) and forked cell rows
        carry `forked_from` provenance.
    workers     — fleet mode (PR 17): enqueue every cell into the
        shared fleet journal and complete the campaign with N worker
        PROCESSES over `fleet_dir` (serve/fleet.py's directory-sharing
        contract) instead of this process's scheduler.  Results come
        back through the shared-ledger join, so the report's cell rows
        are bit-identical to a single-process run's; final states stay
        in the worker processes (`MatrixRun.states` is empty — pass
        cells through tools/matrix.py --spot-check for verification).
        `fleet_opts` forwards run_grid_fleet keywords (lease_ttl_s,
        timeout_s, on_spawned, ...).
    """
    if workers is not None:
        if scheduler is not None or resume or memo:
            raise ValueError(
                "run_grid(workers=N) is a separate-process fleet: it "
                "cannot reuse an in-process scheduler, and resume/memo "
                "are single-process drivers (the fleet serves finished "
                "cells from the shared ledger automatically). Fix: "
                "drop workers=, or drop scheduler=/resume=/memo=")
        if fleet_dir is None:
            raise ValueError(
                "run_grid(workers=N) needs fleet_dir= — the one shared "
                "directory every worker process derives journal/"
                "checkpoints/ledger paths from (serve.fleet_paths)")
        return run_grid_fleet(grid, plan_, fleet_dir=fleet_dir,
                              workers=workers, progress=progress,
                              **dict(fleet_opts or {}))
    plan_ = plan_ or plan(grid)
    sch = scheduler or Scheduler(ledger_path=ledger_path,
                                 checkpoint_dir=checkpoint_dir,
                                 journal_dir=journal_dir)
    keep_all = "*" in keep_states
    keep = set(keep_states)
    stats0 = sch.registry.stats()
    cold = stats0["entries"] == 0
    t0 = time.time()
    results: dict = {}
    artifacts: dict = {}
    states: dict = {}
    requests: dict = {}
    done_cells = 0
    resume_counts = None
    groups = plan_.groups
    expected_builds = plan_.expected_builds
    mcfg = table = None
    memo_stats = None
    forks: dict = {}
    if memo:
        from ..memo import MemoConfig
        mcfg = MemoConfig.coerce(memo)
        table = mcfg.open_table()
    if resume:
        served, pre, resume_counts = _load_resume(
            plan_, sch, ledger_path or sch.ledger_path)
        results.update(served)
        done_cells += len(served)
        # the resumed run's build CEILING: ledger-served groups never
        # compile; checkpoint-requeued groups do (during the pre-drain
        # below, inside this run's accounting window) and so stay in
        # the ceiling
        expected_builds = sum(
            g.builds for g in plan_.remaining(set(served)))
        # drive the checkpoint-requeued groups to completion first —
        # they re-enter mid-flight and harvest like any other cell
        if pre:
            requests.update({c.id: rid for c, rid in pre})
            _drain(sch, [rid for _, rid in pre])
            done_cells += _harvest(sch, pre, results, artifacts,
                                   states, keep_all, keep)
        groups = plan_.remaining(set(results))
    if mcfg is not None and mcfg.fork:
        from ..memo import plan_prefixes
        fplan = plan_prefixes(plan_, min_cells=mcfg.min_cells,
                              done_ids=set(results),
                              include_singles=table is not None)
        memo_stats = {"fork_groups": len(fplan.groups),
                      "predicted_chunks_saved":
                      fplan.predicted_chunks_saved,
                      "prefix_runs": 0, "prefix_failed": 0,
                      "table_hits": 0, "forked_cells": 0,
                      "fork_vetoed": 0, "prefix_chunks_saved": 0}
        # build-accounting ceiling: a prefix whose compile key is new
        # to the plan (no clean sibling in the grid) adds its own
        # program builds; a prefix sharing a plan key just performs
        # that group's builds EARLY (the group then registry-hits)
        plan_keys = {g.compile_key for g in groups}
        seen = set()
        for fg in fplan.groups:
            if fg.prefix_key not in plan_keys \
                    and fg.prefix_key not in seen:
                seen.add(fg.prefix_key)
                expected_builds += fg.prefix_builds
        forks = _run_prefixes(sch, plan_, fplan, table, memo_stats,
                              max_wave)
        if table is not None:
            memo_stats["table"] = table.stats()
    ins = getattr(sch, "_ins", None)
    for gi, group in enumerate(groups):
        cells = list(group.cells)
        for lo in range(0, len(cells), max_wave):
            wave = cells[lo:lo + max_wave]
            t_sub = 0.0 if ins is None else ins.now()
            rids = []
            for cell in wave:
                try:
                    # the AS-AUTHORED cell spec, not the resolved one:
                    # provenance digests what the grid requested (the
                    # serve convention); submit re-validates cheaply
                    rid = sch.submit(
                        cell.spec,
                        label=f"matrix:{cell.id}",
                        ledger_extra={"grid_digest": plan_.grid_digest,
                                      "cell": cell.id,
                                      "axes": dict(cell.labels)},
                        fork=forks.get(cell.id))
                except ValueError as e:     # plan validated; belt and
                    # braces for env drift between plan and run
                    results[cell.id] = {"status": "error",
                                        "error": str(e)}
                    continue
                requests[cell.id] = rid
                rids.append((cell, rid))
            if ins is not None:
                from ..serve.instrument import GRID_SUBMIT
                ins.end(GRID_SUBMIT, t_sub, key=group.compile_key,
                        n=len(rids))
            _drain(sch, [rid for _, rid in rids])
            done_cells += _harvest(sch, rids, results, artifacts,
                                   states, keep_all, keep)
            if progress is not None:
                reg = sch.registry.stats()
                progress({"done": done_cells,
                          "total": len(plan_.cells),
                          "errors": sum(1 for r in results.values()
                                        if r["status"] == "error"),
                          "groups_done": gi + (1 if lo + max_wave >=
                                               len(cells) else 0),
                          "groups_total": len(groups),
                          "planned_compiles": plan_.planned_compiles,
                          "program_builds": reg["misses"]
                          - stats0["misses"],
                          "wall_s": round(time.time() - t0, 3)})
    wall = time.time() - t0
    reg = sch.registry.stats()
    builds = reg["misses"] - stats0["misses"]
    # the compile-key-minimal contract, ASSERTED (module docstring).
    # An errored cell may legitimately leave its group's programs
    # unbuilt (builds < expected), so the exact-equality check only
    # applies to fully-clean cold runs — errored cells are the
    # report's/CLI's exit-1 story, not a scheduling bug.  A resumed
    # run asserts only the CEILING, but against its narrowed
    # expected_builds (live + checkpoint-requeued groups): a served
    # group that somehow re-compiles is a scheduling bug there too.
    clean = all(r["status"] == "done" for r in results.values())
    # a memo-table hit or a failed prefix legitimately leaves prefix
    # programs unbuilt: the exact-equality contract only applies when
    # every planned program (cells + prefixes) actually ran cold
    memo_partial = bool(memo_stats) and (
        memo_stats["table_hits"] or memo_stats["prefix_failed"])
    if strict_builds and cold and clean and not resume \
            and not memo_partial and builds != expected_builds:
        raise RuntimeError(
            f"matrix: compile-key-minimal contract violated — "
            f"{builds} program builds for {expected_builds} "
            f"expected ({plan_.planned_compiles} distinct compile "
            "keys); a group was re-built mid-run")
    if strict_builds and builds > expected_builds:
        raise RuntimeError(
            f"matrix: {builds} program builds exceed the "
            f"{'resume-narrowed ' if resume else ''}expected "
            f"{expected_builds} even on a warm registry")
    report = MatrixReport.build(
        plan_, results, wall_s=wall,
        compiles={"program_builds": builds,
                  "distinct_compile_keys": plan_.planned_compiles,
                  "registry": reg},
        scheduler_stats=sch.resilience,
        resume=resume_counts, memo=memo_stats)
    return MatrixRun(report=report, artifacts=artifacts, states=states,
                     requests=requests)


# ------------------------------------------------------------ fleet mode


def _fleet_join(plan_: MatrixPlan, ledger_path):
    """One scan of the shared ledger -> ``(by_cell, by_digest)`` clean
    summary-bearing rows (the `_load_resume` join, re-read every poll
    because worker processes append concurrently)."""
    from ..obs import ledger as ledger_mod

    by_cell: dict = {}
    by_digest: dict = {}
    for row in ledger_mod.read_all(ledger_path):
        ex = row.extra or {}
        if "summary" not in ex or row.audit_clean is False:
            continue
        if ex.get("grid_digest") == plan_.grid_digest and ex.get("cell"):
            by_cell.setdefault(ex["cell"], row)
        by_digest.setdefault(row.config_digest, row)
    return by_cell, by_digest


def fleet_enqueue(plan_: MatrixPlan, fleet_dir) -> dict:
    """Append one durable journal entry per not-yet-finished cell of
    the grid (fsync'd submit rows — the fleet's shared work queue) and
    return ``{cell id: rid}`` for the cells enqueued.  Cells already
    served by a clean ledger row, or already live in the journal from
    an interrupted fleet run of the SAME grid, are skipped — re-running
    a campaign driver over an existing fleet directory resumes it."""
    import uuid

    from ..serve.fleet import fleet_paths
    from ..serve.journal import SubmissionJournal

    paths = fleet_paths(fleet_dir)
    journal = SubmissionJournal(paths["journal_dir"])
    by_cell, by_digest = _fleet_join(plan_, paths["ledger_path"])
    live = {}
    for e in journal.replay():
        ex = e.get("ledger_extra") or {}
        if ex.get("grid_digest") == plan_.grid_digest and ex.get("cell"):
            live[ex["cell"]] = e["rid"]
    nonce = uuid.uuid4().hex[:8]
    rids = {}
    for i, cell in enumerate(plan_.cells):
        if cell.id in by_cell or cell.spec.digest() in by_digest:
            continue                    # the row IS the result
        if cell.id in live:
            rids[cell.id] = live[cell.id]
            continue                    # survivor of an interrupted run
        rid = f"mx{nonce}-{i:04d}"
        journal.record_submit(
            rid, cell.spec, label=f"matrix:{cell.id}",
            ledger_extra={"grid_digest": plan_.grid_digest,
                          "cell": cell.id, "axes": dict(cell.labels)})
        rids[cell.id] = rid
    return rids


def fleet_wait(plan_: MatrixPlan, fleet_dir, *, procs=(),
               timeout_s: float = 900.0, poll_s: float = 0.5,
               progress=None) -> dict:
    """Poll the shared ledger until every cell of the grid has a clean
    row (or a quarantine tombstone), building the per-cell results
    table.  Raises RuntimeError when every worker process has exited
    with cells still unserved (their logs are named), or on timeout —
    a wedged fleet must fail loudly, not hang a campaign forever."""
    from ..serve.fleet import fleet_paths
    from ..serve.journal import SubmissionJournal

    paths = fleet_paths(fleet_dir)
    journal = SubmissionJournal(paths["journal_dir"])
    cells = plan_.cells
    t0 = time.time()
    saw_all_exited = False
    while True:
        by_cell, by_digest = _fleet_join(plan_, paths["ledger_path"])
        results: dict = {}
        counts = {"from_ledger": 0, "deduped": 0, "quarantined": 0}
        for cell in cells:
            row = by_cell.get(cell.id)
            dedup = False
            if row is None:
                row, dedup = by_digest.get(cell.spec.digest()), True
            if row is not None:
                results[cell.id] = {"status": "done",
                                    "artifacts": _row_artifacts(row)}
                counts["deduped" if dedup else "from_ledger"] += 1
        # a quarantined entry never grows a ledger row — surface it as
        # the cell's error instead of waiting for the timeout
        for rid, st in journal.settled().items():
            if st != "quarantined":
                continue
            ex = (journal.lookup(rid) or {}).get("ledger_extra") or {}
            cid = ex.get("cell")
            if ex.get("grid_digest") == plan_.grid_digest \
                    and cid and cid not in results:
                results[cid] = {
                    "status": "error",
                    "error": f"fleet: entry {rid} quarantined (poison "
                             "lane) — see the workers' logs"}
                counts["quarantined"] += 1
        if progress is not None:
            progress({"done": len(results), "total": len(cells),
                      "journal_lag": journal.lag(),
                      "wall_s": round(time.time() - t0, 3)})
        if len(results) == len(cells):
            return {"results": results, "counts": counts}
        if procs and all(p.poll() is not None for p in procs):
            if not saw_all_exited:
                # one more immediate join: a worker may have appended
                # the final ledger row just after this poll's scan
                saw_all_exited = True
                continue
            missing = [c.id for c in cells if c.id not in results]
            logs = sorted({getattr(p, "log_path", "?") for p in procs})
            raise RuntimeError(
                f"fleet: all {len(procs)} worker process(es) exited "
                f"with {len(missing)} cell(s) unserved "
                f"({missing[:4]}{'...' if len(missing) > 4 else ''}). "
                f"Worker logs: {logs}")
        if time.time() - t0 > timeout_s:
            missing = [c.id for c in cells if c.id not in results]
            raise RuntimeError(
                f"fleet: campaign incomplete after {timeout_s:.0f}s — "
                f"{len(missing)} cell(s) unserved ({missing[:4]}...). "
                "The journal entries survive; re-running the driver "
                "over the same fleet_dir resumes them")
        time.sleep(poll_s)


def run_grid_fleet(grid: SweepGrid, plan_: MatrixPlan | None = None, *,
                   fleet_dir, workers: int = 2,
                   lease_ttl_s: float = 10.0, idle_exit_s: float = 2.0,
                   poll_s: float = 0.5, timeout_s: float = 900.0,
                   progress=None, on_spawned=None,
                   spawn: bool = True, timeline=None) -> MatrixRun:
    """`run_grid(workers=N)`'s engine, decomposed (enqueue / spawn /
    wait / report) so tools/crash_test.py can SIGKILL workers between
    the pieces.  Enqueues the grid into the shared fleet journal,
    spawns `workers` worker subprocesses over `fleet_dir`, waits for
    the shared-ledger join to serve every cell, and builds the same
    `MatrixReport` a single-process run would — cell rows are ledger
    round-trips, bit-identical by the `_row_artifacts` contract; the
    run-local accounting (wall, aggregate program builds, the `resume`
    block's fleet counters) honestly differs and is exactly the
    volatile set crash_test normalizes away.

    `on_spawned(procs)` fires after the workers launch (the crash
    harness's kill hook); `spawn=False` skips launching (the caller
    runs its own workers).  A dead worker needs no respawn: its leases
    expire and survivors adopt its work (serve/fleet.py).  `timeline`
    (a directory) turns each worker's host-plane flight recorder ON —
    one ``spans-<worker>.jsonl`` per worker under it, a dead worker's
    torn tail included (tools/timeline.py renders them)."""
    from ..serve.fleet import aggregate_worker_stats, spawn_worker

    plan_ = plan_ or plan(grid)
    t0 = time.time()
    requests = fleet_enqueue(plan_, fleet_dir)
    procs = []
    if spawn:
        procs = [spawn_worker(fleet_dir, f"w{i}",
                              lease_ttl_s=lease_ttl_s,
                              idle_exit_s=idle_exit_s,
                              max_wall_s=timeout_s,
                              timeline=timeline)
                 for i in range(int(workers))]
    if on_spawned is not None:
        on_spawned(procs)
    try:
        waited = fleet_wait(plan_, fleet_dir, procs=procs,
                            timeout_s=timeout_s, poll_s=poll_s,
                            progress=progress)
    finally:
        # reap: workers idle-exit on their own once the journal is
        # fully settled (their final stats snapshot lands in their
        # `finally`); only a wedged/errored fleet gets terminated
        deadline = time.time() + max(10.0, 3 * idle_exit_s)
        for p in procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
        for p in procs:
            if p.poll() is None:
                p.terminate()
    results = waited["results"]
    agg = aggregate_worker_stats(fleet_dir)
    wall = time.time() - t0
    resume_counts = {
        "fleet_workers": int(workers),
        **waited["counts"],
        "resumed_requests": 0,
        "journal_replayed": agg["counters"].get("claimed", 0),
        "worker_deduped": agg["counters"].get("deduped", 0),
        "adopted_checkpoints": agg["counters"].get(
            "adopted_checkpoints", 0)}
    compiles = {"program_builds": agg["registry"].get("misses", 0),
                "distinct_compile_keys": plan_.planned_compiles,
                "registry": agg["registry"]}
    report = MatrixReport.build(
        plan_, results, wall_s=wall, compiles=compiles,
        scheduler_stats=agg["resilience"] or None,
        resume=resume_counts)
    artifacts = {cid: r["artifacts"] for cid, r in results.items()
                 if r.get("status") == "done"}
    return MatrixRun(report=report, artifacts=artifacts, states={},
                     requests=requests)


# ---------------------------------------------------------- verification


def _runner_reference(spec, seed):
    """One seed of a cell run twice through `Runner` (one obs plane per
    pass — bit-identical on the trajectory), chunked exactly like the
    scheduler: the tests/test_serve.py sequential-reference shape, the
    matrix's pinned-subset oracle."""
    import numpy as np

    from ..core.network import Runner
    from ..obs.audit import AuditSpec
    from ..obs.spec import MetricsSpec

    proto = spec.build_protocol()
    frame = audit = None
    runner = Runner(proto, donate=False, chunk_limit=spec.chunk_ms,
                    metrics=MetricsSpec(stat_each_ms=spec.stat_each_ms)
                    if "metrics" in spec.obs else None)
    net, ps = proto.init(np.int32(seed))
    if spec.partition:
        import jax.numpy as jnp
        idx = jnp.asarray(spec.partition, jnp.int32)
        net = net.replace(nodes=net.nodes.replace(
            down=net.nodes.down.at[idx].set(True)))
    net, ps = runner.run_ms(net, ps, spec.sim_ms)
    if "metrics" in spec.obs:
        frame = runner.metrics_frame()
    if "audit" in spec.obs:
        auditor = Runner(proto, donate=False, chunk_limit=spec.chunk_ms,
                         audit=AuditSpec())
        anet, aps = proto.init(np.int32(seed))
        if spec.partition:
            import jax.numpy as jnp
            idx = jnp.asarray(spec.partition, jnp.int32)
            anet = anet.replace(nodes=anet.nodes.replace(
                down=anet.nodes.down.at[idx].set(True)))
        auditor.run_ms(anet, aps, spec.sim_ms)
        audit = auditor.audit_report()
    return (net, ps), frame, audit


def verify_cell(spec, final_state, artifacts) -> list:
    """Bit-identity check of one matrix cell against per-seed `Runner`
    runs: full final pytree per lane, plus the metrics/audit blocks
    (exact for single-seed cells; seed-summed series/totals for wider
    ones, matching the blocks' own batch aggregation).  Returns
    human-readable mismatch strings — empty means bit-identical."""
    import jax
    import numpy as np

    mismatches = []
    spec = spec if isinstance(spec.superstep, int) else spec.validate()
    refs = [_runner_reference(spec, s) for s in spec.seeds]
    for i, (state, frame, audit) in enumerate(refs):
        lane = jax.tree.map(lambda x, i=i: x[i], final_state)
        for a, b in zip(jax.tree.leaves(lane), jax.tree.leaves(state)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                mismatches.append(
                    f"seed {spec.seeds[i]}: final-state pytree differs "
                    "from the sequential Runner run")
                break
    if "metrics" in spec.obs and "engine_metrics" in artifacts:
        blk = artifacts["engine_metrics"]
        frames = [f for _, f, _ in refs]
        if len(frames) == 1:
            from ..obs.export import engine_metrics_block
            ref_blk = engine_metrics_block(
                frames[0], extra={"metrics_seeds": 1})
            if blk != ref_blk:
                mismatches.append("engine_metrics block differs from "
                                  "the sequential reference")
        elif "series" in blk:
            for name in blk["series"]:
                if name == "time":
                    continue
                want = np.sum([f.column(name) for f in frames],
                              axis=0)
                if list(map(int, want)) != blk["series"][name]:
                    mismatches.append(
                        f"metrics series {name!r} != the seed-summed "
                        "sequential reference")
    if "audit" in spec.obs and "audit" in artifacts:
        blk = artifacts["audit"]
        audits = [a for _, _, a in refs]
        if len(audits) == 1:
            from ..obs.audit_report import audit_block
            ref_blk = audit_block(audits[0], extra={"audit_seeds": 1})
            if blk != ref_blk:
                mismatches.append("audit block differs from the "
                                  "sequential reference")
        else:
            want_totals = {
                k: sum(a.totals_dict()[k] for a in audits)
                for k in audits[0].totals_dict()}
            if blk["totals"] != want_totals:
                mismatches.append("audit totals != the seed-summed "
                                  "sequential reference")
            if blk["clean"] != all(a.clean for a in audits):
                mismatches.append("audit verdict differs from the "
                                  "sequential reference")
    return mismatches


def pick_spot_cells(cells, k: int) -> list:
    """A deterministic spread of `k` cell ids over the expansion order
    (first/last/evenly between) — the pinned verification subset."""
    if k <= 0 or not cells:
        return []
    k = min(k, len(cells))
    if k == 1:
        return [cells[0].id]
    idx = sorted({round(i * (len(cells) - 1) / (k - 1))
                  for i in range(k)})
    return [cells[i].id for i in idx]
