"""Tier-3 evidence run: 1M-node cardinal Handel.

Builds HandelCardinal at node_count=2^20 and either (a) GSPMD-shards the
node axis over an n-device virtual CPU mesh (the same layout
dryrun_multichip validates), or (b) with WTPU_CARDINAL_PLATFORM=tpu runs
single-device on the REAL chip (state 11.7 GB vs 16 GB HBM; the mailbox
ring is split into node-range sub-planes, EngineConfig.box_split, to
stay under the runtime's ~1 GB single-buffer execution limit).  Runs
>= 100 simulated ms and asserts zero drops/clamps/evictions.  Writes
reports/CARDINAL_<label>.md — every config-dependent value in the
report prose is derived from the live config (the r3 template hardcoded
them, which produced a mislabeled report; BENCH_NOTES.md postmortem).

Usage:  python tools/cardinal_1m.py [sim_ms]    (default 120)
Env:    WTPU_CARDINAL_N (default 2^20), WTPU_CARDINAL_DEVS (default 8),
        WTPU_CARDINAL_PLATFORM=tpu (real chip, forces DEVS=1),
        WTPU_CARDINAL_SPLIT (box_split override)
"""

import pathlib
import resource
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import os  # noqa: E402

from wittgenstein_tpu.utils.platform import force_virtual_cpu  # noqa: E402

ON_TPU = os.environ.get("WTPU_CARDINAL_PLATFORM") == "tpu"
# WTPU_CARDINAL_DEVS=1 runs unsharded on one device: the GSPMD pipeline
# at N=2^20 x 8 partitions needs more compile/exec workspace than this
# 125 GB host has; the 1-device run proves tier-3 state + engine at 1M,
# and the mesh path is separately proven at smaller N (dryrun equality)
# and at the largest N the host fits.
N_DEV = 1 if ON_TPU else int(os.environ.get("WTPU_CARDINAL_DEVS", 8))
if not ON_TPU:
    # 8 virtual devices time-slice ONE physical core here, so the
    # per-device compute between collectives (minutes at 1M nodes) far
    # exceeds XLA:CPU's default 40 s rendezvous termination timeout —
    # raise both timeouts; on a real 8-chip mesh devices run
    # concurrently and the skew disappears.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        " --xla_cpu_collective_call_warn_stuck_timeout_seconds=3600"
        " --xla_cpu_collective_call_terminate_timeout_seconds=86400"
    ).strip()
    force_virtual_cpu(N_DEV)

import jax                                         # noqa: E402
import jax.numpy as jnp                            # noqa: E402
import numpy as np                                 # noqa: E402
from jax.sharding import (Mesh, NamedSharding,     # noqa: E402
                          PartitionSpec as P)

from wittgenstein_tpu.core.network import scan_chunk   # noqa: E402
from wittgenstein_tpu.models.handel_cardinal import (  # noqa: E402
    HandelCardinal)


def main():
    import dataclasses
    import os
    sim_ms = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    n = int(os.environ.get("WTPU_CARDINAL_N", 1 << 20))   # override: smoke
    # horizon 128 keeps the flat mailbox ring under the int32 index limit
    # (128 * 2^20 * 4 entries per plane); NetworkUniformLatency(100)
    # keeps every arrival inside the ring, so nothing can clamp or drop.
    # horizon 96 > Uniform(90)'s max one-way latency + 2, so every
    # arrival fits the ring (nothing may clamp); the tighter ring plus
    # cardinal's 2-word messages keep the donated state ~13 GB on a
    # 15.75 GB chip (the hz128/3-word config measured 17.16 GB — OOM).
    # inbox default 4 sized for 1M HBM fit; at 131k traffic it measured
    # 86k drops over 200 sim-ms — override per run (the zero-drop assert
    # below is the arbiter).
    inbox_cap = int(os.environ.get("WTPU_CARDINAL_INBOX", 4))
    queue_cap = int(os.environ.get("WTPU_CARDINAL_QUEUE", 8))
    proto = HandelCardinal(
        node_count=n, threshold=int(0.99 * n), nodes_down=0,
        pairing_time=4, dissemination_period_ms=20, fast_path=10,
        queue_cap=queue_cap, inbox_cap=inbox_cap, horizon=96,
        network_latency_name="NetworkUniformLatency(90)")
    # Keep every ring sub-plane under the TPU runtime's ~1 GB
    # single-buffer execution limit (BENCH_NOTES.md r3): at 2^20 x hz128
    # x ic4 a monolithic plane is 2.1 GB -> split 4 ways (537 MB each).
    plane_bytes = 4 * proto.cfg.horizon * n * proto.cfg.inbox_cap
    min_split = max(1, -(-plane_bytes // (800 * 1024 * 1024)))
    # Round up to a power of two: box_split must divide the (power-of-two)
    # node count.
    pow2_split = 1 << (min_split - 1).bit_length()
    split = int(os.environ.get("WTPU_CARDINAL_SPLIT",
                               pow2_split if ON_TPU else 1))
    if split > 1:
        proto.cfg = dataclasses.replace(proto.cfg, box_split=split)

    devices = jax.devices()[:N_DEV]
    mesh = Mesh(np.array(devices), ("sp",))

    def shard_spec(x):
        # Single seed (no leading batch axis): shard any size-n axis over
        # 'sp'; flat ring arrays shard across their flat index space.
        matches = [i for i in range(x.ndim) if x.shape[i] == n]
        spec = [None] * x.ndim
        if matches:
            spec[matches[-1]] = "sp"
        elif x.ndim == 1 and x.shape[0] >= n and x.shape[0] % (n * N_DEV) == 0:
            spec[0] = "sp"
        return NamedSharding(mesh, P(*spec))

    t0 = time.perf_counter()
    net, ps = jax.jit(proto.init)(jnp.asarray(0, jnp.int32))
    int(jax.device_get(net.time))           # host copy = completion proof
    t_init = time.perf_counter() - t0
    print(f"init: {t_init:.1f}s", flush=True)

    net = jax.tree.map(lambda x: jax.device_put(x, shard_spec(x)), net)
    ps = jax.tree.map(lambda x: jax.device_put(x, shard_spec(x)), ps)

    state_bytes = sum(x.size * x.dtype.itemsize
                      for x in jax.tree.leaves((net, ps)))
    print(f"state: {state_bytes / 1e9:.2f} GB across {N_DEV} shards",
          flush=True)

    # 20 = the config's schedule lcm (pairing 4, period 20): the
    # phase-specialized scan applies from t=0 (bit-identical,
    # tests/test_phase_hints.py) and chunk boundaries stay aligned.
    chunk = 20
    if ON_TPU:
        # Plain per-ms scan on the chip: the phase-specialized block
        # unrolls 20 step bodies whose staggered buffer lifetimes cost
        # 63% HBM fragmentation at 2^20 nodes (8.35 GB wasted — OOM,
        # observed 2026-07-31); the uniform per-ms body keeps temp
        # small.  This run proves FIT + correctness; the fused/phased
        # paths are the throughput configuration (bit-identical either
        # way, tests/test_superstep.py + test_phase_hints.py).
        base_step = scan_chunk(proto, chunk)
    else:
        # superstep=2: fused 2-ms engine pass — halves per-ms fixed
        # cost on the virtual-mesh runs.
        base_step = scan_chunk(proto, chunk, t0_mod=0, superstep=2)
    # Selective >=1MB-leaf donation (network.split_donate_jit — the
    # Runner donate="big" mechanics, validated on this hardware in r3):
    # without it the while-loop carry cannot alias the 11.7 GB input
    # state and the program OOMs at compile (17.9 GB HLO temp vs
    # 15.75 GB HBM, observed 2026-07-31).
    from wittgenstein_tpu.core.network import (split_donate_jit,
                                                split_spec)
    step = split_donate_jit(base_step, *split_spec((net, ps)))
    t0 = time.perf_counter()
    with mesh:
        net, ps = step(net, ps)
        int(jax.device_get(net.time))
    t_compile = time.perf_counter() - t0
    print(f"first chunk ({chunk} ms incl. compile): {t_compile:.1f}s",
          flush=True)

    t0 = time.perf_counter()
    steps = (sim_ms - chunk + chunk - 1) // chunk
    with mesh:
        for i in range(steps):
            net, ps = step(net, ps)
        # Materialize every asserted value INSIDE the timed window: the
        # host copies are the completion proof (block_until_ready alone
        # measured dispatch, not compute, on this runtime — BENCH_NOTES
        # round-4 postmortem).
        total_ms = int(jax.device_get(net.time))
        dropped = int(jax.device_get(net.dropped))
        clamped = int(jax.device_get(net.clamped))
        bc_dropped = int(jax.device_get(net.bc_dropped))
        evicted = int(jax.device_get(ps.evicted))
        lvl_sum = np.asarray(jax.device_get(
            1 + jnp.sum(ps.lvl_best, axis=1)))
    t_run = time.perf_counter() - t0
    per_ms = t_run / max(1, steps * chunk)
    peak_rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6

    print(f"time={total_ms}ms wall={t_run:.1f}s ({per_ms:.2f}s/sim-ms) "
          f"dropped={dropped} clamped={clamped} bc_dropped={bc_dropped} "
          f"evicted={evicted}", flush=True)
    print(f"aggregate progress: mean={lvl_sum.mean():.1f} "
          f"max={lvl_sum.max()} of {n}", flush=True)
    print(f"peak RSS: {peak_rss:.1f} GB", flush=True)

    assert total_ms >= sim_ms, (total_ms, sim_ms)
    assert dropped == 0 and clamped == 0 and bc_dropped == 0, (
        dropped, clamped, bc_dropped)
    # Aggregation must actually be progressing (counts grow past own sig).
    assert lvl_sum.max() > 1

    cfg = proto.cfg
    label = f"{n // 1024}k_{N_DEV}dev"
    if ON_TPU:
        label += "_tpu"
        plat = jax.devices()[0].device_kind
        topo = (f"single REAL {plat} chip ({jax.default_backend()} "
                f"backend), mailbox ring split into {cfg.box_split} "
                "node-range sub-planes (EngineConfig.box_split) to stay "
                "under the runtime's ~1 GB single-buffer execution limit")
        per_chip = (f"measured here directly: {state_bytes / 1e9:.2f} GB "
                    "resident on one chip's 16 GB HBM.")
    elif N_DEV > 1:
        topo = (f"GSPMD node-axis sharding over a {N_DEV}-device virtual "
                f"CPU mesh (`xla_force_host_platform_device_count="
                f"{N_DEV}`, the same layout "
                "`__graft_entry__.dryrun_multichip` validates)")
        per_chip = (f"it shards evenly over the node axis, so a v5e-8 "
                    f"holds {state_bytes / 1e9 / N_DEV:.1f} GB/chip "
                    "against 16 GB HBM.")
    else:
        topo = ("UNSHARDED single virtual CPU device (GSPMD at this N x 8 "
                "partitions exceeds the host's compile/exec workspace; "
                "the mesh path is proven separately by the smaller-N "
                "mesh run and dryrun_multichip's bit-equality check)")
        per_chip = (f"on a v5e-8 the node axis shards it to "
                    f"{state_bytes / 1e9 / 8:.1f} GB/chip against "
                    "16 GB HBM.")
    report = REPO / "reports" / f"CARDINAL_{label}.md"
    report.write_text(f"""# Cardinal-mode {n:,}-node run ({N_DEV} device{"s" if N_DEV > 1 else ""})

Evidence for SCALE.md tier 3: `HandelCardinal` at N = {n:,} nodes,
{topo}, single seed.

Config: threshold 0.99N, pairing {proto.pairing_time} ms, period
{proto.period} ms, fastPath {proto.fast_path}, queue_cap
{proto.queue_cap}, inbox_cap {cfg.inbox_cap}, horizon {cfg.horizon},
{proto.latency!r} (all arrivals inside the ring by construction —
nothing may clamp).

| metric | value |
|---|---|
| simulated ms | {total_ms} |
| init wall-clock | {t_init:.1f} s |
| first {chunk}-ms chunk (incl. compile) | {t_compile:.1f} s |
| steady-state wall per sim-ms | {per_ms:.2f} s ({"real TPU chip" if ON_TPU else "1-core CPU host"}) |
| device state | {state_bytes / 1e9:.2f} GB across {N_DEV} device(s) |
| peak host RSS | {peak_rss:.1f} GB |
| dropped / clamped / bc_dropped / evicted | {dropped} / {clamped} / {bc_dropped} / {evicted} |
| aggregate count (mean / max over nodes) | {lvl_sum.mean():.1f} / {lvl_sum.max()} |

State is O(N*L): lvl_best [N, {proto.levels}] + queue counts, vs the
exact mode's Theta(N^2) bitsets (>= 0.8 TB at 1M — SCALE.md).  The
mailbox ring ({cfg.payload_words} x {cfg.horizon} x {n:,} x
{cfg.inbox_cap} int32 words + src/size/count) dominates at this scale;
{per_chip}

{"Measured on the real chip: fit, correct execution and honest per-ms cost at 1M-class N on one device."
 if ON_TPU else
 "Wall-clock caveat: this host is a 1-core CPU; the run validates fit + correct execution, not speed.  The per-sim-ms cost above is an upper bound that a real 8-chip mesh shrinks by the usual 2-3 orders."}
""")
    print(f"wrote {report}", flush=True)


if __name__ == "__main__":
    main()
