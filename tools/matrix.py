"""One-command sweep-grid campaign: expand, plan, run, report, verify.

Loads a `SweepGrid` JSON (file, inline JSON, or '-' for stdin), plans
it (every cell validated, grouped by compile key), runs it through the
serve scheduler with live progress on stderr, prints the cross-cell
`MatrixReport` summary, and optionally spot-checks a deterministic
subset of cells bit-for-bit against sequential `Runner` runs (full
final pytree + metrics/audit blocks — the matrix acceptance pin).

Exit codes (the tools/chaos.py convention):
  0  every cell done, every audit verdict clean, spot checks
     bit-identical
  1  violations or divergence: errored cells, audit violations, or a
     spot-checked cell differing from its sequential reference (all
     printed)
  2  configuration error: malformed grid JSON, unknown axis path, a
     cell that fails `ScenarioSpec.validate` (the offending cell is
     named)

    # a 2 x 2 x 2 grid from a file, report to disk, 3 spot checks
    python tools/matrix.py --grid grid.json --out report.json \
        --spot-check 3

    # inline grid
    python tools/matrix.py --grid '{"base": {"protocol": "PingPong",
        "params": {"node_count": 32}, "sim_ms": 120, "chunk_ms": 120},
        "axes": [{"name": "seed", "field": "seeds",
                  "values": [[0], [1]]}]}'
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def _load_grid_json(arg: str):
    if arg == "-":
        return json.load(sys.stdin)
    if arg.lstrip().startswith("{"):
        return json.loads(arg)
    with open(arg) as f:
        return json.load(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/matrix.py",
        description="declarative sweep-grid campaign: plan, run, "
                    "report, verify")
    ap.add_argument("--grid", required=True, metavar="JSON|PATH|-",
                    help="SweepGrid JSON: a file path, inline JSON, or "
                         "'-' for stdin (schema: matrix/grid.py)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the MatrixReport artifact here")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="per-cell RunManifest JSONL (default: the "
                         "shared reports/ledger)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="write chunk-boundary group checkpoints; a "
                         "killed campaign restarts with --resume from "
                         "exactly where it died (bit-identical "
                         "continuation, spec digests verified)")
    ap.add_argument("--journal-dir", default=None, metavar="DIR",
                    help="durable submission journal (WAL): every "
                         "accepted cell submit is fsync'd before ack "
                         "and tombstoned on completion, so --resume "
                         "recovers even cells that were queued but "
                         "never launched when the process died")
    ap.add_argument("--resume", action="store_true",
                    help="resume a killed campaign: re-enqueue this "
                         "grid's per-group checkpoints (needs the "
                         "interrupted run's --checkpoint-dir), replay "
                         "the submission journal (--journal-dir, if "
                         "the interrupted run used one), serve "
                         "finished cells from their ledger rows "
                         "(--ledger; exact config-digest matches from "
                         "other grids dedup too), and re-run only the "
                         "unfinished cells.  Stale/mismatched "
                         "checkpoints refuse loudly (exit 2)")
    ap.add_argument("--memo", action="store_true",
                    help="memoized supersteps (wittgenstein_tpu/memo): "
                         "cells differing only in post-fork adversity "
                         "share ONE honest-prefix run and fork from "
                         "its checkpoint — bit-identical, and "
                         "spot-checks verify forked cells like any "
                         "other (their rows carry forked_from)")
    ap.add_argument("--memo-table", default=None, metavar="DIR",
                    help="cross-run memo table directory (implies "
                         "--memo): completed prefixes are reused "
                         "across campaign invocations")
    ap.add_argument("--max-wave", type=int, default=64,
                    help="max cells per coalesced launch wave "
                         "(default 64)")
    ap.add_argument("--workers", type=int, default=None, metavar="N",
                    help="fleet mode (serve/fleet.py): enqueue the "
                         "grid into a shared journal and run it with "
                         "N worker PROCESSES over --fleet-dir; cell "
                         "rows come back through the shared-ledger "
                         "join, bit-identical to a single-process run")
    ap.add_argument("--fleet-dir", default=None, metavar="DIR",
                    help="the shared fleet directory for --workers "
                         "(holds journal/, checkpoints/, ledger.jsonl, "
                         "workers/); re-running over the same dir "
                         "resumes an interrupted fleet campaign")
    ap.add_argument("--spot-check", type=int, default=0, metavar="N",
                    help="verify N cells (deterministic spread) "
                         "bit-for-bit against sequential Runner runs")
    ap.add_argument("--plan-only", action="store_true",
                    help="expand + plan + print the compile accounting, "
                         "run nothing")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-wave progress lines")
    args = ap.parse_args(argv)

    import wittgenstein_tpu.models  # noqa: F401 — fills the registry
    from wittgenstein_tpu.matrix import (SweepGrid, pick_spot_cells,
                                         plan, run_grid, verify_cell)
    from wittgenstein_tpu.serve import Scheduler

    try:
        grid = SweepGrid.from_json(_load_grid_json(args.grid))
        mplan = plan(grid)
    except (ValueError, OSError, json.JSONDecodeError, TypeError) as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2

    summ = mplan.summary()
    print(f"grid {grid.name!r} [{summ['grid_digest']}]: "
          f"{summ['cells']} cells -> {summ['planned_compiles']} compile "
          f"keys ({summ['expected_builds']} program builds, largest "
          f"group {summ['largest_group']} cells)")
    if args.plan_only:
        return 0

    spot = pick_spot_cells(mplan.cells, args.spot_check)

    def progress(p):
        if not args.quiet:
            print(f"  [{p['wall_s']:8.1f}s] {p['done']}/{p['total']} "
                  f"cells, {p['program_builds']} builds, "
                  f"{p['groups_done']}/{p['groups_total']} groups",
                  file=sys.stderr, flush=True)

    if args.resume and not args.checkpoint_dir:
        print("config error: --resume needs --checkpoint-dir (the "
              "interrupted run's checkpoint directory)", file=sys.stderr)
        return 2
    if args.workers is not None:
        if not args.fleet_dir:
            print("config error: --workers needs --fleet-dir (the one "
                  "shared directory the worker processes derive "
                  "journal/checkpoint/ledger paths from)",
                  file=sys.stderr)
            return 2
        if args.resume or args.memo or args.memo_table:
            print("config error: --workers is a separate-process "
                  "fleet; --resume/--memo are single-process drivers "
                  "(the fleet serves finished cells from the shared "
                  "ledger automatically)", file=sys.stderr)
            return 2

        def fleet_progress(p):
            if not args.quiet:
                print(f"  [{p['wall_s']:8.1f}s] {p['done']}/"
                      f"{p['total']} cells, journal lag "
                      f"{p['journal_lag']}", file=sys.stderr,
                      flush=True)

        run = run_grid(grid, plan_=mplan, keep_states=(),
                       progress=fleet_progress, workers=args.workers,
                       fleet_dir=args.fleet_dir)
        report = run.report
        r = report.data["resume"]
        print(f"fleet: {r['fleet_workers']} workers, "
              f"{r['journal_replayed']} entries claimed, "
              f"{r['worker_deduped']} worker-deduped, "
              f"{r['adopted_checkpoints']} checkpoints adopted")
        print(report.format())
        if args.out:
            print(f"report -> {report.save(args.out)}")
        if spot:
            print("spot checks: SKIPPED (fleet cells' final states "
                  "live in the worker processes; re-run without "
                  "--workers to verify)")
        if report.clean:
            print("CLEAN: all cells done, audits clean")
        return 0 if report.clean else 1
    memo = None
    if args.memo or args.memo_table:
        memo = {"table": args.memo_table} if args.memo_table else True
    sch = Scheduler(ledger_path=args.ledger,
                    checkpoint_dir=args.checkpoint_dir,
                    journal_dir=args.journal_dir)
    try:
        run = run_grid(grid, sch, plan_=mplan, max_wave=args.max_wave,
                       keep_states=tuple(spot), progress=progress,
                       resume=args.resume, memo=memo)
    except ValueError as e:
        # ONLY the resume staleness refusals are config errors; a
        # ValueError from a plain campaign is an internal failure and
        # must keep its traceback
        if not args.resume:
            raise
        print(f"config error: {e}", file=sys.stderr)
        return 2
    report = run.report
    if args.resume and "resume" in report.data:
        r = report.data["resume"]
        print(f"resume: {r['from_ledger']} cells from this grid's "
              f"ledger rows, {r['deduped']} deduped from exact config "
              f"matches, {r['resumed_requests']} requests resumed "
              f"from checkpoints ({r.get('journal_replayed', 0)} of "
              "them replayed from the submission journal)")
    print(report.format())
    if args.out:
        path = report.save(args.out)
        print(f"report -> {path}")

    rc = 0 if report.clean else 1
    for cid in spot:
        row = report.cell(cid)
        if row["status"] != "done":
            print(f"spot check {cid}: SKIPPED (cell "
                  f"{row['status']}: {row.get('error')})")
            rc = 1
            continue
        if cid not in run.states:
            # a resume run served this cell from its ledger row — no
            # fresh state to verify; it was spot-checkable when it ran
            print(f"spot check {cid}: SKIPPED (served from the "
                  "ledger; re-run without --resume to re-verify)")
            continue
        mism = verify_cell(mplan.resolved[cid], run.states[cid],
                           run.artifacts[cid])
        # a forked cell verifies like any other — its final state and
        # stitched blocks are compared against the same sequential
        # twin, with the fork provenance named instead of skipped
        fk = row.get("forked_from")
        how = (f" (forked from prefix {fk['prefix_digest']} @ "
               f"{fk['fork_ms']} ms)") if fk else ""
        if mism:
            print(f"spot check {cid}: DIVERGENCE vs the sequential "
                  f"Runner reference{how}:")
            for m in mism:
                print(f"  {m}")
            rc = 1
        else:
            print(f"spot check {cid}: bit-identical to the sequential "
                  f"Runner reference (full pytree + obs blocks){how}")
    if rc == 0:
        print("CLEAN: all cells done, audits clean"
              + (", spot checks bit-identical" if spot else ""))
    return rc


if __name__ == "__main__":
    sys.exit(main())
