"""Coalescing scheduler — many requests, few device programs.

Requests whose specs share a `compile_key()` are the same compiled
chunk program over different DATA (seeds, partitions, spans).  The
scheduler exploits that: pending compatible requests are grouped and
run as ONE vmapped seed-batched program, one chunk at a time, with
continuous seed batching — on the `vmapped` engine, a compatible
request submitted while a group is in flight joins at the next chunk
boundary (freshly-initialized lanes concatenate onto the batch; each
lane carries its own clock, so mixed entry times are sound for the
per-lane dense engine).  The `batched` and `fast_forward` engines
assume LOCKSTEP times across the batch (one fused mailbox / one shared
jump), so their groups close at launch and later arrivals form the
next group.

Per chunk the scheduler advances state with the PRIMARY pass (the
metrics-instrumented engine when the spec captures metrics — that is
what streams progress — else the plain engine) and runs any remaining
obs planes as SHADOW passes from the same entry state: every plane is
bit-identical on the trajectory (tests/test_obs.py, test_trace.py,
test_audit.py), so the shadows describe exactly the run that advanced.

Each finished request gets per-request artifacts (ProgressPerTime-style
`engine_metrics` block, `trace` block, `audit` block, final-state
summary) and ONE `RunManifest` ledger row whose `config_digest` is the
spec digest (obs/ledger.py).

Resilience (PR 10): every device-program launch goes through
`_launch` — a failed chunk is retried with exponential backoff
(`max_retries`, `retry_backoff_s`; `launcher` is the injectable seam
the fault-injection tests drive), and a launch that keeps failing at
full batch width DEGRADES instead of dropping requests: the lane batch
is split in half and the halves run sequentially (recursively, down to
one lane), then re-concatenated — bit-identical state for the scan
engines, since the per-lane trajectory never depends on its batch
neighbors.  With `checkpoint_dir` set, the group state is written at
every chunk boundary (utils/checkpoint .npz + request metadata) and
`resume_checkpoints()` re-enqueues interrupted groups after a crash:
the resumed trajectory is bit-identical to an uninterrupted run
(chunk-boundary restore of a deterministic pure engine is exact —
tests/test_serve_resilience.py), with obs-plane artifacts covering the
post-restore span and the ledger row carrying `resumed_from_ms`.
Checkpoint metadata is schema 2: each stored request carries its spec
digest, and `resume_checkpoints` REFUSES a file whose stored spec no
longer digests to its recorded value (a stale .npz from an edited
spec would otherwise be silently restored into the wrong trajectory).

Tenancy (PR 13 — the survivability half of ROADMAP item 5): the FIFO
single-tenant queue becomes a multi-tenant one.

  * Admission control: `Scheduler(tenants={name: TenantPolicy})`
    bounds each tenant's QUEUED depth (`max_queued`); an over-budget
    `submit` raises `AdmissionError` — carrying `retry_after_s`
    estimated from the tenant's queued chunk backlog times a running
    EMA of chunk wall time — which the HTTP layer maps to 429 +
    Retry-After instead of letting the queue grow without bound.
  * Weighted-fair queueing: `run_pending` picks the next group by
    DEFICIT ROUND ROBIN over the tenants with queued work (strict
    priority classes first — only the highest queued `spec.priority`
    competes; within a tenant, earliest `deadline_ms` first, then
    FIFO).  Each tenant's turn adds `weight x quantum_chunks` to its
    deficit; the selected group runs with that deficit as its chunk
    budget and pays back what it consumed, so a thousand-cell campaign
    wave and an interactive spec INTERLEAVE instead of the campaign
    starving everything behind it.
  * Checkpoint-based preemption: a running group yields at the next
    CHUNK BOUNDARY — never mid-program — when (a) its DRR budget is
    exhausted and non-coalescable work waits, (b) a strictly
    higher-priority request waits, or (c) every deadline-carrying lane
    in the group is past its deadline and other work waits.  Yielding
    re-enqueues the requests with their chunk-boundary lane states
    (and their stashed obs-plane carries) held in memory — the group
    checkpoint file, when `checkpoint_dir` is set, covers the
    process-death case exactly as in PR 10 — so a preempted-then-
    resumed run is BIT-IDENTICAL to an uninterrupted one, including
    its metrics/trace/audit artifacts (tests/test_tenancy.py).

With no `tenants=` config the scheduler behaves exactly as before
(FIFO within the top priority class, no slice preemption): tenancy is
scheduler-side only, and the compiled programs are untouched — the
`PingPong+tenancy` analysis target pins carry_extra_leaves=0 /
transfer_ops=0 over a tenancy-labelled spec.

Memo (PR 14 — wittgenstein_tpu/memo, ROADMAP item 3):

  * Snapshot-fork seam: ``submit(spec, fork=ForkState(...))`` enqueues
    a request that enters at a mid-run chunk boundary with a shared
    honest-prefix state AND the prefix's per-chunk obs carries — the
    in-memory preemption machinery reused as a fork: `_init_lanes`
    consumes the state, `_Lane` the carries, so the finished artifacts
    stitch the WHOLE span and the trajectory is bit-identical to an
    unforked run.  `forked_from` provenance (prefix digest + fork ms)
    rides the artifacts and the ledger row.
  * Fixed-point lane freezing: with ``freeze=True`` (default: the
    ``WTPU_MEMO=1`` env flag), lanes the `next_work` oracle proves
    quiet to their end are sliced out of the batch at chunk boundaries
    and their tails synthesized analytically (memo/freeze.py) —
    bit-identical state and artifacts, engine scope and soundness
    conditions documented there.
  * `memo_stats()` is the `/w/batch/memo` block (forked requests,
    frozen lanes/chunks, freeze flag).

Streaming (ROADMAP item 5 leftover): every chunk boundary appends the
request's primary-pass totals (and their per-chunk DELTA) to
`Request.chunk_totals` and notifies a condition variable;
`stream_chunks` long-polls it — the `/w/batch/stream/{id}` endpoint
blocks until the next boundary and returns the new per-chunk deltas.
A stream always TERMINATES: settling a request any way at all (done,
error, quarantined, withdrawn) notifies the boundary condition, so a
long-poll on a failed request returns its final error/quarantined
record instead of hanging until the client timeout.

Crash-only serve (PR 15): every failure mode either recovers
bit-identically or is isolated to exactly the request that caused it.

  * Durable submission journal: `Scheduler(journal_dir=)` appends
    every ACCEPTED submit (canonical spec JSON + rid + label/
    ledger_extra) to an append-only JSONL WAL — fsync'd BEFORE the
    submit acks — and tombstones it when the request completes, is
    quarantined or is withdrawn (serve/journal.py; transient group
    errors stay replayable).  `resume_journal()` replays un-tombstoned
    entries after a crash; composed with `resume_checkpoints()` (use
    `recover()`, which orders them) a kill at ANY point — queued,
    mid-chunk, between groups — loses nothing: checkpointed groups
    resume from their chunk boundary, queued-but-unlaunched requests
    re-run from their journaled specs, and both continuations are
    bit-identical (deterministic pure engine).  Torn tail lines are
    tolerated loudly (utils/jsonl.py).
  * Poison-lane quarantine: when `_launch` exhausts retries and width
    degradation still fails, the halving recursion bottoms out at ONE
    lane (log2 launches — the bisection IS the degradation tree) and
    that request alone is QUARANTINED: status error with a
    `quarantined` artifact, its own ledger row, a per-tenant stat and
    a journal tombstone — while every coalesced neighbor completes
    bit-identically to a solo run (per-lane trajectories never depend
    on batch neighbors; tests/test_serve_resilience.py pins it with a
    deterministic always-fails-for-one-lane launcher).  A launch where
    EVERY lane fails is a dead device, not poison (a bisection that
    eliminates everything isolated nothing): it keeps the PR-10
    group-failure semantics — error + RETAINED group checkpoint, so a
    recovered device resumes mid-run work.
  * Hung-launch watchdog: with `watchdog_factor` set, every launch
    gets a wall deadline of max(`watchdog_floor_s`, factor x the
    PR-13 chunk-wall EMA) — the floor covers cold compiles.  A launch
    past deadline is ABANDONED on its daemon worker thread and
    surfaces as a `WatchdogTimeout` failure into the existing
    retry -> degrade -> quarantine ladder, so a wedged device stalls
    one group (at worst one request) — the drain loop's waits are
    bounded by the deadline per launch attempt, never by the hang, and
    only the top-level attempt retries a timeout (bisection subsets of
    a wedged device would all time out identically).
"""

from __future__ import annotations

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from .registry import CompileRegistry
from .spec import ScenarioSpec

#: request lifecycle states
STATUSES = ("queued", "running", "done", "error")

#: group-checkpoint metadata schema (bump on field changes).  2 (PR
#: 13): per-request `spec_digest` — resume verifies each stored spec
#: still digests to it and refuses a tampered/stale file with remedy
#: text instead of silently restoring the wrong trajectory.
CKPT_META_SCHEMA = 2


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's admission + fairness budget (module docstring)."""

    #: DRR weight — this tenant's share of chunk budget per rotation
    weight: int = 1
    #: max QUEUED requests before submit is refused with 429/retry-
    #: after (0 = unbounded, the single-tenant default)
    max_queued: int = 0
    #: floor of the retry-after estimate an over-budget submit carries
    retry_after_s: float = 1.0

    def __post_init__(self):
        if self.weight < 1:
            raise ValueError(f"TenantPolicy: weight must be >= 1, got "
                             f"{self.weight} (a zero-weight tenant "
                             "would starve by construction)")
        if self.max_queued < 0 or self.retry_after_s < 0:
            raise ValueError("TenantPolicy: max_queued and "
                             "retry_after_s must be >= 0")


class WatchdogTimeout(RuntimeError):
    """A device-program launch abandoned past its per-chunk wall
    deadline (module docstring).  The launch may still complete on its
    abandoned worker thread; its result is discarded — the retried
    launch recomputes the identical chunk (pure function), so the
    trajectory stays bit-identical."""


class StaleCheckpointError(ValueError):
    """A checkpoint refused by the staleness gate (schema mismatch or
    a stored spec that no longer digests to its recorded value) — the
    ONE resume failure that must raise through `resume_checkpoints`
    instead of being skipped: silently restoring a different spec's
    trajectory is worse than restarting.  Plain IO/decode failures
    (torn files, garbage .npz) keep the PR-10 skip-with-stderr
    behavior."""


@dataclasses.dataclass
class ForkState:
    """A snapshot-fork handoff (`submit(spec, fork=...)`): the shared
    honest prefix's final (net, pstate) lane state, its per-chunk
    obs carries (plane -> [carry, ...]) covering ``[entry, at_ms)``,
    the chunk-aligned fork point, and the prefix-spec digest the
    forked request's provenance records."""

    state: tuple
    carries: dict
    at_ms: int
    prefix_digest: str


class AdmissionError(RuntimeError):
    """An over-budget submission, refused — the HTTP layer's 429 (the
    `http_status` attribute is what `server/http.py` keys on; the
    worker never crashes, the client retries after `retry_after_s`)."""

    http_status = 429

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = round(float(retry_after_s), 3)


@dataclasses.dataclass
class Request:
    """One submitted scenario (scheduler-internal mutable record)."""

    id: str
    spec: ScenarioSpec              # RESOLVED (validate() output)
    compile_key: str
    #: the spec AS SUBMITTED (e.g. superstep="auto" before resolution)
    #: — provenance digests THIS one, like bench/bench_suite digest
    #: their requested configs, so a client correlating by its own
    #: spec digest always matches the ledger row
    requested: ScenarioSpec | None = None
    status: str = "queued"
    submitted: float = dataclasses.field(default_factory=time.time)
    started: float | None = None
    finished: float | None = None
    progress_ms: int = 0
    progress: dict = dataclasses.field(default_factory=dict)
    error: str | None = None
    artifacts: dict | None = None
    #: final (net, pstate) slices, seed axis kept — in-process consumers
    final_state: tuple | None = None
    manifest_path: str | None = None
    #: EngineConfig captured at lane init (protocol construction is
    #: heavy host work at tier-2 sizes — never rebuilt just for .cfg)
    cfg: object = None
    #: checkpoint-restored (net, pstate) lane slices — consumed by
    #: `_init_lanes` instead of a fresh init (see resume_checkpoints)
    restored_state: tuple | None = None
    #: chunk boundary this request resumed from (0 = never resumed)
    resumed_from_ms: int = 0
    #: ledger-row label override (default "serve:<id>") — the matrix
    #: driver labels rows "matrix:<cell>" so a sweep's provenance reads
    #: by cell, not by scheduler-internal request id
    label: str | None = None
    #: extra keys merged into the ledger row's `extra` dict (the matrix
    #: driver rides the grid digest + axis labels here, so every
    #: per-cell RunManifest row is joinable back to its SweepGrid)
    ledger_extra: dict | None = None
    #: chunk-boundary preemptions this request absorbed (tenancy)
    preempted: int = 0
    #: obs-plane carries stashed before a preemption — restored into
    #: the next `_Lane` so the final artifacts cover the WHOLE span
    saved_carries: dict | None = None
    #: group-level fast-forward skip stats accumulated across
    #: preemption segments (the artifact's `fast_forward` block)
    ff_accum: dict | None = None
    #: snapshot-fork provenance: {"prefix_digest", "fork_ms"} — the
    #: honest prefix this request entered from (memo; rides artifacts
    #: AND the ledger row so forked cells verify, not skip)
    forked_from: dict | None = None
    #: fixed-point freeze marker: the chunk boundary this request's
    #: lane was proven quiet-to-end and sliced out of the batch
    frozen_from_ms: int | None = None
    #: stash the raw per-chunk obs carries on the finished request
    #: (the memo driver's prefix handoff needs them; artifacts keep
    #: only the decoded blocks)
    keep_carries: bool = False
    final_carries: dict | None = None
    #: per-chunk-boundary primary-pass totals + deltas (the streaming
    #: endpoint's backing store; evicted with the request)
    chunk_totals: list = dataclasses.field(default_factory=list)
    #: monotonic enqueue timestamp on the INSTRUMENT's clock (set only
    #: when the scheduler is instrumented; reset on preempt/resume so
    #: the queue-wait span covers the current wait, not the lifetime)
    enq_mono: float | None = None

    @property
    def tenant(self) -> str:
        return self.spec.tenant

    @property
    def deadline_at(self) -> float | None:
        """Absolute wall-clock deadline (None = none).  A checkpoint-
        resumed request's clock restarts at re-submission — the
        original process is gone, and so is its wall budget."""
        if self.spec.deadline_ms is None:
            return None
        return self.submitted + self.spec.deadline_ms / 1000.0

    def status_json(self) -> dict:
        out = {"id": self.id, "status": self.status,
               "compile_key": self.compile_key,
               "progress_ms": self.progress_ms,
               "sim_ms": self.spec.sim_ms,
               "tenant": self.spec.tenant}
        if self.spec.priority:
            out["priority"] = self.spec.priority
        if self.spec.deadline_ms is not None:
            out["deadline_ms"] = self.spec.deadline_ms
        if self.preempted:
            out["preempted"] = self.preempted
        if self.progress:
            out["progress"] = dict(self.progress)
        if self.error:
            out["error"] = self.error
        return out


class _Lane:
    """One request's slice of the running batch."""

    def __init__(self, req: Request):
        self.req = req
        self.width = len(req.spec.seeds)
        # a checkpoint-restored request re-enters with progress already
        # made — only the remaining chunks run
        self.remaining = (req.spec.sim_ms -
                          req.progress_ms) // req.spec.chunk_ms
        # a PREEMPTED request re-enters with its pre-yield obs carries
        # intact, so the finished artifacts stitch the whole span
        self.carries: dict = req.saved_carries or {}
        req.saved_carries = None    # plane -> [per-chunk carry slices]

    def stash(self, plane: str, carry, lo: int):
        sl = jax.tree.map(lambda x: x[lo:lo + self.width], carry)
        self.carries.setdefault(plane, []).append(sl)


class Scheduler:
    """See module docstring.  Thread-compat: `submit`/`request`/
    `status` are safe from any thread; `run_pending` drains from one
    thread at a time (a second concurrent call returns immediately)."""

    #: lock inventory (checked by analysis rule ``host_locks``): every
    #: read or write of these attributes must hold `_mu`.  Listed:
    #: the queue/request tables, tenancy accounting, the resilience
    #: and memo counters (mutated from drain, watchdog and HTTP
    #: threads), and the chunk-wall EMA the watchdog deadline reads.
    _LOCK_OWNS = {"_mu": ("_requests", "_queue", "_n", "_draining",
                          "_deficit", "_last_tenant", "_tstats",
                          "resilience", "memo", "chunk_wall_ema_s")}
    #: `_boundary` is Condition(self._mu): holding it IS holding `_mu`
    _LOCK_ALIASES = {"_boundary": "_mu"}

    def __init__(self, registry: CompileRegistry | None = None,
                 ledger_path=None, on_boundary=None, keep_done: int = 256,
                 launcher=None, max_retries: int = 2,
                 retry_backoff_s: float = 0.05, checkpoint_dir=None,
                 tenants: dict | None = None,
                 quantum_chunks: int | None = None,
                 freeze: bool | None = None, journal_dir=None,
                 watchdog_factor: float | None = None,
                 watchdog_floor_s: float = 30.0,
                 worker_id: str | None = None,
                 instrument=None, catalog=None):
        self.registry = registry or CompileRegistry(catalog=catalog)
        #: host flight recorder + metrics bundle
        #: (serve/instrument.Instrumentation; None = OFF, the default).
        #: Every instrumented site guards on ``self._ins is not None``
        #: — one attribute load, zero allocations when off.
        self._ins = instrument
        #: program observatory (obs/programs.ProgramCatalog; None =
        #: OFF, the default — one is-None branch per chunk, nothing
        #: imported).  A caller-provided registry adopts it unless it
        #: already carries its own; with both instrumentation and a
        #: catalog on, chunk-wall samples also feed the shared metrics
        #: registry's wtpu_program_chunk_seconds histogram.
        self.catalog = catalog
        if catalog is not None:
            if self.registry.catalog is None:
                self.registry.catalog = catalog
            if instrument is not None and catalog.metrics is None:
                catalog.metrics = instrument.metrics
        if instrument is not None and worker_id \
                and instrument.spans.worker is None:
            instrument.spans.worker = str(worker_id)
        #: fleet identity (None = the single-process default, nothing
        #: changes).  When set, this scheduler is ONE worker among N
        #: sharing a journal/ledger/checkpoint directory: request ids
        #: and checkpoint filenames are prefixed with the worker id so
        #: two workers can never mint the same rid or clobber each
        #: other's group checkpoint.  The id rides in checkpoint meta
        #: so a survivor can tell whose file it is adopting.  Uses "-"
        #: as the separator (the HTTP id route accepts [A-Za-z0-9_-]).
        self.worker_id = str(worker_id) if worker_id else None
        self.ledger_path = ledger_path      # None = the shared default
        #: the device-program launch seam: ``launcher(fn, *args)``
        #: (default: call fn).  Tests inject flaky/width-limited
        #: launchers to drive the retry and degradation paths.
        self.launcher = launcher
        #: failed-launch retries per width level before degrading
        self.max_retries = int(max_retries)
        #: base backoff (doubles per attempt); 0 disables sleeping
        self.retry_backoff_s = float(retry_backoff_s)
        #: directory for chunk-boundary group checkpoints (None = off)
        self.checkpoint_dir = checkpoint_dir
        #: durable submission journal (None = off): every accepted
        #: submit is WAL'd before ack, settled requests are
        #: tombstoned, `resume_journal()` replays the survivors
        if journal_dir:
            from .journal import SubmissionJournal
            self.journal = SubmissionJournal(journal_dir)
        else:
            self.journal = None
        #: hung-launch watchdog (None = off): per-launch wall deadline
        #: = max(floor, factor x chunk_wall_ema_s); the floor alone
        #: applies while the EMA is cold (first chunk = compile time)
        self.watchdog_factor = (None if watchdog_factor is None
                                else float(watchdog_factor))
        self.watchdog_floor_s = float(watchdog_floor_s)
        #: tenancy: tenant name -> `TenantPolicy` (plain dicts accepted
        #: for JSON-authored configs; "*" sets the default policy).
        #: Empty = the single-tenant PR-7 behavior: FIFO within the top
        #: priority class, no DRR slicing.
        self.tenants = {name: (pol if isinstance(pol, TenantPolicy)
                               else TenantPolicy(**pol))
                        for name, pol in (tenants or {}).items()}
        #: DRR quantum in CHUNKS per weight point per rotation; None
        #: defaults to 4 when any tenant policy exists.  Slicing is
        #: active iff this resolves non-None.
        if quantum_chunks is None and self.tenants:
            quantum_chunks = 4
        self.quantum_chunks = quantum_chunks
        self._deficit: dict = {}            # tenant -> chunk deficit
        #: DRR rotation pointer: the last-served tenant NAME (the ring
        #: itself is rebuilt per selection from the tenants with
        #: queued work, so bookkeeping stays bounded by live tenants —
        #: client-supplied tenant strings must not leak memory in a
        #: long-lived service)
        self._last_tenant: str | None = None
        #: EMA of one coalesced chunk's wall seconds — the retry-after
        #: estimate's unit cost (0.0 until the first chunk lands)
        self.chunk_wall_ema_s = 0.0
        #: per-tenant lifetime counters (tenancy_stats())
        self._tstats: dict = {}
        #: resilience accounting, surfaced in per-request artifacts
        self.resilience = {"retries": 0, "demotions": 0, "resumed": 0,
                           "preemptions": 0, "rejected": 0,
                           "quarantined": 0, "watchdog_trips": 0,
                           "replayed": 0, "repacked": 0}
        #: scheduler birth time — the health endpoint's uptime anchor
        self._t0 = time.time()
        #: fixed-point lane freezing (memo/freeze.py); None defers to
        #: the WTPU_MEMO env flag so an operator can flip a deployed
        #: service without touching code
        if freeze is None:
            import os
            freeze = os.environ.get("WTPU_MEMO", "0") == "1"
        self.freeze = bool(freeze)
        #: memo accounting (the `/w/batch/memo` block)
        self.memo = {"forked": 0, "frozen_lanes": 0, "frozen_chunks": 0}
        #: test/ops hook: called at every chunk boundary of a running
        #: group, BEFORE admission — a callback may `submit()` and see
        #: its request join this group (the continuous-batching pin)
        self.on_boundary = on_boundary
        #: finished-request retention bound: a long-lived service must
        #: not pin every past request's final-state device arrays —
        #: beyond this many done/errored records the OLDEST are evicted
        #: (their ledger row is the durable artifact; status() then
        #: answers unknown).  0 = unbounded (tests, short-lived tools).
        self.keep_done = int(keep_done)
        self._mu = threading.RLock()
        #: chunk-boundary pulse for the streaming long-poll
        self._boundary = threading.Condition(self._mu)
        self._requests: dict[str, Request] = {}
        self._queue: list[str] = []         # FIFO of queued request ids
        self._n = 0
        self._draining = False

    # ------------------------------------------------------------ tenancy

    def policy(self, tenant: str) -> TenantPolicy:
        """The tenant's admission/fairness policy ("*" = the default
        for unlisted tenants; unbounded weight-1 otherwise)."""
        pol = self.tenants.get(tenant) or self.tenants.get("*")
        return pol or TenantPolicy()

    #: lifetime-counter retention bound for UNCONFIGURED tenants —
    #: tenant is a client-supplied string, so a long-lived service
    #: must not let per-name stat dicts grow without limit (configured
    #: tenants are never evicted)
    MAX_TENANT_STATS = 4096

    def _tstat(self, tenant: str) -> dict:
        """Per-tenant lifetime counters (caller holds the lock)."""
        if tenant not in self._tstats and \
                len(self._tstats) >= self.MAX_TENANT_STATS:
            queued_now = {self._requests[r].spec.tenant
                          for r in self._queue}
            for victim in list(self._tstats):    # oldest-first (dict
                # insertion order); skip configured/live tenants
                if victim not in self.tenants and \
                        victim not in queued_now:
                    del self._tstats[victim]
                    break
        return self._tstats.setdefault(
            tenant, {"submitted": 0, "rejected": 0, "done": 0,
                     "errors": 0, "preemptions": 0, "quarantined": 0})

    def _admit(self, spec: ScenarioSpec):
        """Refuse an over-budget submission with a retry-after remedy
        (caller holds the lock).  Only QUEUED requests count against
        the budget — a running request's slot is already freed for the
        next submit, which is what keeps the queue bounded while the
        device stays busy."""
        pol = self.policy(spec.tenant)
        self._tstat(spec.tenant)["submitted"] += 1
        if not pol.max_queued:
            return
        mine = [self._requests[r] for r in self._queue
                if self._requests[r].spec.tenant == spec.tenant]
        if len(mine) < pol.max_queued:
            return
        self.resilience["rejected"] += 1
        self._tstat(spec.tenant)["rejected"] += 1
        backlog_chunks = sum(
            (r.spec.sim_ms - r.progress_ms) // r.spec.chunk_ms
            for r in mine)
        retry = max(pol.retry_after_s,
                    backlog_chunks * self.chunk_wall_ema_s)
        raise AdmissionError(
            f"tenant {spec.tenant!r} queue is full ({len(mine)}/"
            f"{pol.max_queued} queued requests): retry after "
            f"~{retry:.1f}s, raise the tenant's max_queued, or split "
            "the submission across tenants", retry_after_s=retry)

    def memo_stats(self) -> dict:
        """The `/w/batch/memo` block: snapshot-fork and lane-freeze
        accounting plus the freeze flag (memo/freeze.py scope)."""
        with self._mu:
            return {"freeze": self.freeze, **self.memo}

    def stream_chunks(self, rid: str, after_ms: int | None = None,
                      timeout_s: float = 25.0) -> dict:
        """Long-poll one request's per-chunk primary-pass totals
        (module docstring): block until a chunk boundary newer than
        `after_ms` lands (or the request settles / `timeout_s`
        expires), then return the new ``{"t_ms", "totals", "delta"}``
        entries.  ``eof`` is True once the request has settled and no
        newer boundary is pending — the client stops polling.  Raises
        KeyError on an unknown/evicted id (the HTTP 400)."""
        after = -1 if after_ms is None else int(after_ms)
        deadline = time.time() + max(0.0, min(float(timeout_s), 60.0))
        with self._boundary:
            while True:
                if rid not in self._requests:
                    raise KeyError(f"unknown request {rid!r}")
                req = self._requests[rid]
                fresh = [dict(c) for c in req.chunk_totals
                         if c["t_ms"] > after]
                status = req.status
                if fresh or status in ("done", "error") \
                        or time.time() >= deadline:
                    break
                self._boundary.wait(
                    timeout=max(0.05, deadline - time.time()))
        out = {"id": rid, "status": status, "after_ms": after,
               "chunks": fresh,
               "next_after_ms": fresh[-1]["t_ms"] if fresh else after,
               "eof": status in ("done", "error") and not fresh}
        if status == "error" and req.error:
            # the stream TERMINATES with the final failure record — a
            # failed/quarantined request must never leave its client
            # long-polling until timeout (module docstring)
            out["error"] = req.error
            if (req.artifacts or {}).get("quarantined"):
                out["quarantined"] = True
        return out

    def tenancy_stats(self) -> dict:
        """The `/w/batch/tenancy` block: per-tenant queue depth +
        lifetime counters, plus the scheduler-level knobs a load
        generator needs to interpret them."""
        with self._mu:
            out = {"tenants": {}, "quantum_chunks": self.quantum_chunks,
                   "chunk_wall_ema_s": round(self.chunk_wall_ema_s, 4),
                   "rejected": self.resilience["rejected"],
                   "preemptions": self.resilience["preemptions"]}
            queued: dict = {}
            for rid in self._queue:
                t = self._requests[rid].spec.tenant
                queued[t] = queued.get(t, 0) + 1
            for t in set(self._tstats) | set(queued) | set(
                    k for k in self.tenants if k != "*"):
                pol = self.policy(t)
                out["tenants"][t] = {
                    **self._tstat(t), "queued": queued.get(t, 0),
                    "weight": pol.weight, "max_queued": pol.max_queued}
            return out

    # ------------------------------------------------------------- submit

    def submit(self, spec: ScenarioSpec, label: str | None = None,
               ledger_extra: dict | None = None,
               keep_carries: bool = False,
               fork: ForkState | None = None) -> str:
        """Validate (raises `ValueError` with remedy text — the HTTP
        layer's 400) and enqueue; returns the request id.  An
        over-budget tenant raises `AdmissionError` (the 429 path; see
        `_admit`).  `label` / `ledger_extra` ride into the request's
        ledger row (the matrix driver's per-cell provenance — see the
        Request fields).  `fork` (a `ForkState`) enters the request at
        a mid-run chunk boundary from a shared honest-prefix state
        with the prefix's obs carries (module docstring: the memo
        snapshot-fork seam); `keep_carries` stashes the raw per-chunk
        carries on the finished request (the prefix handoff)."""
        ins = self._ins
        t_sub = 0.0 if ins is None else ins.now()
        resolved = spec.validate()
        key = resolved.compile_key()
        if fork is not None:
            self._check_fork(resolved, fork)
        with self._mu:
            self._admit(resolved)
            rid = self._rid_locked()
            req = Request(id=rid, spec=resolved, compile_key=key,
                          requested=spec, label=label,
                          keep_carries=bool(keep_carries),
                          ledger_extra=dict(ledger_extra)
                          if ledger_extra else None)
            if ins is not None:
                # set under the lock so a concurrent drain marking the
                # request running always sees the enqueue time
                req.enq_mono = t_sub
            if fork is not None:
                req.restored_state = fork.state
                req.saved_carries = {p: list(cs) for p, cs
                                     in (fork.carries or {}).items()}
                req.progress_ms = int(fork.at_ms)
                req.forked_from = {"prefix_digest": fork.prefix_digest,
                                   "fork_ms": int(fork.at_ms)}
                req.ledger_extra = {**(req.ledger_extra or {}),
                                    "forked_from": dict(req.forked_from)}
                self.memo["forked"] += 1
            self._requests[rid] = req
            self._queue.append(rid)
            if self.journal is not None:
                # the WAL write precedes the ack BY CONSTRUCTION: a
                # journal failure un-accepts the request — promising
                # durability the disk refused would be worse than a
                # loud 500.  The append+fsync deliberately runs under
                # the scheduler lock: releasing first would let the
                # drain launch (or even finalize) the request before
                # its submit row exists — a tombstone-before-submit
                # ordering the replay would mis-resurrect.  The cost
                # is one fsync of lock hold per submit; the journal
                # is an explicit opt-in for deployments that want
                # durability over submit throughput.
                try:
                    self.journal.record_submit(
                        rid, spec, label=label,
                        ledger_extra=req.ledger_extra)
                except OSError as e:
                    self._queue.remove(rid)
                    del self._requests[rid]
                    raise RuntimeError(
                        f"serve: submission journal append failed "
                        f"({e}); request NOT accepted — fix the "
                        f"journal_dir volume or disable journaling"
                    ) from e
        if ins is not None:
            from .instrument import SPAN_SUBMIT
            ins.end(SPAN_SUBMIT, t_sub, rid=rid, key=key,
                    tenant=resolved.tenant)
        return rid

    @staticmethod
    def _check_fork(resolved: ScenarioSpec, fork: ForkState) -> None:
        """Refuse (ValueError with remedy text) a `ForkState` that
        cannot soundly enter `resolved` mid-run: off-boundary fork
        point, wrong lane width, or carries that don't cover the
        prefix span (shared by `submit` and the journal-adoption
        path)."""
        at = int(fork.at_ms)
        if at < resolved.chunk_ms or at % resolved.chunk_ms or \
                at >= resolved.sim_ms:
            raise ValueError(
                f"fork.at_ms={at} must be a positive multiple of "
                f"chunk_ms={resolved.chunk_ms} inside the span "
                f"[chunk_ms, sim_ms={resolved.sim_ms}): requests "
                "enter and leave groups only on chunk boundaries")
        import jax
        width = jax.tree.leaves(fork.state)[0].shape[0]
        if width != len(resolved.seeds):
            raise ValueError(
                f"fork state carries {width} lane(s) but the spec "
                f"has {len(resolved.seeds)} seed(s): the prefix "
                "must have been run with exactly the cell's seeds")
        # the stitched-artifact contract: every captured plane must
        # arrive with one carry per prefix CHUNK, or the finished
        # artifacts would silently claim a full span they don't
        # cover (same refuse-with-remedy discipline as above)
        want_chunks = at // resolved.chunk_ms
        carries = fork.carries or {}
        for plane in resolved.obs:
            got = len(carries.get(plane, ()))
            if got != want_chunks:
                raise ValueError(
                    f"fork carries cover {got} chunk(s) of the "
                    f"{plane!r} plane but the prefix spans "
                    f"{want_chunks} chunk(s) ([0, {at}) at "
                    f"chunk_ms={resolved.chunk_ms}): the forked "
                    "request could not stitch a full-span "
                    "artifact. Fix: hand over the prefix run's "
                    "complete per-chunk carries (submit the "
                    "prefix with keep_carries=True), or drop the "
                    "plane from the spec's obs")

    def _rid_locked(self) -> str:
        """Mint the next request id (caller holds the lock).  Worker-
        prefixed under a fleet identity so N workers sharing one
        journal can never collide; checkpoint-restored requests keep
        their original ids, which may sit ahead of this counter — the
        skip loop never overwrites one."""
        prefix = f"{self.worker_id}-r" if self.worker_id else "r"
        self._n += 1
        rid = f"{prefix}{self._n:04d}"
        while rid in self._requests:
            self._n += 1
            rid = f"{prefix}{self._n:04d}"
        return rid

    def request(self, rid: str) -> Request:
        with self._mu:
            if rid not in self._requests:
                raise KeyError(f"unknown request {rid!r}")
            return self._requests[rid]

    def peek(self, rid: str) -> Request | None:
        """The Request for `rid`, or None when unknown — which for a
        previously-valid rid means the keep_done eviction already
        dropped the finished record (its ledger row is the durable
        artifact).  The lookup drivers polling after a drain want,
        without the try/except-KeyError dance at every site."""
        with self._mu:
            return self._requests.get(rid)

    def pending(self) -> list:
        with self._mu:
            return list(self._queue)

    def withdraw(self, rids) -> int:
        """Remove still-QUEUED requests from the scheduler (running/
        settled ones are left alone); returns how many were removed.
        The matrix driver's resume rollback: when a later checkpoint
        fails validation, the earlier files' re-enqueued requests must
        not be left orphaned on a shared scheduler — they would run
        with no harvester."""
        gone = []
        with self._mu:
            for rid in rids:
                req = self._requests.get(rid)
                if req is not None and req.status == "queued":
                    if rid in self._queue:
                        self._queue.remove(rid)
                    del self._requests[rid]
                    gone.append(rid)
            # a long-poll streaming a withdrawn id must terminate NOW
            # (it re-checks membership on wake and raises the 400),
            # not at its client timeout
            self._boundary.notify_all()
        if self.journal is not None:
            for rid in gone:
                self.journal.record_settled(rid, "withdrawn")
        return len(gone)

    # -------------------------------------------------------------- drain

    def run_pending(self) -> dict:
        """Drain the queue: pick the next group (DRR over tenants
        within the top priority class — `_next_head`), run it up to
        its chunk budget, repeat until empty.  A preempted group goes
        back on the queue and is re-picked on a later rotation, so the
        loop terminates: every `_run_group` call advances at least one
        chunk or settles a request.  Returns ``{"processed": N,
        "registry": stats}``."""
        with self._mu:
            if self._draining:
                return {"processed": 0, "registry": self.registry.stats()}
            self._draining = True
        processed = 0
        try:
            while True:
                key, budget, tenant = self._next_head()
                if key is None:
                    break
                try:
                    done, used = self._run_group(key, budget)
                    processed += done
                except Exception as e:      # noqa: BLE001 — a broken
                    # group must not wedge the whole queue
                    self._fail_group(key, e)
                    used = 0
                with self._mu:
                    if tenant in self._deficit:
                        self._deficit[tenant] -= used
                        if not any(self._requests[r].spec.tenant == tenant
                                   for r in self._queue):
                            # classic DRR: an emptied tenant forfeits
                            # its leftover deficit (no banking idle
                            # credit against future contention) — and
                            # its entry, so arbitrary client-supplied
                            # tenant names never accumulate
                            del self._deficit[tenant]
        finally:
            with self._mu:
                self._draining = False
        return {"processed": processed, "registry": self.registry.stats()}

    def _next_head(self):
        """Pick the next group to run: ``(compile_key, budget_chunks,
        tenant)`` or ``(None, None, None)`` on an empty queue.

        Strict priority classes first: only requests at the highest
        queued `spec.priority` compete.  Without tenancy config the
        winner is the class's FIFO head with an unbounded budget (the
        PR-7 behavior).  With tenancy, deficit round robin over the
        class's tenants: the rotation pointer advances tenant by
        tenant, each turn adds ``weight x quantum_chunks`` to the
        tenant's deficit, and the tenant's EDF-then-FIFO head runs
        with the accumulated deficit as its chunk budget (floor 1 —
        a group always makes progress)."""
        with self._mu:
            if not self._queue:
                return None, None, None
            reqs = [self._requests[r] for r in self._queue]
            top = max(r.spec.priority for r in reqs)
            cand = [r for r in reqs if r.spec.priority == top]
            if self.quantum_chunks is None:
                return cand[0].compile_key, None, cand[0].spec.tenant
            import bisect
            # the rotation ring is the sorted set of tenants with
            # candidate work, entered just AFTER the last-served name
            # (circular) — equivalent to a persistent round robin, but
            # bounded: nothing is remembered for tenants with no
            # queued work except their banked deficit (pruned by
            # run_pending when they empty)
            ring = sorted({r.spec.tenant for r in cand})
            i = bisect.bisect_right(ring, self._last_tenant) \
                if self._last_tenant is not None else 0
            tenant = ring[i % len(ring)]
            self._last_tenant = tenant
            self._deficit[tenant] = (
                self._deficit.get(tenant, 0)
                + self.policy(tenant).weight * self.quantum_chunks)
            mine = [r for r in cand if r.spec.tenant == tenant]
            # earliest deadline first; deadline-less requests keep
            # FIFO order behind every deadline-carrying one
            head = min(mine, key=lambda r: (
                r.deadline_at if r.deadline_at is not None
                else float("inf"), r.submitted))
            budget = max(1, int(self._deficit[tenant]))
            return head.compile_key, budget, tenant

    def _fail_group(self, key: str, e: Exception):
        """Mark every unfinished request of this compile key errored —
        including ones already popped from the queue but not yet
        marked running (a group that dies in lane init)."""
        msg = f"{type(e).__name__}: {e!s:.500}"
        with self._mu:
            for req in self._requests.values():
                if req.compile_key == key and req.status in ("queued",
                                                             "running"):
                    if req.id in self._queue:
                        self._queue.remove(req.id)
                    req.status, req.error = "error", msg
                    self._tstat(req.spec.tenant)["errors"] += 1
            self._boundary.notify_all()     # wake stream long-polls
        # deliberately NO journal tombstone: a group failure is
        # presumed transient (dead device, wedged runtime) — the
        # journal's crash-only contract is redo-beats-lose, so these
        # entries REPLAY on the next recovery.  Only a completed,
        # quarantined (deterministic verdict) or withdrawn request
        # tombstones.

    # ----------------------------------------------------------- grouping

    def _take_compatible(self, key: str,
                         progress_ms: int | None = None) -> list:
        """Pop every queued request with this compile key (FIFO
        order).  With `progress_ms` set (the lockstep lane-repack
        admission), only requests that can soundly join a RUNNING
        group at that chunk boundary: equal progress AND a restored
        state (checkpoint, preemption or fork) — a fresh request
        enters at progress 0 and can never match a mid-run boundary,
        while equal progress under one compile key implies equal
        device time arrays, which is all the fused mailbox/shared-jump
        engines require."""
        with self._mu:
            taken = []
            for rid in self._queue:
                r = self._requests[rid]
                if r.compile_key != key:
                    continue
                if progress_ms is not None and (
                        r.progress_ms != progress_ms
                        or r.restored_state is None):
                    continue
                taken.append(rid)
            for rid in taken:
                self._queue.remove(rid)
            return [self._requests[rid] for rid in taken]

    def _init_lanes(self, reqs: list, proto):
        """Fresh state for each request's seeds (+ partition applied —
        data, not program), concatenated along the seed axis.  `proto`
        is the GROUP's shared protocol instance: requests in a group
        have equal compile keys, hence equal protocol/params — one
        construction serves them all (heavy host work at tier-2
        sizes)."""
        states = []
        for req in reqs:
            spec = req.spec
            req.cfg = proto.cfg
            if req.restored_state is not None:
                # checkpoint-restored lanes re-enter with their saved
                # chunk-boundary state (partition/faults already in it)
                states.append(req.restored_state)
                req.restored_state = None
                continue
            seeds = jnp.asarray(spec.seeds, jnp.int32)
            nets, ps = jax.vmap(proto.init)(seeds)
            if spec.partition:
                idx = jnp.asarray(spec.partition, jnp.int32)
                nodes = nets.nodes
                nets = nets.replace(nodes=nodes.replace(
                    down=nodes.down.at[:, idx].set(True)))
            k = spec.superstep
            if k > 1:
                t = np.asarray(jax.device_get(nets.time)).reshape(-1)
                if (t % k).any():
                    raise ValueError(
                        f"request {req.id}: {spec.protocol}.init enters "
                        f"at time(s) {sorted(set(t.tolist()))}, not "
                        f"multiples of superstep={k} — the fused window "
                        "contract needs a K-aligned entry. Fix: "
                        "superstep=1 (or 'auto') for this protocol")
            states.append((nets, ps))
        return states

    @staticmethod
    def _concat(states: list):
        if len(states) == 1:
            return states[0]
        return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0),
                            *states)

    @staticmethod
    def _take_lanes(state, idx):
        idx = jnp.asarray(idx, jnp.int32)
        return jax.tree.map(lambda x: jnp.take(x, idx, axis=0), state)

    # --------------------------------------------------------- resilience

    @staticmethod
    def _split_state(state, w: int):
        left = jax.tree.map(lambda x: x[:w], state)
        right = jax.tree.map(lambda x: x[w:], state)
        return left, right

    @staticmethod
    def _combine(a, b, engine: str, has_plane: bool):
        """Re-concatenate two half-batch chunk results into the full-
        width result tuple the callers index (``out[0], out[1],
        out[2]=stats (ff), out[-1]=carry``).  State and plane carries
        concatenate on the seed axis; the fast-forward skip stats are
        batch-level scalars and sum."""
        def cat(x, y):
            return jnp.concatenate([jnp.asarray(x), jnp.asarray(y)],
                                   axis=0)

        state = jax.tree.map(cat, (a[0], a[1]), (b[0], b[1]))
        out = [state[0], state[1]]
        if engine == "fast_forward":
            out.append(jax.tree.map(lambda x, y: x + y, a[2], b[2]))
        if has_plane:
            out.append(jax.tree.map(cat, a[-1], b[-1]))
        return tuple(out)

    def launch_deadline_s(self) -> float | None:
        """The watchdog's per-launch wall deadline (None = watchdog
        off): max(floor, factor x chunk-wall EMA); the floor alone
        while the EMA is cold, so a first-chunk compile is never
        mistaken for a hang."""
        if self.watchdog_factor is None:
            return None
        with self._mu:      # EMA is written at chunk boundaries
            ema = self.chunk_wall_ema_s
        if not ema:
            return self.watchdog_floor_s
        return max(self.watchdog_floor_s, self.watchdog_factor * ema)

    def _call_bounded(self, call, fn, entry):
        """One launch attempt under the watchdog deadline (module
        docstring).  Past deadline the worker thread is ABANDONED
        (daemon — its late result is discarded; the retried launch
        recomputes the identical pure-function chunk) and the hang
        surfaces as a `WatchdogTimeout` failure into the retry ->
        degrade -> quarantine ladder, so the drain loop's wait is
        bounded by the deadline, never by the wedged call."""
        deadline = self.launch_deadline_s()
        if deadline is None:
            return call(fn, *entry)
        box: dict = {}
        settled = threading.Event()

        def work():
            try:
                box["out"] = call(fn, *entry)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["err"] = e
            finally:
                settled.set()

        t = threading.Thread(target=work, daemon=True,
                             name="wtpu-launch")
        t.start()
        if not settled.wait(deadline):
            with self._mu:      # drain thread holds no lock here
                self.resilience["watchdog_trips"] += 1
                ema = self.chunk_wall_ema_s
            if self._ins is not None:
                from .instrument import MARK_WATCHDOG
                self._ins.mark(MARK_WATCHDOG,
                               deadline_s=round(deadline, 3))
            raise WatchdogTimeout(
                f"launch exceeded its {deadline:.2f}s wall deadline "
                f"(chunk-wall EMA {ema:.3f}s x "
                f"factor {self.watchdog_factor}, floor "
                f"{self.watchdog_floor_s}s); abandoned on its worker "
                "thread and fed to the retry->degrade->quarantine "
                "ladder")
        if "err" in box:
            raise box["err"]
        return box["out"]

    def _try_launch(self, fn, entry, retry_timeouts: bool = True):
        """One width level of the resilience ladder: retry-with-backoff
        around the (watchdog-bounded) launch; raises the last failure
        once retries are exhausted.  `retry_timeouts=False` (the
        bisection's inner nodes) gives a `WatchdogTimeout` ONE attempt:
        a transient hang earns its retries at full width, but once the
        ladder is bisecting a wedged device every subset would time out
        identically — re-retrying each one would multiply the total
        stall by (max_retries+1) for no information."""
        call = self.launcher or (lambda f, *a: f(*a))
        ins = self._ins
        last = None
        for attempt in range(self.max_retries + 1):
            t0 = 0.0 if ins is None else ins.now()
            try:
                out = self._call_bounded(call, fn, entry)
                if ins is not None:
                    from .instrument import SPAN_LAUNCH
                    ins.end(SPAN_LAUNCH, t0, attempt=attempt)
                return out
            except Exception as e:      # noqa: BLE001 — retry any launch
                last = e
                if ins is not None:
                    from .instrument import MARK_RETRY, SPAN_LAUNCH
                    ins.end(SPAN_LAUNCH, t0, attempt=attempt,
                            error=type(e).__name__)
                if isinstance(e, WatchdogTimeout) and not retry_timeouts:
                    break
                if attempt < self.max_retries:
                    with self._mu:
                        self.resilience["retries"] += 1
                    if ins is not None:
                        ins.mark(MARK_RETRY, attempt=attempt,
                                 error=type(e).__name__)
                    if self.retry_backoff_s:
                        time.sleep(self.retry_backoff_s * (2 ** attempt))
        raise last

    def _launch(self, fn, entry, widths, engine: str, has_plane: bool,
                _nested: bool = False):
        """Run one chunk program through the full resilience ladder:
        retry-with-backoff (+ watchdog), then batch-width degradation,
        then poison-lane quarantine (module docstring).  `entry` is
        the concatenated (net, pstate) batch; `widths` the per-lane
        seed counts — the only legal split points (a lane's seeds stay
        together so carry slicing by lane offset keeps working).

        Returns ``(out, lane_errors)``: `lane_errors` has one entry
        per lane (None = healthy); `out` is the chunk result for the
        healthy lanes ONLY, concatenated in lane order (None when every
        lane failed).  The halving recursion IS the bisection —
        isolating one poison lane among 2^k costs log2 launches, and
        every healthy lane's result comes from a launch that ran it
        (possibly at reduced width — bit-identical per lane, since a
        lane's trajectory never depends on its batch neighbors).

        Wedged-device bound: only the TOP-level attempt retries a
        `WatchdogTimeout` (`_try_launch(retry_timeouts=)`), so a fully
        hung device costs at most (max_retries + deadline-per-
        bisection-node) deadlines before the all-lanes-failed
        dead-device raise — bounded per launch attempt, never by the
        hang itself."""
        try:
            return (self._try_launch(fn, entry,
                                     retry_timeouts=not _nested),
                    [None] * len(widths))
        except Exception as e:      # noqa: BLE001 — the ladder continues
            if len(widths) == 1:
                # bottom of the bisection: exactly this lane is the
                # poison — the caller quarantines its request alone
                return None, [e]
            # graceful degradation: halve the lane batch and run the
            # halves sequentially instead of dropping the requests
            with self._mu:
                self.resilience["demotions"] += 1
            if self._ins is not None:
                from .instrument import MARK_DEGRADE
                self._ins.mark(MARK_DEGRADE, lanes=len(widths),
                               error=type(e).__name__)
            mid = len(widths) // 2
            w_left = int(sum(widths[:mid]))
            left, right = self._split_state(entry, w_left)
            out_l, err_l = self._launch(fn, left, widths[:mid], engine,
                                        has_plane, _nested=True)
            out_r, err_r = self._launch(fn, right, widths[mid:],
                                        engine, has_plane,
                                        _nested=True)
            errs = err_l + err_r
            if out_l is None:
                return out_r, errs
            if out_r is None:
                return out_l, errs
            return self._combine(out_l, out_r, engine, has_plane), errs

    def _quarantine(self, ln: _Lane, err: Exception):
        """Settle ONE poison request (module docstring): status error
        with a `quarantined` artifact, its own ledger row (extra
        carries `quarantined` + the chunk boundary it died at), a
        per-tenant stat and a journal tombstone — its coalesced
        neighbors keep running untouched."""
        req = ln.req
        spec = req.spec
        requested = req.requested or spec
        msg = (f"quarantined: the lane bisection isolated this request "
               f"after retry+width-degradation failed — "
               f"{type(err).__name__}: {err!s:.300}")
        art = {"request": req.id, "compile_key": req.compile_key,
               "quarantined": True, "error": msg,
               "spec_digest": requested.digest(),
               "spec": requested.to_json(),
               "seeds": list(spec.seeds), "sim_ms": spec.sim_ms,
               "tenant": spec.tenant, "progress_ms": req.progress_ms}
        line = {"metric": f"serve_{req.id}", "sim_ms": spec.sim_ms,
                "superstep": spec.superstep, "batch": len(spec.seeds),
                "quarantined": True}
        req.ledger_extra = {**(req.ledger_extra or {}),
                            "quarantined": True,
                            "quarantined_at_ms": req.progress_ms}
        path = self._append_ledger(req, line)
        with self._mu:
            self.resilience["quarantined"] += 1
            st = self._tstat(spec.tenant)
            st["quarantined"] = st.get("quarantined", 0) + 1
            st["errors"] += 1
            req.artifacts = art
            req.status, req.error = "error", msg
            req.finished = time.time()
            req.manifest_path = path
            self._evict_old_done()
            # the stream long-poll must terminate with this final
            # quarantined record, not hang until its client timeout
            self._boundary.notify_all()
        if self.journal is not None:
            self.journal.record_settled(req.id, "quarantined")
        if self._ins is not None:
            from .instrument import MARK_QUARANTINE
            self._ins.mark(MARK_QUARANTINE, rid=req.id,
                           key=req.compile_key, tenant=spec.tenant,
                           at_ms=req.progress_ms)
        import sys
        print(f"serve: QUARANTINED request {req.id} "
              f"({spec.tenant}/{req.label or 'serve'}): {msg}",
              file=sys.stderr)

    def _quarantine_failed(self, lanes: list, lane_errors: list,
                           *trees):
        """Quarantine every lane with a recorded error and narrow the
        given state trees (seed axis) to the survivors.  Returns
        ``(surviving_lanes, *narrowed_trees)`` (trees become None when
        no lane survives)."""
        offsets = np.cumsum([0] + [ln.width for ln in lanes])
        keep_lanes, keep_idx = [], []
        for ln, lo, err in zip(lanes, offsets, lane_errors):
            if err is None:
                keep_lanes.append(ln)
                keep_idx.extend(range(int(lo), int(lo) + ln.width))
            else:
                self._quarantine(ln, err)
        narrowed = tuple(
            self._take_lanes(t, keep_idx) if keep_lanes and t is not None
            else None
            for t in trees)
        return (keep_lanes, *narrowed)

    # -------------------------------------------------------- checkpoints

    def _ckpt_path(self, key: str) -> str | None:
        if not self.checkpoint_dir:
            return None
        import os
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        # fleet workers share one checkpoint_dir: the worker prefix
        # keeps two workers running the same compile key from
        # clobbering each other's boundary file (single-process
        # filenames are unchanged)
        tag = f"{self.worker_id}-" if self.worker_id else ""
        return os.path.join(self.checkpoint_dir,
                            f"group-{tag}{key[:16]}.npz")

    def _save_checkpoint(self, key: str, lanes: list, state):
        """Write the group's chunk-boundary state + request metadata
        (atomic replace — a crash mid-write leaves the previous
        boundary's file intact).  Never raises into the drain loop:
        checkpointing is insurance, not a dependency."""
        path = self._ckpt_path(key)
        if path is None:
            return
        import os

        from ..utils import checkpoint
        meta = {"compile_key": key, "schema": CKPT_META_SCHEMA,
                "worker": self.worker_id,
                "requests": [
                    {"id": ln.req.id,
                     "spec": ln.req.spec.to_json(),
                     # the resume-time staleness gate: the stored spec
                     # must still digest to this value, or the file
                     # predates a spec edit and is refused
                     "spec_digest": ln.req.spec.digest(),
                     "requested": (ln.req.requested
                                   or ln.req.spec).to_json(),
                     "progress_ms": ln.req.progress_ms,
                     "width": ln.width,
                     "label": ln.req.label,
                     "ledger_extra": ln.req.ledger_extra}
                    for ln in lanes]}
        try:
            tmp = path + ".tmp.npz"
            checkpoint.save(tmp, state[0], state[1], meta=meta)
            os.replace(tmp, path)
        except Exception as e:      # noqa: BLE001 — insurance only
            import sys
            print(f"serve: checkpoint write failed for group {key[:8]}: "
                  f"{type(e).__name__}: {e!s:.200}", file=sys.stderr)

    def _drop_checkpoint(self, key: str):
        path = self._ckpt_path(key)
        if path is None:
            return
        import contextlib
        import os
        with contextlib.suppress(OSError):
            os.remove(path)

    def discard_checkpoint(self, key: str):
        """Drop one compile key's group checkpoint file (public seam:
        the matrix driver's memo resume discards mid-prefix checkpoints
        — a prefix resumed without its pre-crash obs carries could not
        stitch full-span artifacts for its forked cells, so the prefix
        re-runs instead)."""
        self._drop_checkpoint(key)

    def resume_checkpoints(self, accept=None) -> list:
        """Re-enqueue every interrupted group found in
        `checkpoint_dir`; returns the re-created request ids.  Each
        request resumes from its group's last written chunk boundary —
        the continuation is bit-identical to an uninterrupted run
        (chunk-boundary restore of the deterministic pure engine), so
        `first_divergence`-style full-pytree comparison passes
        (tests/test_serve_resilience.py).  Run `run_pending()` (or the
        service worker) afterwards to drive them to completion.

        `accept(path, meta) -> bool` filters candidate files by their
        metadata BEFORE the leaf arrays load (the fleet seam: a
        survivor adopting a dead worker's checkpoints must take only
        files whose every request it holds the lease for — adopting a
        LIVE worker's file would fork the run's identity).  None
        accepts everything (the single-process restart).

        Staleness refusal (module docstring): a `StaleCheckpointError`
        — checkpoint meta from another schema, or a stored spec that
        no longer digests to its recorded `spec_digest` — RAISES
        through with remedy text; any other failure (torn file,
        garbage .npz) keeps the PR-10
        one-bad-file-must-not-block-the-others behavior."""
        import glob
        import os
        if not self.checkpoint_dir:
            return []
        resumed = []
        for path in sorted(glob.glob(os.path.join(
                self.checkpoint_dir, "group-*.npz"))):
            try:
                resumed += self._resume_one(path, accept=accept)
            except StaleCheckpointError:
                raise       # a staleness refusal, never swallowed
            except Exception as e:      # noqa: BLE001 — one bad file
                # must not block the others
                import sys
                print(f"serve: checkpoint resume failed for {path}: "
                      f"{type(e).__name__}: {e!s:.300}", file=sys.stderr)
        return resumed

    def _resume_one(self, path: str, accept=None) -> list:
        from ..utils import checkpoint
        specs_meta = checkpoint.peek_meta(path)
        for problem in checkpoint.stale_meta_problems(specs_meta):
            raise StaleCheckpointError(
                f"serve: refusing checkpoint {path}: {problem}. "
                "Fix: delete the stale file (the run restarts from "
                "scratch), or resume with the tree/spec that wrote it")
        if accept is not None and not accept(path, specs_meta):
            return []
        ins = self._ins
        t0 = 0.0 if ins is None else ins.now()
        reqs_meta = specs_meta["requests"]
        spec0 = ScenarioSpec.from_json(reqs_meta[0]["spec"])
        proto = spec0.build_protocol()
        net, ps, _ = checkpoint.load(path, proto, seed=0)
        rids = []
        lo = 0
        with self._mu:
            for rm in reqs_meta:
                spec = ScenarioSpec.from_json(rm["spec"])
                w = int(rm["width"])
                sl = jax.tree.map(lambda x, lo=lo, w=w: x[lo:lo + w],
                                  (net, ps))
                lo += w
                rid = rm["id"] if rm["id"] not in self._requests \
                    else self._rid_locked()
                req = Request(
                    id=rid, spec=spec,
                    compile_key=specs_meta["compile_key"],
                    requested=ScenarioSpec.from_json(rm["requested"]),
                    label=rm.get("label"),
                    ledger_extra=rm.get("ledger_extra"))
                req.progress_ms = int(rm["progress_ms"])
                req.resumed_from_ms = int(rm["progress_ms"])
                req.restored_state = sl
                self._requests[rid] = req
                self._queue.append(rid)
                rids.append(rid)
            self.resilience["resumed"] += len(rids)
        if ins is not None:
            from .instrument import SPAN_RESUME
            t1 = ins.now()
            resumed_at = {}
            with self._mu:
                for rid in rids:
                    r = self._requests[rid]
                    r.enq_mono = t1
                    resumed_at[rid] = r.resumed_from_ms
            attrs = {"key": specs_meta["compile_key"]}
            if specs_meta.get("worker") is not None:
                attrs["from_worker"] = specs_meta["worker"]
            for rid in rids:
                ins.end(SPAN_RESUME, t0, t1, rid=rid,
                        from_ms=resumed_at[rid], **attrs)
        # adoption CONSUMES a foreign worker's file: this scheduler
        # checkpoints the group under its OWN name from the next
        # boundary on, so a dead worker's file left behind would go
        # stale immediately — and a stale same-key file is exactly
        # what a second adopter could fork the run's identity from.
        # (Our own file keeps the PR-15 lifecycle: overwritten each
        # boundary, dropped at group completion.)
        import contextlib
        import os
        own = self._ckpt_path(specs_meta["compile_key"])
        if own is not None and os.path.abspath(path) != \
                os.path.abspath(own):
            with contextlib.suppress(OSError):
                os.remove(path)
        return rids

    # ------------------------------------------------------------ journal

    def resume_journal(self) -> list:
        """Replay the durable submission journal (module docstring):
        every un-tombstoned entry re-enters the queue from its
        journaled spec, with its ORIGINAL request id, label and
        ledger_extra.  Run AFTER `resume_checkpoints()` — `recover()`
        orders the two — so a request that ALSO left a group
        checkpoint resumes from the checkpoint (its rid is already
        live here and the journal entry is skipped), never re-run from
        scratch.  A second replay is a no-op: duplicate rids are
        refused with a stderr note.  Finishes by compacting the
        journal down to the live entries.  Returns the re-enqueued
        request ids."""
        if self.journal is None:
            return []
        entries = self.journal.replay()
        rids = []
        with self._mu:
            for e in entries:
                rid = self._adopt_entry_locked(e)
                if rid is not None:
                    rids.append(rid)
            self.resilience["replayed"] += len(rids)
        if self._ins is not None and rids:
            from .instrument import SPAN_REPLAY
            for rid in rids:
                self._ins.mark(SPAN_REPLAY, rid=rid)
        self.journal.compact()
        return rids

    def _adopt_entry_locked(self, e: dict,
                            fork: ForkState | None = None,
                            keep_carries: bool = False) -> str | None:
        """Re-enqueue ONE journal entry under its original rid (caller
        holds the lock).  Returns the rid, or None when refused
        (already live — re-running a live request would fork its
        identity) or skipped (no longer validates) — both with the
        stderr notes the crash tests pin.  `fork` enters the adopted
        request mid-run from a memo-table prefix (the fleet search
        path); a fork that no longer validates degrades LOUDLY to an
        unforked full-span re-run, which is bit-identical."""
        import sys
        rid = e.get("rid")
        if rid in self._requests:
            print(f"serve: journal entry {rid} is already "
                  "live (checkpoint-resumed or double "
                  "replay); refused", file=sys.stderr)
            return None
        try:
            spec = ScenarioSpec.from_json(e["spec"])
            resolved = spec.validate()
        except (KeyError, ValueError, TypeError) as err:
            print(f"serve: journal entry {rid} no longer "
                  f"validates ({err!s:.200}); skipped — the "
                  "request must be re-submitted under the "
                  "current tree", file=sys.stderr)
            return None
        if fork is not None:
            try:
                self._check_fork(resolved, fork)
            except ValueError as err:
                print(f"serve: journal entry {rid} fork rejected "
                      f"({err!s:.200}); adopting unforked — the "
                      "full-span re-run is bit-identical",
                      file=sys.stderr)
                fork = None
        extra = dict(e.get("ledger_extra") or {})
        # an UNFORKED replay re-runs its full span (the fork state
        # died with the process): the provenance must not claim a
        # fork the re-run didn't take.  A memo-table fork below
        # re-stamps it.
        extra.pop("forked_from", None)
        req = Request(id=rid, spec=resolved,
                      compile_key=resolved.compile_key(),
                      requested=spec, label=e.get("label"),
                      keep_carries=bool(keep_carries),
                      ledger_extra=extra or None)
        if self._ins is not None:
            req.enq_mono = self._ins.now()
        if fork is not None:
            req.restored_state = fork.state
            req.saved_carries = {p: list(cs) for p, cs
                                 in (fork.carries or {}).items()}
            req.progress_ms = int(fork.at_ms)
            req.forked_from = {"prefix_digest": fork.prefix_digest,
                               "fork_ms": int(fork.at_ms)}
            req.ledger_extra = {**(req.ledger_extra or {}),
                                "forked_from": dict(req.forked_from)}
            self.memo["forked"] += 1
        self._requests[rid] = req
        self._queue.append(rid)
        return rid

    def adopt_journal_entry(self, entry: dict,
                            fork: ForkState | None = None,
                            keep_carries: bool = False) -> str | None:
        """Re-enqueue ONE journal entry under its original rid — the
        fleet worker's per-lease admission path (`resume_journal` is
        the adopt-everything restart variant; a fleet worker adopts
        exactly the entries whose lease it holds, so it must not
        vacuum the whole journal).  `fork` / `keep_carries` mirror
        `submit` — the fleet memo-table seam: a worker that finds the
        entry's honest prefix in the shared table enters it mid-run.
        Counts into ``resilience["replayed"]``; returns the rid or
        None."""
        with self._mu:
            rid = self._adopt_entry_locked(entry, fork=fork,
                                           keep_carries=keep_carries)
            if rid is not None:
                self.resilience["replayed"] += 1
        if rid is not None and self._ins is not None:
            from .instrument import SPAN_REPLAY
            self._ins.mark(SPAN_REPLAY, rid=rid)
        return rid

    def recover(self) -> dict:
        """Crash-only restart, one call: checkpoints first (mid-run
        groups restore their chunk-boundary state under their original
        ids), then the journal (queued-but-unlaunched submits replay
        from their specs; entries a checkpoint already restored are
        skipped by rid).  Returns the two request-id lists.  Drive
        with `run_pending()` (or the service worker) afterwards."""
        return {"checkpoints": self.resume_checkpoints(),
                "journal": self.resume_journal()}

    # -------------------------------------------------------------- health

    def health_stats(self) -> dict:
        """The `/w/batch/health` block: uptime, per-tenant queue
        depths, journal lag (accepted-but-unsettled entries),
        quarantine count, watchdog trips and the chunk-wall EMA — the
        numbers an operator needs to decide whether a serve process is
        healthy, wedged, or bleeding requests."""
        # journal lag reads the WAL file — outside the lock (IO)
        lag = self.journal.lag() if self.journal is not None else None
        deadline = self.launch_deadline_s()
        with self._mu:
            queued: dict = {}
            running = 0
            for r in self._requests.values():
                if r.status == "queued":
                    queued[r.spec.tenant] = queued.get(r.spec.tenant,
                                                       0) + 1
                elif r.status == "running":
                    running += 1
            out = {"uptime_s": round(time.time() - self._t0, 3),
                   "queued": sum(queued.values()),
                   "queued_by_tenant": queued,
                   "running": running,
                   "submitted": self._n,
                   "journal": self.journal is not None,
                   "journal_lag": lag,
                   "quarantined": self.resilience["quarantined"],
                   "watchdog_trips": self.resilience["watchdog_trips"],
                   "watchdog_deadline_s": (round(deadline, 3)
                                           if deadline is not None
                                           else None),
                   "chunk_wall_ema_s": round(self.chunk_wall_ema_s, 4),
                   "resilience": dict(self.resilience),
                   "draining": self._draining}
        if self._ins is not None:
            # span-derived phase p50/p99 (queue-wait/compile/launch) —
            # the EMA says how long a chunk takes, this says where a
            # request's wall actually went (outside the lock: reads
            # the recorder's own ring under its own lock)
            out["phases"] = self._ins.health_phases()
        return out

    # --------------------------------------------------------- preemption

    def _waiting_elsewhere(self, key: str, engine: str,
                           progress_ms: int | None = None) -> list:
        """Queued requests that CANNOT join the running group (caller
        holds the lock): a different compile key, or a lockstep lane
        the repack admission can't absorb at the group's current
        boundary (fresh request, or a restored one at a different
        progress).  Only these justify yielding — a same-key vmapped
        request late-joins for free, and a same-key restored lockstep
        request at the group's progress repacks in for free too."""
        out = []
        for rid in self._queue:
            r = self._requests[rid]
            if r.compile_key != key:
                out.append(r)
            elif engine != "vmapped" and not (
                    progress_ms is not None
                    and r.progress_ms == progress_ms
                    and r.restored_state is not None):
                out.append(r)
        return out

    def _should_yield(self, key: str, lanes: list, chunks_run: int,
                      budget: int | None) -> str | None:
        """The chunk-boundary preemption decision (module docstring).
        Returns the reason ("priority" | "slice" | "deadline") or
        None."""
        engine = lanes[0].req.spec.engine
        now = time.time()
        with self._mu:
            others = self._waiting_elsewhere(
                key, engine, progress_ms=lanes[0].req.progress_ms)
            if not others:
                return None
            group_pri = max(ln.req.spec.priority for ln in lanes)
            if any(r.spec.priority > group_pri for r in others):
                return "priority"
            if budget is not None and chunks_run >= budget:
                return "slice"
            deadlines = [d for d in (ln.req.deadline_at for ln in lanes)
                         if d is not None]
            if deadlines and all(now >= d for d in deadlines):
                # every deadline-CARRYING lane blew its wall budget:
                # the group no longer holds the device against waiting
                # work (soft — the run continues on a later rotation,
                # never killed; deadline-less lanes ride the yield and
                # resume bit-identically)
                return "deadline"
        return None

    def _preempt(self, key: str, lanes: list, state, ff_stats,
                 reason: str):
        """Yield at a chunk boundary: slice each lane's state out of
        the batch and re-enqueue its request carrying that state (and
        its stashed obs carries) — the in-memory twin of the group
        checkpoint, consumed by `_init_lanes` exactly like a
        checkpoint restore, so the continuation is bit-identical."""
        offsets = np.cumsum([0] + [ln.width for ln in lanes])
        slices = [jax.tree.map(
            lambda x, lo=int(lo), w=ln.width: x[lo:lo + w], state)
            for ln, lo in zip(lanes, offsets)]
        ins = self._ins
        t_pre = 0.0 if ins is None else ins.now()
        with self._mu:
            self.resilience["preemptions"] += 1
            for ln, sl in zip(lanes, slices):
                req = ln.req
                req.restored_state = sl
                req.saved_carries = ln.carries
                if ff_stats is not None:
                    acc = req.ff_accum or {"skipped_ms": 0,
                                           "jump_count": 0}
                    req.ff_accum = {k: acc[k] + ff_stats[k]
                                    for k in acc}
                req.preempted += 1
                req.status = "queued"
                if ins is not None:
                    # queue-wait restarts at the re-enqueue boundary
                    req.enq_mono = t_pre
                self._queue.append(req.id)
                self._tstat(req.spec.tenant)["preemptions"] += 1
        if ins is not None:
            from .instrument import MARK_PREEMPT
            for ln in lanes:
                ins.mark(MARK_PREEMPT, rid=ln.req.id, key=key,
                         reason=reason,
                         at_ms=ln.req.progress_ms)

    # ------------------------------------------------------------ the run

    def _run_group(self, key: str,
                   budget_chunks: int | None = None) -> tuple:
        """Run one compile-key group until it finishes or yields
        (`_should_yield`); returns ``(requests_done, chunks_run)``."""
        reqs = self._take_compatible(key)
        if not reqs:
            return 0, 0
        spec0 = reqs[0].spec
        if spec0.engine != "vmapped" and len(reqs) > 1:
            # lockstep engines (one fused mailbox / one shared jump)
            # need equal clocks across the batch: a checkpoint-resumed
            # request's progress differs from a fresh one's, so only
            # same-progress requests group together; the rest go back
            # to the queue head and form the next group
            head_prog = reqs[0].progress_ms
            defer = [r for r in reqs if r.progress_ms != head_prog]
            if defer:
                with self._mu:
                    self._queue[0:0] = [r.id for r in defer]
                reqs = [r for r in reqs if r.progress_ms == head_prog]
        planes = list(spec0.obs)
        primary = "metrics" if "metrics" in planes else None
        shadows = [p for p in planes if p != primary]
        # The per-lane dense engine admits ANY same-key late joiner at
        # every chunk boundary; lockstep engines (one fused mailbox /
        # one shared jump over the whole batch) admit only restored
        # requests whose clock matches the group's — see the repack
        # branch at the bottom of the loop.
        admit_inflight = spec0.engine == "vmapped"
        lanes = [_Lane(r) for r in reqs]
        proto0 = spec0.build_protocol()     # ONE construction per group
        state = self._concat(self._init_lanes(reqs, proto0))
        now = time.time()
        with self._mu:
            for r in reqs:
                r.status, r.started = "running", now
        ins = self._ins
        if ins is not None:
            # queue-wait ends where the group marks its requests
            # running (enq_mono is drain-private once dequeued)
            from .instrument import SPAN_COMPILE, SPAN_QUEUE_WAIT
            t_run = ins.now()
            for r in reqs:
                if r.enq_mono is not None:
                    ins.end(SPAN_QUEUE_WAIT, r.enq_mono, t_run,
                            rid=r.id, key=key, tenant=r.spec.tenant)
                    r.enq_mono = None
        ff_stats = {"skipped_ms": 0, "jump_count": 0}
        done = 0
        chunks_run = 0
        # One registry lookup per plane per GROUP (the programs are
        # constant across chunks) — hit/miss counters then reflect
        # warm/cold submits, not chunk counts.
        t_cmp = 0.0 if ins is None else ins.now()
        fn = self.registry.chunk_fn(spec0, primary, proto=proto0)
        shadow_fns = [(p, self.registry.chunk_fn(spec0, p, proto=proto0))
                      for p in shadows]
        if ins is not None:
            ins.end(SPAN_COMPILE, t_cmp, key=key, lanes=len(reqs))
        freeze_probe = None
        if self.freeze:
            from ..memo import build_probe, freeze_supported
            if freeze_supported(spec0, proto0.cfg):
                freeze_probe = build_probe(proto0)
        while lanes:
            entry = state
            widths = [ln.width for ln in lanes]
            t_chunk = time.time()
            tc0 = 0.0 if ins is None else ins.now()
            out, lane_errs = self._launch(fn, entry, widths,
                                          spec0.engine,
                                          primary is not None)
            if out is None:
                # EVERY lane failed: that is a dead device, not a
                # poison verdict (a bisection that eliminates
                # everything isolated nothing) — keep the PR-10
                # group-failure semantics: raise into _fail_group,
                # group checkpoint RETAINED for a later resume
                raise lane_errs[0]
            if any(e is not None for e in lane_errs):
                # poison-lane quarantine: a lane that failed while its
                # batch siblings succeeded is the poison — it settles
                # alone; `out` already covers the survivors — narrow
                # `entry` to match (the shadow passes below must run
                # the identical surviving batch)
                lanes, entry = self._quarantine_failed(
                    lanes, lane_errs, entry)
                widths = [ln.width for ln in lanes]
            state = (out[0], out[1])
            if spec0.engine == "fast_forward":
                st = out[2]
                ff_stats["skipped_ms"] += int(np.asarray(
                    jax.device_get(st["skipped_ms"])).reshape(-1)[0])
                ff_stats["jump_count"] += int(np.asarray(
                    jax.device_get(st["jump_count"])).reshape(-1)[0])
            offsets = np.cumsum([0] + [ln.width for ln in lanes])
            if primary is not None:
                for ln, lo in zip(lanes, offsets):
                    ln.stash(primary, out[-1], int(lo))
            for plane, sfn in shadow_fns:
                sout, serrs = self._launch(sfn, entry, widths,
                                           spec0.engine, True)
                if sout is None:
                    # whole-batch shadow failure = dead device, like
                    # the primary case above
                    raise serrs[0]
                if any(e is not None for e in serrs):
                    # a lane poisoning only its SHADOW pass is
                    # quarantined too: its state advanced but the
                    # plane carry is unrecoverable, and an artifact
                    # silently missing a requested plane would lie
                    lanes, state, entry = self._quarantine_failed(
                        lanes, serrs, state, entry)
                    widths = [ln.width for ln in lanes]
                    offsets = np.cumsum([0] + [ln.width
                                               for ln in lanes])
                for ln, lo in zip(lanes, offsets):
                    ln.stash(plane, sout[-1], int(lo))
            # snapshots force a device sync — compute them OUTSIDE the
            # lock (lane fields are drain-thread-private; only the
            # request records need the lock) so submit/status threads
            # never stall on a chunk's device_get
            updates = []
            for ln in lanes:
                ln.remaining -= 1
                t_ms = ln.req.progress_ms + spec0.chunk_ms
                updates.append((ln.req, t_ms, self._snapshot(ln, t_ms)))
            with self._mu:
                for req, t_ms, snap in updates:
                    req.progress_ms = t_ms
                    req.progress = snap
                    # the streaming endpoint's backing store: this
                    # boundary's primary-pass totals + their delta vs
                    # the previous boundary (cumulative counters become
                    # per-chunk contributions client-side for free)
                    totals = {k: v for k, v in snap.items()
                              if k not in ("t_ms", "sim_ms")}
                    prev = req.chunk_totals[-1]["totals"] \
                        if req.chunk_totals else {}
                    req.chunk_totals.append(
                        {"t_ms": t_ms, "totals": totals,
                         "delta": {k: v - prev.get(k, 0)
                                   for k, v in totals.items()}})
                self._boundary.notify_all()
            finished = [ln for ln in lanes if ln.remaining == 0]
            if finished:
                for ln, lo in zip(lanes, offsets):
                    if ln.remaining == 0:
                        final = jax.tree.map(
                            lambda x, lo=int(lo), w=ln.width: x[lo:lo + w],
                            state)
                        self._finalize(ln, final,
                                       ff_stats if spec0.engine ==
                                       "fast_forward" else None)
                done += len(finished)
                keep = [i for s, ln in zip(offsets, lanes)
                        if ln.remaining > 0
                        for i in range(int(s), int(s) + ln.width)]
                lanes = [ln for ln in lanes if ln.remaining > 0]
                if lanes:
                    state = self._take_lanes(state, keep)
            if freeze_probe is not None and lanes:
                state, lanes, n_frozen = self._freeze_pass(
                    spec0, proto0, freeze_probe, lanes, state)
                done += n_frozen
            if self.checkpoint_dir:
                if lanes:
                    self._save_checkpoint(key, lanes, state)
                else:
                    self._drop_checkpoint(key)
            chunks_run += 1
            # the retry-after estimate's unit cost: an EMA of one
            # coalesced chunk's wall time (the snapshot above already
            # synced the device, so this is honest compute time)
            dt = time.time() - t_chunk
            with self._mu:      # read by watchdog/health threads
                ema = self.chunk_wall_ema_s
                self.chunk_wall_ema_s = (dt if not ema
                                         else 0.8 * ema + 0.2 * dt)
            if self.catalog is not None:
                # per-launch chunk-wall sample into the program
                # observatory (drift pass: measured walls next to the
                # capture row's predicted/analyzed costs)
                self.catalog.observe_chunk(key, dt, lanes=len(widths))
            if ins is not None:
                from .instrument import SPAN_CHUNK
                ins.end(SPAN_CHUNK, tc0, key=key, lanes=len(widths))
            if self.on_boundary is not None:
                self.on_boundary()
            if lanes:
                reason = self._should_yield(key, lanes, chunks_run,
                                            budget_chunks)
                if reason is not None:
                    self._preempt(key, lanes, state,
                                  ff_stats if spec0.engine ==
                                  "fast_forward" else None, reason)
                    return done, chunks_run
            if admit_inflight:
                joiners = self._take_compatible(key)
            elif lanes:
                # lockstep lane repacking: a restored request
                # (checkpoint, preemption or fork) whose saved boundary
                # equals this group's clock re-enters HERE instead of
                # stranding until the group finishes — equal progress
                # under one compile key means equal device time arrays,
                # so the fused mailbox / shared jump stays sound and
                # the continuation is the same program it would have
                # run solo (the bit-identity tests pin this)
                joiners = self._take_compatible(
                    key, progress_ms=lanes[0].req.progress_ms)
            else:
                joiners = []
            if joiners:
                now = time.time()
                with self._mu:
                    if not admit_inflight:
                        self.resilience["repacked"] += len(joiners)
                    for r in joiners:
                        r.status, r.started = "running", now
                if ins is not None:
                    from .instrument import SPAN_QUEUE_WAIT
                    t_run = ins.now()
                    for r in joiners:
                        if r.enq_mono is not None:
                            ins.end(SPAN_QUEUE_WAIT, r.enq_mono,
                                    t_run, rid=r.id, key=key,
                                    tenant=r.spec.tenant)
                            r.enq_mono = None
                new = self._init_lanes(joiners, proto0)
                state = self._concat(
                    ([state] if lanes else []) + new)
                lanes.extend(_Lane(r) for r in joiners)
        return done, chunks_run

    # -------------------------------------------------------------- memo

    def _freeze_pass(self, spec0, proto0, probe, lanes: list, state):
        """Fixed-point lane freezing at one chunk boundary (module
        docstring + memo/freeze.py): lanes whose every seed's
        `next_work` lands at or past the lane's end are finalized NOW —
        final state via the quiet-window jump, remaining obs carries
        synthesized — and sliced out of the batch, so the surviving
        lanes stop paying for converged neighbors.  Returns the
        narrowed ``(state, lanes, frozen_count)``."""
        nw = np.asarray(jax.device_get(probe(*state))).reshape(-1)
        times = np.asarray(jax.device_get(state[0].time)).reshape(-1)
        offsets = np.cumsum([0] + [ln.width for ln in lanes])
        attack = spec0.attack
        frozen = []
        for ln, lo in zip(lanes, offsets):
            lo = int(lo)
            t_lane = int(times[lo])
            if attack is not None and t_lane <= int(attack["at_ms"]):
                continue        # a pending FaultInjector perturbation
                # is outside the oracle's view — never freeze across it
            t_end = t_lane + ln.remaining * spec0.chunk_ms
            if int(nw[lo:lo + ln.width].min()) >= t_end:
                frozen.append((ln, lo, t_lane, t_end))
        if not frozen:
            return state, lanes, 0
        from ..memo import frozen_carries, frozen_final
        for ln, lo, t_lane, t_end in frozen:
            lane_state = jax.tree.map(
                lambda x, lo=lo, w=ln.width: x[lo:lo + w], state)
            final = frozen_final(proto0.cfg, lane_state, t_end)
            tails = frozen_carries(spec0, proto0.cfg, lane_state,
                                   t_lane, ln.remaining)
            for plane, chunks in tails.items():
                ln.carries.setdefault(plane, []).extend(chunks)
            # the stream must see every boundary the ARTIFACT claims:
            # synthesized tail chunks get their (constant — the lane is
            # a fixed point) totals appended like executed ones, so a
            # /w/batch/stream client and serve_load's --stream smoke
            # count sim_ms/chunk_ms entries whether or not lanes froze
            snap = self._snapshot(ln, t_end)
            totals = {k: v for k, v in snap.items()
                      if k not in ("t_ms", "sim_ms")}
            with self._mu:
                for i in range(int(ln.remaining)):
                    prev = ln.req.chunk_totals[-1]["totals"] \
                        if ln.req.chunk_totals else {}
                    ln.req.chunk_totals.append(
                        {"t_ms": t_lane + (i + 1) * spec0.chunk_ms,
                         "totals": dict(totals),
                         "delta": {k: v - prev.get(k, 0)
                                   for k, v in totals.items()}})
                self._boundary.notify_all()
                ln.req.frozen_from_ms = t_lane
                self.memo["frozen_lanes"] += 1
                self.memo["frozen_chunks"] += int(ln.remaining)
            self._finalize(ln, final, None)
        gone = {id(ln) for ln, *_ in frozen}
        keep = [i for s, ln in zip(offsets, lanes)
                if id(ln) not in gone
                for i in range(int(s), int(s) + ln.width)]
        lanes = [ln for ln in lanes if id(ln) not in gone]
        if lanes:
            state = self._take_lanes(state, keep)
        return state, lanes, len(frozen)

    # ------------------------------------------------------- per-request

    def _snapshot(self, ln: _Lane, t_ms: int) -> dict:
        """Streaming-progress snapshot from the LAST metrics carry (the
        on-device metrics plane is what status() streams); falls back
        to the clock alone when metrics are off.  Forces a device sync
        — callers run it outside the scheduler lock."""
        snap = {"t_ms": t_ms, "sim_ms": ln.req.spec.sim_ms}
        carries = ln.carries.get("metrics")
        if carries:
            from ..obs.export import MetricsFrame
            from ..obs.spec import MetricsSpec
            mspec = MetricsSpec(stat_each_ms=ln.req.spec.stat_each_ms)
            totals = MetricsFrame.from_carry(mspec, carries[-1]).totals()
            for name in ("done_count", "live_count", "msg_sent",
                         "drop_count"):
                if name in totals:
                    snap[name] = totals[name]
        return snap

    def _finalize(self, ln: _Lane, final_state, ff_stats):
        ins = self._ins
        t_set = 0.0 if ins is None else ins.now()
        req, spec = ln.req, ln.req.spec
        proto_cfg = req.cfg
        requested = req.requested or spec
        art = {"request": req.id, "compile_key": req.compile_key,
               "spec_digest": requested.digest(),
               "spec": requested.to_json(),
               "seeds": list(spec.seeds), "sim_ms": spec.sim_ms,
               "engine": spec.engine, "superstep": spec.superstep}
        nodes = final_state[0].nodes
        down = np.asarray(nodes.down)
        done_at = np.asarray(nodes.done_at)
        art["summary"] = {
            "done_count": int(((done_at > 0) & ~down).sum()),
            "live_count": int((~down).sum()),
            "msg_sent": int(np.asarray(nodes.msg_sent).sum()),
            "msg_received": int(np.asarray(nodes.msg_received).sum()),
        }
        if ff_stats is not None:
            acc = req.ff_accum or {}
            art["fast_forward"] = {k: ff_stats[k] + acc.get(k, 0)
                                   for k in ff_stats}   # group-level
        with self._mu:      # watchdog/retry threads mutate counters
            art["resilience"] = dict(self.resilience)   # scheduler-level
        art["tenant"] = spec.tenant
        if req.preempted:
            art["preempted"] = req.preempted
        if req.forked_from:
            # snapshot-fork provenance: the artifacts (and the ledger
            # row, via ledger_extra at submit) name the honest prefix
            # this request entered from, so verification tooling checks
            # forked cells against sequential twins instead of skipping
            art["forked_from"] = dict(req.forked_from)
        if req.frozen_from_ms is not None:
            art["memo"] = {"frozen_from_ms": req.frozen_from_ms,
                           "frozen_chunks":
                           (spec.sim_ms - req.frozen_from_ms)
                           // spec.chunk_ms}
        line = {"metric": f"serve_{req.id}", "sim_ms": spec.sim_ms,
                "superstep": spec.superstep, "batch": len(spec.seeds)}
        if req.resumed_from_ms:
            # the obs-plane blocks below cover the post-restore span;
            # the trajectory itself is bit-identical to an
            # uninterrupted run (module docstring)
            art["resumed_from_ms"] = req.resumed_from_ms
            line["resumed_from_ms"] = req.resumed_from_ms
        if "metrics" in ln.carries:
            from ..obs.export import MetricsFrame, engine_metrics_block
            from ..obs.spec import MetricsSpec
            mspec = MetricsSpec(stat_each_ms=spec.stat_each_ms)
            frame = MetricsFrame.from_carries(mspec, ln.carries["metrics"])
            art["engine_metrics"] = engine_metrics_block(
                frame, extra={"metrics_seeds": len(spec.seeds)})
            line["engine_metrics"] = art["engine_metrics"]
        if "trace" in ln.carries:
            from ..obs.decode import TraceFrame, trace_block
            from ..obs.trace import TraceSpec
            tspec = TraceSpec(capacity=spec.trace_capacity)
            tframe = TraceFrame.from_carries(tspec, ln.carries["trace"])
            art["trace"] = trace_block(tframe,
                                       extra={"trace_seeds":
                                              len(spec.seeds)})
            line["trace"] = art["trace"]
        if "audit" in ln.carries:
            from ..obs.audit import AuditSpec, monitored_invariants
            from ..obs.audit_report import AuditReport, audit_block
            aspec = AuditSpec()
            report = AuditReport.from_carries(
                aspec, ln.carries["audit"],
                monitored=monitored_invariants(aspec, proto_cfg))
            art["audit"] = audit_block(report,
                                       extra={"audit_seeds":
                                              len(spec.seeds)})
            line["audit"] = art["audit"]
            if not report.clean:
                import sys
                print(f"serve: AUDIT VIOLATIONS in request {req.id}:\n"
                      f"{report.format()}", file=sys.stderr)
        now = time.time()
        wall = now - (req.started or now)
        if req.deadline_at is not None and now > req.deadline_at:
            # observability only — a deadline demotes the request's
            # hold on the device (_should_yield), it never kills it
            art["deadline_missed"] = True
        line["wall_total_s"] = round(wall, 3)
        # durable completion facts ride the ledger row's extra: the
        # matrix driver's campaign resume / cross-grid dedup rebuilds
        # a finished cell's report row from them without re-running it
        durable = {"summary": dict(art["summary"])}
        if "engine_metrics" in art:
            from ..obs.export import time_to_done_ms
            ttd = time_to_done_ms(art["engine_metrics"])
            if ttd is not None:
                durable["time_to_done_ms"] = ttd
        if "audit" in art and not art["audit"]["clean"]:
            durable["violations"] = {
                k: v for k, v in art["audit"]["violations"].items() if v}
        req.ledger_extra = {**(req.ledger_extra or {}), **durable}
        if ins is not None:
            # the scrapeable registry's state at settle time rides the
            # ledger row — a campaign postmortem reads the metric
            # trajectory from the rows alone, no scraper needed
            from .instrument import ledger_metrics_block
            line["host_metrics"] = ledger_metrics_block(self)
        path = self._append_ledger(req, line)
        art["wall_s"] = round(wall, 3)
        art["registry"] = self.registry.stats()
        with self._mu:
            self._tstat(spec.tenant)["done"] += 1
            req.artifacts = art
            req.final_state = final_state
            if req.keep_carries:
                req.final_carries = {p: list(cs)
                                     for p, cs in ln.carries.items()}
            req.finished = now
            req.manifest_path = path
            req.progress_ms = spec.sim_ms
            req.status = "done"
            self._evict_old_done()
            self._boundary.notify_all()     # wake stream long-polls
        if self.journal is not None:
            self.journal.record_settled(req.id, "done")
        if ins is not None:
            from .instrument import SPAN_SETTLE
            ins.end(SPAN_SETTLE, t_set, rid=req.id,
                    key=req.compile_key, tenant=spec.tenant,
                    wall_s=round(wall, 3))

    def _evict_old_done(self):
        """Drop the oldest finished records past `keep_done` (caller
        holds the lock).  Their ledger rows remain the durable
        artifact; in-memory final_state/artifacts are what must not
        accumulate in a long-lived service."""
        if not self.keep_done:
            return
        done = sorted((r for r in self._requests.values()
                       if r.status in ("done", "error")),
                      key=lambda r: r.finished or r.submitted)
        for victim in done[:max(0, len(done) - self.keep_done)]:
            del self._requests[victim.id]

    def _append_ledger(self, req: Request, line: dict) -> str | None:
        """One `RunManifest` row per request; the config digest IS the
        AS-SUBMITTED spec digest (the PR-6 ledger's promised
        ScenarioSpec hookup — bench and the suite digest their
        requested configs too, so rows correlate across all three).
        The line's own engine/superstep fields carry the resolved
        dispatch.  Never raises — provenance must not fail a finished
        request."""
        from ..obs import ledger
        try:
            mani = ledger.manifest_from_spec(
                line, req.requested or req.spec,
                label=req.label or f"serve:{req.id}",
                compile_key=req.compile_key,
                **(req.ledger_extra or {}))
            return ledger.append(mani, self.ledger_path)
        except Exception as e:      # noqa: BLE001 — provenance only
            import sys
            print(f"serve: ledger append failed for {req.id}: "
                  f"{type(e).__name__}: {e!s:.200}", file=sys.stderr)
            return None
