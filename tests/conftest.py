"""Test harness platform setup.

Force an 8-device virtual CPU mesh so sharding paths are exercised without
TPU hardware (the driver separately dry-runs the multi-chip path).  The
sandbox's sitecustomize imports jax and registers a TPU plugin before pytest
starts, so the env-var route is too late — but backends are not initialized
yet, so `jax.config.update` still wins, and XLA_FLAGS is read at CPU-client
init (first device use), which also happens later.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
