"""Bit-equality of the fused Pallas delivery-merge kernel
(ops/pallas_merge.py, interpret mode on CPU) against the reference XLA
implementation `_levels.merge_bounded_queue` — every output column,
including the junk lvl/rank/sig values carried by invalid slots.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from wittgenstein_tpu.models._levels import merge_bounded_queue
from wittgenstein_tpu.ops.pallas_merge import merge_queue_pallas


def _random_case(rng, n, q_cap, s_cap, w, n_ids, dup_rate=0.3,
                 fill=0.7):
    """A randomized (queue, inbox) pair with deliberate (sender, level)
    collisions across inbox slots and against the queue."""
    q_from = np.where(rng.random((n, q_cap)) < fill,
                      rng.integers(0, n_ids, (n, q_cap)), -1).astype(
                          np.int32)
    q_lvl = rng.integers(0, 8, (n, q_cap)).astype(np.int32)
    q_rank = rng.integers(0, 2 * n_ids, (n, q_cap)).astype(np.int32)
    q_bad = rng.random((n, q_cap)) < 0.2
    q_sig = rng.integers(0, 2 ** 32, (n, q_cap, w), dtype=np.uint32)

    src = rng.integers(0, n_ids, (n, s_cap)).astype(np.int32)
    level = rng.integers(0, 8, (n, s_cap)).astype(np.int32)
    # Planted collisions: some inbox slots repeat another slot's
    # (sender, level); some repeat a queued entry's.
    for i in range(n):
        for s in range(s_cap):
            r = rng.random()
            if r < dup_rate and s > 0:
                s2 = rng.integers(0, s)
                src[i, s] = src[i, s2]
                level[i, s] = level[i, s2]
            elif r < 2 * dup_rate:
                qq = rng.integers(0, q_cap)
                if q_from[i, qq] >= 0:
                    src[i, s] = q_from[i, qq]
                    level[i, s] = q_lvl[i, qq]
    rank_all = rng.integers(0, 2 * n_ids, (n, s_cap)).astype(np.int32)
    ok = rng.random((n, s_cap)) < 0.6
    sig_all = rng.integers(0, 2 ** 32, (n, s_cap, w), dtype=np.uint32)
    return (jnp.asarray(q_from), jnp.asarray(q_lvl), jnp.asarray(q_rank),
            jnp.asarray(q_bad), jnp.asarray(q_sig), jnp.asarray(src),
            jnp.asarray(level), jnp.asarray(rank_all), jnp.asarray(ok),
            jnp.asarray(sig_all))


def _reference(q_from, q_lvl, q_rank, q_bad, q_sig, src, level,
               rank_all, ok, sig_all, q_cap):
    sel2, sel3, ev = merge_bounded_queue(
        q_from, q_lvl, q_rank, src, level, rank_all, ok, q_cap,
        {"bad": (q_bad, jnp.zeros_like(ok))},
        {"sig": (q_sig, sig_all)})
    return (sel2["from"], sel2["lvl"], sel2["rank"], sel2["bad"],
            sel3["sig"], ev)


@pytest.mark.parametrize("q_cap,s_cap,w", [(16, 12, 8), (8, 4, 2),
                                           (4, 16, 4)])
def test_merge_kernel_bit_equal(q_cap, s_cap, w):
    rng = np.random.default_rng(q_cap * 100 + s_cap)
    args = _random_case(rng, 64, q_cap, s_cap, w, n_ids=256)
    ref = _reference(*args, q_cap=q_cap)
    got = merge_queue_pallas(*args, q_cap=q_cap, interpret=True)
    for name, r, g in zip(("from", "lvl", "rank", "bad", "sig",
                           "evicted"), ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg=name)


def test_merge_kernel_empty_and_full():
    """All-empty queue + all-valid inbox, and full queue + no valid
    incoming — the two boundary regimes."""
    rng = np.random.default_rng(7)
    q_cap, s_cap, w = 8, 8, 4
    args = list(_random_case(rng, 32, q_cap, s_cap, w, n_ids=128))
    # empty queue
    a = list(args)
    a[0] = jnp.full_like(a[0], -1)
    a[8] = jnp.ones_like(a[8])                  # all ok
    ref = _reference(*a, q_cap=q_cap)
    got = merge_queue_pallas(*a, q_cap=q_cap, interpret=True)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # full queue, nothing valid incoming
    b = list(args)
    b[0] = jnp.abs(b[0])                        # all filled
    b[8] = jnp.zeros_like(b[8])                 # nothing ok
    ref = _reference(*b, q_cap=q_cap)
    got = merge_queue_pallas(*b, q_cap=q_cap, interpret=True)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_merge_kernel_rank_ties():
    """Equal ranks across existing and incoming: existing entries must
    win, then incoming by slot order (the position tie-break)."""
    q_cap, s_cap, w = 4, 4, 2
    n = 16
    q_from = jnp.full((n, q_cap), 5, jnp.int32)
    q_lvl = jnp.asarray(np.tile(np.arange(q_cap, dtype=np.int32),
                                (n, 1)))
    q_rank = jnp.full((n, q_cap), 7, jnp.int32)
    q_bad = jnp.zeros((n, q_cap), bool)
    q_sig = jnp.asarray(
        np.arange(n * q_cap * w, dtype=np.uint32).reshape(n, q_cap, w))
    src = jnp.full((n, s_cap), 9, jnp.int32)
    level = jnp.asarray(np.tile(np.arange(s_cap, dtype=np.int32) + 4,
                                (n, 1)))
    rank_all = jnp.full((n, s_cap), 7, jnp.int32)
    ok = jnp.ones((n, s_cap), bool)
    sig_all = jnp.asarray(
        (np.arange(n * s_cap * w, dtype=np.uint32) + 999).reshape(
            n, s_cap, w))
    args = (q_from, q_lvl, q_rank, q_bad, q_sig, src, level, rank_all,
            ok, sig_all)
    ref = _reference(*args, q_cap=q_cap)
    got = merge_queue_pallas(*args, q_cap=q_cap, interpret=True)
    for name, r, g in zip(("from", "lvl", "rank", "bad", "sig", "ev"),
                          ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg=name)


def test_resolve_pallas_default(monkeypatch):
    """The shared auto-default policy: explicit wins; None resolves
    from WTPU_PALLAS + backend (off on CPU regardless of the env)."""
    import jax

    from wittgenstein_tpu.ops.pallas_merge import resolve_pallas_default
    assert resolve_pallas_default(True) is True
    assert resolve_pallas_default(False) is False
    monkeypatch.setenv("WTPU_PALLAS", "1")
    # These tests run on the CPU backend: auto must stay off.
    assert jax.default_backend() == "cpu"
    assert resolve_pallas_default(None) is False
    monkeypatch.delenv("WTPU_PALLAS", raising=False)
    assert resolve_pallas_default(None) is False
