"""Probe: does Pallas/Mosaic compile and run through the axon remote-compile
path?  Decides whether a fused delivery kernel (merge + gathers — ~30% of
the step per reports/PROFILE_r4.md) is buildable this round.

Runs a trivial elementwise kernel and a small row-topk-style kernel shape.
Prints PALLAS_OK / PALLAS_FAIL with the error head.
"""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main():
    try:
        from jax.experimental import pallas as pl

        def add_kernel(x_ref, y_ref, o_ref):
            o_ref[...] = x_ref[...] + y_ref[...]

        x = jnp.arange(8 * 128, dtype=jnp.int32).reshape(8, 128)
        out = pl.pallas_call(
            add_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x, x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(2 * x))

        # Row-local compute at the delivery-merge shape class: [rows, W]
        # u32 word ops + a row reduction (the building blocks the fused
        # delivery kernel needs).
        def popmerge_kernel(a_ref, b_ref, o_ref, s_ref):
            a = a_ref[...]
            b = b_ref[...]
            u = a | b
            o_ref[...] = u
            # popcount via bit tricks (no lax.population_count in some
            # Mosaic versions — test the fallback formula too)
            v = u - ((u >> 1) & 0x55555555)
            v = (v & 0x33333333) + ((v >> 2) & 0x33333333)
            v = (((v + (v >> 4)) & 0x0F0F0F0F) * 0x01010101) >> 24
            s_ref[...] = jnp.sum(v.astype(jnp.int32), axis=1,
                                 keepdims=True)

        rows, w = 256, 128
        a = jnp.arange(rows * w, dtype=jnp.uint32).reshape(rows, w)
        b = a ^ jnp.uint32(0xFFFF)
        u, s = pl.pallas_call(
            popmerge_kernel,
            out_shape=(jax.ShapeDtypeStruct((rows, w), jnp.uint32),
                       jax.ShapeDtypeStruct((rows, 1), jnp.int32)))(a, b)
        ref_u = np.asarray(a) | np.asarray(b)
        np.testing.assert_array_equal(np.asarray(u), ref_u)
        ref_s = np.unpackbits(
            ref_u.view(np.uint8), axis=1).sum(axis=1, dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(s)[:, 0], ref_s)
        print(f"PALLAS_OK platform={jax.default_backend()}")
    except Exception as e:  # noqa: BLE001 — probe reports, caller decides
        print(f"PALLAS_FAIL {type(e).__name__}: {e!s:.500}")


if __name__ == "__main__":
    main()
