"""Snapshot-fork prefix planning — simulate the honest prefix ONCE.

The memoization half of the fast-forward paper (PAPERS.md 2602.10615,
ROADMAP item 3): BFT-scale campaigns (2208.14745) sweep adversity —
attack timings, chaos windows, loss rates — over a base scenario, and
every cell of such a sweep resimulates an identical honest prefix
before its adversity opens.  This module makes that redundancy a
planned, audited artifact:

  `strip_adversity(spec)`     — the spec with `attack` and
      `fault_schedule` removed: the program every adverse sibling
      provably runs until its first window opens (the ChaosProtocol
      wrap is bitwise inert before any window — loss keeps
      probability 0, delay adds 0, churn/partition vectors match the
      entry state — and the FaultInjector perturbs nothing before
      `at_ms`), so the stripped spec's trajectory IS the shared prefix.
  `first_adversity_ms(spec)`  — the earliest simulated ms at which the
      spec's adversity can act (attack `at_ms`, the schedule's first
      churn/partition/loss/delay window start); None for a clean spec.
  `plan_prefixes(plan)`       — for a `MatrixPlan`, group cells whose
      ADVERSITY-STRIPPED specs are identical (same protocol, params,
      seeds, engine, K, chunking, obs, latency, partition, span — only
      the post-fork adversity differs), and give each group the longest
      chunk-aligned fork point `fork_ms <= min(first_adversity)`.  The
      driver runs each group's `prefix_spec` (the stripped spec cut to
      `fork_ms`) ONCE through the serve scheduler and forks every cell
      from the restored state with the prefix's obs carries — a
      126-seed x 8-chaos-window grid then simulates the honest prefix
      8x fewer times.
  `chaos_noop_before_fork`    — the runtime soundness gate for
      state-mutating schedules (churn/partition): fork only when the
      window-entry fault write is a bitwise no-op on the forked state
      (and the protocol does not mutate liveness mid-prefix, so no-op
      at the fork boundary implies no-op at every earlier entry).  A
      veto falls back to the unforked path — never a wrong trajectory.

Bit-identity is the contract everywhere: a forked cell's final pytree
and stitched metrics/trace/audit artifacts equal an unforked sequential
`Runner` run's (tests/test_memo.py; `tools/memo.py` drives the PR-5
`first_divergence` bisector on any violation).
"""

from __future__ import annotations

import dataclasses

from ..serve.spec import ScenarioSpec

#: memo-prefix schema version (the checkpoint/table meta `prefix_digest`
#: readers key on it)
SCHEMA = 1


def strip_adversity(spec: ScenarioSpec) -> ScenarioSpec:
    """The spec with every post-fork adversity source removed (module
    docstring) — the program the honest prefix runs."""
    return dataclasses.replace(spec, attack=None, fault_schedule=None)


def first_adversity_ms(spec: ScenarioSpec):
    """Earliest simulated ms the spec's adversity can act, or None for
    a clean spec.  Window STARTS are what matter: before the first
    start the chaos wrap is bitwise inert (loss probability 0, delay
    +0, churn/partition vectors equal to the honest state — the
    `chaos_noop_before_fork` gate re-verifies the state-mutating
    classes on the actual forked state)."""
    starts = []
    if spec.attack is not None:
        starts.append(int(spec.attack["at_ms"]))
    if spec.fault_schedule is not None:
        from ..chaos import FaultSchedule
        fs = FaultSchedule.from_json(spec.fault_schedule)
        starts += [dm for _, dm, _ in fs.churn]
        starts += [s for s, *_ in fs.partitions]
        starts += [s for s, *_ in fs.loss]
        starts += [s for s, *_ in fs.delay]
    return min(starts) if starts else None


@dataclasses.dataclass(frozen=True)
class ForkGroup:
    """One shared honest prefix and the cells that fork from it."""

    #: the stripped spec cut to the fork point — what the driver runs
    #: once (as-authored form: the serve provenance convention)
    prefix_spec: ScenarioSpec
    #: resolved compile key of the prefix program (build accounting)
    prefix_key: str
    #: registry builds the prefix needs if its key is new to the plan
    prefix_builds: int
    fork_ms: int                    # chunk-aligned fork point
    cells: tuple                    # cell ids forking from this prefix
    #: digest of the prefix spec (adversity stripped, span = fork) —
    #: the `forked_from` provenance every forked ledger row carries
    prefix_digest: str

    @property
    def fork_chunks(self) -> int:
        return self.fork_ms // self.prefix_spec.chunk_ms


@dataclasses.dataclass(frozen=True)
class ForkPlan:
    """Every plannable fork of a `MatrixPlan` + why the rest were not."""

    groups: tuple
    skipped: dict                   # strip digest -> human-readable why

    @property
    def predicted_chunks_saved(self) -> int:
        """Chunks of honest prefix the fork plan avoids resimulating
        (each group's prefix runs once instead of once per cell) — the
        number the driver's reported `prefix_chunks_saved` must match
        on a veto-free, table-cold run (the acceptance pin)."""
        return sum((len(g.cells) - 1) * g.fork_chunks
                   for g in self.groups)

    def by_cell(self) -> dict:
        return {cid: g for g in self.groups for cid in g.cells}


def plan_prefixes(mplan, min_cells: int = 2, done_ids=(),
                  include_singles: bool = False) -> ForkPlan:
    """Fork plan for a `MatrixPlan` (module docstring).  `done_ids`
    excludes already-served cells (campaign resume); groups smaller
    than `min_cells` are skipped unless `include_singles` (a cross-run
    memo table makes even a singleton's prefix worth keeping)."""
    from ..matrix.planner import _builds_per_key

    done = set(done_ids)
    by_strip: dict = {}
    order: list = []
    for cell in mplan.cells:
        if cell.id in done:
            continue
        stripped = strip_adversity(cell.spec)
        key = stripped.digest()
        if key not in by_strip:
            by_strip[key] = {"strip": stripped, "cells": [], "adv": []}
            order.append(key)
        by_strip[key]["cells"].append(cell.id)
        by_strip[key]["adv"].append(
            first_adversity_ms(mplan.resolved[cell.id]))
    groups, skipped = [], {}
    floor = 1 if include_singles else int(min_cells)
    for key in order:
        rec = by_strip[key]
        chunk = int(rec["strip"].chunk_ms)
        bounds = [a for a in rec["adv"] if a is not None]
        if not bounds:
            skipped[key] = ("no adversity to strip — the cells already "
                            "share a compile-key group end to end")
            continue
        if len(rec["cells"]) < floor:
            skipped[key] = (f"only {len(rec['cells'])} cell(s) share "
                            "this honest prefix — nothing to dedup "
                            "(a memo table makes singletons reusable "
                            "across runs)")
            continue
        fork_ms = (min(bounds) // chunk) * chunk
        if fork_ms < chunk:
            skipped[key] = (f"adversity opens at ms {min(bounds)}, "
                            "inside the first chunk — no chunk-aligned "
                            "honest prefix exists")
            continue
        prefix_spec = dataclasses.replace(rec["strip"], sim_ms=fork_ms)
        try:
            resolved = prefix_spec.validate()
        except ValueError as e:     # belt and braces: the stripped
            # spec is strictly more permissive than its cells', which
            # the planner already validated
            skipped[key] = f"prefix spec fails validation: {e}"
            continue
        groups.append(ForkGroup(
            prefix_spec=prefix_spec, prefix_key=resolved.compile_key(),
            prefix_builds=_builds_per_key(resolved), fork_ms=fork_ms,
            cells=tuple(rec["cells"]),
            prefix_digest=prefix_spec.digest()))
    return ForkPlan(groups=tuple(groups), skipped=skipped)


def chaos_noop_before_fork(rspec: ScenarioSpec, state, fork_ms: int) \
        -> bool:
    """Runtime soundness gate for forking under a state-mutating
    schedule (module docstring).  `state` is the prefix's final
    (net, pstate) with the lane/seed axis leading; `rspec` the RESOLVED
    cell spec whose chaos wrap will run the suffix.  True iff applying
    the cell's window-entry faults anywhere in ``[0, fork_ms)`` is a
    bitwise no-op on the forked state — churn/partition vectors are
    constant before the first transition, so ONE check at
    ``fork_ms - 1`` covers the whole prefix, PROVIDED the protocol does
    not mutate liveness itself (checked statically: a liveness-mutating
    step could have downed an owned node mid-prefix, which the real
    chaos run would have revived at every window entry)."""
    if rspec.fault_schedule is None:
        return True
    from ..chaos import FaultSchedule
    fs = FaultSchedule.from_json(rspec.fault_schedule)
    if not fs.mutates_state:
        return True                 # loss/delay act on emitted outboxes
    proto = rspec.build_protocol()
    if getattr(proto, "mutates_liveness", False):
        return False
    import jax
    import numpy as np
    net = state[0]
    mutated = proto.apply_faults(net, int(fork_ms) - 1)
    for a, b in zip(jax.tree.leaves(net), jax.tree.leaves(mutated)):
        if not np.array_equal(np.asarray(jax.device_get(a)),
                              np.asarray(jax.device_get(b))):
            return False
    return True
