"""Line-plot output — tools/Graph.java parity (XChart -> matplotlib).

`Graph` accumulates named `Series` and saves a PNG; `stat_series` merges a
set of runs into min/max/avg series (Graph.statSeries, Graph.java:214-251);
`clean_series` trims the common flat tail (cleanSeries, :160-186).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Series:
    name: str
    xs: list = dataclasses.field(default_factory=list)
    ys: list = dataclasses.field(default_factory=list)

    def add(self, x, y):
        self.xs.append(float(x))
        self.ys.append(float(y))


def stat_series(name: str, runs: list) -> dict:
    """min/max/avg across same-x series (Graph.java:214-251)."""
    assert runs and all(len(r.xs) == len(runs[0].xs) for r in runs)
    out = {k: Series(f"{name}.{k}") for k in ("min", "max", "avg")}
    for i, x in enumerate(runs[0].xs):
        vals = [r.ys[i] for r in runs]
        out["min"].add(x, min(vals))
        out["max"].add(x, max(vals))
        out["avg"].add(x, sum(vals) / len(vals))
    return out


def clean_series(runs: list) -> None:
    """Trim the shared flat tail across runs (Graph.java:160-186)."""
    if not runs:
        return
    def tail_start(s):
        i = len(s.ys)
        while i > 1 and s.ys[i - 1] == s.ys[i - 2]:
            i -= 1
        return i
    cut = max(tail_start(s) for s in runs)
    for s in runs:
        del s.xs[cut:], s.ys[cut:]


class Graph:
    def __init__(self, title: str, x_label: str, y_label: str):
        self.title, self.x_label, self.y_label = title, x_label, y_label
        self.series: list = []

    def add_series(self, s: Series):
        self.series.append(s)

    def save(self, path: str) -> None:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, ax = plt.subplots(figsize=(9, 5.5))
        for s in self.series:
            ax.plot(s.xs, s.ys, label=s.name, linewidth=1.4)
        ax.set_title(self.title)
        ax.set_xlabel(self.x_label)
        ax.set_ylabel(self.y_label)
        if self.series:
            ax.legend(loc="best", fontsize=8)
        ax.grid(True, alpha=0.3)
        fig.tight_layout()
        fig.savefig(path, dpi=110)
        plt.close(fig)
