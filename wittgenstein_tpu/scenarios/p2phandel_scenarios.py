"""P2PHandel experiment sweeps — P2PHandelScenarios.java parity.

The reference collects BasicStats (doneAt min/avg/max, msgReceived
min/avg/max, bytesReceived avg, P2PHandelScenarios.java:18-80) per sweep
point via RunMultipleTimes; here every point is ONE vmapped batch of seeds
(core/harness.run_multiple_times).  Sweeps mirror sigsPerStrategy /
byNodeCount (:82-180): send-strategy comparison and node-count scaling.

Run `python -m wittgenstein_tpu.scenarios.p2phandel_scenarios [out_dir]`
for a smoke sweep.
"""

from __future__ import annotations

from ..core import builders
from ..core.harness import run_multiple_times
from ..models.p2phandel import (CMP_ALL, CMP_DIFF, DIF, ALL, P2PHandel,
                                cont_if_p2phandel)
from ..tools.csvf import CSVFormatter
from ..utils import stats as stats_mod

STRATEGY_NAMES = {ALL: "all", DIF: "dif", CMP_ALL: "cmp_all",
                  CMP_DIFF: "cmp_diff"}


def default_params(signers=100, relays=20, dead_ratio=0.0, **overrides):
    """Default P2PHandel configuration (P2PHandelParameters defaults,
    P2PHandel.java:37-112); threshold = 99% of signers."""
    params = dict(signing_node_count=signers, relaying_node_count=relays,
                  threshold=int(signers * 0.99), connection_count=40,
                  pairing_time=100, sigs_send_period=1000,
                  node_builder_name=builders.registry_name(
                      "cities", True, 0.0),
                  network_latency_name="NetworkLatencyByCityWJitter")
    params.update(overrides)
    return params


def basic_stats(proto, seeds, max_time=60_000, chunk=500):
    """BasicStats for one sweep point (P2PHandelScenarios.java:18-80):
    doneAt/msgReceived min/avg/max over live nodes + bytesReceived avg."""
    res = run_multiple_times(
        proto, run_count=seeds, max_time=max_time, chunk=chunk,
        cont_if=cont_if_p2phandel,
        stats_getters=(stats_mod.simple_stats("doneAt", "done_at"),
                       stats_mod.simple_stats("msgReceived", "msg_received"),
                       stats_mod.simple_stats("bytesReceived",
                                              "bytes_received")))
    d, m, b = (res.stats["doneAt"], res.stats["msgReceived"],
               res.stats["bytesReceived"])
    return {"done_min": d["min"], "done_avg": d["avg"], "done_max": d["max"],
            "msg_min": m["min"], "msg_avg": m["avg"], "msg_max": m["max"],
            "bytes_avg": b["avg"]}


def strategy_sweep(signers=64, relays=8, seeds=2, out_dir=".",
                   strategies=(ALL, DIF, CMP_ALL, CMP_DIFF)):
    """Compare the send strategies {all, dif, cmp_all, cmp_diff}
    (P2PHandel.java:25-34, sweep analog of sigsPerStrategy).  Each strategy
    is a distinct compiled program (~3 min apiece on CPU); pass a subset
    for smoke runs."""
    csv = CSVFormatter(["strategy", "done_avg", "msg_avg", "bytes_avg"])
    for strat in strategies:
        proto = P2PHandel(**default_params(signers, relays,
                                           send_sigs_strategy=strat))
        r = basic_stats(proto, seeds)
        csv.add(strategy=STRATEGY_NAMES[strat],
                done_avg=round(r["done_avg"], 1),
                msg_avg=round(r["msg_avg"], 1),
                bytes_avg=round(r["bytes_avg"], 1))
        print(f"strategy={STRATEGY_NAMES[strat]}: {r}")
    csv.save(f"{out_dir}/p2phandel_strategies.csv")
    return csv


def node_scaling(counts=(64, 128, 256), relay_ratio=0.2, seeds=2,
                 out_dir="."):
    """Node-count scaling (byNodeCount analog)."""
    csv = CSVFormatter(["signers", "done_avg", "done_max", "msg_avg"])
    for n in counts:
        proto = P2PHandel(**default_params(n, max(1, int(n * relay_ratio))))
        r = basic_stats(proto, seeds)
        csv.add(signers=n, done_avg=round(r["done_avg"], 1),
                done_max=round(r["done_max"], 1),
                msg_avg=round(r["msg_avg"], 1))
        print(f"signers={n}: {r}")
    csv.save(f"{out_dir}/p2phandel_scaling.csv")
    return csv


if __name__ == "__main__":
    import sys
    out = sys.argv[1] if len(sys.argv) > 1 else "."
    strategy_sweep(out_dir=out, strategies=(ALL, DIF))
    node_scaling(counts=(64, 128), out_dir=out)
