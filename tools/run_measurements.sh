#!/bin/bash
# Serialized round-3 measurement queue for the 1-core sandbox.
# Reordered after the mid-round sandbox restore: artifacts still missing
# (drift study, emission attack rows, reference-scale sweeps, dfinity
# variance) run first; the long supplementary cardinal re-runs go last.
# Logs land in reports/*.log; each tool writes its own .md report.
cd "$(dirname "$0")/.."

echo "[queue] cardinal_drift (1024,4096 x 8 seeds + attack rows)"
python tools/cardinal_drift.py --sizes 1024,4096 --seeds 8 \
    > reports/cardinal_drift.log 2>&1

echo "[queue] emission drift attacks at 1024 x 8 seeds"
PYTHONPATH= JAX_PLATFORMS=cpu python - > reports/emission_attacks.log 2>&1 <<'EOF'
from wittgenstein_tpu.scenarios.emission_drift import compare
compare(nodes=1024, seeds=8, max_time=10000, out_dir="reports",
        attack="byzantine_suicide", dead_ratio=0.25)
compare(nodes=1024, seeds=8, max_time=10000, out_dir="reports",
        attack="hidden_byzantine", dead_ratio=0.25)
EOF

echo "[queue] emission drift 8192 honest x 8 seeds"
PYTHONPATH= JAX_PLATFORMS=cpu python -m \
    wittgenstein_tpu.scenarios.emission_drift reports 8192 8 \
    > reports/emission_8192.log 2>&1

echo "[queue] reference-scale scenario sweeps (2048 x 8)"
python tools/scenario_sweeps_2048.py > reports/sweeps_2048.log 2>&1

echo "[queue] dfinity variance (32 seeds x 300 s)"
python tools/dfinity_variance.py 32 300 > reports/dfinity_variance.log 2>&1

echo "[queue] 262k cardinal on the 8-device mesh"
WTPU_CARDINAL_N=262144 python tools/cardinal_1m.py 120 \
    > reports/cardinal_262k.log 2>&1

echo "[queue] 1M cardinal unsharded (single device; GSPMD at 1M x 8"
echo "        partitions exceeds this host's compile/exec workspace)"
WTPU_CARDINAL_DEVS=1 python tools/cardinal_1m.py 120 \
    > reports/cardinal_1m_1dev.log 2>&1

echo "[queue] done"
