"""MetricsSpec — the static shape/enable contract of the metrics plane.

A spec is hashable and safe to close over in jit (like `EngineConfig`):
the interval length and the enabled-counter subset select which
reductions are compiled into the instrumented step, and fix the
``[T, K]`` series layout (T intervals x K enabled counters, canonical
column order).
"""

from __future__ import annotations

import dataclasses

#: Canonical counter order (= series column order when all are enabled).
#: Three kinds, by how an executed ms updates its interval row:
#:   sampled   — cumulative engine counters / state gauges written with
#:               last-write-wins (the row holds the value AS OF the last
#:               executed ms of the interval; host-side diffing turns the
#:               cumulative ones into per-interval deltas);
#:   high-water — max over the interval's executed ms;
#:   additive  — accumulated into the interval (samples per executed ms,
#:               ff_* by `record_jump` at a jump's origin interval).
COUNTERS = (
    "samples",          # additive: engine steps executed in this interval
    "msg_sent",         # sampled cum: sum over nodes of NodeState.msg_sent
    "msg_received",     # sampled cum
    "bytes_sent",       # sampled cum
    "bytes_received",   # sampled cum
    "done_count",       # sampled gauge: live nodes with done_at > 0
    "live_count",       # sampled gauge: nodes not down
    "ring_rows",        # sampled gauge: mailbox ring rows holding any delivery
    "ring_occupancy",   # sampled gauge: total pending unicast deliveries
    "bc_live",          # sampled gauge: active broadcast-table records
    "spill_hwm",        # high-water: parked spill entries (spill_cap > 0)
    "drop_count",       # sampled cum: dropped + bc_dropped + clamped + sp_dropped
    "ff_skipped_ms",    # additive: fast-forwarded ms originating here
    "ff_jumps",         # additive: fast-forward jumps originating here
)

_ADDITIVE = ("samples", "ff_skipped_ms", "ff_jumps")
_HIGH_WATER = ("spill_hwm",)
#: cumulative counters a host-side diff turns into per-interval deltas
CUMULATIVE = ("msg_sent", "msg_received", "bytes_sent", "bytes_received",
              "drop_count")
GAUGES = ("done_count", "live_count", "ring_rows", "ring_occupancy",
          "bc_live")


@dataclasses.dataclass(frozen=True)
class MetricsSpec:
    """Static instrumentation parameters (hashable, jit-closable).

    stat_each_ms — interval length in simulated ms (the reference's
    `ProgressPerTime` sampling period, ProgressPerTime.java:53-149).
    counters — enabled counter subset; stored in canonical COUNTERS
    order regardless of the order passed.
    """

    stat_each_ms: int = 10
    counters: tuple = COUNTERS

    def __post_init__(self):
        if self.stat_each_ms < 1:
            raise ValueError(f"stat_each_ms must be >= 1, got "
                             f"{self.stat_each_ms}")
        unknown = [c for c in self.counters if c not in COUNTERS]
        if unknown:
            raise ValueError(f"unknown counters {unknown}; known: "
                             f"{COUNTERS}")
        # canonical order + dedup, so the column layout is a pure
        # function of the enabled SET
        object.__setattr__(
            self, "counters",
            tuple(c for c in COUNTERS if c in set(self.counters)))

    @property
    def columns(self) -> tuple:
        """Series column names, in order."""
        return self.counters

    def col(self, name: str) -> int | None:
        """Column index of `name`, or None when not enabled."""
        try:
            return self.columns.index(name)
        except ValueError:
            return None

    def n_intervals(self, ms: int) -> int:
        """Rows needed to cover a chunk of `ms` simulated milliseconds."""
        return -(-int(ms) // self.stat_each_ms)
