"""Tier-2 (65k-131k) device working-set accounting -> reports/TIER2_MEMORY.md.

Computes the EXACT device residency of the tier-2 Handel configurations
from `jax.eval_shape` (no allocation): per-leaf bytes, the donated-vs-
undonated step peak, and the chips-needed verdict against v5e HBM
(16 GB/chip).  Complements reports/TIER2_CPU.md (round-2 host-RSS
measurement, which included XLA compile workspace and host copies —
device residency is what HBM sizing needs).

Usage: python tools/tier2_memory.py
"""

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from wittgenstein_tpu.utils.platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(1)

import jax                                             # noqa: E402
import jax.numpy as jnp                                # noqa: E402

from wittgenstein_tpu.models.handel import Handel      # noqa: E402

HBM_PER_CHIP = 16e9          # v5e


def account(proto, label):
    shapes = jax.eval_shape(proto.init, jnp.asarray(0, jnp.int32))
    leaves = jax.tree_util.tree_leaves_with_path(shapes)
    rows = [(jax.tree_util.keystr(p), x.size * x.dtype.itemsize)
            for p, x in leaves]
    rows.sort(key=lambda r: -r[1])
    total = sum(b for _, b in rows)
    big = sum(b for _, b in rows if b >= 1 << 20)
    # Peak without donation: input + output state live together (2x);
    # with donate="big" the >=1MB leaves are reused in place, so peak ~
    # total + big-transient margin (XLA temporaries are dominated by the
    # largest sort/scatter operands, ~1 extra copy of the ring slice).
    peak_nodonate = 2 * total
    peak_big = total + (total - big) + 0.25 * big
    print(f"\n== {label}: total {total / 1e9:.2f} GB "
          f"(big leaves {big / 1e9:.2f} GB)")
    for name, b in rows[:8]:
        print(f"   {b / 1e9:7.3f} GB  {name}")
    return {"label": label, "rows": rows, "total": total, "big": big,
            "peak_nodonate": peak_nodonate, "peak_big": peak_big}


def main():
    cfgs = []
    for n in (65536, 131072):
        down = n // 10
        proto = Handel(
            node_count=n, nodes_down=down,
            threshold=int(0.99 * (n - down)), pairing_time=4,
            dissemination_period_ms=20, fast_path=10,
            emission_mode="hashed", snapshot_pool=False,
            queue_cap=(2 ** 31 - 1) // (n * ((n + 31) // 32)),
            inbox_cap=8, horizon=256)
        cfgs.append(account(proto, f"exact-hashed {n}"))
        from wittgenstein_tpu.models.handel_cardinal import HandelCardinal
        protoc = HandelCardinal(
            node_count=n, nodes_down=down,
            threshold=int(0.99 * (n - down)), pairing_time=4,
            dissemination_period_ms=20, fast_path=10, queue_cap=8,
            inbox_cap=8, horizon=256)
        cfgs.append(account(protoc, f"cardinal {n}"))

    lines = [
        "# Tier-2 device working set (exact accounting, jax.eval_shape)",
        "",
        "State bytes per seed for the tier-2 Handel configs (hashed",
        "emission, pool-free, horizon 256, inbox 8; queue_cap at the",
        "int32-index ceiling for exact mode, 8 for cardinal).  Peaks:",
        "undonated step = 2x state (input + output buffers both live);",
        "`Runner(donate=\"big\")` reuses every >= 1 MB leaf in place",
        "(tests/test_engine.py proves bit-identity), leaving ~2x only the",
        "small leaves plus a ~25% transient margin on the big ones.",
        "",
        "| config | state GB | peak (no donation) | peak (donate=big) |"
        " v5e chips (16 GB) |",
        "|---|---|---|---|---|",
    ]
    for c in cfgs:
        chips = max(1, int(-(-c["peak_big"] // HBM_PER_CHIP)))
        lines.append(
            f"| {c['label']} | {c['total'] / 1e9:.2f} "
            f"| {c['peak_nodonate'] / 1e9:.2f} "
            f"| {c['peak_big'] / 1e9:.2f} | {chips} |")
    lines += [
        "",
        "Top leaves (exact-hashed 65536):",
        "",
        "```",
    ]
    for name, b in cfgs[0]["rows"][:8]:
        lines.append(f"{b / 1e9:7.3f} GB  {name}")
    lines += [
        "```",
        "",
        "The verification queue (`q_sig`) and the mailbox ring dominate",
        "exact mode, as SCALE.md predicted; cardinal mode removes every",
        "O(N^2) leaf and drops tier-2 residency by an order of magnitude —",
        "its 131k config fits ONE chip with donation.  Round-2's 42.9 GB",
        "host RSS at 65k (reports/TIER2_CPU.md) was host-side (XLA",
        "compile workspace + host copies), not device residency.",
    ]
    out = REPO / "reports" / "TIER2_MEMORY.md"
    out.write_text("\n".join(lines) + "\n")
    print(f"\nwrote {out}")


if __name__ == "__main__":
    main()
