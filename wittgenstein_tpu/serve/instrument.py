"""Serve-plane instrumentation bundle: spans + metrics, one handle.

The scheduler, fleet worker and matrix driver each accept
``instrument=`` (default None).  When None — the production default —
every site reduces to one attribute load and an is-None branch: zero
allocations, zero locks, nothing imported beyond this module
(tests/test_obs_spans.py pins it).  When set, the handle carries

  * a `SpanRecorder` (obs/spans.py): the request-lifecycle flight
    recorder, optionally durable as JSONL for crash postmortems;
  * a `MetricsRegistry` (obs/metrics.py): the scrapeable counters /
    gauges / histograms behind ``GET /w/batch/metrics``.

`end()` is the one write path phases go through: it closes the span
AND feeds the matching phase histogram, so the Perfetto timeline and
the Prometheus exposition can never disagree about what was measured.

Counters are NOT incremented at event sites.  The scheduler already
keeps monotone resilience counters under its lock; duplicating them
here would invite drift.  Instead `refresh_scheduler_metrics`
projects them (and the fleet's lease counters, via
`refresh_fleet_counters`) into the registry at scrape/settle time
through `set_counter`, which keeps max() — so the exposed series are
monotone across scrapes by construction.
"""

from __future__ import annotations

from ..obs.metrics import MetricsRegistry
from ..obs.spans import SpanRecorder

# ----------------------------------------------------------- span names

SPAN_SUBMIT = "serve.submit"          # validate + admit + journal ack
SPAN_QUEUE_WAIT = "serve.queue_wait"  # journal ack -> marked running
SPAN_COMPILE = "serve.compile"        # registry chunk_fn build/lookup
SPAN_LAUNCH = "serve.launch"          # one bounded launch attempt
SPAN_CHUNK = "serve.chunk"            # one chunk boundary to the next
SPAN_SETTLE = "serve.settle"          # artifact build + ledger append
SPAN_RESUME = "serve.resume"          # checkpoint restore, per request
SPAN_REPLAY = "serve.replay"          # journal replay adoption
MARK_PREEMPT = "serve.preempt"        # checkpoint-preempted at boundary
MARK_RETRY = "serve.retry"            # launch attempt failed, retrying
MARK_DEGRADE = "serve.degrade"        # width-degradation bisection step
MARK_QUARANTINE = "serve.quarantine"  # poison-lane verdict
MARK_WATCHDOG = "serve.watchdog_trip"
FLEET_CLAIM = "fleet.claim"
FLEET_RENEW = "fleet.renew"
FLEET_ADOPT_CKPT = "fleet.adopt_checkpoint"
FLEET_ADOPT_JOURNAL = "fleet.adopt_journal"
GRID_SUBMIT = "grid.submit"           # one submission wave
GRID_DRAIN = "grid.drain"             # drain-to-settled wait
GRID_HARVEST = "grid.harvest"         # cell artifact harvest

#: the per-request lifecycle in first-occurrence start order
#: (bench_suite `spans_smoke` asserts a served request produced all of
#: these, in this order).  The launch attempt nests INSIDE its chunk
#: span — the chunk opens at the boundary, then launches the device
#: call — so chunk precedes launch by t0 while enclosing it by span.
LIFECYCLE = (SPAN_SUBMIT, SPAN_QUEUE_WAIT, SPAN_COMPILE, SPAN_CHUNK,
             SPAN_LAUNCH, SPAN_SETTLE)

#: the phase block surfaced in `/w/batch/health` (satellite: span-
#: derived p50/p99 next to the chunk-wall EMA)
HEALTH_PHASES = (SPAN_QUEUE_WAIT, SPAN_COMPILE, SPAN_LAUNCH)

#: span name -> histogram fed by `Instrumentation.end`
PHASE_HISTOGRAMS = {
    SPAN_QUEUE_WAIT: "wtpu_serve_queue_wait_seconds",
    SPAN_COMPILE: "wtpu_serve_compile_seconds",
    SPAN_LAUNCH: "wtpu_serve_launch_seconds",
    SPAN_CHUNK: "wtpu_serve_chunk_seconds",
}

#: scheduler resilience counter -> exposed counter name
RESILIENCE_COUNTERS = {
    "rejected": "wtpu_serve_rejected_429_total",
    "retries": "wtpu_serve_retries_total",
    "demotions": "wtpu_serve_degradations_total",
    "preemptions": "wtpu_serve_preemptions_total",
    "resumed": "wtpu_serve_resumed_total",
    "quarantined": "wtpu_serve_quarantined_total",
    "watchdog_trips": "wtpu_serve_watchdog_trips_total",
    "replayed": "wtpu_serve_replayed_total",
    "repacked": "wtpu_serve_repacked_total",
}

#: fleet worker counter -> exposed counter name (reclaims = foreign
#: checkpoints adopted from another worker's lease)
FLEET_COUNTERS = {
    "claimed": "wtpu_fleet_lease_claims_total",
    "renewed": "wtpu_fleet_lease_renews_total",
    "adopted_checkpoints": "wtpu_fleet_lease_reclaims_total",
}

#: memo/search counter -> exposed counter name.  The sources are the
#: fleet worker's counters dict and the search driver's memo-stats
#: block (matrix/search.py) — both monotone over a process lifetime,
#: so max-keeping `set_counter` projection preserves monotonicity
#: across scrapes (the PR-18 convention; module docstring).
SEARCH_COUNTERS = {
    "memo_table_hits": "wtpu_memo_table_hits_total",
    "memo_table_misses": "wtpu_memo_table_misses_total",
    "prefix_chunks_saved": "wtpu_memo_prefix_chunks_saved_total",
    "search_probes_total": "wtpu_search_probes_total",
}


class Instrumentation:
    """One handle bundling the span recorder and the metrics registry.

    Constructed by the operator-facing entry points (serve_load
    ``--timeline``, fleet ``--timeline``, tests) and handed to
    `Scheduler(instrument=...)` / `FleetWorker(instrument=...)`; the
    serve plane itself never constructs one."""

    def __init__(self, *, span_path=None, fsync: bool = False,
                 clock=None, worker=None, capacity: int = 4096,
                 metrics=None):
        self.spans = SpanRecorder(capacity=capacity, path=span_path,
                                  fsync=fsync, clock=clock,
                                  worker=worker)
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()

    # thin delegations so instrumented sites touch one object --------

    def now(self) -> float:
        return self.spans.now()

    def end(self, name, t0, t1=None, **attrs) -> dict:
        """Close a phase span and feed its histogram (if any)."""
        row = self.spans.emit(name, t0, t1, **attrs)
        hist = PHASE_HISTOGRAMS.get(name)
        if hist is not None:
            self.metrics.observe(hist, row["dur"])
        return row

    def mark(self, name, **attrs) -> dict:
        return self.spans.mark(name, **attrs)

    def health_phases(self) -> dict:
        """Span-derived phase quantiles for `/w/batch/health`."""
        return self.spans.phase_quantiles(names=HEALTH_PHASES)


# ------------------------------------------------------- projections

def refresh_scheduler_metrics(metrics, sch) -> None:
    """Project a scheduler's monotone state into `metrics` (see
    module docstring for why scrape-time projection, not event-time
    increments)."""
    hs = sch.health_stats()
    res = hs.get("resilience") or {}
    for key, name in RESILIENCE_COUNTERS.items():
        metrics.set_counter(name, res.get(key, 0))
    # total submission attempts = rids minted + admission rejections
    metrics.set_counter("wtpu_serve_submits_total",
                        hs.get("submitted", 0) + res.get("rejected", 0))
    metrics.set_gauge("wtpu_serve_queue_depth", hs.get("queued", 0))
    metrics.set_gauge("wtpu_serve_running", hs.get("running", 0))
    lag = hs.get("journal_lag")
    if lag is not None:
        metrics.set_gauge("wtpu_serve_journal_lag", lag)
    ema = hs.get("chunk_wall_ema_s")
    if ema:
        metrics.set_gauge("wtpu_serve_chunk_wall_ema_seconds", ema)
    # compile-registry warm/cold story (satellite of the program
    # observatory: the per-artifact registry_block()s stay, but a
    # scrape should not need an artifact to see the hit ratio)
    reg = getattr(sch, "registry", None)
    if reg is not None:
        metrics.set_gauge("wtpu_registry_hits", reg.hits)
        metrics.set_gauge("wtpu_registry_misses", reg.misses)


def refresh_fleet_counters(metrics, counters) -> None:
    """Project a `FleetWorker.counters` dict into `metrics`."""
    for key, name in FLEET_COUNTERS.items():
        if key in counters:
            metrics.set_counter(name, counters[key])


def refresh_search_counters(metrics, counters) -> None:
    """Project memo/search counters (fleet worker counters dict or a
    search driver's accounting block) into `metrics`."""
    for key, name in SEARCH_COUNTERS.items():
        if key in counters:
            metrics.set_counter(name, counters[key])


def scheduler_exposition(sch) -> str:
    """The `GET /w/batch/metrics` body for an in-process scheduler:
    refresh projections, then render.  Works uninstrumented too — a
    transient registry still yields monotone series because every
    projected source is itself monotone."""
    ins = getattr(sch, "_ins", None)
    metrics = ins.metrics if ins is not None else MetricsRegistry()
    refresh_scheduler_metrics(metrics, sch)
    cat = getattr(sch, "catalog", None)
    if cat is not None:
        from ..obs.programs import refresh_catalog_metrics
        refresh_catalog_metrics(metrics, cat)
    return metrics.exposition()


def ledger_metrics_block(sch) -> dict:
    """The per-settle metrics snapshot embedded in ledger rows (only
    called when the scheduler is instrumented)."""
    ins = sch._ins
    refresh_scheduler_metrics(ins.metrics, sch)
    return ins.metrics.snapshot()
