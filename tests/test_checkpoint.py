"""Checkpoint/resume tests: a resumed run must be bit-identical to an
uninterrupted one."""

import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.handel import Handel
from wittgenstein_tpu.utils import checkpoint


def test_checkpoint_roundtrip(tmp_path):
    p = Handel(node_count=128, threshold=115, nodes_down=12,
               network_latency_name="NetworkLatencyByDistanceWJitter")
    r = Runner(p, donate=False)

    # Straight run: 1000 ms.
    net_a, ps_a = p.init(0)
    for _ in range(4):
        net_a, ps_a = r.run_ms(net_a, ps_a, 250)

    # Checkpointed run: 500 ms, save, load, 500 ms more.
    net_b, ps_b = p.init(0)
    net_b, ps_b = r.run_ms(net_b, ps_b, 250)
    net_b, ps_b = r.run_ms(net_b, ps_b, 250)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(path, net_b, ps_b, meta={"time": int(net_b.time)})
    net_c, ps_c, meta = checkpoint.load(path, p, seed=0)
    assert meta["time"] == 500
    for _ in range(2):
        net_c, ps_c = r.run_ms(net_c, ps_c, 250)

    for name in ("done_at", "msg_received", "bytes_sent"):
        assert np.array_equal(np.asarray(getattr(net_a.nodes, name)),
                              np.asarray(getattr(net_c.nodes, name))), name
    assert np.array_equal(np.asarray(ps_a.ver_ind), np.asarray(ps_c.ver_ind))
    assert np.array_equal(np.asarray(ps_a.last_agg),
                          np.asarray(ps_c.last_agg))
    assert int(net_a.time) == int(net_c.time) == 1000
