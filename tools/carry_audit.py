"""HLO-level audit of the scan-carry writeback churn.

The round-4 post-fix profile attributed ~22% of device time to
dynamic-update-slice churn "around the scan carry" and asked for an
HLO-level look at WHICH carry leaves bounce (BENCH_NOTES.md).  This tool
answers that: it compiles the exact bench build (scan_chunk_batched on
Handel) at a small config, walks the optimized HLO, and reports

  * every `copy` / `dynamic-update-slice` inside the scan's while body,
    sized in bytes, attributed to its source line when available;
  * which while-loop carry tuple elements are NOT updated in place
    (the copies XLA's copy-insertion pass adds when it cannot prove
    aliasing) — the "bouncing" leaves, matched back to NetState /
    HandelState field names by shape.

Run anywhere (CPU HLO shows the same copy-insertion decisions; run
on-chip for the Mosaic view):
  python tools/carry_audit.py [n] [seeds] [chunk_ms]
"""

from __future__ import annotations

import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(n=256, seeds=2, chunk=40):
    import jax
    import jax.numpy as jnp

    from wittgenstein_tpu.core.batched import scan_chunk_batched
    from wittgenstein_tpu.models.handel import Handel

    down = n // 10
    proto = Handel(node_count=n, threshold=int(0.99 * (n - down)),
                   nodes_down=down, pairing_time=4, level_wait_time=50,
                   dissemination_period_ms=20, fast_path=10,
                   horizon=64, inbox_cap=12)
    lcm = getattr(proto, "schedule_lcm", None)
    t0 = 0 if (lcm and chunk % lcm == 0) else None
    # Same knob bench.py honors: WTPU_PLANE_BARRIER=0 audits the
    # pre-fix build (reproduces the 40-copies-per-body baseline).
    base = scan_chunk_batched(
        proto, chunk, t0_mod=t0,
        plane_barrier=os.environ.get("WTPU_PLANE_BARRIER", "1") != "0")

    def init(seed0=0):
        return jax.vmap(proto.init)(
            seed0 + jnp.arange(seeds, dtype=jnp.int32))

    args = init(0)
    lowered = jax.jit(base).lower(*args)
    compiled = lowered.compile()
    return proto, args, compiled


_BYTES = {"f32": 4, "s32": 4, "u32": 4, "s8": 1, "u8": 1, "pred": 1,
          "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "s64": 8, "u64": 8}


def shape_bytes(shape: str) -> int:
    m = re.match(r"(\w+)\[([\d,]*)\]", shape)
    if not m:
        return 0
    dt, dims = m.groups()
    total = _BYTES.get(dt, 4)
    for d in dims.split(","):
        if d:
            total *= int(d)
    return total


def leaf_names(proto, args):
    """shape-string -> candidate state field names, for attribution."""
    import jax
    names = collections.defaultdict(set)

    def walk(prefix, obj):
        import dataclasses
        if dataclasses.is_dataclass(obj):
            for f in dataclasses.fields(obj):
                walk(f"{prefix}.{f.name}" if prefix else f.name,
                     getattr(obj, f.name))
        elif isinstance(obj, (tuple, list)):
            for i, x in enumerate(obj):
                walk(f"{prefix}[{i}]", x)
        elif hasattr(obj, "shape"):
            dt = str(obj.dtype)
            dt = {"float32": "f32", "int32": "s32", "uint32": "u32",
                  "bool": "pred", "int8": "s8", "uint8": "u8"}.get(dt, dt)
            dims = ",".join(str(d) for d in obj.shape)
            names[f"{dt}[{dims}]"].add(prefix)

    walk("", args)
    return names


def audit(compiled, names):
    text = compiled.as_text()
    # The scan lowers to while(...) with body=<name>; extract each body
    # computation by name.
    body_names = set(re.findall(r"body=%?([\w.\-]+)", text))
    bodies = []
    for bn in body_names:
        m = re.search(
            r"^(?:%" + re.escape(bn) + r"|" + re.escape(bn) +
            r") \([^)]*\) -> .*?\{(.*?)^\}", text, re.M | re.S)
        if m:
            bodies.append((bn, m.group(1)))
    if not bodies:
        bodies = [("whole-module", text)]
    report = []
    for name, body in bodies:
        dus = []
        copies = []
        for line in body.splitlines():
            line = line.strip()
            m = re.match(r"%?([\w.\-]+) = (\S+?) (dynamic-update-slice|copy)\(",
                         line)
            if not m:
                m2 = re.match(r"%?([\w.\-]+) = (\S+?)\s+"
                              r"(dynamic-update-slice|copy)", line)
                if not m2:
                    continue
                m = m2
            out, shape, op = m.groups()
            b = shape_bytes(shape)
            src = ""
            mm = re.search(r'metadata=\{[^}]*op_name="([^"]+)"', line)
            if mm:
                src = mm.group(1)[-70:]
            mm = re.search(r'source_file="([^"]+)"[^}]*source_line=(\d+)',
                           line)
            if mm:
                src += f" {os.path.basename(mm.group(1))}:{mm.group(2)}"
            bare = shape.split("{")[0]
            leaf = "/".join(sorted(names.get(bare, []))[:3])
            (dus if op == "dynamic-update-slice" else copies).append(
                (b, shape, src, leaf))
        report.append((name, dus, copies))
    return report


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    seeds = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 40
    proto, args, compiled = build(n, seeds, chunk)
    names = leaf_names(proto, args)
    for body, dus, copies in audit(compiled, names):
        tot_d = sum(b for b, *_ in dus)
        tot_c = sum(b for b, *_ in copies)
        if not dus and not copies:
            continue
        print(f"== {body}: {len(dus)} DUS ({tot_d/1e6:.1f} MB), "
              f"{len(copies)} copies ({tot_c/1e6:.1f} MB)")
        agg = collections.Counter()
        size = collections.Counter()
        for b, shape, src, leaf in dus:
            agg[("DUS", shape, src, leaf)] += 1
            size[("DUS", shape, src, leaf)] += b
        for b, shape, src, leaf in copies:
            agg[("copy", shape, src, leaf)] += 1
            size[("copy", shape, src, leaf)] += b
        for key, cnt in sorted(agg.items(), key=lambda kv: -size[kv[0]]):
            op, shape, src, leaf = key
            print(f"  {op:4s} x{cnt:<4d} {size[key]/1e6:9.2f} MB  {shape:24s}"
                  f" {leaf or '?':40s} {src}")


if __name__ == "__main__":
    main()
