"""Host-plane span recorder — the request-lifecycle flight recorder.

The device plane has a flight recorder (obs/trace.py) on SIMULATED
time; this module is its host twin on WALL time.  A span is one named
interval of host work — submit, queue wait, compile, launch, chunk,
preempt, resume, settle, lease claim, adoption — carrying the request
id / compile key / tenant / worker attributes that let a Perfetto
merge (obs/export.spans_to_perfetto) put every request's host
lifecycle on one track next to its device timeline.

Design constraints, in order:

  * OFF costs nothing: the serve plane holds ``instrument=None`` by
    default and guards every site with a plain is-None test — this
    module is never imported, let alone allocated, on the
    uninstrumented hot path (tests/test_obs_spans.py pins it).
  * Crash postmortems keep the timeline: with ``path=`` set, every
    span is ALSO appended to a JSONL log through the sanctioned
    `utils/jsonl.append_line` write path, so a SIGKILLed worker's
    spans survive it (torn final line tolerated by `read_spans`, the
    `iter_lines` contract).  The rule ``host_durability`` covers this
    file as part of its strict zone.
  * Deterministic under an injected clock: all timestamps come from
    the ``clock`` callable (default `time.monotonic`) and nothing
    else, so a fake clock yields byte-identical JSONL across runs —
    the span log is testable the way the engines are.

The in-memory side is a bounded ring (`capacity` most-recent spans):
a long-lived service must not grow a span list without bound, and the
ring is what `phase_quantiles` (the `/w/batch/health` p50/p99 block)
and ad-hoc snapshots read.  The durable JSONL, when enabled, is the
complete record.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time

from ..utils import jsonl

#: span-row schema (bump on field changes)
SCHEMA = 1


def _quantile(sorted_vals, q: float) -> float:
    """Upper nearest-rank quantile over a sorted list (the serve_load
    convention: ceil, so a p99 over ~100 samples reads the true tail
    outlier, not ~p98)."""
    import math
    i = min(len(sorted_vals) - 1,
            math.ceil(q * (len(sorted_vals) - 1)))
    return sorted_vals[i]


class SpanRecorder:
    """Bounded-ring span recorder with optional durable JSONL.

    ``emit(name, t0)`` records one COMPLETED span (start/stop on the
    injected monotonic clock); ``span(name)`` is the context-manager
    sugar for coarse phases.  Thread-safe: serve drain, watchdog,
    renewal and HTTP threads all emit into one recorder."""

    #: lock inventory (analysis rule ``host_locks``): `_mu` guards the
    #: ring and the emit/drop counters — written from every emitting
    #: thread, read by snapshot/quantile callers (health endpoint).
    _LOCK_OWNS = {"_mu": ("_ring", "_emitted", "_write_errors")}

    def __init__(self, *, capacity: int = 4096, path=None,
                 fsync: bool = False, clock=None,
                 worker: str | None = None):
        self.capacity = max(1, int(capacity))
        #: durable JSONL log (None = ring only).  Appends go through
        #: utils/jsonl.append_line — the one sanctioned append path —
        #: so a crash leaves at worst one torn final line.
        self.path = str(path) if path else None
        #: fsync each span row (off by default: the span log is
        #: postmortem evidence, not an ack barrier — flush-per-line
        #: already bounds loss to the in-flight row)
        self.fsync = bool(fsync)
        #: the ONLY time source (injectable for byte-identical tests)
        self.clock = clock if clock is not None else time.monotonic
        #: default worker attribute stamped on every span
        self.worker = str(worker) if worker is not None else None
        import collections
        self._ring = collections.deque(maxlen=self.capacity)
        self._emitted = 0
        self._write_errors = 0
        self._mu = threading.Lock()

    # ------------------------------------------------------------- emit

    def now(self) -> float:
        """The recorder's clock — span starts MUST come from here, so
        an injected clock governs every timestamp."""
        return self.clock()

    def emit(self, name: str, t0: float, t1=None, *, rid=None,
             key=None, tenant=None, worker=None, **extra) -> dict:
        """Record one completed span ``[t0, t1]`` (t1 defaults to
        now).  Attribute fields are omitted when None so the JSONL
        stays compact and byte-stable.  Returns the row."""
        if t1 is None:
            t1 = self.clock()
        row = {"schema": SCHEMA, "name": str(name),
               "t0": float(t0),
               "dur": max(0.0, float(t1) - float(t0))}
        w = worker if worker is not None else self.worker
        if w is not None:
            row["worker"] = w
        if rid is not None:
            row["rid"] = rid
        if key is not None:
            row["key"] = key
        if tenant is not None:
            row["tenant"] = tenant
        if extra:
            row.update(extra)
        with self._mu:
            self._ring.append(row)
            self._emitted += 1
        if self.path is not None:
            try:
                jsonl.append_line(self.path, row, fsync=self.fsync)
            except OSError as e:
                # the ring keeps the span; the durable log degrades
                # loudly instead of failing the instrumented operation
                with self._mu:
                    self._write_errors += 1
                print(f"spans: append to {self.path} failed ({e}); "
                      "span kept in ring only", file=sys.stderr)
        return row

    def mark(self, name: str, **attrs) -> dict:
        """A zero-duration event marker (retry, degradation, watchdog
        trip, quarantine verdict) — a span whose t0 == t1."""
        t = self.clock()
        return self.emit(name, t, t, **attrs)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context-manager sugar: the enclosed block is the span."""
        t0 = self.clock()
        try:
            yield
        finally:
            self.emit(name, t0, **attrs)

    # ------------------------------------------------------------- read

    def snapshot(self) -> list:
        """The ring's spans, oldest first (copies of the row dicts are
        NOT taken — rows are append-only by convention)."""
        with self._mu:
            return list(self._ring)

    def stats(self) -> dict:
        with self._mu:
            return {"emitted": self._emitted,
                    "in_ring": len(self._ring),
                    "capacity": self.capacity,
                    "write_errors": self._write_errors,
                    "durable": self.path is not None}

    def phase_quantiles(self, names=None) -> dict:
        """Per-span-name duration quantiles over the ring — the
        `/w/batch/health` ``phases`` block: ``{name: {count, p50_ms,
        p99_ms}}``.  `names` (optional) restricts to those span
        names."""
        by: dict = {}
        for row in self.snapshot():
            n = row["name"]
            if names is not None and n not in names:
                continue
            by.setdefault(n, []).append(row["dur"])
        out = {}
        for n in sorted(by):
            ds = sorted(by[n])
            out[n] = {"count": len(ds),
                      "p50_ms": round(1e3 * _quantile(ds, 0.50), 3),
                      "p99_ms": round(1e3 * _quantile(ds, 0.99), 3)}
        return out


def read_spans(path) -> list:
    """Parse one span JSONL log (torn tail tolerated — the
    `utils/jsonl.iter_lines` contract: a SIGKILL mid-append loses at
    most the in-flight row, loudly).  Rows that are not span-shaped
    (no name/t0) are skipped with a stderr note rather than failing
    the postmortem."""
    out = []
    for i, row in jsonl.iter_lines(path, label="spans"):
        if not isinstance(row, dict) or "name" not in row \
                or "t0" not in row:
            print(f"spans: row {i} of {path} is not a span "
                  "(no name/t0); skipped", file=sys.stderr)
            continue
        out.append(row)
    return out
