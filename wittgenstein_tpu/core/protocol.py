"""Protocol contract — the TPU-native analogue of core/Protocol.java:9-22.

The reference contract is three methods: ``network()``, ``copy()``, ``init()``.
Here a protocol is a *pure description*:

  - static attributes: `cfg` (EngineConfig), `latency` (a latency model), and
    whatever parameters the protocol needs (the WParameters analogue is the
    protocol's constructor arguments, kept as plain Python/JSON-able values);
  - ``init(seed) -> (NetState, pstate)`` builds the whole simulation state
    from a seed (the analogue of copy()+init(): re-calling init with the same
    seed IS the reference's copy()-reproducibility contract, tested the same
    way HandelTest.java:14-34 tests it);
  - ``step(pstate, nodes, inbox, t, key) -> (pstate, nodes, outbox)`` is the
    per-ms transition for ALL nodes at once — the vectorized replacement for
    every Message.action + registered task of the reference.

Protocols register themselves by class name so the scenario harness and the
REST server can look them up by string, mirroring the wserver's classpath
scan (wserver/Server.java:56-70).
"""

from __future__ import annotations

from .state import EngineConfig  # noqa: F401  (re-export for implementors)

PROTOCOLS: dict[str, type] = {}


def register(cls):
    """Class decorator: adds the protocol to the global name registry."""
    PROTOCOLS[cls.__name__] = cls
    return cls


def get_protocol(name: str):
    if name not in PROTOCOLS:
        raise KeyError(
            f"unknown protocol {name!r}; known: {sorted(PROTOCOLS)}")
    return PROTOCOLS[name]
