"""Rule ``trace_zero_cost`` — the flight recorder may never silently
tax an untraced build, and may never silently die.

Sibling of `metrics_zero_cost` (rules_metrics.py), for the EVENT plane
(wittgenstein_tpu/obs/trace.py).  The contract is two-sided:

  * trace-OFF builds carry ZERO recorder residue.  The engine's `tap`
    hook defaults to None — a plain Python branch, so the
    uninstrumented program is the historical one BY CONSTRUCTION; this
    rule makes that structural claim an enforced ratchet: the chunk's
    outermost scan/while carry width over the state leaf count
    (`carry_extra_leaves`) is measured on every pre-existing target and
    budgeted at its known instrumentation (0 for dense targets, the
    fast-forward skip counters for `+ff`, the MetricsCarry leaves for
    `+metrics` — all already pinned by the metrics rule's budgets), so
    a tap accidentally left threaded into a production builder fails
    the gate with the measured width;
  * a ``+trace`` target whose loop carry does NOT widen by the
    `TraceCarry` leaves (buf + cursor + dropped = 3) has a silently-
    dead recorder — an error, not a budget.
"""

from __future__ import annotations

from .framework import Finding, Rule, register_rule
from .rules_metrics import _count_eqns, _loop_carry_widths

#: TraceCarry contributes this many pytree leaves (buf, cursor, dropped).
_TRACE_CARRY_LEAVES = 3

#: analysis target-name suffix of the flight-recorder builds
TRACE_SUFFIX = "+trace"


@register_rule
class TraceZeroCostRule(Rule):
    name = "trace_zero_cost"
    scope = "protocol"
    budgeted_metrics = ("carry_extra_leaves", "jaxpr_eqns")

    def run(self, target, budget):
        import jax

        n_state = len(jax.tree.leaves(target.args))
        loops = _loop_carry_widths(target.jaxpr.jaxpr)
        if not loops:
            return [Finding(
                rule=self.name, target=target.name, severity="warning",
                message="no top-level scan/while loop in the traced "
                        "chunk — carry-residue check has nothing to "
                        "measure")]
        prim, carry = max(loops, key=lambda pc: pc[1])
        extra = carry - n_state
        findings = [
            Finding(rule=self.name, target=target.name, severity="info",
                    metric="carry_extra_leaves", value=extra,
                    message=f"{prim} carry holds {carry} vars for "
                            f"{n_state} state leaves "
                            f"(carry_extra_leaves={extra})"),
            Finding(rule=self.name, target=target.name, severity="info",
                    metric="jaxpr_eqns",
                    value=_count_eqns(target.jaxpr.jaxpr),
                    message="total jaxpr equations in the compiled "
                            "chunk"),
        ]
        if (target.name.endswith(TRACE_SUFFIX)
                and extra < _TRACE_CARRY_LEAVES):
            findings.append(Finding(
                rule=self.name, target=target.name, severity="error",
                message=f"traced target carries only {extra} extra loop "
                        f"vars (< {_TRACE_CARRY_LEAVES}: the TraceCarry "
                        "leaves) — the flight recorder is silently dead "
                        "in this build"))
        return findings
