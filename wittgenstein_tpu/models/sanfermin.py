"""San Fermín signature aggregation — pairwise binomial swaps.

Two reference protocols share the geometry (SURVEY.md §2.4):

* `SanFermin` — protocols/SanFerminSignature.java (619 lines).  Each node
  walks prefix levels from log2(N)-1 down to 0; at each level it swaps its
  aggregate with its mirror node in the sibling block (SwapRequest /
  SwapReply), retrying other candidates on timeout; optimistic replies are
  served from a per-level signature cache (:229-270); a verification
  (pairingTime) gates every aggregation (transition, :519-540);
  doneAt = time + 2*pairingTime once level 0 completes (:379-419).
* `SanFerminCappos` — protocols/SanFerminCappos.java (523 lines).  Variant
  with one `Swap(level, value, wantReply)` message, a per-level cache of
  *best received values* whose total (1 + sum of per-level maxima at or
  above the current level, totalNumberOfSigs :352-360) drives a threshold,
  `candidateCount`~50 batch fan-out, and cached levels skipped on entry
  (goNextLevel :307-345).

Geometry (SanFerminHelper.java:46-100, power-of-two N): at prefix length
cpl, half = 2^(log2(N)-cpl-1); own set = the node's `half`-block; candidate
set = the sibling `half`-block; the deterministic first pick is the mirror
node (same offset in the sibling block, getExactCandidateNode :104-116);
later picks walk the remaining candidates in order (pickNextNodes
:123-158).  All of it is index arithmetic — nothing stored.

TPU-native simplifications (statistical equivalence, SURVEY §7.4.3):
* one outstanding timeout per node (the reference chains one task per send);
* at most one candidate batch triggered per node per ms (multiple same-ms
  NO-replies coalesce);
* replies are capped at `reply_cap` per node per ms — an over-capacity
  requester just retries on its timeout.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core import builders
from ..core import latency as latency_mod
from ..core.protocol import register
from ..core.state import EngineConfig, empty_outbox, init_net
from ..ops import prng
from ..ops.flat import gather2d, set2d

TAG_PICK = 0x53465049

REQ, OK, NO = 0, 1, 2          # SanFermin message kinds
SWAP_ASK, SWAP_INFO = 0, 1     # Cappos: wantReply true / false


def _half(bits, cpl):
    """Block size at prefix length cpl: 2^(bits - cpl - 1)."""
    return jnp.int32(1) << jnp.clip(bits - cpl - 1, 0, 30)


def _own_base(ids, half):
    return ids & ~(half - 1)


def _cand_base(ids, half):
    """Base of the sibling half-block (the candidate set)."""
    return _own_base(ids, half) ^ half


def _pick_offset(j, partner_off, half):
    """The j-th pick in a level's candidate order: mirror node first,
    then the remaining offsets in a PER-NODE ROTATION
    ``(partner_off + j) mod half``.

    The reference walks the candidates in plain index order after the
    mirror (pickNextNodes, SanFerminHelper.java:123-158) — which means
    every straggler in a block hammers the sibling block's FIRST few
    ids: at 32k nodes the top level put ~16k same-wave requests on one
    node, which the reference absorbs with unbounded queues
    (bench_suite_r4: 61,684 inbox drops here).  Rotating each walk by
    the node's own in-block offset keeps pick j a BIJECTION between
    requesters and candidates — worst-case same-tick fan-in drops from
    half-block to candidate_count + 1 — while every node still walks
    its full candidate set exactly once per level in a deterministic
    order (same sets, same counts; WHICH stranger you try next is
    protocol-irrelevant — a documented statistical-equivalence
    coarsening, SURVEY §7.4.3)."""
    return (partner_off + j) % jnp.maximum(half, 1)


def _expected(off, partner_off, used, half):
    """Was candidate-offset `off` among our first `used` picks?"""
    rank = (off - partner_off) % jnp.maximum(half, 1)
    return rank < used


class _SanFerminBase:
    """Shared scaffolding: parameters, node building, level geometry."""

    def _setup(self, node_count, pairing_time, signature_size,
               candidate_count, reply_cap, inbox_cap, horizon,
               node_builder_name, network_latency_name):
        if node_count & (node_count - 1):
            raise ValueError("power-of-two node counts only")
        self.node_count = node_count
        self.pairing_time = pairing_time
        self.signature_size = signature_size
        self.candidate_count = candidate_count
        self.reply_cap = reply_cap
        self.builder = builders.get_by_name(node_builder_name)
        self.latency = latency_mod.get_by_name(network_latency_name)
        self.bits = int(math.log2(node_count))
        self.levels = self.bits + 1          # cpl values 0..bits
        self.cfg = EngineConfig(
            n=node_count, horizon=horizon, inbox_cap=inbox_cap,
            payload_words=3,
            # +1: the first pick batch is mirror + candidate_count
            # (pickNextNodes, SanFerminHelper.java:123-158)
            out_deg=candidate_count + 1 + reply_cap,
            bcast_slots=1)

    def _partner_off(self, ids, cpl):
        half = _half(self.bits, cpl)
        return ids & (half - 1)

    def _pick_batch(self, ids, cpl, used, count):
        """Candidate ids for the next request batch at level cpl; -1 where
        the candidate set is exhausted.

        Matches SanFerminHelper.pickNextNodes (:123-158): the FIRST call
        returns the exact mirror candidate PLUS up to `count` further
        candidates (the reference adds the mirror, then unconditionally
        appends up to `howMany` more — so the initial fan-out is count+1,
        which is what seeds the reference's non-mirror swaps and level
        desynchronization); subsequent calls return the next `count`
        unused candidates in index order.  The reference's bit-set filter
        over the idx-shifted list is approximated by plain sequential
        order, and its within-batch `Collections.shuffle` is unobservable
        here (all requests leave in the same tick with i.i.d. latencies).
        Returns (dest [N, count+1], n_taken)."""
        half = _half(self.bits, cpl)                        # [N]
        base = _cand_base(ids, half)
        partner = self._partner_off(ids, cpl)
        first = used == 0
        width = count + 1
        j = used[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
        off = _pick_offset(j, partner[:, None], half[:, None])
        ok = (j < half[:, None]) & \
            (first[:, None] | (jnp.arange(width)[None, :] < count))
        dest = jnp.where(ok, base[:, None] + off, -1)
        return dest, jnp.sum(ok, axis=1).astype(jnp.int32)


@struct.dataclass
class SanFerminState:
    seed: jnp.ndarray
    cpl: jnp.ndarray           # int32 [N] currentPrefixLength
    agg: jnp.ndarray           # int32 [N] aggValue
    cache: jnp.ndarray         # int32 [N, L] signatureCache (0 = none)
    used: jnp.ndarray          # int32 [N] picks consumed at current level
    swapping: jnp.ndarray      # bool [N]
    pend_val: jnp.ndarray      # int32 [N] value being "verified"
    pend_at: jnp.ndarray       # int32 [N]
    pend_on: jnp.ndarray       # bool [N]
    timeout_at: jnp.ndarray    # int32 [N] (0 = none)
    timeout_lvl: jnp.ndarray   # int32 [N]
    threshold_at: jnp.ndarray  # int32 [N]
    done: jnp.ndarray          # bool [N]
    sent_requests: jnp.ndarray    # int32 [N]
    received_requests: jnp.ndarray  # int32 [N]


@register
class SanFermin(_SanFerminBase):
    """protocols/SanFerminSignature.java; parameters mirror
    SanFerminSignatureParameters (:42-111)."""

    def __init__(self, node_count=1024, threshold=None, pairing_time=2,
                 signature_size=48, reply_timeout=300, candidate_count=1,
                 node_builder_name=None, network_latency_name=None,
                 reply_cap=4, inbox_cap=16, horizon=512):
        self.threshold = node_count if threshold is None else threshold
        self.reply_timeout = reply_timeout
        self._setup(node_count, pairing_time, signature_size,
                    candidate_count, reply_cap, inbox_cap, horizon,
                    node_builder_name, network_latency_name)

    def init(self, seed):
        n, L = self.node_count, self.levels
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        net = init_net(self.cfg, nodes, seed)

        def zi():
            return jnp.zeros((n,), jnp.int32)

        pstate = SanFerminState(
            seed=seed,
            cpl=jnp.full((n,), self.bits, jnp.int32),
            agg=jnp.ones((n,), jnp.int32),
            cache=jnp.zeros((n, L), jnp.int32),
            used=zi(), swapping=jnp.zeros((n,), bool),
            pend_val=zi(), pend_at=zi(),
            pend_on=jnp.zeros((n,), bool),
            timeout_at=zi(), timeout_lvl=zi(),
            threshold_at=zi(),
            done=jnp.zeros((n,), bool),
            sent_requests=zi(), received_requests=zi(),
        )
        return net, pstate

    # ------------------------------------------------------------------

    def _enter_level(self, p, nodes, go, t):
        """goNextLevel (SanFerminSignature.java:379-419): threshold / done
        checks, cpl decrement, cache own agg, request-batch trigger."""
        n = self.node_count
        ids = jnp.arange(n, dtype=jnp.int32)

        hit = go & ~(p.threshold_at > 0) & (p.agg >= self.threshold)
        threshold_at = jnp.where(hit, t + 2 * self.pairing_time,
                                 p.threshold_at)
        finish = go & (p.cpl == 0) & ~p.done
        done = p.done | finish
        done_at = jnp.where(finish & (nodes.done_at == 0),
                            jnp.maximum(1, t + 2 * self.pairing_time),
                            nodes.done_at)
        nodes = nodes.replace(done_at=done_at.astype(jnp.int32))

        desc = go & ~finish & ~p.done
        cpl = jnp.where(desc, p.cpl - 1, p.cpl)
        cache = set2d(p.cache, ids, jnp.maximum(cpl, 0), p.agg, ok=desc)
        p = p.replace(cpl=cpl, cache=cache, swapping=p.swapping & ~desc,
                      used=jnp.where(desc, 0, p.used), done=done,
                      threshold_at=threshold_at)
        return p, nodes, desc        # desc nodes send a fresh batch

    def step(self, p: SanFerminState, nodes, inbox, t, key):
        n, L = self.node_count, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        S = inbox.src.shape[1]
        alive = ~nodes.down

        # Reply buffer for this step.
        rc = self.reply_cap
        r_dest = jnp.full((n, rc), -1, jnp.int32)
        r_kind = jnp.zeros((n, rc), jnp.int32)
        r_lvl = jnp.zeros((n, rc), jnp.int32)
        r_val = jnp.zeros((n, rc), jnp.int32)
        r_cnt = jnp.zeros((n,), jnp.int32)
        want_batch = jnp.zeros((n,), bool)

        def push_reply(bufs, cnt, to, kind, lvl, val, ok):
            d, k, l, v = bufs
            ok = ok & (cnt < rc)
            slot = jnp.minimum(cnt, rc - 1)
            d = set2d(d, ids, slot, to, ok=ok)
            k = set2d(k, ids, slot, kind, ok=ok)
            l = set2d(l, ids, slot, lvl, ok=ok)
            v = set2d(v, ids, slot, val, ok=ok)
            return (d, k, l, v), cnt + ok.astype(jnp.int32)

        swapping, cache = p.swapping, p.cache
        pend_val, pend_at, pend_on = p.pend_val, p.pend_at, p.pend_on
        recvd = p.received_requests
        bufs = (r_dest, r_kind, r_lvl, r_val)

        for s in range(S):
            ok_s = inbox.valid[:, s] & alive
            src = jnp.clip(inbox.src[:, s], 0, n - 1)
            kind = inbox.data[:, s, 0]
            lvl = jnp.clip(inbox.data[:, s, 1], 0, L - 1)
            val = inbox.data[:, s, 2]

            half = _half(self.bits, lvl)
            is_cand = ok_s & (_cand_base(ids, half) == _own_base(src, half))

            # ---- SwapRequest (onSwapRequest, :229-270) ----
            is_req = ok_s & (kind == REQ)
            recvd = recvd + is_req.astype(jnp.int32)
            wrong = is_req & (p.done | (lvl != p.cpl))
            cached = gather2d(cache, ids, lvl)
            # cached value -> optimistic OK reply
            bufs, r_cnt = push_reply(bufs, r_cnt, src, OK, lvl, cached,
                                     wrong & (cached > 0))
            # no cache -> NO reply, remember the value if from a candidate.
            # The NO carries the REPLIER's current level (the 3-arg
            # sendSwapReply overload, SanFerminSignature.java:421-423), so
            # it only triggers the requester's immediate retry when the two
            # nodes happen to sit at the same level — usually the requester
            # recovers via its timeout instead.
            bufs, r_cnt = push_reply(bufs, r_cnt, src, NO, p.cpl,
                                     0, wrong & (cached == 0))
            cache = set2d(cache, ids, lvl, val,
                          ok=wrong & (cached == 0) & is_cand)
            # current level, already swapping -> optimistic OK with our agg
            cur = is_req & ~wrong
            busy = cur & swapping
            bufs, r_cnt = push_reply(bufs, r_cnt, src, OK, lvl, p.agg, busy)
            # valid swap -> latch the verification (transition, :519-540).
            # Faithfully NO reply is sent on accept: the requester's own
            # swap completes via the crossing request, or via the
            # busy/cached optimistic replies on its timeout retries
            # (onSwapRequest, :229-270).
            accept = cur & ~swapping & is_cand
            swapping = swapping | accept
            pend_val = jnp.where(accept, val, pend_val)
            pend_at = jnp.where(accept, t + self.pairing_time, pend_at)
            pend_on = pend_on | accept

            # ---- SwapReply (onSwapReply, :273-324) ----
            is_rep = ok_s & ((kind == OK) | (kind == NO)) & ~p.done & \
                (lvl == p.cpl) & ~swapping
            off = src - _cand_base(ids, half)
            expected = _expected(off, self._partner_off(ids, p.cpl),
                                 p.used, _half(self.bits, p.cpl))
            acc2 = is_rep & (kind == OK) & is_cand
            swapping = swapping | acc2
            pend_val = jnp.where(acc2, val, pend_val)
            pend_at = jnp.where(acc2, t + self.pairing_time, pend_at)
            pend_on = pend_on | acc2
            # NO from an expected candidate -> try the next ones (:311-318)
            want_batch = want_batch | (is_rep & (kind == NO) & expected)

        p = p.replace(swapping=swapping, cache=cache, pend_val=pend_val,
                      pend_at=pend_at, pend_on=pend_on,
                      received_requests=recvd)

        # ---- apply finished verification -> aggregate + goNextLevel ----
        due = pend_on & (t >= p.pend_at) & ~p.done
        p = p.replace(agg=jnp.where(due, p.agg + p.pend_val, p.agg),
                      pend_on=pend_on & ~due)
        p, nodes, desc = self._enter_level(p, nodes, due, t)

        # ---- init kick (registerTask(goNextLevel, 1), :141) ----
        kick = alive & (t == 1) & (p.cpl == self.bits)
        p, nodes, desc0 = self._enter_level(p, nodes, kick, t)
        desc = desc | desc0

        # ---- timeout (sendToNodes' chained task, :329-369) ----
        fired = alive & ~p.done & (p.timeout_at > 0) & (t >= p.timeout_at) & \
            (p.cpl == p.timeout_lvl)
        want_batch = (want_batch & ~p.done & alive) | desc | fired

        # ---- assemble outbox ----
        cc = self.candidate_count
        dest_req, taken = self._pick_batch(ids, p.cpl, p.used, cc)
        dest_req = jnp.where(want_batch[:, None], dest_req, -1)
        sent_some = want_batch & (taken > 0)
        p = p.replace(
            used=jnp.where(want_batch, p.used + taken, p.used),
            sent_requests=p.sent_requests + jnp.where(
                want_batch, jnp.sum(dest_req >= 0, axis=1), 0),
            timeout_at=jnp.where(sent_some, t + self.reply_timeout,
                                 p.timeout_at),
            timeout_lvl=jnp.where(sent_some, p.cpl, p.timeout_lvl))

        K, F = self.cfg.out_deg, self.cfg.payload_words
        dest = jnp.full((n, K), -1, jnp.int32)
        payload = jnp.zeros((n, K, F), jnp.int32)
        w = cc + 1                         # mirror + cc on first batch
        dest = dest.at[:, :w].set(dest_req)
        payload = payload.at[:, :w, 0].set(REQ)
        payload = payload.at[:, :w, 1].set(p.cpl[:, None])
        payload = payload.at[:, :w, 2].set(p.agg[:, None])
        rd, rk, rl, rv = bufs
        live_r = jnp.arange(rc)[None, :] < r_cnt[:, None]
        dest = dest.at[:, w:w + rc].set(jnp.where(live_r, rd, -1))
        payload = payload.at[:, w:w + rc, 0].set(rk)
        payload = payload.at[:, w:w + rc, 1].set(rl)
        payload = payload.at[:, w:w + rc, 2].set(rv)
        sizes = jnp.full((n, K), self.signature_size + 1, jnp.int32)

        out = empty_outbox(self.cfg).replace(dest=dest, payload=payload,
                                             size=sizes)
        return p, nodes, out

    def done(self, pstate, nodes):
        return jnp.all(nodes.down | pstate.done)


@struct.dataclass
class CapposState:
    seed: jnp.ndarray
    cpl: jnp.ndarray           # int32 [N]
    cache_best: jnp.ndarray    # int32 [N, L] max received value per level
    used: jnp.ndarray          # int32 [N]
    swapping: jnp.ndarray      # bool [N]
    pend_val: jnp.ndarray      # int32 [N]
    pend_lvl: jnp.ndarray      # int32 [N]
    pend_at: jnp.ndarray       # int32 [N]
    pend_on: jnp.ndarray       # bool [N]
    timeout_at: jnp.ndarray    # int32 [N]
    timeout_lvl: jnp.ndarray   # int32 [N]
    threshold_at: jnp.ndarray  # int32 [N]
    done: jnp.ndarray          # bool [N]


@register
class SanFerminCappos(_SanFerminBase):
    """protocols/SanFerminCappos.java; parameters mirror SanFerminParameters
    (:43-106).  threshold counts 1 + sum of per-level best cached values at
    or above the current level (totalNumberOfSigs, :352-360)."""

    def __init__(self, node_count=2048, threshold=1024, pairing_time=2,
                 signature_size=48, timeout=150, candidate_count=50,
                 node_builder_name=None, network_latency_name=None,
                 reply_cap=8, inbox_cap=32, horizon=512):
        self.threshold = threshold
        self.timeout = timeout
        self._setup(node_count, pairing_time, signature_size,
                    candidate_count, reply_cap, inbox_cap, horizon,
                    node_builder_name, network_latency_name)

    def init(self, seed):
        n, L = self.node_count, self.levels
        seed = jnp.asarray(seed, jnp.int32)
        nodes = self.builder.build(seed, n)
        net = init_net(self.cfg, nodes, seed)

        def zi():
            return jnp.zeros((n,), jnp.int32)

        pstate = CapposState(
            seed=seed,
            cpl=jnp.full((n,), self.bits, jnp.int32),
            cache_best=jnp.zeros((n, L), jnp.int32),
            used=zi(), swapping=jnp.zeros((n,), bool),
            pend_val=zi(), pend_lvl=zi(), pend_at=zi(),
            pend_on=jnp.zeros((n,), bool),
            timeout_at=zi(), timeout_lvl=zi(), threshold_at=zi(),
            done=jnp.zeros((n,), bool),
        )
        return net, pstate

    def _total(self, cache_best, level):
        """totalNumberOfSigs(level) = 1 + sum of best cached values at
        levels >= level (:352-360)."""
        L = self.levels
        lvl_idx = jnp.arange(L, dtype=jnp.int32)[None, :]
        return 1 + jnp.sum(jnp.where(lvl_idx >= level[:, None],
                                     cache_best, 0), axis=1)

    def _enter_level(self, p, nodes, go, t):
        """goNextLevel (:307-345): cached levels are skipped recursively."""
        n, L = self.node_count, self.levels

        def one(p, nodes, go):
            total_cur = self._total(p.cache_best, p.cpl)
            hit = go & ~(p.threshold_at > 0) & (total_cur >= self.threshold)
            threshold_at = jnp.where(hit, t + 2 * self.pairing_time,
                                     p.threshold_at)
            finish = go & (p.cpl == 0) & ~p.done
            done = p.done | finish
            done_at = jnp.where(finish & (nodes.done_at == 0),
                                jnp.maximum(1, t + 2 * self.pairing_time),
                                nodes.done_at)
            nodes = nodes.replace(done_at=done_at.astype(jnp.int32))
            desc = go & ~finish & ~done
            cpl = jnp.where(desc, p.cpl - 1, p.cpl)
            p = p.replace(cpl=cpl, swapping=p.swapping & ~desc,
                          used=jnp.where(desc, 0, p.used), done=done,
                          threshold_at=threshold_at)
            ids = jnp.arange(n, dtype=jnp.int32)
            has_cache = gather2d(p.cache_best, ids, p.cpl) > 0
            return p, nodes, desc & ~has_cache, desc & has_cache

        send = jnp.zeros((n,), bool)
        again = go
        for _ in range(L):        # cached-level skips, at most L deep
            p, nodes, fresh, again = one(p, nodes, again)
            send = send | fresh
        return p, nodes, send

    def step(self, p: CapposState, nodes, inbox, t, key):
        n, L = self.node_count, self.levels
        ids = jnp.arange(n, dtype=jnp.int32)
        S = inbox.src.shape[1]
        alive = ~nodes.down

        rc = self.reply_cap
        r_dest = jnp.full((n, rc), -1, jnp.int32)
        r_lvl = jnp.zeros((n, rc), jnp.int32)
        r_val = jnp.zeros((n, rc), jnp.int32)
        r_cnt = jnp.zeros((n,), jnp.int32)

        def push_reply(bufs, cnt, to, lvl, val, ok):
            d, l, v = bufs
            ok = ok & (cnt < rc)
            slot = jnp.minimum(cnt, rc - 1)
            d = set2d(d, ids, slot, to, ok=ok)
            l = set2d(l, ids, slot, lvl, ok=ok)
            v = set2d(v, ids, slot, val, ok=ok)
            return (d, l, v), cnt + ok.astype(jnp.int32)

        swapping, cache = p.swapping, p.cache_best
        pend_val, pend_lvl, pend_at, pend_on = (p.pend_val, p.pend_lvl,
                                                p.pend_at, p.pend_on)
        bufs = (r_dest, r_lvl, r_val)
        thr_at = p.threshold_at

        for s in range(S):
            ok_s = inbox.valid[:, s] & alive
            src = jnp.clip(inbox.src[:, s], 0, n - 1)
            kind = inbox.data[:, s, 0]
            lvl = jnp.clip(inbox.data[:, s, 1], 0, L - 1)
            val = inbox.data[:, s, 2]
            want_reply = kind == SWAP_ASK

            half = _half(self.bits, lvl)
            is_cand = ok_s & (_cand_base(ids, half) == _own_base(src, half))

            wrong = ok_s & (p.done | (lvl != p.cpl))
            cached = gather2d(cache, ids, lvl)
            bufs, r_cnt = push_reply(bufs, r_cnt, src, lvl, cached,
                                     wrong & want_reply & (cached > 0))
            # keep for later (putCachedSig, :240-247) — max, not replace
            upd = wrong & ~(want_reply & (cached > 0)) & is_cand
            cache = set2d(cache, ids, lvl, jnp.maximum(cached, val), ok=upd)
            hit = upd & ~(thr_at > 0) & \
                (self._total(cache, p.cpl) >= self.threshold)
            thr_at = jnp.where(hit, t + 2 * self.pairing_time, thr_at)

            cur = ok_s & ~wrong
            bufs, r_cnt = push_reply(bufs, r_cnt, src, lvl,
                                     self._total(cache, lvl),
                                     cur & want_reply)
            accept = cur & is_cand & ~swapping
            swapping = swapping | accept
            pend_val = jnp.where(accept, val, pend_val)
            pend_lvl = jnp.where(accept, lvl, pend_lvl)
            pend_at = jnp.where(accept, t + self.pairing_time, pend_at)
            pend_on = pend_on | accept

        p = p.replace(swapping=swapping, cache_best=cache,
                      pend_val=pend_val, pend_lvl=pend_lvl, pend_at=pend_at,
                      pend_on=pend_on, threshold_at=thr_at)

        # apply verification: putCachedSig(level, value) + goNextLevel
        due = p.pend_on & (t >= p.pend_at) & ~p.done
        old = gather2d(p.cache_best, ids, p.pend_lvl)
        cache = set2d(p.cache_best, ids, p.pend_lvl,
                      jnp.maximum(old, p.pend_val), ok=due)
        p = p.replace(cache_best=cache, pend_on=p.pend_on & ~due)
        hit = due & ~(p.threshold_at > 0) & \
            (self._total(p.cache_best, p.cpl) >= self.threshold)
        p = p.replace(threshold_at=jnp.where(
            hit, t + 2 * self.pairing_time, p.threshold_at))
        p, nodes, send = self._enter_level(p, nodes, due, t)

        kick = alive & (t == 1) & (p.cpl == self.bits)
        p, nodes, send0 = self._enter_level(p, nodes, kick, t)
        send = send | send0

        fired = alive & ~p.done & (p.timeout_at > 0) & (t >= p.timeout_at) & \
            (p.cpl == p.timeout_lvl)
        send = (send & alive & ~p.done) | fired

        cc = self.candidate_count
        dest_req, taken = self._pick_batch(ids, p.cpl, p.used, cc)
        dest_req = jnp.where(send[:, None], dest_req, -1)
        sent_some = send & (taken > 0)
        # Swap value sent with a request = totalNumberOfSigs(cpl + 1)
        # (:274-278).
        req_val = self._total(p.cache_best, p.cpl + 1)
        p = p.replace(
            used=jnp.where(send, p.used + taken, p.used),
            timeout_at=jnp.where(sent_some, t + self.timeout, p.timeout_at),
            timeout_lvl=jnp.where(sent_some, p.cpl, p.timeout_lvl))

        K, F = self.cfg.out_deg, self.cfg.payload_words
        dest = jnp.full((n, K), -1, jnp.int32)
        payload = jnp.zeros((n, K, F), jnp.int32)
        w = cc + 1                         # mirror + cc on first batch
        dest = dest.at[:, :w].set(dest_req)
        payload = payload.at[:, :w, 0].set(SWAP_ASK)
        payload = payload.at[:, :w, 1].set(p.cpl[:, None])
        payload = payload.at[:, :w, 2].set(req_val[:, None])
        rd, rl, rv = bufs
        live_r = jnp.arange(rc)[None, :] < r_cnt[:, None]
        dest = dest.at[:, w:w + rc].set(jnp.where(live_r, rd, -1))
        payload = payload.at[:, w:w + rc, 0].set(SWAP_INFO)
        payload = payload.at[:, w:w + rc, 1].set(rl)
        payload = payload.at[:, w:w + rc, 2].set(rv)
        sizes = jnp.full((n, K), self.signature_size + 1, jnp.int32)

        out = empty_outbox(self.cfg).replace(dest=dest, payload=payload,
                                             size=sizes)
        return p, nodes, out

    def done(self, pstate, nodes):
        return jnp.all(nodes.down | pstate.done)


def cont_if_sanfermin(net, pstate):
    live = ~net.nodes.down
    return jnp.any(live & ~pstate.done)
