"""Kademlia XOR-distance helpers (core/utils/Kademlia.java:5-29).

The reference keeps a scalar byte-array distance function (the bit length
of the XOR of two node ids) plus the k-bucket / node-lookup design notes
from the Kademlia paper; no shipped protocol uses it.  Here the distance is
vectorized: node ids are `[..., B]` uint8 arrays (e.g. the SHA-256 node
hashes of `NodeBuilder`), and `distance` maps over arbitrary leading axes —
one call scores a node against its whole routing table, the idiomatic shape
for a future discv4/discv5-style protocol model.

K-bucket semantics for such a model (see the paper + devp2p discv4 notes
mirrored at Kademlia.java:31-73): bucket i holds peers at distance
(2^i, 2^(i+1)]; on any message the sender moves to the bucket tail, with a
ping-the-oldest eviction rule when full; lookups are alpha-parallel
FIND_NODE recursions over the closest known nodes.  Ethereum discv4 uses
k=16 with 256 buckets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U8 = jnp.uint8


def distance(a, b):
    """Bit-length of XOR distance between byte ids (Kademlia.java:8-29).

    a, b: broadcast-compatible uint8 arrays [..., B] -> int32 [...]:
    0 for equal ids, else (number of significant bits of a XOR b counted
    from the most significant byte).  Matches the reference loop: a full
    byte prefix match drops 8 per byte, the first differing byte drops its
    leading zeros, later bytes don't matter."""
    a = jnp.asarray(a, U8)
    b = jnp.asarray(b, U8)
    x = (a ^ b).astype(jnp.int32)                       # [..., B]
    nbytes = x.shape[-1]
    nz = x != 0
    # Index of the first nonzero byte (B if none).
    first = jnp.where(jnp.any(nz, axis=-1),
                      jnp.argmax(nz, axis=-1), nbytes)
    byte = jnp.take_along_axis(
        x, jnp.minimum(first, nbytes - 1)[..., None], axis=-1)[..., 0]
    # Bit length of that byte (byte is in [0, 255]).
    blen = jnp.where(byte > 0, 32 - jax.lax.clz(byte), 0)
    return jnp.where(first >= nbytes, 0,
                     (nbytes - 1 - first) * 8 + blen)


def bucket_index(a, b, n_buckets: int = 256):
    """k-bucket index for peer b as seen by a: distance-1 clamped to the
    table size (bucket i spans distances (2^i, 2^(i+1)], discv4 uses 256
    buckets of k=16)."""
    d = distance(a, b)
    return jnp.clip(d - 1, 0, n_buckets - 1)
