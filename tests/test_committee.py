"""Committee-protocol tests: Slush, Snowflake (SlushTest/SnowflakeTest
analogues — colors converge), Paxos (PaxosTest — every proposer accepts the
same value), plus determinism checks (the testCopy recipe, SURVEY.md §4.2)."""

import pytest

import numpy as np

from wittgenstein_tpu.core.network import Runner
from wittgenstein_tpu.models.avalanche import Slush, Snowflake
from wittgenstein_tpu.models.paxos import Paxos


def _colors_converged(c, d):
    assert d.all(), "every node must decide"
    counts = np.bincount(c, minlength=3)
    assert counts[0] == 0, "no node may stay uncolored"
    return counts[1] == 0 or counts[2] == 0


def test_slush_converges():
    proto = Slush(node_count=100, rounds=5, k=7)
    net, p = proto.init(0)
    net, p = Runner(proto, donate=False).run_ms(net, p, 3000)
    assert _colors_converged(np.asarray(p.color), np.asarray(p.decided))
    assert int(net.dropped) == 0
    assert (np.asarray(net.nodes.done_at) > 0).all()


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 50 s; test_slush_converges keeps the Avalanche family fast-gated
def test_snowflake_converges_with_confidence():
    proto = Snowflake(node_count=100, k=7, beta=3)
    net, p = proto.init(0)
    net, p = Runner(proto, donate=False).run_ms(net, p, 4000)
    assert _colors_converged(np.asarray(p.color), np.asarray(p.decided))
    # beta confidence means more rounds than Slush's fixed M in expectation.
    assert int(np.asarray(p.round).max()) >= 0
    assert int(net.dropped) == 0


@pytest.mark.slow      # tier-1 budget (reports/TIER1_DURATIONS.md):
# 46 s; slush convergence keeps the family fast-gated; the determinism
# contract stays gated by the Handel/GSF/Casper/PingPong fast runs
def test_avalanche_deterministic():
    proto = Slush(node_count=64, rounds=4, k=5)
    outs = []
    for seed in (2, 2, 3):
        net, p = proto.init(seed)
        net, p = Runner(proto, donate=False).run_ms(net, p, 2500)
        outs.append(np.asarray(p.color))
    assert np.array_equal(outs[0], outs[1])
    # different seed -> different query samples -> (almost surely)
    # different per-node decision trace; compare done_at times instead of
    # colors (both seeds may still converge to the same color).


def test_paxos_agreement():
    proto = Paxos(acceptor_count=3, proposer_count=3, timeout=1000)
    net, p = proto.init(0)
    runner = Runner(proto, donate=False)
    for _ in range(10):
        net, p = runner.run_ms(net, p, 500)
        va = np.asarray(p.value_accepted)[proto.a:]
        if (va >= 0).all():
            break
    assert (va >= 0).all(), "all proposers must accept a value"
    assert len(set(va.tolist())) == 1, "Paxos safety: single agreed value"
    assert va[0] in np.asarray(p.value_proposed)[proto.a:]
    assert int(net.dropped) == 0


@pytest.mark.slow
def test_paxos_more_nodes_and_determinism():
    proto = Paxos(acceptor_count=5, proposer_count=4, timeout=800)
    outs = []
    for seed in (1, 1):
        net, p = proto.init(seed)
        net, p = Runner(proto, donate=False).run_ms(net, p, 6000)
        va = np.asarray(p.value_accepted)[proto.a:]
        assert (va >= 0).all() and len(set(va.tolist())) == 1
        outs.append((va.tolist(), np.asarray(net.nodes.done_at).tolist()))
    assert outs[0] == outs[1]
