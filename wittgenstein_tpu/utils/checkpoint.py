"""Checkpoint / resume for simulation state.

The reference has no checkpointing — its replication mechanism is
`Protocol.copy()` + `init()` + reseed (core/Protocol.java:14-18,
RunMultipleTimes.java:45-47; SURVEY.md §5.4 notes the Envelope design
explicitly enabled-but-never-used on-disk serialization).  Here the whole
simulation is one state pytree, so checkpointing is exact by construction:
save the (NetState, pstate) pair, restore it, and the continuation is
bit-identical to an uninterrupted run (tests/test_checkpoint.py).

Format: a single .npz of flattened pytree leaves (portable, no directory
trees, loads anywhere numpy does).  `save`/`load` round-trip any pytree of
jax/numpy arrays; shapes/dtypes are restored exactly.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np


def save(path: str, net, pstate, meta: dict | None = None) -> None:
    """Write the full simulator state to `path` (.npz)."""
    leaves, treedef = jax.tree.flatten((net, pstate))
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8)
    np.savez_compressed(path, **arrays)


def peek_meta(path: str) -> dict:
    """Read ONLY the metadata dict of a checkpoint — the serve plane's
    resume path needs the stored request specs to rebuild the pytree
    template before it pays for the leaf arrays."""
    with np.load(path) as z:
        return json.loads(bytes(z["__meta__"]).decode()) \
            if "__meta__" in z else {}


def stale_meta_problems(meta: dict) -> list:
    """Staleness audit of a serve GROUP-checkpoint metadata dict
    (serve/scheduler.py writes them; schema 2 records each request's
    spec digest).  Returns human-readable problem strings — empty
    means the file is internally consistent and safe to restore.  The
    scheduler's `resume_checkpoints` and the matrix driver's campaign
    resume share this one definition, so "stale" can never mean two
    different things on the two resume paths.

    Checks: meta schema (an older tree's file lacks the digests this
    gate needs — refusing beats guessing), and that every stored spec
    STILL digests to its recorded `spec_digest` (a hand-edited or
    torn file would otherwise restore a trajectory its spec never
    produced)."""
    from ..serve.spec import ScenarioSpec

    schema = meta.get("schema")
    if schema != 2:
        return [f"checkpoint meta schema {schema!r} != 2 — written by "
                "a different tree, so its specs cannot be verified"]
    problems = []
    for rm in meta.get("requests", ()):
        want = rm.get("spec_digest")
        try:
            got = ScenarioSpec.from_json(rm["spec"]).digest()
        except (ValueError, KeyError, TypeError) as e:
            problems.append(f"request {rm.get('id')!r}: stored spec "
                            f"no longer parses ({e})")
            continue
        if got != want:
            problems.append(
                f"request {rm.get('id')!r}: stored spec digests to "
                f"{got} but the checkpoint recorded {want} — the spec "
                "was edited after this checkpoint was written")
    return problems


def load(path: str, protocol, seed=0):
    """Restore (net, pstate, meta).  `protocol` must be constructed with
    the same parameters as at save time — its `init` supplies the pytree
    structure the stored leaves are poured back into.  Only the TREE
    STRUCTURE comes from the template (leaf shapes/dtypes restore from
    the file), so vmap-batched states — the serve scheduler's
    concatenated lane batches, the bench's seed batches — round-trip
    through the same single-seed template."""
    net0, ps0 = protocol.init(seed)
    _, treedef = jax.tree.flatten((net0, ps0))
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode()) if "__meta__" in z \
            else {}
        leaves = []
        i = 0
        while f"leaf_{i}" in z:
            leaves.append(jnp.asarray(z[f"leaf_{i}"]))
            i += 1
    net, pstate = jax.tree.unflatten(treedef, leaves)
    return net, pstate, meta
